// Standalone native bench binary (no Python in the loop).
//
// Reads a flat binary trace dump produced by
// `python -m crdt_benches_tpu.bench.dump_trace <name>` and times upstream
// replay through both native backends (gap-buffer rope and treap CRDT),
// reporting elements/sec where element = one patch — the reference's
// Criterion throughput semantics (reference src/main.rs:25).
//
// Dump format (little-endian int64 header then int32 arrays):
//   [n_patches, init_n, ins_flat_n]
//   pos[n_patches] del[n_patches] ins_off[n_patches+1] ins_flat[ins_flat_n]
//   init[init_n]

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <vector>

extern "C" int64_t rope_replay(const int32_t*, int64_t, const int32_t*,
                               const int32_t*, const int32_t*, const int32_t*,
                               int64_t);
extern "C" int64_t crdt_replay(const int32_t*, int64_t, const int32_t*,
                               const int32_t*, const int32_t*, const int32_t*,
                               int64_t);

static std::vector<int32_t> read_i32(FILE* f, int64_t n) {
    std::vector<int32_t> v((size_t)n);
    if (n && fread(v.data(), 4, (size_t)n, f) != (size_t)n) {
        fprintf(stderr, "short read\n");
        exit(1);
    }
    return v;
}

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s trace.bin [samples=7]\n", argv[0]);
        return 1;
    }
    int samples = argc > 2 ? atoi(argv[2]) : 7;
    FILE* f = fopen(argv[1], "rb");
    if (!f) { perror("open"); return 1; }
    int64_t hdr[3];
    if (fread(hdr, 8, 3, f) != 3) { fprintf(stderr, "bad header\n"); return 1; }
    int64_t n_patches = hdr[0], init_n = hdr[1], flat_n = hdr[2];
    auto pos = read_i32(f, n_patches);
    auto del = read_i32(f, n_patches);
    auto off = read_i32(f, n_patches + 1);
    auto flat = read_i32(f, flat_n);
    auto init = read_i32(f, init_n);
    fclose(f);

    struct { const char* name; int64_t (*fn)(const int32_t*, int64_t, const int32_t*, const int32_t*, const int32_t*, const int32_t*, int64_t); } backends[] = {
        {"cpp-rope", rope_replay},
        {"cpp-crdt", crdt_replay},
    };

    for (auto& b : backends) {
        double best = 1e300;
        int64_t len = 0;
        len = b.fn(init.data(), init_n, pos.data(), del.data(), off.data(),
                   flat.data(), n_patches);  // warmup
        for (int s = 0; s < samples; s++) {
            auto t0 = std::chrono::steady_clock::now();
            len = b.fn(init.data(), init_n, pos.data(), del.data(), off.data(),
                       flat.data(), n_patches);
            auto t1 = std::chrono::steady_clock::now();
            double dt = std::chrono::duration<double>(t1 - t0).count();
            if (dt < best) best = dt;
        }
        printf("%-10s len=%lld  %.4fs  %.0f elements/sec\n", b.name,
               (long long)len, best, (double)n_patches / best);
    }
    return 0;
}
