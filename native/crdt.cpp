// Native sequence CRDT engine — the op-log capability of the reference's
// diamond-types path (SURVEY.md C8+C11; reference src/rope.rs:105-137 and
// 193-225): agent ids, an append-only op log, position-addressed local edits,
// incremental binary update encoding from a version frontier (the analog of
// encode_from, reference src/rope.rs:214), and decode-and-merge apply.
//
// Design (original, TPU-era native tier): elements live in an order-statistic
// treap (randomized BST with parent pointers) over the full sequence
// *including tombstones*; each node tracks subtree totals for both all
// elements and visible elements, so
//   - visible-rank -> node is O(log n) (position resolution for local edits),
//   - insert-after-origin is O(log n) (remote integration),
//   - tombstone delete is O(log n) count maintenance up the parent chain.
// An id -> node hash map resolves remote ops' origins/targets.  Update wire
// format is fixed-width little-endian records (content compression is out of
// scope, as in the reference's EncodeOptions, src/rope.rs:201-208).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <unordered_map>
#include <vector>

namespace {

struct Id {
    uint32_t agent;
    uint32_t seq;
    bool operator==(const Id& o) const { return agent == o.agent && seq == o.seq; }
};

struct IdHash {
    size_t operator()(const Id& id) const {
        return ((uint64_t)id.agent << 32 | id.seq) * 0x9E3779B97F4A7C15ull;
    }
};

struct Node {
    Node *l = nullptr, *r = nullptr, *p = nullptr;
    Node *origin = nullptr;   // left-origin element (nullptr = head)
    uint64_t prio;
    uint32_t cnt_all = 1;     // subtree size incl. tombstones
    uint32_t cnt_vis = 1;     // visible subtree size
    bool visible = true;
    int32_t ch;
    Id id;
};

inline uint32_t call(Node* n) { return n ? n->cnt_all : 0; }
inline uint32_t cvis(Node* n) { return n ? n->cnt_vis : 0; }

// Op log records.
enum OpType : uint8_t { OP_INSERT = 1, OP_DELETE = 2 };
struct Op {
    uint8_t type;
    Id id;        // inserted element / delete target
    Id origin;    // left origin for inserts ({0,0} = document head)
    int32_t ch;
};

constexpr Id HEAD{0, 0};  // agent 0 reserved for the head sentinel

// Total order on ids for concurrent-sibling ordering: (seq, agent)
// lexicographic.  seq is a Lamport clock (bumped past every integrated op),
// so causally-later inserts at the same origin always order first — the RGA
// intention-preservation property.
inline bool id_less(const Id& a, const Id& b) {
    return a.seq != b.seq ? a.seq < b.seq : a.agent < b.agent;
}

constexpr size_t OP_WIRE = 1 + 4 * 5;  // type + id(2x4) + origin(2x4) + ch(4)

struct Crdt {
    Node* root = nullptr;
    std::unordered_map<Id, Node*, IdHash> by_id;
    std::vector<Op> oplog;
    uint32_t agent;
    uint32_t next_seq = 1;
    uint64_t rng = 0x853c49e6748fea9bull;

    uint64_t rand64() {
        rng ^= rng << 13; rng ^= rng >> 7; rng ^= rng << 17;
        return rng;
    }

    // ---- treap primitives ----
    static void pull(Node* n) {
        n->cnt_all = 1 + call(n->l) + call(n->r);
        n->cnt_vis = (n->visible ? 1 : 0) + cvis(n->l) + cvis(n->r);
    }

    void rot_up(Node* x) {  // rotate x above its parent
        Node* p = x->p;
        Node* g = p->p;
        if (p->l == x) { p->l = x->r; if (x->r) x->r->p = p; x->r = p; }
        else { p->r = x->l; if (x->l) x->l->p = p; x->l = p; }
        p->p = x; x->p = g;
        if (g) { (g->l == p ? g->l : g->r) = x; } else root = x;
        pull(p); pull(x);
    }

    void bubble(Node* x) {
        while (x->p && x->p->prio < x->prio) rot_up(x);
        for (Node* a = x->p; a; a = a->p) pull(a);
    }

    Node* by_vis_rank(uint32_t r) const {  // r-th visible element (0-based)
        Node* n = root;
        while (n) {
            uint32_t lv = cvis(n->l);
            if (r < lv) { n = n->l; continue; }
            r -= lv;
            if (n->visible) {
                if (r == 0) return n;
                r -= 1;
            }
            n = n->r;
        }
        return nullptr;
    }

    Node* first() const {
        Node* n = root;
        while (n && n->l) n = n->l;
        return n;
    }

    Node* successor(Node* n) const {
        if (n->r) {
            n = n->r;
            while (n->l) n = n->l;
            return n;
        }
        while (n->p && n->p->r == n) n = n->p;
        return n->p;
    }

    // Index of node in the full sequence (incl. tombstones); head = -1.
    int64_t pos_all(Node* n) const {
        if (!n) return -1;
        int64_t r = call(n->l);
        for (Node* a = n; a->p; a = a->p)
            if (a->p->r == a) r += call(a->p->l) + 1;
        return r;
    }

    // Insert a fresh node immediately after `after` in sequence order
    // (after == nullptr: at the very front).
    Node* insert_after(Node* after, int32_t ch, Id id) {
        Node* n = new Node;
        n->prio = rand64();
        n->ch = ch;
        n->id = id;
        if (!after) {
            if (!root) { root = n; by_id.emplace(id, n); return n; }
            Node* f = first();
            f->l = n; n->p = f;
        } else if (!after->r) {
            after->r = n; n->p = after;
        } else {
            Node* s = after->r;
            while (s->l) s = s->l;
            s->l = n; n->p = s;
        }
        for (Node* a = n->p; a; a = a->p) pull(a);
        bubble(n);
        by_id.emplace(id, n);
        return n;
    }

    // RGA integration point: scan right from `origin` skipping concurrent
    // sibling subtrees whose root id orders after `id` (children of one
    // origin sit in descending id order; descendants have origins deeper in
    // the region, ancestors'-sibling elements have origins left of it).
    Node* integration_point(Node* origin, Id id) {
        int64_t o_pos = pos_all(origin);
        Node* last = origin;
        Node* e = origin ? successor(origin) : first();
        while (e) {
            int64_t eo_pos = pos_all(e->origin);
            if (eo_pos < o_pos) break;  // left the origin's child region
            if (eo_pos == o_pos && id_less(e->id, id)) break;  // smaller sib
            last = e;
            e = successor(e);
        }
        return last;  // insert immediately after this node
    }

    void tombstone(Node* n) {
        if (!n->visible) return;
        n->visible = false;
        for (Node* a = n; a; a = a->p) pull(a);
    }

    uint32_t len() const { return cvis(root); }

    // ---- local (upstream) edits: position-addressed ----
    void local_insert(uint32_t at, const int32_t* codes, size_t n) {
        uint32_t l = len();
        if (at > l) at = l;
        Node* origin_node = at == 0 ? nullptr : by_vis_rank(at - 1);
        for (size_t i = 0; i < n; i++) {
            Id id{agent, next_seq++};  // next_seq is a Lamport clock
            Id origin = origin_node ? origin_node->id : HEAD;
            oplog.push_back(Op{OP_INSERT, id, origin, codes[i]});
            // Local ops carry the max Lamport seen, so the sibling scan
            // terminates immediately and this is an O(1) placement.
            Node* after = integration_point(origin_node, id);
            Node* n_ = insert_after(after, codes[i], id);
            n_->origin = origin_node;
            origin_node = n_;
        }
    }

    void local_remove(uint32_t start, uint32_t end) {
        uint32_t l = len();
        if (start > l) start = l;
        if (end > l) end = l;
        for (uint32_t i = start; i < end; i++) {
            Node* n = by_vis_rank(start);  // ranks shift as we delete
            if (!n) break;
            oplog.push_back(Op{OP_DELETE, n->id, HEAD, 0});
            tombstone(n);
        }
    }

    // ---- remote integration ----
    void integrate(const Op& op) {
        if (op.type == OP_INSERT) {
            if (by_id.count(op.id)) return;  // idempotent
            Node* origin_node = nullptr;
            if (!(op.origin == HEAD)) {
                auto it = by_id.find(op.origin);
                if (it == by_id.end()) return;  // missing causal dep: drop
                origin_node = it->second;
            }
            if (op.id.seq >= next_seq) next_seq = op.id.seq + 1;  // Lamport
            oplog.push_back(op);
            Node* after = integration_point(origin_node, op.id);
            Node* n = insert_after(after, op.ch, op.id);
            n->origin = origin_node;
        } else {
            auto it = by_id.find(op.id);
            if (it != by_id.end() && it->second->visible) {
                oplog.push_back(op);
                tombstone(it->second);
            }
        }
    }

    void read(int32_t* out) const {
        // iterative in-order traversal, visible only
        std::vector<Node*> stack;
        Node* n = root;
        size_t k = 0;
        while (n || !stack.empty()) {
            while (n) { stack.push_back(n); n = n->l; }
            n = stack.back(); stack.pop_back();
            if (n->visible) out[k++] = n->ch;
            n = n->r;
        }
    }

    void free_all() {
        std::vector<Node*> stack;
        if (root) stack.push_back(root);
        while (!stack.empty()) {
            Node* n = stack.back(); stack.pop_back();
            if (n->l) stack.push_back(n->l);
            if (n->r) stack.push_back(n->r);
            delete n;
        }
    }
};

void encode_op(const Op& op, uint8_t* out) {
    out[0] = op.type;
    memcpy(out + 1, &op.id.agent, 4);
    memcpy(out + 5, &op.id.seq, 4);
    memcpy(out + 9, &op.origin.agent, 4);
    memcpy(out + 13, &op.origin.seq, 4);
    memcpy(out + 17, &op.ch, 4);
}

Op decode_op(const uint8_t* in) {
    Op op;
    op.type = in[0];
    memcpy(&op.id.agent, in + 1, 4);
    memcpy(&op.id.seq, in + 5, 4);
    memcpy(&op.origin.agent, in + 9, 4);
    memcpy(&op.origin.seq, in + 13, 4);
    memcpy(&op.ch, in + 17, 4);
    return op;
}

Crdt* crdt_make(const int32_t* init, int64_t n, uint32_t agent) {
    Crdt* c = new Crdt;
    c->agent = agent;
    c->rng ^= (uint64_t)agent * 0xD1342543DE82EF95ull + 1;
    if (n > 0) c->local_insert(0, init, (size_t)n);
    return c;
}

}  // namespace

extern "C" {

void* crdt_new(const int32_t* init, int64_t n, uint32_t agent) {
    return crdt_make(init, n, agent);
}

void crdt_free(void* h) {
    Crdt* c = static_cast<Crdt*>(h);
    c->free_all();
    delete c;
}

int64_t crdt_len(void* h) { return static_cast<Crdt*>(h)->len(); }

int64_t crdt_oplog_len(void* h) {
    return (int64_t)static_cast<Crdt*>(h)->oplog.size();
}

void crdt_insert(void* h, int64_t at, const int32_t* codes, int64_t n) {
    static_cast<Crdt*>(h)->local_insert((uint32_t)at, codes, (size_t)n);
}

void crdt_remove(void* h, int64_t start, int64_t end) {
    static_cast<Crdt*>(h)->local_remove((uint32_t)start, (uint32_t)end);
}

void crdt_read(void* h, int32_t* out) { static_cast<Crdt*>(h)->read(out); }

// Incremental update: serialize ops[from_op..] (the version-frontier encoding
// capability; analog of reference src/rope.rs:214).  Returns bytes written,
// or -(bytes needed) if cap is too small.
int64_t crdt_encode_from(void* h, int64_t from_op, uint8_t* out, int64_t cap) {
    Crdt* c = static_cast<Crdt*>(h);
    int64_t n_ops = (int64_t)c->oplog.size() - from_op;
    if (n_ops < 0) n_ops = 0;
    int64_t need = n_ops * (int64_t)OP_WIRE;
    if (need > cap) return -need;
    for (int64_t i = 0; i < n_ops; i++)
        encode_op(c->oplog[(size_t)(from_op + i)], out + i * OP_WIRE);
    return need;
}

// Decode-and-merge one update (analog of decode_and_add, reference
// src/rope.rs:223).  Idempotent; unknown-origin ops are dropped.
void crdt_apply_update(void* h, const uint8_t* bytes, int64_t n) {
    Crdt* c = static_cast<Crdt*>(h);
    for (int64_t off = 0; off + (int64_t)OP_WIRE <= n; off += OP_WIRE)
        c->integrate(decode_op(bytes + off));
}

// Apply a batch of concatenated updates (offsets[i]..offsets[i+1] each) —
// the downstream hot loop (reference src/main.rs:65-67) in one native call.
int64_t crdt_apply_updates(void* h, const uint8_t* flat, const int64_t* offsets,
                           int64_t n_updates) {
    Crdt* c = static_cast<Crdt*>(h);
    for (int64_t u = 0; u < n_updates; u++) {
        const uint8_t* p = flat + offsets[u];
        int64_t nb = offsets[u + 1] - offsets[u];
        for (int64_t off = 0; off + (int64_t)OP_WIRE <= nb; off += OP_WIRE)
            c->integrate(decode_op(p + off));
    }
    return c->len();
}

// Replay patches on a fresh single-agent replica and dump the FULL final
// node order (slot = seq-1 per node, tombstones included), per-node final
// visibility, and the per-unit-op delete-target sequence (slot of each
// tombstoned char, in op order, from the op log).  This is the
// insertion-faithful order the range-granular update generation
// (engine/downstream_range.py) anchors against: local inserts splice
// DIRECTLY after their origin (insert_after), the same convention the
// receiver's anchor/rank apply reproduces — unlike a content-equivalent
// order variant, it keeps delete-interval contiguity exact.
// order_out/vis_out sized >= total nodes; dtarget_out sized >= total
// deletes.  Returns total node count (or -1 if caps insufficient).
int64_t crdt_replay_dump(const int32_t* init, int64_t init_n,
                         const int32_t* pos, const int32_t* del_count,
                         const int32_t* ins_off, const int32_t* ins_flat,
                         int64_t n_patches,
                         int32_t* order_out, int64_t order_cap,
                         uint8_t* vis_out,
                         int32_t* dtarget_out, int64_t dtarget_cap) {
    Crdt* c = crdt_make(init, init_n, 1);
    for (int64_t i = 0; i < n_patches; i++) {
        uint32_t p = (uint32_t)pos[i];
        uint32_t d = (uint32_t)del_count[i];
        if (d) c->local_remove(p, p + d);
        int32_t a = ins_off[i], b = ins_off[i + 1];
        if (b > a) c->local_insert(p, ins_flat + a, (size_t)(b - a));
    }
    int64_t total = (int64_t)call(c->root);
    int64_t n_del = 0;
    for (const Op& op : c->oplog)
        if (op.type == OP_DELETE) n_del++;
    if (total > order_cap || n_del > dtarget_cap) {
        c->free_all();
        delete c;
        return -1;
    }
    // full in-order traversal (tombstones included)
    std::vector<Node*> stack;
    Node* n = c->root;
    size_t k = 0;
    while (n || !stack.empty()) {
        while (n) { stack.push_back(n); n = n->l; }
        n = stack.back(); stack.pop_back();
        order_out[k] = (int32_t)(n->id.seq - 1);
        vis_out[k] = n->visible ? 1 : 0;
        k++;
        n = n->r;
    }
    k = 0;
    for (const Op& op : c->oplog)
        if (op.type == OP_DELETE)
            dtarget_out[k++] = (int32_t)(op.id.seq - 1);
    c->free_all();
    delete c;
    return total;
}

// Integrate a raw multi-agent op log (arrays of struct-of-array ops) into
// the replica — the independent native RGA oracle/baseline for the
// concurrent-merge path (engine/merge.py): same (seq=lamport, agent) id
// order, same insert-after-origin intention rule, entirely separate
// implementation (order-statistic treap + right-scan integration point).
// type: 1=INSERT, 2=DELETE (DELETE's id fields name the TARGET element);
// origin agent/seq = HEAD (0,0) for document-head inserts.  Returns the
// visible length after integration.
int64_t crdt_integrate_ops(void* h, int64_t n, const uint8_t* type,
                           const uint32_t* id_agent, const uint32_t* id_seq,
                           const uint32_t* org_agent, const uint32_t* org_seq,
                           const int32_t* ch) {
    Crdt* c = static_cast<Crdt*>(h);
    for (int64_t i = 0; i < n; i++) {
        Op op;
        op.type = type[i];
        op.id = Id{id_agent[i], id_seq[i]};
        op.origin = Id{org_agent[i], org_seq[i]};
        op.ch = ch[i];
        c->integrate(op);
    }
    return c->len();
}

// One timed upstream iteration entirely native: init + per-patch replace +
// final length (reference src/main.rs:28-37 semantics).
int64_t crdt_replay(const int32_t* init, int64_t init_n,
                    const int32_t* pos, const int32_t* del_count,
                    const int32_t* ins_off, const int32_t* ins_flat,
                    int64_t n_patches) {
    Crdt* c = crdt_make(init, init_n, 1);
    for (int64_t i = 0; i < n_patches; i++) {
        uint32_t p = (uint32_t)pos[i];
        uint32_t d = (uint32_t)del_count[i];
        if (d) c->local_remove(p, p + d);
        int32_t a = ins_off[i], b = ins_off[i + 1];
        if (b > a) c->local_insert(p, ins_flat + a, (size_t)(b - a));
    }
    int64_t out = c->len();
    c->free_all();
    delete c;
    return out;
}

// Untimed downstream generation (analog of upstream_updates, reference
// src/rope.rs:196-220): replay every patch on a fresh upstream replica,
// emitting one encoded update per patch (ops since the previous patch).
// Returns total bytes (or -needed if cap too small); offsets_out must hold
// n_patches+1 entries.
int64_t crdt_gen_updates(const int32_t* init, int64_t init_n,
                         const int32_t* pos, const int32_t* del_count,
                         const int32_t* ins_off, const int32_t* ins_flat,
                         int64_t n_patches, uint8_t* out, int64_t cap,
                         int64_t* offsets_out) {
    Crdt* c = crdt_make(init, init_n, 1);
    int64_t total = 0;
    offsets_out[0] = 0;
    for (int64_t i = 0; i < n_patches; i++) {
        size_t from = c->oplog.size();
        uint32_t p = (uint32_t)pos[i];
        uint32_t d = (uint32_t)del_count[i];
        if (d) c->local_remove(p, p + d);
        int32_t a = ins_off[i], b = ins_off[i + 1];
        if (b > a) c->local_insert(p, ins_flat + a, (size_t)(b - a));
        int64_t n_ops = (int64_t)(c->oplog.size() - from);
        int64_t need = n_ops * (int64_t)OP_WIRE;
        if (total + need <= cap) {
            for (int64_t k = 0; k < n_ops; k++)
                encode_op(c->oplog[from + (size_t)k], out + total + k * OP_WIRE);
        }
        total += need;
        offsets_out[i + 1] = total;
    }
    c->free_all();
    delete c;
    return total <= cap ? total : -total;
}

}  // extern "C"
