// CPU rope baseline — the native tier of crdt_benches_tpu.
//
// Re-provides the capability of the reference's Upstream trait surface
// (reference src/rope.rs:6-33: from_str/insert/remove/len/replace with
// replace = remove-then-insert) as a gap buffer over int32 codepoints, plus a
// one-call whole-trace replay entry so the benchmark hot loop
// (reference src/main.rs:30-34) runs entirely in native code rather than
// through per-op FFI calls.
//
// A gap buffer is the right CPU baseline for these workloads: real editing
// traces are overwhelmingly local, so the gap rarely moves far and most ops
// are O(1) amortized; worst case is O(distance) memmove.  Exposed through a
// plain C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <algorithm>

namespace {

struct Rope {
    int32_t* buf;       // [0, gap_start) ++ [gap_end, cap) is the document
    size_t cap;
    size_t gap_start;
    size_t gap_end;

    size_t len() const { return cap - (gap_end - gap_start); }

    void reserve(size_t need) {
        size_t gap = gap_end - gap_start;
        if (gap >= need) return;
        size_t new_cap = std::max(cap * 2, cap + need + 4096);
        int32_t* nb = static_cast<int32_t*>(malloc(new_cap * sizeof(int32_t)));
        size_t tail = cap - gap_end;
        memcpy(nb, buf, gap_start * sizeof(int32_t));
        memcpy(nb + new_cap - tail, buf + gap_end, tail * sizeof(int32_t));
        free(buf);
        buf = nb;
        gap_end = new_cap - tail;
        cap = new_cap;
    }

    void move_gap(size_t at) {  // place gap_start at document position `at`
        if (at < gap_start) {
            size_t n = gap_start - at;
            memmove(buf + gap_end - n, buf + at, n * sizeof(int32_t));
            gap_start = at;
            gap_end -= n;
        } else if (at > gap_start) {
            size_t n = at - gap_start;
            memmove(buf + gap_start, buf + gap_end, n * sizeof(int32_t));
            gap_start = at;
            gap_end += n;
        }
    }

    void insert(size_t at, const int32_t* codes, size_t n) {
        if (at > len()) at = len();
        reserve(n);
        move_gap(at);
        memcpy(buf + gap_start, codes, n * sizeof(int32_t));
        gap_start += n;
    }

    void remove(size_t start, size_t end) {
        size_t l = len();
        if (start > l) start = l;
        if (end > l) end = l;
        if (end <= start) return;
        move_gap(start);
        gap_end += end - start;
    }

    void read(int32_t* out) const {
        memcpy(out, buf, gap_start * sizeof(int32_t));
        memcpy(out + gap_start, buf + gap_end, (cap - gap_end) * sizeof(int32_t));
    }
};

Rope* make(const int32_t* codes, size_t n) {
    Rope* r = new Rope;
    size_t cap = std::max<size_t>(n * 2 + 4096, 8192);
    r->buf = static_cast<int32_t*>(malloc(cap * sizeof(int32_t)));
    r->cap = cap;
    memcpy(r->buf, codes, n * sizeof(int32_t));
    r->gap_start = n;
    r->gap_end = cap;
    return r;
}

}  // namespace

extern "C" {

void* rope_new(const int32_t* codes, int64_t n) { return make(codes, (size_t)n); }

void rope_free(void* h) {
    Rope* r = static_cast<Rope*>(h);
    free(r->buf);
    delete r;
}

int64_t rope_len(void* h) { return (int64_t)static_cast<Rope*>(h)->len(); }

void rope_insert(void* h, int64_t at, const int32_t* codes, int64_t n) {
    static_cast<Rope*>(h)->insert((size_t)at, codes, (size_t)n);
}

void rope_remove(void* h, int64_t start, int64_t end) {
    static_cast<Rope*>(h)->remove((size_t)start, (size_t)end);
}

void rope_read(void* h, int32_t* out) { static_cast<Rope*>(h)->read(out); }

// One timed benchmark iteration, entirely native: doc init from start
// content, per-patch replace (remove-then-insert, reference src/rope.rs:21-32),
// returns the final length (the reference's length oracle, src/main.rs:35).
//
// Patch layout (from the Python trace layer): pos[i], del_count[i], and the
// insert text for patch i is ins_flat[ins_off[i] .. ins_off[i+1]).
int64_t rope_replay(const int32_t* init, int64_t init_n,
                    const int32_t* pos, const int32_t* del_count,
                    const int32_t* ins_off, const int32_t* ins_flat,
                    int64_t n_patches) {
    Rope* r = make(init, (size_t)init_n);
    for (int64_t i = 0; i < n_patches; i++) {
        size_t p = (size_t)pos[i];
        size_t d = (size_t)del_count[i];
        if (d) r->remove(p, p + d);
        int32_t a = ins_off[i], b = ins_off[i + 1];
        if (b > a) r->insert(p, ins_flat + a, (size_t)(b - a));
    }
    int64_t out = (int64_t)r->len();
    free(r->buf);
    delete r;
    return out;
}

// Replay and also write the final document (for byte-identical checks).
// Returns final length; writes at most out_cap codepoints.
int64_t rope_replay_read(const int32_t* init, int64_t init_n,
                         const int32_t* pos, const int32_t* del_count,
                         const int32_t* ins_off, const int32_t* ins_flat,
                         int64_t n_patches, int32_t* out, int64_t out_cap) {
    Rope* r = make(init, (size_t)init_n);
    for (int64_t i = 0; i < n_patches; i++) {
        size_t p = (size_t)pos[i];
        size_t d = (size_t)del_count[i];
        if (d) r->remove(p, p + d);
        int32_t a = ins_off[i], b = ins_off[i + 1];
        if (b > a) r->insert(p, ins_flat + a, (size_t)(b - a));
    }
    int64_t n = (int64_t)r->len();
    if (n <= out_cap) r->read(out);
    free(r->buf);
    delete r;
    return n;
}

}  // extern "C"
