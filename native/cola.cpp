// Content-free sequence-CRDT replica — the cola capability of the reference
// (reference src/rope.rs:79-101): a replica that stores NO text at all, only
// CRDT metadata.  `cola::Replica::new(1, s.len())` seeds from a LENGTH, every
// edit is `(offset, length)`, and the only readback is `len()` — the cheapest
// possible upstream form, exercised here so the framework reproduces the
// reference's lengths-only adapter shape (VERDICT r3 missing #2).
//
// Design (original, shared with nothing in the reference): an implicit-key
// split/merge treap whose nodes are RUNS of consecutively-inserted elements
// (cola is likewise run-length-encoded internally).  Each run keeps only
//   - len       element count (bytes, since cola is byte-addressed)
//   - (agent, seq0)  the id range [seq0, seq0+len) — CRDT identity metadata,
//                    so runs are real addressable insertions, not bare ints
//   - vis       whole-run visibility; partial deletes split the run
// Tombstoned runs STAY in the tree (cola keeps them as anchors); a lazy
// kill flag makes range-delete O(log n) instead of O(runs covered).
// Subtree visible totals give offset->run resolution in O(log n).

#include <cstdint>
#include <deque>

namespace {

struct CNode {
    CNode *l = nullptr, *r = nullptr;
    uint64_t prio;
    uint64_t sum_vis;   // visible elements in subtree
    uint32_t len;
    uint32_t agent;
    uint32_t seq0;
    bool vis;
    bool lazy_kill;
};

inline uint64_t svis(CNode* n) { return n ? n->sum_vis : 0; }

struct Cola {
    CNode* root = nullptr;
    std::deque<CNode> arena;    // deque: stable addresses on push_back
    uint32_t agent = 1;
    uint32_t next_seq = 1;
    uint64_t rng = 0x9E3779B97F4A7C15ull;

    uint64_t rand64() {
        rng ^= rng << 13; rng ^= rng >> 7; rng ^= rng << 17;
        return rng;
    }

    CNode* alloc(uint32_t len, uint32_t agent_, uint32_t seq0, bool vis) {
        arena.push_back(CNode{nullptr, nullptr, rand64(), 0,
                              len, agent_, seq0, vis, false});
        CNode* n = &arena.back();
        n->sum_vis = vis ? len : 0;
        return n;
    }

    static void pull(CNode* n) {
        n->sum_vis = svis(n->l) + svis(n->r) + (n->vis ? n->len : 0);
    }

    static void kill(CNode* n) {
        if (!n) return;
        n->vis = false;
        n->sum_vis = 0;
        n->lazy_kill = true;
    }

    static void push(CNode* n) {
        if (n->lazy_kill) {
            kill(n->l);
            kill(n->r);
            n->lazy_kill = false;
        }
    }

    CNode* merge(CNode* a, CNode* b) {
        if (!a) return b;
        if (!b) return a;
        if (a->prio >= b->prio) {
            push(a);
            a->r = merge(a->r, b);
            pull(a);
            return a;
        }
        push(b);
        b->l = merge(a, b->l);
        pull(b);
        return b;
    }

    // Split off the first v VISIBLE elements.  A cut strictly inside a
    // visible run splits the run into two nodes with adjacent id ranges
    // (identity is preserved: [seq0, seq0+k) | [seq0+k, seq0+len)).
    void split(CNode* t, uint64_t v, CNode*& a, CNode*& b) {
        if (!t) { a = b = nullptr; return; }
        push(t);
        uint64_t lv = svis(t->l);
        uint64_t my = t->vis ? t->len : 0;
        if (v <= lv) {
            split(t->l, v, a, t->l);
            pull(t);
            b = t;
            return;
        }
        if (v < lv + my) {  // cut inside this visible run
            uint32_t k = (uint32_t)(v - lv);
            CNode* left = alloc(k, t->agent, t->seq0, true);
            t->len -= k;
            t->seq0 += k;
            CNode* lsub = t->l;
            t->l = nullptr;
            pull(t);
            a = merge(lsub, left);
            b = t;
            return;
        }
        split(t->r, v - lv - my, t->r, b);
        pull(t);
        a = t;
        return;
    }

    void insert(uint64_t at, uint32_t n) {
        if (n == 0) return;
        CNode *a, *b;
        split(root, at, a, b);
        CNode* run = alloc(n, agent, next_seq, true);
        next_seq += n;
        root = merge(merge(a, run), b);
    }

    void remove(uint64_t start, uint64_t end) {
        if (end <= start) return;
        CNode *ab, *c, *a, *b;
        split(root, end, ab, c);
        split(ab, start, a, b);
        kill(b);  // tombstones retained as anchors, subtree-lazily
        root = merge(merge(a, b), c);
    }

    uint64_t len() const { return svis(root); }
};

Cola* cola_make(int64_t init_len) {
    Cola* c = new Cola();
    if (init_len > 0) {
        // the base document is agent 0's run (the seed text of
        // Replica::new, reference src/rope.rs:91-93)
        CNode* run = c->alloc((uint32_t)init_len, 0, 1, true);
        c->root = run;
    }
    return c;
}

}  // namespace

extern "C" {

void* cola_new(int64_t init_len) { return cola_make(init_len); }

void cola_free(void* h) { delete (Cola*)h; }

int64_t cola_len(void* h) { return (int64_t)((Cola*)h)->len(); }

void cola_insert(void* h, int64_t at, int64_t n) {
    ((Cola*)h)->insert((uint64_t)at, (uint32_t)n);
}

void cola_remove(void* h, int64_t start, int64_t end) {
    ((Cola*)h)->remove((uint64_t)start, (uint64_t)end);
}

// Whole-trace replay in one call (the bench hot loop; analog of
// rope_replay/crdt_replay): fresh lengths-only replica + every patch as
// remove-then-insert (the Upstream::replace default, reference
// src/rope.rs:21-32) + final length.  No character data crosses the FFI —
// only offsets and lengths, which is the point of this backend.
int64_t cola_replay(int64_t init_len, const int32_t* pos,
                    const int32_t* del_count, const int32_t* ins_off,
                    int64_t n_patches) {
    Cola* c = cola_make(init_len);
    for (int64_t i = 0; i < n_patches; i++) {
        uint64_t p = (uint64_t)pos[i];
        int32_t d = del_count[i];
        if (d > 0) c->remove(p, p + (uint64_t)d);
        int32_t n = ins_off[i + 1] - ins_off[i];
        if (n > 0) c->insert(p, (uint32_t)n);
    }
    int64_t out = (int64_t)c->len();
    delete c;
    return out;
}

}  // extern "C"
