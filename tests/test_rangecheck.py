"""graftlint v6 headline harness: the dtype-edge adversarial drains
(serve/edgecheck.py) run armed and byte-exact across both kernels, the
G029 cross-check is green in both directions on a real sanitized bench
artifact (and red on a doctored one), and the ``ranges`` block rides
bench_compare's one-sided skip matrix."""

import importlib.util
import json
import os
import pathlib
import sys

import pytest

from crdt_benches_tpu.lint import range_sanitizer as rs
from crdt_benches_tpu.lint.core import run_lint

PACKAGE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "crdt_benches_tpu")

_BANDS = {"synth-small": ("synth", (8, 36))}
_MIX = {"synth-small": 1.0}


@pytest.fixture(autouse=True)
def _rs_reset(monkeypatch):
    """Every test owns a clean sanitizer window."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_RANGES", raising=False)
    rs.disarm()
    rs.reset_counters()
    yield
    rs.disarm()
    rs.reset_counters()


# ---------------------------------------------------------------------------
# the headline drain
# ---------------------------------------------------------------------------


def test_edgecheck_small_is_byte_exact_with_full_coverage(tmp_path):
    """THE graftlint v6 acceptance gate (tier-1 shape): the structural
    dtype-edge fleet — position extremes, empty churn, the zero-op
    all-PAD stream, exact-capacity landings, id pressure — drains
    armed through BOTH kernels, every doc oracle- and cross-kernel
    byte-identical, every required range check and mask counter
    nonzero, and every boundary contract rejects its edge
    perturbations."""
    from crdt_benches_tpu.serve.edgecheck import (
        _REQUIRED_CHECKS, _REQUIRED_MASKS, run_edgecheck)

    report = run_edgecheck(str(tmp_path), small=True)
    assert set(report["ladders"]) == {"small-ladder"}
    lad = report["ladders"]["small-ladder"]
    assert lad["docs"] >= 9
    assert lad["rounds"]["fused"] > 0 and lad["rounds"]["scan"] > 0
    for name in _REQUIRED_CHECKS:
        assert report["checks"].get(name), report["checks"]
    for tag in _REQUIRED_MASKS:
        assert report["masks"].get(tag), report["masks"]
    fuzz = report["boundary_fuzz"]
    assert fuzz["contracts"] >= 10
    assert fuzz["rejected"] > 0
    assert all(n > 0 for n in fuzz["per_entry"].values())
    # the harness leaves the sanitizer disarmed for the rest of the suite
    assert not rs.armed()


def test_edgecheck_cli_exit_codes(tmp_path, capsys):
    from crdt_benches_tpu.serve import edgecheck

    assert edgecheck.main(["--bogus"]) == 2
    assert "usage:" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# G029 cross-check on a real sanitized bench artifact
# ---------------------------------------------------------------------------


def test_g029_cross_check_clean_both_directions(tmp_path, monkeypatch):
    """A sanitized fused-kernel drain emits a ``ranges`` block that
    cross-checks clean against the static ``inrange=``/``mask=``
    markers in BOTH directions: no dead declared fact or mask on an
    armed surface, no rogue runtime counter."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_RANGES", "1")
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=_MIX, bands=_BANDS,
        n_docs=10, batch=16, classes=(256,), slots=(2,),
        macro_k=2, batch_chars=64, arrival_span=2, verify_sample=3,
        results_dir=str(tmp_path), save_name="rg_smoke",
        log=lambda s: None,
    )
    assert info["verify_ok"]
    block = r.extra["ranges"]
    assert block["version"] == 1 and block["sanitized"]
    assert block["staging"] and block["fused"] and not block["scan"]
    # the scheduler's batched install path keeps the write-row check
    # alive (DocPool.admit is NOT on this path — upload_bucket is)
    assert block["checks"].get("pool.write-row", 0) > 0
    assert block["checks"].get("pool.macro-pos", 0) > 0
    assert block["checks"].get("pool.macro-ids", 0) > 0
    assert block["masks"].get("count-le-clamp", 0) > 0
    assert block["masks"].get("fused-gap-gather", 0) > 0
    artifact = str(tmp_path / "rg_smoke.json")
    assert os.path.exists(artifact)
    findings = run_lint([PACKAGE], select={"G029"},
                        ranges_artifact=artifact)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line} {f.rule} {f.msg}" for f in findings
    )


def test_g029_flags_dead_fact_dead_mask_and_rogue_on_doctored(tmp_path):
    """All the failure directions against a doctored block: every
    declared staging fact/mask is dead (the doctored run counted none
    of them), the fused-scoped mask is NOT dead-checked (that surface
    was not armed), and rogue runtime counters are flagged against the
    artifact."""
    artifact = tmp_path / "doctored.json"
    artifact.write_text(json.dumps({"ranges": {
        "version": 1, "sanitized": True,
        "staging": True, "fused": False, "scan": False,
        "checks": {"ghost.check": 3},
        "masks": {"rogue-tag": 2},
    }}))
    findings = run_lint([PACKAGE], select={"G029"},
                        ranges_artifact=str(artifact))
    msgs = [f.msg for f in findings]
    assert any("`pool.write-row`" in m and "dead fact" in m for m in msgs)
    assert any("`pool.macro-pos`" in m and "dead fact" in m for m in msgs)
    assert any("`count-le-clamp`" in m for m in msgs)
    # fused not armed in the doctored run -> the fused gap-gather mask
    # is out of scope, not dead
    assert not any("fused-gap-gather" in m for m in msgs)
    assert any("`ghost.check`" in m for m in msgs)
    assert any("`rogue-tag`" in m for m in msgs)


# ---------------------------------------------------------------------------
# bench_compare: the ranges block rides the one-sided matrix
# ---------------------------------------------------------------------------


def _bench_compare():
    repo = pathlib.Path(PACKAGE).parent
    spec = importlib.util.spec_from_file_location(
        "bench_compare_ranges", repo / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare_ranges"] = mod
    spec.loader.exec_module(mod)
    return mod


def _compare_artifact(tmp_path, name: str, *, ranges: bool) -> str:
    extra = {
        "family": "serve",
        "patches_per_sec": 100_000.0,
        "batch_latency": {"p50": 0.001, "p95": 0.004, "p99": 0.005},
        "rounds": 40,
        "range_ops": 10_000,
        "journal": None,
    }
    if ranges:
        extra["ranges"] = {
            "version": 1, "sanitized": True,
            "staging": True, "fused": True, "scan": False,
            "checks": {"pool.write-row": 40},
            "masks": {"count-le-clamp": 40},
        }
    data = [{"group": "serve", "trace": "mixed", "backend": "512",
             "extra": extra}]
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_bench_compare_ranges_block_skips_both_directions(
        tmp_path, capsys):
    """A sanitized run diffed against a pre-v6 baseline (and vice
    versa) is a schema difference, never an error: the ranges block is
    a skip-with-note in both directions, and matched pairs diff
    silently."""
    bc = _bench_compare()
    with_rg = _compare_artifact(tmp_path, "rg.json", ranges=True)
    without = _compare_artifact(tmp_path, "plain.json", ranges=False)
    for pair in ((with_rg, without), (without, with_rg)):
        assert bc.main(list(pair)) == 0
        out = capsys.readouterr().out
        assert "SKIP" in out and "ranges block" in out
        assert "present only in" in out
    # both sides carrying the block is NOT a schema difference
    assert bc.main([with_rg, with_rg]) == 0
    assert "ranges block" not in capsys.readouterr().out


# ---------------------------------------------------------------------------
# runner rejection matrix (exit 2 — rejected before any fleet is built)
# ---------------------------------------------------------------------------


_REJECTS = [
    (["--serve-edgecheck", "small", "--serve-stream"], "stream"),
    (["--serve-edgecheck", "small", "--serve-journal", "auto"],
     "journal"),
    (["--serve-edgecheck", "small", "--serve-mesh", "3"], "mesh"),
    (["--serve-edgecheck", "small", "--serve-writers", "2"], "writers"),
    (["--serve-edgecheck", "small", "--serve-open", "32"], "open"),
    (["--serve-edgecheck", "small", "--serve-record-evict"],
     "record-evict"),
    (["--serve-edgecheck", "bogus"], "bad-mode"),
]


@pytest.mark.parametrize("extra,tag", _REJECTS,
                         ids=[t for _, t in _REJECTS])
def test_runner_rejects_edgecheck_conflicts(extra, tag):
    """--serve-edgecheck owns its fleets, both kernels, and the armed
    sanitizer: bench-drain-shaping flags are usage errors — exit 2
    with a message naming the flag, no fleet built."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "crdt_benches_tpu.bench.runner",
         "--family", "serve", "--serve-docs", "8"] + extra,
        capture_output=True, text=True, timeout=120,
        cwd=str(pathlib.Path(PACKAGE).parent),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2, proc.stderr
    assert "--serve-edgecheck" in proc.stderr
    if tag != "bad-mode":  # bad-mode is argparse's own choices error
        assert "not supported with" in proc.stderr, proc.stderr
