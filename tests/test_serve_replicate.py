"""serve/replicate/: multi-writer groups, broadcast merge, convergence.

Ground truth everywhere is the sequential oracle replay of the logical
stream: the writer group's arbitration order (ascending turn-block
sequence) concatenates back to exactly that stream, so EVERY replica —
through broadcast delivery, downstream merge in the macro scan, churn,
chaos, and crash recovery — must land byte-identical to it.  The
RA-linearizability checker is additionally tested as a checker: doctored
histories must be caught (a verifier that cannot fail verifies nothing).
"""

import numpy as np
import pytest

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.faults import FaultInjector, FaultPlan
from crdt_benches_tpu.serve.journal import OpJournal
from crdt_benches_tpu.serve.pool import DocPool, decode_row_np
from crdt_benches_tpu.serve.replicate import (
    ConvergenceReport,
    ReplicatedScheduler,
    build_writer_groups,
    check_convergence,
    check_ra_linearizability,
    recover_replicated_fleet,
)
from crdt_benches_tpu.serve.replicate.checker import _axiom_violations
from crdt_benches_tpu.serve.replicate.group import ReplicaGroup
from crdt_benches_tpu.serve.scheduler import (
    FleetScheduler,
    prepare_streams,
)
from crdt_benches_tpu.serve.workload import (
    build_fleet,
    split_turns,
)

TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


def _fleet(n_docs, writers, tmp_path, *, seed=3, slots=(8, 4),
           arrival_span=2, serve_kernel="fused", **sched_kw):
    sessions = build_fleet(
        n_docs, mix=TINY_MIX, seed=seed, arrival_span=arrival_span,
        bands=TINY_BANDS,
    )
    reps, table = build_writer_groups(sessions, writers)
    pool = DocPool(classes=(128, 512), slots=slots,
                   spool_dir=str(tmp_path), serve_kernel=serve_kernel)
    streams = prepare_streams(reps, pool, batch=16)
    sched = ReplicatedScheduler(
        pool, streams, table, batch=16,
        **{"turn_ops": 8, "macro_k": 4, **sched_kw},
    )
    return sessions, table, pool, streams, sched


def _check(pool, table, sessions, streams, bus=None):
    rep = ConvergenceReport()
    check_convergence(pool, table, sessions, streams, rep)
    if bus is not None:
        check_ra_linearizability(bus, table, rep)
    return rep


# ---- the turn split --------------------------------------------------------


def test_split_turns_partitions_round_robin():
    blocks = split_turns(21, writers=3, turn_ops=4)
    # contiguous partition of [0, 21)
    assert blocks[0][0] == 0 and blocks[-1][1] == 21
    for (lo, hi, _w), (lo2, _hi2, _w2) in zip(blocks, blocks[1:]):
        assert hi == lo2 and hi > lo
    # round-robin authorship, deterministic
    assert [w for _lo, _hi, w in blocks] == [0, 1, 2, 0, 1, 2]
    assert split_turns(21, 3, 4) == blocks
    with pytest.raises(ValueError):
        split_turns(10, 0, 4)


def test_remote_interval_attribution():
    g = ReplicaGroup(logical_id=0, writers=2, replica_ids=(0, 1),
                     blocks=split_turns(20, 2, 4), n_ops=20)
    # writer 0 owns [0,4) [8,12) [16,20); writer 1 the complement
    assert g.remote_intervals(0, 0, 20) == [(4, 8), (12, 16)]
    loc, rem = g.split_local_remote(0, 2, 10)
    assert (loc, rem) == (4, 4)
    loc, rem = g.split_local_remote(1, 2, 10)
    assert (loc, rem) == (4, 4)
    assert g.split_local_remote(0, 5, 5) == (0, 0)


# ---- convergence across topologies -----------------------------------------


def test_two_writer_groups_converge_byte_identical(tmp_path):
    """2-writer groups across both capacity classes: every replica
    byte-identical to the oracle, RA axioms hold on every sampled
    history, and the merge/broadcast accounting balances."""
    sessions, table, pool, streams, sched = _fleet(
        6, 2, tmp_path, history_sample=6,
    )
    stats = sched.run()
    assert sched.done
    rep = _check(pool, table, sessions, streams, sched.bus)
    assert rep.converged and rep.replicas_checked == 12
    assert rep.ra_ok and rep.ra_groups_checked == 6
    # with 2 writers and a fair round-robin split, local and remote
    # shares are exactly equal, and they partition the applied ops
    assert sched.merged_ops == sched.local_ops
    assert sched.merged_ops + sched.local_ops == stats.ops
    # labeled per-class counters partition the totals (sum parity, the
    # obs/shard.py series discipline)
    m_ops, m_units = sched.replica_metrics.merged_total()
    assert (m_ops, m_units) == (sched.merged_ops, sched.merged_unit_ops)
    # every block reaches exactly W-1 remote replicas; fan-out bytes
    # are the delivered remote ops at the packed lane width
    nbytes = sum(dt.itemsize for dt in pool.op_dtypes)
    assert sched.bus.bytes_broadcast == sched.merged_ops * nbytes
    assert sched.bus.divergence_max >= 1  # remote lag is real
    pool.close()


def test_four_writer_groups_with_churn(tmp_path):
    """4-writer groups through a pool small enough to force eviction/
    restore churn on replica rows: replica rows ARE pool rows."""
    sessions, table, pool, streams, sched = _fleet(
        5, 4, tmp_path, slots=(6, 3), history_sample=5,
    )
    stats = sched.run()
    assert sched.done
    assert stats.evictions > 0 and stats.restores > 0
    rep = _check(pool, table, sessions, streams, sched.bus)
    assert rep.converged and rep.replicas_checked == 20
    assert rep.ra_ok
    # 4 writers: each replica merges 3/4 of the stream remotely
    assert sched.merged_ops > sched.local_ops
    pool.close()


def test_k1_vs_k8_byte_parity(tmp_path):
    """The macro depth must not change any replica's bytes (the K=1
    degenerate form and the deep pipelined form agree)."""
    decoded = {}
    for k in (1, 8):
        sessions, table, pool, streams, sched = _fleet(
            5, 2, tmp_path / f"k{k}", macro_k=k,
        )
        sched.run()
        assert sched.done
        decoded[k] = {
            rid: pool.decode(rid)
            for g in table for rid in g.replica_ids
        }
        rep = _check(pool, table, sessions, streams)
        assert rep.converged
        pool.close()
    assert decoded[1] == decoded[8]


def test_fused_vs_scan_kernel_repl_parity(tmp_path):
    """Both serve kernels carry the replicated merge: the scan form
    (routed through engine/merge_fleet.py merge_rows_body) and the
    fused form produce byte-identical replicas — and both converge to
    the oracle."""
    decoded = {}
    for kernel in ("fused", "scan"):
        sessions, table, pool, streams, sched = _fleet(
            4, 2, tmp_path / kernel, serve_kernel=kernel,
        )
        sched.run()
        assert sched.done
        decoded[kernel] = {
            rid: pool.decode(rid)
            for g in table for rid in g.replica_ids
        }
        rep = _check(pool, table, sessions, streams)
        assert rep.converged, (kernel, rep.byte_mismatches[:3])
        pool.close()
    assert decoded["fused"] == decoded["scan"]


def test_writers1_matches_plain_scheduler(tmp_path):
    """A 1-writer group is the plain fleet: same docs, same bytes, no
    remote merge anywhere — the replication plumbing adds nothing when
    replication is off."""
    sessions = build_fleet(5, mix=TINY_MIX, seed=11, arrival_span=2,
                           bands=TINY_BANDS)
    pool_a = DocPool(classes=(128, 512), slots=(8, 4),
                     spool_dir=str(tmp_path / "a"))
    st_a = prepare_streams(sessions, pool_a, batch=16)
    FleetScheduler(pool_a, st_a, batch=16, macro_k=4).run()

    reps, table = build_writer_groups(sessions, 1)
    pool_b = DocPool(classes=(128, 512), slots=(8, 4),
                     spool_dir=str(tmp_path / "b"))
    st_b = prepare_streams(reps, pool_b, batch=16)
    sched = ReplicatedScheduler(pool_b, st_b, table, batch=16,
                                macro_k=4, turn_ops=8)
    sched.run()
    assert sched.done
    assert sched.merged_ops == 0 and sched.bus.bytes_broadcast == 0
    for s in sessions:
        assert pool_a.decode(s.doc_id) == pool_b.decode(s.doc_id)
    pool_a.close()
    pool_b.close()


# ---- churn + divergence ----------------------------------------------------


def test_mid_macro_evict_restore_of_diverged_replica(tmp_path):
    """Force one replica out through the checkpoint spool while its
    writer group is mid-divergence (its peers' cursors differ), then
    finish the drain: the spool round-trip must preserve the replica's
    partial merge state and still reconverge byte-exactly."""
    sessions, table, pool, streams, sched = _fleet(
        5, 2, tmp_path, macro_k=2,
    )
    victim = None
    for _ in range(40):
        assert sched.run_round()
        cand = [
            rid for g in table for rid in g.replica_ids
            if 0 < streams[rid].cursor < streams[rid].n_total
            and pool.docs[rid].cls is not None
        ]
        # prefer a replica whose group peers sit at a DIFFERENT cursor
        # (genuinely mid-divergence)
        for rid in cand:
            g, w = table.group_of(rid)
            peers = [streams[o].cursor for o in g.replica_ids if o != rid]
            if peers and any(p != streams[rid].cursor for p in peers):
                victim = rid
                break
        if victim is not None:
            break
    assert victim is not None, "no mid-divergence resident replica found"
    spool = pool.evict(victim)
    assert spool and pool.docs[victim].cls is None
    sched.run()
    assert sched.done
    rep = _check(pool, table, sessions, streams)
    assert rep.converged, rep.byte_mismatches[:3]
    pool.close()


def test_replica_partition_heals_and_reconverges(tmp_path):
    """The replica_partition chaos kind: broadcasts to one replica drop
    for a span (divergence window grows), the heal flushes the backlog,
    and the fleet reconverges — event fired AND recovered."""
    plan = FaultPlan.from_spec("seed=5,span=4,replica_partition=1")
    sessions, table, pool, streams, sched = _fleet(
        6, 2, tmp_path, faults=FaultInjector(plan), history_sample=6,
    )
    sched.run()
    assert sched.done
    ev = plan.events[0]
    assert ev.fired and ev.recovered, ev.to_dict()
    assert sched.bus.partitions_healed == 1
    assert sched.bus.divergence_max > 1  # the window visibly grew
    rep = _check(pool, table, sessions, streams, sched.bus)
    assert rep.converged and rep.ra_ok
    pool.close()


def test_merge_reorder_commutes(tmp_path):
    """The merge_reorder chaos kind: one round's remote batches arrive
    writer-permuted; sequence-keyed reassembly makes delivery order
    commute, so byte parity AND the RA axioms stay green."""
    plan = FaultPlan.from_spec("seed=2,span=3,merge_reorder=1")
    sessions, table, pool, streams, sched = _fleet(
        6, 3, tmp_path, faults=FaultInjector(plan), history_sample=6,
    )
    sched.run()
    assert sched.done
    ev = plan.events[0]
    assert ev.fired and ev.recovered and ev.detail.get("commuted")
    assert sched.bus.reordered_rounds >= 1
    rep = _check(pool, table, sessions, streams, sched.bus)
    assert rep.converged and rep.ra_ok
    pool.close()


# ---- the engine merge path -------------------------------------------------


def test_merge_rows_macro_equals_sequential_oracle(tmp_path):
    """The engine's batched downstream-merge entry points
    (engine/merge_fleet.py): replaying a 3-writer group's assembled
    broadcast stream over a fresh replica row — K rounds in one
    merge_rows_macro dispatch, AND round-by-round through
    merge_rows_round — equals the sequential oracle interleaving
    byte-for-byte."""
    import jax.numpy as jnp

    from crdt_benches_tpu.engine.merge_fleet import (
        merge_rows_macro,
        merge_rows_round,
    )
    from crdt_benches_tpu.ops.packing import widen_ops
    from crdt_benches_tpu.serve.pool import PackedState, _fresh_row_np
    from crdt_benches_tpu.traces.synth import synth_trace
    from crdt_benches_tpu.serve.workload import Session

    trace = synth_trace(seed=77, n_ops=120)
    sessions = [Session(doc_id=0, band="synth-medium", source="synth",
                        trace=trace)]
    reps, table = build_writer_groups(sessions, 3)
    pool = DocPool(classes=(512,), slots=(4,), spool_dir=str(tmp_path))
    streams = prepare_streams(reps, pool, batch=16)
    st = streams[0]
    n = st.n_total
    # stage the whole assembled stream as K slices of (1, B) ops — the
    # broadcast order is the stream order, so this IS the merge the
    # replicas perform, minus the scheduling
    B = 16
    slices = []
    c = 0
    while c < n:
        e = st.slice_end(c, B, 256, n)
        slices.append((c, e))
        c = e
    K = len(slices)
    kind = np.zeros((K, 1, B), np.int32)
    pos = np.zeros((K, 1, B), np.int32)
    rlen = np.zeros((K, 1, B), np.int32)
    slot0 = np.zeros((K, 1, B), np.int32)
    wide = widen_ops(st.kind, st.pos, st.rlen, st.slot0)
    for k, (lo, hi) in enumerate(slices):
        take = hi - lo
        kind[k, 0, :take] = wide[0][lo:hi]
        pos[k, 0, :take] = wide[1][lo:hi]
        rlen[k, 0, :take] = wide[2][lo:hi]
        slot0[k, 0, :take] = wide[3][lo:hi]
    rec = pool.docs[0]
    state = PackedState(
        doc=jnp.asarray(_fresh_row_np(512, rec.n_init)[None]),
        length=jnp.asarray([rec.n_init], jnp.int32),
        nvis=jnp.asarray([rec.n_init], jnp.int32),
    )
    out = merge_rows_macro(
        state, jnp.asarray(kind), jnp.asarray(pos), jnp.asarray(rlen),
        jnp.asarray(slot0), nbits=9,
    )
    got = decode_row_np(
        np.asarray(out.doc[0]), int(out.length[0]), int(out.nvis[0]),
        rec.chars,
    )
    assert got == replay_trace(trace)
    # round-by-round through the single-round entry: same bytes
    state2 = PackedState(
        doc=jnp.asarray(_fresh_row_np(512, rec.n_init)[None]),
        length=jnp.asarray([rec.n_init], jnp.int32),
        nvis=jnp.asarray([rec.n_init], jnp.int32),
    )
    for k in range(K):
        state2 = merge_rows_round(
            state2, jnp.asarray(kind[k]), jnp.asarray(pos[k]),
            jnp.asarray(rlen[k]), jnp.asarray(slot0[k]), nbits=9,
        )
    got2 = decode_row_np(
        np.asarray(state2.doc[0]), int(state2.length[0]),
        int(state2.nvis[0]), rec.chars,
    )
    assert got2 == got
    pool.close()


# ---- the checker checks ----------------------------------------------------


def _clean_history(group, rounds_apart=1):
    """A synthetic axiom-clean history: every block published at round
    seq, locally delivered at publish, remotely one round later."""
    publish_log = [(seq, seq) for seq in range(group.n_blocks)]
    hist = [[] for _ in range(group.writers)]
    for seq in range(group.n_blocks):
        owner = group.owner(seq)
        hist[owner].append((seq, seq))
        for w in range(group.writers):
            if w != owner:
                hist[w].append((seq + rounds_apart, seq))
    return hist, publish_log


def test_ra_checker_accepts_clean_and_rejects_doctored():
    g = ReplicaGroup(logical_id=7, writers=2, replica_ids=(14, 15),
                     blocks=split_turns(24, 2, 4), n_ops=24)
    hist, plog = _clean_history(g)
    assert _axiom_violations(7, g, hist, plog) == []

    # A1: one writer's blocks observed out of program order
    bad = [list(h) for h in hist]
    i = next(i for i, (_r, s) in enumerate(bad[1]) if g.owner(s) == 0)
    j = next(j for j in range(i + 1, len(bad[1]))
             if g.owner(bad[1][j][1]) == 0)
    bad[1][i], bad[1][j] = bad[1][j], bad[1][i]
    axioms = {v["axiom"] for v in _axiom_violations(7, g, bad, plog)}
    assert "A1-session-order" in axioms

    # A2: duplicate delivery
    bad = [list(h) for h in hist]
    bad[0].append(bad[0][0])
    axioms = {v["axiom"] for v in _axiom_violations(7, g, bad, plog)}
    assert "A2-exactly-once" in axioms

    # A3: a writer never sees its own block at publish time
    bad = [list(h) for h in hist]
    own = next(k for k, (_r, s) in enumerate(bad[0]) if g.owner(s) == 0)
    r, s = bad[0][own]
    bad[0][own] = (r + 5, s)
    axioms = {v["axiom"] for v in _axiom_violations(7, g, bad, plog)}
    assert "A3-read-your-writes" in axioms

    # A4 + A5: a block never delivered anywhere near the tail
    bad = [list(h) for h in hist]
    bad[1] = [e for e in bad[1] if e[1] != 3]
    axioms = {v["axiom"] for v in _axiom_violations(7, g, bad, plog)}
    assert "A4-eventual-visibility" in axioms
    assert "A5-arbitration-prefix" in axioms


def test_checker_reports_byte_divergence(tmp_path):
    """check_convergence must FAIL when a replica's device state is
    corrupted post-drain — the convergence gate actually discriminates."""
    sessions, table, pool, streams, sched = _fleet(4, 2, tmp_path)
    sched.run()
    assert sched.done
    # corrupt one resident replica row's visibility bit
    rid = next(
        rid for g in table for rid in g.replica_ids
        if pool.docs[rid].cls is not None
    )
    rec = pool.docs[rid]
    doc, length, nvis = pool.pull_bucket(rec.cls)
    doc = np.array(doc)
    doc[rec.row, 0] ^= 1
    nvis = np.array(nvis)
    nvis[rec.row] += 1 if (doc[rec.row, 0] & 1) else -1
    pool.upload_bucket(rec.cls, doc, length, nvis)
    rep = _check(pool, table, sessions, streams)
    assert not rep.converged
    assert any(m["replica"] == rid for m in rep.byte_mismatches)
    pool.close()


# ---- crash recovery --------------------------------------------------------


def test_journaled_broadcasts_recover_to_convergence(tmp_path):
    """Crash mid-drain with the WAL + snapshot barriers on: recovery
    restores residency/cursors (recover_fleet), rebuilds the bus from
    the journaled bcast records, and the resumed drain converges every
    replica byte-exactly."""
    jd = str(tmp_path / "journal")
    sessions = build_fleet(5, mix=TINY_MIX, seed=9, arrival_span=1,
                           bands=TINY_BANDS)
    reps, table = build_writer_groups(sessions, 2)
    pool = DocPool(classes=(128, 512), slots=(6, 3),
                   spool_dir=str(tmp_path / "a"))
    streams = prepare_streams(reps, pool, batch=16)
    j = OpJournal(jd)
    sched = ReplicatedScheduler(
        pool, streams, table, turn_ops=8, batch=16, macro_k=2,
        journal=j, snapshot_every=2,
    )
    sched.run(max_rounds=4)  # crash: abandon mid-drain
    assert not sched.done
    j.close()
    pool.close()

    reps2, table2 = build_writer_groups(sessions, 2)
    pool2 = DocPool(classes=(128, 512), slots=(6, 3),
                    spool_dir=str(tmp_path / "b"))
    streams2 = prepare_streams(reps2, pool2, batch=16)
    j2 = OpJournal(jd)
    sched2, report, replayed = recover_replicated_fleet(
        pool2, streams2, table2, jd, journal=j2,
        turn_ops=8, batch=16, macro_k=2, snapshot_every=2,
    )
    assert report.snapshot_round >= 0  # a barrier was actually used
    assert replayed > 0  # bcast records drove the bus rebuild
    # delivery resumed at (or past) every restored cursor
    for rid, st in streams2.items():
        assert st.delivered >= st.cursor
    sched2.run()
    assert sched2.done
    # the verification TIER must hold on a recovered fleet too: the
    # replayed deliveries are recorded at the pre-crash marker round,
    # so the sampled histories still form a complete arbitration prefix
    rep = _check(pool2, table2, sessions, streams2, sched2.bus)
    assert rep.converged, rep.byte_mismatches[:3]
    assert rep.ra_ok and rep.ra_groups_checked > 0, rep.ra_violations[:3]
    j2.close()
    pool2.close()


def test_plain_bench_rejects_replication_fault_kinds():
    """A plain (single-writer) serve bench armed with replication-only
    fault kinds is a configuration error caught BEFORE the fleet
    builds — not a full drain ending in a not_fired chaos failure."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    with pytest.raises(ValueError, match="replica_partition"):
        run_serve_bench(
            mix=TINY_MIX, n_docs=2, bands=TINY_BANDS,
            classes=(128,), slots=(4,),
            faults="replica_partition=1",
            log=lambda *_a, **_k: None,
        )
    # and the mirror: the replicated family rejects the plain-only kind
    from crdt_benches_tpu.serve.replicate.bench import (
        run_serve_repl_bench,
    )

    with pytest.raises(ValueError, match="queue_overflow"):
        run_serve_repl_bench(
            mix=TINY_MIX, n_docs=2, writers=2, bands=TINY_BANDS,
            classes=(128,), slots=(4,),
            faults="queue_overflow=1",
            log=lambda *_a, **_k: None,
        )


# ---- the bench family ------------------------------------------------------


def test_repl_bench_family_smoke(tmp_path):
    """run_serve_repl_bench end to end: verify + RA gates green, the
    artifact carries the replication/convergence blocks with the
    documented fields, and the bench id follows the grammar."""
    from crdt_benches_tpu.serve.replicate.bench import (
        run_serve_repl_bench,
    )

    r, info = run_serve_repl_bench(
        mix=TINY_MIX, n_docs=6, writers=2, batch=16, macro_k=4,
        batch_chars=64, classes=(128, 512), slots=(8, 4),
        bands=TINY_BANDS, arrival_span=2, turn_ops=8, seed=0,
        results_dir=str(tmp_path), save_name="repl_test",
        log=lambda *_a, **_k: None,
    )
    assert info["verify_ok"] and info["ra_ok"] and info["faults_ok"]
    assert r.bench_id == "serve/repl/custom/6x2"
    rb = r.extra["replication"]
    assert rb["writers"] == 2 and rb["groups"] == 6
    assert rb["merged_ops"] > 0 and rb["broadcast_bytes"] > 0
    assert rb["convergence_rounds_max"] >= rb["convergence_rounds_mean"]
    conv = r.extra["convergence"]
    assert conv["converged"] and conv["replicas_checked"] == 12
    assert conv["ra_ok"] and conv["ra_groups_checked"] > 0
    # the labeled replica series landed in the artifact's registry dump
    names = set(r.extra["metrics"]["counters"])
    assert any(n.startswith("serve.replica.merged_ops{") for n in names)
    assert "serve.replica.broadcast_bytes" in names
