"""Checkpoint/resume: stop a replay after any op batch, restore from disk,
finish, and get a bit-identical document (the subsystem the reference lacks,
SURVEY.md section 5).  Durability half: saves are atomic (a kill mid-write
can't tear a file) and loads are CRC-verified (damage raises the typed
CorruptCheckpointError, with a legacy fallback for pre-manifest spools)."""

import os

import numpy as np

from crdt_benches_tpu.engine.replay import ReplayEngine
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import tensorize
from crdt_benches_tpu.utils.checkpoint import load_state, save_state
import pytest


@pytest.mark.slow
def test_checkpoint_resume_mid_replay(tmp_path):
    tt = tensorize(synth_trace(seed=3, n_ops=200, base="checkpointed"),
                   batch=16)
    eng = ReplayEngine(tt)
    want = eng.decode(eng.run_blocking())

    # replay only the first half of the batches, checkpoint, restore, finish
    half = tt.n_batches // 2
    from crdt_benches_tpu.engine.replay import replay_batches

    st = eng.fresh_state()
    st = replay_batches(
        st, eng.kind_b[:half], eng.pos_b[:half], eng.slot_b[:half]
    )
    path = str(tmp_path / "ck.npz")
    save_state(path, st)

    st2 = load_state(path)
    assert type(st2).__name__ == "DocState"
    st3 = replay_batches(
        st2, eng.kind_b[half:], eng.pos_b[half:], eng.slot_b[half:]
    )
    assert eng.decode(st3) == want


@pytest.mark.slow
def test_checkpoint_roundtrip_downstream(tmp_path):
    from crdt_benches_tpu.engine.downstream import JaxDownstreamEngine

    tt = tensorize(synth_trace(seed=4, n_ops=100), batch=16)
    eng = JaxDownstreamEngine(tt)
    state = eng.run()
    path = str(tmp_path / "down.npz")
    save_state(path, state)
    st2 = load_state(path)
    for f in state._fields:
        assert (np.asarray(getattr(state, f)) == getattr(st2, f)).all()


def test_checkpoint_bf16_state4_roundtrip(tmp_path):
    """PackedState4 carries a bfloat16 field (cv_intile): np.savez alone
    loses the dtype (loads as void |V2) — the dtype manifest must bring
    it back bit-exactly (round-5 fix)."""
    import ml_dtypes

    from crdt_benches_tpu.ops.apply2 import init_state4

    st = init_state4(2, 256, 7)
    path = str(tmp_path / "s4.npz")
    save_state(path, st)
    st2 = load_state(path)
    assert np.asarray(st2.cv_intile).dtype == np.dtype(ml_dtypes.bfloat16)
    for f in st._fields:
        a, b = np.asarray(getattr(st, f)), np.asarray(getattr(st2, f))
        assert a.dtype == b.dtype and (a == b).all(), f


def _small_state(r=2, c=256):
    from crdt_benches_tpu.ops.apply2 import PackedState

    rng = np.random.default_rng(5)
    return PackedState(
        doc=rng.integers(0, 1 << 20, (r, c)).astype(np.int32),
        length=np.asarray([c] * r, np.int32),
        nvis=np.asarray([c // 2] * r, np.int32),
    )


def test_save_state_atomic_on_midwrite_crash(tmp_path, monkeypatch):
    """A save killed mid-write (injected exception after partial bytes)
    leaves the PREVIOUS checkpoint intact and no temp litter — the
    eviction spool can never be torn."""
    from crdt_benches_tpu.utils import checkpoint as cp

    st = _small_state()
    path = str(tmp_path / "spool.npz")
    cp.save_state(path, st, compress=False)
    good = open(path, "rb").read()

    def boom(fh, **kw):
        fh.write(b"partial garbage that must never reach the target")
        raise RuntimeError("killed mid-write")

    monkeypatch.setattr(np, "savez", boom)
    with pytest.raises(RuntimeError, match="killed mid-write"):
        cp.save_state(path, _small_state(3, 128), compress=False)
    assert open(path, "rb").read() == good  # old checkpoint untouched
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []
    st2 = cp.load_state(path)
    for f in st._fields:
        assert (np.asarray(getattr(st, f)) == getattr(st2, f)).all()


@pytest.mark.parametrize("damage", ["bitflip", "truncate"])
def test_load_state_detects_damage(tmp_path, damage):
    """Any on-disk damage (flipped bytes, truncation) surfaces as the
    typed CorruptCheckpointError, not a numpy decode crash."""
    from crdt_benches_tpu.utils.checkpoint import (
        CorruptCheckpointError,
        load_state,
        save_state,
    )

    path = str(tmp_path / "st.npz")
    save_state(path, _small_state(), compress=False)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        if damage == "bitflip":
            f.seek(size // 2)
            chunk = f.read(8)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        else:
            f.truncate(int(size * 0.6))
    with pytest.raises(CorruptCheckpointError):
        load_state(path)


def test_load_state_legacy_no_crc_manifest(tmp_path):
    """Pre-CRC checkpoints (no __crcs__ field) still load — the legacy
    fallback skips verification instead of rejecting old spools."""
    from crdt_benches_tpu.utils.checkpoint import load_state

    st = _small_state()
    path = str(tmp_path / "legacy.npz")
    arrays = {f: np.asarray(getattr(st, f)) for f in st._fields}
    np.savez(
        path, __class__=np.asarray("PackedState"),
        __fields__=np.asarray(st._fields),
        __dtypes__=np.asarray([str(a.dtype) for a in arrays.values()]),
        **arrays,
    )
    st2 = load_state(path)
    for f in st._fields:
        assert (np.asarray(getattr(st, f)) == getattr(st2, f)).all()


def test_checkpoint_legacy_void_fails_loudly(tmp_path):
    """A pre-manifest checkpoint with a bf16 field must raise a clear
    error instead of returning opaque void arrays."""
    import pytest

    from crdt_benches_tpu.ops.apply2 import init_state4

    st = init_state4(1, 128, 0)
    path = str(tmp_path / "legacy.npz")
    # simulate the old save format: raw arrays, no __dtypes__ manifest
    arrays = {f: np.asarray(getattr(st, f)) for f in st._fields}
    np.savez_compressed(
        path, __class__=np.asarray("PackedState4"),
        __fields__=np.asarray(st._fields), **arrays,
    )
    with pytest.raises(ValueError, match="legacy checkpoint"):
        load_state(path)
