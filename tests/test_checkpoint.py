"""Checkpoint/resume: stop a replay after any op batch, restore from disk,
finish, and get a bit-identical document (the subsystem the reference lacks,
SURVEY.md section 5)."""

import numpy as np

from crdt_benches_tpu.engine.replay import ReplayEngine
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import tensorize
from crdt_benches_tpu.utils.checkpoint import load_state, save_state
import pytest


@pytest.mark.slow
def test_checkpoint_resume_mid_replay(tmp_path):
    tt = tensorize(synth_trace(seed=3, n_ops=200, base="checkpointed"),
                   batch=16)
    eng = ReplayEngine(tt)
    want = eng.decode(eng.run_blocking())

    # replay only the first half of the batches, checkpoint, restore, finish
    half = tt.n_batches // 2
    from crdt_benches_tpu.engine.replay import replay_batches

    st = eng.fresh_state()
    st = replay_batches(
        st, eng.kind_b[:half], eng.pos_b[:half], eng.slot_b[:half]
    )
    path = str(tmp_path / "ck.npz")
    save_state(path, st)

    st2 = load_state(path)
    assert type(st2).__name__ == "DocState"
    st3 = replay_batches(
        st2, eng.kind_b[half:], eng.pos_b[half:], eng.slot_b[half:]
    )
    assert eng.decode(st3) == want


@pytest.mark.slow
def test_checkpoint_roundtrip_downstream(tmp_path):
    from crdt_benches_tpu.engine.downstream import JaxDownstreamEngine

    tt = tensorize(synth_trace(seed=4, n_ops=100), batch=16)
    eng = JaxDownstreamEngine(tt)
    state = eng.run()
    path = str(tmp_path / "down.npz")
    save_state(path, state)
    st2 = load_state(path)
    for f in state._fields:
        assert (np.asarray(getattr(state, f)) == getattr(st2, f)).all()
