"""Whole-doc-reconcile backend (C6, the automerge capability shape): the
edit position must be recoverable from a whole-document diff, per-edit,
byte-identical to the oracle on a real trace (reference src/rope.rs:35-78)."""

import numpy as np

from crdt_benches_tpu.backends.base import upstream_backends
from crdt_benches_tpu.backends.reconcile import PyReconcile
from crdt_benches_tpu.oracle import OracleDocument
from crdt_benches_tpu.traces.synth import synth_trace


def test_registered_under_backend_trait():
    assert upstream_backends()["py-reconcile"] is PyReconcile


def test_basic_replace_shapes():
    d = PyReconcile.from_str("hello world")
    ids0 = d._doc_ids.copy()
    d.replace(6, 11, "there")
    assert d.content() == "hello there"
    # reconcile preserved the untouched prefix's element ids
    assert (d._doc_ids[:6] == ids0[:6]).all()
    # and assigned fresh ids to the spliced middle
    assert (d._doc_ids[6:] >= 11).all()
    # byte length semantics (src/rope.rs:74-77)
    d.replace(0, 0, "é")  # 2 UTF-8 bytes
    assert len(d) == len("éhello there".encode())


def test_pure_insert_and_delete():
    d = PyReconcile.from_str("abc")
    d.replace(1, 1, "XY")  # insert only
    assert d.content() == "aXYbc"
    d.replace(0, 2, "")  # delete only
    assert d.content() == "Ybc"
    d.replace(0, 3, "")  # delete everything
    assert d.content() == ""
    d.replace(0, 0, "new")
    assert d.content() == "new"


def test_repeated_char_ambiguity():
    # common prefix/suffix overlap: "aaaa" -> "aaa" must not double-count
    d = PyReconcile.from_str("aaaa")
    d.replace(1, 2, "")
    assert d.content() == "aaa"
    d2 = PyReconcile.from_str("abab")
    d2.replace(2, 2, "ab")
    assert d2.content() == "ababab"


def test_synth_trace_byte_identical():
    trace = synth_trace(seed=11, n_ops=400, base="reconcile me")
    d = PyReconcile.from_str(trace.start_content)
    o = OracleDocument.from_str(trace.start_content)
    for pos, dl, ins in trace.iter_patches():
        d.replace(pos, pos + dl, ins)
        o.replace(pos, pos + dl, ins)
    assert d.content() == o.content()
    assert len(d._doc_ids) == len(np.unique(d._doc_ids))


def test_svelte_trace_byte_identical(svelte_trace):
    d = PyReconcile.from_str(svelte_trace.start_content)
    for pos, dl, ins in svelte_trace.iter_patches():
        d.replace(pos, pos + dl, ins)
    assert d.content() == svelte_trace.end_content
