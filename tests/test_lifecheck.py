"""graftlint v5 headline harness: the churn-heavy protocol-complete
lifecheck drain runs armed and leak-free, drained-doc record eviction
keeps pool records O(active-set) regardless of fleet size, and the
G025 cross-check is green in both directions on a real sanitized
bench artifact (plus red on a doctored one)."""

import importlib.util
import json
import os
import pathlib
import sys

import pytest

from crdt_benches_tpu.lint import lifecycle_sanitizer as lcs
from crdt_benches_tpu.lint.core import run_lint
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import FleetScheduler, LazyStreams
from crdt_benches_tpu.serve.workload import FleetSpec

PACKAGE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "crdt_benches_tpu")

_BANDS = {"synth-small": ("synth", (8, 36))}
_MIX = {"synth-small": 1.0}


@pytest.fixture(autouse=True)
def _lc_reset(monkeypatch):
    """Every test owns a clean sanitizer (declarations restored — other
    suites' pools declare machines as a construction side effect)."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_LIFECYCLE", raising=False)
    saved = dict(lcs._decls)
    lcs.disarm()
    lcs.reset_counters()
    yield
    lcs.disarm()
    lcs.reset_counters()
    lcs._decls.clear()
    lcs._decls.update(saved)


# ---------------------------------------------------------------------------
# the headline drain
# ---------------------------------------------------------------------------


def test_lifecheck_small_drains_leak_free_with_full_coverage(tmp_path):
    """THE graftlint v5 acceptance gate: both drains (journaled churn
    + reshard + ingest, then journal-less record-evict streaming) run
    armed with zero unreleased acquisitions at each drain end, every
    required machine/resource records activity, and acquire==release
    across the board."""
    from crdt_benches_tpu.serve.lifecheck import (
        _REQUIRED_MACHINES, _REQUIRED_RESOURCES, run_lifecheck)

    report = run_lifecheck(str(tmp_path), small=True)
    assert report["leaked"] == 0
    assert report["unattributed"] == []
    for name in _REQUIRED_MACHINES:
        assert report["machines"].get(name), report["machines"]
    for res in _REQUIRED_RESOURCES:
        t = report["resources"][res]
        assert t["acquire"] == t["release"] > 0, (res, t)
    # drain 1 actually churned (the keyed doc machine walked edges)
    assert report["churn"]["evictions"] > 0
    # drain 2 reclaimed records and stayed inside the active-set bound
    ev = report["record_evict"]
    assert ev["gc_docs"] > 0 and ev["released_streams"] > 0
    assert ev["records_at_end"] <= ev["fleet"]
    # the sanitizer is left disarmed for the rest of the suite
    assert not lcs.armed()


# ---------------------------------------------------------------------------
# O(active-set) record eviction: footprint must not scale with fleet
# ---------------------------------------------------------------------------


def _drained_gc_records(tmp_path, n: int) -> tuple[int, int]:
    pool = DocPool(classes=(256,), slots=(2,),
                   spool_dir=str(tmp_path / f"sp{n}"), warm_docs=2)
    try:
        spec = FleetSpec.build(n, mix=_MIX, seed=7, arrival_span=4,
                               bands=_BANDS)
        streams = LazyStreams(spec, pool, batch=16, batch_chars=64)
        sched = FleetScheduler(pool, streams, batch=16, macro_k=2,
                               batch_chars=64, drained_gc=True)
        sched.run()
        return len(pool.docs), sched.spool_gc_docs
    finally:
        for doc_id, rec in sorted(pool.docs.items()):
            if rec.cls is not None:
                pool.evict(doc_id)
        pool.gc_drained_docs(sorted(pool.docs))
        pool.close()


def test_record_eviction_keeps_pool_records_o_active_set(tmp_path):
    """ROADMAP million-doc item (b): with ``drained_gc`` the record
    table at drain end is bounded by hot slots + warm budget + one
    unflushed GC batch — the SAME bound at 3x the fleet — while the
    number of reclaimed records scales with the fleet."""
    bound = 2 + 2 + 32  # slots + warm_docs + one GC batch
    rec_small, gc_small = _drained_gc_records(tmp_path, 12)
    rec_big, gc_big = _drained_gc_records(tmp_path, 36)
    assert gc_small > 0 and gc_big > gc_small
    assert rec_small <= bound and rec_big <= bound
    # the steady-state footprint did not grow with the fleet
    assert rec_big <= rec_small + 32


# ---------------------------------------------------------------------------
# G025 cross-check on a real sanitized record-evict bench
# ---------------------------------------------------------------------------


def test_g025_cross_check_clean_both_directions(tmp_path, monkeypatch):
    """A sanitized streaming record-evict drain emits a lifecycle
    block that cross-checks clean against the static markers in BOTH
    directions: no dead declared machine/resource on an armed surface,
    no rogue runtime names, no unattributed transitions."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_LIFECYCLE", "1")
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=_MIX, bands=_BANDS,
        n_docs=10, batch=16, classes=(256,), slots=(2,),
        macro_k=2, batch_chars=64, arrival_span=2, verify_sample=3,
        stream=True, record_evict=True,
        results_dir=str(tmp_path), save_name="lc_smoke",
        log=lambda s: None,
    )
    assert info["verify_ok"]
    block = r.extra["lifecycle"]
    assert block["version"] == 1 and block["sanitized"]
    assert block["pool"] and block["stream"]
    assert block["machines"].get("doc"), block["machines"]
    assert block["machines"].get("stream"), block["machines"]
    assert block["resources"].get("rows", {}).get("acquire", 0) > 0
    assert block["unattributed"] == []
    artifact = str(tmp_path / "lc_smoke.json")
    assert os.path.exists(artifact)
    findings = run_lint([PACKAGE], select={"G025"},
                        lifecycle_artifact=artifact)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line} {f.rule} {f.msg}" for f in findings
    )


def test_g025_flags_dead_machine_rogue_and_unattributed_on_doctored(
        tmp_path):
    """All three failure directions against a doctored block: a dead
    declared machine on an armed surface, a rogue runtime machine no
    static declaration explains, and an unattributed transition."""
    artifact = tmp_path / "doctored.json"
    artifact.write_text(json.dumps({"lifecycle": {
        "version": 1, "sanitized": True,
        "pool": True, "reshard": False, "stream": False,
        "ingest": False, "journal": False, "prefetch": False,
        "machines": {"ghost": {"a->b": 3}},
        "resources": {"rows": {"acquire": 4, "release": 4}},
        "unattributed": ["spool:live->cold"],
    }}))
    findings = run_lint([PACKAGE], select={"G025"},
                        lifecycle_artifact=str(artifact))
    msgs = [f.msg for f in findings]
    # pool armed but the doc machine recorded nothing -> dead
    assert any("`doc` recorded zero transitions" in m for m in msgs)
    # reshard NOT armed -> row machine is not dead-checked
    assert not any("`row` recorded zero" in m for m in msgs)
    assert any("runtime machine `ghost`" in m for m in msgs)
    assert any("unattributed runtime transition `spool:live->cold`"
               in m for m in msgs)


# ---------------------------------------------------------------------------
# bench_compare: the lifecycle block rides the one-sided matrix
# ---------------------------------------------------------------------------


def _bench_compare():
    repo = pathlib.Path(PACKAGE).parent
    spec = importlib.util.spec_from_file_location(
        "bench_compare_lifecycle", repo / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare_lifecycle"] = mod
    spec.loader.exec_module(mod)
    return mod


def _compare_artifact(tmp_path, name: str, *, lifecycle: bool) -> str:
    extra = {
        "family": "serve",
        "patches_per_sec": 100_000.0,
        "batch_latency": {"p50": 0.001, "p95": 0.004, "p99": 0.005},
        "rounds": 40,
        "range_ops": 10_000,
        "journal": None,
    }
    if lifecycle:
        extra["lifecycle"] = {
            "version": 1, "sanitized": True,
            "pool": True, "reshard": False, "stream": True,
            "ingest": False, "journal": False, "prefetch": False,
            "machines": {"doc": {"cold->live": 40, "live->cold": 40}},
            "resources": {"rows": {"acquire": 41, "release": 41}},
            "unattributed": [],
        }
    data = [{"group": "serve", "trace": "mixed", "backend": "512",
             "extra": extra}]
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_bench_compare_lifecycle_block_skips_both_directions(
        tmp_path, capsys):
    """A sanitized run diffed against a pre-v5 baseline (and vice
    versa) is a schema difference, never an error: the lifecycle block
    is a skip-with-note in both directions, and matched pairs diff
    silently."""
    bc = _bench_compare()
    with_lc = _compare_artifact(tmp_path, "lc.json", lifecycle=True)
    without = _compare_artifact(tmp_path, "plain.json", lifecycle=False)
    for pair in ((with_lc, without), (without, with_lc)):
        assert bc.main(list(pair)) == 0
        out = capsys.readouterr().out
        assert "SKIP" in out and "lifecycle block" in out
        assert "present only in" in out
    # both sides carrying the block is NOT a schema difference
    assert bc.main([with_lc, with_lc]) == 0
    assert "lifecycle block" not in capsys.readouterr().out
