"""Differential coverage for the resolver token-count simulation
(ops/token_sim.py): a token cap taken from the simulation must leave the
Pallas resolver's outputs identical to the uncapped (2B+2 worst-case)
kernel.  Runs the kernel in interpret mode so the TPU-only fast path is
exercised on CPU CI (an undersized cap silently corrupts results — this is
the test the round-1 kernel shipped without)."""

import numpy as np
import jax.numpy as jnp
import pytest

from crdt_benches_tpu.ops.resolve_pallas import resolve_batch_pallas
from crdt_benches_tpu.ops.token_sim import simulate_token_counts
from crdt_benches_tpu.traces.tensorize import DELETE, INSERT, tensorize


def _random_stream(rng, n_ops, start_len):
    kinds, poss = [], []
    doc_len = start_len
    for _ in range(n_ops):
        if doc_len == 0 or rng.random() < 0.6:
            kinds.append(INSERT)
            poss.append(int(rng.integers(0, doc_len + 1)))
            doc_len += 1
        else:
            kinds.append(DELETE)
            poss.append(int(rng.integers(0, doc_len)))
            doc_len -= 1
    return np.asarray(kinds, np.int32), np.asarray(poss, np.int32)


def _compare_capped(kind_b, pos_b, n_init):
    caps = simulate_token_counts(kind_b, pos_b, n_init)
    v0 = jnp.full((8,), n_init, jnp.int32)
    nb, B = kind_b.shape
    v = v0
    for b in range(nb):
        kind = jnp.asarray(kind_b[b])
        pos = jnp.asarray(pos_b[b])
        full = resolve_batch_pallas(kind, pos, v, interpret=True)
        capped = resolve_batch_pallas(
            kind, pos, v, interpret=True, token_cap=int(caps[b]) + 8
        )
        for f, c in zip(full, capped):
            np.testing.assert_array_equal(np.asarray(f), np.asarray(c))
        n_ins = int((kind_b[b] == INSERT).sum())
        n_del = int(
            ((kind_b[b] == DELETE) & (pos_b[b] >= 0)).sum()
        )
        v = v + n_ins - n_del


@pytest.mark.parametrize("seed", [0, 5])
@pytest.mark.slow
def test_random_streams_capped_equals_uncapped(seed):
    rng = np.random.default_rng(seed)
    B = 64
    kinds, poss = _random_stream(rng, 4 * B, start_len=16)
    _compare_capped(
        kinds.reshape(4, B), poss.reshape(4, B), n_init=16
    )


@pytest.mark.slow
def test_svelte_chunk_capped_equals_uncapped(svelte_trace):
    tt = tensorize(svelte_trace, batch=128)
    kind_b, pos_b, _, _ = tt.batched()
    _compare_capped(kind_b[:4], pos_b[:4], n_init=len(tt.init_chars))


@pytest.mark.slow
def test_simulated_counts_bounded(svelte_trace):
    """Sim never exceeds the kernel's worst case and covers the typing
    regime (~B+2 tokens) the engine relies on."""
    tt = tensorize(svelte_trace, batch=512)
    kind_b, pos_b, _, _ = tt.batched()
    caps = simulate_token_counts(kind_b, pos_b, len(tt.init_chars))
    assert (caps <= 2 * 512 + 2).all()
    assert caps.min() >= 1
