"""Trace-layer tests: schema, stats self-check (SURVEY.md section 6 table),
chars_to_bytes, tensorizer invariants."""

import numpy as np
import pytest

from crdt_benches_tpu.traces import load_testing_data, tensorize
from crdt_benches_tpu.traces.tensorize import DELETE, INSERT, PAD
from crdt_benches_tpu.oracle import replay_trace, replay_unit_ops

# Expected workload constants, measured independently in the survey
# (BASELINE.md "Workload constants" table).
EXPECTED_STATS = {
    "sveltecomponent": dict(
        txns=18335, patches=19749, ins_ops=17786, del_ops=3227,
        ins_chars=93984, del_chars=75533, final_chars=18451, unit_ops=169517,
    ),
    "rustcode": dict(
        txns=36981, patches=40173, ins_ops=35249, del_ops=7148,
        ins_chars=522531, del_chars=457313, final_chars=65218, unit_ops=979844,
    ),
    "seph-blog1": dict(
        txns=137154, patches=137993, ins_ops=128855, del_ops=12021,
        ins_chars=212489, del_chars=155720, final_chars=56769, unit_ops=368209,
    ),
    "automerge-paper": dict(
        txns=259778, patches=259778, ins_ops=182315, del_ops=77463,
        ins_chars=182315, del_chars=77463, final_chars=104852, unit_ops=259778,
    ),
}


@pytest.mark.parametrize("name", list(EXPECTED_STATS))
@pytest.mark.slow
def test_stats_match_survey(name):
    trace = load_testing_data(name)
    stats = trace.stats()
    for key, want in EXPECTED_STATS[name].items():
        assert stats[key] == want, f"{name}.{key}: {stats[key]} != {want}"
    assert len(trace) == EXPECTED_STATS[name]["patches"]


@pytest.mark.slow
def test_all_traces_start_empty_end_ascii():
    for name in EXPECTED_STATS:
        trace = load_testing_data(name)
        assert trace.start_content == ""
        assert all(ord(c) < 128 for c in trace.end_content)


def test_oracle_replay_svelte(svelte_trace):
    assert replay_trace(svelte_trace) == svelte_trace.end_content


def test_oracle_replay_seph(seph_trace):
    assert replay_trace(seph_trace) == seph_trace.end_content


def test_chars_to_bytes_rustcode(rustcode_trace):
    """rustcode inserts 12 non-ASCII chars mid-trace (SURVEY.md 3.4); replaying
    the byte-offset trace over a *byte* document must still converge."""
    btrace = rustcode_trace.chars_to_bytes()
    doc = bytearray()
    for pos, del_count, ins in btrace.iter_patches():
        doc[pos : pos + del_count] = ins.encode("utf-8")
    assert doc.decode("utf-8") == rustcode_trace.end_content


def test_chars_to_bytes_identity_on_ascii(svelte_trace):
    btrace = svelte_trace.chars_to_bytes()
    for (p1, d1, i1), (p2, d2, i2) in zip(
        svelte_trace.iter_patches(), btrace.iter_patches()
    ):
        assert (p1, d1, i1) == (p2, d2, i2)


def test_tensorize_invariants(svelte_trace):
    tt = tensorize(svelte_trace, batch=256)
    assert len(tt.kind) % 256 == 0
    assert tt.n_ops == EXPECTED_STATS["sveltecomponent"]["unit_ops"]
    assert tt.n_patches == len(svelte_trace)
    assert tt.capacity == len(tt.init_chars) + tt.n_inserts
    # padding is all PAD and only at the tail
    assert (tt.kind[tt.n_ops :] == PAD).all()
    assert (tt.kind[: tt.n_ops] != PAD).all()
    # slots: dense, increasing over insert ops, -1 elsewhere
    ins_mask = tt.kind == INSERT
    slots = tt.slot[ins_mask]
    assert (np.diff(slots) == 1).all()
    assert slots[0] == len(tt.init_chars)
    assert (tt.slot[~ins_mask] == -1).all()
    # delete ops carry no char
    assert (tt.ch[tt.kind == DELETE] == 0).all()


def test_unit_op_replay_matches_end_content(svelte_trace):
    tt = tensorize(svelte_trace, batch=256)
    out = replay_unit_ops(
        tt.kind[: tt.n_ops], tt.pos[: tt.n_ops], tt.ch[: tt.n_ops], start=""
    )
    assert out == svelte_trace.end_content
