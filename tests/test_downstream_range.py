"""Range-layout downstream (engine/downstream_range.py): run-granular
updates integrate to byte-identical final content, including block
replaces, same-batch insert+delete kills, and the real block-edit traces."""

import numpy as np
import pytest

from crdt_benches_tpu.engine.downstream_range import (
    JaxRangeDownstreamEngine,
    generate_range_updates,
)
from crdt_benches_tpu.oracle import OracleDocument
from crdt_benches_tpu.traces.loader import TestData, TestTxn


def check(patches, start="", batch_ops=4, n_replicas=1, epoch=2):
    trace = TestData(start, "", [TestTxn("", patches)])
    doc = OracleDocument.from_str(start)
    for pos, d, ins in trace.iter_patches():
        doc.replace(pos, pos + d, ins)
    want = doc.content()
    trace = TestData(start, want, [TestTxn("", patches)])
    eng = JaxRangeDownstreamEngine(
        trace, n_replicas=n_replicas, batch_ops=batch_ops, epoch=epoch
    )
    state = eng.run()
    for r in range(n_replicas):
        assert eng.decode(state, replica=r) == want


@pytest.mark.slow
def test_block_appends():
    check([[0, 0, "hello "], [6, 0, "world"], [0, 0, ">> "]])


@pytest.mark.slow
def test_block_replace():
    check([[0, 0, "abcdefgh"], [2, 3, "XY"], [0, 1, "z"]])


@pytest.mark.slow
def test_same_batch_insert_then_delete_block():
    # insert a block and delete part of it within the same wire batch
    check([[0, 0, "abcdef"], [1, 3, ""], [1, 0, "Q"]], batch_ops=8)


@pytest.mark.slow
def test_delete_spanning_batches():
    check(
        [[0, 0, "abcdefghij"], [0, 0, "123"], [2, 8, "Z"]],
        batch_ops=2,
    )


@pytest.mark.slow
def test_multi_replica():
    check(
        [[0, 0, "hello"], [5, 0, " there"], [0, 2, "HE"]],
        n_replicas=3,
    )


@pytest.mark.parametrize("seed", [0, 3, 8])
@pytest.mark.slow
def test_random_block_edits_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    patches = []
    doc_len = 0
    letters = "abcdefghijklmnop"
    for _ in range(120):
        pos = int(rng.integers(0, doc_len + 1))
        if doc_len and rng.random() < 0.35:
            d = int(rng.integers(1, min(doc_len - pos, 9) + 1)) if (
                pos < doc_len
            ) else 0
        else:
            d = 0
        n_ins = int(rng.integers(0, 7))
        ins = "".join(
            rng.choice(list(letters), n_ins)
        ) if n_ins else ""
        if d == 0 and not ins:
            ins = "x"
        patches.append([pos, d, ins])
        doc_len += len(ins) - d
    check(patches, batch_ops=8, epoch=4)


@pytest.mark.slow
def test_svelte_trace_byte_identical(svelte_trace):
    eng = JaxRangeDownstreamEngine(svelte_trace, batch_ops=256)
    state = eng.run()
    assert int(np.asarray(state.nvis).reshape(-1)[0]) == len(
        svelte_trace.end_content
    )
    assert eng.decode(state) == svelte_trace.end_content


def test_wire_size_reported(svelte_trace):
    upd = generate_range_updates(svelte_trace, batch_ops=256)
    assert upd.nbytes() > 0
    assert upd.n_patches == len(svelte_trace)
