"""Quick-tier engine coverage: one tiny oracle-checked case per engine
family, so `pytest -m "not slow"` exercises every engine's small shapes
even though the heavy differential suites are marked slow (VERDICT r4
task 7).  Every test here must stay in the low single-digit seconds on a
single CPU core — anything bigger belongs in the slow tier.
"""

import numpy as np
import pytest

from crdt_benches_tpu.oracle import OracleDocument
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import tensorize, tensorize_ranges

from test_merge import sim_for


def _oracle(trace):
    doc = OracleDocument.from_str(trace.start_content)
    for p, d, ins in trace.iter_patches():
        doc.replace(p, p + d, ins)
    return doc.content()


@pytest.fixture(scope="module")
def tiny_trace():
    return synth_trace(seed=21, n_ops=60, base="quick smoke ")


@pytest.mark.parametrize("engine", ["v3", "v4"])
def test_unit_engine(tiny_trace, engine):
    from crdt_benches_tpu.engine.replay import ReplayEngine

    tt = tensorize(tiny_trace, batch=16)
    eng = ReplayEngine(tt, n_replicas=2, resolver="scan", engine=engine,
                       pack=2)
    st = eng.run()
    assert eng.decode(st, replica=1) == _oracle(tiny_trace)


@pytest.mark.parametrize("engine", ["v3", "v4"])
def test_range_engine(tiny_trace, engine):
    from crdt_benches_tpu.engine.replay_range import RangeReplayEngine

    rt = tensorize_ranges(tiny_trace, batch=16, coalesce=True)
    eng = RangeReplayEngine(rt, n_replicas=2, interpret=True, chunk=4,
                            engine=engine)
    st = eng.run()
    assert eng.decode(st, replica=1) == _oracle(tiny_trace)


def test_downstream_v5(tiny_trace):
    from crdt_benches_tpu.engine.downstream import JaxDownstreamEngine

    tt = tensorize(tiny_trace, batch=16)
    eng = JaxDownstreamEngine(tt, n_replicas=2)
    st = eng.run()
    assert eng.decode(st, replica=1) == _oracle(tiny_trace)


def test_downstream_range(tiny_trace):
    from crdt_benches_tpu.engine.downstream_range import (
        JaxRangeDownstreamEngine,
    )
    from crdt_benches_tpu.traces.loader import TestData

    want = _oracle(tiny_trace)
    trace = TestData(tiny_trace.start_content, want, tiny_trace.txns)
    eng = JaxRangeDownstreamEngine(trace, n_replicas=1, batch_ops=8,
                                   epoch=2)
    assert eng.decode(eng.run()) == want


def test_merge_v1_and_packed():
    from crdt_benches_tpu.engine.merge import merge_oracle

    sim = sim_for(seed=2, n_agents=2, n_ops=12, batch=8)
    want = merge_oracle(sim.log, "base text", np.asarray(sim.chars))
    assert sim.decode(sim.merge()) == want
    assert sim.decode(sim.merge_packed()) == want


def test_merge_runs():
    from crdt_benches_tpu.engine.merge_range import RunMergeSimulation

    sim = sim_for(seed=3, n_agents=2, n_ops=12, batch=8)
    want = sim.decode(sim.merge())
    rm = RunMergeSimulation(sim, batch=8, epoch=2)
    assert rm.decode(rm.merge()) == want


def test_checkpoint_roundtrip(tiny_trace, tmp_path):
    from crdt_benches_tpu.engine.replay import ReplayEngine
    from crdt_benches_tpu.utils.checkpoint import load_state, save_state

    tt = tensorize(tiny_trace, batch=16)
    eng = ReplayEngine(tt, n_replicas=1, resolver="scan")
    st = eng.run_blocking()
    path = str(tmp_path / "smoke.npz")
    save_state(path, st)
    import jax.numpy as jnp

    st2 = type(st)(*(jnp.asarray(x) for x in load_state(path)))
    assert eng.decode(st2) == _oracle(tiny_trace)


def test_resolver_token_cap(tiny_trace):
    from crdt_benches_tpu.ops.resolve_pallas import resolve_batch_pallas

    tt = tensorize(tiny_trace, batch=16)
    kind_b, pos_b, _, _ = tt.batched()
    v = np.full((2,), len(tt.init_chars), np.int32)
    full = resolve_batch_pallas(kind_b[0], pos_b[0], v, interpret=True)
    capped = resolve_batch_pallas(
        kind_b[0], pos_b[0], v, interpret=True, token_cap=128
    )
    for f, c in zip(full, capped):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(c))
