"""Tiered state residency: device-hot / pinned-host-warm /
compressed-cold DocPool with predictive async prefetch.

Ground truth throughout is the oracle: whatever tier a doc's state
rides — device rows, warm host arrays, compressed spools, a prefetch
payload in flight — the decoded bytes must match an uninterrupted
replay of the same stream."""

import json
import os
import time
import zipfile

import numpy as np
import pytest

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.bench import parse_tier_spec, run_serve_bench
from crdt_benches_tpu.serve.faults import FaultEvent, FaultInjector, FaultPlan
from crdt_benches_tpu.serve.journal import OpJournal, recover_fleet
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import FleetScheduler, prepare_streams
from crdt_benches_tpu.serve.workload import build_fleet

TINY_BANDS = {"synth-small": ("synth", (40, 120))}
TINY_MIX = {"synth-small": 1.0}
#: two capacity classes actually hosting docs, so the cross-class
#: parity tests mean something
TWO_BANDS = {
    "synth-small": ("synth", (40, 120)),
    "synth-medium": ("synth", (300, 600)),
}
TWO_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


def _fleet(tmp_path, n=8, seed=11, classes=(128,), slots=(2,),
           warm_docs=4, bands=TINY_BANDS, mix=TINY_MIX, **kw):
    sessions = build_fleet(
        n, mix=mix, seed=seed, arrival_span=2, bands=bands
    )
    pool = DocPool(classes=classes, slots=slots,
                   spool_dir=str(tmp_path / "spool"),
                   warm_docs=warm_docs)
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32, **kw)
    return sessions, pool, streams, sched


def _assert_parity(sessions, pool, streams, skip_lossy=True):
    for s in sessions:
        if skip_lossy and streams[s.doc_id].lossy:
            continue
        assert pool.decode(s.doc_id) == replay_trace(s.trace), (
            f"doc {s.doc_id} diverged"
        )


# ---------------------------------------------------------------------------
# warm tier mechanics
# ---------------------------------------------------------------------------


def test_warm_lru_eviction_order(tmp_path):
    """Warm overflow demotes strictly least-recently-SCHEDULED first,
    and the demoted docs land on the compressed cold spool."""
    sessions, pool, streams, _ = _fleet(tmp_path, n=5, slots=(5,),
                                        warm_docs=2)
    rows = {}
    for i in range(5):
        pool.admit(i, need=16)
        rows[i] = pool.docs[i]
    # distinct recency: doc i last scheduled at round 10 + i
    for i in range(5):
        rows[i].last_sched = 10 + i
    order = []
    for i in (2, 0, 4, 3, 1):  # deposit order is NOT the LRU order
        st = pool._pull_row(rows[i])
        pool._free_row(rows[i])
        pool.warm_deposit(i, np.asarray(st.doc[0]), int(st.length[0]),
                          int(st.nvis[0]))
        order.append(i)
    # budget 2: three demotions happened, in last_sched order among
    # what was warm at each overflow
    assert sorted(d for d in range(5) if pool.docs[d].spool) == [0, 1, 2]
    assert sorted(pool.warm.entries) == [3, 4]  # the most recent two
    assert pool.warm_evictions == 3
    # cold writes are COMPRESSED (warm→hot stays memory-only)
    with zipfile.ZipFile(pool.docs[0].spool) as z:
        assert all(i.compress_type == zipfile.ZIP_DEFLATED
                   for i in z.infolist())


def test_warm_hit_skips_disk_and_decodes(tmp_path):
    """Evict→warm→admit round-trips through memory only: the doc comes
    back byte-identical with zero cold restores."""
    sessions, pool, streams, sched = _fleet(tmp_path, n=3, slots=(3,),
                                            warm_docs=4)
    sched.run(max_rounds=2)
    doc_id = next(d for d, r in pool.residents(128))
    rec = pool.docs[doc_id]
    before = pool.decode(doc_id)
    st = pool._pull_row(rec)
    pool._free_row(rec)
    pool.warm_deposit(doc_id, np.asarray(st.doc[0]), int(st.length[0]),
                      int(st.nvis[0]))
    assert doc_id in pool.warm and rec.spool is None
    assert pool.decode(doc_id) == before  # decode reads the warm tier
    pool.admit(doc_id, need=rec.length)
    assert pool.decode(doc_id) == before
    assert pool.warm_hits == 1 and pool.restores == 0


def test_mid_macro_round_evict_to_warm_restore_round_trip(tmp_path):
    """An oversubscribed drain with the warm tier big enough to hold
    every eviction: docs cycle hot→warm→hot across macro-rounds with
    NO disk restores, and every doc drains byte-identical."""
    sessions, pool, streams, sched = _fleet(tmp_path, n=6, slots=(2,),
                                            warm_docs=16)
    sched.run()
    assert sched.done
    assert pool.evictions > 0
    assert pool.warm_hits > 0  # evicted docs came back from warm
    assert pool.restores == 0  # ...never from disk
    assert pool.warm_evictions == 0
    _assert_parity(sessions, pool, streams, skip_lossy=False)


def test_two_tier_pool_unchanged_without_warm_budget(tmp_path):
    """warm_docs=0 (the default) is exactly the historical two-tier
    pool: evictions spool straight to disk, uncompressed, no prefetch
    thread."""
    sessions, pool, streams, sched = _fleet(tmp_path, n=6, slots=(2,),
                                            warm_docs=0)
    assert pool.prefetcher is None
    sched.run()
    assert sched.done
    assert pool.warm_hits == 0 and len(pool.warm) == 0
    assert pool.restores > 0  # the spool round-trips still happened
    _assert_parity(sessions, pool, streams, skip_lossy=False)


def test_same_round_victim_promotion_keeps_state(tmp_path):
    """Regression: a doc evicted as a smaller class's victim in the
    SAME round its promotion installs into a larger class.  The
    two-tier pool marked the victim's spool at plan time; warm mode
    defers the deposit to the boundary, so without the plan's limbo
    tracking the later class saw a state-less doc and installed it
    FRESH — silently losing its whole edit history (caught by the
    oracle on the first full-mix tier run)."""
    sessions = build_fleet(
        12, mix={"synth-medium": 1.0}, seed=4, arrival_span=2,
        bands={"synth-medium": ("synth", (300, 600))},
    )
    pool = DocPool(classes=(128, 512, 1024), slots=(3, 2, 2),
                   spool_dir=str(tmp_path / "spool"), warm_docs=4)
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32)
    sched.run()
    assert sched.done
    assert pool.promotions > 0 and pool.evictions > 0
    assert sched.limbo_pulls > 0, (
        "test setup: no same-round victim→promotion collision occurred"
    )
    # no doc may ever hold two tiers at once
    for d, rec in pool.docs.items():
        tiers = [rec.cls is not None, d in pool.warm,
                 rec.spool is not None]
        assert sum(tiers) <= 1, (d, tiers)
    # the O(1) cold counter never drifted from ground truth across
    # all the churn above (every rec.spool transition is audited)
    n = pool.cold_docs
    assert n == pool.recount_cold(), "cold counter drifted"
    _assert_parity(sessions, pool, streams, skip_lossy=False)


# ---------------------------------------------------------------------------
# deferred spool unlink (the crash-window fix)
# ---------------------------------------------------------------------------


def test_rehydrate_keeps_spool_until_resident(tmp_path, monkeypatch):
    """The crash window: a rehydrate that dies between the spool read
    and the install must leave the doc's only durable copy intact —
    the unlink is deferred until the doc is resident and
    dirty-tracked.  (The historical order unlinked first: an install
    crash stranded the doc with neither device state nor spool.)"""
    sessions, pool, streams, sched = _fleet(tmp_path, n=2, slots=(2,),
                                            warm_docs=0)
    sched.run(max_rounds=2)
    doc_id = next(d for d, r in pool.residents(128))
    before = pool.decode(doc_id)
    spool = pool.evict(doc_id)
    rec = pool.docs[doc_id]
    assert os.path.exists(spool) and rec.spool == spool

    boom = RuntimeError("install died mid-rehydrate")

    def dead_install(*a, **kw):
        raise boom

    monkeypatch.setattr(pool, "_install", dead_install)
    with pytest.raises(RuntimeError, match="mid-rehydrate"):
        pool.admit(doc_id, need=rec.length)
    # the durable copy survived the crash window
    assert rec.spool == spool and os.path.exists(spool)
    assert pool.decode(doc_id) == before
    monkeypatch.undo()
    cls, row = pool.admit(doc_id, need=rec.length)
    assert rec.cls == cls and rec.spool is None
    assert pool.decode(doc_id) == before
    # the stale file is left behind by design (superseded by the next
    # eviction's atomic replace), marked stale via rec.spool = None
    assert os.path.exists(spool)


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------


def _drain_prefetcher(pf, want: int, timeout=5.0):
    """Poll the non-blocking harvest until ``want`` payloads arrived."""
    out = []
    t0 = time.monotonic()
    while len(out) < want and time.monotonic() - t0 < timeout:
        out.extend(pf.drain())
        time.sleep(0.005)
    return out


def test_prefetch_hit_vs_synchronous_miss_byte_parity(tmp_path):
    """Across every hosted capacity class: a doc admitted through the
    prefetch path (cold → worker rehydrate → warm → compose) is
    byte-identical to the same doc admitted through the synchronous
    cold path."""
    sessions = build_fleet(8, mix=TWO_MIX, seed=7, arrival_span=1,
                           bands=TWO_BANDS)
    pool = DocPool(classes=(128, 1024), slots=(8, 4),
                   spool_dir=str(tmp_path / "spool"), warm_docs=8)
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32)
    sched.run()  # full drain: medium docs end promoted to class 1024
    by_cls = {}
    for cls in (128, 1024):
        for d, _row in pool.residents(cls):
            by_cls.setdefault(cls, d)
    assert len(by_cls) == 2, "fleet setup: both classes must host docs"
    for cls, doc_id in sorted(by_cls.items()):
        rec = pool.docs[doc_id]
        want = pool.decode(doc_id)
        spool = pool.evict(doc_id)
        # -- synchronous miss --
        pool.admit(doc_id, need=rec.length)
        got_sync = pool.decode(doc_id)
        spool = pool.evict(doc_id)
        # -- prefetch hit --
        pf = pool.prefetcher
        assert pf.submit(doc_id, spool, pool.spool_gen(doc_id))
        (payload,) = _drain_prefetcher(pf, 1)
        assert payload["error"] is None and payload["doc"] == doc_id
        assert pool.store_prefetched(
            payload["doc"], payload["row"], payload["length"],
            payload["nvis"], round_no=0,
        )
        assert doc_id in pool.warm
        pool.admit(doc_id, need=rec.length)
        got_pf = pool.decode(doc_id)
        assert got_sync == got_pf == want
    assert pool.prefetch_hits == 2
    pool.close()
    assert not pool.prefetcher.alive


def test_stale_prefetch_payload_is_dropped(tmp_path):
    """A prefetch read that raced a re-eviction (spool generation
    moved) must be rejected at store time — the superseded bytes never
    reach the warm tier."""
    sessions, pool, streams, sched = _fleet(tmp_path, n=2, slots=(2,),
                                            warm_docs=4)
    sched.run(max_rounds=2)
    doc_id = next(d for d, r in pool.residents(128))
    rec = pool.docs[doc_id]
    spool = pool.evict(doc_id)
    gen = pool.spool_gen(doc_id)
    pf = pool.prefetcher
    assert pf.submit(doc_id, spool, gen)
    (payload,) = _drain_prefetcher(pf, 1)
    # the doc advances: rehydrate, (pretend to) apply, re-evict
    pool.admit(doc_id, need=rec.length)
    pool.evict(doc_id)
    assert pool.spool_gen(doc_id) != payload["gen"]
    # generation mismatch = dropped: the superseded bytes never land
    assert payload["gen"] == gen
    assert not pool.store_prefetched(
        payload["doc"], payload["row"], payload["length"],
        payload["nvis"], round_no=0, gen=payload["gen"],
    )
    assert doc_id not in pool.warm
    assert rec.spool is not None  # the CURRENT durable copy survives


def test_scheduled_drain_prefetches_under_pressure(tmp_path):
    """An oversubscribed drain with a warm tier smaller than the
    pending set: the prefetcher must actually run (submissions +
    publish-point entries) and the drain stays byte-exact whatever
    mix of warm hits and synchronous misses admission took."""
    from crdt_benches_tpu.lint import race_sanitizer

    race_sanitizer.reset_counters()
    sessions, pool, streams, sched = _fleet(tmp_path, n=10, slots=(3,),
                                            warm_docs=3, seed=5)
    sched.run()
    assert sched.done
    pf = pool.prefetcher
    assert pf.submitted > 0
    assert pf.harvested == pf.submitted
    counts = race_sanitizer.counters()
    assert counts["publishes"].get("Prefetcher._publish", 0) > 0
    _assert_parity(sessions, pool, streams, skip_lossy=False)


# ---------------------------------------------------------------------------
# chaos kinds
# ---------------------------------------------------------------------------


def test_tier_chaos_kinds_fire_and_recover(tmp_path):
    """``tier_evict_pressure`` forces warm→cold churn mid-drain and
    ``prefetch_miss`` drops a planned prefetch batch; both must fire,
    recover, and leave the fleet byte-identical (admission's
    synchronous fallback is the designed recovery)."""
    plan = FaultPlan([
        FaultEvent(kind="tier_evict_pressure", round=2),
        FaultEvent(kind="prefetch_miss", round=2),
    ], seed=3)
    sessions, pool, streams, sched = _fleet(
        tmp_path, n=10, slots=(3,), warm_docs=3, seed=5,
        faults=FaultInjector(plan),
    )
    sched.run()
    assert sched.done
    by_kind = {e.kind: e for e in plan.events}
    ev_p = by_kind["tier_evict_pressure"]
    assert ev_p.fired and ev_p.recovered and ev_p.detail["demoted"] >= 1
    ev_m = by_kind["prefetch_miss"]
    assert ev_m.fired and ev_m.recovered and ev_m.detail["dropped"] >= 1
    assert sched.prefetch_missed >= 1
    assert pool.warm_evictions >= ev_p.detail["demoted"]
    _assert_parity(sessions, pool, streams, skip_lossy=False)


# ---------------------------------------------------------------------------
# journal / snapshot / recovery: one residency story
# ---------------------------------------------------------------------------


def test_recover_fleet_across_all_three_tiers(tmp_path):
    """A snapshot barrier over a fleet split hot/warm/cold restores
    EVERY tier through one composed path: warm members ride the
    barrier as shadow spool members, recovery puts them back in the
    warm tier, and the resumed drain ends byte-identical."""
    jd = str(tmp_path / "journal")
    sessions, pool, streams, sched = _fleet(
        tmp_path, n=9, slots=(3,), warm_docs=3, seed=13,
        journal=OpJournal(jd), snapshot_every=2,
    )
    # drain partway: with 9 docs on 3 rows and warm budget 3, the
    # fleet is genuinely split across tiers mid-drain
    sched.run(max_rounds=5)
    assert not sched.done
    hot = sum(1 for r in pool.docs.values() if r.cls is not None)
    warm = len(pool.warm)
    cold = pool.cold_docs
    assert hot and warm and cold, (hot, warm, cold)
    assert sched.stats.snapshots >= 1
    sched.journal.close()

    # the crash: fresh pool + streams from nothing but the journal dir
    pool2 = DocPool(classes=(128,), slots=(3,),
                    spool_dir=str(tmp_path / "spool2"), warm_docs=3)
    streams2 = prepare_streams(sessions, pool2, batch=8, batch_chars=32)
    rep = recover_fleet(pool2, streams2, jd)
    assert rep.snapshot_round >= 0
    assert rep.warm_restored >= 1  # warm residency came back as warm
    assert len(pool2.warm) >= 1
    assert rep.docs_restored >= 1
    sched2 = FleetScheduler(pool2, streams2, batch=8, macro_k=4,
                            batch_chars=32,
                            start_round=rep.resume_round)
    sched2.run()
    assert sched2.done
    _assert_parity(sessions, pool2, streams2, skip_lossy=False)
    pool.close()
    pool2.close()


def test_snapshot_shadows_make_second_barrier_free(tmp_path):
    """Warm entries are immutable, so the shadow written for barrier N
    is reused (hard-linked) by barrier N+1 — the second barrier does
    not rewrite unchanged warm members."""
    from crdt_benches_tpu.serve.journal import write_snapshot

    jd = str(tmp_path / "journal")
    os.makedirs(jd)
    sessions, pool, streams, sched = _fleet(tmp_path, n=4, slots=(4,),
                                            warm_docs=4)
    sched.run(max_rounds=2)
    doc_id = next(d for d, r in pool.residents(128))
    rec = pool.docs[doc_id]
    st = pool._pull_row(rec)
    pool._free_row(rec)
    pool.warm_deposit(doc_id, np.asarray(st.doc[0]), int(st.length[0]),
                      int(st.nvis[0]))
    d1, m1 = write_snapshot(jd, pool, streams, 10, kind="full")
    shadow = pool.warm.entries[doc_id].shadow
    assert shadow is not None and os.path.exists(shadow)
    assert str(doc_id) in m1["warm"]
    ino1 = os.stat(os.path.join(d1, m1["warm"][str(doc_id)])).st_ino
    d2, m2 = write_snapshot(jd, pool, streams, 20, kind="full")
    ino2 = os.stat(os.path.join(d2, m2["warm"][str(doc_id)])).st_ino
    assert pool.warm.entries[doc_id].shadow == shadow
    assert ino1 == ino2 == os.stat(shadow).st_ino  # one inode, linked


# ---------------------------------------------------------------------------
# bench surface: --serve-tiers grammar, residency block, gauges
# ---------------------------------------------------------------------------


def test_parse_tier_spec_grammar():
    slots = (2048, 512, 128, 32, 16)
    scaled, warm = parse_tier_spec("hot=1024,warm=4096", slots)
    assert warm == 4096
    assert all(s >= 2 for s in scaled)
    assert abs(sum(scaled) - 1024) <= len(slots) * 2  # ~proportional
    # warm alone keeps the explicit slot table
    same, warm2 = parse_tier_spec("warm=64", slots)
    assert same == slots and warm2 == 64
    with pytest.raises(ValueError, match="warm=DOCS"):
        parse_tier_spec("hot=64", slots)
    with pytest.raises(ValueError, match="unknown key"):
        parse_tier_spec("lukewarm=3", slots)
    with pytest.raises(ValueError, match="floor"):
        parse_tier_spec("hot=4,warm=8", slots)


def test_zipf_arrival_dist_is_skewed_and_deterministic():
    a = build_fleet(400, mix=TINY_MIX, seed=9, bands=TINY_BANDS,
                    arrival_span=16, arrival_dist="zipf")
    b = build_fleet(400, mix=TINY_MIX, seed=9, bands=TINY_BANDS,
                    arrival_span=16, arrival_dist="zipf")
    assert [s.arrival for s in a] == [s.arrival for s in b]
    arrivals = np.array([s.arrival for s in a])
    assert arrivals.max() >= 8  # the tail really spans the window
    # the head is dense: far more than the uniform share arrives at 0
    assert (arrivals == 0).mean() > 2.5 / 16
    with pytest.raises(ValueError, match="arrival_dist"):
        build_fleet(4, mix=TINY_MIX, bands=TINY_BANDS,
                    arrival_dist="pareto")


def test_bench_residency_block_gauges_and_chaos_gate(tmp_path):
    """run_serve_bench under --serve-tiers: the artifact carries the
    versioned residency block (hit accounting + prefetch counters),
    the tier gauges land in the metrics registry, the status surface
    carries the residency dict, and the tier chaos kinds pass the
    chaos gate."""
    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=10, batch=8,
        classes=(128,), slots=(16,), seed=5, arrival_span=2,
        verify_sample=4, bands=TINY_BANDS, macro_k=4, batch_chars=32,
        serve_tiers="hot=3,warm=3",
        faults="seed=3,span=3,tier_evict_pressure=1,prefetch_miss=1",
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        log=lambda *_: None,
    )
    assert info["verify_ok"] and info["faults_ok"]
    with open(info["path"]) as f:
        (d,) = json.load(f)
    ex = d["extra"]
    assert d["trace"] == "tier/custom"  # serve/tier/<mix>/<fleet> ids
    res = ex["residency"]
    assert res["version"] == 1 and res["warm_budget"] == 3
    assert res["warm_hits"] + res["cold_restores"] > 0
    assert res["hit_rate"] is not None
    assert res["prefetch_submitted"] >= 0
    g = ex["metrics"]["gauges"]
    for name in ("serve.tier.hot_rows", "serve.tier.warm_docs",
                 "serve.tier.cold_docs", "serve.tier.prefetch_inflight"):
        assert name in g, (name, sorted(g))
    c = ex["metrics"]["counters"]
    for name in ("serve.tier.warm_hits", "serve.tier.warm_evictions",
                 "serve.tier.prefetch_hits"):
        assert name in c, (name, sorted(c))
    kinds = {e["kind"]: e for e in ex["faults"]["events"]}
    assert kinds["tier_evict_pressure"]["fired"]
    assert kinds["prefetch_miss"]["fired"]
    # the prefetch publish surface is armed in the crossings block
    assert ex["thread_crossings"]["prefetch"] is True


def test_tier_fault_kinds_require_tiers(tmp_path):
    with pytest.raises(ValueError, match="serve-tiers"):
        run_serve_bench(
            mix=TINY_MIX, n_docs=4, bands=TINY_BANDS,
            classes=(128,), slots=(4,),
            faults="tier_evict_pressure=1",
            spool_dir=str(tmp_path / "spool"),
            results_dir=str(tmp_path / "results"),
            log=lambda *_: None,
        )


def test_status_fields_carry_residency(tmp_path):
    sessions, pool, streams, sched = _fleet(tmp_path, n=6, slots=(2,),
                                            warm_docs=3)
    sched.run(max_rounds=3)
    out = sched.status_fields()
    res = out["residency"]
    assert res["warm_budget"] == 3
    assert res["warm_docs"] == len(pool.warm)
    assert res["hot_rows"] == pool.hot_rows
    assert res["cold_docs"] == pool.cold_docs
    assert json.dumps(res)  # plain scalars only (the status contract)
