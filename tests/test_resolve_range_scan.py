"""The pure-JAX range resolver vs the Pallas kernel and the oracle.

``ops/resolve_range_scan.py`` must be bit-identical to
``ops/resolve_range_pallas.py`` (interpret mode on CPU) on every output —
token arrays, per-delete rank intervals, nused — because the serve fleet
and the off-TPU replay engine trust it as a drop-in; and per-ROW batches
(the fleet's whole reason for its existence) must replay documents
byte-exactly through ``apply_range_batch``.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from crdt_benches_tpu.ops.resolve_range_pallas import (
    resolve_range_pallas,
)
from crdt_benches_tpu.ops.resolve_range_scan import (
    resolve_ranges_rows,
    resolve_ranges_shared,
)
from crdt_benches_tpu.oracle import OracleDocument
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import (
    INSERT,
    split_insert_runs,
    tensorize_ranges,
)


def _oracle(trace):
    doc = OracleDocument.from_str(trace.start_content)
    for p, d, ins in trace.iter_patches():
        doc.replace(p, p + d, ins)
    return doc.content()


@pytest.mark.parametrize("seed,coalesce", [(0, False), (1, True), (3, True)])
def test_scan_resolver_matches_pallas_kernel(seed, coalesce):
    """Every output bit-identical to the kernel across a full replay's
    batches (interpret mode = the kernel's own CPU reference)."""
    tr = synth_trace(seed=seed, n_ops=260, base="scan-vs-pallas base ")
    rt = tensorize_ranges(tr, batch=32, coalesce=coalesce)
    kb, pb, lb, sb = rt.batched()
    nvis = len(rt.init_chars)
    for i in range(rt.n_batches):
        k, p, l, s = (jnp.asarray(x[i]) for x in (kb, pb, lb, sb))
        v = jnp.asarray([nvis], jnp.int32)
        tok_p, di_p, nu_p = resolve_range_pallas(
            k, p, l, s, v, interpret=True
        )
        tok_s, di_s, nu_s = resolve_ranges_shared(k, p, l, s, v)
        T = np.asarray(tok_s[0]).shape[1]  # kernel pads T up to 128
        for a, b, name in zip(tok_p, tok_s, ("ttype", "ta", "tch", "tlen")):
            np.testing.assert_array_equal(
                np.asarray(a)[:, :T], np.asarray(b), err_msg=f"{i}/{name}"
            )
        for a, b, name in zip(di_p, di_s, ("dlo", "dhi", "dcount")):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"{i}/{name}"
            )
        assert int(np.asarray(nu_p)[0, 0]) == int(np.asarray(nu_s)[0])
        ins = int(np.where(kb[i] == INSERT, lb[i], 0).sum())
        nvis += ins - int(np.asarray(di_s[2]).sum())


def test_per_row_batches_replay_byte_exact():
    """The fleet contract: R lanes, each a DIFFERENT document with its
    own coalesced range stream, replayed via vmapped scan-resolve +
    apply_range_batch — every lane byte-identical to its oracle."""
    from crdt_benches_tpu.ops.apply2 import PackedState, decode_state3
    from crdt_benches_tpu.ops.apply_range import apply_range_batch
    from crdt_benches_tpu.serve.pool import _fresh_row_np

    R, B, C, CAP = 4, 8, 256, 32
    traces = [
        synth_trace(seed=40 + r, n_ops=90, base="doc base " * (r + 1))
        for r in range(R)
    ]
    rts = [tensorize_ranges(t, batch=1, coalesce=True) for t in traces]
    streams = [
        split_insert_runs(
            rt.kind[: rt.n_ops], rt.pos[: rt.n_ops],
            rt.rlen[: rt.n_ops], rt.slot0[: rt.n_ops], CAP,
        )
        for rt in rts
    ]
    n_batches = max(-(-len(s[0]) // B) for s in streams)
    state = PackedState(
        doc=jnp.asarray(np.stack([
            _fresh_row_np(C, len(rt.init_chars)) for rt in rts
        ])),
        length=jnp.asarray([len(rt.init_chars) for rt in rts], jnp.int32),
        nvis=jnp.asarray([len(rt.init_chars) for rt in rts], jnp.int32),
    )
    for i in range(n_batches):
        kind = np.zeros((R, B), np.int32)  # PAD
        pos = np.zeros((R, B), np.int32)
        rlen = np.zeros((R, B), np.int32)
        slot0 = np.full((R, B), -1, np.int32)
        for r, (k, p, l, s) in enumerate(streams):
            lo, hi = i * B, min((i + 1) * B, len(k))
            if lo < hi:
                kind[r, : hi - lo] = k[lo:hi]
                pos[r, : hi - lo] = p[lo:hi]
                rlen[r, : hi - lo] = l[lo:hi]
                slot0[r, : hi - lo] = s[lo:hi]
        tokens, dints, _ = resolve_ranges_rows(
            *(jnp.asarray(a) for a in (kind, pos, rlen, slot0)),
            state.nvis,
        )
        state = apply_range_batch(state, tokens, dints, nbits=6)
    for r, (t, rt) in enumerate(zip(traces, rts)):
        codes, nvis = decode_state3(state, jnp.asarray(rt.chars), replica=r)
        got = "".join(map(chr, np.asarray(codes)[: int(nvis)].tolist()))
        assert got == _oracle(t), f"lane {r} diverged"


def test_split_insert_runs_invariants():
    kind = np.asarray([1, 2, 1, 1], np.int32)  # INSERT, DELETE, INSERT x2
    pos = np.asarray([0, 5, 10, 3], np.int32)
    rlen = np.asarray([70, 99, 32, 5], np.int32)
    slot0 = np.asarray([100, -1, 200, 300], np.int32)
    k2, p2, r2, s2 = split_insert_runs(kind, pos, rlen, slot0, 32)
    # 70 -> 32+32+6; delete untouched; 32 and 5 untouched
    assert list(r2) == [32, 32, 6, 99, 32, 5]
    assert list(p2) == [0, 32, 64, 5, 10, 3]
    assert list(s2) == [100, 132, 164, -1, 200, 300]
    assert (r2[k2 == 1] <= 32).all()
    # char totals preserved
    assert r2[k2 == 1].sum() == rlen[kind == 1].sum()
    with pytest.raises(ValueError):
        split_insert_runs(kind, pos, rlen, slot0, 0)
    # no-op when nothing exceeds the cap: same arrays pass through
    k3, p3, r3, s3 = split_insert_runs(kind, pos, rlen, slot0, 128)
    assert r3 is rlen and s3 is slot0
