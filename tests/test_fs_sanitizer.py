"""graftlint v4 runtime twin: the fs sanitizer's disarmed-identity
contract, per-protocol op-sequence attribution (pinning the
fsync-before-replace audit fixes), the live G019 ordering enforcement,
crash-injection freeze semantics, the exhaustive crash-point
enumeration over the whole durability stack, and the G021 cross-check
green in both directions on a sanitized 12-doc drain."""

import json
import os
import time

import numpy as np
import pytest

from crdt_benches_tpu.lint import fs_sanitizer as fss
from crdt_benches_tpu.lint.core import run_lint
from crdt_benches_tpu.ops.apply2 import PackedState
from crdt_benches_tpu.serve.journal import OpJournal, wal_segments
from crdt_benches_tpu.utils.checkpoint import load_state, save_state

PACKAGE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "crdt_benches_tpu")


@pytest.fixture(autouse=True)
def _fs_reset(monkeypatch):
    """Every test owns a clean sanitizer: counters zeroed, watch roots
    cleared, disarmed unless the test arms it."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_FS", raising=False)
    fss.disarm()
    fss.clear_watch_roots()
    fss.reset_counters()
    yield
    fss.disarm()
    fss.clear_watch_roots()
    fss.reset_counters()


def _state(n: int = 6) -> PackedState:
    return PackedState(
        doc=np.full((1, n), 2, np.int32),
        length=np.asarray([n], np.int32),
        nvis=np.asarray([n], np.int32),
    )


# ---------------------------------------------------------------------------
# disarmed identity + timing
# ---------------------------------------------------------------------------


def test_disarmed_counts_entries_but_records_no_ops(tmp_path):
    fss.watch_root(str(tmp_path))
    p = str(tmp_path / "doc.npz")
    save_state(p, _state(), compress=False, durable=True)
    load_state(p)
    c = fss.counters()
    assert c["protocols"] == {"spool": 2}
    assert c["ops"] == {} and c["unattributed"] == {}
    assert fss.op_log() == []
    assert fss.mutation_count() == 0


def test_disarmed_protocol_entry_timing_smoke():
    """The always-on cost is one lock-guarded dict bump per protocol
    entry — generous ceiling so the smoke never flakes, but a real
    regression (interposition leaking into disarmed mode) blows
    through it."""
    t0 = time.perf_counter()
    for _ in range(10_000):
        with fss.fs_protocol("spool"):
            pass
    dt = time.perf_counter() - t0
    assert dt < 2.0, f"10k disarmed protocol entries took {dt:.3f}s"
    assert fss.counters()["protocols"]["spool"] == 10_000


# ---------------------------------------------------------------------------
# armed: attribution + the audit-fix regression pins
# ---------------------------------------------------------------------------


def test_armed_spool_sequence_pins_fsync_before_replace(
        tmp_path, monkeypatch):
    """The graftlint v4 audit fix, as a runtime regression pin: a
    durable save's committed replace is preceded by an fsync in the
    SAME protocol entry (content durability before name durability)."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_FS", "1")
    fss.watch_root(str(tmp_path))
    p = str(tmp_path / "doc.npz")
    save_state(p, _state(), compress=False, durable=True)
    seq = [(t, o) for t, o, _ in fss.op_log()]
    assert ("spool", "replace") in seq
    assert ("spool", "fsync") in seq
    assert seq.index(("spool", "fsync")) < seq.index(("spool", "replace"))
    # non-durable saves skip the per-eviction fsync (the PR 2 cost
    # contract): replace present, no fsync before it
    fss.reset_counters()
    save_state(p, _state(), compress=False)
    seq = [(t, o) for t, o, _ in fss.op_log()]
    assert seq and seq[0] == ("spool", "replace")
    c = fss.counters()
    assert c["ops"]["spool"]["replace"] == 1
    assert c["unattributed"] == {}


def test_armed_wal_seal_and_gc_attribution(tmp_path, monkeypatch):
    """Journal protocols attribute where declared: seals (wal) fsync
    before their rename; a GC pass (gc) commits its manifest before
    any victim unlink — live-checked by the sanitizer, sequence-pinned
    here."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_FS", "1")
    jd = str(tmp_path / "j")
    fss.watch_root(jd)
    j = OpJournal(jd, segment_bytes=120)
    for r in range(10):
        j.round_record(r, {256: [[1, r, r + 1]]})
        j.maybe_roll()
    assert len(wal_segments(jd)) >= 2
    info = j.compact(10)
    assert info["deleted"] >= 1
    j.close()
    seq = [(t, o) for t, o, _ in fss.op_log()]
    # seal: fsync precedes the segment rename, inside wal
    first_seal = seq.index(("wal", "replace"))
    assert ("wal", "fsync") in seq[:first_seal]
    # GC: the manifest commit (gc replace) precedes the first victim
    # unlink
    gc_replace = seq.index(("gc", "replace"))
    gc_unlink = seq.index(("gc", "unlink"))
    assert gc_replace < gc_unlink
    c = fss.counters()
    assert c["unattributed"] == {}
    assert set(c["ops"]) >= {"wal", "gc"}


def test_reset_arms_eagerly_so_pre_entry_ops_are_unattributed(
        tmp_path, monkeypatch):
    """The G021 accounting must see mutating ops on watched roots from
    the RESET on, not from the first protocol entry on — arming lazily
    would blind the unattributed-op check for exactly the run prefix
    where setup code touches durable territory outside any declared
    protocol."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_FS", "1")
    fss.watch_root(str(tmp_path))
    fss.reset_counters()  # the bench's reset: installs + arms
    src = tmp_path / "a"
    src.write_text("x")
    os.replace(str(src), str(tmp_path / "b"))  # no protocol entered yet
    c = fss.counters()
    assert c["unattributed"] == {"replace": 1}, c
    assert fss.mutation_count() == 1


def test_staging_dir_contents_are_staging_and_update_mode_is_mutating(
        tmp_path, monkeypatch):
    """Two path-role/op-vocabulary pins: (a) a file INSIDE a
    ``snap_*.tmp`` staging directory is staging — destroying it needs
    no prior commit (the sweep_staging shape on rmtree fallbacks that
    unlink member-by-member); (b) an ``r+`` open is an UPDATE — a
    crash boundary, frozen post-crash, and never a G019 read-witness
    (the WAL torn-tail truncate repair mutates in place)."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_FS", "1")
    fss.watch_root(str(tmp_path))
    fss.reset_counters()
    staging = tmp_path / "snap_00000004.tmp"
    staging.mkdir()
    member = staging / "MANIFEST.json"
    member.write_text("{}")
    with fss.fs_protocol("snapshot"):
        os.unlink(str(member))  # staging: legal with no prior commit
    durable = tmp_path / "journal.log"
    durable.write_text("rec\n")
    fss.reset_counters()
    with fss.fs_protocol("wal"):
        with open(str(durable), "r+b") as f:
            f.truncate(2)
    assert fss.counters()["ops"]["wal"] == {"update": 1}
    assert fss.mutation_count() == 1  # the update IS a crash boundary
    # ...and it is not a read-witness: a destructive op after it still
    # raises
    with pytest.raises(fss.DurableOrderingError):
        with fss.fs_protocol("wal"):
            with open(str(durable), "r+b") as f:
                pass
            os.unlink(str(durable))


def test_live_g019_raises_on_unlink_before_install(tmp_path, monkeypatch):
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_FS", "1")
    fss.watch_root(str(tmp_path))
    p = str(tmp_path / "member.npz")
    save_state(p, _state(), compress=False)
    with pytest.raises(fss.DurableOrderingError):
        with fss.fs_protocol("gc"):
            os.unlink(p)
    assert os.path.exists(p)  # the violating op never executed
    # staging destruction is exempt...
    t = str(tmp_path / "member.npz.tmp")
    open(t, "w").close()
    with fss.fs_protocol("gc"):
        os.unlink(t)
    # ...and the read-witness form (torn-pass completion) is legal
    with fss.fs_protocol("gc"):
        with open(p, "rb") as f:
            f.read(4)
        os.unlink(p)
    assert not os.path.exists(p)


def test_crash_freeze_keeps_cleanup_handlers_off_the_disk(tmp_path):
    """Crash semantics are a DEAD PROCESS, not an exception: after the
    injected crash, even the atomic writer's own `except: unlink(tmp)`
    cleanup is frozen — the orphaned staging file stays behind exactly
    as a real kill would leave it (recovery sweeps ignore `.tmp`)."""
    fss.watch_root(str(tmp_path))
    p = str(tmp_path / "doc.npz")
    with pytest.raises(fss.InjectedCrash):
        with fss.crash_at(0):  # op 0 = the commit replace
            save_state(p, _state(), compress=False)
    assert not os.path.exists(p)  # the commit never happened
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp" in f]
    assert leftovers, "the frozen cleanup should strand the tmp file"
    assert not fss._armed  # crash_at disarms on exit (env unset)


# ---------------------------------------------------------------------------
# the headline: exhaustive crash-point enumeration
# ---------------------------------------------------------------------------


def test_crash_enumeration_every_boundary_recovers(tmp_path):
    """THE graftlint v4 acceptance gate: for every declared protocol
    (snapshot barrier, delta chain, WAL seal + GC, spool churn, flight
    dump), a crash injected at EVERY mutating fs-op boundary is
    followed by byte-verified recovery — and the per-protocol point
    counts are nonzero, so the harness cannot silently cover
    nothing."""
    from crdt_benches_tpu.serve.fscrash import enumerate_crash_points

    report = enumerate_crash_points(str(tmp_path / "w"), small=True)
    assert report["mutations"] > 0
    assert report["verified"] == report["mutations"]
    for tag in fss.KNOWN_PROTOCOLS:
        assert report["per_protocol"].get(tag, 0) > 0, report


# ---------------------------------------------------------------------------
# G021 cross-check on a real sanitized drain
# ---------------------------------------------------------------------------


def test_g021_cross_check_clean_both_directions(tmp_path, monkeypatch):
    """A sanitized 12-doc journaled drain emits an fs_ops block that
    cross-checks clean against the static durable= markers in BOTH
    directions: no dead declared protocols (every armed surface's
    protocols entered) and no unattributed runtime fs ops."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_FS", "1")
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix={"synth-small": 0.6, "synth-medium": 0.4},
        bands={
            "synth-small": ("synth", (10, 60)),
            "synth-medium": ("synth", (150, 360)),
        },
        n_docs=12, batch=16, classes=(256, 1024), slots=(4, 2),
        macro_k=2, batch_chars=64, arrival_span=2, verify_sample=4,
        journal_dir="auto", snapshot_every=2, snapshot_full_every=2,
        wal_segment_bytes=256,
        results_dir=str(tmp_path), save_name="fs_smoke", log=lambda s: None,
    )
    assert info["verify_ok"]
    block = r.extra["fs_ops"]
    assert block["version"] == 1 and block["sanitized"]
    assert block["journal"] and block["spool"]
    for tag in ("snapshot", "gc", "wal", "spool"):
        assert block["protocols"].get(tag, 0) > 0, block["protocols"]
    assert block["unattributed"] == {}
    artifact = str(tmp_path / "fs_smoke.json")
    assert os.path.exists(artifact)
    findings = run_lint([PACKAGE], select={"G021"}, fs_artifact=artifact)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line} {f.rule} {f.msg}" for f in findings
    )


def test_g021_flags_dead_protocol_and_rogue_tag_on_doctored_block(
        tmp_path):
    """Both failure directions, against a doctored artifact: a dead
    declared protocol (armed surface, zero entries) and a runtime tag
    + unattributed ops no static marker explains."""
    artifact = tmp_path / "doctored.json"
    artifact.write_text(json.dumps({"fs_ops": {
        "version": 1, "sanitized": True,
        "journal": True, "spool": False, "flight": False,
        "protocols": {"gc": 3, "wal": 9, "rogue": 1},
        "ops": {"gc": {"replace": 3}, "rogue": {"unlink": 1}},
        "unattributed": {"rmtree": 2},
    }}))
    findings = run_lint([PACKAGE], select={"G021"},
                        fs_artifact=str(artifact))
    msgs = [f.msg for f in findings]
    # snapshot is journal-armed but never entered -> dead
    assert any("`snapshot` never entered" in m for m in msgs)
    # spool surface not armed -> spool NOT dead-checked
    assert not any("`spool` never entered" in m for m in msgs)
    assert any("rogue" in m for m in msgs)
    assert any("unattributed runtime `rmtree`" in m for m in msgs)


def test_fs_ops_block_present_and_entry_counted_disarmed(tmp_path):
    """A plain (disarmed, journal-less) drain still carries the fs_ops
    block with protocol ENTRY counts — the always-on half of the G021
    ground truth, exactly like publish entries for G017."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix={"synth-small": 1.0},
        bands={"synth-small": ("synth", (10, 40))},
        n_docs=6, batch=16, classes=(256,), slots=(3,),
        macro_k=2, batch_chars=64, arrival_span=1, verify_sample=2,
        results_dir=str(tmp_path), save_name="fs_plain",
        log=lambda s: None,
    )
    assert info["verify_ok"]
    block = r.extra["fs_ops"]
    assert block["version"] == 1 and not block["sanitized"]
    assert not block["journal"] and not block["flight"]
    assert block["ops"] is None and block["unattributed"] is None
    # spool entries show up whenever the pool spooled (evictions with
    # 6 docs on 3 rows)
    if block["spool"]:
        assert block["protocols"].get("spool", 0) > 0
