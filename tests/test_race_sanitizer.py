"""The race sanitizer (lint/race_sanitizer.py): the runtime proof of
the static G014/G015 thread-confinement model.

Covers the contract points ISSUE 10 names: an unpublished cross-thread
access raises at its callsite; crossings attribute to the publish
point (and generation) that licensed them; a published object is
frozen on both sides; disarmed, ``share``/``reveal`` are IDENTITY (the
zero-overhead contract, like the ``@fenced`` no-op path); and a full
race-sanitized drain with the live status server up finishes
verify-green with its artifact ``thread_crossings`` a subset of the
static publish set — G017 clean in both directions.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from crdt_benches_tpu.lint import race_sanitizer
from crdt_benches_tpu.lint.core import build_index
from crdt_benches_tpu.lint.race_sanitizer import (
    SharedProxy,
    UndeclaredCrossThreadAccess,
    generation,
    publish_point,
    published,
    reveal,
    share,
)
from crdt_benches_tpu.lint.threads import g017_thread_crossings
from crdt_benches_tpu.serve.bench import run_serve_bench

TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_RACES", "1")
    race_sanitizer.reset_counters()
    yield
    race_sanitizer.reset_counters()


def _on_thread(fn):
    """Run ``fn`` on a fresh thread; return {'result': ...} or
    {'error': exc}."""
    box: dict = {}

    def target():
        try:
            box["result"] = fn()
        except Exception as e:  # noqa: BLE001 (the exception IS the assertion)
            box["error"] = e

    t = threading.Thread(target=target)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    return box


# ---------------------------------------------------------------------------
# the access rule
# ---------------------------------------------------------------------------


def test_unpublished_cross_thread_access_raises(armed):
    shared = share({"v": 1}, "test.unpublished")
    assert isinstance(shared, SharedProxy)
    assert shared["v"] == 1  # owner access is free pre-publish
    shared["v"] = 2  # owner mutation too (not yet published)
    for access in (
        lambda: shared["v"],
        lambda: len(shared),
        lambda: list(shared),
        lambda: reveal(shared),
    ):
        box = _on_thread(access)
        assert isinstance(box.get("error"), UndeclaredCrossThreadAccess)
        assert "test.unpublished" in str(box["error"])


def test_publish_generation_and_attribution(armed):
    with publish_point("pt.alpha"):
        shared = share({"v": 7}, "test.attributed")
    assert generation(shared) == 1
    box = _on_thread(lambda: reveal(shared)["v"])
    assert box.get("result") == 7
    box = _on_thread(lambda: shared["v"])
    assert box.get("result") == 7
    c = race_sanitizer.counters()
    assert c["publishes"]["pt.alpha"] == 1
    assert c["crossings"]["pt.alpha"] == 2
    # a re-publish through another point re-attributes the handoff
    with publish_point("pt.beta"):
        again = share(shared)
    assert again is shared and generation(shared) == 2
    _on_thread(lambda: reveal(shared))
    c = race_sanitizer.counters()
    assert c["publishes"]["pt.beta"] == 1
    assert c["crossings"]["pt.beta"] == 1
    assert c["crossings"]["pt.alpha"] == 2  # old attributions keep


def test_published_object_is_frozen_both_sides(armed):
    with publish_point("pt.freeze"):
        shared = share({"v": 1}, "test.frozen")
    # owner-side mutation after publish: readers may already hold it
    with pytest.raises(UndeclaredCrossThreadAccess, match="AFTER publish"):
        shared["v"] = 9
    with pytest.raises(UndeclaredCrossThreadAccess, match="AFTER publish"):
        shared.update({"v": 9})
    # reader-side mutation: published snapshots are read-only far-side
    box = _on_thread(lambda: shared.__setitem__("w", 1))
    assert isinstance(box.get("error"), UndeclaredCrossThreadAccess)
    assert "read-only" in str(box["error"])
    # reads stay legal on both sides
    assert shared["v"] == 1
    assert _on_thread(lambda: shared["v"]).get("result") == 1


def test_torn_publish_detected_at_cross_thread_read(armed):
    """The proxy cannot see a mutation made through a bare alias the
    publisher retained — but the fingerprint taken at publish can: the
    tear raises at the next legal cross-thread read."""
    snap = {"phase": "steady", "rounds": 3}
    with publish_point("pt.torn"):
        shared = share(snap, "test.torn")
    # a clean read crosses fine first
    assert _on_thread(lambda: reveal(shared)["phase"]).get("result") \
        == "steady"
    snap["phase"] = "torn"  # bare-alias mutation AFTER publish
    box = _on_thread(lambda: reveal(shared))
    assert isinstance(box.get("error"), UndeclaredCrossThreadAccess)
    assert "torn publish" in str(box["error"])
    assert "pt.torn" in str(box["error"])


def test_published_decorator_keys_by_qualname(armed):
    class Feed:
        @published
        def publish_snap(self, snap):
            return share(snap, "Feed.snap")

    shared = Feed().publish_snap({"x": 1})
    key = "test_published_decorator_keys_by_qualname.<locals>.Feed.publish_snap"
    assert race_sanitizer.counters()["publishes"][key] == 1
    assert generation(shared) == 1
    assert _on_thread(lambda: reveal(shared)["x"]).get("result") == 1
    assert race_sanitizer.counters()["crossings"][key] == 1


# ---------------------------------------------------------------------------
# disarmed: identity, entries-only counters
# ---------------------------------------------------------------------------


def test_disarmed_share_and_reveal_are_identity(monkeypatch):
    """The zero-overhead contract, same as the ``@fenced``/span no-op
    paths: disarmed, the 'proxy' IS the bare object."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_RACES", raising=False)
    race_sanitizer.reset_counters()
    obj = {"v": 1}
    assert share(obj, "test.identity") is obj
    assert reveal(obj) is obj
    assert generation(obj) is None
    with publish_point("pt.disarmed"):
        assert share(obj) is obj
    # entry counters still tick in every mode: G017's ground truth
    assert race_sanitizer.counters() == {
        "publishes": {"pt.disarmed": 1}, "crossings": {},
    }


def test_disarmed_share_timing_smoke(monkeypatch):
    """The disarmed fast path is one env read + an isinstance — a loose
    ceiling pins it from regressing into per-call proxy construction
    (flake margin: ~50x headroom on this container)."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_RACES", raising=False)
    obj = {"v": 1}
    t0 = time.perf_counter()
    for _ in range(20_000):
        reveal(share(obj))
    assert time.perf_counter() - t0 < 2.0


# ---------------------------------------------------------------------------
# the sanitized drain: runtime ground truth vs the static publish set
# ---------------------------------------------------------------------------


def _static_publish_qualnames() -> set[str]:
    import crdt_benches_tpu

    pkg = crdt_benches_tpu.__path__[0]
    index, errors = build_index([pkg])
    assert not errors
    return {
        fi.qualname
        for m in index.modules for fi in m.functions.values() if fi.publish
    }


def test_race_sanitized_drain_with_live_status(armed, tmp_path):
    """A full (tiny) 12-doc drain under CRDT_BENCH_SANITIZE_RACES=1
    with the status server live on an ephemeral port and a scraper
    thread hammering it MID-DRAIN: finishes verify-green (an
    unpublished cross-thread access would have raised), every observed
    crossing is attributed to a declared ``# graftlint: publish``
    point, and the artifact's ``thread_crossings`` block passes G017 in
    both directions."""
    ports: dict = {}
    scrapes = {"ok": 0}

    def log(msg):
        m = re.search(r"status server on http://127\.0\.0\.1:(\d+)",
                      str(msg))
        if m:
            ports["port"] = int(m.group(1))

    stop = threading.Event()

    def scraper():
        deadline = time.time() + 120
        while time.time() < deadline and not stop.is_set():
            port = ports.get("port")
            if port is None:
                time.sleep(0.01)
                continue
            base = f"http://127.0.0.1:{port}"
            try:
                json.load(urllib.request.urlopen(
                    base + "/status.json", timeout=2
                ))
                urllib.request.urlopen(base + "/metrics", timeout=2).read()
                scrapes["ok"] += 1
            except OSError:
                pass  # server booting or already down: keep polling
            time.sleep(0.02)

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    try:
        r, info = run_serve_bench(
            mix=TINY_MIX, n_docs=12, batch=16, macro_k=2, batch_chars=64,
            classes=(128, 512), slots=(8, 4), arrival_span=2,
            verify_sample=4, bands=TINY_BANDS, seed=7,
            spool_dir=str(tmp_path / "spool"),
            results_dir=str(tmp_path), save_name="race_smoke",
            status_port=0,
            timeseries_path=str(tmp_path / "race_smoke_ts.jsonl"),
            log=log,
        )
    finally:
        stop.set()
        t.join(timeout=10)

    assert info["verify_ok"]
    assert ports.get("port"), "status server never announced its port"
    assert scrapes["ok"] > 0, "scraper never landed a mid-drain read"
    block = r.extra["thread_crossings"]
    assert block["sanitized"] is True and block["status"] is True
    # disk parity: the block the artifact carries is the one in memory
    disk = json.loads((tmp_path / "race_smoke.json").read_text())
    assert disk[0]["extra"]["thread_crossings"] == block
    static = _static_publish_qualnames()
    assert set(block["publishes"]) <= static
    assert set(block["crossings"]) <= set(block["publishes"])
    # the drain actually published, and the scraper actually crossed
    assert block["publishes"].get("StatusServer.publish_status")
    assert block["publishes"].get("StatusServer.publish_metrics")
    assert sum(block["crossings"].values()) > 0
    # G017 clean in both directions against this very artifact
    import crdt_benches_tpu

    index, errors = build_index([crdt_benches_tpu.__path__[0]])
    assert not errors
    findings = g017_thread_crossings(
        index, str(tmp_path / "race_smoke.json")
    )
    assert findings == [], "\n".join(f.msg for f in findings)


def test_unsanitized_drain_records_publish_entries(monkeypatch, tmp_path):
    """Publish-entry counters are ground truth in EVERY run (G017's
    food), sanitizer or not — and the disarmed snapshot path stores
    the BARE dict (identity contract on the serving surface itself)."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_RACES", raising=False)
    race_sanitizer.reset_counters()
    from crdt_benches_tpu.obs.status import StatusServer

    srv = StatusServer(port=0)
    snap = {"phase": "steady", "rounds": 3}
    srv.publish_status(snap)
    assert srv._status is snap  # identity: no proxy disarmed
    assert srv.status_snapshot() is snap
    c = race_sanitizer.counters()
    assert c["publishes"] == {"StatusServer.publish_status": 1}
    assert c["crossings"] == {}
