"""obs/: span tracer, typed metric registry, device profiler, and the
bench_compare regression gate.

Contracts under test:

- disarmed tracing is the IDENTITY path (one shared no-op object, like
  the unset-@boundary decorator);
- armed tracing emits schema-valid Chrome trace JSON: spans nest, fence
  crossings land as instants inside their owning span;
- histograms round-trip through the artifact, merge associatively, and
  reproduce exact-list quantiles within bucket resolution (the parity
  guarantee that let ServeStats drop its unbounded lists);
- per-doc admission-to-drain latency is attributed to the right cause
  tag under injected shed / quarantine faults;
- ``tools/bench_compare.py`` fails a synthetic regression and passes an
  identical artifact.
"""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

from crdt_benches_tpu.bench.harness import steady_quantiles
from crdt_benches_tpu.obs import trace as obs_trace
from crdt_benches_tpu.obs.metrics import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from crdt_benches_tpu.obs.trace import (
    NOOP_SPAN,
    arm,
    disarm,
    instant,
    span,
    validate_trace,
    validate_trace_file,
)
from crdt_benches_tpu.serve.faults import (
    FaultEvent,
    FaultInjector,
    FaultPlan,
)
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import (
    DOC_CAUSE_TAGS,
    FleetScheduler,
    prepare_streams,
)
from crdt_benches_tpu.serve.workload import build_fleet

REPO = Path(__file__).resolve().parent.parent

TINY_BANDS = {"synth-small": ("synth", (40, 120))}
TINY_MIX = {"synth-small": 1.0}


def _fleet(tmp_path, n=6, seed=11, classes=(128,), slots=(2,), **kw):
    sessions = build_fleet(
        n, mix=TINY_MIX, seed=seed, arrival_span=2, bands=TINY_BANDS
    )
    pool = DocPool(classes=classes, slots=slots,
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32, **kw)
    return sessions, pool, streams, sched


# ---------------------------------------------------------------------------
# tracer: disarmed identity, armed schema
# ---------------------------------------------------------------------------


def test_disarmed_span_is_the_shared_noop():
    """The zero-overhead contract: with no tracer armed, every span()
    call returns THE SAME no-op object — no allocation, no clock read
    (the @boundary identity-path analog)."""
    assert not obs_trace.armed()
    s1 = span("serve.plan")
    s2 = span("serve.dispatch", round=7)
    assert s1 is NOOP_SPAN and s2 is NOOP_SPAN
    with s1:
        pass  # enter/exit are empty
    instant("serve.fault", kind="stall")  # no-op, no error


def test_armed_tracer_records_nested_spans_and_validates():
    tracer = arm()
    try:
        with span("outer", round=1):
            with span("inner"):
                instant("tick", n=3)
    finally:
        assert disarm() is tracer
    doc = tracer.to_dict()
    assert validate_trace(doc) == []
    names = [e["name"] for e in doc["traceEvents"]]
    assert names == ["tick", "inner", "outer"]  # spans close inner-first
    inner = next(e for e in doc["traceEvents"] if e["name"] == "inner")
    outer = next(e for e in doc["traceEvents"] if e["name"] == "outer")
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    tick = next(e for e in doc["traceEvents"] if e["name"] == "tick")
    assert tick["args"]["span"] == "inner"
    # disarmed again: back to the identity path
    assert span("outer") is NOOP_SPAN


def test_validator_rejects_malformed_traces():
    assert validate_trace([]) != []  # not a dict
    assert validate_trace({"traceEvents": [{"ph": "X"}]})  # missing keys
    # partially overlapping spans on one thread = corrupted stack
    bad = {"traceEvents": [
        {"ph": "X", "name": "a", "ts": 0, "dur": 10, "pid": 1, "tid": 1},
        {"ph": "X", "name": "b", "ts": 5, "dur": 10, "pid": 1, "tid": 1},
    ]}
    assert any("overlap" in e for e in validate_trace(bad))
    # a fence instant outside every span is a finding
    orphan = {"traceEvents": [
        {"ph": "X", "name": "a", "ts": 0, "dur": 5, "pid": 1, "tid": 1},
        {"ph": "i", "s": "t", "name": "f", "cat": "fence", "ts": 50,
         "pid": 1, "tid": 1},
    ]}
    assert any("inside no span" in e for e in validate_trace(orphan))


def test_traced_drain_emits_valid_trace_with_fence_instants(tmp_path):
    """A real (tiny) drain under the armed tracer: schema-valid, the
    macro-round phases all present, and every declared-fence crossing
    recorded as an instant inside its owning span."""
    sessions, pool, streams, sched = _fleet(tmp_path)
    tracer = arm()
    try:
        sched.run()
    finally:
        disarm()
    assert sched.done
    doc = tracer.to_dict()
    assert validate_trace(doc) == []
    span_names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    for phase in ("serve.round", "serve.plan", "serve.stage",
                  "serve.moves", "serve.dispatch", "serve.drain_fence"):
        assert phase in span_names, f"missing phase span {phase}"
    fences = [
        e for e in doc["traceEvents"]
        if e["ph"] == "i" and e.get("cat") == obs_trace.FENCE_CAT
    ]
    assert fences, "no fence crossings on the timeline"
    names = {e["name"] for e in fences}
    # the oversubscribed fleet must move rows -> boundary pulls fence
    assert "DocPool.pull_bucket" in names
    assert "DocPool.block" in names
    assert all((e.get("args") or {}).get("span") for e in fences)
    # file round-trip + CLI validator contract
    path = tracer.write(str(tmp_path / "trace.json"))
    assert validate_trace_file(path) == []


# ---------------------------------------------------------------------------
# metrics: round-trip, merge, quantile parity
# ---------------------------------------------------------------------------


def test_registry_serialization_round_trip():
    reg = MetricsRegistry()
    reg.counter("a.count").inc(7)
    reg.gauge("a.gauge").set(1.5)
    reg.gauge("a.gauge").set(-2.0)
    h = reg.histogram("a.lat", LATENCY_BUCKETS_S)
    for v in (0.001, 0.01, 0.01, 0.5, 3.0):
        h.observe(v)
    blob = json.dumps(reg.to_dict())  # artifact form: JSON-serializable
    back = MetricsRegistry.from_dict(json.loads(blob))
    assert back.to_dict() == reg.to_dict()
    assert back.counters["a.count"].value == 7
    assert back.gauges["a.gauge"].value == -2.0
    assert back.gauges["a.gauge"].vmax == 1.5
    h2 = back.histograms["a.lat"]
    assert h2.count == 5 and h2.vmin == 0.001 and h2.vmax == 3.0
    assert h2.quantile(0.5) == pytest.approx(h.quantile(0.5))
    # version drift is an error, not a silent misread
    stale = json.loads(blob)
    stale["version"] = 999
    with pytest.raises(ValueError):
        MetricsRegistry.from_dict(stale)


def test_histogram_merge_is_associative_and_exactish():
    import random

    rng = random.Random(7)
    hs = []
    for i in range(3):
        h = Histogram(f"h{i}", LATENCY_BUCKETS_S)
        for _ in range(200):
            h.observe(rng.lognormvariate(-4, 1.5))
        hs.append(h)
    a, b, c = hs
    left = Histogram.merged(Histogram.merged(a, b), c)
    right = Histogram.merged(a, Histogram.merged(b, c))
    # bucket state is exactly associative; the float `sum` is only
    # associative up to rounding
    assert left.counts == right.counts
    assert (left.count, left.vmin, left.vmax) == (
        right.count, right.vmin, right.vmax
    )
    assert left.total == pytest.approx(right.total)
    assert left.count == 600
    assert left.total == pytest.approx(a.total + b.total + c.total)
    # merged quantiles stay within one bucket of each input's range
    assert left.vmin == min(h.vmin for h in hs)
    assert left.vmax == max(h.vmax for h in hs)


def test_histogram_quantiles_match_exact_within_bucket_resolution():
    import random

    rng = random.Random(3)
    xs = [rng.lognormvariate(-5, 1.0) for _ in range(5000)]
    h = Histogram("lat", LATENCY_BUCKETS_S)
    for x in xs:
        h.observe(x)
    xs.sort()
    factor = 2.0 ** (1.0 / 4.0)  # one LATENCY bucket's width
    for p in (0.5, 0.95, 0.99, 0.999):
        exact = xs[int(p * (len(xs) - 1))]
        got = h.quantile(p)
        assert exact / factor <= got <= exact * factor, (p, exact, got)


def test_drain_quantile_parity_and_bounded_stats(tmp_path):
    """THE satellite contract: the histogram-backed ServeStats
    reproduces the quantiles the raw lists used to give, keyed off the
    same compile/barrier flags, while holding O(buckets) state."""
    sessions, pool, streams, sched = _fleet(tmp_path, n=8)
    sched.stats.keep_raw = True  # test-only raw mirror
    stats = sched.run()
    assert sched.done
    raw = stats.raw_round_latencies
    assert len(raw) == stats.rounds > 0
    # classification parity: one source of truth for both paths
    skip = [c or b for c, b in zip(stats.raw_compile_flags,
                                   stats.raw_barrier_flags)]
    exact, _, skipped_n = steady_quantiles(raw, skip)
    assert skipped_n == stats.compile_rounds + stats.barrier_rounds
    assert stats.lat_steady.count == stats.rounds - skipped_n
    got = stats.latency_quantiles()
    # parity within bucket resolution: the histogram quantile must lie
    # between the two order statistics the exact quantile interpolates
    # (the list value itself can sit anywhere in that gap), widened by
    # one bucket's ratio
    import math

    kept = sorted(
        lat for lat, s in zip(raw, skip) if not s
    ) or sorted(raw)
    factor = 2.0 ** (1.0 / 4.0)
    for key, p in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
        rank = p * (len(kept) - 1)
        lo = kept[math.floor(rank)] / factor
        hi = kept[math.ceil(rank)] * factor
        assert lo <= got[key] <= hi, (key, exact[key], got[key], lo, hi)
        # and the exact interpolated value obeys the same bracket
        assert lo <= exact[key] <= hi
    # compile time parity with the raw flags
    assert stats.compile_time == pytest.approx(sum(
        lat for lat, c in zip(raw, stats.raw_compile_flags) if c
    ))
    # the memory contract: histograms, not per-round lists
    assert len(stats.lat_steady.counts) == len(LATENCY_BUCKETS_S) + 1
    assert stats.occupancy.count == stats.rounds
    assert stats.queue_depth.count == stats.rounds
    # registry carries pool counters (identity-preserved via attach)
    m = stats.metrics.to_dict()
    assert m["version"] == 1
    assert m["counters"]["serve.pool.evictions"] == stats.evictions > 0
    # a clean unbounded drain ends every doc with cause tag `ok`
    assert stats.doc_latency["ok"].count == len(sessions)
    assert all(
        stats.doc_latency[t].count == 0
        for t in DOC_CAUSE_TAGS if t != "ok"
    )


def test_doc_drain_latency_cause_tags(tmp_path):
    """Cause-tag attribution: a clean doc lands in `ok`, an
    overflow-shed doc in `shed`, a poisoned-rebuild doc in
    `quarantined` — each doc counted exactly once."""
    plan = FaultPlan(
        [
            FaultEvent(kind="queue_overflow", round=3),
            FaultEvent(kind="spool_corrupt", round=2),
            FaultEvent(kind="poison_rebuild", round=0),
        ],
        seed=3,
    )
    sessions, pool, streams, sched = _fleet(
        tmp_path, n=6, faults=FaultInjector(plan),
        queue_cap=16, overflow_policy="shed",
    )
    stats = sched.run()
    assert sched.done
    assert stats.quarantines, "poisoned rebuild should quarantine"
    assert stats.shed_ops > 0
    by_tag = {tag: h.count for tag, h in stats.doc_latency.items()}
    assert set(by_tag) == set(DOC_CAUSE_TAGS)
    assert by_tag["quarantined"] == len(stats.quarantines)
    assert by_tag["shed"] >= 1
    # the bounded queue backpressures every long stream, so surviving
    # docs attribute to `deferred`/`ok` — both are non-lossy outcomes
    assert by_tag["deferred"] + by_tag["ok"] >= 1
    # exactly-once: every doc that was ever admitted has one sample
    assert sum(by_tag.values()) == len(sessions)
    # artifact form: the tagged histograms ride in the registry
    m = stats.metrics.to_dict()
    assert m["histograms"]["serve.doc.drain_latency.quarantined"][
        "count"
    ] == by_tag["quarantined"]


# ---------------------------------------------------------------------------
# profiler: top-ops parsing
# ---------------------------------------------------------------------------


def test_profiler_top_ops_filters_python_frames(tmp_path):
    import gzip

    from crdt_benches_tpu.obs.profiler import top_ops

    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    events = {"traceEvents": [
        {"ph": "X", "name": "fusion.123", "ts": 0, "dur": 5000},
        {"ph": "X", "name": "fusion.123", "ts": 9000, "dur": 3000},
        {"ph": "X", "name": "convert.7", "ts": 5000, "dur": 2000},
        # host python frames must not pollute the op table
        {"ph": "X", "name": "$scheduler.py:1231 run_round", "ts": 0,
         "dur": 9e9},
        {"ph": "M", "name": "process_name"},
    ]}
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump(events, f)
    ops = top_ops(str(tmp_path))
    assert [o["name"] for o in ops] == ["fusion.123", "convert.7"]
    assert ops[0]["calls"] == 2
    assert ops[0]["total_ms"] == pytest.approx(8.0)


# ---------------------------------------------------------------------------
# bench_compare: the regression gate
# ---------------------------------------------------------------------------


def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", REPO / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare"] = mod  # dataclasses need a real home
    spec.loader.exec_module(mod)
    return mod


def _artifact(tmp_path, name, *, pps=100_000.0, p99=0.005,
              jbytes=50_000, syncs=40, rounds=20):
    data = [{
        "group": "serve", "trace": "mixed", "backend": "512",
        "extra": {
            "family": "serve",
            "patches_per_sec": pps,
            "batch_latency": {"p50": p99 / 3, "p95": p99 / 1.2,
                              "p99": p99},
            "rounds": rounds,
            "range_ops": 10_000,
            "journal": {"bytes": jbytes, "records": rounds},
            "boundary_syncs": {"entries": {"DocPool.block": syncs}},
        },
    }]
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_bench_compare_passes_identical_and_fails_regressions(tmp_path, capsys):
    bc = _bench_compare()
    base = _artifact(tmp_path, "base.json")
    same = _artifact(tmp_path, "same.json")
    assert bc.main([same, base]) == 0

    # the synthetic regression fixture: throughput -20%, p99 2x,
    # journal bytes +60%, sync rate 3x — every check trips
    bad = _artifact(tmp_path, "bad.json", pps=80_000.0, p99=0.010,
                    jbytes=80_000, syncs=120)
    assert bc.main([bad, base]) == 1
    out = capsys.readouterr().out
    assert out.count("FAIL") == 4

    # an IMPROVEMENT never fails the gate
    good = _artifact(tmp_path, "good.json", pps=150_000.0, p99=0.003)
    assert bc.main([good, base]) == 0

    # thresholds are honored (a 5% drop passes the default 10% gate,
    # fails a 2% one — the smoke's tracing-overhead leg)
    slight = _artifact(tmp_path, "slight.json", pps=95_000.0)
    assert bc.main([slight, base]) == 0
    assert bc.main([slight, base, "--max-throughput-regress", "2"]) == 1


def test_bench_compare_skips_missing_blocks(tmp_path):
    bc = _bench_compare()
    base = _artifact(tmp_path, "base.json")
    nojournal = json.loads(Path(base).read_text())
    nojournal[0]["extra"]["journal"] = None
    del nojournal[0]["extra"]["boundary_syncs"]
    p = tmp_path / "nojournal.json"
    p.write_text(json.dumps(nojournal))
    # skipped checks are reported, not failed
    assert bc.main([str(p), base]) == 0
    # a non-serve artifact is a usage error (exit 2)
    bogus = tmp_path / "bogus.json"
    bogus.write_text(json.dumps([{"group": "upstream", "extra": {}}]))
    assert bc.main([str(bogus), base]) == 2


def test_bench_compare_tolerates_replication_blocks(tmp_path, capsys):
    """A serve/replicate/ artifact (family serve-repl, with the
    replication + convergence blocks) diffed against a pre-replication
    baseline must report the one-sided blocks as skip-with-note and
    NEVER exit 2 — a new baseline is not required to start
    replicating."""
    bc = _bench_compare()
    base = _artifact(tmp_path, "base.json")
    repl = json.loads(Path(base).read_text())
    repl[0]["extra"]["family"] = "serve-repl"
    repl[0]["extra"]["replication"] = {
        "version": 1, "writers": 4, "merged_ops": 123,
        "broadcast_bytes": 4096,
    }
    repl[0]["extra"]["convergence"] = {"converged": True, "ra_ok": True}
    p = tmp_path / "repl.json"
    p.write_text(json.dumps(repl))
    assert bc.main([str(p), base]) == 0
    out = capsys.readouterr().out
    assert "replication block" in out and "SKIP" in out
    # and symmetric: plain new run vs a replicated baseline
    assert bc.main([base, str(p)]) == 0
    # graftlint v4: the fs_ops durable-protocol block rides the same
    # one-sided matrix — present on either side alone is a skip with a
    # note in BOTH directions, never exit 2
    fsops = json.loads(Path(base).read_text())
    fsops[0]["extra"]["fs_ops"] = {
        "version": 1, "sanitized": True, "journal": True,
        "spool": True, "flight": False,
        "protocols": {"wal": 9, "gc": 2, "snapshot": 3, "spool": 12},
        "ops": {"wal": {"replace": 3}}, "unattributed": {},
    }
    q = tmp_path / "fsops.json"
    q.write_text(json.dumps(fsops))
    assert bc.main([str(q), base]) == 0
    out = capsys.readouterr().out
    assert "fs_ops block" in out and "SKIP" in out
    assert bc.main([base, str(q)]) == 0
    out = capsys.readouterr().out
    assert "fs_ops block" in out and "SKIP" in out
