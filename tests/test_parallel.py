"""Mesh/shard_map layer: sharded replay over the virtual 8-device CPU mesh,
psum/pmin/pmax convergence, and the driver entry points."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from crdt_benches_tpu.parallel.mesh import (
    make_sharded_state,
    replica_mesh,
    sharded_replay_and_digest,
)
from crdt_benches_tpu.traces.tensorize import tensorize
from crdt_benches_tpu.utils.digest import doc_digest
from crdt_benches_tpu.engine.replay import ReplayEngine

needs_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


@needs_8
@pytest.mark.slow
def test_sharded_replay_converges(svelte_trace):
    """16 replicas over 8 devices replay sveltecomponent's first batches;
    digests agree across devices and match the single-replica engine."""
    tt = tensorize(svelte_trace, batch=256)
    nb = 32  # first 32 batches only (test speed)
    kind_b, pos_b, _, slot_b = tt.batched()
    kind_b, pos_b, slot_b = kind_b[:nb], pos_b[:nb], slot_b[:nb]

    capacity = ((tt.capacity + 127) // 128) * 128
    chars = np.zeros(capacity, np.int32)
    ins = tt.slot >= 0
    chars[tt.slot[ins]] = tt.ch[ins]

    mesh = replica_mesh(8)
    step, _ = sharded_replay_and_digest(mesh)
    state = make_sharded_state(mesh, 16, capacity, 0)
    state, digests, converged = step(
        state, jnp.asarray(kind_b), jnp.asarray(pos_b), jnp.asarray(slot_b),
        jnp.asarray(chars),
    )
    jax.block_until_ready(state)
    assert bool(np.asarray(converged))
    digests = np.asarray(digests)
    assert (digests == digests[0]).all()

    # cross-check against the unsharded single-replica engine
    eng = ReplayEngine(tt, n_replicas=1)
    st1 = eng.fresh_state()
    from crdt_benches_tpu.engine.replay import replay_batches

    st1 = replay_batches(st1, jnp.asarray(kind_b), jnp.asarray(pos_b),
                         jnp.asarray(slot_b))
    ref = np.asarray(doc_digest(st1.order, st1.visible, st1.length, eng.chars))
    assert (digests[0] == ref).all()


@needs_8
@pytest.mark.slow
def test_sharded_divergence_detected():
    """A tampered replica (one visibility bit flipped after replay) must
    break the cross-device convergence verdict."""
    import __graft_entry__ as g

    tt = g._tiny_problem()
    kind_b, pos_b, _, slot_b = tt.batched()
    capacity = 128
    chars = np.zeros(capacity, np.int32)
    ins = tt.slot >= 0
    chars[tt.slot[ins]] = tt.ch[ins]

    mesh = replica_mesh(8)
    step, _ = sharded_replay_and_digest(mesh)
    state = make_sharded_state(mesh, 8, capacity, 0)
    args = (jnp.asarray(kind_b), jnp.asarray(pos_b), jnp.asarray(slot_b),
            jnp.asarray(chars))
    state, _, converged = step(state, *args)
    assert bool(np.asarray(converged))

    # tombstone one live char on replica 0 only, then a PAD-only step
    live_slot = int(tt.slot[ins][0])
    tampered = state._replace(
        visible=state.visible.at[0, live_slot].set(False),
        nvis=state.nvis.at[0].add(-1),
    )
    pad = jnp.zeros((1, tt.batch), jnp.int32)
    _, _, converged2 = step(tampered, pad, pad, pad - 1, jnp.asarray(chars))
    assert not bool(np.asarray(converged2))


@pytest.mark.slow
def test_entry_and_dryrun():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert len(out) == 5
    if jax.device_count() >= 8:
        g.dryrun_multichip(8)


def test_harness_stats_and_baseline(tmp_path):
    from crdt_benches_tpu.bench.harness import (
        BenchResult, compare_to_baseline, markdown_table, measure, save_results,
    )

    calls = []
    times = measure(lambda: calls.append(1), warmup=2, samples=3)
    assert len(times) == 3 and len(calls) == 5

    r = BenchResult("upstream", "t", "b", elements=1000,
                    samples=[0.2, 0.1, 0.3])
    assert r.median == 0.2
    assert r.elements_per_sec == 1000 / 0.2
    r2 = BenchResult("upstream", "t", "jax-r4", elements=1000,
                     samples=[0.1], replicas=4)
    assert r2.elements_per_sec == 4000 / 0.1

    d = str(tmp_path)
    save_results([r, r2], "base", results_dir=d)
    lines = compare_to_baseline(
        [BenchResult("upstream", "t", "b", 1000, [0.1])], "base", results_dir=d
    )
    assert any("-50.0%" in ln for ln in lines)
    table = markdown_table([r, r2])
    assert "upstream" in table and "jax-r4" in table
