"""jit-boundary contract runtime enforcement (lint/boundary.py).

Covers the satellite's three claims: a wrong-dtype call and an
aliased-donation call are caught under CRDT_BENCH_CHECK_BOUNDARIES=1;
with the flag unset the decorator is a NO-OP wrapper (the identical
function object — asserted directly and via a timing smoke)."""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path
from typing import NamedTuple

import numpy as np
import pytest

from crdt_benches_tpu.lint.boundary import (
    REGISTRY,
    BoundaryError,
    boundary,
    boundary_table,
)

REPO = Path(__file__).resolve().parent.parent
_ENV = "CRDT_BENCH_CHECK_BOUNDARIES"


# ---- enforcement under the env flag ---------------------------------------

def test_wrong_dtype_caught_under_env(monkeypatch):
    monkeypatch.setenv(_ENV, "1")

    @boundary(dtypes=("int32", "int32"))
    def f(kind, pos):
        return kind

    f(np.zeros(4, np.int32), np.zeros(4, np.int32))
    with pytest.raises(BoundaryError, match="dtype"):
        f(np.zeros(4, np.float32), np.zeros(4, np.int32))


def test_aliased_donation_caught_under_env(monkeypatch):
    monkeypatch.setenv(_ENV, "1")

    @boundary(donates=(0,))
    def g(state, ops):
        return state

    x = np.zeros(8, np.int32)
    g(x, x.copy())  # distinct buffers: fine
    with pytest.raises(BoundaryError, match="alias"):
        g(x, x)  # the donated buffer IS the other argument


def test_keyword_args_bound_to_contract_positions(monkeypatch):
    """`f(state, kind=k)` is checked exactly like `f(state, k)` —
    keyword call sites must not bypass enforcement."""
    monkeypatch.setenv(_ENV, "1")

    @boundary(dtypes=("int32", "int32"), donates=(0,))
    def f(state, kind):
        return state

    s = np.zeros(4, np.int32)
    f(s, kind=np.zeros(4, np.int32))
    with pytest.raises(BoundaryError, match="dtype"):
        f(s, kind=np.zeros(4, np.float32))
    with pytest.raises(BoundaryError, match="alias"):
        f(s, kind=s)


def test_pytree_state_leaves_checked(monkeypatch):
    monkeypatch.setenv(_ENV, "1")

    class State(NamedTuple):
        doc: np.ndarray
        length: np.ndarray

    @boundary(dtypes=("int32",), donates=(0,))
    def step(state):
        return state

    ok = State(np.zeros((2, 8), np.int32), np.zeros(2, np.int32))
    step(ok)
    bad = State(np.zeros((2, 8), np.int32), np.zeros(2, np.float64))
    with pytest.raises(BoundaryError, match="dtype"):
        step(bad)
    # aliased pytree leaf inside another arg
    @boundary(donates=(0,))
    def step2(state, extra):
        return state

    with pytest.raises(BoundaryError, match="alias"):
        step2(ok, ok.doc)


def test_shape_symbols_bind_across_args():
    @boundary(shapes=("R B", "R"), check=True)
    def h(ops, v0):
        return ops

    h(np.zeros((3, 4), np.int32), np.zeros(3, np.int32))
    with pytest.raises(BoundaryError, match="contradicts"):
        h(np.zeros((3, 4), np.int32), np.zeros(5, np.int32))
    with pytest.raises(BoundaryError, match="rank"):
        h(np.zeros(3, np.int32), np.zeros(3, np.int32))


# ---- zero overhead when unset ---------------------------------------------

def test_identity_when_unset(monkeypatch):
    monkeypatch.delenv(_ENV, raising=False)

    def raw(x):
        return x

    decorated = boundary(dtypes=("int32",), donates=(0,))(raw)
    assert decorated is raw  # literally no wrapper
    assert decorated.__boundary__.donates == (0,)


def test_noop_timing_smoke(monkeypatch):
    """The production path must not grow a per-call wrapper: with the
    flag unset, calling the decorated function costs the same as the
    raw one (identity makes this exact; the timing bound is a tripwire
    should the identity shortcut ever be lost)."""
    monkeypatch.delenv(_ENV, raising=False)

    def raw(x):
        return x + 1

    decorated = boundary(dtypes=(None,))(raw)
    N = 50_000
    t0 = time.perf_counter()
    for _ in range(N):
        raw(1)
    t_raw = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(N):
        decorated(1)
    t_dec = time.perf_counter() - t0
    assert t_dec < max(2.5 * t_raw, t_raw + 0.05), (t_raw, t_dec)


# ---- the registry ----------------------------------------------------------

def test_registry_covers_the_public_entry_points():
    import crdt_benches_tpu.engine.downstream  # noqa: F401
    import crdt_benches_tpu.engine.downstream_range  # noqa: F401
    import crdt_benches_tpu.engine.merge  # noqa: F401
    import crdt_benches_tpu.engine.merge_fleet  # noqa: F401
    import crdt_benches_tpu.engine.merge_range  # noqa: F401
    import crdt_benches_tpu.engine.replay  # noqa: F401
    import crdt_benches_tpu.engine.replay_range  # noqa: F401
    import crdt_benches_tpu.serve.pool  # noqa: F401

    expected = {
        "crdt_benches_tpu.serve.pool.fleet_step",
        "crdt_benches_tpu.serve.pool.DocPool.macro_step",
        "crdt_benches_tpu.engine.merge_fleet.merge_rows_round",
        "crdt_benches_tpu.engine.merge_fleet.merge_rows_macro",
        "crdt_benches_tpu.ops.apply2.apply_batch3",
        "crdt_benches_tpu.ops.apply_range.apply_range_batch",
        "crdt_benches_tpu.ops.resolve.resolve_batch",
        "crdt_benches_tpu.ops.resolve_range_scan.resolve_ranges_rows",
        "crdt_benches_tpu.engine.replay.replay_batches",
        "crdt_benches_tpu.engine.replay_range.replay_ranges",
        "crdt_benches_tpu.engine.merge.merge_oplogs_packed",
        "crdt_benches_tpu.engine.merge_range.merge_runlogs",
        "crdt_benches_tpu.engine.downstream.apply_updates5",
        "crdt_benches_tpu.engine.downstream_range.apply_range_updates5",
    }
    assert expected <= set(REGISTRY)
    table = boundary_table()
    assert table["crdt_benches_tpu.serve.pool.fleet_step"]["donates"] == [0]


# ---- end to end: a REAL entry point under the env flag ---------------------

def test_real_entry_enforced_in_subprocess():
    """fleet_step is decorated at import time, so flipping the env var
    needs a fresh interpreter: call it with an aliased donated buffer
    and with a wrong dtype; both must raise BoundaryError."""
    code = """
import numpy as np
from crdt_benches_tpu.lint.boundary import BoundaryError
from crdt_benches_tpu.ops.apply2 import init_state3
from crdt_benches_tpu.serve.pool import fleet_step

state = init_state3(2, 128, n_init=1)
k = np.zeros((2, 4), np.int32)
try:
    fleet_step(state, k.astype(np.float32), k, k)
    raise SystemExit("wrong dtype NOT caught")
except BoundaryError:
    pass
try:
    fleet_step(state, state.doc, state.doc, state.doc)
    raise SystemExit("aliased donation NOT caught")
except BoundaryError:
    pass
print("ENFORCED_OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env[_ENV] = "1"
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ENFORCED_OK" in proc.stdout
