"""serve/ingest/ live front door: wire framing, per-tenant admission,
deadline scheduling, and the open-loop drive path.

The admission decision matrix is pinned directly against
AdmissionController (no sockets); the wire protocol is pinned against a
LIVE IngestFront over loopback; recovery parity reuses the durability
suite's recover_fleet pattern — an admission shed journaled by the
ingest path must replay exactly like an overflow shed.
"""

import importlib.util
import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

from crdt_benches_tpu.serve.ingest.admission import (
    AdmissionController,
    TenantPolicy,
    TenantSpecError,
    parse_tenant_spec,
)
from crdt_benches_tpu.serve.ingest.front import (
    IngestFront,
    decode_frame,
    encode_frame,
)
from crdt_benches_tpu.serve.ingest.loadgen import parse_open_spec
from crdt_benches_tpu.serve.journal import OpJournal, read_journal, recover_fleet
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import prepare_streams
from crdt_benches_tpu.serve.workload import build_fleet

REPO = Path(__file__).resolve().parent.parent

TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------


def test_frame_codec_roundtrip_and_rejects():
    obj = {"t": "ops", "seq": 3, "start": 0, "count": 8, "round": 2}
    assert decode_frame(encode_frame(obj)) == obj
    # CRC mismatch: flip a payload byte behind a valid header
    line = bytearray(encode_frame(obj))
    line[-3] ^= 0x01
    with pytest.raises(ValueError, match="crc mismatch"):
        decode_frame(bytes(line))
    with pytest.raises(ValueError, match="short frame"):
        decode_frame(b"deadbeef\n")
    with pytest.raises(ValueError, match="bad crc"):
        decode_frame(b"nothexx! {}\n")
    # valid CRC over non-object / t-less JSON still rejects
    import zlib

    for body in (b"[1,2]", b'{"x":1}'):
        framed = f"{zlib.crc32(body):08x} ".encode() + body + b"\n"
        with pytest.raises(ValueError, match="not an object"):
            decode_frame(framed)


# ---------------------------------------------------------------------------
# spec parsers
# ---------------------------------------------------------------------------


def test_parse_open_spec():
    assert parse_open_spec("32") == (32.0, "poisson")
    assert parse_open_spec("64:burst") == (64.0, "burst")
    assert parse_open_spec("12.5:poisson") == (12.5, "poisson")
    for bad in ("", "0", "-4", "32:steady", "x", "32:poisson:extra"):
        with pytest.raises(ValueError):
            parse_open_spec(bad)


def test_parse_tenant_spec_matrix():
    pol = parse_tenant_spec("gold=48:192,free=8:16:64")
    assert set(pol) == {"gold", "free"}
    assert pol["gold"].rate == 48.0 and pol["gold"].burst == 192.0
    assert pol["gold"].budget == 0  # unset -> unlimited queue
    assert pol["free"].budget == 64
    # burst defaults to 4x rate when omitted
    assert parse_tenant_spec("t=10")["t"].burst == 40.0
    for bad in ("", "=4", "t=", "t=0", "t=-3", "t=4:x", "t=4:8:2:9",
                "a=4,a=8"):
        with pytest.raises(TenantSpecError):
            parse_tenant_spec(bad)
    with pytest.raises(TenantSpecError):
        TenantPolicy("t", rate=4.0, burst=-1.0)


# ---------------------------------------------------------------------------
# admission decision matrix
# ---------------------------------------------------------------------------


class _FakeSlo:
    """status_fields() stand-in: inject exact per-class burn rates."""

    def __init__(self, classes):
        self._classes = classes

    def status_fields(self):
        return {"classes": self._classes}


def _controller(spec="gold=16:32,free=4:8:24", *, burns=None):
    adm = AdmissionController(
        parse_tenant_spec(spec),
        slo=_FakeSlo(burns or {}) if burns is not None else None,
    )
    adm.refill()
    return adm


def test_admission_burn_matrix():
    """SLO burn gates the verdict before any token math: a sustained
    burn (fast AND slow > 1) sheds, a spike (fast only) defers."""
    adm = _controller(burns={
        "c128": {"burn_fast": 2.0, "burn_slow": 1.5},
        "c512": {"burn_fast": 1.8, "burn_slow": 0.4},
        "c4096": {"burn_fast": 0.2, "burn_slow": 0.1},
    })
    assert adm.decide("gold", 8, "c128", pending=0) == (
        "shed", "burn_sustained")
    assert adm.decide("gold", 8, "c512", pending=0) == (
        "defer", "burn_spike")
    assert adm.decide("gold", 8, "c4096", pending=0) == ("admit", "ok")
    # unknown class: no burn signal, normal admission
    assert adm.decide("gold", 8, "nope", pending=0) == ("admit", "ok")
    assert adm.decisions["shed:burn_sustained"] == 1
    assert adm.decisions["defer:burn_spike"] == 1
    assert adm.decisions["admit:ok"] == 2


def test_admission_defer_limit_sheds():
    """A batch pushed back MAX_DEFERS rounds sheds even with a clean
    SLO — the starvation backstop."""
    adm = _controller()
    assert adm.decide("gold", 8, "c128", pending=0,
                      defers=AdmissionController.MAX_DEFERS) == (
        "shed", "defer_limit")
    # one short of the limit with empty tokens: still only a defer
    adm.tokens["gold"] = 0.0
    assert adm.decide("gold", 8, "c128", pending=0,
                      defers=AdmissionController.MAX_DEFERS - 1) == (
        "defer", "tokens")


def test_admission_queue_budget_and_tokens():
    adm = _controller()
    # free: budget=24 — pending + batch over budget defers regardless
    # of token balance
    assert adm.decide("free", 8, "c128", pending=20) == (
        "defer", "queue_budget")
    # token exhaustion: burst 8 admits one 8-op batch, defers the next
    assert adm.decide("free", 8, "c128", pending=0) == ("admit", "ok")
    assert adm.decide("free", 8, "c128", pending=0) == ("defer", "tokens")
    # refill restores rate (4/round, capped at burst) -> one more round
    # is still short, two refills cover the batch
    adm.refill()
    assert adm.decide("free", 8, "c128", pending=0) == ("defer", "tokens")
    adm.refill()
    assert adm.decide("free", 8, "c128", pending=0) == ("admit", "ok")
    assert adm.admitted_ops["free"] == 16
    assert adm.deferred_ops["free"] == 24


def test_admission_tenant_isolation():
    """One tenant draining its bucket never touches a neighbour's."""
    adm = _controller()
    for _ in range(4):
        adm.decide("free", 8, "c128", pending=0)
    assert adm.tokens["gold"] == 32.0  # untouched
    assert adm.decide("gold", 24, "c128", pending=0) == ("admit", "ok")
    assert adm.shed_ops["gold"] == 0 and adm.shed_ops["free"] == 0
    with pytest.raises(KeyError, match="unknown tenant"):
        adm.decide("mystery", 1, "c128", pending=0)
    fields = adm.status_fields()
    assert set(fields["tenants"]) == {"gold", "free"}
    assert fields["tenants"]["gold"]["admitted_ops"] == 24


def test_admission_shed_recovery_parity(tmp_path):
    """An admission shed journaled by the ingest path replays through
    recover_fleet exactly like an overflow shed: the doc comes back
    lossy with its cursor limit clamped, and the report carries the
    shed ops — zero ingest-specific replay code."""
    sessions = build_fleet(6, mix=TINY_MIX, seed=7, arrival_span=2,
                           bands=TINY_BANDS)
    jd = str(tmp_path / "journal")
    journal = OpJournal(jd)
    adm = AdmissionController(parse_tenant_spec("free=4:8"),
                              journal=journal)
    pool = DocPool(classes=(128, 512), slots=(6, 3),
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(sessions, pool, batch=16)
    doc = max(streams, key=lambda d: streams[d].n_total)
    total = streams[doc].n_total
    assert total > 5
    adm.journal_shed(doc, keep=5, shed=total - 5, tenant="free", rnd=2)
    journal.close()
    records, dropped = read_journal(jd)
    assert dropped == 0
    (rec,) = records
    assert rec == {"t": "shed", "r": 2, "doc": doc, "at": 5,
                   "ops": total - 5, "tenant": "free",
                   "why": "admission"}
    # fresh pool + streams, same deterministic workload
    pool_b = DocPool(classes=(128, 512), slots=(6, 3),
                     spool_dir=str(tmp_path / "spool_b"))
    streams_b = prepare_streams(sessions, pool_b, batch=16)
    rep = recover_fleet(pool_b, streams_b, jd)
    st = streams_b[doc]
    assert st.lossy and st.limit == 5
    assert rep.shed_ops == total - 5
    assert rep.records == 1
    # no round barriers were journaled: recovery is a cold start, the
    # shed decision still applies from round 0
    assert rep.snapshot_round == -1 and rep.resume_round == 0


# ---------------------------------------------------------------------------
# deadline scheduler
# ---------------------------------------------------------------------------


def test_deadline_budgets_and_scoring(tmp_path):
    from crdt_benches_tpu.serve.ingest.deadline import DeadlineScheduler

    sessions = build_fleet(12, mix=TINY_MIX, seed=5, arrival_span=3,
                           bands=TINY_BANDS)
    pool = DocPool(classes=(128, 512), slots=(6, 3),
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(sessions, pool, batch=16)
    sched = DeadlineScheduler(pool, streams, batch=16, edf=True,
                              deadline_budgets={128: 5, 512: 9},
                              default_budget=7)
    # the per-class budget resolves through the doc's capacity class
    for doc, st in streams.items():
        cls = pool.class_for(max(pool.docs[doc].length, 1))
        want = {128: 5, 512: 9}[cls]
        assert sched.deadline_for(doc) == st.arrival + want
    sched.run()
    assert sched.done
    fields = sched.deadline_fields()
    assert fields["edf"] is True
    assert fields["met"] + fields["missed"] == len(streams)
    assert 0.0 <= fields["hit_rate"] <= 1.0
    assert fields["budgets"] == {"128": 5, "512": 9}
    # the block rides the status surface (the sidecar's scrape)
    assert sched.status_fields()["deadline"]["met"] == fields["met"]


# ---------------------------------------------------------------------------
# the live front over loopback
# ---------------------------------------------------------------------------


def _connect(port):
    sk = socket.create_connection(("127.0.0.1", port), timeout=5.0)
    return sk, sk.makefile("rwb")


def _xchg(f, obj):
    f.write(encode_frame(obj))
    f.flush()
    return json.loads(f.readline())


def test_live_front_session_protocol():
    front = IngestFront({7}, ("gold",), pace_slack=2)
    port = front.start()
    try:
        # rejected hellos: unknown doc, unknown tenant
        for hello, why in (
            ({"t": "hello", "session": "s0", "doc": 9, "tenant": "gold"},
             "unknown doc"),
            ({"t": "hello", "session": "s0", "doc": 7, "tenant": "x"},
             "unknown tenant"),
        ):
            sk, f = _connect(port)
            r = _xchg(f, hello)
            assert r["t"] == "err" and why in r["why"]
            sk.close()
        # ops before hello is a protocol error
        sk, f = _connect(port)
        r = _xchg(f, {"t": "ops", "seq": 0, "count": 4})
        assert r["t"] == "err" and "before hello" in r["why"]
        sk.close()
        # the happy path: hello -> paced ops -> bye
        sk, f = _connect(port)
        r = _xchg(f, {"t": "hello", "session": "s1", "doc": 7,
                      "tenant": "gold"})
        assert r == {"t": "ack", "seq": -1}
        # a frame planned past now + pace_slack is retried, not acked:
        # the wire enforces the open-loop arrival process
        r = _xchg(f, {"t": "ops", "seq": 0, "start": 0, "count": 4,
                      "round": 9})
        assert r == {"t": "retry", "seq": 0}
        front.now = 7  # the pump's per-round clock publish
        r = _xchg(f, {"t": "ops", "seq": 0, "start": 0, "count": 4,
                      "round": 9})
        assert r == {"t": "ack", "seq": 0}
        # seq regression closes the session
        r = _xchg(f, {"t": "ops", "seq": 0, "start": 4, "count": 4,
                      "round": 9})
        assert r["t"] == "err" and "seq" in r["why"]
        sk.close()
        # clean close on a fresh session
        sk, f = _connect(port)
        _xchg(f, {"t": "hello", "session": "s2", "doc": 7,
                  "tenant": "gold"})
        r = _xchg(f, {"t": "bye"})
        assert r["t"] == "ack"
        sk.close()
        # corrupt frame surfaces as bad_frame
        sk, f = _connect(port)
        f.write(b"00000000 {broken\n")
        f.flush()
        r = json.loads(f.readline())
        assert r["t"] == "err"
        sk.close()
        # drain() tallies on the hot side; handlers never touch counters
        payloads = front.drain()
        kinds = [p["kind"] for p in payloads]
        assert kinds.count("hello") == 2
        assert kinds.count("ops") == 1
        assert kinds.count("bye") == 1
        assert kinds.count("bad_frame") >= 1
        assert front.sessions_opened == 2
        assert front.sessions_closed == 1
        assert front.ops_delivered == 4
        assert front.bad_frames >= 1
        fields = front.status_fields()
        assert fields["port"] == port and fields["queue_depth"] == 0
    finally:
        front.stop()


def test_live_front_churn_drops_connection():
    front = IngestFront({3}, ("default",))
    port = front.start()
    try:
        sk, f = _connect(port)
        _xchg(f, {"t": "hello", "session": "s0", "doc": 3,
                  "tenant": "default"})
        front.now = 10
        front.churn()  # the conn_churn fault hook
        r = _xchg(f, {"t": "ops", "seq": 0, "count": 2, "round": 0})
        assert r == {"t": "churn"}
        sk.close()
        front.drain()
        assert front.churn_drops == 1
        # resume-hello is counted separately from a fresh open
        sk, f = _connect(port)
        r = _xchg(f, {"t": "hello", "session": "s0", "doc": 3,
                      "tenant": "default", "resume": True})
        assert r["t"] == "ack"
        sk.close()
        front.drain()
        assert front.sessions_resumed == 1
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# runner rejection matrix (exit 2 — rejected before any fleet is built)
# ---------------------------------------------------------------------------


_REJECTS = [
    (["--serve-open", "32", "--serve-longhaul", "1"], "longhaul"),
    (["--serve-open", "32", "--serve-recover"], "recover"),
    (["--serve-open", "32", "--serve-mesh", "3"], "mesh"),
    (["--serve-open", "bogus"], "open"),
    (["--serve-tenants", "gold=8"], "tenants"),
    (["--serve-deadline"], "deadline"),
    (["--serve-open-sweep", "8,16"], "sweep"),
]


@pytest.mark.parametrize("extra,tag", _REJECTS, ids=[t for _, t in _REJECTS])
def test_runner_rejects_open_loop_conflicts(extra, tag, tmp_path):
    """Unsupported --serve-open combinations (and orphaned open-loop
    flags) are usage errors: exit 2 with a message, no artifact."""
    proc = subprocess.run(
        [sys.executable, "-m", "crdt_benches_tpu.bench.runner",
         "--family", "serve", "--serve-docs", "8",
         "--results-dir", str(tmp_path)] + extra,
        capture_output=True, text=True, timeout=120,
        cwd=str(REPO), env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 2, proc.stderr
    assert not list(Path(tmp_path).glob("*.json"))


# ---------------------------------------------------------------------------
# bench_compare: open-loop gating semantics
# ---------------------------------------------------------------------------


def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare_ingest", REPO / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare_ingest"] = mod
    spec.loader.exec_module(mod)
    return mod


def _artifact(tmp_path, name, *, pps=100_000.0, p99=0.005, rate=None,
              knee=False):
    extra = {
        "family": "serve",
        "patches_per_sec": pps,
        "batch_latency": {"p50": p99 / 3, "p95": p99 / 1.2, "p99": p99},
        "rounds": 40,
        "range_ops": 10_000,
        "journal": None,
    }
    if rate is not None:
        extra["ingest"] = {
            "version": 1,
            "open": {"rate": rate, "process": "poisson"},
            "admission": {"tenants": {}},
        }
    if knee:
        extra["knee"] = {"version": 1, "capacity": 120.0, "points": []}
    data = [{"group": "serve", "trace": "mixed", "backend": "512",
             "extra": extra}]
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_bench_compare_open_loop_matrix(tmp_path, capsys):
    bc = _bench_compare()
    closed = _artifact(tmp_path, "closed.json")
    open_a = _artifact(tmp_path, "open_a.json", rate=64.0)
    # open vs closed: throughput is skip-with-note (it follows the
    # offered load), p99 is skip-with-note (no comparable load point),
    # the one-sided ingest block is a note — never exit 2
    assert bc.main([open_a, closed]) == 0
    out = capsys.readouterr().out
    assert "SKIP" in out and "offered load" in out
    assert bc.main([closed, open_a]) == 0
    # same offered load: p99 IS gated — a regression fails
    slow = _artifact(tmp_path, "slow.json", rate=64.0, p99=0.05)
    assert bc.main([slow, open_a]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out and "offered load 64" in out
    # identical open runs pass, and the gate names the load point
    assert bc.main([open_a, open_a]) == 0
    out = capsys.readouterr().out
    assert "offered load 64" in out
    # different offered loads: p99 not comparable, skip with note
    open_b = _artifact(tmp_path, "open_b.json", rate=32.0, p99=0.05)
    assert bc.main([open_b, open_a]) == 0
    out = capsys.readouterr().out
    assert "offered load differs" in out
    # the knee block rides the one-sided matrix both directions
    kneed = _artifact(tmp_path, "kneed.json", rate=64.0, knee=True)
    assert bc.main([kneed, open_a]) == 0
    out = capsys.readouterr().out
    assert "knee block" in out and "SKIP" in out
    assert bc.main([open_a, kneed]) == 0


# ---------------------------------------------------------------------------
# end to end: open-loop drain over the live wire at toy scale
# ---------------------------------------------------------------------------


def test_open_loop_drain_end_to_end(tmp_path):
    """A TINY fleet served through the real TCP front under an open
    Poisson arrival process with tenants + EDF: byte-exact verification,
    every op accounted for across wire -> admission -> scheduler, and
    the artifact carries the full ingest block."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=12, batch=16,
        classes=(128, 512), slots=(8, 4), seed=3, arrival_span=2,
        verify_sample=4, bands=TINY_BANDS,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        open_spec="48", deadline=True,
        tenants_spec="gold=48:192,free=12:24:96",
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    assert r.bench_id == "serve/open/custom/12"
    with open(info["path"]) as f:
        (d,) = json.load(f)
    ing = d["extra"]["ingest"]
    assert ing["open"]["rate"] == 48.0
    assert ing["open"]["process"] == "poisson"
    # conservation: every planned op arrived over the wire and every
    # admitted op was delivered to the scheduler
    assert ing["front"]["ops_delivered"] == ing["open"]["total_ops"]
    assert ing["client"]["errors"] == 0
    assert ing["client"]["sent_frames"] >= ing["open"]["total_frames"]
    adm = ing["admission"]["tenants"]
    assert set(adm) == {"gold", "free"}
    admitted = sum(t["admitted_ops"] for t in adm.values())
    shed = sum(t["shed_ops"] for t in adm.values())
    # >= because a partially admitted batch's refused tail is re-held
    # and re-decided (its ops count again on the later verdict)
    assert admitted + shed >= ing["open"]["total_ops"]
    assert ing["dup_frames"] == 0  # no chaos, no redelivery
    assert ing["deadline"]["met"] + ing["deadline"]["missed"] == 12
    # the ingest surface is armed AND published in the crossings map
    assert d["extra"]["thread_crossings"]["ingest"] is True


def test_dead_listener_exhausts_retry_budget_with_typed_error():
    """The regression the backoff satellite pins: a client pointed at a
    port nobody listens on must NOT spin forever (nor crash with a raw
    socket error) — it burns its capped, jittered retry budget and
    surfaces a typed ``RetryBudgetExceeded`` naming the session, the
    attempt count, and the last transport error."""
    import socket
    import time as _time

    from crdt_benches_tpu.serve.ingest.loadgen import (
        OpenLoadClient,
        OpenLoadPlan,
        RetryBudgetExceeded,
        _SessionLoad,
    )

    # bind-then-close: a port that is guaranteed dead right now
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    plan = OpenLoadPlan(
        [_SessionLoad("s0", 0, "default", [(0, 0, 4)]),
         _SessionLoad("s1", 1, "default", [(0, 0, 4)])],
        rate=8.0, process="poisson", seed=3, total_ops=8, horizon=1,
    )
    client = OpenLoadClient(port, plan, shards=1, connect_timeout=0.2,
                            retry_base=0.0005, retry_cap=0.002,
                            retry_budget=6)
    t0 = _time.monotonic()
    client.start()
    with pytest.raises(RetryBudgetExceeded) as ei:
        client.join(timeout=30.0)
    # the budget bounds wall time: 6 capped 2ms sleeps, not minutes
    assert _time.monotonic() - t0 < 10.0
    err = ei.value
    assert err.session == "s0" and err.doc == 0
    assert err.attempts == 6  # the whole budget, no more
    assert err.last_error  # the transport cause is carried, not eaten
    assert "retry budget exhausted" in str(err)
    # the shard abandoned its remaining sessions instead of burning a
    # fresh budget per session against a front known to be dead
    assert client.sent_frames == 0
