"""Seeded G002: the replicated merge dispatch's sync discipline.

``replicated_round`` is the hot root (the serve/replicate/ macro-round
shape: bus tick -> stage -> one merge dispatch).  The broadcast bus is
HOST bookkeeping — reading a device counter inside the tick
(``.item()``) or snapshotting replica state during staging
(``np.asarray``) is exactly the stray sync that would break the PR 2/
PR 8 fence model when remote-apply joined the scan.  The declared
``_drain_fence`` shows the sanctioned boundary: syncs live behind a
``# graftlint: fence`` function, nowhere else.
"""

import numpy as np


def _bus_tick(bus, nvis):
    head = bus.published
    depth = nvis.sum().item()  # expect: G002
    return head - depth


def _stage_remote(state, lanes):
    view = np.asarray(state.doc)  # expect: G002
    return lanes, view


def _drain_fence(state):  # graftlint: fence
    # the sanctioned boundary: the final fence after the merge dispatch
    state.doc.block_until_ready()


def replicated_round(bus, state, lanes):  # graftlint: hot-path
    lag = _bus_tick(bus, state.nvis)
    staged, view = _stage_remote(state, lanes)
    _drain_fence(state)
    return lag, staged, view
