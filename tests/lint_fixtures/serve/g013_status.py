"""G013 seed: status/telemetry isolation in hot-path scopes.

``hot_round`` is the declared hot root; ``_publish`` and
``_lazy_series`` are reached from it.  Constructing or serving an HTTP
server / raw socket there, and mutating the registry's shape
(get-or-create, attach), are the violations; swapping a snapshot in
through a pre-registered reference is the sanctioned pattern.
``driver_setup`` shows the same calls are LEGAL off the hot call graph
— server lifecycle and series registration belong to the bench driver.
"""

import socket
from http.server import ThreadingHTTPServer

from crdt_benches_tpu.obs.metrics import MetricsRegistry
from crdt_benches_tpu.obs.status import StatusServer

REG = MetricsRegistry()
ROUNDS = REG.counter("fixture.rounds")  # pre-registered at bind: clean


def hot_round(snapshot):  # graftlint: hot-path
    ROUNDS.inc()  # held reference: clean
    _publish(snapshot)
    _lazy_series()


def _publish(snapshot):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), None)  # expect: G013
    srv.serve_forever()  # expect: G013
    sock = socket.socket()  # expect: G013
    sock.close()
    StatusServer(port=0)  # expect: G013


def _lazy_series():
    REG.counter("fixture.lazy").inc()  # expect: G013
    REG.attach(ROUNDS)  # expect: G013


def driver_setup(reg):
    # off the hot call graph: registration and server lifecycle are the
    # driver's job — exactly where these calls belong
    reg.histogram("tool.lat")
    return StatusServer(port=0)
