"""Seeded G006: nondeterminism feeding journaled paths.  Recovery
replays the journal assuming the same inputs re-produce the same
tensors; wall-clock, unseeded RNGs, and set iteration order all break
byte parity between the original run and its replay."""

import random
import time

import numpy as np


def pick_victim(doc_ids):
    return random.choice(doc_ids)  # expect: G006


def shuffle_lanes(lanes):
    rng = np.random.default_rng()  # expect: G006
    np.random.shuffle(lanes)  # expect: G006
    return rng, lanes


def journal_round(journal, lanes):
    journal.round_record(time.time(), lanes)  # expect: G006
    for lane in {1, 2, 3}:  # expect: G006
        journal.event("lane", lane=lane)
