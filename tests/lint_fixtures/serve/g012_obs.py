"""G012 seed: observability hygiene in hot-path scopes.

``macro_dispatch`` is the declared hot root; ``_plan_phase`` is
reached from it.  Constant span/metric names there are clean; an
f-string span name, a variable histogram name, and arming the tracer
mid-drain are the three violations.  ``off_hot_path`` shows the same
dynamic naming is LEGAL outside the hot call graph.
"""

from crdt_benches_tpu.obs.metrics import MetricsRegistry
from crdt_benches_tpu.obs.trace import arm, span

REG = MetricsRegistry()
ROUNDS = REG.counter("fixture.rounds")  # pre-registered: G013-clean too


def macro_dispatch(depth):  # graftlint: hot-path
    with span("fixture.round"):  # constant name: clean
        _plan_phase(depth)
    ROUNDS.inc()  # held reference: clean


def _plan_phase(depth):
    with span(f"fixture.plan.{depth}"):  # expect: G012
        pass
    name = "fixture.depth." + str(depth)
    REG.histogram(name)  # expect: G012  expect: G013
    arm()  # expect: G012


def off_hot_path(depth):
    # unreachable from any hot root: dynamic names carry no risk here
    REG.counter(f"tool.{depth}").inc()


def hot_regex_user(match):  # graftlint: hot-path
    # an unrelated API sharing a method name (re.Match.span) takes a
    # constant NON-str first arg: not an obs callsite, stays clean
    return match.span(1)
