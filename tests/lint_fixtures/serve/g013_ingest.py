"""G013 seed: network-endpoint isolation in hot-path scopes — the
ingest-front edition of ``g013_status.py``.

``hot_pump_round`` is the declared hot root; ``_accept_inline`` and
``_dial_peer`` are reached from it.  Constructing or serving the TCP
front there, and opening outbound sockets, are the violations; the
sanctioned pattern is the driver building the front ONCE and the hot
pump only draining its bounded queue.  ``driver_setup`` shows the same
calls are LEGAL off the hot call graph — server lifecycle belongs to
the bench driver.
"""

import socket
import socketserver

from crdt_benches_tpu.serve.ingest.front import IngestFront


def hot_pump_round(front):  # graftlint: hot-path
    payloads = front.drain()  # held reference: clean
    _accept_inline()
    _dial_peer()
    return payloads


def _accept_inline():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), None)  # expect: G013
    srv.serve_forever()  # expect: G013
    IngestFront({0}).start()  # expect: G013


def _dial_peer():
    sk = socket.create_connection(("127.0.0.1", 9))  # expect: G013
    sk.close()
    socket.create_server(("127.0.0.1", 0))  # expect: G013


def driver_setup(docs):
    # off the hot call graph: binding the port and spinning the
    # handler threads up is the driver's job — exactly where it belongs
    front = IngestFront(docs)
    front.start()
    return front
