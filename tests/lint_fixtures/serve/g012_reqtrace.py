"""G012/G013 seed: request-tracing edge discipline on the hot path.

``hot_round`` is the declared hot root.  Opening request contexts and
sampling exemplars is admission/drain-EDGE work: once per admitted doc
in the depth-1 selection loop (the sanctioned pattern, shown clean),
never in a per-op inner loop — a context per op allocates per op and
exemplar-per-op explodes bucket state (G012).  Constructing the flight
recorder or the request tracker mid-drain is driver-side lifecycle
(the tracker installs a global publish observer when armed) — G013,
the same contract as the status server.  ``driver_setup`` shows the
identical calls are LEGAL off the hot call graph.
"""

from crdt_benches_tpu.obs.flight import FlightRecorder
from crdt_benches_tpu.obs.reqtrace import RequestContext, RequestTracker

TRACKER = RequestTracker()  # driver-built, disarmed: clean


def hot_round(docs, ops):  # graftlint: hot-path
    for doc in docs:  # the admission edge: one context per admitted doc
        TRACKER.open_request(doc, 0)  # depth 1: clean
        for op in ops[doc]:  # the per-op inner loop
            TRACKER.open_request(doc, op)  # expect: G012
            TRACKER.sample_exemplar("ok", 0.1, None)  # expect: G012
            RequestContext(doc, op, 1, "default", 0)  # expect: G012
    FlightRecorder("/tmp/flight.json")  # expect: G013
    RequestTracker(samples=8)  # expect: G013


def driver_setup(path):
    # off the hot call graph: lifecycle construction and nested-loop
    # sampling are the driver's (and the tests') business
    tracker = RequestTracker(samples=8)
    for doc in range(4):
        for op in range(4):
            tracker.sample_exemplar("ok", 0.2, None)
    return FlightRecorder(path), tracker
