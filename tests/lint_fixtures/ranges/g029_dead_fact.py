"""G029 seeds (artifact-driven, see artifact.json): a declared check
and a declared mask the recorded run — staging surface armed — never
counted, vs runtime counters for a check and a mask nothing here
declares.  The fused-scoped pair stays silent: that surface was not
armed in the recorded run."""

import jax.numpy as jnp


def stage(pos):
    # graftlint: inrange=pos<=4096 check=fx.dead-check  # expect: G029
    return pos


def gather(doc, idx):
    safe = jnp.clip(idx, 0, 7)
    g = jnp.take_along_axis(doc, safe, axis=1)  # graftlint: mask=fx-dead-mask  # expect: G029
    return jnp.where(idx < 8, g, 0)  # graftlint: mask=fx-dead-mask


def fused_gather(doc, idx):
    safe = jnp.maximum(idx, 0)
    g = jnp.take_along_axis(doc, safe, axis=1)  # graftlint: mask=fx-fused-mask surface=fused
    return jnp.where(idx > 0, g, 0)  # graftlint: mask=fx-fused-mask surface=fused
