"""G027 seeds: arithmetic on uint16 op lanes before the widen — the
pos+rlen end-position sum that wraps past 65535 — plus a
marker-declared narrow lane, next to the legal orders (widen-first,
and arithmetic dominated by the OpRangeError staging bound check)."""

import numpy as np


class OpRangeError(ValueError):
    pass


def overflow_pos_rlen(pos, rlen):
    pos16 = pos.astype(np.uint16)
    rlen16 = rlen.astype(np.uint16)
    # the end-position sum on two narrow lanes: wraps, never faults
    return pos16 + rlen16  # expect: G027  expect: G027


def declared_lane(slot0):
    slot = slot0  # graftlint: narrow=slot
    return slot * 2  # expect: G027


def widen_first(pos):
    pos16 = pos.astype(np.uint16)
    wide = pos16.astype(np.int32)
    return wide + 1


def checked_first(pos, rlen):
    pos16 = pos.astype(np.uint16)
    if int(pos16.max()) > 65535:
        raise OpRangeError("pos lane out of range")
    return pos16 + 1
