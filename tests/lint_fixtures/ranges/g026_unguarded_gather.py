"""G026 seeds: an unguarded dynamic gather, a clamped gather whose
clamp region has no declared mask consumer, and a mask tag with no
paired consumer site — next to the legal twins (a clip+mask pair and
a declared-inrange scatter) that must stay silent."""

import jax.numpy as jnp


def unguarded_gather(doc, idx):
    # idx is a bare parameter with no guard on any call path
    return jnp.take_along_axis(doc, idx, axis=1)  # expect: G026


def clamp_and_hope(doc, idx):
    safe = jnp.maximum(idx, 0)
    # clamped, so "guarded" — but the clamp region's garbage has no
    # declared mask consumer
    return jnp.take_along_axis(doc, safe, axis=1)  # expect: G026


def half_pair(doc, idx):
    safe = jnp.minimum(idx, 9)
    # the tag never appears on a consuming `where` line
    return jnp.take_along_axis(doc, safe, axis=1)  # graftlint: mask=fx-lonely  # expect: G026


def masked_pair_ok(doc, idx):
    safe = jnp.clip(idx, 0, 7)
    g = jnp.take_along_axis(doc, safe, axis=1)  # graftlint: mask=fx-gap
    return jnp.where(idx >= 0, g, 0)  # graftlint: mask=fx-gap


def declared_fact_ok(doc, row):
    # graftlint: inrange=row<128
    return doc.at[row].set(0)
