"""G028 seeds: the PAD sentinel used directly in arithmetic, and a
sentinel-carrying local (planted by a `where`) leaking into a sum and
an ordering comparison — next to the legal twins (comparison AGAINST
the sentinel, and a mask applied before the arithmetic)."""

import jax.numpy as jnp

PAD = 0
_BIG = 1 << 30


def pad_in_arithmetic(kind):
    return kind + PAD  # expect: G028


def carrier_into_sum(live, d):
    dd = jnp.where(live, d, _BIG)  # plants the sentinel on dead lanes
    return dd + 1  # expect: G028


def carrier_into_ordering(live, d, other):
    dd = jnp.where(live, d, _BIG)
    return dd < other  # expect: G028


def compare_against_sentinel_ok(live, d):
    dd = jnp.where(live, d, _BIG)
    return dd >= _BIG  # the masking idiom itself


def masked_first_ok(live, d):
    dd = jnp.where(live, d, _BIG)
    clean = jnp.where(dd >= _BIG, 0, dd)
    return clean + 1
