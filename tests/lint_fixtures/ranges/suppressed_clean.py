"""Suppression contract for the ranges suite: every violation on this
page is explicitly `# graftlint: disable=`d, so the file lints clean —
the reviewed escape hatch works for G026-G028 like every other rule."""

import jax.numpy as jnp
import numpy as np

PAD = 0


def unguarded(doc, idx):
    return jnp.take_along_axis(doc, idx, axis=1)  # graftlint: disable=G026


def narrow_sum(pos):
    pos16 = pos.astype(np.uint16)
    return pos16 + 1  # graftlint: disable=G027


def pad_math(kind):
    return kind + PAD  # graftlint: disable=G028
