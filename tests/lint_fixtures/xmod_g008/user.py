"""G008 corpus, consumer side: importing ``LANE`` is what promotes it
to a shared cross-module symbol; the capacity-class tuple below then
disagrees with the imported dimension two different ways."""

from producer import LANE


def tiles(c):
    return c // LANE


def make_pool(classes=(256, 320),  # expect: G008
              slots=(4, 2, 1)):  # expect: G008
    """320 is not a LANE multiple (the serve/pool.py capacity-class
    contract), and three slot counts cannot pair with two classes."""
    return classes, slots
