"""G008 corpus, shadow side: importing the shared dimension and then
rebinding the same name module-level — the import is dead code and the
local fork wins silently."""

from producer import LANE

LANE = 512  # expect: G008
