"""G008 corpus, drift side: an independent module-level fork of the
shared dimension — the producer/consumer pair above still agree with
each other, so only the runtime would ever notice this copy diverging
(a half-migrated LANE bump looks exactly like this)."""

LANE = 64  # expect: G008
