"""G008 corpus, producer side: this module OWNS the shared dimension
constant — consumers import it, so any independent redefinition
elsewhere in the package is drift.  Linted as a directory with its
siblings (cross-module rules see nothing in a single-file run)."""

LANE = 128
