"""Seeded G018: atomic-commit discipline broken three ways — an
in-place write-mode open of a durable path role (a crash mid-write
leaves a torn artifact under its committed name), a committed rename
with no fsync anywhere earlier in the protocol sequence (rename
durability does not imply content durability), and a typo'd protocol
tag (which would silently exempt the function from the fs-protocol
accounting forever).  The legal twins — a staged `.tmp` write, and a
commit preceded by fsync — stay silent."""

import os


def clobber_manifest(path: str, blob: bytes) -> None:  # graftlint: durable=snapshot
    with open(path, "wb") as f:  # expect: G018
        f.write(blob)


def seal_segment(path: str, blob: bytes) -> None:  # graftlint: durable=wal
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:  # staging write: legal
        f.write(blob)
    os.replace(tmp, path)  # expect: G018


def seal_segment_durably(path: str, blob: bytes) -> None:  # graftlint: durable=wal
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # fsynced first: legal


def mislabeled(path: str) -> None:  # graftlint: durable=wall  # expect: G018
    os.fsync(os.open(path, os.O_RDONLY))
