"""G021 fixture: a declared durable protocol vs a runtime ``fs_ops``
artifact (fsops/artifact.json).  ``flush_ring`` declares the flight
protocol; the artifact's run ARMED the flight surface but recorded
zero flight entries — a dead protocol — and carries a ``rogue_proto``
tag plus an unattributed unlink no static marker explains.  Like the
G011/G017 fixtures, this file is artifact-driven: without the
artifact, no findings."""

import os


def flush_ring(path: str, blob: str) -> None:  # graftlint: durable=flight  # expect: G021
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
