"""Seeded G019: durable-ordering broken — destruction of a durable
copy (the old spool member) before the committed install of its
replacement, the exact PR 13 unlink-before-install crash window.  The
legal twins — commit-then-destroy, the torn-pass read-witness form,
and staging cleanup — stay silent."""

import os
import shutil


def rotate_spool(old: str, dst: str, blob: bytes) -> None:  # graftlint: durable=spool
    os.unlink(old)  # expect: G019
    tmp = dst + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dst)


def rotate_spool_safely(old: str, dst: str, blob: bytes) -> None:  # graftlint: durable=spool
    tmp = dst + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dst)  # the committed install...
    os.unlink(old)  # ...dominates the destruction: legal


def torn_pass_cleanup(manifest: str, victim_dir: str) -> None:  # graftlint: durable=gc
    with open(manifest, "rb") as f:  # read of the committed record...
        f.read()
    shutil.rmtree(victim_dir)  # ...licenses the destruction: legal


def drop_staging(dst: str) -> None:  # graftlint: durable=snapshot
    leftover = dst + ".tmp"
    shutil.rmtree(leftover, ignore_errors=True)  # staging: legal
