"""The suppression contract for the crash-consistency rules: each
seeded violation carries a same-line ``# graftlint: disable=G0XX`` and
the file must lint CLEAN — the reviewed escape hatch works for G018-
G020 exactly as it does for every other rule."""

import numpy as np
import os


def overwrite_in_place(path: str, blob: bytes) -> None:  # graftlint: durable=snapshot
    with open(path, "wb") as f:  # graftlint: disable=G018
        f.write(blob)


def destroy_first(old: str, dst: str, blob: bytes) -> None:  # graftlint: durable=spool
    os.unlink(old)  # graftlint: disable=G019
    tmp = dst + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dst)


def trusting_read(path: str):  # graftlint: durable=spool
    return np.load(path)["doc"]  # graftlint: disable=G020
