"""Seeded G020: verify-before-trust broken both ways — a trusted
``np.load`` of a durable artifact with no CRC verification in the
reading function (bit flips surface as field-access crashes far from
the load site), and a recovery fallback whose try-body indexes into
parsed manifest data while catching too narrow a set (a bit-flipped
manifest stays PARSEABLE json with garbled values and escapes as
KeyError/IndexError/TypeError — the ``_read_manifest`` incident).  The
verifying reader and the garbage-covering fallback stay silent."""

import json
import zlib

import numpy as np

_RECOVERABLE = (ValueError, KeyError, IndexError, TypeError, OSError)


def read_member(path: str):  # graftlint: durable=spool
    z = np.load(path)  # expect: G020
    return z["doc"]


def read_member_verified(path: str):  # graftlint: durable=spool
    z = np.load(path)
    got = zlib.crc32(z["doc"].tobytes())
    if got != int(z["crc"]):
        raise ValueError("member damaged")
    return z["doc"]


def pick_candidate(manifests: list[str]):  # graftlint: durable=snapshot
    for raw in manifests:
        try:
            m = json.loads(raw)
            return int(m["round"])
        except ValueError:  # expect: G020
            continue
    return None


def pick_candidate_safely(manifests: list[str]):  # graftlint: durable=snapshot
    for raw in manifests:
        try:
            m = json.loads(raw)
            return int(m["round"])
        except _RECOVERABLE:  # parseable garbage covered: legal
            continue
    return None
