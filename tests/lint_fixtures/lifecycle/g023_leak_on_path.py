"""G023 seed: an acquired row whose only exits drop the handle — no
release on the fall-off path, no ownership escape.  The legal twin
releases in a finally, covering every exit."""


class Rows:
    def alloc(self):  # graftlint: acquire=rows
        return object()

    def free(self, row):  # graftlint: release=rows
        return row


class Sched:
    def __init__(self):
        self.rows = Rows()

    def place_ok(self, doc):
        row = self.rows.alloc()
        try:
            return bind(doc, row)
        finally:
            self.rows.free(row)

    def place_leaks(self, doc):
        row = self.rows.alloc()  # expect: G023
        if doc is None:
            return None
        return None


def bind(doc, row):
    return (doc, row)
