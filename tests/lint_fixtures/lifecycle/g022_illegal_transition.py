"""G022 seed: the doc-residency machine with one illegal declared
edge (the PR 18 same-round-admit shape — a migration straight out of
GENESIS) and one rogue direct write to the guarded state field."""


class Pool:  # graftlint: state=doc field=phase states=genesis,live,cold,gone edges=genesis->live,live->cold,cold->live,live->gone,cold->gone
    def __init__(self):
        self.phase = "genesis"

    def install(self, rec):  # graftlint: transition=doc:genesis->live
        rec.phase = "live"

    def spool_out(self, rec):  # graftlint: transition=doc:live->cold,cold->live
        rec.phase = "cold"

    def migrate(self, rec):  # graftlint: transition=doc:genesis->gone  # expect: G022
        rec.phase = "gone"

    def evict(self, rec):
        rec.phase = "cold"  # expect: G022
