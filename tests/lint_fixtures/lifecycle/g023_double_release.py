"""G023 seed: the duplicate-GC-enqueue shape — the same resource
released twice, once past a live acquire (balance goes negative) and
once in a release-only cleanup that repeats itself verbatim."""


class Spool:
    def open_segment(self):  # graftlint: acquire=segment
        return object()

    def drop_segment(self):  # graftlint: release=segment
        return None


def reclaim(spool):
    seg = spool.open_segment()
    spool.drop_segment()
    spool.drop_segment()  # expect: G023
    return seg


def teardown(spool):
    spool.drop_segment()
    spool.drop_segment()  # expect: G023
