"""G024 seed: the PR 17 incident pair — a long-lived cache keyed by
a recyclable ``id()`` and a paired inflight counter decremented with
no underflow guard.  The generation-tupled key and the
positivity-guarded decrement are the legal twins."""


class Prefetch:
    def start(self):  # graftlint: acquire=thread
        self.inflight = 0
        return self

    def stop(self):  # graftlint: release=thread
        return None

    def enqueue(self, item):
        self._cache[id(item)] = item  # expect: G024
        self.inflight += 1

    def enqueue_generational(self, item, gen):
        self._cache[(id(item), gen)] = item

    def lookup(self, item):
        return self._cache.get(id(item))  # expect: G024

    def drain_one(self):
        self.inflight -= 1  # expect: G024

    def drain_guarded(self):
        if self.inflight > 0:
            self.inflight -= 1
