"""Suppression contract for the lifecycle suite: every violation on
this page is explicitly `# graftlint: disable=`d, so the file lints
clean — the reviewed escape hatch works for G022-G024 like every
other rule."""


class Pool:  # graftlint: state=doc field=phase states=genesis,live edges=genesis->live
    def __init__(self):
        self.phase = "genesis"

    def rogue_write(self, rec):
        rec.phase = "live"  # graftlint: disable=G022

    def alloc(self):  # graftlint: acquire=rows
        return object()

    def free(self, row):  # graftlint: release=rows
        return row

    def leaky(self, doc):
        row = self.alloc()  # graftlint: disable=G023
        if doc is None:
            return None
        return None

    def poisoned(self, item):
        self._cache[id(item)] = item  # graftlint: disable=G024
