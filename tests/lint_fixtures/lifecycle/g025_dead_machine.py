"""G025 seed (artifact-driven, see artifact.json): a declared doc
machine and a declared rows resource the recorded run — pool surface
armed — never touched, vs runtime counters for a session machine and
a socket resource nothing here declares."""


class Pool:  # graftlint: state=doc states=genesis,live edges=genesis->live  # expect: G025
    def install(self, rec):  # graftlint: transition=doc:genesis->live
        rec.resident = True


class Bucket:
    def alloc_row(self):  # graftlint: acquire=rows  # expect: G025
        return 1

    def release_row(self, row):  # graftlint: release=rows
        return row
