"""Seeded confinement hazards of a PREFETCH thread — the minimized
shape of the tiered-residency rehydrate worker (serve/prefetch.py) with
each rule's canonical mistake planted next to its legal twin:

- **G014**: the worker appends a freshly-loaded row into a shared list
  the hot thread's admission reads — a mutable object escaping the
  prefetch thread with no declared publish point;
- **G015**: the worker's declared publish point mutates the published
  payload in place AFTER the swap — a hot-side reader can observe the
  half-applied handoff;
- **G016**: the admission walk BLOCKS on the result queue when the
  warm tier misses — the exact wait the contract forbids (a miss must
  fall back to a synchronous rehydrate, never park the drain behind
  the prefetch thread).  The non-blocking twin on the next line stays
  legal.
"""

import queue

_RESULTS = queue.Queue()


class PrefetchBridge:
    def __init__(self):
        self.warm = {}  # hot-owned tier (only the hot thread touches it)
        self.loaded = []  # shared scratch: the G014 escape below
        self.latest = {}

    def worker(self) -> None:  # graftlint: thread=prefetch
        row = {"doc": 7, "bytes": [1, 2, 3]}
        self.loaded.append(row)  # expect: G014
        self.publish_row(row)

    def publish_row(self, row: dict) -> None:  # graftlint: publish  # graftlint: thread=prefetch
        self.latest = {"row": row}  # the legal atomic swap
        self.latest["seq"] = 1  # expect: G015

    def admit(self, doc_id: int):  # graftlint: hot-path
        if doc_id in self.warm:
            return self.warm[doc_id]
        if not self.loaded:  # reads the escaped list on the hot thread
            _RESULTS.get()  # expect: G016
        try:
            return _RESULTS.get_nowait()  # non-blocking twin: legal
        except queue.Empty:
            return self.rehydrate(doc_id)

    def rehydrate(self, doc_id: int) -> dict:
        return {"doc": doc_id}  # the synchronous fallback path
