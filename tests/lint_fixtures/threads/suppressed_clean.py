"""The thread-rule suppression contract: every seeded G014/G015/G016
violation here carries a same-line ``graftlint: disable=G01X`` comment
(or rides the file-wide directive below) and the file must lint CLEAN
— the escape hatch works for the concurrency rules exactly like it
does for the JAX-hygiene ones (this docstring mentioning the directive
does not count; only real comments do)."""

# graftlint: disable-file=G016

import threading

_LOCK = threading.Lock()


class Escapee:
    def __init__(self):
        self.shared = {}
        self.escaped = {}

    def record(self, v: int) -> None:  # graftlint: thread=hot
        self.escaped["v"] = v  # graftlint: disable=G014
        self.shared["v"] = v  # graftlint: disable=G015

    def publish(self, snap: dict) -> None:  # graftlint: publish  # graftlint: thread=hot
        self.shared = snap
        self.shared["late"] = True  # graftlint: disable=G015

    def read(self) -> dict:  # graftlint: thread=status
        return dict(self.shared) | dict(self.escaped)


def drain_round():  # graftlint: hot-path
    with _LOCK:  # covered by the file-wide G016 disable
        pass
