"""Seeded G017 corpus (lints with ``threads/artifact.json`` as
``--thread-artifact``; without an artifact the rule has no ground truth
and stays silent, so tests/test_lint.py drives this file explicitly
instead of through the per-file marker contract):

- ``publish_snap`` is a DECLARED publish point the artifact's run
  never entered -> dead-point finding at its def line;
- ``publish_status_only`` is tagged ``publish=status`` and the
  artifact says the status surface was NOT armed -> exempt;
- ``publish_typod`` is tagged ``publish=statsu`` — a surface the
  artifact does not even record -> unknown-tag finding (a tag that can
  never match an armed surface would silently disable the dead-point
  check forever);
- the artifact's ``rogue_handoff`` counter has no matching marker ->
  unattributed-crossing finding against the artifact itself.
"""


class Feed:
    def __init__(self):
        self._snap = {}

    def publish_snap(self, snap: dict) -> None:  # graftlint: publish  # expect: G017
        self._snap = snap

    def publish_status_only(self, snap: dict) -> None:  # graftlint: publish=status
        self._snap = snap

    def publish_typod(self, snap: dict) -> None:  # graftlint: publish=statsu  # expect: G017
        self._snap = snap
