"""Seeded G016: blocking host primitives reachable from the serving
hot path — a lock-guarded section and a bare ``acquire`` (the drain
stalls behind whoever holds the lock), an unbounded stdlib-queue get,
a bare event wait, and a thread join hiding INSIDE a declared fence
(the G016 walk descends: a fence declares a device sync, not a license
to wedge the drain).  Every hazard sits next to its legal twin: the
non-blocking / bounded forms (``get_nowait``, positional timeouts,
``acquire(blocking=False)``, ``wait(timeout=...)``) and a ``block``-
named context manager that must NOT read as a lock."""

import queue
import threading

_LOCK = threading.Lock()
_INBOX = queue.Queue()


_DONE = threading.Event()


class _BlockCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_BLOCK_GUARD = _BlockCtx()


def drain_round():  # graftlint: hot-path
    with _LOCK:  # expect: G016
        plan_next()
    with _BLOCK_GUARD:  # "block" is not "lock": stays legal
        pass
    _INBOX.get()  # expect: G016
    _INBOX.get_nowait()  # bounded: stays legal
    _INBOX.get(True, 0.1)  # positional timeout: stays legal
    _INBOX.put("x", False)  # positional block=False: stays legal
    _DONE.wait()  # expect: G016
    _DONE.wait(timeout=0.1)  # bounded: stays legal
    boundary_pull()


def plan_next():
    _LOCK.acquire()  # expect: G016
    if _LOCK.acquire(blocking=False):  # poll, never stalls: legal
        _LOCK.release()
    _LOCK.release()


def boundary_pull():  # graftlint: fence
    worker = threading.Thread()
    worker.join()  # expect: G016
