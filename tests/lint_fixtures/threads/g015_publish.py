"""Seeded G015: the publish-point contract broken all five ways — an
in-place mutation INSIDE a declared publish point (readers can observe
the half-applied state), a reader-thread mutation of an attribute it
received through one (published snapshots are read-only on the far
side), an OWNER-side mutation outside the publish point (readers may
already hold the published reference), a far-side REASSIGNMENT of the
published attribute (even an atomic swap races the publisher when a
non-writer thread does it), and an OWNER-side reassignment to a fresh
mutable object outside the publish point (the swap is atomic but the
replacement carries no publish generation — the race sanitizer cannot
track it)."""


class SnapshotFeed:
    def __init__(self):
        self._snap = {}

    def publish(self, snap: dict) -> None:  # graftlint: publish  # graftlint: thread=hot
        self._snap = snap  # the legal atomic swap
        self._snap["late_field"] = True  # expect: G015

    def bump(self) -> None:  # graftlint: thread=hot
        self._snap["n"] = 1  # expect: G015

    def read(self) -> dict:  # graftlint: thread=status
        got = self._snap
        got["seen"] = True  # expect: G015
        return got

    def reset(self) -> None:  # graftlint: thread=status
        self._snap = {}  # expect: G015

    def clear(self) -> None:  # graftlint: thread=hot
        self._snap = {}  # expect: G015
