"""Seeded G014: a mutable dict written on the hot thread and read from
the status thread, with no declared publish point anywhere on its write
path — the minimized shape of the shared-mutable-escape hazard the
thread-confinement audit polices (compare obs/status.py, where the same
handoff rides a ``# graftlint: publish`` reference swap)."""


class RoundStats:
    def __init__(self):
        # __init__ writes precede thread handoff: never a finding
        self.latest = {}
        self.rounds = 0

    def record(self, rnd: int, patched: int) -> None:  # graftlint: thread=hot
        # hot-confined scalar: only one owning thread, stays legal
        self.rounds = rnd
        self.latest["patched"] = patched  # expect: G014

    def snapshot(self) -> dict:  # graftlint: thread=status
        return dict(self.latest)
