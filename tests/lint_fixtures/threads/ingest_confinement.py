"""Seeded confinement hazards of an INGEST handler thread — the
minimized shape of the live front's connection handlers
(serve/ingest/front.py) with each rule's canonical mistake planted
next to its legal twin:

- **G014**: the handler appends a decoded frame into a shared list the
  hot pump reads — a mutable object escaping the ingest thread with no
  declared publish point;
- **G015**: the handler's declared publish point mutates the published
  payload in place AFTER the swap — the hot pump can observe the
  half-applied handoff;
- **G016**: the pump's drain BLOCKS on the delivery queue when no
  frame is pending — the exact wait the contract forbids (an empty
  queue means "nothing arrived this round", never "park the drain
  behind a TCP handler").  The non-blocking twin on the next line
  stays legal.
"""

import queue

_DELIVERY = queue.Queue()


class FrontBridge:
    def __init__(self):
        self.holding = []  # hot-owned (only the pump touches it)
        self.seen = []  # shared scratch: the G014 escape below
        self.latest = {}

    def handle_frame(self) -> None:  # graftlint: thread=ingest
        frame = {"doc": 3, "seq": 1, "count": 8}
        self.seen.append(frame)  # expect: G014
        self.publish_frame(frame)

    def publish_frame(self, frame: dict) -> None:  # graftlint: publish  # graftlint: thread=ingest
        self.latest = {"frame": frame}  # the legal atomic swap
        self.latest["acked"] = True  # expect: G015

    def pump_step(self):  # graftlint: hot-path
        if self.holding:
            return self.holding.pop()
        if not self.seen:  # reads the escaped list on the hot thread
            _DELIVERY.get()  # expect: G016
        try:
            return _DELIVERY.get_nowait()  # non-blocking twin: legal
        except queue.Empty:
            return None
