"""Seeded G010 violation: a VMEM block whose minor dimension is not a
multiple of LANE=128 — every copy into and out of the block serializes
on TPU (the (Rt, nt, 1) per-tile-scalar shape is the one exemption)."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANE = 128


def _kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def launch_narrow_block(x):
    narrow = pl.BlockSpec((8, 64), lambda i: (i, 0))  # expect: G010
    return pl.pallas_call(
        _kernel,
        grid=(2,),
        in_specs=[narrow],
        out_specs=pl.BlockSpec((8, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, LANE), jnp.int32),
    )(x)
