"""Seeded G003: tracer formatting inside a jitted body (runs at trace
time only — or leaks a tracer repr into logs), and an unhashable
literal passed for a declared static argument (fails or retraces every
call)."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("tiles",))
def apply_tiles(doc, shift, *, tiles=4):
    print("applying shift", shift)  # expect: G003
    return doc + shift * tiles


def run(doc, shift):
    return apply_tiles(doc, shift, tiles=[4, 8])  # expect: G003
