"""Seeded G009/G010 violations in a minimized copy of the fused serve
kernel's launch (ops/serve_fused.py serve_macro_fused): the real thing
runs grid (row_blocks, K) with the doc block revisited along K and the
per-round op tensors streamed in — which is exactly the geometry where
a stale index map or an unpadded token width would compile into silent
cross-round corruption.  Seeded here: a doc spec whose index map still
has the pre-K single-axis arity, a per-round spec whose token width is
not LANE-padded, and a launch invoked with one round tensor missing."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANE = 128
Rt = 8
nt = 2
K = 4
T = 130  # deliberately the UNPADDED 2B+2 token width


def _round_kernel(doc_ref, tok_ref, doc_out):
    doc_out[:] = doc_ref[:] + tok_ref[0, :, :1]


def serve_macro_minimized(doc, tokens):
    doc_spec = pl.BlockSpec((Rt, nt, LANE), lambda i: (i, 0, 0))  # expect: G009
    tok_spec = pl.BlockSpec((1, Rt, T), lambda i, k: (k, i, 0))  # expect: G010
    return pl.pallas_call(
        _round_kernel,
        grid=(2, K),
        in_specs=[doc_spec, tok_spec],
        out_specs=pl.BlockSpec((Rt, nt, LANE), lambda i, k: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((16, nt, LANE), jnp.int32),
    )(doc, tokens)


def serve_macro_missing_round_input(doc, tokens, dints):
    spec3 = pl.BlockSpec((Rt, nt, LANE), lambda i, k: (i, 0, 0))
    rnd = pl.BlockSpec((1, Rt, LANE), lambda i, k: (k, i, 0))
    return pl.pallas_call(  # expect: G009
        _round_kernel,
        grid=(2, K),
        in_specs=[spec3, rnd, rnd],
        out_specs=spec3,
        out_shape=jax.ShapeDtypeStruct((16, nt, LANE), jnp.int32),
    )(doc, tokens, dints)
