"""Seeded G001: module-scope device arrays, with and without a jitted
closure (both are flagged — the committed buffer alone forces the slow
dispatch path per executable launch on the axon tunnel)."""

import jax
import jax.numpy as jnp

PAD_ROW = jnp.zeros(128, jnp.int32)  # expect: G001
SENTINEL = jnp.int32(-1)  # expect: G001


@jax.jit
def mask_tail(doc):
    return jnp.where(doc < 0, SENTINEL, doc)
