"""Seeded G004: a donated buffer read after the donating call.  With
``donate_argnums=(0,)`` XLA may reuse ``state``'s memory for the
output; the later ``state.sum()`` reads a dead buffer (on TPU this is
garbage, on CPU it "works" — the worst kind of portability bug)."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def fold(state, ops):
    return state + ops


def drain(state, ops):
    out = fold(state, ops)
    checksum = state.sum()  # expect: G004
    return out, checksum
