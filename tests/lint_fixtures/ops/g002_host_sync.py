"""Seeded G002: a host sync buried two calls deep under a hot-path
root.  ``macro_dispatch`` is the declared hot path; ``_occupancy``
looks like innocent bookkeeping but ``.item()`` fences the device —
exactly the class of stray sync that melted the round-loop engine."""

import numpy as np


def _occupancy(lanes):
    return lanes.sum().item()  # expect: G002


def _plan_round(state, lanes):
    depth = _occupancy(lanes)
    host_view = np.asarray(state.doc)  # expect: G002
    return depth, host_view


def macro_dispatch(state, lanes):  # graftlint: hot-path
    depth, view = _plan_round(state, lanes)
    return depth, view
