"""Seeded G009 violations: pallas_call launch geometry that disagrees
with itself — an index map built for a 2-D grid on a 1-D launch, an
output block that does not divide the extent it tiles, and a kernel
whose ref list is one spec short.  Every one of these compiles into
out-of-bounds tile traffic (or a Mosaic error naming none of this)."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANE = 128


def _kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def launch_bad_geometry(x):
    stale_map = pl.BlockSpec((24, LANE), lambda i, j: (i, 0))  # expect: G009
    return pl.pallas_call(
        _kernel,
        grid=(4,),
        in_specs=[stale_map],
        out_specs=pl.BlockSpec((24, LANE), lambda i: (i, 0)),  # expect: G009
        out_shape=jax.ShapeDtypeStruct((100, LANE), jnp.int32),
    )(x)


def launch_missing_ref(x, y):
    spec = pl.BlockSpec((8, LANE), lambda i: (i, 0))
    return pl.pallas_call(  # expect: G009
        _kernel,
        grid=(2,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((16, LANE), jnp.int32),
    )(x, y)
