"""Seeded G005: array creation without an explicit dtype.  Under
JAX_ENABLE_X64 (or a future default flip) these become int64/float64,
silently recompiling every int32-keyed kernel downstream — and the
packed doc layout assumes 32-bit lanes."""

import jax.numpy as jnp


def staging_buffers(rows, batch):
    kind = jnp.zeros((rows, batch))  # expect: G005
    lanes = jnp.arange(rows)  # expect: G005
    ok = jnp.zeros((rows, batch), jnp.int32)  # explicit: clean
    return kind, lanes, ok
