"""Seeded G005: array creation without an explicit dtype.  Under
JAX_ENABLE_X64 (or a future default flip) these become int64/float64,
silently recompiling every int32-keyed kernel downstream — and the
packed doc layout assumes 32-bit lanes.

The three violations span the autofixer's outcomes: a value-less
creator (zeros -> float32, today's default made explicit), an all-int
literal arange (-> int32), and a runtime-typed arange bound the fixer
must REFUSE (the dtype follows the argument's runtime type)."""

import jax.numpy as jnp


def staging_buffers(rows, batch):
    kind = jnp.zeros((rows, batch))  # expect: G005
    lanes = jnp.arange(128)  # expect: G005
    tiles = jnp.arange(rows)  # expect: G005
    ok = jnp.zeros((rows, batch), jnp.int32)  # explicit: clean
    return kind, lanes, tiles, ok
