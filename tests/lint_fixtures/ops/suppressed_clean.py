"""The suppression escape hatch: every violation here carries a
same-line ``graftlint: disable=G00X`` comment (or is covered by the
file-wide comment directive below) and the file must lint CLEAN —
tests pin the contract that suppressions are honored exactly, and that
they only work as REAL comments (this docstring mentioning the
directive does not count)."""

# graftlint: disable-file=G005

import jax
import jax.numpy as jnp

BIG = jnp.int32(2**30)  # graftlint: disable=G001


@jax.jit
def shift(x):
    return x + BIG  # graftlint: disable=G028


def make(n):
    return jnp.zeros((n, 4))  # covered by the file-wide G005 disable
