"""Seeded G007: the @boundary contract lies about donation.  The
registry says nothing is donated, the jit wrapper donates arg 0 — a
caller trusting the table would keep using the buffer."""

from functools import partial

import jax

from crdt_benches_tpu.lint.boundary import boundary


@boundary(dtypes=("int32",), donates=())  # expect: G007
@partial(jax.jit, donate_argnums=(0,))
def entry(doc):
    return doc * 2
