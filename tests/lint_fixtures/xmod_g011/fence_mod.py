"""G011 corpus: a hot path with one LIVE declared fence and one STALE
one.  ``artifact.json`` next door is the matching runtime ground truth
(a ``boundary_syncs`` block as the serve bench emits it): ``pull_all``
crossed three times, ``stale_boundary`` never, and the run also counted
a fence the static model has no marker for."""


def hot_loop():  # graftlint: hot-path
    for _ in range(2):
        pull_all()


def pull_all():  # graftlint: fence
    return 1


def stale_boundary():  # graftlint: fence -- expect: G011
    return 2
