"""Historical bug 2 (minimized): pre-shim CompilerParams drift.  jax
<= 0.4 names the Pallas-TPU params class ``TPUCompilerParams``; the
rename to ``CompilerParams`` landed in 0.5.  Importing the tpu namespace
directly ties the module to whichever jax happens to be installed — the
repo's kernels broke exactly this way until PR 1 centralized the import
behind ops/pallas_compat.py (which pins the shim in ONE place)."""

from jax.experimental.pallas import tpu as pltpu  # expect: G003


def kernel_params(dims):
    return pltpu.CompilerParams(dimension_semantics=dims)
