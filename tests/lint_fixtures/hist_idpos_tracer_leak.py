"""Historical bug 1 (minimized): the ops/idpos.py module-level device
constant.  ``BIG`` is created at import time — if the first import
happens inside a live trace (the serve runner imports engines lazily
from jitted regions), the "constant" is a TRACER, and every @jit that
closes over it dies with a leaked-tracer error in a completely different
stack (__graft_entry__.dryrun_multichip was the victim).  Fixed in PR 1
by making it a host-side np.int32."""

import jax
import jax.numpy as jnp

BIG = jnp.int32(2**30)  # expect: G001


@jax.jit
def level_shift(sub, p):
    # BIG closed over by a jitted body — the leak vector
    return jnp.where(sub <= p, sub, BIG)
