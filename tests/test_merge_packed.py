"""Packed fast-path merge (engine/merge.py merge_oplogs_packed): the
parallel chain-structure + id-resolved integration must agree byte-for-byte
with the merge oracle, the portable v1 merge kernel, and across replicas,
delivery orders, duplication, and batch/epoch choices."""

import numpy as np
import pytest

from crdt_benches_tpu.engine.merge import (
    MergeSimulation,
    OpLog,
    merge_oracle,
)

from test_merge import make_stream, shuffled_log, sim_for


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.slow
def test_packed_vs_oracle_and_v1(seed):
    sim = sim_for(seed=seed, n_agents=3, n_ops=40)
    want = merge_oracle(sim.log, "base text", np.asarray(sim.chars))
    assert sim.decode(sim.merge()) == want
    got = sim.decode(sim.merge_packed())
    assert got == want


@pytest.mark.slow
def test_packed_replica_batched():
    sim = sim_for(seed=9, n_agents=2, n_ops=30)
    want = sim.decode(sim.merge())
    state = sim.merge_packed(n_replicas=4)
    for r in range(4):
        from crdt_benches_tpu.ops.apply2 import PackedState, decode_state3
        import jax

        codes, nvis = jax.jit(
            decode_state3, static_argnames=("replica",)
        )(
            PackedState(
                doc=state.doc, length=state.length, nvis=state.nvis
            ),
            sim.chars,
            replica=r,
        )
        got = "".join(map(chr, np.asarray(codes)[: int(nvis)].tolist()))
        assert got == want


@pytest.mark.slow
def test_packed_delivery_order_and_duplication():
    sim = sim_for(seed=4, n_agents=3, n_ops=30)
    rng = np.random.default_rng(11)
    want = sim.decode(sim.merge_packed())
    got = sim.decode(sim.merge_packed(shuffled_log(sim.log, rng)))
    assert got == want
    dup = OpLog.concat([sim.log, sim.log])
    got = sim.decode(sim.merge_packed(shuffled_log(dup, rng)))
    assert got == want


@pytest.mark.slow
def test_packed_epoch_and_batch_independence():
    rng = np.random.default_rng(6)
    base = "shared"
    streams = [make_stream(rng, base, 40, batch=16) for _ in range(2)]
    sim16 = MergeSimulation(streams, base=base, batch=16)
    sim8 = MergeSimulation(streams, base=base, batch=8)
    want = sim16.decode(sim16.merge())
    assert sim16.decode(sim16.merge_packed(epoch=2)) == want
    assert sim16.decode(sim16.merge_packed(epoch=8)) == want
    assert sim8.decode(sim8.merge_packed(epoch=4)) == want


@pytest.mark.slow
def test_packed_deep_chains_single_anchor():
    """Adversarial shape: every agent types at position 0 (deep
    same-anchor sibling chains + long internal runs)."""
    from crdt_benches_tpu.traces.loader import TestData, TestTxn
    from crdt_benches_tpu.traces.tensorize import tensorize

    base = "x"
    streams = []
    for a in range(3):
        patches = [[0, 0, chr(ord("a") + a) * 1] for _ in range(17)]
        streams.append(
            tensorize(TestData(base, "", [TestTxn("", patches)]), batch=8)
        )
    sim = MergeSimulation(streams, base=base, batch=8)
    want = merge_oracle(sim.log, base, np.asarray(sim.chars))
    assert sim.decode(sim.merge()) == want
    assert sim.decode(sim.merge_packed(epoch=4)) == want


@pytest.mark.slow
def test_native_treap_agrees_small():
    """The independent native RGA treap (separate implementation, C++)
    agrees with both the Python oracle and the packed kernel."""
    from crdt_benches_tpu.backends.native import native_available
    from crdt_benches_tpu.engine.merge import native_merge_content

    if not native_available():
        import pytest as _pytest

        _pytest.skip("native library unavailable")
    for seed in range(3):
        sim = sim_for(seed=seed, n_agents=3, n_ops=40)
        want = merge_oracle(sim.log, "base text", np.asarray(sim.chars))
        assert native_merge_content(sim) == want
        assert sim.decode(sim.merge_packed()) == want


@pytest.mark.slow
def test_native_treap_agrees_100k_ops_24_agents():
    """Independent large-scale validation (VERDICT round 1 item 6): >=100k
    ops across dozens of agents, cross-checked against the native treap's
    RGA integration — a separate implementation, not the shared-spec Python
    oracle (which is infeasible at this size)."""
    from crdt_benches_tpu.backends.native import native_available
    from crdt_benches_tpu.engine.merge import native_merge_content

    if not native_available():
        import pytest as _pytest

        _pytest.skip("native library unavailable")
    rng = np.random.default_rng(42)
    base = "base text for the concurrent merge scale test"
    streams = [
        make_stream(rng, base, 4200, batch=512) for _ in range(24)
    ]
    sim = MergeSimulation(streams, base=base, batch=512)
    assert len(sim.log) >= 100_000
    want = native_merge_content(sim)
    got = sim.decode(sim.merge_packed(epoch=8))
    assert len(got) == len(want)
    assert got == want


@pytest.mark.slow
def test_sharded_packed_merge_converges():
    """8 divergent replicas sharded over the 8-device CPU mesh, merged on
    the packed fast path: union exchange via all_gather, id-resolved
    integration per shard, pmin/pmax digest agreement."""
    import jax
    import jax.numpy as jnp

    from crdt_benches_tpu.parallel.mesh import (
        replica_mesh,
        sharded_merge_packed,
    )

    sim = sim_for(seed=9, n_agents=8, n_ops=12, base="mesh base", batch=16)
    logs = sim.stacked_logs()
    # gathered union length (8 * N_local) must divide batch * epoch
    n_local = logs["kind"].shape[1]
    assert (8 * n_local) % (16 * 2) == 0
    mesh = replica_mesh(8)
    step = sharded_merge_packed(
        mesh, sim.capacity, sim.n_base, batch=16, epoch=2
    )
    state, digests, converged = step(
        jnp.asarray(logs["lamport"]),
        jnp.asarray(logs["agent"]),
        jnp.asarray(logs["kind"]),
        jnp.asarray(logs["elem"]),
        jnp.asarray(logs["origin"]),
        jnp.asarray(logs["ch"]),
        sim.chars,
    )
    assert bool(np.asarray(converged))
    d = np.asarray(digests)
    assert (d == d[0]).all()
    from crdt_benches_tpu.engine.downstream import DownPacked

    st0 = jax.tree.map(lambda x: x[:1], state)
    assert sim.decode(
        DownPacked(st0.doc, st0.snap, st0.length, st0.nvis)
    ) == sim.decode(sim.merge())
