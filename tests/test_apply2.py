"""Differential tests for the scatter-free doc-order apply (ops/apply2.py):
the v2 engine must be byte-identical to the oracle and to the v1 engine on
random streams and real traces, and its building blocks (tiled searchsorted,
log-shift expansion) must match their reference formulations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_benches_tpu.engine.replay import ReplayEngine
from crdt_benches_tpu.ops.apply2 import _expand, count_le_tiled
from crdt_benches_tpu.oracle import OracleDocument
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import tensorize


@pytest.mark.parametrize("seed", range(4))
def test_count_le_tiled_matches_searchsorted(seed):
    rng = np.random.default_rng(seed)
    R, C, B = 3, 512, 40
    base = np.sort(rng.integers(0, 300, size=(R, C)), axis=1)
    q = rng.integers(-5, 320, size=(R, B))
    got = count_le_tiled(jnp.asarray(base, jnp.int32), jnp.asarray(q, jnp.int32))
    want = np.stack(
        [np.searchsorted(base[r], q[r], side="right") for r in range(R)]
    )
    np.testing.assert_array_equal(np.asarray(got), want)


@pytest.mark.parametrize("seed", range(4))
def test_expand_matches_reference(seed):
    rng = np.random.default_rng(seed)
    R, C, B = 2, 256, 31
    x = rng.integers(0, 1000, size=(R, C)).astype(np.int32)
    # distinct insert destinations -> 1-Lipschitz monotone r
    r = np.zeros((R, C), np.int32)
    for row in range(R):
        dests = rng.choice(C, size=B, replace=False)
        ind = np.zeros(C, np.int32)
        ind[dests] = 1
        r[row] = np.cumsum(ind)
    got = np.asarray(
        _expand([jnp.asarray(x)], jnp.asarray(r), nbits=6)[0]
    )
    for row in range(R):
        for d in range(C):
            src = d - r[row, d]
            if src >= 0:
                assert got[row, d] == x[row, src], (row, d)


def _oracle_replay(trace):
    doc = OracleDocument.from_str(trace.start_content)
    for p, d, ins in trace.iter_patches():
        doc.replace(p, p + d, ins)
    return doc.content()


@pytest.mark.parametrize("seed", [0, 1, 2, 7])
@pytest.mark.parametrize("batch", [16, 64])
@pytest.mark.slow
def test_v2_random_streams_vs_oracle(seed, batch):
    trace = synth_trace(seed=seed, n_ops=400, base="doc-order state v2 ")
    tt = tensorize(trace, batch=batch)
    eng = ReplayEngine(tt, n_replicas=2, resolver="scan", engine="v2")
    st = eng.run()
    want = _oracle_replay(trace)
    assert eng.decode(st, replica=0) == want
    assert eng.decode(st, replica=1) == want
    assert (np.asarray(st.nvis) == len(want)).all()


@pytest.mark.slow
def test_v2_matches_v1_on_svelte_prefix(svelte_trace):
    tt = tensorize(svelte_trace, batch=256)
    # replay only a prefix cheaply by truncating the tensorized stream
    import dataclasses

    n = 256 * 40
    tt = dataclasses.replace(
        tt,
        kind=tt.kind[:n], pos=tt.pos[:n], ch=tt.ch[:n], slot=tt.slot[:n],
        n_ops=n,
    )
    e1 = ReplayEngine(tt, n_replicas=1, resolver="scan", engine="v1")
    e2 = ReplayEngine(tt, n_replicas=1, resolver="scan", engine="v2")
    assert e2.decode(e2.run()) == e1.decode(e1.run())


@pytest.mark.slow
def test_v2_pack_invariance():
    trace = synth_trace(seed=11, n_ops=300, base="packing")
    tt = tensorize(trace, batch=32)
    outs = []
    for pack in (1, 2, 8):
        eng = ReplayEngine(
            tt, n_replicas=1, resolver="scan", engine="v2", pack=pack
        )
        outs.append(eng.decode(eng.run()))
    assert outs[0] == outs[1] == outs[2] == _oracle_replay(trace)


@pytest.mark.parametrize("seed", range(3))
def test_expand_pallas_kernel_matches_xla(seed):
    from crdt_benches_tpu.ops.expand_pallas import expand_fill_zero

    rng = np.random.default_rng(seed)
    R, C, B = 2, 384, 25
    order = rng.integers(0, 1000, size=(R, C)).astype(np.int32)
    vis = rng.integers(0, 2, size=(R, C)).astype(np.int32)
    ind = np.zeros((R, C), np.int32)
    for row in range(R):
        ind[row, rng.choice(C, size=B, replace=False)] = 1
    cnt = np.cumsum(ind, axis=1).astype(np.int32)
    o1, v1 = expand_fill_zero(
        jnp.asarray(order), jnp.asarray(vis), jnp.asarray(cnt),
        jnp.asarray(ind), nbits=6, interpret=True,
    )
    o2, v2 = _expand([jnp.asarray(order), jnp.asarray(vis)],
                     jnp.asarray(cnt), 6)
    hole = ind != 0
    np.testing.assert_array_equal(np.asarray(o1), np.where(hole, 0, np.asarray(o2)))
    np.testing.assert_array_equal(np.asarray(v1), np.where(hole, 0, np.asarray(v2)))


@pytest.mark.parametrize("seed", [0, 3])
@pytest.mark.slow
def test_v3_packed_matches_v2_and_oracle(seed):
    trace = synth_trace(seed=seed, n_ops=350, base="packed state v3 ")
    tt = tensorize(trace, batch=32)
    e2 = ReplayEngine(tt, n_replicas=2, resolver="scan", engine="v2")
    e3 = ReplayEngine(tt, n_replicas=2, resolver="scan", engine="v3")
    want = _oracle_replay(trace)
    assert e2.decode(e2.run()) == want
    st3 = e3.run()
    assert e3.decode(st3, replica=0) == want
    assert e3.decode(st3, replica=1) == want


@pytest.mark.parametrize("batch", [2048])
@pytest.mark.slow
def test_v3_large_batch_sort_rank_path(batch):
    # Exercises the argsort dest path (B > 1024) and hierarchical searchsorted.
    trace = synth_trace(seed=21, n_ops=3000, base="large batch " * 4)
    tt = tensorize(trace, batch=batch)
    eng = ReplayEngine(tt, n_replicas=1, resolver="scan", engine="v3", pack=1)
    assert eng.decode(eng.run()) == _oracle_replay(trace)


def test_spread_fill_combo_wide_capacity():
    # Capacities beyond 2^21 engage the fourth fill chunk: combo must be
    # exactly (fill << 1) | 1 at each destination, 0 elsewhere, including
    # fills whose high bits live in chunk 3 (slots near the top).
    import jax.numpy as jnp

    from crdt_benches_tpu.ops.apply2 import pack_doc, spread_fill_combo

    C = (1 << 21) + 1024  # wide but small enough for a CPU test
    slots = jnp.asarray([0, 5, (1 << 21) - 3, (1 << 21) + 500], jnp.int32)
    vis = jnp.asarray([1, 0, 1, 1], jnp.int32)
    fill = pack_doc(slots, vis)[None, :]
    dest = jnp.asarray([[7, 129, 4096, C - 1]], jnp.int32)
    combo, cnt_base = spread_fill_combo(dest, fill, C)
    combo = np.asarray(combo)[0]
    want = np.zeros(C, np.int64)
    for d, f in zip(np.asarray(dest)[0], np.asarray(fill)[0]):
        want[d] = (int(f) << 1) | 1
    assert (combo == want).all()
    # count base: one destination in tile 0, one in tile 1, one in tile 32
    cb = np.asarray(cnt_base)[0]
    assert cb[0] == 0 and cb[1] == 1 and cb[2] == 2 and cb[33] == 3
