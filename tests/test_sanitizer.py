"""The sync sanitizer (lint/sanitizer.py): the runtime proof of the
static G002 fence model.

Covers the three contract points ISSUE 5 names: an undeclared host sync
on the hot path raises at its callsite; a drain whose every sync sits
behind declared fences passes with the sanitizer armed; and the
per-fence counters the serve bench emits (``boundary_syncs``) are in
parity with the sanitizer's own tables — including that every observed
sync attributes to a fence that exists in the STATIC fence graph (the
set graftlint's G011 accounts against).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import jax.numpy as jnp

from crdt_benches_tpu.lint import sanitizer
from crdt_benches_tpu.lint.core import build_index
from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.bench import run_serve_bench

#: same tiny two-class sizing as tests/test_serve.py: docs span both
#: classes, the drain stays a few thousand unit ops
TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_SYNCS", "1")
    sanitizer.reset_counters()
    yield
    sanitizer.reset_counters()


def _device_array():
    return jnp.arange(16, dtype=jnp.int32)


def test_undeclared_sync_raises_at_callsite(armed):
    """Every modeled sync surface trips the sanitizer when no declared
    fence is active — np.asarray (the CPU buffer-protocol funnel the
    native transfer guard cannot see), scalar pulls, item/tolist, and
    block_until_ready."""
    x = _device_array()
    for label, sync in [
        ("np.asarray", lambda: np.asarray(x)),
        ("np.array", lambda: np.array(x)),
        ("item", lambda: x[0].item()),
        ("tolist", lambda: x.tolist()),
        ("int", lambda: int(x[1])),
        ("float", lambda: float(x[2])),
        ("block_until_ready", lambda: x.block_until_ready()),
    ]:
        with pytest.raises(sanitizer.UndeclaredSyncError):
            with sanitizer.hot_path():
                sync()
        # the same sync OUTSIDE the hot scope is ordinary host traffic
        sync()


def test_declared_fence_allows_and_attributes(armed):
    x = _device_array()
    with sanitizer.hot_path():
        with sanitizer.fence("test.boundary"):
            np.asarray(x)
            x.block_until_ready()
    c = sanitizer.counters()
    assert c["entries"]["test.boundary"] == 1
    assert c["syncs"]["test.boundary"] == 2
    # innermost fence wins the attribution
    with sanitizer.hot_path():
        with sanitizer.fence("outer"):
            with sanitizer.fence("inner"):
                np.asarray(x)
    c = sanitizer.counters()
    assert c["syncs"].get("inner") == 1
    assert "outer" not in c["syncs"]


def test_unarmed_mode_counts_entries_only(monkeypatch):
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_SYNCS", raising=False)
    sanitizer.reset_counters()
    x = _device_array()
    with sanitizer.hot_path():  # no-op scope
        np.asarray(x)  # must NOT raise
    with sanitizer.fence("cheap.crossing"):
        pass
    assert sanitizer.counters()["entries"] == {"cheap.crossing": 1}


def test_fenced_decorator_keys_by_qualname(armed):
    class Pool:
        @sanitizer.fenced
        def pull(self):
            return np.asarray(_device_array())

    with sanitizer.hot_path():
        Pool().pull()
    c = sanitizer.counters()
    key = "test_fenced_decorator_keys_by_qualname.<locals>.Pool.pull"
    assert c["entries"][key] == 1 and c["syncs"][key] == 1


def _static_fence_qualnames() -> set[str]:
    import crdt_benches_tpu

    pkg = crdt_benches_tpu.__path__[0]
    index, errors = build_index([pkg])
    assert not errors
    return {
        fi.qualname
        for m in index.modules for fi in m.functions.values() if fi.fence
    }


def test_sanitized_drain_proves_the_fence_model(armed, tmp_path):
    """A full (tiny) serve drain under CRDT_BENCH_SANITIZE_SYNCS=1:
    completes verify-green (observed syncs are a subset of declared
    fences — an undeclared one would have raised), the artifact's
    boundary_syncs block is in exact parity with the sanitizer
    counters, and every runtime fence name exists in the static fence
    graph."""
    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=12, batch=16, macro_k=2, batch_chars=64,
        classes=(128, 512), slots=(8, 4), arrival_span=2,
        verify_sample=4, bands=TINY_BANDS, seed=7,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path), save_name="sanitized_smoke",
    )
    assert info["verify_ok"]
    block = r.extra["boundary_syncs"]
    assert block["sanitized"] is True
    live = sanitizer.counters()
    # parity with the artifact ON DISK, not just the in-memory result
    disk = json.loads((tmp_path / "sanitized_smoke.json").read_text())
    disk_block = disk[0]["extra"]["boundary_syncs"]
    assert disk_block == block
    assert block["entries"] == live["entries"]
    assert block["syncs"] == live["syncs"]
    static = _static_fence_qualnames()
    assert set(block["entries"]) <= static
    assert set(block["syncs"]) <= set(block["entries"])
    # the drain actually crossed the serving boundaries
    assert block["entries"].get("FleetScheduler._execute_moves")
    assert block["entries"].get("DocPool.block")
    assert sum(block["syncs"].values()) > 0


def test_unsanitized_drain_still_records_entries(monkeypatch, tmp_path):
    """The boundary_syncs entries block is ground truth in EVERY run
    (G011's food), not only under the sanitizer."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_SYNCS", raising=False)
    from crdt_benches_tpu.serve.pool import DocPool
    from crdt_benches_tpu.serve.scheduler import (
        FleetScheduler,
        prepare_streams,
    )
    from crdt_benches_tpu.serve.workload import build_fleet

    sanitizer.reset_counters()
    sessions = build_fleet(
        8, mix=TINY_MIX, seed=5, arrival_span=1, bands=TINY_BANDS
    )
    pool = DocPool(classes=(128, 512), slots=(6, 3),
                   spool_dir=str(tmp_path))
    streams = prepare_streams(sessions, pool, batch=16)
    sched = FleetScheduler(pool, streams, batch=16, macro_k=2)
    sched.run()
    assert sched.done
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)
    c = sanitizer.counters()
    assert c["entries"].get("FleetScheduler._execute_moves")
    assert c["entries"].get("DocPool.block") == 1
    assert c["syncs"] == {} or not sanitizer.sanitizing()
