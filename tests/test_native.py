"""Native tier differential tests: C++ rope and C++ CRDT vs the oracle,
byte-identical on real traces (SURVEY.md section 4 rebuild implication)."""

import numpy as np
import pytest

from crdt_benches_tpu.backends.native import (
    CppCrdt,
    CppCrdtDownstream,
    CppRope,
    native_available,
)
from crdt_benches_tpu.traces.patches import patch_arrays

pytestmark = pytest.mark.skipif(
    not native_available(), reason="libcrdtnative.so not built"
)


def test_rope_basic_ops():
    r = CppRope.from_str("hello")
    r.insert(5, " world")
    assert len(r) == 11
    r.remove(0, 1)
    assert r.content() == "ello world"
    r.replace(0, 4, "hi")
    assert r.content() == "hi world"


def test_crdt_basic_ops():
    c = CppCrdt.from_str("hello")
    c.insert(5, " world")
    c.remove(0, 1)
    c.replace(0, 4, "hi")
    assert c.content() == "hi world"
    assert len(c) == 8


@pytest.mark.parametrize("backend", [CppRope, CppCrdt])
def test_replay_svelte_byte_identical(svelte_trace, backend):
    pa = patch_arrays(svelte_trace)
    assert backend.replay_patches(pa) == len(svelte_trace.end_content)
    if backend is CppRope:
        assert CppRope.replay_patches_content(pa) == svelte_trace.end_content


@pytest.mark.parametrize("backend", [CppRope, CppCrdt])
@pytest.mark.slow
def test_replay_all_traces_length(request, backend):
    for fixture in ("rustcode_trace", "seph_trace", "automerge_trace"):
        trace = request.getfixturevalue(fixture)
        pa = patch_arrays(trace)
        assert backend.replay_patches(pa) == len(trace.end_content)


def test_crdt_content_after_replay(svelte_trace):
    """Replay through per-op API on a live object, then decode content."""
    # use a truncated trace for speed through the FFI path
    doc = CppCrdt.from_str(svelte_trace.start_content)
    want = list(svelte_trace.start_content)
    for i, (pos, d, ins) in enumerate(svelte_trace.iter_patches()):
        if i >= 2000:
            break
        doc.replace(pos, pos + d, ins)
        want[pos : pos + d] = list(ins)
    assert doc.content() == "".join(want)


def test_crdt_update_exchange_roundtrip():
    """Incremental encode_from -> apply_update replicates edits remotely."""
    a = CppCrdt.from_str("", agent=1)
    b = CppCrdt.from_str("", agent=2)
    watermark = 0
    for text, at in [("hello", 0), (" world", 5), ("!", 11)]:
        a.insert(at, text)
        update = a.encode_from(watermark)
        watermark = a.oplog_len()
        b.apply_update(update)
    a.remove(0, 1)
    b.apply_update(a.encode_from(watermark))
    assert b.content() == a.content() == "ello world!"


def test_crdt_update_idempotent_and_reordered():
    """CRDT convergence properties: duplicated and dropped-then-late updates
    must not corrupt the downstream (the fault-injection capability,
    SURVEY.md section 7 aux)."""
    a = CppCrdt.from_str("", agent=1)
    updates = []
    w = 0
    for ch in "abcdef":
        a.insert(len(a), ch)
        updates.append(a.encode_from(w))
        w = a.oplog_len()
    b = CppCrdt.from_str("", agent=2)
    # duplicate every update
    for u in updates:
        b.apply_update(u)
        b.apply_update(u)
    assert b.content() == "abcdef"
    # causally-premature update is dropped, then applied once dep arrives
    c = CppCrdt.from_str("", agent=3)
    c.apply_update(updates[1])  # 'b' depends on 'a' -> dropped
    assert c.content() == ""
    c.apply_update(updates[0])
    c.apply_update(updates[1])
    assert c.content() == "ab"


def test_downstream_apply_svelte(svelte_trace):
    down, updates = CppCrdtDownstream.upstream_updates(svelte_trace)
    assert len(updates) == len(svelte_trace)
    # native batch apply (the timed path)
    assert down.apply_all_native() == len(svelte_trace.end_content)
    # per-update python loop on a clone agrees (sample first 500)
    clone = down.clone()
    for u in updates[:500]:
        clone.apply_update(u)
    assert len(clone) > 0


def test_concurrent_same_origin_inserts_converge():
    """Two agents concurrently insert at the head; replicas applying the
    updates in opposite orders must converge to the same document (the RGA
    sibling tie-break, native/crdt.cpp integration_point)."""
    a = CppCrdt.from_str("", agent=1)
    b = CppCrdt.from_str("", agent=2)
    a.insert(0, "A")
    b.insert(0, "B")
    ua = a.encode_from(0)
    ub = b.encode_from(0)
    x = CppCrdt.from_str("", agent=10)
    y = CppCrdt.from_str("", agent=11)
    x.apply_update(ua); x.apply_update(ub)
    y.apply_update(ub); y.apply_update(ua)
    assert x.content() == y.content()
    assert sorted(x.content()) == ["A", "B"]


def test_concurrent_runs_interleave_convergently():
    """Concurrent multi-char runs from two agents interleave as contiguous
    blocks, identically regardless of apply order."""
    a = CppCrdt.from_str("", agent=1)
    b = CppCrdt.from_str("", agent=2)
    a.insert(0, "aaa")
    b.insert(0, "bbb")
    ua, ub = a.encode_from(0), b.encode_from(0)
    x = CppCrdt.from_str("", agent=10)
    y = CppCrdt.from_str("", agent=11)
    x.apply_update(ua); x.apply_update(ub)
    y.apply_update(ub); y.apply_update(ua)
    assert x.content() == y.content()
    assert x.content() in ("aaabbb", "bbbaaa")  # blocks stay contiguous
    # causally-later insert between: agent 3 saw both, inserts at pos 3
    z_src = CppCrdt.from_str("", agent=3)
    z_src.apply_update(ua); z_src.apply_update(ub)
    w = z_src.oplog_len()
    z_src.insert(3, "X")
    uz = z_src.encode_from(w)
    x.apply_update(uz); y.apply_update(uz)
    assert x.content() == y.content()
    assert x.content()[3] == "X"


def test_downstream_nonempty_start_content():
    """Regression: the downstream replica must share the upstream's init
    element ids (agent mismatch silently dropped every update that referenced
    start-content chars — caught only because all four real traces start
    empty)."""
    from crdt_benches_tpu.traces.loader import TestData, TestTxn, TestPatch

    trace = TestData(
        "hello world", "helXo wrld!",
        [TestTxn("", [TestPatch(3, 1, "X"), TestPatch(7, 1, ""),
                      TestPatch(10, 0, "!")])],
    )
    down, updates = CppCrdtDownstream.upstream_updates(trace)
    assert down.apply_all_native() == len(trace.end_content)
    assert down.content() == trace.end_content
    # per-update path too
    down2, _ = CppCrdtDownstream.upstream_updates(trace)
    for u in updates:
        down2.apply_update(u)
    assert down2.content() == trace.end_content


def test_byte_offset_rope_backend():
    """Byte-addressed rope (EDITS_USE_BYTE_OFFSETS capability, reference
    cola/yrs adapters): non-ASCII edits addressed in UTF-8 byte units."""
    from crdt_benches_tpu.backends.native import CppRopeBytes

    r = CppRopeBytes.from_str("héllo")  # é = 2 bytes -> 6 bytes total
    assert len(r) == 6
    r.insert(3, "X")  # after the 2-byte é
    assert r.content() == "héXllo"
    r.remove(1, 3)  # delete the é (bytes 1..2)
    assert r.content() == "hXllo"


def test_byte_offset_replay_rustcode(rustcode_trace):
    """Full rustcode replay in byte units (the trace with mid-stream
    non-ASCII chars, SURVEY.md section 3.4) through the runner's byte path."""
    from crdt_benches_tpu.backends.native import CppRopeBytes
    from crdt_benches_tpu.traces.patches import patch_arrays

    pa = patch_arrays(rustcode_trace.chars_to_bytes(), bytes_mode=True)
    n = CppRopeBytes.replay_patches(pa)
    assert n == pa.end_len == len(rustcode_trace.end_content.encode("utf-8"))


def test_byte_offset_crdt_backend():
    """Byte-addressed CRDT (the yrs capability: a full sequence CRDT with
    UTF-8 byte offsets, reference src/rope.rs:139-183)."""
    from crdt_benches_tpu.backends.native import CppCrdtBytes

    r = CppCrdtBytes.from_str("héllo")
    assert len(r) == 6
    r.insert(3, "X")
    assert r.content() == "héXllo"
    r.remove(1, 3)
    assert r.content() == "hXllo"


@pytest.mark.slow
def test_byte_offset_crdt_replay_rustcode(rustcode_trace):
    """Full rustcode replay in byte units through the CRDT engine,
    byte-identical to the oracle (stricter than the reference's
    length-only assert, src/main.rs:35)."""
    from crdt_benches_tpu.backends.native import CppCrdtBytes
    from crdt_benches_tpu.traces.patches import patch_arrays

    pa = patch_arrays(rustcode_trace.chars_to_bytes(), bytes_mode=True)
    n = CppCrdtBytes.replay_patches(pa)
    assert n == pa.end_len == len(rustcode_trace.end_content.encode("utf-8"))

    doc = CppCrdtBytes.from_str(rustcode_trace.start_content)
    t = rustcode_trace.chars_to_bytes()
    for pos, d, ins in t.iter_patches():
        if d:
            doc.remove(pos, pos + d)
        if ins:
            doc.insert(pos, ins)
    assert doc.content() == rustcode_trace.end_content


def test_cola_content_free_basic():
    """Lengths-only replica (the cola capability, reference
    src/rope.rs:79-101): seeded from a byte LENGTH, edits are
    (offset, length), readback is len() only — content() is None."""
    from crdt_benches_tpu.backends.native import CppCola

    r = CppCola.from_str("héllo")  # 6 bytes
    assert len(r) == 6
    assert r.content() is None
    r.insert(3, "XY")
    assert len(r) == 8
    r.remove(1, 4)
    assert len(r) == 5
    r.replace(0, 2, "abc")  # trait-default replace: remove + insert
    assert len(r) == 6


def test_cola_random_differential_lengths():
    """Randomized edit sequence vs a Python shadow byte-list: every
    intermediate length must agree (the only observable of a
    content-free replica)."""
    import numpy as np

    from crdt_benches_tpu.backends.native import CppCola

    rng = np.random.default_rng(7)
    r = CppCola.from_str("x" * 40)
    shadow = 40
    for _ in range(3000):
        if shadow and rng.integers(3) == 0:
            a = int(rng.integers(shadow))
            b = int(rng.integers(a, min(shadow, a + 12) + 1))
            r.remove(a, b)
            shadow -= b - a
        else:
            at = int(rng.integers(shadow + 1))
            n = int(rng.integers(1, 9))
            r.insert(at, "y" * n)
            shadow += n
        assert len(r) == shadow


@pytest.mark.slow
def test_cola_replay_all_traces_length(request):
    """Full four-trace replay through the one-call native path, in UTF-8
    byte units (the runner's EDITS_USE_BYTE_OFFSETS path), asserting the
    end length — exactly the observable the reference's cola bench
    asserts (src/main.rs:35)."""
    from crdt_benches_tpu.backends.native import CppCola
    from crdt_benches_tpu.traces.patches import patch_arrays

    for fixture in (
        "svelte_trace", "rustcode_trace", "seph_trace", "automerge_trace"
    ):
        trace = request.getfixturevalue(fixture)
        pa = patch_arrays(trace.chars_to_bytes(), bytes_mode=True)
        assert CppCola.replay_patches(pa) == pa.end_len == len(
            trace.end_content.encode("utf-8")
        )


def test_coalesced_stream_native_replay_byte_identical(svelte_trace):
    """The RLE-coalesced patch stream (traces/tensorize.py
    coalesce_patches) replayed through the native engines is
    byte-identical — the guarantee behind the stream-symmetric headline
    baseline (bench.py feeds cpp-crdt the same coalesced stream the JAX
    range engine replays)."""
    from crdt_benches_tpu.backends.native import CppCrdt, CppRope
    from crdt_benches_tpu.traces.patches import patch_arrays
    from crdt_benches_tpu.traces.tensorize import coalesce_patches

    patches = list(coalesce_patches(svelte_trace))
    assert len(patches) < len(svelte_trace)  # RLE actually coalesced
    pa = patch_arrays(svelte_trace, patches=patches)
    assert CppCrdt.replay_patches(pa) == len(svelte_trace.end_content)
    assert (
        CppRope.replay_patches_content(pa) == svelte_trace.end_content
    )
