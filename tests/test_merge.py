"""Concurrent multi-agent merge: determinism, convergence, idempotence, and
delivery-order independence (the CRDT properties the reference never tests —
SURVEY.md section 4 — plus fault injection per section 5)."""

import numpy as np
import pytest

from crdt_benches_tpu.engine.merge import (
    MergeSimulation,
    OpLog,
    merge_oracle,
)
from crdt_benches_tpu.traces.tensorize import DELETE, INSERT

from test_engine import tensorize_ops

A = ord("a")


def make_stream(rng, base: str, n_ops: int, batch: int = 8):
    """A random local edit stream (unit ops) starting from ``base``."""
    from crdt_benches_tpu.traces.synth import random_patches
    from crdt_benches_tpu.traces.tensorize import tensorize
    from crdt_benches_tpu.traces.loader import TestData, TestTxn

    patches, _ = random_patches(rng, n_ops, len(base))
    return tensorize(TestData(base, "", [TestTxn("", patches)]), batch=batch)


def sim_for(seed: int, n_agents: int, n_ops: int, base: str = "base text",
            batch: int = 16) -> MergeSimulation:
    rng = np.random.default_rng(seed)
    streams = [make_stream(rng, base, n_ops, batch=batch)
               for _ in range(n_agents)]
    return MergeSimulation(streams, base=base, batch=batch)


def shuffled_log(log: OpLog, rng) -> OpLog:
    perm = rng.permutation(len(log))
    return OpLog(*(getattr(log, f)[perm] for f in
                   ("lamport", "agent", "kind", "elem", "origin", "ch")))


@pytest.mark.slow
def test_single_agent_matches_local_replay():
    """With one agent, merging its op log must reproduce its local edit."""
    from crdt_benches_tpu.oracle import replay_unit_ops

    base = "hello"
    tt = tensorize_ops(
        [INSERT, INSERT, DELETE, INSERT],
        [5, 0, 2, 3],
        [A, A + 1, 0, A + 2],
        start=base,
    )
    want = replay_unit_ops(
        tt.kind[: tt.n_ops], tt.pos[: tt.n_ops], tt.ch[: tt.n_ops], start=base
    )
    sim = MergeSimulation([tt], base=base, batch=8)
    got = sim.decode(sim.merge())
    assert got == want


@pytest.mark.slow
def test_two_agents_deterministic_vs_oracle():
    sim = sim_for(seed=0, n_agents=2, n_ops=20)
    state = sim.merge()
    got = sim.decode(state)
    want = merge_oracle(sim.log, "base text", np.asarray(sim.chars))
    assert got == want


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.slow
def test_random_agents_vs_oracle(seed):
    sim = sim_for(seed=seed, n_agents=3, n_ops=40)
    got = sim.decode(sim.merge())
    want = merge_oracle(sim.log, "base text", np.asarray(sim.chars))
    assert got == want


@pytest.mark.slow
def test_delivery_order_independence():
    """Fault injection: shuffled delivery must converge to the same doc."""
    sim = sim_for(seed=1, n_agents=3, n_ops=30)
    rng = np.random.default_rng(7)
    want = sim.decode(sim.merge())
    for _ in range(3):
        got = sim.decode(sim.merge(shuffled_log(sim.log, rng)))
        assert got == want


@pytest.mark.slow
def test_duplicated_delivery_idempotent():
    """Fault injection: every update delivered twice -> same doc."""
    sim = sim_for(seed=2, n_agents=2, n_ops=25)
    want = sim.decode(sim.merge())
    dup = OpLog.concat([sim.log, sim.log])
    rng = np.random.default_rng(3)
    got = sim.decode(sim.merge(shuffled_log(dup, rng)))
    assert got == want


@pytest.mark.slow
def test_batch_size_independence():
    """The same op set merged with different batch sizes must agree (batch
    boundaries are an implementation detail, not semantics)."""
    rng = np.random.default_rng(5)
    base = "shared"
    streams16 = [make_stream(rng, base, 30, batch=16) for _ in range(2)]
    sim16 = MergeSimulation(streams16, base=base, batch=16)
    sim4 = MergeSimulation(streams16, base=base, batch=4)
    assert sim16.decode(sim16.merge()) == sim4.decode(sim4.merge())


@pytest.mark.slow
def test_empty_base_concurrent_typing():
    """Two agents typing concurrently from an empty doc: both texts survive
    in full, in a deterministic interleaving."""
    t1 = tensorize_ops([INSERT] * 3, [0, 1, 2], [ord(c) for c in "abc"])
    t2 = tensorize_ops([INSERT] * 3, [0, 1, 2], [ord(c) for c in "xyz"])
    sim = MergeSimulation([t1, t2], base="", batch=8)
    got = sim.decode(sim.merge())
    assert sorted(got) == sorted("abcxyz")
    # each agent's text must appear in order (RGA preserves intention)
    def subseq(s, t):
        it = iter(t)
        return all(c in it for c in s)
    assert subseq("abc", got) and subseq("xyz", got)
    want = merge_oracle(sim.log, "", np.asarray(sim.chars))
    assert got == want


def test_concurrent_delete_same_element():
    """Both agents delete the same base char: tombstone once (commutes)."""
    base = "abcd"
    t1 = tensorize_ops([DELETE], [1], [0], start=base)
    t2 = tensorize_ops([DELETE, INSERT], [1, 2], [0, ord("Z")], start=base)
    sim = MergeSimulation([t1, t2], base=base, batch=8)
    got = sim.decode(sim.merge())
    want = merge_oracle(sim.log, base, np.asarray(sim.chars))
    assert got == want
    assert "b" not in got and "Z" in got


@pytest.mark.slow
def test_sharded_merge_divergent_replicas_converge():
    """8 divergent replicas (one agent each) sharded over the 8-device CPU
    mesh: all_gather the op logs, every replica integrates the union, all
    digests agree, and the content matches the single-device merge."""
    import jax.numpy as jnp

    from crdt_benches_tpu.parallel.mesh import (
        replica_mesh,
        sharded_merge_and_converge,
    )

    sim = sim_for(seed=9, n_agents=8, n_ops=12, base="mesh base", batch=16)
    logs = sim.stacked_logs()
    mesh = replica_mesh(8)
    step = sharded_merge_and_converge(
        mesh, sim.capacity, sim.n_base, batch=16
    )
    states, digests, converged = step(
        jnp.asarray(logs["lamport"]),
        jnp.asarray(logs["agent"]),
        jnp.asarray(logs["kind"]),
        jnp.asarray(logs["elem"]),
        jnp.asarray(logs["origin"]),
        jnp.asarray(logs["ch"]),
        sim.chars,
    )
    assert bool(np.asarray(converged))
    d = np.asarray(digests)
    assert (d == d[0]).all()
    # content identical to the one-replica merge of the same union
    import jax

    st0 = jax.tree.map(lambda x: x[0], states)
    assert sim.decode(st0) == sim.decode(sim.merge())
