"""serve/ document-fleet engine: byte-exact multi-tenant serving.

Every test's ground truth is oracle/text_oracle.py replaying the same
per-doc stream — the correctness gate of the serve subsystem: documents
hosted in shared bucketed device states, churned through checkpoint
eviction/restore and capacity-class promotion, must finish byte-identical
to an uninterrupted single-doc replay.
"""

import os

import numpy as np
import pytest

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import FleetScheduler, prepare_streams
from crdt_benches_tpu.serve.workload import (
    Session,
    build_fleet,
    trace_prefix,
)

#: tiny band table: docs span both test classes (128 / 512) while the
#: whole fleet stays a few thousand unit ops.
TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


def _drain(sessions, pool, batch=16):
    streams = prepare_streams(sessions, pool, batch=batch)
    sched = FleetScheduler(pool, streams, batch=batch)
    stats = sched.run()
    assert sched.done
    return stats


def test_fleet_all_docs_byte_identical_under_churn(tmp_path):
    """24 docs through 12 rows: admission churn (evict + restore) and
    medium docs promoted 128 -> 512 mid-replay, every doc oracle-exact."""
    sessions = build_fleet(
        24, mix=TINY_MIX, seed=3, arrival_span=3, bands=TINY_BANDS
    )
    pool = DocPool(classes=(128, 512), slots=(8, 4),
                   spool_dir=str(tmp_path))
    stats = _drain(sessions, pool)
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace), (
            f"doc {s.doc_id} ({s.band}) diverged from oracle"
        )
    # the point of the sizing: the policies actually ran
    assert stats.evictions > 0 and stats.restores > 0
    assert stats.promotions > 0
    # per-round telemetry lives in O(buckets) histograms now: every
    # round classified exactly once (steady vs compile/barrier-skipped)
    assert stats.rounds == stats.lat_steady.count + stats.lat_skipped.count
    scratch = DocPool(classes=(512,), slots=(4,),
                      spool_dir=str(tmp_path / "scratch"))
    assert stats.ops == sum(
        len(st.kind) for st in
        prepare_streams(sessions, scratch, batch=16).values()
    )
    assert stats.occupancy.count == stats.rounds
    assert 0.0 < stats.occupancy.vmin and stats.occupancy.vmax <= 1.0


def test_real_trace_prefix_sessions_oracle(tmp_path):
    """Folded real-trace windows (incl. sveltecomponent's pasted opener
    folded into start_content) serve byte-exactly next to synth docs."""
    tr_small = trace_prefix("automerge-paper", 240)
    tr_med = trace_prefix("sveltecomponent", 1000)
    assert len(tr_med.start_content) > 0  # the fold actually happened
    sessions = build_fleet(
        4, mix=TINY_MIX, seed=11, arrival_span=1, bands=TINY_BANDS
    )
    nxt = len(sessions)
    sessions += [
        Session(doc_id=nxt, band="trace-small", source="automerge-paper",
                trace=tr_small),
        Session(doc_id=nxt + 1, band="trace-medium",
                source="sveltecomponent", trace=tr_med),
    ]
    pool = DocPool(classes=(256, 1024), slots=(4, 2),
                   spool_dir=str(tmp_path))
    _drain(sessions, pool)
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)


def test_checkpoint_roundtrip_evict_into_different_row(tmp_path):
    """The satellite case: evict a doc mid-replay through the checkpoint
    spool, restore it into a DIFFERENT bucket row, finish the replay —
    byte-identical to an uninterrupted replay of the same stream."""
    from crdt_benches_tpu.traces.synth import synth_trace

    traces = [synth_trace(seed=100 + i, n_ops=80) for i in range(3)]
    sessions = [
        Session(doc_id=i, band="synth-small", source="synth", trace=t)
        for i, t in enumerate(traces)
    ]
    pool = DocPool(classes=(128,), slots=(2,), spool_dir=str(tmp_path))
    streams = prepare_streams(sessions, pool, batch=16)
    sched = FleetScheduler(pool, streams, batch=16)

    # run a couple of rounds, then force doc 0 out mid-replay
    sched.run(max_rounds=2)
    rec0 = pool.docs[0]
    assert streams[0].cursor > 0 and streams[0].remaining > 0
    if rec0.cls is None:  # ensure doc 0 is resident so we can evict it
        if not pool.buckets[128].free:
            pool.evict(pool.residents(128)[0][0])
        pool.admit(0, need=rec0.length)
    row_before = rec0.row
    spool = pool.evict(0)
    assert os.path.exists(spool) and rec0.spool == spool
    assert rec0.cls is None

    # occupy the freed row with a non-resident doc (the free list is
    # LIFO, so it lands exactly in doc 0's old row), then make room in
    # the OTHER row — doc 0 must rehydrate into a different slot
    other = next(d for d in (1, 2) if pool.docs[d].cls is None)
    assert pool.admit(other, need=pool.docs[other].length)[1] == row_before
    for d, _row in pool.residents(128):
        if pool.docs[d].row != row_before:
            pool.evict(d)
    cls, row_after = pool.admit(0, need=rec0.length)
    assert (cls, row_after) != (128, row_before), (
        "test setup: doc 0 restored into its old slot; churn not exercised"
    )

    sched.run()  # drain the rest
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)
    assert pool.restores >= 1


def test_mesh_fleet_matches_unsharded(tmp_path):
    """Docs-over-mesh: the same fleet sharded over the 8 virtual CPU
    devices (parallel/mesh.py) decodes identically to the single-device
    run, and both match the oracle."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")
    from crdt_benches_tpu.parallel.mesh import replica_mesh

    sessions = build_fleet(
        12, mix={"synth-small": 1.0}, seed=5, arrival_span=2,
        bands=TINY_BANDS,
    )

    def run(mesh, sub):
        pool = DocPool(classes=(128,), slots=(8,), mesh=mesh,
                       spool_dir=str(tmp_path / sub))
        _drain(sessions, pool)
        return {s.doc_id: pool.decode(s.doc_id) for s in sessions}

    plain = run(None, "plain")
    sharded = run(replica_mesh(8), "mesh")
    assert plain == sharded
    for s in sessions:
        assert plain[s.doc_id] == replay_trace(s.trace)


def test_pool_rejects_bad_config(tmp_path):
    with pytest.raises(ValueError):
        # the point IS the bad class: G008 now catches it statically too
        DocPool(classes=(100,), slots=(4,))  # graftlint: disable=G008
    with pytest.raises(ValueError):
        DocPool(classes=(512, 128), slots=(2, 2))  # not ascending
    pool = DocPool(classes=(128,), slots=(2,), spool_dir=str(tmp_path))
    with pytest.raises(ValueError):
        pool.register(0, n_init=0, capacity_need=4096,
                      chars=np.zeros(4096, np.int32))  # beyond largest


def test_build_fleet_deterministic_and_weighted():
    a = build_fleet(40, mix=TINY_MIX, seed=9, bands=TINY_BANDS)
    b = build_fleet(40, mix=TINY_MIX, seed=9, bands=TINY_BANDS)
    assert [(s.band, s.arrival, len(s.trace)) for s in a] == [
        (s.band, s.arrival, len(s.trace)) for s in b
    ]
    assert {s.band for s in a} == set(TINY_MIX)
    with pytest.raises(ValueError):
        build_fleet(4, mix={"synth-small": -1.0}, bands=TINY_BANDS)


def test_serve_bench_smoke(tmp_path):
    """The bench family end to end at toy scale: artifact written with
    throughput + latency quantiles, in-run verification green."""
    import json

    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=16, batch=16,
        classes=(128, 512), slots=(8, 4), seed=2, arrival_span=2,
        verify_sample=4, bands=TINY_BANDS,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    assert r.bench_id == "serve/custom/16"
    with open(info["path"]) as f:
        (d,) = json.load(f)
    assert d["group"] == "serve" and d["elements"] > 0
    lat = d["extra"]["batch_latency"]
    assert set(lat) == {"p50", "p95", "p99"}
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"]
    assert d["extra"]["verify_ok"] is True
    assert d["elements_per_sec"] > 0
    # the sample spans every class that hosted docs
    hosted = set(d["extra"]["docs_per_class"])
    assert len(d["extra"]["verified_docs"]) >= min(
        4, sum(d["extra"]["docs_per_class"].values())
    )
    assert hosted  # at least one class in use


@pytest.mark.slow
def test_fleet_moderate_scale(tmp_path):
    """Full-gate scale: 256 docs over three classes with real-trace
    windows in the mix; a 24-doc sample (every class) oracle-verified."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix={
            "synth-small": 0.36, "synth-medium": 0.12, "synth-large": 0.06,
            "trace-small": 0.21, "trace-medium": 0.15, "trace-large": 0.10,
        },
        n_docs=256, batch=32,
        classes=(256, 1024, 4096), slots=(64, 24, 12), seed=1,
        arrival_span=4, verify_sample=24,
        bands={
            "synth-small": ("synth", (24, 160)),
            "synth-medium": ("synth", (320, 900)),
            "synth-large": ("synth", (1400, 3400)),
            "trace-small": ("trace", (240, None)),
            "trace-medium": ("trace", (1000, None)),
            "trace-large": ("trace", (3900, None)),
        },
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    assert len(r.extra["docs_per_class"]) == 3
