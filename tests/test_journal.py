"""Write-ahead journal + snapshot barriers + crash recovery.

The recovery contract under test: after ANY crash point, restoring the
last consistent snapshot set and replaying the journal tail through the
normal macro-round path yields final documents byte-identical to an
uninterrupted run — and to the oracle."""

import json
import os
import zlib

import numpy as np

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.journal import (
    OpJournal,
    list_snapshots,
    read_journal,
    recover_fleet,
)
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import FleetScheduler, prepare_streams
from crdt_benches_tpu.serve.workload import Session, build_fleet, trace_prefix

TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


def _sessions():
    """Synth + real-trace docs so recovery spans capacity classes."""
    sessions = build_fleet(
        10, mix=TINY_MIX, seed=7, arrival_span=3, bands=TINY_BANDS
    )
    nxt = len(sessions)
    sessions += [
        Session(doc_id=nxt, band="trace-small", source="automerge-paper",
                trace=trace_prefix("automerge-paper", 240), arrival=1),
        Session(doc_id=nxt + 1, band="trace-medium",
                source="sveltecomponent",
                trace=trace_prefix("sveltecomponent", 500)),
    ]
    return sessions


def _fresh(sessions, tmp_path, sub):
    pool = DocPool(classes=(256, 1024), slots=(6, 3),
                   spool_dir=str(tmp_path / sub))
    streams = prepare_streams(sessions, pool, batch=16, batch_chars=64)
    return pool, streams


def test_journal_records_crc_framed_and_torn_tail(tmp_path):
    """Records round-trip; a torn tail (partial line, flipped bytes) is
    dropped at read time, never parsed into garbage."""
    jd = str(tmp_path / "j")
    j = OpJournal(jd)
    j.round_record(0, {256: [[1, 0, 16], [2, 0, 8]]})
    j.event("quarantine", r=3, doc=2, at=8, ops=5, reason="test")
    j.round_record(4, {256: [[1, 16, 32]]})
    j.close()
    recs, dropped = read_journal(jd)
    assert dropped == 0 and len(recs) == 3
    assert recs[0] == {"t": "round", "r": 0,
                       "lanes": {"256": [[1, 0, 16], [2, 0, 8]]}}
    assert recs[1]["t"] == "quarantine" and recs[1]["doc"] == 2

    # crash tear: a partial final line is dropped, the prefix survives
    with open(os.path.join(jd, "journal.log"), "a") as f:
        f.write('deadbeef {"t":"round","r":8')  # no newline, bad crc
    recs2, dropped2 = read_journal(jd)
    assert len(recs2) == 3 and dropped2 == 1

    # reopening for append TRUNCATES the torn tail first — records
    # appended behind a damaged line would be invisible to the next
    # recovery (readers stop at the first bad line)
    j2 = OpJournal(jd)
    j2.round_record(8, {256: [[1, 32, 40]]})
    j2.close()
    recs2b, dropped2b = read_journal(jd)
    assert dropped2b == 0 and len(recs2b) == 4
    assert recs2b[-1]["r"] == 8

    # mid-file damage: reading stops at the first bad line (append-only
    # discipline means everything after is suspect)
    path = os.path.join(jd, "journal.log")
    lines = open(path).readlines()
    payload = lines[1].split(" ", 1)[1].rstrip("\n")
    bad = f"{zlib.crc32(payload.encode()) ^ 1:08x} {payload}\n"
    with open(path, "w") as f:
        f.writelines([lines[0], bad] + lines[2:])
    recs3, dropped3 = read_journal(jd)
    assert len(recs3) == 1 and dropped3 >= 1


def test_snapshot_commit_is_atomic(tmp_path):
    """A staging directory without the final rename is invisible to
    recovery; committed snapshots are pruned by CHAIN to the keep
    count — a retained delta's base links always survive with it."""
    sessions = _sessions()
    pool, streams = _fresh(sessions, tmp_path, "p")
    jd = str(tmp_path / "j")
    sched = FleetScheduler(pool, streams, batch=16, macro_k=4,
                           batch_chars=64, journal=OpJournal(jd),
                           snapshot_every=1, snapshot_keep=2,
                           snapshot_full_every=2)
    sched.run(max_rounds=6)
    snaps = list_snapshots(jd)
    manifests = {
        s: json.load(open(os.path.join(jd, s, "MANIFEST.json")))
        for s in snaps
    }
    # pruned to keep=2 CHAINS (full_every=2 -> chains of <= 2 members)
    fulls = [s for s in snaps if manifests[s]["kind"] == "full"]
    assert 1 <= len(fulls) <= 2
    assert 1 <= len(snaps) <= 4
    # every retained delta's base link is retained with it and the
    # recorded CRC matches the base manifest on disk
    import zlib as _zlib
    for s in snaps:
        m = manifests[s]
        if m["kind"] != "delta":
            continue
        assert m["base"] in snaps, (s, m["base"], snaps)
        raw = open(
            os.path.join(jd, m["base"], "MANIFEST.json"), "rb"
        ).read()
        assert m["base_crc"] == f"{_zlib.crc32(raw):08x}"
        assert m["chain"] in snaps and manifests[m["chain"]]["kind"] \
            == "full"
    # a torn (uncommitted) staging dir must be ignored
    os.makedirs(os.path.join(jd, "snap_99999999.tmp"))
    assert "snap_99999999.tmp" not in list_snapshots(jd)
    m = manifests[snaps[-1]]
    assert set(m) >= {"round", "kind", "classes", "resident", "spooled",
                      "docs"}
    assert len(m["docs"]) == len(sessions)


def test_crash_recovery_parity_seeded_kill(tmp_path):
    """THE recovery gate (satellite): kill the fleet at a seeded random
    macro-round, recover from snapshot + journal into a FRESH pool, and
    drain — final documents are byte-identical to an uninterrupted run
    across capacity classes, and to the oracle."""
    sessions = _sessions()

    # ground truth: uninterrupted drain of the identical fleet
    pool_a, streams_a = _fresh(sessions, tmp_path, "a")
    FleetScheduler(pool_a, streams_a, batch=16, macro_k=4,
                   batch_chars=64).run()
    want = {s.doc_id: pool_a.decode(s.doc_id) for s in sessions}

    rng = np.random.default_rng(0xC0FFEE)
    # seeded random kill point, odd so the crash lands BETWEEN snapshot
    # barriers (snapshot_every=2) and leaves a real journal redo tail
    kill = 3 + 2 * int(rng.integers(0, 2))
    jd = str(tmp_path / "journal")
    pool_b, streams_b = _fresh(sessions, tmp_path, "b")
    jb = OpJournal(jd)
    sb = FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                        batch_chars=64, journal=jb, snapshot_every=2)
    sb.run(max_rounds=kill)
    assert not sb.done  # the crash interrupts real pending work
    del pool_b, streams_b, sb  # host state lost; disk survives

    # simulate a torn final append on top of the kill
    with open(os.path.join(jd, "journal.log"), "a") as f:
        f.write('0bad0bad {"t":"round"')

    pool_c, streams_c = _fresh(sessions, tmp_path, "c")
    rep = recover_fleet(pool_c, streams_c, jd)
    assert rep.torn_records >= 1
    assert rep.snapshot_round >= 0  # a barrier was used, not cold start
    assert rep.docs_restored + rep.spools_restored > 0
    # the WAL tip is ahead of the barrier: there is a real redo tail
    assert rep.ops_replayed > 0
    sc = FleetScheduler(pool_c, streams_c, batch=16, macro_k=4,
                        batch_chars=64, journal=OpJournal(jd),
                        snapshot_every=2, start_round=rep.resume_round)
    sc.run()
    assert sc.done
    hosted = set()
    for s in sessions:
        assert pool_c.decode(s.doc_id) == want[s.doc_id], (
            f"doc {s.doc_id} diverged after recovery"
        )
        assert want[s.doc_id] == replay_trace(s.trace)
        rec = pool_c.docs[s.doc_id]
        hosted.add(rec.cls or pool_c.class_for(max(rec.length, 1)))
    assert len(hosted) >= 2  # parity really spans capacity classes


def test_recovery_falls_back_on_damaged_snapshot(tmp_path):
    """A snapshot whose class state fails its CRC is skipped — recovery
    uses an older barrier (or a cold start) and parity still holds."""
    sessions = _sessions()
    pool_a, streams_a = _fresh(sessions, tmp_path, "a")
    FleetScheduler(pool_a, streams_a, batch=16, macro_k=4,
                   batch_chars=64).run()
    want = {s.doc_id: pool_a.decode(s.doc_id) for s in sessions}

    jd = str(tmp_path / "journal")
    pool_b, streams_b = _fresh(sessions, tmp_path, "b")
    sb = FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                        batch_chars=64, journal=OpJournal(jd),
                        snapshot_every=2)
    sb.run(max_rounds=5)
    del pool_b, streams_b, sb

    snaps = list_snapshots(jd)
    assert snaps
    newest = os.path.join(jd, snaps[-1])
    victim = next(
        os.path.join(newest, f) for f in sorted(os.listdir(newest))
        if f.startswith(("class_", "delta_")) or f == "MANIFEST.json"
    )
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        f.write(b"\xff" * 16)

    pool_c, streams_c = _fresh(sessions, tmp_path, "c")
    rep = recover_fleet(pool_c, streams_c, jd)
    assert rep.snapshot_round < int(snaps[-1].split("_")[1])
    assert rep.chain_fallbacks >= 1  # the damaged candidate was skipped
    sc = FleetScheduler(pool_c, streams_c, batch=16, macro_k=4,
                        batch_chars=64, start_round=rep.resume_round)
    sc.run()
    for s in sessions:
        assert pool_c.decode(s.doc_id) == want[s.doc_id]


def test_recovery_cold_start_without_journal(tmp_path):
    """No journal directory at all: recovery degrades to a cold start
    (streams are deterministic, the fleet rebuilds from nothing)."""
    sessions = build_fleet(
        6, mix=TINY_MIX, seed=9, arrival_span=2, bands=TINY_BANDS
    )
    pool, streams = _fresh(sessions, tmp_path, "p")
    rep = recover_fleet(pool, streams, str(tmp_path / "nonexistent"))
    assert rep.snapshot_round == -1 and rep.resume_round == 0
    FleetScheduler(pool, streams, batch=16, macro_k=4,
                   batch_chars=64).run()
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)
