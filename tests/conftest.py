"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on host CPU devices instead (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).

This environment's sitecustomize registers the single-client axon TPU plugin
in every python process and force-overrides the ``jax_platforms`` config to
"axon,cpu", so env vars alone cannot keep tests off the TPU.  Overriding the
config again here — before any backend is initialized — reliably pins tests
to CPU (a second TPU client would deadlock against any concurrently running
jax process, and TPU compiles are far too slow for this many test shapes).
Set CRDT_TPU_TESTS=1 to opt out and run tests on the real chip (serially,
with nothing else using it).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("CRDT_TPU_TESTS") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from crdt_benches_tpu.traces import load_testing_data  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_per_module():
    """Drop compiled executables after each test module: a full-suite run
    in one process otherwise accumulates enough XLA CPU compile state to
    segfault mid-run (round-2 verdict, weak #2)."""
    yield
    import jax

    jax.clear_caches()


@pytest.fixture(scope="session")
def svelte_trace():
    return load_testing_data("sveltecomponent")


@pytest.fixture(scope="session")
def rustcode_trace():
    return load_testing_data("rustcode")


@pytest.fixture(scope="session")
def seph_trace():
    return load_testing_data("seph-blog1")


@pytest.fixture(scope="session")
def automerge_trace():
    return load_testing_data("automerge-paper")
