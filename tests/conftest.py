"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on host CPU devices instead (the driver separately dry-run-compiles
the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os
import sys

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import pytest  # noqa: E402

from crdt_benches_tpu.traces import load_testing_data  # noqa: E402


@pytest.fixture(scope="session")
def svelte_trace():
    return load_testing_data("sveltecomponent")


@pytest.fixture(scope="session")
def rustcode_trace():
    return load_testing_data("rustcode")


@pytest.fixture(scope="session")
def seph_trace():
    return load_testing_data("seph-blog1")


@pytest.fixture(scope="session")
def automerge_trace():
    return load_testing_data("automerge-paper")
