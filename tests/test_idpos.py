"""Differential tests for the epoch id->position structure (ops/idpos.py)
against a direct NumPy document simulation."""

import numpy as np
import jax.numpy as jnp

from crdt_benches_tpu.ops.idpos import (
    make_level,
    query,
    snap_init,
    snap_rebuild,
)
from crdt_benches_tpu.ops.apply2 import pack_doc


def _sim_insert(doc: list[int], dests: list[tuple[int, int]]):
    """Insert (dest, slot) pairs (dests are post-batch positions)."""
    for d, s in sorted(dests):
        doc.insert(d, s)


def test_query_matches_simulation():
    rng = np.random.default_rng(7)
    R, B, K = 2, 16, 5
    n_init = 40
    docs = [list(range(n_init)) for _ in range(R)]
    C = 512

    snap = snap_init(R, C)
    levels = []
    next_slot = n_init
    for k in range(K):
        # check queries against the simulation BEFORE this batch
        present = [
            rng.choice(len(docs[0]) and docs[0] or [0], B)
            for _ in range(R)
        ]
        ids = np.stack([np.asarray(p, np.int32) for p in present])
        got = np.asarray(query(snap, levels, jnp.asarray(ids)))
        for r in range(R):
            for b in range(B):
                assert docs[r][got[r, b]] == ids[r, b], (k, r, b)

        # random insert batch (same across replicas, like a shared stream)
        n_ins = int(rng.integers(1, B))
        gaps = np.sort(rng.integers(0, len(docs[0]) + 1, n_ins))
        # post-batch destinations: gap + #earlier inserts at smaller-or-equal
        # gaps that land before it = gap_i + i for sorted gaps
        dests = gaps + np.arange(n_ins)
        slots = np.arange(next_slot, next_slot + n_ins, dtype=np.int32)
        next_slot += n_ins

        is_ins = np.zeros((R, B), bool)
        is_ins[:, :n_ins] = True
        dest_arr = np.zeros((R, B), np.int32)
        dest_arr[:, :n_ins] = dests
        slot_arr = np.full((R, B), -1, np.int32)
        slot_arr[:, :n_ins] = slots
        levels.append(
            make_level(
                jnp.asarray(dest_arr), jnp.asarray(is_ins),
                jnp.asarray(slot_arr),
            )
        )
        for r in range(R):
            _sim_insert(docs[r], list(zip(dests.tolist(), slots.tolist())))

        # same-epoch ids (just inserted) must also resolve
        got2 = np.asarray(
            query(snap, levels, jnp.asarray(slot_arr))
        )
        for r in range(R):
            for b in range(n_ins):
                assert docs[r][got2[r, b]] == slot_arr[r, b]

    # epoch boundary: rebuild snap from the packed doc and drop levels
    doc_arr = np.full((R, C), -1, np.int32)
    for r in range(R):
        doc_arr[r, : len(docs[r])] = docs[r]
    packed = pack_doc(jnp.asarray(doc_arr), jnp.ones((R, C), jnp.int32))
    snap = snap_rebuild(packed)
    ids = np.stack(
        [rng.choice(docs[r], B).astype(np.int32) for r in range(R)]
    )
    got = np.asarray(query(snap, [], jnp.asarray(ids)))
    for r in range(R):
        for b in range(B):
            assert docs[r][got[r, b]] == ids[r, b]


def test_snap_rebuild_ignores_unused():
    doc = pack_doc(
        jnp.asarray([[4, 3, 0, -1, -1]]), jnp.asarray([[1, 0, 1, 0, 0]])
    )
    snap = np.asarray(snap_rebuild(doc))
    assert snap[0, 4] == 0 and snap[0, 3] == 1 and snap[0, 0] == 2
