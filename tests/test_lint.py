"""graftlint regression tests: the fixture corpus is flagged exactly
(rule id + line), the real package lints clean, suppressions are
honored, and the CLI carries the gate in its exit code."""

from __future__ import annotations

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from crdt_benches_tpu.lint import format_json, run_lint

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "lint_fixtures"
PACKAGE = REPO / "crdt_benches_tpu"

#: markers must sit in a comment ('#' somewhere before them) — prose in
#: a docstring saying "expect: G0xx" must not become a phantom marker.
#: A line may carry several (`# expect: G012  expect: G013`) when rules
#: legitimately layer on one call.
_EXPECT_RE = re.compile(r"expect:\s*(G\d{3})")


def expected_markers(path: Path) -> set[tuple[str, int]]:
    out = set()
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            if "#" not in line:
                continue
            comment = line.split("#", 1)[1]
            for m in _EXPECT_RE.finditer(comment):
                out.add((m.group(1), i))
    return out


ALL_FIXTURE_FILES = sorted(p for p in FIXTURES.glob("**/*.py"))

#: Cross-module corpora (``xmod_*`` directories) lint as a UNIT — their
#: rules see nothing in a single-file run — so the per-file contract
#: below covers only the standalone fixtures.  The G017, G021, G025,
#: and G029 fixtures are artifact-driven the same way G011 is (no
#: ground truth, no findings), so their explicit tests pass the
#: artifact instead.
FIXTURE_FILES = [
    p for p in ALL_FIXTURE_FILES
    if not any(part.startswith("xmod_") for part in p.parts)
    and p.name not in ("g017_dead_publish.py", "g021_dead_protocol.py",
                       "g025_dead_machine.py", "g029_dead_fact.py")
]
XMOD_DIRS = sorted(
    d for d in FIXTURES.iterdir()
    if d.is_dir() and d.name.startswith("xmod_")
)
G008_DIR = FIXTURES / "xmod_g008"
G011_DIR = FIXTURES / "xmod_g011"
THREADS_DIR = FIXTURES / "threads"
FSOPS_DIR = FIXTURES / "fsops"
LIFECYCLE_DIR = FIXTURES / "lifecycle"
RANGES_DIR = FIXTURES / "ranges"


def test_corpus_is_nonempty():
    assert len(FIXTURE_FILES) >= 10
    assert len(XMOD_DIRS) >= 2


@pytest.mark.parametrize(
    "path", FIXTURE_FILES, ids=lambda p: p.relative_to(FIXTURES).as_posix()
)
def test_fixture_flagged_exactly(path: Path):
    """Every `# expect: G00X` line is flagged with that rule — and
    NOTHING else fires (false positives in the corpus are bugs too)."""
    expected = expected_markers(path)
    findings = run_lint([str(path)])
    got = {(f.rule, f.line) for f in findings}
    assert got == expected, (
        f"{path.name}: expected {sorted(expected)}, got {sorted(got)}\n"
        + "\n".join(f"  {f.rule} L{f.line}: {f.msg}" for f in findings)
    )


def test_replicate_merge_dispatch_fixture_covers_g002():
    """The replicated-merge-dispatch fixture (the serve/replicate/
    macro-round shape: bus tick -> stage -> merge dispatch) must seed
    exactly two G002 host syncs — a device read inside the bus tick and
    a state snapshot during remote staging — while the declared
    ``_drain_fence`` stays clean.  Guards the new subsystem's "the bus
    is host-only, syncs live behind fences" invariant at the rule
    level."""
    path = FIXTURES / "serve" / "g002_replicate.py"
    findings = run_lint([str(path)])
    got = {(f.rule, f.line) for f in findings}
    assert got == expected_markers(path)
    assert {f.rule for f in findings} == {"G002"}
    assert len(findings) == 2


def test_serve_fused_kernel_fixture_covers_both_pallas_rules():
    """The fused-serve-kernel fixture (a minimized copy of
    ops/serve_fused.py serve_macro_fused's launch geometry) must seed
    BOTH Pallas rules — a stale pre-K index-map arity and a missing
    round input under G009, an unpadded 2B+2 token width under G010 —
    at exact (rule, line) positions.  Guards the fixture against
    decaying into a file that asserts nothing."""
    path = FIXTURES / "ops" / "g009_g010_serve_fused.py"
    findings = run_lint([str(path)])
    got = {(f.rule, f.line) for f in findings}
    assert got == expected_markers(path)
    assert {f.rule for f in findings} == {"G009", "G010"}
    assert sum(f.rule == "G009" for f in findings) == 2


def test_xmod_g008_corpus_flagged_exactly():
    """The cross-module drift corpus lints as a directory: every
    marker across its files is flagged (path, rule, line)-exactly and
    nothing else fires."""
    expected = {
        (str(p), r, ln)
        for p in sorted(G008_DIR.glob("*.py"))
        for r, ln in expected_markers(p)
    }
    findings = run_lint([str(G008_DIR)])
    got = {(f.path, f.rule, f.line) for f in findings}
    assert got == expected, "\n".join(
        f"  {f.path}:{f.line} {f.rule} {f.msg}" for f in findings
    )
    assert all(f.rule == "G008" for f in findings)


def test_g011_dead_fence_and_unattributed_counter():
    """G011 cross-validates the static fence graph against the runtime
    boundary_syncs ground truth: the stale fence is flagged at its def
    line; the counter with no marker is flagged against the artifact.
    Without an artifact the rule stays silent (no ground truth)."""
    artifact = G011_DIR / "artifact.json"
    findings = run_lint([str(G011_DIR)], sync_artifact=str(artifact))
    expected_dead = {
        (str(p), "G011", ln)
        for p in sorted(G011_DIR.glob("*.py"))
        for _r, ln in expected_markers(p)
    }
    dead = {
        (f.path, f.rule, f.line) for f in findings
        if f.path.endswith(".py")
    }
    assert dead == expected_dead, "\n".join(
        f"  {f.path}:{f.line} {f.rule} {f.msg}" for f in findings
    )
    rogue = [f for f in findings if f.path == str(artifact)]
    assert len(rogue) == 1 and "rogue_sync_path" in rogue[0].msg
    assert run_lint([str(G011_DIR)]) == []  # no artifact -> no G011


def test_g011_fence_tags_scope_the_accounting():
    """chaos/journal/flight fences are only dead-checked against
    artifacts whose run could have crossed them; cold fences never
    are.  The flight tag keys on an actual DUMP, not on chaos — a
    chaos run whose faults all recover never enters the flight
    trigger, so chaos-scoping it would false-positive."""
    import json
    import tempfile

    src = (
        "def drain():  # graftlint: hot-path\n"
        "    chaos_repair(); barrier(); dump(); api()\n"
        "def chaos_repair():  # graftlint: fence=chaos\n"
        "    return 1\n"
        "def barrier():  # graftlint: fence=journal\n"
        "    return 2\n"
        "def dump():  # graftlint: fence=flight\n"
        "    return 3\n"
        "def api():  # graftlint: fence=cold\n"
        "    return 4\n"
    )
    with tempfile.TemporaryDirectory() as td:
        mod = Path(td) / "serve_mod.py"
        mod.write_text(src)

        def artifact(chaos, journal, flight=False):
            p = Path(td) / f"a_{chaos}_{journal}_{flight}.json"
            p.write_text(json.dumps({"boundary_syncs": {
                "sanitized": True, "chaos": chaos, "journal": journal,
                "flight": flight, "entries": {}, "syncs": {},
            }}))
            return str(p)

        quiet = run_lint(
            [str(mod)], sync_artifact=artifact(False, False)
        )
        assert quiet == [], [f.msg for f in quiet]
        loud = run_lint(
            [str(mod)], sync_artifact=artifact(True, True)
        )
        dead = {f.msg.split("`")[1] for f in loud}
        # a chaos run that never dumped leaves the flight fence exempt
        assert dead == {"chaos_repair", "barrier"}
        dumped = run_lint(
            [str(mod)], sync_artifact=artifact(True, True, flight=True)
        )
        dead = {f.msg.split("`")[1] for f in dumped}
        assert dead == {"chaos_repair", "barrier", "dump"}


def test_hot_walk_covers_subclass_overrides(tmp_path):
    """A ``self.m()`` dispatch in a hot-path root resolves to subclass
    OVERRIDES too (virtual dispatch: the override runs when the
    subclass does) — the ReplicatedScheduler `_plan`/`_deliver` bus
    tick shape.  A host sync seeded in the override must be flagged
    even though no hot marker sits anywhere near the subclass."""
    mod = tmp_path / "sched.py"
    mod.write_text(
        "class Base:\n"
        "    def run_round(self):  # graftlint: hot-path\n"
        "        self._plan()\n"
        "    def _plan(self):\n"
        "        return 0\n"
        "class Replicated(Base):\n"
        "    def _plan(self):\n"
        "        return self.x.item()\n"
    )
    findings = run_lint([str(mod)])
    assert [(f.rule, f.line) for f in findings] == [("G002", 8)]


def test_thread_labels_reach_inherited_helpers(tmp_path):
    """`self.m()` dispatches UP the hierarchy too: a helper defined on
    a base class and called from an annotated subclass entry must
    inherit the thread label, or hazards in inherited helpers are
    invisible to the whole confinement suite."""
    mod = tmp_path / "inh.py"
    mod.write_text(
        "class Base:\n"
        "    def __init__(self):\n"
        "        self._d = {}\n"
        "    def helper(self):\n"
        "        self._d['k'] = 1\n"
        "class Sub(Base):\n"
        "    def hot_entry(self):  # graftlint: thread=hot\n"
        "        self.helper()\n"
        "    def status_read(self):  # graftlint: thread=status\n"
        "        return self._d\n"
    )
    findings = run_lint([str(mod)])
    assert [(f.rule, f.line) for f in findings] == [("G014", 5)]


def test_attr_scanner_sees_tuple_unpacking_stores(tmp_path):
    """`self._a, x = {}, y` stores into self._a just as surely as the
    single-target form — tuple-unpacked writes must reach the G014/G015
    access table, or the hazard hides behind an unpacking."""
    mod = tmp_path / "tup.py"
    mod.write_text(
        "class S:\n"
        "    def __init__(self):\n"
        "        self._snap = {}\n"
        "        self._other = {}\n"
        "    def publish(self, snap):  # graftlint: publish\n"
        "        self._snap = snap\n"
        "    def hot_write(self):  # graftlint: thread=hot\n"
        "        self._other, _x = {}, 1\n"
        "    def reset(self):  # graftlint: thread=status\n"
        "        self._snap, _old = {}, self._snap\n"
        "    def status_read(self):  # graftlint: thread=status\n"
        "        return self._other\n"
    )
    findings = run_lint([str(mod)])
    assert [(f.rule, f.line) for f in findings] == [
        ("G014", 8), ("G015", 10),
    ]


def test_hot_walk_reaches_replicated_scheduler_in_the_package():
    """The real package's PR 9 overrides are inside the walked scope —
    the thing the subclass-dispatch extension exists for."""
    from crdt_benches_tpu.lint.core import build_index, walk_hot_scope

    index, errors = build_index([str(PACKAGE)])
    assert not errors
    walked = {fi.qualname for fi, _ in
              walk_hot_scope(index, descend_fences=True)}
    assert {"ReplicatedScheduler._plan", "ReplicatedScheduler._deliver",
            "BroadcastBus.tick"} <= walked


def test_every_rule_has_a_detection_case():
    covered = set()
    for p in ALL_FIXTURE_FILES:
        covered |= {r for r, _ in expected_markers(p)}
    assert {
        "G001", "G002", "G003", "G004", "G005", "G006", "G007",
        "G008", "G009", "G010", "G011", "G012", "G013",
        "G014", "G015", "G016", "G017",
        "G018", "G019", "G020", "G021",
        "G022", "G023", "G024", "G025",
        "G026", "G027", "G028", "G029",
    } <= covered


def test_threads_corpus_covers_each_rule_exactly_once_per_hazard():
    """The thread-confinement corpus seeds the canonical shape of each
    hazard: one escaped dict (G014), all five publish-contract breaks
    (G015: in-place inside the point, owner-side mutation outside it,
    reader-side mutation, far-side reassignment, owner-side mutable
    reassignment outside the point),
    and the five blocking kinds the walker must reach — including one
    inside a declared fence (G016 descends)."""
    g014 = run_lint([str(THREADS_DIR / "g014_escape.py")])
    assert [(f.rule, f.line) for f in g014] == [("G014", 17)]
    g015_path = THREADS_DIR / "g015_publish.py"
    g015 = run_lint([str(g015_path)])
    assert {f.rule for f in g015} == {"G015"}
    assert [(f.rule, f.line) for f in g015] == sorted(
        expected_markers(g015_path), key=lambda rl: rl[1]
    )
    assert len(g015) == 5
    assert "inside publish point" in g015[0].msg
    assert "outside its publish point" in g015[1].msg
    assert "read-only" in g015[2].msg
    assert "reassigned" in g015[3].msg
    assert "no publish generation" in g015[4].msg
    g016 = run_lint([str(THREADS_DIR / "g016_hot_blocking.py")])
    assert {f.rule for f in g016} == {"G016"}
    # with-lock, queue get, bare event wait, acquire, fence join —
    # while the bounded/non-blocking twins on adjacent lines stay legal
    assert len(g016) == 5


def test_prefetch_thread_confinement_fixture():
    """The tiered-residency prefetch thread's canonical hazards, one
    per rule at exact lines: a loaded row escaping the worker into a
    hot-read list (G014), an in-place mutation inside the declared
    result publish point (G015), and the admission walk blocking on
    the result queue (G016 — a warm miss must fall back to the
    synchronous rehydrate, never wait on the prefetch thread).  The
    legal twins — the atomic swap, ``get_nowait``, the sync fallback —
    stay silent."""
    path = THREADS_DIR / "prefetch_confinement.py"
    findings = run_lint([str(path)])
    assert [(f.rule, f.line) for f in findings] == sorted(
        expected_markers(path), key=lambda rl: rl[1]
    )
    assert [(f.rule, f.line) for f in findings] == [
        ("G014", 31), ("G015", 36), ("G016", 42),
    ]
    assert "prefetch" in findings[0].msg  # the owning-thread set named
    assert "publish point" in findings[1].msg
    assert "hot thread" in findings[2].msg


def test_ingest_thread_confinement_fixture():
    """The live ingest front's canonical handler-thread hazards, one
    per rule at exact lines: a decoded frame escaping the handler into
    a hot-read list (G014), an in-place mutation inside the declared
    frame publish point (G015), and the pump blocking on the delivery
    queue (G016 — an empty queue means nothing arrived this round,
    never a reason to park the drain behind a TCP handler).  The legal
    twins — the atomic swap, ``get_nowait``, the hot-owned holding
    list — stay silent."""
    path = THREADS_DIR / "ingest_confinement.py"
    findings = run_lint([str(path)])
    assert [(f.rule, f.line) for f in findings] == sorted(
        expected_markers(path), key=lambda rl: rl[1]
    )
    assert [(f.rule, f.line) for f in findings] == [
        ("G014", 32), ("G015", 37), ("G016", 43),
    ]
    assert "ingest" in findings[0].msg  # the owning-thread set named
    assert "publish point" in findings[1].msg
    assert "hot thread" in findings[2].msg


def test_g013_ingest_front_fixture_covers_socket_construction():
    """The ingest-front G013 seed: constructing/serving a TCP server,
    constructing the front itself, and opening outbound sockets are
    all flagged in hot-path scopes at exact lines — while the same
    calls in ``driver_setup`` (off the hot call graph) stay legal."""
    path = FIXTURES / "serve" / "g013_ingest.py"
    findings = run_lint([str(path)])
    got = {(f.rule, f.line) for f in findings}
    assert got == expected_markers(path), "\n".join(
        f"  {f.rule} L{f.line}: {f.msg}" for f in findings
    )
    assert {f.rule for f in findings} == {"G013"}
    assert len(findings) == 5
    ctor = [f for f in findings if "IngestFront" in f.msg]
    assert len(ctor) == 1 and "driver-owned" in ctor[0].msg


def test_g017_dead_publish_and_unattributed_counter():
    """G017 mirrors G011 for publish points: a declared point the run
    never entered is flagged at its def line, a ``publish=status`` tag
    exempts the point when the artifact's run never armed that surface,
    and a runtime counter with no marker is flagged against the
    artifact.  Without an artifact the rule stays silent."""
    artifact = THREADS_DIR / "artifact.json"
    path = THREADS_DIR / "g017_dead_publish.py"
    findings = run_lint([str(path)], thread_artifact=str(artifact))
    dead = {(f.path, f.rule, f.line) for f in findings
            if f.path.endswith(".py")}
    assert dead == {
        (str(path), r, ln) for r, ln in expected_markers(path)
    }, "\n".join(f"  {f.path}:{f.line} {f.rule} {f.msg}" for f in findings)
    rogue = [f for f in findings if f.path == str(artifact)]
    assert len(rogue) == 1 and "rogue_handoff" in rogue[0].msg
    assert run_lint([str(path)]) == []  # no artifact -> no G017


def test_g017_armed_surface_counts_tagged_points():
    """When the artifact's run DID arm the status surface, the tagged
    point participates in the dead-point accounting like any other."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        armed = Path(td) / "armed.json"
        armed.write_text(json.dumps({"thread_crossings": {
            "sanitized": True, "status": True,
            "publishes": {"Feed.publish_snap": 4},
            "crossings": {"Feed.publish_snap": 9},
        }}))
        findings = run_lint(
            [str(THREADS_DIR / "g017_dead_publish.py")],
            thread_artifact=str(armed),
        )
        dead = {f.msg.split("`")[1] for f in findings}
        assert dead == {"Feed.publish_status_only", "Feed.publish_typod"}
        typod = [f for f in findings if "publish_typod" in f.msg]
        assert len(typod) == 1 and "statsu" in typod[0].msg


def test_g017_selected_without_artifact_fails_like_g011():
    """Explicitly selecting an artifact-driven rule with no ground
    truth must FAIL the gate, never silently no-op."""
    findings = run_lint(
        [str(THREADS_DIR / "g017_dead_publish.py")], select={"G017"}
    )
    assert [f.rule for f in findings] == ["G000"]
    assert "--thread-artifact" in findings[0].msg


def test_fsops_corpus_covers_each_rule_per_hazard():
    """The crash-consistency corpus seeds the canonical shape of each
    hazard at exact lines: the in-place durable write + the
    fsync-less commit + the typo'd protocol tag (G018), the PR 13
    unlink-before-install window (G019), and both verify-before-trust
    breaks — the trusted np.load and the too-narrow recovery catch-set
    (G020) — while every legal twin (staged write, fsynced commit,
    commit-then-destroy, read-witness cleanup, CRC-verified read,
    garbage-covering fallback) stays silent."""
    g018_path = FSOPS_DIR / "g018_atomic.py"
    g018 = run_lint([str(g018_path)])
    assert {f.rule for f in g018} == {"G018"}
    assert [(f.rule, f.line) for f in g018] == sorted(
        expected_markers(g018_path), key=lambda rl: rl[1]
    )
    assert "in-place write-mode open" in g018[0].msg
    assert "no fsync" in g018[1].msg
    assert "unknown durable protocol" in g018[2].msg
    g019_path = FSOPS_DIR / "g019_order.py"
    g019 = run_lint([str(g019_path)])
    assert [(f.rule, f.line) for f in g019] == sorted(
        expected_markers(g019_path), key=lambda rl: rl[1]
    )
    assert len(g019) == 1 and "destroys the only copy" in g019[0].msg
    g020_path = FSOPS_DIR / "g020_trust.py"
    g020 = run_lint([str(g020_path)])
    assert [(f.rule, f.line) for f in g020] == sorted(
        expected_markers(g020_path), key=lambda rl: rl[1]
    )
    assert "trusted np.load" in g020[0].msg
    assert "parseable-garbage" in g020[1].msg


def test_g021_dead_protocol_and_unattributed_ops():
    """G021 mirrors G011/G017 for durable protocols: a declared
    protocol the artifact's run never entered is flagged at its def
    line (scoped by armed surface — the fixture artifact armed
    ``flight`` only), a runtime tag with no marker and unattributed
    mutating ops are flagged against the artifact.  Without an
    artifact the rule stays silent."""
    artifact = FSOPS_DIR / "artifact.json"
    path = FSOPS_DIR / "g021_dead_protocol.py"
    findings = run_lint([str(path)], fs_artifact=str(artifact))
    dead = {(f.path, f.rule, f.line) for f in findings
            if f.path.endswith(".py")}
    assert dead == {
        (str(path), r, ln) for r, ln in expected_markers(path)
    }, "\n".join(f"  {f.path}:{f.line} {f.rule} {f.msg}" for f in findings)
    from_artifact = [f for f in findings if f.path == str(artifact)]
    assert len(from_artifact) == 2
    assert any("rogue_proto" in f.msg for f in from_artifact)
    assert any("unattributed runtime `unlink`" in f.msg
               for f in from_artifact)
    assert run_lint([str(path)]) == []  # no artifact -> no G021


def test_g021_selected_without_artifact_fails_like_g011():
    findings = run_lint(
        [str(FSOPS_DIR / "g021_dead_protocol.py")], select={"G021"}
    )
    assert [f.rule for f in findings] == ["G000"]
    assert "--fs-artifact" in findings[0].msg


def test_fsops_suppression_contract():
    """`# graftlint: disable=G018/19/20` silences the crash-
    consistency rules exactly like every other rule."""
    findings = run_lint([str(FSOPS_DIR / "suppressed_clean.py")])
    assert findings == []


def test_sarif_covers_the_fsops_rules():
    """The SARIF reporter carries the new rules with the same
    everything-is-an-error gate semantics (CI annotation surfaces
    ingest the crash-consistency findings like any other)."""
    from crdt_benches_tpu.lint import format_sarif

    findings = run_lint([str(FSOPS_DIR / "g018_atomic.py"),
                         str(FSOPS_DIR / "g019_order.py"),
                         str(FSOPS_DIR / "g020_trust.py")])
    doc = json.loads(format_sarif(findings))
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert rules == {"G018", "G019", "G020"}
    assert all(r["level"] == "error" for r in doc["runs"][0]["results"])


def test_lifecycle_corpus_covers_each_rule_per_hazard():
    """The lifecycle corpus seeds the canonical shape of each hazard
    at exact lines: the illegal declared edge + the rogue direct write
    to the state field (G022), the leak-on-path acquire, the
    balance-negative release past a live acquire, and the verbatim
    repeated release (G023), and the PR 17 incident pair — the bare
    id()-keyed long-lived map and the unguarded paired-counter
    decrement (G024) — while every legal twin (declared edges routed
    through transition functions, the finally-covered release, the
    generation-tupled key, the positivity-guarded decrement) stays
    silent."""
    g022_path = LIFECYCLE_DIR / "g022_illegal_transition.py"
    g022 = run_lint([str(g022_path)])
    assert {f.rule for f in g022} == {"G022"}
    assert [(f.rule, f.line) for f in g022] == sorted(
        expected_markers(g022_path), key=lambda rl: rl[1]
    )
    assert "not an edge of the declared graph" in g022[0].msg
    assert "direct write to state field" in g022[1].msg
    leak_path = LIFECYCLE_DIR / "g023_leak_on_path.py"
    leak = run_lint([str(leak_path)])
    assert [(f.rule, f.line) for f in leak] == sorted(
        expected_markers(leak_path), key=lambda rl: rl[1]
    )
    assert len(leak) == 1 and "never released" in leak[0].msg
    dbl_path = LIFECYCLE_DIR / "g023_double_release.py"
    dbl = run_lint([str(dbl_path)])
    assert {f.rule for f in dbl} == {"G023"}
    assert [(f.rule, f.line) for f in dbl] == sorted(
        expected_markers(dbl_path), key=lambda rl: rl[1]
    )
    assert "without a dominating acquire" in dbl[0].msg
    assert "double release" in dbl[1].msg
    g024_path = LIFECYCLE_DIR / "g024_id_keyed_cache.py"
    g024 = run_lint([str(g024_path)])
    assert {f.rule for f in g024} == {"G024"}
    assert [(f.rule, f.line) for f in g024] == sorted(
        expected_markers(g024_path), key=lambda rl: rl[1]
    )
    assert "recycles" in g024[0].msg
    assert "recycles" in g024[1].msg
    assert "underflow guard" in g024[2].msg


def test_g025_dead_machine_and_unattributed_transitions():
    """G025 mirrors G011/G017/G021 for lifecycle declarations: a
    declared machine/resource the artifact's run never touched is
    flagged at its decl line (scoped by armed surface — the fixture
    artifact armed ``pool`` only), runtime machines/resources with no
    marker and unattributed transitions are flagged against the
    artifact.  Without an artifact the rule stays silent."""
    artifact = LIFECYCLE_DIR / "artifact.json"
    path = LIFECYCLE_DIR / "g025_dead_machine.py"
    findings = run_lint([str(path)], lifecycle_artifact=str(artifact))
    dead = {(f.path, f.rule, f.line) for f in findings
            if f.path.endswith(".py")}
    assert dead == {
        (str(path), r, ln) for r, ln in expected_markers(path)
    }, "\n".join(f"  {f.path}:{f.line} {f.rule} {f.msg}" for f in findings)
    from_artifact = [f for f in findings if f.path == str(artifact)]
    assert len(from_artifact) == 3
    assert any("runtime machine `session`" in f.msg for f in from_artifact)
    assert any("runtime resource `socket`" in f.msg for f in from_artifact)
    assert any("unattributed runtime transition `spool:live->cold`" in f.msg
               for f in from_artifact)
    assert run_lint([str(path)]) == []  # no artifact -> no G025


def test_g025_selected_without_artifact_fails_like_g011():
    findings = run_lint(
        [str(LIFECYCLE_DIR / "g025_dead_machine.py")], select={"G025"}
    )
    assert [f.rule for f in findings] == ["G000"]
    assert "--lifecycle-artifact" in findings[0].msg


def test_lifecycle_suppression_contract():
    """`# graftlint: disable=G022/23/24` silences the lifecycle rules
    exactly like every other rule."""
    findings = run_lint([str(LIFECYCLE_DIR / "suppressed_clean.py")])
    assert findings == []


def test_sarif_covers_the_lifecycle_rules():
    """The SARIF reporter carries the lifecycle rules with the same
    everything-is-an-error gate semantics."""
    from crdt_benches_tpu.lint import format_sarif

    findings = run_lint([
        str(LIFECYCLE_DIR / "g022_illegal_transition.py"),
        str(LIFECYCLE_DIR / "g023_double_release.py"),
        str(LIFECYCLE_DIR / "g024_id_keyed_cache.py"),
    ])
    doc = json.loads(format_sarif(findings))
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert rules == {"G022", "G023", "G024"}
    assert all(r["level"] == "error" for r in doc["runs"][0]["results"])


def test_ranges_corpus_covers_each_rule_exactly():
    """The value-range corpus seeds the canonical shape of each static
    hazard: the unguarded dynamic gather, the clamp-and-hope gather
    with no declared mask consumer, the half-declared mask pair
    (G026); narrow uint16 arithmetic before the widen and a
    marker-declared narrow lane (G027); the PAD constant in
    arithmetic and a sentinel-carrying local leaking into a sum and
    an ordering comparison (G028) — while every legal twin (clip+mask
    pair, declared inrange fact, widen-first, OpRangeError-dominated,
    compare-against-sentinel, mask-first) stays silent."""
    g026_path = RANGES_DIR / "g026_unguarded_gather.py"
    g026 = run_lint([str(g026_path)])
    assert {f.rule for f in g026} == {"G026"}
    assert [(f.rule, f.line) for f in g026] == sorted(
        expected_markers(g026_path), key=lambda rl: rl[1]
    )
    assert "unguarded dynamic index" in g026[0].msg
    assert "no declared mask consumer" in g026[1].msg
    assert "no paired consumer" in g026[2].msg
    g027_path = RANGES_DIR / "g027_narrow_overflow.py"
    g027 = run_lint([str(g027_path)])
    assert {f.rule for f in g027} == {"G027"}
    # line 17 fires twice — once per narrow operand lane
    assert sorted((f.rule, f.line) for f in g027) == [
        ("G027", 17), ("G027", 17), ("G027", 22),
    ]
    assert expected_markers(g027_path) == {("G027", 17), ("G027", 22)}
    assert all("before a widen" in f.msg for f in g027)
    g028_path = RANGES_DIR / "g028_pad_flow.py"
    g028 = run_lint([str(g028_path)])
    assert {f.rule for f in g028} == {"G028"}
    assert [(f.rule, f.line) for f in g028] == sorted(
        expected_markers(g028_path), key=lambda rl: rl[1]
    )
    assert "used directly in arithmetic" in g028[0].msg
    assert "no intervening mask" in g028[1].msg
    assert "ordering comparison" in g028[2].msg


def test_g029_dead_fact_and_rogue_counters():
    """G029 mirrors G011/G017/G021/G025 for range declarations: a
    declared check/mask the artifact's run never counted is flagged at
    its declaration line (scoped by armed surface — the fixture
    artifact armed ``staging`` only, so the fused-scoped mask stays
    silent), and runtime counters with no declaration are flagged
    against the artifact.  Without an artifact the rule stays
    silent."""
    artifact = RANGES_DIR / "artifact.json"
    path = RANGES_DIR / "g029_dead_fact.py"
    findings = run_lint([str(path)], ranges_artifact=str(artifact))
    dead = {(f.path, f.rule, f.line) for f in findings
            if f.path.endswith(".py")}
    assert dead == {
        (str(path), r, ln) for r, ln in expected_markers(path)
    }, "\n".join(f"  {f.path}:{f.line} {f.rule} {f.msg}" for f in findings)
    assert any("dead fact" in f.msg for f in findings)
    assert any("dead mask" in f.msg for f in findings)
    from_artifact = [f for f in findings if f.path == str(artifact)]
    assert len(from_artifact) == 2
    assert any("runtime range check `fx.rogue-check`" in f.msg
               for f in from_artifact)
    assert any("runtime mask counter `fx-rogue-mask`" in f.msg
               for f in from_artifact)
    assert run_lint([str(path)]) == []  # no artifact -> no G029


def test_g029_selected_without_artifact_fails_like_g011():
    findings = run_lint(
        [str(RANGES_DIR / "g029_dead_fact.py")], select={"G029"}
    )
    assert [f.rule for f in findings] == ["G000"]
    assert "--ranges-artifact" in findings[0].msg


def test_ranges_suppression_contract():
    """`# graftlint: disable=G026/27/28` silences the range rules
    exactly like every other rule."""
    findings = run_lint([str(RANGES_DIR / "suppressed_clean.py")])
    assert findings == []


def test_sarif_covers_the_range_rules():
    from crdt_benches_tpu.lint import format_sarif

    findings = run_lint([
        str(RANGES_DIR / "g026_unguarded_gather.py"),
        str(RANGES_DIR / "g027_narrow_overflow.py"),
        str(RANGES_DIR / "g028_pad_flow.py"),
    ])
    doc = json.loads(format_sarif(findings))
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert rules == {"G026", "G027", "G028"}
    assert all(r["level"] == "error" for r in doc["runs"][0]["results"])


def test_historical_bugs_caught_by_the_right_rule():
    """The two bugs this linter exists for: the idpos tracer leak is a
    G001, the pre-shim CompilerParams drift is a G003."""
    leak = run_lint([str(FIXTURES / "hist_idpos_tracer_leak.py")])
    assert any(f.rule == "G001" for f in leak)
    drift = run_lint([str(FIXTURES / "hist_compiler_params.py")])
    assert any(f.rule == "G003" for f in drift)


def test_suppression_escape_hatch():
    findings = run_lint([str(FIXTURES / "ops" / "suppressed_clean.py")])
    assert findings == []


def test_real_package_lints_clean():
    """The full gate surface — package, tools, tests — is clean under
    every rule including the new interprocedural/Pallas passes (zero
    false positives is an acceptance criterion, not a nice-to-have)."""
    findings = run_lint([
        str(PACKAGE), str(REPO / "tools"), str(REPO / "tests"),
    ])
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.msg}" for f in findings
    )


def test_fixture_corpus_is_pruned_from_directory_walks():
    """Linting tests/ must not trip over the intentionally-dirty
    fixture corpus — but a fixture passed explicitly still lints."""
    clean = run_lint([str(REPO / "tests")])
    assert clean == []
    direct = run_lint([str(FIXTURES / "ops" / "g005_implicit_dtype.py")])
    assert direct, "explicit fixture path must still lint dirty"


def test_select_filters_rules():
    path = str(FIXTURES / "ops" / "g002_host_sync.py")
    only_g5 = run_lint([path], select={"G005"})
    assert only_g5 == []
    only_g2 = run_lint([path], select={"G002"})
    assert {f.rule for f in only_g2} == {"G002"}


def test_missing_target_fails_the_gate(tmp_path):
    """A typo'd path must FAIL lint, never report clean on nothing —
    otherwise a renamed package turns the CI gate permanently green."""
    findings = run_lint([str(tmp_path / "no_such_dir")])
    assert findings and findings[0].rule == "G000"
    findings = run_lint([str(tmp_path / "no_such_file.py")])
    assert findings and findings[0].rule == "G000"
    empty = tmp_path / "empty_pkg"
    empty.mkdir()
    findings = run_lint([str(empty)])  # exists, but holds no .py at all
    assert findings and findings[0].rule == "G000"
    proc = _cli("definitely_not_a_real_path")
    assert proc.returncode == 1


def test_docstring_text_is_not_a_suppression(tmp_path):
    """Only real comments carry directives: a module that *documents*
    the escape hatch in its docstring must not trigger it."""
    mod = tmp_path / "ops" / "doc_mention.py"
    mod.parent.mkdir()
    mod.write_text(
        '"""Suppress G001 findings with `# graftlint: disable-file=G001`\n'
        'on any line of the file."""\n'
        "import jax.numpy as jnp\n"
        "BIG = jnp.int32(7)\n"
    )
    findings = run_lint([str(mod)])
    assert {f.rule for f in findings} == {"G001"}


def test_json_reporter_roundtrips():
    findings = run_lint([str(FIXTURES / "ops" / "g004_donation.py")])
    blob = json.loads(format_json(findings))
    assert blob["count"] == len(findings) > 0
    assert blob["findings"][0]["rule"] == "G004"


def test_sarif_reporter_schema_shape():
    """--format sarif: valid SARIF 2.1.0 skeleton, one result per
    finding at 1-based positions, every ruleId declared in the driver
    — and artifact-level findings (line 0) clamp to line 1 instead of
    emitting an out-of-spec region."""
    from crdt_benches_tpu.lint import format_sarif

    findings = run_lint(
        [str(THREADS_DIR / "g017_dead_publish.py")],
        thread_artifact=str(THREADS_DIR / "artifact.json"),
    )
    sarif = json.loads(format_sarif(findings))
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {res["ruleId"] for res in run["results"]} == declared == {"G017"}
    assert len(run["results"]) == len(findings) == 3
    for res in run["results"]:
        region = res["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1 and region["startColumn"] >= 1
        assert res["level"] == "error"


def test_cli_sarif_keeps_exit_code_semantics():
    """A reporter changes the rendering, never the gate: sarif output
    on a dirty fixture still exits 1, and on the clean tree exits 0
    with a parseable empty result set."""
    dirty = _cli(
        "--format", "sarif", str(THREADS_DIR / "g016_hot_blocking.py")
    )
    assert dirty.returncode == 1
    blob = json.loads(dirty.stdout)
    assert len(blob["runs"][0]["results"]) == 5
    clean = _cli("--format", "sarif", "crdt_benches_tpu")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert json.loads(clean.stdout)["runs"][0]["results"] == []


def _cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "crdt_benches_tpu.lint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_codes():
    """The CI contract: nonzero on any finding, zero on the shipped
    tree — graftlint is pure-AST so this spawns fast (no jax import)."""
    clean = _cli("crdt_benches_tpu")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    for fixture in FIXTURE_FILES:
        if fixture.name in ("suppressed_clean.py",):
            continue
        dirty = _cli(str(fixture))
        assert dirty.returncode == 1, (
            f"{fixture.name}: expected exit 1\n{dirty.stdout}"
        )
    for d in XMOD_DIRS:
        if d == G011_DIR:  # dirty only WITH its artifact
            dirty = _cli(
                str(d), "--sync-artifact", str(d / "artifact.json")
            )
        else:
            dirty = _cli(str(d))
        assert dirty.returncode == 1, (
            f"{d.name}: expected exit 1\n{dirty.stdout}"
        )


def _copy_fixture_into_scope(tmp_path: Path, name: str) -> Path:
    """G005's dir scoping keys on an ops/ path segment — replicate it
    for tmp copies."""
    dst = tmp_path / "ops" / name
    dst.parent.mkdir(exist_ok=True)
    dst.write_text((FIXTURES / "ops" / name).read_text())
    return dst


def test_fix_g005_is_exact_and_idempotent(tmp_path):
    """--fix rewrites the fixable sites (re-lint shows them clean),
    refuses the runtime-typed one, and a second run changes nothing."""
    from crdt_benches_tpu.lint.fix import fix_g005

    mod = _copy_fixture_into_scope(tmp_path, "g005_implicit_dtype.py")
    assert {f.rule for f in run_lint([str(mod)])} == {"G005"}
    results = fix_g005([str(mod)])
    assert [r.applied for r in results] == [True, True, False]
    fixed_src = mod.read_text()
    assert "jnp.zeros((rows, batch), dtype=jnp.float32)" in fixed_src
    assert "jnp.arange(128, dtype=jnp.int32)" in fixed_src
    # only the refused runtime-typed site survives the re-lint
    left = run_lint([str(mod)])
    assert [(f.rule, f.line) for f in left] == [("G005", 17)]
    again = fix_g005([str(mod)])
    assert [r.applied for r in again] == [False]  # idempotent
    assert mod.read_text() == fixed_src
    # the rewrite must still be valid python
    compile(fixed_src, str(mod), "exec")


def test_fix_g005_refuses_ambiguous_sites(tmp_path):
    """A non-literal arange bound's dtype follows the runtime argument
    type — the fixer must refuse, and the finding must survive."""
    from crdt_benches_tpu.lint.fix import fix_g005

    mod = tmp_path / "ops" / "ambiguous.py"
    mod.parent.mkdir(exist_ok=True)
    mod.write_text(
        "import jax.numpy as jnp\n\n\n"
        "def f(n):\n"
        "    return jnp.arange(n)\n"
    )
    results = fix_g005([str(mod)])
    assert len(results) == 1 and not results[0].applied
    assert "refused" in results[0].detail
    assert {f.rule for f in run_lint([str(mod)])} == {"G005"}


def test_cli_changed_mode(tmp_path):
    """--changed lints exactly the working-tree .py delta: clean exit
    on a clean file, nonzero once a violation lands, and a no-change
    tree is a clean no-op."""
    import os

    env = dict(os.environ)
    repo = tmp_path / "wt"
    repo.mkdir()
    (repo / "ops").mkdir()

    def git(*args):
        return subprocess.run(
            ["git", *args], cwd=repo, capture_output=True, text=True,
            env={**env, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    def lint_changed():
        return subprocess.run(
            [sys.executable, "-m", "crdt_benches_tpu.lint", "--changed"],
            cwd=repo, capture_output=True, text=True, timeout=120,
            env={**env, "PYTHONPATH": str(REPO)},
        )

    git("init", "-q")
    (repo / "ops" / "mod.py").write_text("X = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    none = lint_changed()
    assert none.returncode == 0 and "no changed python files" in none.stdout
    (repo / "ops" / "mod.py").write_text(
        "import jax.numpy as jnp\nX = jnp.int32(1)\n"
    )
    dirty = lint_changed()
    assert dirty.returncode == 1 and "G001" in dirty.stdout
    (repo / "ops" / "fresh.py").write_text("Y = 2\n")  # untracked, clean
    (repo / "ops" / "mod.py").write_text("X = 1\n")
    ok = lint_changed()
    assert ok.returncode == 0, ok.stdout + ok.stderr


def test_lint_sh_gate():
    """tools/lint.sh: exit 0 on the shipped tree, nonzero on a
    fixture."""
    ok = subprocess.run(
        ["bash", "tools/lint.sh"], cwd=REPO,
        capture_output=True, text=True, timeout=300,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        ["bash", "tools/lint.sh",
         str(FIXTURES / "hist_idpos_tracer_leak.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert bad.returncode != 0
