"""graftlint regression tests: the fixture corpus is flagged exactly
(rule id + line), the real package lints clean, suppressions are
honored, and the CLI carries the gate in its exit code."""

from __future__ import annotations

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from crdt_benches_tpu.lint import format_json, run_lint

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "lint_fixtures"
PACKAGE = REPO / "crdt_benches_tpu"

_EXPECT_RE = re.compile(r"#\s*expect:\s*(G\d{3})")


def expected_markers(path: Path) -> set[tuple[str, int]]:
    out = set()
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                out.add((m.group(1), i))
    return out


FIXTURE_FILES = sorted(
    p for p in FIXTURES.glob("**/*.py")
)


def test_corpus_is_nonempty():
    assert len(FIXTURE_FILES) >= 8


@pytest.mark.parametrize(
    "path", FIXTURE_FILES, ids=lambda p: p.relative_to(FIXTURES).as_posix()
)
def test_fixture_flagged_exactly(path: Path):
    """Every `# expect: G00X` line is flagged with that rule — and
    NOTHING else fires (false positives in the corpus are bugs too)."""
    expected = expected_markers(path)
    findings = run_lint([str(path)])
    got = {(f.rule, f.line) for f in findings}
    assert got == expected, (
        f"{path.name}: expected {sorted(expected)}, got {sorted(got)}\n"
        + "\n".join(f"  {f.rule} L{f.line}: {f.msg}" for f in findings)
    )


def test_every_rule_has_a_detection_case():
    covered = set()
    for p in FIXTURE_FILES:
        covered |= {r for r, _ in expected_markers(p)}
    assert {
        "G001", "G002", "G003", "G004", "G005", "G006", "G007"
    } <= covered


def test_historical_bugs_caught_by_the_right_rule():
    """The two bugs this linter exists for: the idpos tracer leak is a
    G001, the pre-shim CompilerParams drift is a G003."""
    leak = run_lint([str(FIXTURES / "hist_idpos_tracer_leak.py")])
    assert any(f.rule == "G001" for f in leak)
    drift = run_lint([str(FIXTURES / "hist_compiler_params.py")])
    assert any(f.rule == "G003" for f in drift)


def test_suppression_escape_hatch():
    findings = run_lint([str(FIXTURES / "ops" / "suppressed_clean.py")])
    assert findings == []


def test_real_package_lints_clean():
    findings = run_lint([str(PACKAGE)])
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.msg}" for f in findings
    )


def test_select_filters_rules():
    path = str(FIXTURES / "ops" / "g002_host_sync.py")
    only_g5 = run_lint([path], select={"G005"})
    assert only_g5 == []
    only_g2 = run_lint([path], select={"G002"})
    assert {f.rule for f in only_g2} == {"G002"}


def test_missing_target_fails_the_gate(tmp_path):
    """A typo'd path must FAIL lint, never report clean on nothing —
    otherwise a renamed package turns the CI gate permanently green."""
    findings = run_lint([str(tmp_path / "no_such_dir")])
    assert findings and findings[0].rule == "G000"
    findings = run_lint([str(tmp_path / "no_such_file.py")])
    assert findings and findings[0].rule == "G000"
    empty = tmp_path / "empty_pkg"
    empty.mkdir()
    findings = run_lint([str(empty)])  # exists, but holds no .py at all
    assert findings and findings[0].rule == "G000"
    proc = _cli("definitely_not_a_real_path")
    assert proc.returncode == 1


def test_docstring_text_is_not_a_suppression(tmp_path):
    """Only real comments carry directives: a module that *documents*
    the escape hatch in its docstring must not trigger it."""
    mod = tmp_path / "ops" / "doc_mention.py"
    mod.parent.mkdir()
    mod.write_text(
        '"""Suppress G001 findings with `# graftlint: disable-file=G001`\n'
        'on any line of the file."""\n'
        "import jax.numpy as jnp\n"
        "BIG = jnp.int32(7)\n"
    )
    findings = run_lint([str(mod)])
    assert {f.rule for f in findings} == {"G001"}


def test_json_reporter_roundtrips():
    findings = run_lint([str(FIXTURES / "ops" / "g004_donation.py")])
    blob = json.loads(format_json(findings))
    assert blob["count"] == len(findings) > 0
    assert blob["findings"][0]["rule"] == "G004"


def _cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "crdt_benches_tpu.lint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_codes():
    """The CI contract: nonzero on any finding, zero on the shipped
    tree — graftlint is pure-AST so this spawns fast (no jax import)."""
    clean = _cli("crdt_benches_tpu")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    for fixture in FIXTURE_FILES:
        if fixture.name in ("suppressed_clean.py",):
            continue
        dirty = _cli(str(fixture))
        assert dirty.returncode == 1, (
            f"{fixture.name}: expected exit 1\n{dirty.stdout}"
        )


def test_lint_sh_gate():
    """tools/lint.sh: exit 0 on the shipped tree, nonzero on a
    fixture."""
    ok = subprocess.run(
        ["bash", "tools/lint.sh"], cwd=REPO,
        capture_output=True, text=True, timeout=300,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        ["bash", "tools/lint.sh",
         str(FIXTURES / "hist_idpos_tracer_leak.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert bad.returncode != 0
