"""Measurement-harness statistics: Tukey outlier classification and the
severe-outlier re-run policy (the capability the reference gets from the
criterion crate, /root/reference/Cargo.toml:11 — warmup calibration,
mild/severe outlier analysis; VERDICT r3 missing #1)."""

import itertools

import pytest

from crdt_benches_tpu.bench.harness import (
    BenchResult,
    SampleList,
    _quantile,
    classify_outliers,
    measure,
    quantiles,
)


def test_classify_clean():
    cls = classify_outliers([1.0, 1.01, 0.99, 1.02, 0.98])
    assert cls["mild"] == 0 and cls["severe"] == 0
    assert cls["flagged"] == []


def test_classify_severe_high():
    # the round-3 artifact shape: four ~24s samples and one 294s sample
    # (with IQR ~= 0.01 the 24.08 low end is ALSO past 3*IQR — Tukey is
    # strict on near-degenerate spreads, as criterion's analysis is)
    cls = classify_outliers([24.08, 24.12, 24.12, 24.13, 294.64])
    assert cls["severe"] >= 1
    assert 294.64 in cls["flagged"]
    assert "fences" in cls


def test_classify_mild_vs_severe():
    # base IQR over [10,10.1,10.2,10.3]; 10.9 is past 1.5*IQR but within
    # 3*IQR of Q3 -> mild; 1000 -> severe
    s = [10.0, 10.1, 10.2, 10.3, 10.9, 1000.0]
    cls = classify_outliers(s)
    assert cls["severe"] >= 1 and 1000.0 in cls["flagged"]


def test_classify_short_lists_never_flag():
    for n in range(4):
        cls = classify_outliers([1.0] * n)
        assert cls == {"mild": 0, "severe": 0, "flagged": []}


def test_measure_reruns_severe_outlier():
    # fn's 3rd sample is a 100x environmental stall; measure must detect
    # it, re-run a replacement, and log the discarded value.
    times = itertools.chain([1.0, 1.01, 100.0, 1.02, 0.99], itertools.repeat(1.0))
    clock = [0.0]

    def fake_fn():
        clock[0] += next(times)

    import crdt_benches_tpu.bench.harness as h

    real = h.time.perf_counter
    try:
        h.time.perf_counter = lambda: clock[0]
        out = measure(fake_fn, warmup=0, samples=5)
    finally:
        h.time.perf_counter = real
    assert len(out) == 5
    assert out.discarded == [100.0]
    assert out.reruns == 1
    assert max(out) < 2.0
    assert classify_outliers(out)["severe"] == 0


def test_measure_keeps_persistent_outliers_annotated():
    # every rerun also produces a severe outlier -> after the budget the
    # survivor stays IN the sample set (annotated, not silently dropped)
    times = itertools.chain(
        [1.0, 1.01, 1.02, 0.99], itertools.repeat(100.0)
    )
    clock = [0.0]

    def fake_fn():
        clock[0] += next(times)

    import crdt_benches_tpu.bench.harness as h

    real = h.time.perf_counter
    try:
        h.time.perf_counter = lambda: clock[0]
        out = measure(fake_fn, warmup=0, samples=5, max_reruns=2)
    finally:
        h.time.perf_counter = real
    assert len(out) == 5
    assert out.reruns == 2
    assert classify_outliers(out)["severe"] >= 1  # still visible


def test_benchresult_persists_outlier_record():
    s = SampleList([24.08, 24.12, 24.12, 24.13])
    s.discarded = [294.64]
    s.reruns = 1
    r = BenchResult("merge", "adv", "jax", 1000, s)
    d = r.to_dict()
    assert d["discarded_outliers"] == [294.64]
    assert d["min"] == 24.08 and d["max"] == 24.13
    assert d["outliers"]["severe"] == 0
    assert r.worst == 24.13


def test_quantile_linear_interpolation():
    # 1..100: p50 sits exactly between the 50th and 51st order stats;
    # p95/p99 interpolate at k = p*(n-1) (the serve family's latency
    # quantiles must match numpy's default 'linear' method)
    s = [float(x) for x in range(1, 101)]
    assert _quantile(s, 0.5) == pytest.approx(50.5)
    assert _quantile(s, 0.95) == pytest.approx(95.05)
    assert _quantile(s, 0.99) == pytest.approx(99.01)
    assert _quantile(s, 0.0) == 1.0 and _quantile(s, 1.0) == 100.0
    import numpy as np

    for p in (0.5, 0.9, 0.95, 0.99):
        assert _quantile(s, p) == pytest.approx(float(np.quantile(s, p)))


def test_quantiles_table_and_benchresult_properties():
    q = quantiles(list(range(1, 101)))
    assert set(q) == {"p50", "p95", "p99"}
    assert q["p50"] <= q["p95"] <= q["p99"]
    # order-independent, single-sample degenerate case, empty rejects
    assert quantiles([3.0, 1.0, 2.0]) == quantiles([1.0, 2.0, 3.0])
    assert quantiles([7.0]) == {"p50": 7.0, "p95": 7.0, "p99": 7.0}
    with pytest.raises(ValueError):
        quantiles([])
    r = BenchResult("serve", "mixed", "16", 100,
                    [float(x) for x in range(1, 101)])
    assert (r.p50, r.p95, r.p99) == (
        pytest.approx(50.5), pytest.approx(95.05), pytest.approx(99.01)
    )
    d = r.to_dict()
    assert d["p50"] == r.p50 and d["p95"] == r.p95 and d["p99"] == r.p99


def test_classify_relative_floor_on_tight_clusters():
    # Near-zero IQR must not turn sub-percent jitter into 'severe'
    # (code-review r4): 0.06% above median is benign on a warm cell.
    c = classify_outliers([24.1201, 24.1214, 24.1216, 24.1219, 24.135])
    assert c["severe"] == 0
    # ...but a genuinely large deviation still flags even when the rest
    # of the cluster is tight.
    c2 = classify_outliers([24.12, 24.121, 24.122, 24.123, 294.6])
    assert c2["severe"] == 1
