"""One-shot flatten integration (engine/downstream_flat.py): differential
vs the batched run merge and the v1 unit merge, plus the downstream
backend at all three wire granularities."""

import numpy as np
import pytest

from crdt_benches_tpu.engine.merge_range import (
    JaxRunDownstreamBackend,
    RunMergeSimulation,
)

from test_merge import sim_for


@pytest.mark.parametrize(
    "seed", [0] + [pytest.param(x, marks=pytest.mark.slow) for x in (3, 7)]
)
@pytest.mark.parametrize("agents", [1, 2, 5])
def test_flat_matches_v1_merge(seed, agents):
    sim = sim_for(seed=seed, n_agents=agents, n_ops=30, batch=8)
    want = sim.decode(sim.merge())
    rm = RunMergeSimulation(sim, batch=8, epoch=2)
    if not rm.fast_ok:
        pytest.skip("no-skip precondition fails for this stream")
    got = rm.decode(rm.merge_flat(n_replicas=2), replica=1)
    assert got == want


def test_flat_empty_and_base_only():
    from crdt_benches_tpu.engine.downstream_flat import flatten_runs
    import jax.numpy as jnp

    # base only, no runs: document = start content
    key = jnp.full((4,), 2**31 - 1, jnp.int32)
    z = jnp.zeros((4,), jnp.int32)
    st = flatten_runs(
        key, z - 1, z, z - 2,
        n_base=3, capacity=128, n_elems=3, n_replicas=2,
    )
    snap = np.asarray(st.snap)
    assert (snap[:, :3] == [0, 1, 2]).all()
    assert (np.asarray(st.nvis) == 3).all()


@pytest.mark.parametrize("granularity", ["patch", "unit", "coalesced"])
@pytest.mark.slow
def test_flat_backend_svelte_byte_identical(svelte_trace, granularity):
    from crdt_benches_tpu.oracle import replay_trace

    want = replay_trace(svelte_trace)
    b = JaxRunDownstreamBackend(n_replicas=2, granularity=granularity)
    b.prepare(svelte_trace)
    assert b.schedule == "flat"
    assert b.final_content() == want


@pytest.mark.slow
def test_flat_schedule_env_fallback(svelte_trace, monkeypatch):
    # CRDT_DOWN_SCHEDULE=batched must still route through merge_runlogs
    from crdt_benches_tpu.oracle import replay_trace

    monkeypatch.setenv("CRDT_DOWN_SCHEDULE", "batched")
    b = JaxRunDownstreamBackend(n_replicas=1, granularity="patch")
    assert b.schedule == "batched"
    b.prepare(svelte_trace)
    assert b.final_content() == replay_trace(svelte_trace)

def _flat_unit_merge(sim, delivered, R=2):
    from crdt_benches_tpu.engine.downstream_flat import make_flat_merge

    return make_flat_merge(sim, delivered, n_replicas=R)()


@pytest.mark.parametrize(
    "seed", [0] + [pytest.param(x, marks=pytest.mark.slow) for x in (2, 5)]
)
@pytest.mark.parametrize("agents", [1, 2, 5])
def test_flat_unit_log_duplicated_shuffled_delivery(seed, agents):
    """The adversarial fault model: every op delivered 3x, shuffled.
    flatten_unit_log must dedup on device and match the v1 merge (unit
    runs make the no-skip precondition vacuous — exact for ANY log)."""
    from crdt_benches_tpu.engine.merge import OpLog

    from test_merge import shuffled_log

    sim = sim_for(seed=seed, n_agents=agents, n_ops=30, batch=8)
    want = sim.decode(sim.merge())
    rng = np.random.default_rng(seed + 41)
    delivered = shuffled_log(OpLog.concat([sim.log] * 3), rng)
    got = sim.decode(_flat_unit_merge(sim, delivered))
    assert got == want


def test_flat_unit_log_plain_union():
    sim = sim_for(seed=9, n_agents=3, n_ops=25, batch=8)
    want = sim.decode(sim.merge())
    assert sim.decode(_flat_unit_merge(sim, sim.log)) == want
