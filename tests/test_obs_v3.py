"""obs/ v3: request-scoped causal tracing, SLO burn-rate accounting,
and the anomaly flight recorder.

Contracts under test:

- the ``--serve-slo`` grammar parses every documented shape and FAILS
  on every malformed one (a typo'd objective silently gating nothing
  is worse than none);
- burn rates follow the multi-window math: violations/budget over the
  fast and slow request windows, a spike separable from a sustained
  burn, compliance cumulative;
- the DISARMED RequestTracker is the identity path: one shared no-op
  segment object, the bare admission-timestamp table, no observer
  installed (the ``@boundary`` / NOOP_SPAN contract);
- episode semantics pin the PR 6 ``_admit_t`` fix: each episode is
  observed exactly once, a re-admitted doc opens a FRESH context with
  its own admission clock — never double-counted under the old one;
- request traces record their publish-point hops (status, journal
  WAL, broadcast bus) and every hop is a subset of the race
  sanitizer's publish counters — the two are one causal picture;
- replica-merge ops are attributed to their ORIGINATING writers and
  sum to the scheduler's merge totals;
- exemplars land in exactly the histogram bucket their latency
  observes into (shared bounds, shared bisect);
- the flight recorder's ring is bounded, its dump is schema-valid and
  atomic, repeated triggers accumulate reasons, and the CLI validator
  gates exactly like the smoke does;
- an anomaly fire (and an anomaly still active at drain end) triggers
  the dump through the telemetry bundle;
- ``tools/bench_compare.py`` gates the drain p99.9 and the SLO
  compliance floor, one-sided like the other obs blocks.
"""

import importlib.util
import json
import sys
import time
from pathlib import Path

import pytest

from crdt_benches_tpu.lint import race_sanitizer
from crdt_benches_tpu.obs.anomaly import AnomalyDetector
from crdt_benches_tpu.obs.flight import (
    FlightRecorder,
    validate_flight,
    validate_flight_file,
)
from crdt_benches_tpu.obs.flight import main as flight_main
from crdt_benches_tpu.obs.reqtrace import (
    NOOP_SEGMENT,
    SEGMENTS,
    RequestTracker,
)
from crdt_benches_tpu.obs.slo import (
    SloSpecError,
    SloTracker,
    parse_slo_spec,
)
from crdt_benches_tpu.obs.timeseries import ServeTelemetry
from crdt_benches_tpu.serve.journal import OpJournal
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import (
    FleetScheduler,
    prepare_streams,
)
from crdt_benches_tpu.serve.workload import build_fleet

REPO = Path(__file__).resolve().parent.parent

TINY_BANDS = {"synth-small": ("synth", (40, 120))}
TINY_MIX = {"synth-small": 1.0}


def _fleet(tmp_path, n=8, seed=11, classes=(128,), slots=(4,),
           bands=TINY_BANDS, mix=TINY_MIX, arrival_span=2, batch=8,
           batch_chars=32, macro_k=4, **kw):
    sessions = build_fleet(
        n, mix=mix, seed=seed, arrival_span=arrival_span, bands=bands
    )
    pool = DocPool(classes=classes, slots=slots,
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(
        sessions, pool, batch=batch, batch_chars=batch_chars
    )
    sched = FleetScheduler(pool, streams, batch=batch, macro_k=macro_k,
                           batch_chars=batch_chars, **kw)
    return sessions, pool, streams, sched


# ---------------------------------------------------------------------------
# the --serve-slo grammar
# ---------------------------------------------------------------------------


def test_slo_spec_grammar_parses_documented_shapes():
    objs = parse_slo_spec("default=p99:250,c4096=p99.9:1500")
    assert set(objs) == {"default", "c4096"}
    assert objs["default"].quantile == pytest.approx(0.99)
    assert objs["default"].threshold_s == pytest.approx(0.250)
    assert objs["default"].budget == pytest.approx(0.01)
    assert objs["c4096"].quantile == pytest.approx(0.999)
    assert objs["c4096"].threshold_s == pytest.approx(1.5)
    # whitespace and a trailing comma are tolerated
    assert set(parse_slo_spec(" default=p90:10 , ")) == {"default"}


@pytest.mark.parametrize("bad", [
    "",                    # names no objective
    "default",             # no '='
    "default=99:250",      # quantile not spelled pQ
    "default=p99",         # no ':MS'
    "default=pXX:250",     # unparsable quantile
    "default=p0:250",      # quantile out of (0, 1)
    "default=p100:250",
    "default=p99:-5",      # non-positive threshold
    "default=p99:nan",     # nan passes a bare <=0 check, gates nothing
    "default=p99:inf",     # infinite threshold gates nothing
    "default=pnan:250",    # nan quantile
    "=p99:250",            # empty class name: unroutable objective
    "default=p99:250,default=p95:100",  # duplicate class
])
def test_slo_spec_grammar_rejects_malformed(bad):
    with pytest.raises(SloSpecError):
        parse_slo_spec(bad)


def test_burn_rate_multi_window_math_and_compliance():
    slo = SloTracker.from_spec("default=p90:100")  # budget = 10%
    st = slo.classes["default"]
    # 60 compliant requests: burn 0 on both windows, compliance 1.0
    for _ in range(60):
        slo.note_request("default", 0.010, doc_id=0)
    assert st.to_dict()["burn_rate_fast"] == 0.0
    assert st.compliance == 1.0
    # a spike: 16 violations — the fast window (64) sees 16/64 = 25%
    # of requests violating against a 10% budget -> burn 2.5; the slow
    # window (512) holds all 76 -> 16/76 ~ 21% -> burn ~2.1; the spike
    # reads HOTTER on the fast window, the separation the two windows
    # exist for
    for _ in range(16):
        slo.note_request("default", 0.500, doc_id=1)
    d = st.to_dict()
    assert d["burn_rate_fast"] == pytest.approx((16 / 64) / 0.10)
    assert d["burn_rate_slow"] == pytest.approx((16 / 76) / 0.10)
    assert d["burn_rate_fast"] > d["burn_rate_slow"]
    assert st.compliance == pytest.approx(1.0 - 16 / 76)
    # an unclassified request never crashes the hot path — counted
    slo.note_request("c9999", 0.001, doc_id=2)
    assert slo.unclassified == 1


def test_slo_classify_prefers_named_class_then_default():
    slo = SloTracker.from_spec("default=p99:250,c4096=p99.9:1500")
    assert slo.classify(4096) == "c4096"
    assert slo.classify(256) == "default"
    assert slo.classify(None) == "default"
    named_only = SloTracker.from_spec("c256=p99:100")
    assert named_only.classify(256) == "c256"
    # no default objective: the budget class still carries the truth
    assert named_only.classify(1024) == "c1024"


def test_slo_top_k_slowest_with_segments():
    slo = SloTracker(parse_slo_spec("default=p99:100"), top_k=3)
    for i in range(8):
        slo.note_request(
            "default", latency_s=float(i), doc_id=i,
            segments={"queue": float(i) / 2},
        )
    worst = slo.slowest()
    assert [e["doc"] for e in worst] == [7, 6, 5]  # worst first, K=3
    assert worst[0]["segments"] == {"queue": 3.5}
    blk = slo.block()
    assert blk["version"] == 1
    assert blk["windows"] == {"fast": 64, "slow": 512}
    assert blk["slow_docs"] == worst


# ---------------------------------------------------------------------------
# request tracker: disarmed identity, episode semantics
# ---------------------------------------------------------------------------


def test_disarmed_tracker_is_the_identity_table():
    before = list(race_sanitizer._publish_observers)
    rt = RequestTracker()  # samples=0, no slo: disarmed
    assert not rt.armed
    # no observer installed, no release needed
    assert race_sanitizer._publish_observers == before
    # one shared no-op segment object (the NOOP_SPAN contract)
    assert rt.segment("plan") is NOOP_SEGMENT
    assert rt.segment("dispatch") is NOOP_SEGMENT
    with rt.segment("plan"):
        pass  # enter/exit are empty
    # the bare admission-timestamp table: open stores a float, close
    # pops it and returns the latency; everything else is a no-op
    rt.open_request(7, 0, cap_cls=128)
    rt.round_begin()
    rt.fold_round(0, [(7, 5)])
    dt = rt.close_request(7, "ok")
    assert dt is not None and dt >= 0
    assert rt.close_request(7, "ok") is None  # already popped
    assert rt.requests_opened == 0  # armed-side counters untouched
    assert rt.sampled() == [] and rt._active == {}


def test_armed_tracker_episode_semantics_and_exactly_once(tmp_path):
    rt = RequestTracker(samples=4)
    try:
        assert rt.armed
        rt.open_request(3, 0, cap_cls=128)
        rt.open_request(3, 1, cap_cls=128)  # already active: no-op
        assert rt.requests_opened == 1
        time.sleep(0.01)
        dt1 = rt.close_request(3, "quarantined", round_no=2)
        assert dt1 is not None and dt1 >= 0.01
        # exactly once per episode: a second close records nothing
        assert rt.close_request(3, "quarantined") is None
        assert rt.requests_closed == 1
        # re-admission opens a FRESH context: new episode, new id, the
        # admission clock restarted (the PR 6 _admit_t scheme kept one
        # doc-keyed timestamp, double-counting the rebuilt episode)
        t_re = time.perf_counter()
        rt.open_request(3, 5, cap_cls=128)
        ctx = rt._active[3]
        assert ctx.episode == 2 and rt.reopened == 1
        assert ctx.admit_t >= t_re
        dt2 = rt.close_request(3, "ok", round_no=6)
        assert rt.requests_closed == 2
        # episode 2 measured from ITS OWN admission, not episode 1's
        assert dt2 < dt1
        eps = [t["episode"] for t in rt.sampled()]
        assert eps == [1, 2]
        causes = [t["cause"] for t in rt.sampled()]
        assert causes == ["quarantined", "ok"]
    finally:
        rt.release()
    # release dropped the observer (idempotent)
    rt.release()
    assert rt._on_publish not in race_sanitizer._publish_observers


def test_scheduler_readmission_opens_fresh_episode(tmp_path):
    """The fix pin at the scheduler surface: `_note_doc_drained` +
    `open_request` on a real FleetScheduler observe each EPISODE
    exactly once in the cause-tagged histograms."""
    rt = RequestTracker(samples=8)
    try:
        _s, _p, _st, sched = _fleet(tmp_path, reqtrace=rt)
        doc = next(iter(sched.streams))
        st = sched.streams[doc]
        sched.reqtrace.open_request(doc, 0, cap_cls=128)
        time.sleep(0.005)
        sched._note_doc_drained(st, tag="quarantined")
        h_q = sched.stats.doc_latency["quarantined"]
        assert h_q.count == 1 and rt.requests_closed == 1
        # the old double-count shape: a second drain note for the same
        # episode must record NOTHING
        sched._note_doc_drained(st, tag="quarantined")
        assert h_q.count == 1 and rt.requests_closed == 1
        # re-admitted (quarantine-rebuild / the ingest refill to come):
        # a fresh episode, closed under its own cause and clock
        sched.reqtrace.open_request(doc, 3, cap_cls=128)
        sched._note_doc_drained(st, tag="ok")
        assert sched.stats.doc_latency["ok"].count == 1
        assert rt.requests_closed == 2 and rt.reopened == 1
        # total histogram observations == closed episodes: no loss, no
        # double count
        total = sum(
            h.count for h in sched.stats.doc_latency.values()
        )
        assert total == rt.requests_closed
    finally:
        rt.release()


def test_dropped_requests_burn_error_budget():
    """A shed/quarantined close is an SLO violation no matter how
    fast the drop was — dropped traffic reading as compliant would
    let a mass-shed regression sail through the compliance gate."""
    slo = SloTracker.from_spec("default=p90:60000")
    rt = RequestTracker(samples=8, slo=slo)
    try:
        rt.open_request(1, 0)
        rt.close_request(1, "ok", round_no=1)       # fast, served
        rt.open_request(2, 0)
        rt.close_request(2, "shed", round_no=1)     # fast, DROPPED
        rt.open_request(3, 0)
        rt.close_request(3, "quarantined", round_no=1)
        rt.open_request(4, 0)
        rt.close_request(4, "deferred", round_no=1)  # late but served
        st = slo.classes["default"]
        assert st.requests == 4 and st.violations == 2
        blk = slo.block()["classes"]["default"]
        assert blk["compliance"] == pytest.approx(0.5)
    finally:
        rt.release()


def test_round_hops_attach_only_to_scheduled_docs():
    """Hops scope to the round's LANE SET: a doc closed mid-round while
    not scheduled (deferred off a lost shard, then quarantined) must
    not be stamped with publish edges its data never rode, while a
    scheduled doc closed after the WAL publish keeps them."""
    rt = RequestTracker(samples=8)
    try:
        rt.open_request(1, 0, cap_cls=128)
        rt.open_request(2, 0, cap_cls=128)
        rt.round_begin()
        rt.note_scheduled([1])  # doc 2 deferred out of this round
        rt._on_publish("OpJournal.round_record")  # the WAL fires
        rt.close_request(2, "quarantined", round_no=0)
        # trailing publish (the end-of-round status snapshot fires
        # AFTER fold/close): round_begin unions it into the prior lane
        # set's still-active contexts — doc 1 gets the status edge,
        # the closed doc 2 stays untouched
        rt._on_publish("StatusServer.publish_status")
        rt.round_begin()
        rt.close_request(1, "ok", round_no=1)
        by_doc = {t["doc"]: t for t in rt.sampled()}
        assert by_doc[1]["hops"] == [
            "OpJournal.round_record", "StatusServer.publish_status"
        ]
        assert by_doc[2]["hops"] == []
    finally:
        rt.release()


def test_malformed_slo_spec_fails_before_resources(tmp_path, monkeypatch):
    """A malformed --serve-slo spec fails the run BEFORE the journal
    tempdir / telemetry threads are acquired — the resource-releasing
    finally is never reached, so there must be nothing to release."""
    from crdt_benches_tpu.serve import bench as serve_bench

    acquired = []
    monkeypatch.setattr(
        serve_bench.tempfile, "mkdtemp",
        lambda *a, **k: acquired.append("journal") or str(tmp_path / "j"),
    )
    monkeypatch.setattr(
        serve_bench, "build_telemetry",
        lambda **k: acquired.append("telemetry"),
    )
    with pytest.raises(SloSpecError):
        serve_bench.run_serve_bench(
            mix=TINY_MIX, bands=TINY_BANDS, n_docs=2,
            journal_dir="auto", status_port=0,
            slo_spec="default=99:250",  # missing the 'p'
            results_dir=str(tmp_path),
        )
    assert acquired == []


# ---------------------------------------------------------------------------
# armed drains: segments, hops, exemplars, SLO block
# ---------------------------------------------------------------------------


def test_armed_drain_traces_requests_with_segments(tmp_path):
    slo = SloTracker.from_spec("default=p99:60000")
    rt = RequestTracker(samples=64, slo=slo)
    try:
        _s, _p, streams, sched = _fleet(
            tmp_path, n=8, reqtrace=rt, slo=slo
        )
        stats = sched.run()
        assert sched.done
        assert rt.requests_opened == len(streams)
        assert rt.requests_closed == rt.requests_opened
        assert not rt._active
        traces = rt.sampled()
        assert len(traces) == len(streams)
        for t in traces:
            assert t["cause"] == "ok"
            assert t["rounds"] >= 1 and t["ops"] >= 1
            assert t["latency_s"] > 0
            assert set(t["segments"]) <= set(SEGMENTS)
            # a drained doc spent time in the timed phases
            assert sum(t["segments"].values()) > 0
            assert t["segments"].get("plan", 0) >= 0
        # ops fold exactly: per-trace ops sum to the drain total
        assert sum(t["ops"] for t in traces) == stats.ops
        # every request landed in the (generous) objective
        blk = slo.block()
        assert blk["classes"]["default"]["requests"] == len(streams)
        assert blk["classes"]["default"]["compliance"] == 1.0
        assert blk["classes"]["default"]["violations"] == 0
        assert [e["latency_s"] for e in blk["slow_docs"]] == sorted(
            (e["latency_s"] for e in blk["slow_docs"]), reverse=True
        )
        # the artifact block round-trips through JSON
        rb = json.loads(json.dumps(rt.block()))
        assert rb["version"] == 1 and rb["armed"] is True
        assert rb["requests_closed"] == len(streams)
    finally:
        rt.release()


def test_trace_hops_cover_declared_publish_points(tmp_path):
    """With the journal armed, every trace's WAL hop is recorded, and
    the hop set is a subset of the race sanitizer's publish counters —
    the G017 ground truth (the smoke cross-checks the same invariant
    on the full artifact)."""
    race_sanitizer.reset_counters()
    rt = RequestTracker(samples=64)
    journal = OpJournal(str(tmp_path / "wal"))
    try:
        _s, _p, streams, sched = _fleet(
            tmp_path, n=6, reqtrace=rt, journal=journal,
        )
        sched.run()
        assert sched.done
        assert rt.hop_counts.get("OpJournal.round_record", 0) >= 1
        publishes = set(race_sanitizer.counters()["publishes"])
        assert set(rt.hop_counts) <= publishes
        traces = rt.sampled()
        assert traces
        for t in traces:
            assert set(t["hops"]) <= set(rt.hop_counts)
            # every drained doc rode at least one WAL record
            assert "OpJournal.round_record" in t["hops"]
    finally:
        rt.release()
        journal.close()


def test_exemplars_agree_with_histogram_buckets(tmp_path):
    rt = RequestTracker(samples=64)
    try:
        _s, _p, streams, sched = _fleet(tmp_path, n=8, reqtrace=rt)
        sched.run()
        assert sched.done
        assert rt.exemplars, "no exemplar sampled over a full drain"
        from bisect import bisect_left
        for tag, buckets in rt.exemplars.items():
            h = sched.stats.doc_latency[tag]
            for i, ex in buckets.items():
                # the exemplar's bucket is exactly where its latency
                # observes into the histogram (shared bounds + bisect)
                assert bisect_left(h.bounds, float(ex["latency_s"])) == i
                assert h.counts[i] >= 1, (
                    f"exemplar in empty bucket {tag}[{i}]"
                )
        # the artifact block serializes bucket indices as strings
        blk = rt.block()
        for tag, buckets in blk["exemplars"].items():
            assert all(isinstance(k, str) for k in buckets)
    finally:
        rt.release()


def test_replica_merge_attributed_to_originating_writer(tmp_path):
    from crdt_benches_tpu.serve.replicate.bench import (
        run_serve_repl_bench,
    )

    r, info = run_serve_repl_bench(
        mix=TINY_MIX, n_docs=4, writers=2, batch=16, macro_k=4,
        batch_chars=64, classes=(128,), slots=(8,), bands=TINY_BANDS,
        arrival_span=2, turn_ops=8, seed=3,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        reqtrace_samples=64,
        log=lambda *_a, **_k: None,
    )
    assert info["verify_ok"]
    sched = info["scheduler"]
    rt = sched.reqtrace
    # the bus hop is on the causal picture
    assert rt.hop_counts.get("BroadcastBus._cross_block", 0) >= 1
    traces = rt.sampled()
    assert traces
    merged_by_trace = 0
    for t in traces:
        # writers=2: every remote op came from writer 0 or 1, and a
        # replica never attributes its OWN writer's ops as remote
        w_self = t["doc"] % 2
        assert set(t["remote_ops"]) <= {"0", "1"} - {str(w_self)}
        merged_by_trace += sum(t["remote_ops"].values())
    # attribution partitions the merge total exactly
    assert merged_by_trace == sched.merged_ops
    # the artifact carries the block
    with open(info["path"]) as f:
        (d,) = json.load(f)
    assert d["extra"]["reqtrace"]["requests_closed"] == len(traces)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def _rounds(rec, n, t0=0.0):
    for i in range(n):
        rec.note_round({"round": i, "seconds": t0 + 0.01})


def test_flight_dump_roundtrip_bounded_ring_and_validator(tmp_path, capsys):
    path = str(tmp_path / "flight.json")
    rec = FlightRecorder(path, ring=4)
    _rounds(rec, 10)
    assert rec.rounds_seen == 10 and len(rec.rounds) == 4
    rec.trigger(
        "anomaly:stuck_round",
        requests=[{"doc": 3, "request": 0, "segments": {}}],
        anomalies=["stuck_round"],
    )
    assert validate_flight_file(path) == []
    with open(path) as f:
        d = json.load(f)
    assert d["version"] == 1 and d["dump_index"] == 1
    assert [r["round"] for r in d["rounds"]] == [6, 7, 8, 9]  # last 4
    assert d["requests"][0]["doc"] == 3
    assert d["anomalies"] == ["stuck_round"]
    assert d["metrics"] is None
    # a later trigger REPLACES the file; reasons accumulate
    rec.note_round({"round": 10, "seconds": 0.5})
    rec.trigger("unrecovered_fault")
    with open(path) as f:
        d2 = json.load(f)
    assert d2["dump_index"] == 2
    assert d2["reasons"] == ["anomaly:stuck_round", "unrecovered_fault"]
    assert d2["rounds"][-1]["round"] == 10
    assert rec.summary()["dumps"] == 2
    # the CLI validator the chaos smoke gates on
    assert flight_main([path]) == 0
    out = capsys.readouterr().out
    assert "valid flight dump" in out
    assert flight_main([]) == 2  # usage
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert flight_main([str(bad)]) == 1


def test_flight_dump_is_best_effort_on_unwritable_path(tmp_path):
    """A dump that cannot be written must never raise out of the
    trigger — it would kill a run the anomaly would have cleared (or,
    on the crash path, replace the exception it documents).  Failures
    are counted and surfaced in the artifact's flight block."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("")  # a FILE where the dump wants a directory
    rec = FlightRecorder(str(blocker / "flight.json"))
    rec.note_round({"round": 0, "seconds": 0.1})
    rec.trigger("anomaly:stuck_round")  # must not raise
    s = rec.summary()
    assert s["dumps"] == 0 and s["dump_failures"] == 1
    assert s["last_error"] and s["reasons"] == ["anomaly:stuck_round"]
    # an unserializable snapshot is the same contract
    ok = FlightRecorder(str(tmp_path / "flight.json"))
    ok.note_round({"round": 0, "seconds": 0.1})
    ok.trigger("x", status={"bad": object()})  # must not raise
    assert ok.summary()["dump_failures"] == 1
    # ...and a later healthy trigger still dumps, with a clean index
    ok.trigger("y")
    assert ok.summary()["dumps"] == 1
    with open(tmp_path / "flight.json") as f:
        d = json.load(f)
    assert d["dump_index"] == 1 and d["reasons"] == ["x", "y"]


def test_flight_validator_rejects_structural_damage():
    assert validate_flight([]) == ["top level must be an object"]
    good = {
        "version": 1, "reason": "x", "dump_index": 1,
        "rounds": [{"round": 0, "seconds": 0.1}],
        "requests": [], "metrics": None, "anomalies": [],
    }
    assert validate_flight(good) == []
    for mutate, frag in [
        (lambda d: d.update(version=2), "version"),
        (lambda d: d.update(reason=""), "reason"),
        (lambda d: d.update(dump_index=0), "dump_index"),
        (lambda d: d.update(rounds=[]), "rounds is empty"),
        (lambda d: d.update(rounds=[{"seconds": 1.0}]), "'round'"),
        (lambda d: d.update(rounds=[{"round": 1}]), "'seconds'"),
        (lambda d: d.update(requests=[{"nope": 1}]), "requests[0]"),
        (lambda d: d.update(metrics={"no": "version"}), "metrics"),
        (lambda d: d.update(anomalies=None), "anomalies"),
    ]:
        d = json.loads(json.dumps(good))
        mutate(d)
        errs = validate_flight(d)
        assert errs and any(frag in e for e in errs), (frag, errs)


def test_anomaly_fire_triggers_flight_dump_through_telemetry(tmp_path):
    path = str(tmp_path / "flight.json")
    tel = ServeTelemetry(
        anomaly=AnomalyDetector(watchdog_s=0.05),
        flight=FlightRecorder(path),
    )

    def round_(i, secs):
        tel.note_round(
            round_no=i, seconds=secs, compiled=False, barrier=False,
            occupancy=0.5, queue_depth=0, cum={"ops": 100 * (i + 1)},
            shard_lanes=[1], shard_ops=[100], shard_units=[100],
            status={"round": i},
        )

    for i in range(5):
        round_(i, 0.01)
    assert not Path(path).exists()  # healthy rounds never dump
    round_(5, 0.2)  # trips the watchdog
    assert Path(path).exists()
    assert validate_flight_file(path) == []
    with open(path) as f:
        d = json.load(f)
    assert d["reason"].startswith("anomaly:stuck_round")
    assert [r["round"] for r in d["rounds"]] == [0, 1, 2, 3, 4, 5]
    assert d["anomalies"] == ["stuck_round"]
    assert d["status"]["round"] == 5
    # the fire is dumped ONCE, not re-dumped every later round
    round_(6, 0.01)  # clears the watchdog
    with open(path) as f:
        assert json.load(f)["dump_index"] == 1
    # a STILL-ACTIVE anomaly at drain end dumps the post-mortem the
    # exit code used to discard
    round_(7, 0.3)
    tel.drain_end({"phase": "done"})
    with open(path) as f:
        d = json.load(f)
    assert d["dump_index"] == 3  # fire at 7, then the drain-end dump
    assert d["reason"].startswith("drain_end_active_anomaly:")
    assert d["reasons"][0].startswith("anomaly:")


def test_run_serve_bench_flight_via_telemetry_stays_quiet_when_clean(tmp_path):
    """An armed flight recorder on a CLEAN drain writes nothing; the
    artifact's flight block says where a dump would have gone."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    flight = str(tmp_path / "flight.json")
    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=8, batch=16, classes=(128,), slots=(8,),
        seed=2, arrival_span=2, verify_sample=2, bands=TINY_BANDS,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        flight_path=flight,
        reqtrace_samples=8, slo_spec="default=p99:60000",
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    assert not Path(flight).exists()
    with open(info["path"]) as f:
        (d,) = json.load(f)
    fb = d["extra"]["flight"]
    assert fb["path"] == flight and fb["dumps"] == 0
    assert fb["rounds_seen"] == d["extra"]["rounds"]
    # reqtrace + slo blocks ride the same artifact
    assert d["extra"]["reqtrace"]["requests_closed"] == 8
    assert d["extra"]["slo"]["classes"]["default"]["requests"] == 8
    # hops ⊆ the artifact's thread-crossing publishes (the smoke's
    # cross-check, at unit scale)
    pubs = set(d["extra"]["thread_crossings"]["publishes"])
    for t in d["extra"]["reqtrace"]["traces"]:
        assert set(t["hops"]) <= pubs
    # boundary_syncs accounts the flight fence per DRAIN: no dump this
    # run, surface unarmed for G011
    assert d["extra"]["boundary_syncs"]["flight"] is False


def test_soak_shared_recorder_flight_surface_is_per_drain(tmp_path):
    """Under soak the flight recorder is shared across iterations: a
    clean drain after an earlier iteration's dump must record
    boundary_syncs.flight=False (its own fence counters were reset, so
    inheriting the cumulative dump would hand G011 a false dead
    fence)."""
    from crdt_benches_tpu.serve.bench import build_telemetry, \
        run_serve_bench

    flight = str(tmp_path / "flight.json")
    telemetry = build_telemetry(flight_path=flight, log=lambda *_: None)
    # "iteration 1" dumped (anomaly fired in an earlier soak drain)
    telemetry.flight.trigger("anomaly:stuck_round")
    assert telemetry.flight.dumps == 1
    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=8, batch=16, classes=(128,), slots=(8,),
        seed=2, arrival_span=2, verify_sample=2, bands=TINY_BANDS,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        telemetry=telemetry,
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    with open(info["path"]) as f:
        (d,) = json.load(f)
    assert d["extra"]["flight"]["dumps"] == 1  # cumulative block
    assert d["extra"]["boundary_syncs"]["flight"] is False  # per-drain


def test_disarmed_artifact_carries_no_v3_blocks(tmp_path):
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=4, batch=16, classes=(128,), slots=(4,),
        seed=5, arrival_span=2, verify_sample=2, bands=TINY_BANDS,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    with open(info["path"]) as f:
        (d,) = json.load(f)
    assert d["extra"]["reqtrace"] is None
    assert d["extra"]["slo"] is None
    assert d["extra"]["flight"] is None


def test_armed_overhead_smoke(tmp_path):
    """Tracing + SLO accounting at smoke scale stays in the same cost
    regime as the disarmed drain (the exact ≤2% acceptance runs at
    full fleet scale through bench_compare — a unit-scale 2% timing
    assertion would be flake, so this bound is deliberately loose)."""
    def drain(arm):
        rt = RequestTracker(
            samples=64 if arm else 0,
            slo=SloTracker.from_spec("default=p99:60000") if arm
            else None,
        )
        try:
            _s, _p, _st, sched = _fleet(
                tmp_path, n=8, seed=7, reqtrace=rt, slo=rt.slo
            )
            t0 = time.perf_counter()
            sched.run()
            return time.perf_counter() - t0
        finally:
            rt.release()

    drain(False)  # warm compile caches out of the measurement
    plain = min(drain(False) for _ in range(2))
    armed = min(drain(True) for _ in range(2))
    assert armed <= plain * 1.5 + 0.05


# ---------------------------------------------------------------------------
# bench_compare: the obs/ v3 gates
# ---------------------------------------------------------------------------


def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare_v3", REPO / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare_v3"] = mod
    spec.loader.exec_module(mod)
    return mod


def _artifact(tmp_path, name, *, pps=100_000.0, p999=0.8,
              compliance=0.995, requests=900, with_v3=True):
    extra = {
        "family": "serve",
        "patches_per_sec": pps,
        "batch_latency": {"p50": 0.002, "p95": 0.004, "p99": 0.005},
        "rounds": 20,
        "range_ops": 10_000,
        "journal": None,
        "boundary_syncs": {"entries": {"DocPool.block": 40}},
    }
    if with_v3:
        extra["doc_drain_latency"] = {
            "ok": {"count": 1000, "quantiles": {
                "p50": 0.1, "p99": 0.5, "p99.9": p999,
            }},
        }
        extra["slo"] = {
            "version": 1,
            "classes": {
                "default": {"requests": requests,
                            "compliance": compliance},
                "c4096": {"requests": 100, "compliance": 0.999},
                "idle": {"requests": 0, "compliance": 1.0},
            },
        }
    data = [{"group": "serve", "trace": "mixed", "backend": "512",
             "extra": extra}]
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_bench_compare_gates_drain_p999_and_slo_floor(tmp_path, capsys):
    bc = _bench_compare()
    base = _artifact(tmp_path, "base.json")
    same = _artifact(tmp_path, "same.json")
    assert bc.main([same, base]) == 0

    # p99.9 doubled: past even the loose default 75% gate
    slow = _artifact(tmp_path, "slow.json", p999=1.8)
    assert bc.main([slow, base]) == 1
    assert "doc drain p99.9" in capsys.readouterr().out

    # violation FLOOR: the worst class with traffic is what gates —
    # default violations grow 0.5% -> 10% of requests (+9.5 points
    # AND a 20x budget blow-up) while c4096 stays perfect
    burn = _artifact(tmp_path, "burn.json", compliance=0.90)
    assert bc.main([burn, base]) == 1
    assert "slo compliance floor" in capsys.readouterr().out
    # the blow-up fails even at a loose points threshold: a 20x error
    # budget explosion is never "within threshold"
    assert bc.main([burn, base, "--max-slo-regress", "15"]) == 1
    # points threshold honored when growth is proportionate (10% ->
    # 20% of requests: +10 points, 2x — under 15 points, no blow-up)
    loose_base = _artifact(tmp_path, "loose_base.json", compliance=0.90)
    loose_new = _artifact(tmp_path, "loose_new.json", compliance=0.80)
    assert bc.main([loose_new, loose_base]) == 1  # default 5 points
    assert bc.main(
        [loose_new, loose_base, "--max-slo-regress", "15"]
    ) == 0
    # the saturation case a relative-compliance gate misses: 0.1% ->
    # 5% violations is a 50x budget blow-up but only a 4.9%/-4.9pt
    # compliance dip — must STILL fail
    tight_base = _artifact(tmp_path, "tight_base.json",
                           compliance=0.999)
    blowout = _artifact(tmp_path, "blowout.json", compliance=0.950)
    assert bc.main([blowout, tight_base]) == 1
    # ...but ONE dropped request in a 24-request smoke vs a clean
    # baseline is a blip the min-violation-count floor absorbs (a
    # fraction floor alone would fail it: 1/24 = 4.2% from zero)
    smoke_base = _artifact(tmp_path, "smoke_base.json",
                           compliance=1.0, requests=24)
    smoke_blip = _artifact(tmp_path, "smoke_blip.json",
                           compliance=23 / 24, requests=24)
    assert bc.main([smoke_blip, smoke_base]) == 0

    # improvements never fail
    better = _artifact(tmp_path, "better.json", p999=0.4,
                       compliance=0.999)
    assert bc.main([better, base]) == 0


def test_bench_compare_v3_blocks_are_one_sided(tmp_path, capsys):
    bc = _bench_compare()
    old = _artifact(tmp_path, "old.json", with_v3=False)
    new = _artifact(tmp_path, "new.json")
    # either direction: skip-with-note, never a failure or exit 2
    assert bc.main([new, old]) == 0
    out = capsys.readouterr().out
    assert "SKIP" in out and "slo" in out
    assert bc.main([old, new]) == 0
