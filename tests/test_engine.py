"""JAX engine correctness: resolve+apply vs the pure-Python oracle,
byte-identical (the upgrade over the reference's length-only assert,
src/main.rs:35)."""

import numpy as np
import pytest

from crdt_benches_tpu.oracle import replay_unit_ops
from crdt_benches_tpu.traces import tensorize
from crdt_benches_tpu.traces.loader import TestData, TestTxn, TestPatch
from crdt_benches_tpu.traces.tensorize import TensorizedTrace, DELETE, INSERT
from crdt_benches_tpu.engine.replay import ReplayEngine, replay_trace_jax


def tensorize_ops(kinds, poss, chs, batch=8, start=""):
    """Build a TensorizedTrace directly from unit ops (test helper)."""
    kind = np.asarray(kinds, np.int32)
    pos = np.asarray(poss, np.int32)
    ch = np.asarray(chs, np.int32)
    n = len(kind)
    n_pad = (-n) % batch if n else batch
    kind = np.concatenate([kind, np.zeros(n_pad, np.int32)])
    pos = np.concatenate([pos, np.zeros(n_pad, np.int32)])
    ch = np.concatenate([ch, np.zeros(n_pad, np.int32)])
    init = np.asarray([ord(c) for c in start], np.int32)
    s = len(init)
    is_ins = kind == INSERT
    slot = np.where(is_ins, s + np.cumsum(is_ins) - 1, -1).astype(np.int32)
    n_ins = int(is_ins.sum())
    return TensorizedTrace(
        kind=kind, pos=pos, ch=ch, slot=slot, init_chars=init,
        n_ops=n, n_patches=n, n_inserts=n_ins, capacity=s + n_ins,
        batch=batch, end_content="",
    )


def check(kinds, poss, chs, batch=8, start=""):
    tt = tensorize_ops(kinds, poss, chs, batch=batch, start=start)
    want = replay_unit_ops(
        tt.kind[: tt.n_ops], tt.pos[: tt.n_ops], tt.ch[: tt.n_ops], start=start
    )
    got = replay_trace_jax(tt)
    assert got == want, f"got {got!r} want {want!r}"


A, B_, C_ = ord("a"), ord("b"), ord("c")


@pytest.mark.slow
def test_append_only():
    check([INSERT] * 4, [0, 1, 2, 3], [A, B_, C_, A])


def test_insert_at_head_repeatedly():
    check([INSERT] * 4, [0, 0, 0, 0], [A, B_, C_, A])


def test_insert_middle():
    # "ab" then 'c' between them
    check([INSERT] * 3, [0, 1, 1], [A, B_, C_])


def test_delete_simple():
    check([INSERT, INSERT, DELETE], [0, 1, 0], [A, B_, 0])


def test_delete_batch_insert_same_batch():
    # insert 3, delete the middle one, insert again at that spot
    check(
        [INSERT, INSERT, INSERT, DELETE, INSERT],
        [0, 1, 2, 1, 1],
        [A, B_, C_, 0, A],
    )


@pytest.mark.slow
def test_cross_batch_boundary():
    # batch=2 forces resolution state handoff across batches
    check([INSERT] * 5 + [DELETE] * 2, [0, 0, 1, 3, 2, 1, 1], [A, B_, C_, A, B_, 0, 0], batch=2)


def test_with_start_content():
    check([INSERT, DELETE, INSERT], [3, 0, 4], [A, 0, B_], start="xyz")


def test_delete_then_insert_at_same_pos_across_batches():
    check([INSERT, INSERT, DELETE, INSERT], [0, 1, 0, 0], [A, B_, 0, C_], batch=2)


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("batch", [4, 16, 64])
@pytest.mark.slow
def test_random_streams(seed, batch):
    """Property test: random valid unit-op streams, byte-identical replay."""
    rng = np.random.default_rng(seed)
    n = 300
    doc_len = 0
    kinds, poss, chs = [], [], []
    for _ in range(n):
        if doc_len == 0 or rng.random() < 0.65:
            kinds.append(INSERT)
            poss.append(int(rng.integers(0, doc_len + 1)))
            chs.append(int(rng.integers(A, A + 26)))
            doc_len += 1
        else:
            kinds.append(DELETE)
            poss.append(int(rng.integers(0, doc_len)))
            chs.append(0)
            doc_len -= 1
    check(kinds, poss, chs, batch=batch)


@pytest.mark.slow
def test_svelte_full_trace_byte_identical(svelte_trace):
    """Config 2 of BASELINE.json: sveltecomponent, 1 replica, CPU JAX backend,
    byte-identical final document."""
    tt = tensorize(svelte_trace, batch=256)
    got = replay_trace_jax(tt)
    assert got == svelte_trace.end_content


@pytest.mark.slow
def test_vmap_replicas_agree(svelte_trace):
    """4 replicas replaying the same trace must all converge byte-identically
    (the de-facto cross-implementation agreement test of the reference,
    SURVEY.md section 4.3)."""
    tt = tensorize(svelte_trace, batch=256)
    eng = ReplayEngine(tt, n_replicas=4)
    state = eng.run_blocking()
    assert (eng.lengths(state) == len(svelte_trace.end_content)).all()
    for r in (0, 3):
        assert eng.decode(state, replica=r) == svelte_trace.end_content


@pytest.mark.slow
def test_flagship_model_api(svelte_trace):
    import numpy as np

    from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
    from crdt_benches_tpu.models.flagship import FlagshipConfig, upstream

    # Default config IS the headline configuration bench.py runs.
    dflt = FlagshipConfig()
    assert (dflt.n_replicas, dflt.batch) == (1024, 1536)
    assert dflt.layout == "auto" and dflt.range_engine == "v4"

    # Small-shape instance of the same path: auto layout must resolve to
    # the coalesced range engine with the v4 fused apply on a real trace.
    cfg = FlagshipConfig(n_replicas=2, batch=256)
    eng = upstream(svelte_trace, cfg)
    assert isinstance(eng, RangeReplayEngine)
    assert eng.engine == "v4"
    st = eng.run()
    assert (np.asarray(st.nvis) == len(svelte_trace.end_content)).all()
    assert eng.decode(st, replica=1) == svelte_trace.end_content

    # The unit engine remains reachable as the differential twin.
    from crdt_benches_tpu.engine.replay import ReplayEngine

    ucfg = FlagshipConfig(n_replicas=2, batch=256, layout="unit",
                          resolver="scan")
    ueng = upstream(svelte_trace, ucfg)
    assert isinstance(ueng, ReplayEngine)
    ust = ueng.run()
    assert (np.asarray(ust.nvis) == len(svelte_trace.end_content)).all()
