"""Streaming fleet construction: lazy ``FleetSpec`` sessions, genesis
residency, ``LazyStreams`` materialization edges, prefetch-thread
tensorization, and the construction-cost accounting that gates it all.

Ground truth is double-ended: the streaming path must match the eager
path byte-for-byte (same seed => same fleet), and both must match an
uninterrupted oracle replay of the same traces."""

import importlib.util
import json
import sys
import time
from functools import partial
from pathlib import Path

import numpy as np
import pytest

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.bench import run_serve_bench
from crdt_benches_tpu.serve.construction import probe, scaling_table
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.prefetch import Prefetcher
from crdt_benches_tpu.serve.scheduler import (
    FleetScheduler,
    LazyStreams,
    build_stream_payload,
    prepare_streams,
)
from crdt_benches_tpu.serve.workload import FleetSpec, build_fleet

REPO = Path(__file__).resolve().parent.parent

TINY_BANDS = {"synth-small": ("synth", (40, 120))}
TINY_MIX = {"synth-small": 1.0}
TWO_BANDS = {
    "synth-small": ("synth", (40, 120)),
    "synth-medium": ("synth", (300, 600)),
}
TWO_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


def _spec(n=12, seed=7, arrival_span=3, **kw):
    kw.setdefault("mix", TINY_MIX)
    kw.setdefault("bands", TINY_BANDS)
    return FleetSpec.build(n, seed=seed, arrival_span=arrival_span, **kw)


def _lazy_fleet(tmp_path, n=12, seed=7, classes=(128,), slots=(3,),
                warm_docs=0, bands=TINY_BANDS, mix=TINY_MIX, **kw):
    spec = FleetSpec.build(n, mix=mix, seed=seed, arrival_span=2,
                           bands=bands)
    pool = DocPool(classes=classes, slots=slots,
                   spool_dir=str(tmp_path / "lspool"),
                   warm_docs=warm_docs)
    streams = LazyStreams(spec, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32, **kw)
    return spec, pool, streams, sched


# ---------------------------------------------------------------------------
# FleetSpec: seed-stable arithmetic fleet
# ---------------------------------------------------------------------------


def test_fleet_spec_matches_eager_builder_exactly():
    """Same seed => same fleet: band, arrival, source, and the full
    trace, doc by doc, across a two-band mix (exercising the lazy
    trace-ordinal bookkeeping too)."""
    n, seed = 40, 13
    spec = FleetSpec.build(n, mix=TWO_MIX, seed=seed, arrival_span=4,
                           bands=TWO_BANDS)
    eager = build_fleet(n, mix=TWO_MIX, seed=seed, arrival_span=4,
                        bands=TWO_BANDS)
    assert len(eager) == spec.n_docs == n
    for s in eager:
        lazy = spec.session(s.doc_id)
        assert lazy.band == s.band
        assert lazy.arrival == s.arrival
        assert lazy.source == s.source
        # TestData is a dataclass tree: == is deep byte equality
        assert lazy.trace == s.trace, f"doc {s.doc_id} diverged"


def test_fleet_spec_session_is_random_access():
    """Materializing docs out of order, repeatedly, yields identical
    sessions — nothing in the spec mutates on access."""
    spec = _spec(n=10, seed=3)
    a = spec.session(7)
    spec.session(2), spec.session(9)
    b = spec.session(7)
    assert a.trace == b.trace and a.arrival == b.arrival
    with pytest.raises(IndexError):
        spec.session(10)
    with pytest.raises(IndexError):
        spec.session(-1)


def test_zipf_arrivals_in_range_and_head_heavy():
    """``arrival_dist="zipf"`` keeps every arrival inside
    ``[0, arrival_span)``, lands more docs in the head round than the
    tail round, and is seed-deterministic against the eager builder."""
    span = 8
    spec = _spec(n=600, seed=5, arrival_span=span, arrival_dist="zipf")
    arr = spec.arrivals
    assert arr.min() >= 0 and arr.max() < span
    head = int((arr == 0).sum())
    tail = int((arr == span - 1).sum())
    assert head > tail > 0
    eager = build_fleet(600, mix=TINY_MIX, seed=5, arrival_span=span,
                        bands=TINY_BANDS, arrival_dist="zipf")
    assert [int(a) for a in arr] == [s.arrival for s in eager]


# ---------------------------------------------------------------------------
# genesis residency
# ---------------------------------------------------------------------------


def test_genesis_population_drains_through_register(tmp_path):
    """Every doc starts in genesis (no pool record at all); each first
    registration moves exactly one doc genesis -> tracked, and repeat
    registrations do not double-count."""
    pool = DocPool(classes=(128,), slots=(4,),
                   spool_dir=str(tmp_path / "spool"))
    chars = np.full(4, ord("a"), np.int32)
    pool.set_genesis_population(3)
    assert pool.genesis_docs == 3
    pool.register(0, n_init=4, capacity_need=16, chars=chars)
    assert pool.genesis_docs == 2
    pool.register(0, n_init=4, capacity_need=16, chars=chars)
    assert pool.genesis_docs == 2  # re-register is not a genesis exit
    pool.register(1, n_init=4, capacity_need=16, chars=chars)
    pool.register(2, n_init=4, capacity_need=16, chars=chars)
    assert pool.genesis_docs == 0
    assert pool.tier_status()["genesis_docs"] == 0
    pool.close()


def test_lazy_streams_genesis_gauge_reaches_zero(tmp_path):
    """A lazy fleet is born fully genesis; a full drain materializes
    every doc, so the gauge ends at zero."""
    spec, pool, streams, sched = _lazy_fleet(tmp_path, n=8)
    assert pool.genesis_docs == 8
    assert streams.materialized == 0
    sched.run()
    assert sched.done and streams.all_done
    assert pool.genesis_docs == 0
    assert streams.materialized == 8


# ---------------------------------------------------------------------------
# LazyStreams mechanics
# ---------------------------------------------------------------------------


def test_lazy_streams_mapping_surface(tmp_path):
    spec, pool, streams, _ = _lazy_fleet(tmp_path, n=6)
    assert len(streams) == 6
    assert 5 in streams and 6 not in streams
    assert list(streams.keys()) == list(range(6))
    # get() never materializes
    assert streams.get(4) is None and streams.get(None) is None
    assert streams.materialized == 0
    st = streams[4]  # [] does
    assert st.doc_id == 4 and streams.get(4) is st
    assert streams.materialized == 1
    assert dict(streams.items()) == {4: st}
    assert list(streams.values()) == [st]


def test_lazy_builder_is_pure_and_matches_sync_path(tmp_path):
    """The construct callable handed to the prefetch worker is a
    ``partial`` over the pure payload builder, and its product installs
    a stream identical to the synchronous materialization."""
    spec, pool, streams, _ = _lazy_fleet(tmp_path, n=6)
    b = streams.builder(2)
    assert isinstance(b, partial) and b.func is build_stream_payload
    payload = b()
    assert streams.adopt(2, payload)
    assert streams.prefetch_built == 1
    sync = _tensorized_reference(spec, pool, 2)
    got = streams[2]
    np.testing.assert_array_equal(got.kind, sync.kind)
    np.testing.assert_array_equal(got.pos, sync.pos)
    np.testing.assert_array_equal(got.rlen, sync.rlen)
    np.testing.assert_array_equal(got.slot0, sync.slot0)
    assert got.n_patches == sync.n_patches
    assert got.arrival == sync.arrival


def _tensorized_reference(spec, pool, doc_id):
    """The eager path's stream for one doc (fresh pool-independent
    tensorization via prepare_streams on a throwaway mapping)."""
    return prepare_streams(
        [spec.session(doc_id)], pool, batch=8, batch_chars=32
    )[doc_id]


def test_lazy_adopt_superseded_by_sync_materialization(tmp_path):
    """A worker-built payload landing after the hot thread already
    materialized the doc is dropped (False), not double-installed."""
    spec, pool, streams, _ = _lazy_fleet(tmp_path, n=6)
    payload = streams.builder(3)()
    st = streams[3]  # sync path wins the race
    assert streams.adopt(3, payload) is False
    assert streams[3] is st
    assert streams.prefetch_built == 0 and streams.materialized == 1


def test_lazy_release_drops_arrays_idempotently(tmp_path):
    spec, pool, streams, _ = _lazy_fleet(tmp_path, n=6)
    st = streams[1]
    assert st.kind.size > 0
    streams.release(1)
    assert st.kind.size == 0 and st.ins_cum.size == 0
    assert streams.released == 1
    streams.release(1)  # idempotent
    streams.release(5)  # never materialized: no-op
    assert streams.released == 1
    # the stub keeps its identity for victim/fault indexing
    assert streams.get(1) is st and st.remaining == 0


def test_lazy_materialize_does_not_reuse_recycled_trace_ids(tmp_path):
    """Regression pin: synth traces are transient in the lazy path, so
    an id(trace)-keyed tensorize cache gets poisoned as soon as CPython
    recycles a freed trace's id — every doc must tensorize ITS OWN
    stream.  (Trace-band prefixes are lru-cached and shared; only the
    unique-per-doc synth source ever hit the recycling hazard.)"""
    spec, pool, streams, _ = _lazy_fleet(tmp_path, n=30, seed=11)
    for d in range(30):
        st = streams[d]  # one at a time: frees each trace before next
        assert st.n_patches == len(spec.session(d).trace), f"doc {d}"


def test_lazy_all_done_requires_full_materialization(tmp_path):
    spec, pool, streams, _ = _lazy_fleet(tmp_path, n=3)
    for d in (0, 1):
        streams[d].cursor = streams[d].n_total
    assert not streams.all_done  # doc 2 still genesis
    streams[2].cursor = streams[2].n_total
    assert streams.all_done


# ---------------------------------------------------------------------------
# byte parity: eager vs streaming, including mid-run evict/restore
# ---------------------------------------------------------------------------


def test_eager_vs_lazy_drain_byte_parity_under_eviction(tmp_path):
    """The acceptance-criteria pin: the SAME fleet drained through the
    eager and streaming paths — with slots oversubscribed so docs
    evict to the spool and restore mid-run — ends byte-identical per
    doc, and both match the oracle."""
    n, seed = 18, 11
    kw = dict(mix=TWO_MIX, seed=seed, arrival_span=3, bands=TWO_BANDS)
    sessions = build_fleet(n, **kw)
    epool = DocPool(classes=(128, 1024), slots=(3, 2),
                    spool_dir=str(tmp_path / "espool"), warm_docs=2)
    estreams = prepare_streams(sessions, epool, batch=8, batch_chars=32)
    esched = FleetScheduler(epool, estreams, batch=8, macro_k=4,
                            batch_chars=32)
    esched.run()
    assert esched.done
    assert epool.evictions > 0  # the mid-run evict/restore actually ran

    spec = FleetSpec.build(n, **kw)
    lpool = DocPool(classes=(128, 1024), slots=(3, 2),
                    spool_dir=str(tmp_path / "lspool"), warm_docs=2)
    lstreams = LazyStreams(spec, lpool, batch=8, batch_chars=32)
    lsched = FleetScheduler(lpool, lstreams, batch=8, macro_k=4,
                            batch_chars=32)
    lsched.run()
    assert lsched.done and lstreams.all_done
    assert lpool.evictions > 0

    assert lsched.stats.patches == esched.stats.patches
    for s in sessions:
        want = replay_trace(s.trace)
        assert epool.decode(s.doc_id) == want, f"eager doc {s.doc_id}"
        assert lpool.decode(s.doc_id) == want, f"lazy doc {s.doc_id}"
    epool.close(), lpool.close()


# ---------------------------------------------------------------------------
# prefetcher: sequence-reaped inflight accounting
# ---------------------------------------------------------------------------


def test_prefetch_inflight_never_underflows_after_reap():
    """The regression pin for the inflight underflow: a submission
    reaped via ``note_lost`` whose payload later lands must not
    decrement ``inflight`` a second time."""
    pf = Prefetcher(capacity=4)
    pf.start()
    try:
        spec = _spec(n=4, seed=1)
        pool = DocPool(classes=(128,), slots=(4,))
        streams = LazyStreams(spec, pool, batch=8, batch_chars=32)
        seqs = [pf.submit_construct(d, streams.builder(d))
                for d in range(3)]
        assert pf.inflight == 3
        # a LIST of seqs arms the double-decrement protection (a bare
        # int is the count-only legacy form)
        pf.note_lost([seqs[0]])  # scheduler reaps one entry
        assert pf.inflight == 2
        # wait for the worker to finish all three builds
        deadline = 200
        harvested = []
        while len(harvested) + pf.reap_dropped < 3 and deadline:
            harvested.extend(pf.drain())
            deadline -= 1
            time.sleep(0.01)
        assert pf.reap_dropped == 1  # the reaped payload was dropped
        assert {p["doc"] for p in harvested} == {1, 2}
        assert pf.inflight == 0  # never negative, fully drained
        pool.close()
    finally:
        pf.stop()


# ---------------------------------------------------------------------------
# construction accounting: probe + scaling table + bench artifact
# ---------------------------------------------------------------------------


def test_construction_probe_both_modes():
    # a dict mix against the default BANDS table keeps the probe on
    # the fast synth source (no trace loading in a unit test)
    kw = dict(mix=TINY_MIX, seed=0, arrival_span=2,
              classes=(4096,), slots=(8,))
    stream = probe(32, **kw)
    assert stream["mode"] == "stream" and stream["n_docs"] == 32
    assert stream["construction_ms"] > 0
    assert stream["genesis_docs"] == 32  # nothing materialized
    eager = probe(32, stream=False, **kw)
    assert eager["mode"] == "eager" and eager["genesis_docs"] == 0
    # VmRSS and ru_maxrss use different kernel accounting; assert
    # presence, not a cross-probe ordering
    assert eager["peak_rss_bytes"] > 0 and eager["rss_before_bytes"] > 0


def test_scaling_table_rows_and_eager_limit(monkeypatch):
    """Table logic without real subprocesses: one fresh cell per
    (size, mode), eager rows capped at ``eager_limit``, failures and
    timeouts become error rows instead of silent omissions."""
    import subprocess as sp
    calls = []

    class _Out:
        def __init__(self, payload, rc=0, err=""):
            self.stdout = json.dumps(payload)
            self.returncode = rc
            self.stderr = err

    def fake_run(cmd, **kw):
        n = int(cmd[cmd.index("--n-docs") + 1])
        mode = cmd[cmd.index("--mode") + 1]
        calls.append((n, mode))
        if n == 64 and mode == "eager":
            raise sp.TimeoutExpired(cmd, kw.get("timeout", 0))
        if n == 256:
            return _Out({}, rc=1, err="boom")
        return _Out({"n_docs": n, "mode": mode, "construction_ms": 1.0,
                     "rss_before_bytes": 1, "rss_after_bytes": 2,
                     "peak_rss_bytes": 3, "genesis_docs": 0})

    monkeypatch.setattr(sp, "run", fake_run)
    rows = scaling_table([64, 16, 256, 16], eager_limit=64,
                         log=lambda *_: None)
    # dedup + sorted sizes; eager stops at the limit (256 > 64)
    assert calls == [(16, "stream"), (16, "eager"),
                     (64, "stream"), (64, "eager"), (256, "stream")]
    by = {(r["n_docs"], r["mode"]): r for r in rows}
    assert "timeout" in by[(64, "eager")]["error"]
    assert by[(256, "stream")]["error"] == "boom"
    assert by[(16, "stream")]["construction_ms"] == 1.0


def test_bench_artifact_construction_block_stream(tmp_path):
    """An end-to-end streamed serve run: verify green, and the
    artifact's construction block carries the auditable sampled-verify
    seed + ids and the genesis/materialization accounting."""
    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=10, batch=8,
        classes=(128,), slots=(4,), seed=5, arrival_span=2,
        verify_sample=4, bands=TINY_BANDS, macro_k=4, batch_chars=32,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        stream=True, sample_seed=21,
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    with open(info["path"]) as f:
        (d,) = json.load(f)
    c = d["extra"]["construction"]
    assert c["mode"] == "stream" and c["version"] == 1
    assert c["construction_ms"] > 0 and c["peak_rss_bytes"] > 0
    assert c["fleet_docs"] == 10 == c["materialized_docs"]
    assert c["genesis_docs_end"] == 0
    assert c["verify_sample_seed"] == 21
    ids = d["extra"]["verified_docs"]
    assert ids == sorted(ids) and len(ids) == 4
    # auditable: the sample is reproducible from the recorded seed —
    # single class, no lossy docs, so the census is exactly range(10)
    rng = np.random.default_rng(21)
    pick = rng.choice(list(range(10)), size=4, replace=False)
    assert ids == sorted(int(x) for x in pick)


def test_bench_stream_rejects_incompatible_modes(tmp_path):
    kw = dict(mix=TINY_MIX, n_docs=4, batch=8, classes=(128,),
              slots=(4,), bands=TINY_BANDS,
              results_dir=str(tmp_path / "r"), stream=True,
              log=lambda *_: None)
    with pytest.raises(ValueError, match="journal"):
        run_serve_bench(journal_dir=str(tmp_path / "j"), **kw)
    with pytest.raises(ValueError, match="open"):
        run_serve_bench(open_spec="64", **kw)
    with pytest.raises(ValueError, match="longhaul|durability"):
        run_serve_bench(longhaul=4, measure_recovery=True, **kw)


def test_bench_artifact_construction_block_eager(tmp_path):
    """The block is ALWAYS present — eager runs carry mode="eager" so
    bench_compare can detect mode mismatches instead of guessing."""
    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=6, batch=8,
        classes=(128,), slots=(4,), seed=5, arrival_span=2,
        verify_sample=2, bands=TINY_BANDS, macro_k=4, batch_chars=32,
        results_dir=str(tmp_path / "results"),
        log=lambda *_: None,
    )
    with open(info["path"]) as f:
        (d,) = json.load(f)
    c = d["extra"]["construction"]
    assert c["mode"] == "eager"
    assert c["fleet_docs"] == 6 and c["genesis_docs_end"] == 0


# ---------------------------------------------------------------------------
# bench_compare: construction gating matrix
# ---------------------------------------------------------------------------


def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare_stream", REPO / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare_stream"] = mod
    spec.loader.exec_module(mod)
    return mod


def _artifact(tmp_path, name, *, mode=None, ms=500.0, rss=2**28):
    extra = {
        "family": "serve",
        "patches_per_sec": 100_000.0,
        "batch_latency": {"p50": 0.001, "p95": 0.004, "p99": 0.005},
        "rounds": 40,
        "range_ops": 10_000,
        "journal": None,
    }
    if mode is not None:
        extra["construction"] = {
            "version": 1, "mode": mode, "construction_ms": ms,
            "rss_after_construction_bytes": rss // 2,
            "peak_rss_bytes": rss, "fleet_docs": 100,
        }
    data = [{"group": "serve", "trace": "mixed", "backend": "512",
             "extra": extra}]
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_bench_compare_construction_matrix(tmp_path, capsys):
    bc = _bench_compare()
    stream = _artifact(tmp_path, "stream.json", mode="stream")
    eager = _artifact(tmp_path, "eager.json", mode="eager", ms=20_000.0)
    legacy = _artifact(tmp_path, "legacy.json")  # pre-block artifact
    # same mode, same numbers: gated and green
    assert bc.main([stream, stream]) == 0
    out = capsys.readouterr().out
    assert "construction time (ms)" in out and "peak RSS" in out
    # regression beyond threshold fails the gate
    slow = _artifact(tmp_path, "slow.json", mode="stream", ms=5_000.0,
                     rss=2**31)
    assert bc.main([slow, stream]) == 1
    out = capsys.readouterr().out
    assert "FAIL" in out
    # improvement direction passes (lower is better)
    assert bc.main([stream, slow]) == 0
    # mode mismatch: BOTH directions skip-with-note, never a fail
    for pair in ((stream, eager), (eager, stream)):
        assert bc.main(list(pair)) == 0
        out = capsys.readouterr().out
        assert "incomparable by design" in out and "SKIP" in out
    # block missing on one side: skip-with-note both directions (the
    # one-sided presence matrix), never exit 2
    for pair in ((stream, legacy), (legacy, stream)):
        assert bc.main(list(pair)) == 0
        out = capsys.readouterr().out
        assert "SKIP" in out


# ---------------------------------------------------------------------------
# mesh split of the streaming path (ROADMAP million-doc item (d))
# ---------------------------------------------------------------------------


def test_shard_range_is_a_balanced_partition():
    """`FleetSpec.shard_range` is a partition of the doc-id space:
    contiguous, disjoint, covering, balanced to within one doc — pure
    (seed, doc_id) arithmetic, so a shard never needs another shard's
    docs to materialize its range."""
    spec = _spec(n=23)
    for n_shards in (1, 2, 5, 8, 23, 30):
        ranges = [spec.shard_range(s, n_shards) for s in range(n_shards)]
        ids = [list(spec.shard_doc_ids(s, n_shards))
               for s in range(n_shards)]
        # covering + disjoint: concatenation IS the doc-id space
        assert [i for chunk in ids for i in chunk] == list(range(23))
        sizes = [hi - lo for lo, hi in ranges]
        assert max(sizes) - min(sizes) <= 1, (n_shards, sizes)
    with pytest.raises(ValueError):
        spec.shard_range(8, 8)
    with pytest.raises(ValueError):
        spec.shard_range(-1, 8)


def test_mesh_stream_fleet_matches_unsharded(tmp_path):
    """The streaming construction path over the 8-device virtual mesh:
    a LazyStreams drain with the pool sharded decodes byte-identically
    to the single-device drain, and both match the oracle."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")
    from crdt_benches_tpu.parallel.mesh import replica_mesh

    def run(mesh, sub):
        spec = _spec(n=12, seed=5, arrival_span=2)
        pool = DocPool(classes=(128,), slots=(8,), mesh=mesh,
                       spool_dir=str(tmp_path / sub))
        try:
            streams = LazyStreams(spec, pool, batch=8, batch_chars=32)
            FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32).run()
            return spec, {i: pool.decode(i) for i in range(spec.n_docs)}
        finally:
            pool.close()

    spec, plain = run(None, "plain")
    _, sharded = run(replica_mesh(8), "mesh")
    assert plain == sharded
    for i in range(spec.n_docs):
        assert plain[i] == replay_trace(spec.session(i).trace)
