"""The fused serve step (ops/serve_fused.py): byte parity and packing.

Every component of the fused path must be byte-identical to the scan
path it replaces — the resolve restructurings (independent per-round
resolves off the scalar totals recurrence, the growing token list, the
narrow front-packed slice), the host-tuned apply, the trivial all-PAD
tokens, and the single-pallas_call macro kernel (run here under the
Pallas interpreter).  The narrow-dtype lane packing must be lossless
in-range and LOUD out of range.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_benches_tpu.ops import serve_fused as SF
from crdt_benches_tpu.ops.apply2 import PackedState
from crdt_benches_tpu.ops.apply_range import apply_range_batch
from crdt_benches_tpu.ops.packing import (
    OpRangeError,
    op_lane_dtypes,
    pack_ops,
    widen_ops,
)
from crdt_benches_tpu.ops.resolve_range_scan import resolve_ranges_rows
from crdt_benches_tpu.traces.tensorize import DELETE, INSERT, PAD


def _gen_ops(rng, K, R, B, nvis0, pad_tail=0):
    """Valid random per-row op streams (inserts/deletes in range) with
    PAD tails; returns int32 (K, R, B) arrays."""
    kind = np.full((K, R, B), PAD, np.int32)
    pos = np.zeros((K, R, B), np.int32)
    rlen = np.zeros((K, R, B), np.int32)
    slot0 = np.zeros((K, R, B), np.int32)
    slot_next = nvis0.astype(np.int64).copy()
    total = nvis0.astype(np.int64).copy()
    for r in range(R):
        for k in range(K):
            nops = int(rng.integers(0, B + 1 - pad_tail))
            for b in range(nops):
                if total[r] > 2 and rng.random() < 0.4:
                    kk = DELETE
                    p = int(rng.integers(0, total[r]))
                    L = int(rng.integers(1, min(6, total[r] - p) + 1))
                else:
                    kk = INSERT
                    p = int(rng.integers(0, total[r] + 1))
                    L = int(rng.integers(1, 6))
                kind[k, r, b] = kk
                pos[k, r, b] = p
                rlen[k, r, b] = L
                if kk == INSERT:
                    slot0[k, r, b] = slot_next[r]
                    slot_next[r] += L
                    total[r] += L
                else:
                    total[r] -= L
    return kind, pos, rlen, slot0


def _mkstate(nvis0, C):
    R = len(nvis0)
    doc = np.full((R, C), 2, np.int32)
    for r in range(R):
        idx = np.arange(nvis0[r])
        doc[r, : nvis0[r]] = ((idx + 2) << 1) | 1
    return PackedState(
        doc=jnp.asarray(doc),
        length=jnp.asarray(nvis0),
        nvis=jnp.asarray(nvis0),
    )


def _scan_reference(state, kind, pos, rlen, slot0, nbits):
    """The scan kernel's body, round by round — THE byte oracle every
    fused component is held to."""
    outs = []
    for k in range(kind.shape[0]):
        outs.append(state)
        tokens, dints, _ = resolve_ranges_rows(
            kind[k], pos[k], rlen[k], slot0[k], state.nvis
        )
        state = apply_range_batch(state, tokens, dints, nbits=nbits)
    return state, outs


@pytest.fixture(scope="module")
def case():
    rng = np.random.default_rng(7)
    K, R, B, C = 4, 6, 12, 256
    nvis0 = rng.integers(3, 24, R).astype(np.int32)
    ops = _gen_ops(rng, K, R, B, nvis0)
    return K, R, B, C, nvis0, ops


def _eq_state(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(a, b)
    )


def test_round_starts_match_interleaved_nvis(case):
    K, R, B, C, nvis0, (kind, pos, rlen, slot0) = case
    _, states = _scan_reference(_mkstate(nvis0, C), kind, pos, rlen,
                                slot0, nbits=6)
    want = np.stack([np.asarray(s.nvis) for s in states])
    got = np.asarray(SF.round_starts(kind, pos, rlen, nvis0))
    assert np.array_equal(got, want)
    # the chained per-round delta walks the same sequence
    v0 = jnp.asarray(nvis0)
    for k in range(K):
        assert np.array_equal(np.asarray(v0), want[k])
        v0 = SF.round_total_delta(kind[k], pos[k], rlen[k], v0)


def test_growing_resolve_byte_identical(case):
    K, R, B, C, nvis0, (kind, pos, rlen, slot0) = case
    t_ref, d_ref, _ = resolve_ranges_rows(
        kind[0], pos[0], rlen[0], slot0[0], nvis0
    )
    t, d = SF.resolve_round_rows_grow(
        kind[0], pos[0], rlen[0], slot0[0], nvis0
    )
    assert all(np.array_equal(a, b) for a, b in zip(t, t_ref))
    assert all(np.array_equal(a, b) for a, b in zip(d, d_ref))


def test_narrow_resolve_pads_to_full_width(case):
    """A front-packed <=16-op slice resolved narrow + padded equals the
    full-width resolve of the same slice with PAD tails."""
    _K, R, _B, C, nvis0, _ = case
    B = 24  # wider than the narrow width so the pad tail is real
    rng = np.random.default_rng(11)
    kind, pos, rlen, slot0 = (
        a[0] for a in _gen_ops(rng, 1, R, B, nvis0,
                               pad_tail=B - SF.NARROW_RESOLVE_OPS)
    )
    NB = SF.NARROW_RESOLVE_OPS
    assert (kind[:, NB:] == PAD).all()
    t_ref, d_ref, _ = resolve_ranges_rows(kind, pos, rlen, slot0, nvis0)
    t, d = SF.resolve_round_rows_padded(
        kind[:, :NB], pos[:, :NB], rlen[:, :NB], slot0[:, :NB],
        nvis0, out_B=B,
    )
    assert all(np.array_equal(a, b) for a, b in zip(t, t_ref))
    assert all(np.array_equal(a, b) for a, b in zip(d, d_ref))


def test_trivial_tokens_match_all_pad_resolve(case):
    K, R, B, C, nvis0, _ = case
    z = np.zeros((R, B), np.int32)
    pad = np.full((R, B), PAD, np.int32)
    t_ref, d_ref, _ = resolve_ranges_rows(pad, z, z, z, nvis0)
    t, d = SF.trivial_round_tokens(jnp.asarray(nvis0), B)
    assert all(np.array_equal(a, b) for a, b in zip(t, t_ref))
    assert all(np.array_equal(a, b) for a, b in zip(d, d_ref))


def test_apply_round_xla_byte_identical(case):
    K, R, B, C, nvis0, (kind, pos, rlen, slot0) = case
    state = _mkstate(nvis0, C)
    tokens, dints, _ = resolve_ranges_rows(
        kind[0], pos[0], rlen[0], slot0[0], state.nvis
    )
    want = apply_range_batch(state, tokens, dints, nbits=6)
    got = SF.serve_apply_round_xla(
        _mkstate(nvis0, C), tokens, dints, nbits=6
    )
    assert _eq_state(want, got)


def test_macro_rounds_xla_byte_identical(case):
    K, R, B, C, nvis0, (kind, pos, rlen, slot0) = case
    want, _ = _scan_reference(_mkstate(nvis0, C), kind, pos, rlen,
                              slot0, nbits=6)
    starts = SF.round_starts(kind, pos, rlen, nvis0)
    parts = [
        SF.resolve_round_rows_grow(
            kind[k], pos[k], rlen[k], slot0[k], starts[k]
        )
        for k in range(K)
    ]
    tokens = tuple(
        jnp.stack([p[0][i] for p in parts]) for i in range(4)
    )
    dints = tuple(
        jnp.stack([p[1][i] for p in parts]) for i in range(3)
    )
    got = SF.serve_macro_rounds_xla(_mkstate(nvis0, C), tokens, dints, 6)
    assert _eq_state(want, got)

    # the single-pallas_call serve kernel, under the interpreter, is
    # byte-identical too (grid (row_blocks, K) with a VMEM-carried doc
    # block — the TPU form of the same dispatch)
    got_k = SF.serve_macro_fused(
        _mkstate(nvis0, C), tokens, dints, nbits=6, replica_tile=3,
        interpret=True,
    )
    assert _eq_state(want, got_k)


def test_pool_fused_tpu_form_interpret(tmp_path, monkeypatch):
    """End to end through DocPool with CRDT_BENCH_SERVE_INTERPRET=1:
    the accelerator-form fused dispatch (one jit wrapping the serve
    kernel) drains a small fleet byte-identical to the oracle —
    INCLUDING row-tier compaction (3 docs on a 16-row bucket pick the
    Rt=4 sub-tier, so the in-jit tier slice/writeback is traced; a
    compiled-executable take/put here is the code-review-r8 crash)."""
    from crdt_benches_tpu.oracle.text_oracle import replay_trace
    from crdt_benches_tpu.serve.pool import DocPool
    from crdt_benches_tpu.serve.scheduler import (
        FleetScheduler,
        prepare_streams,
    )
    from crdt_benches_tpu.serve.workload import Session
    from crdt_benches_tpu.traces.synth import synth_trace

    monkeypatch.setenv("CRDT_BENCH_SERVE_INTERPRET", "1")
    traces = [synth_trace(seed=300 + i, n_ops=60) for i in range(3)]
    sessions = [
        Session(doc_id=i, band="synth-small", source="synth", trace=t)
        for i, t in enumerate(traces)
    ]
    pool = DocPool(classes=(128,), slots=(16,), spool_dir=str(tmp_path))
    assert pool.fused_accel_form
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32)
    sched.run()
    assert sched.done
    # the sub-tier really was exercised (the fused jit cache holds a
    # key whose Rt is below the bucket's 16 rows)
    assert any(k[2] < 16 for k in pool._fused_tpu_fns)
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)


# ---------------------------------------------------------------------
# narrow-dtype lane packing (ops/packing.py)
# ---------------------------------------------------------------------


def test_op_lane_dtypes_static_rule():
    assert [str(d) for d in op_lane_dtypes(49152)] == [
        "int8", "uint16", "uint16", "uint16",
    ]
    assert [str(d) for d in op_lane_dtypes(1 << 20)] == [
        "int8", "int32", "int32", "int32",
    ]


def test_pack_widen_round_trip_property():
    """Property: for ALL in-range values, widen(pack(x)) == x exactly
    (both dtype regimes), across the full lane ranges including the
    boundary values."""
    rng = np.random.default_rng(3)
    for max_class in (49152, 1 << 20):
        dts = op_lane_dtypes(max_class)
        his = [np.iinfo(d).max for d in dts]
        kind = rng.integers(0, 3, 4096).astype(np.int32)
        pos = rng.integers(0, min(his[1], 1 << 22) + 1, 4096).astype(
            np.int32
        )
        rlen = rng.integers(0, min(his[2], 1 << 22) + 1, 4096).astype(
            np.int32
        )
        slot0 = rng.integers(0, min(his[3], 1 << 22) + 1, 4096).astype(
            np.int32
        )
        # pin the exact lane boundary values into the sample
        pos[0], rlen[0], slot0[0] = (
            min(his[1], 1 << 22), min(his[2], 1 << 22),
            min(his[3], 1 << 22),
        )
        packed = pack_ops(kind, pos, rlen, slot0, max_class=max_class)
        assert [p.dtype for p in packed] == list(dts)
        wide = widen_ops(*packed)
        for w, orig in zip(wide, (kind, pos, rlen, slot0)):
            assert w.dtype == np.int32
            assert np.array_equal(w, orig)


def test_pack_raises_not_wraps_out_of_range():
    """An id-space bump past the narrow bound must raise LOUDLY, never
    truncate: 65536 wraps to 0 in uint16 — exactly the silent slot-id
    corruption the checked pack exists to prevent."""
    kind = np.zeros(4, np.int32)
    ok = np.zeros(4, np.int32)
    big = np.array([0, 1, 65536, 2], np.int32)
    for lane in range(1, 4):
        args = [kind, ok, ok, ok]
        args[lane] = big
        with pytest.raises(OpRangeError, match="do not fit uint16"):
            pack_ops(*args, max_class=49152)
    with pytest.raises(OpRangeError):
        pack_ops(np.array([999], np.int32), ok[:1], ok[:1], ok[:1],
                 max_class=49152)
    # the same values pack fine once the pool's id space forces int32
    out = pack_ops(kind, big, big, big, max_class=1 << 20)
    assert all(o.dtype == np.int32 for o in out[1:])


def test_aot_jit_applies_options_and_falls_back():
    calls = {}

    def f(x):
        return x + 1

    g = SF.AotJit(f)
    x = jnp.arange(4, dtype=jnp.int32)
    assert np.array_equal(np.asarray(g(x)), np.arange(1, 5))
    assert g._compiled is not None
    # bogus options fall back to the plain jit rather than failing
    h = SF.AotJit(f, options={"definitely_not_an_xla_flag": True})
    assert np.array_equal(np.asarray(h(x)), np.arange(1, 5))
    del calls
