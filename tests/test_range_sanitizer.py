"""Unit contract of the value-range sanitizer (lint/range_sanitizer.py)
and the dtype ceilings of the packed op lanes: counters bump in every
mode, armed violations raise their typed error with attribution, and
``pack_ops`` refuses — never wraps — a value past the uint16 ceiling."""

import numpy as np
import pytest

from crdt_benches_tpu.lint import range_sanitizer as rs
from crdt_benches_tpu.ops.packing import (
    NARROW_ID_BOUND, OpRangeError, op_lane_dtypes, pack_ops)


@pytest.fixture(autouse=True)
def _reset(monkeypatch):
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_RANGES", raising=False)
    rs.disarm()
    rs.reset_counters()
    yield
    rs.disarm()
    rs.reset_counters()


# ---------------------------------------------------------------------------
# counters: the G029 ground truth bumps in EVERY mode
# ---------------------------------------------------------------------------


def test_counters_bump_disarmed_and_nothing_validates():
    assert not rs.armed()
    # wildly out of range — disarmed, only the counter moves
    rs.check_index("t.idx", np.array([99, -5]), 4)
    rs.check_narrow("t.lane", np.array([1 << 20]), 255)
    rs.check_no_pad("t.pad", np.array([0, 0]), 0)
    rs.note_mask("t-mask", n=3)
    c = rs.counters()
    assert c["checks"] == {"t.idx": 1, "t.lane": 1, "t.pad": 1}
    assert c["masks"] == {"t-mask": 3}


def test_callable_operand_is_not_evaluated_disarmed():
    """The lazy-operand contract: disarmed cost is ONE counter bump —
    a callable arr (e.g. a lambda masking PAD lanes) must not run."""
    def boom():
        raise RuntimeError("evaluated while disarmed")

    rs.check_index("t.lazy", boom, 8)
    rs.arm()
    with pytest.raises(RuntimeError, match="evaluated while disarmed"):
        rs.check_index("t.lazy", boom, 8)
    assert rs.counters()["checks"]["t.lazy"] == 2


def test_env_flag_arms_at_reset(monkeypatch):
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_RANGES", "1")
    assert rs.sanitizing()
    rs.reset_counters()  # arming happens here, eagerly
    assert rs.armed()


# ---------------------------------------------------------------------------
# armed: each violation is its typed error, with attribution
# ---------------------------------------------------------------------------


def test_index_out_of_bounds_is_typed_and_attributed():
    rs.arm()
    rs.check_index("t.idx", np.array([0, 3]), 4, doc=7, cls=256, rnd=2)
    with pytest.raises(rs.IndexOutOfBoundsError) as ei:
        rs.check_index("t.idx", np.array([0, 4]), 4, doc=7, cls=256)
    msg = str(ei.value)
    assert "value 4 outside [0, 4)" in msg
    assert "doc=7" in msg and "class=256" in msg
    with pytest.raises(rs.IndexOutOfBoundsError, match="value -1"):
        rs.check_index("t.idx", np.array([-1]), 4)
    # the lo= floor widens the legal window
    rs.check_index("t.idx", np.array([-1]), 4, lo=-1)


def test_narrow_overflow_is_inclusive_at_the_ceiling():
    rs.arm()
    rs.check_narrow("t.lane", np.array([NARROW_ID_BOUND]),
                    NARROW_ID_BOUND)  # == bound is legal (inclusive)
    with pytest.raises(rs.NarrowOverflowError, match="65536"):
        rs.check_narrow("t.lane", np.array([NARROW_ID_BOUND + 1]),
                        NARROW_ID_BOUND)


def test_pad_leak_is_typed():
    rs.arm()
    rs.check_no_pad("t.pad", np.array([1, 2, 3]), 0)
    with pytest.raises(rs.PadLeakError, match="sentinel value 0"):
        rs.check_no_pad("t.pad", np.array([1, 0, 3]), 0)


def test_typed_errors_share_a_base_class():
    for exc in (rs.IndexOutOfBoundsError, rs.NarrowOverflowError,
                rs.PadLeakError):
        assert issubclass(exc, rs.RangeSanitizerError)


# ---------------------------------------------------------------------------
# pack_ops at the uint16 ceiling: refuse, never wrap
# ---------------------------------------------------------------------------


def _lanes(slot0_val: int):
    kind = np.array([1], np.int8)
    pos = np.array([0], np.int64)
    rlen = np.array([1], np.int64)
    slot0 = np.array([slot0_val], np.int64)
    return kind, pos, rlen, slot0


def test_pack_ops_narrow_ceiling_65534_65535_65536():
    """The headline dtype edge: 65534 and 65535 pack losslessly into
    the narrow uint16 lanes; 65536 raises ``OpRangeError`` — it must
    NEVER wrap to 0 and alias slot id 0."""
    assert op_lane_dtypes(NARROW_ID_BOUND)[3] == np.dtype(np.uint16)
    for v in (65534, 65535):
        k, p, r, s = pack_ops(*_lanes(v), max_class=NARROW_ID_BOUND)
        assert s.dtype == np.uint16 and int(s[0]) == v
    with pytest.raises(OpRangeError, match="65536"):
        pack_ops(*_lanes(65536), max_class=NARROW_ID_BOUND)


def test_pack_ops_wide_lanes_carry_past_the_ceiling():
    """One past the narrow bound flips the WHOLE pool to int32 lanes —
    and 65536 is then a legal id, not an error."""
    assert op_lane_dtypes(NARROW_ID_BOUND + 1)[3] == np.dtype(np.int32)
    k, p, r, s = pack_ops(*_lanes(65536), max_class=NARROW_ID_BOUND + 1)
    assert s.dtype == np.int32 and int(s[0]) == 65536


def test_pack_ops_negative_never_wraps_into_uint16():
    with pytest.raises(OpRangeError, match="-1"):
        pack_ops(*_lanes(-1), max_class=NARROW_ID_BOUND)
