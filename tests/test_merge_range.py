"""Run-granular merge (engine/merge_range.py): RLE wire translation,
run-atomicity precondition, and byte-identical convergence against the
unit-op merge on multi-agent divergent edits."""

import numpy as np
import pytest

from crdt_benches_tpu.engine.merge import MergeSimulation
from crdt_benches_tpu.engine.merge_range import (
    RunMergeSimulation,
    check_no_skip,
    runs_from_oplog,
)
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import tensorize


def _sim(seeds, base, batch=16, n_ops=60):
    streams = [
        tensorize(synth_trace(seed=s, n_ops=n_ops, base=base), batch=batch)
        for s in seeds
    ]
    return MergeSimulation(streams, base=base, batch=batch)


@pytest.mark.slow
def test_runs_roundtrip_counts():
    sim = _sim([0, 1], base="shared base text here")
    for log in sim.agent_logs:
        rl = runs_from_oplog(log)
        # every unit op is covered exactly once
        n_ins = int(rl.rlen.sum())
        n_del = int((rl.dhi - rl.dlo + 1).sum()) if len(rl.dlo) else 0
        assert n_ins + n_del == rl.n_unit_ops == len(log)
        # far fewer runs than unit ops on synth streams with runs
        assert len(rl.slot0) + len(rl.dlo) <= len(log)
        # runs are slot- and lamport-contiguous by construction
        assert (rl.rlen >= 1).all()


@pytest.mark.slow
def test_no_skip_holds_for_diverged_agents():
    sim = _sim([2, 3, 4], base="the shared base document ")
    assert check_no_skip(
        [runs_from_oplog(l) for l in sim.agent_logs]
    )


@pytest.mark.parametrize("seeds", [[0, 1], [2, 3, 4], [5, 6, 7, 8]])
@pytest.mark.slow
def test_run_merge_matches_unit_merge(seeds):
    base = "concurrent editing from a shared base "
    sim = _sim(seeds, base=base, n_ops=50)
    want = sim.decode(sim.merge())  # unit-op v1 merge (ground truth)
    rm = RunMergeSimulation(sim, batch=8, epoch=2)
    assert rm.fast_ok
    st = rm.merge(n_replicas=2)
    assert rm.decode(st, replica=0) == want
    assert rm.decode(st, replica=1) == want
    assert (np.asarray(st.nvis) == len(want)).all()


@pytest.mark.slow
def test_run_merge_empty_base():
    sim = _sim([9, 10], base="", n_ops=40)
    want = sim.decode(sim.merge())
    rm = RunMergeSimulation(sim, batch=8, epoch=2)
    st = rm.merge()
    assert rm.decode(st) == want


@pytest.mark.slow
def test_run_merge_batch_epoch_independence():
    sim = _sim([11, 12], base="invariance base ", n_ops=45)
    want = sim.decode(sim.merge())
    for batch, epoch in [(4, 1), (8, 4), (32, 2)]:
        rm = RunMergeSimulation(sim, batch=batch, epoch=epoch)
        assert rm.decode(rm.merge()) == want, (batch, epoch)


@pytest.mark.slow
def test_run_merge_traces_prefix(rustcode_trace, seph_trace):
    import dataclasses

    a = dataclasses.replace(rustcode_trace, txns=rustcode_trace.txns[:120])
    b = dataclasses.replace(seph_trace, txns=seph_trace.txns[:120])
    streams = [tensorize(a, batch=64), tensorize(b, batch=64)]
    sim = MergeSimulation(streams, base="", batch=64)
    want = sim.decode(sim.merge())
    rm = RunMergeSimulation(sim, batch=16, epoch=2)
    assert rm.fast_ok
    assert rm.n_runs < rm.n_unit_ops // 3  # the point: fewer sequential steps
    st = rm.merge(n_replicas=1)
    assert rm.decode(st) == want


@pytest.mark.slow
def test_nbits_sized_on_sorted_batches():
    # Interleaved key ranges with uneven run lengths: per-batch char sums
    # must be computed on the SORTED batch layout the device integrates
    # (host-order sizing undercounted and corrupted the expansion).
    base = "x" * 8
    streams = [
        tensorize(synth_trace(seed=s, n_ops=70, base=base), batch=8)
        for s in (21, 22)
    ]
    sim = MergeSimulation(streams, base=base, batch=8)
    want = sim.decode(sim.merge())
    rm = RunMergeSimulation(sim, batch=4, epoch=2)
    nb = len(rm.lamport) // 4
    sorted_sums = (
        np.where(rm.rlen > 0, rm.rlen, 0).reshape(nb, 4).sum(axis=1)
    )
    assert 2 ** rm.nbits > int(sorted_sums.max())
    assert rm.decode(rm.merge()) == want


@pytest.mark.slow
def test_delete_only_union():
    # A union with zero insert runs must not divide by zero: the base
    # document with deletes folded is the converged result.
    from crdt_benches_tpu.traces.loader import TestData, TestTxn

    base = "abcdefghij"
    streams = [
        tensorize(TestData(base, "", [TestTxn("", [[2, 3, ""]])]), batch=4),
        tensorize(TestData(base, "", [TestTxn("", [[7, 1, ""]])]), batch=4),
    ]
    sim = MergeSimulation(streams, base=base, batch=4)
    want = sim.decode(sim.merge())
    rm = RunMergeSimulation(sim, batch=4, epoch=2)
    assert rm.n_runs == 0
    st = rm.merge(n_replicas=2)
    assert rm.decode(st, replica=0) == want == "abfgij"


def test_capacity_guard():
    sim = _sim([0, 1], base="guard")
    RunMergeSimulation(sim, batch=4)  # small capacity passes
    sim.capacity = 1 << 20  # fresh sim per _sim call; safe to mutate
    with pytest.raises(ValueError, match="2\\^20"):
        RunMergeSimulation(sim, batch=4)


@pytest.mark.slow
def test_run_downstream_backend_byte_identical():
    # single-writer special case: the run merge as a downstream apply
    from crdt_benches_tpu.engine.merge_range import JaxRunDownstreamBackend
    from crdt_benches_tpu.oracle import OracleDocument

    from crdt_benches_tpu.traces.loader import TestData

    trace = synth_trace(seed=31, n_ops=300, base="downstream via runs ")
    doc = OracleDocument.from_str(trace.start_content)
    for p, d, ins in trace.iter_patches():
        doc.replace(p, p + d, ins)
    want = doc.content()
    trace = TestData(trace.start_content, want, trace.txns)
    b = JaxRunDownstreamBackend(n_replicas=2, batch=16, epoch=2)
    b.prepare(trace)
    assert b.replay_once() == len(want)
    assert b.final_content() == want


@pytest.mark.slow
def test_patch_granularity_downstream_byte_identical():
    """The strict like-for-like wire (granularity='patch'): one update
    per trace patch component, NO cross-patch RLE coalescing — matching
    the reference's per-patch generation loop (src/rope.rs:196-220).
    Byte-identical apply, and every wire run must lie inside a single
    patch's insert range."""
    import numpy as np

    from crdt_benches_tpu.engine.merge_range import JaxRunDownstreamBackend
    from crdt_benches_tpu.oracle import OracleDocument
    from crdt_benches_tpu.traces.loader import TestData

    trace = synth_trace(seed=33, n_ops=400, base="per-patch wire ")
    doc = OracleDocument.from_str(trace.start_content)
    for p, d, ins in trace.iter_patches():
        doc.replace(p, p + d, ins)
    want = doc.content()
    trace = TestData(trace.start_content, want, trace.txns)

    b = JaxRunDownstreamBackend(n_replicas=2, batch=16, epoch=2,
                                granularity="patch")
    b.prepare(trace)
    assert b.replay_once() == len(want)
    assert b.final_content() == want

    # granularity: map every insert slot to its patch; no run may span two
    from crdt_benches_tpu.traces.tensorize import tensorize

    tt = tensorize(trace, batch=512)
    n_base = len(trace.start_content)
    patch_of_slot = np.full(int(tt.slot.max(initial=0)) + 2, -1, np.int64)
    u = 0
    for i, (_p, d, ins) in enumerate(trace.iter_patches()):
        for k in range(len(ins)):
            patch_of_slot[tt.slot[u + d + k]] = i
        u += d + len(ins)
    rl = b._rm.runlogs[0]
    s0 = rl.slot0
    ln = rl.rlen
    assert (
        patch_of_slot[s0] == patch_of_slot[s0 + ln - 1]
    ).all(), "a wire run crosses a patch boundary"

    # the coalesced wire on the same trace is allowed to be coarser
    bc = JaxRunDownstreamBackend(n_replicas=1, batch=16, epoch=2)
    bc.prepare(trace)
    assert b._rm.n_runs >= bc._rm.n_runs
