"""Differential tests: fused Pallas resolver (interpret mode on CPU) vs the
lax.scan resolver, and the full R-native replay path vs the oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crdt_benches_tpu.engine.replay import ReplayEngine, replay_batches_r
from crdt_benches_tpu.ops.resolve import resolve_batch
from crdt_benches_tpu.ops.resolve_pallas import resolve_batch_pallas
from crdt_benches_tpu.oracle import OracleDocument
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import tensorize


def _random_stream(rng, n, v0):
    """Random unit-op (kind, pos) stream valid against a doc of v0 chars."""
    from crdt_benches_tpu.traces.tensorize import DELETE, INSERT, PAD

    kind, pos = [], []
    v = v0
    for _ in range(n):
        r = rng.random()
        if r < 0.1:
            kind.append(PAD)
            pos.append(0)
        elif r < 0.6 or v == 0:
            kind.append(INSERT)
            pos.append(int(rng.integers(0, v + 1)))
            v += 1
        else:
            kind.append(DELETE)
            pos.append(int(rng.integers(0, v)))
            v -= 1
    return (
        np.asarray(kind, np.int32),
        np.asarray(pos, np.int32),
    )


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("v0", [0, 7, 40])
@pytest.mark.slow
def test_pallas_matches_scan_resolver(seed, v0):
    rng = np.random.default_rng(seed)
    B = 64
    kind, pos = _random_stream(rng, B, v0)
    want = resolve_batch(
        jnp.asarray(kind), jnp.asarray(pos), jnp.int32(v0)
    )
    R = 4
    got = resolve_batch_pallas(
        jnp.asarray(kind),
        jnp.asarray(pos),
        jnp.full((R,), v0, jnp.int32),
        interpret=True,
    )
    for f in want._fields:
        w = np.asarray(getattr(want, f))
        g = np.asarray(getattr(got, f))
        assert g.shape == (R,) + w.shape, f
        for r in range(R):
            np.testing.assert_array_equal(g[r], w, err_msg=f)


@pytest.mark.parametrize("seed", [0, 5])
@pytest.mark.slow
def test_replay_r_scan_resolver_vs_oracle(seed):
    trace = synth_trace(seed=seed, n_ops=300, base="hello pallas world")
    tt = tensorize(trace, batch=32)
    eng = ReplayEngine(tt, n_replicas=2, resolver="scan", chunk=3)
    st = eng.run()
    doc = OracleDocument.from_str(trace.start_content)
    for p, d, ins in trace.iter_patches():
        doc.replace(p, p + d, ins)
    assert eng.decode(st, replica=0) == doc.content()
    assert eng.decode(st, replica=1) == doc.content()


@pytest.mark.slow
def test_replay_r_chunking_invariant():
    trace = synth_trace(seed=9, n_ops=200, base="chunks")
    tt = tensorize(trace, batch=16)
    a = ReplayEngine(tt, n_replicas=1, resolver="scan", chunk=1)
    b = ReplayEngine(tt, n_replicas=1, resolver="scan", chunk=100)
    assert a.decode(a.run()) == b.decode(b.run())
