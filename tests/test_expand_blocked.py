"""Differential tests for the blocked (halo-windowed) fused apply kernel
(ops/expand_pallas.py apply_fused_blocked) against the XLA reference,
including block-boundary shifts and the j == 0 fake-halo edge."""

import numpy as np
import jax.numpy as jnp
import pytest

from crdt_benches_tpu.ops.expand_pallas import (
    LANE,
    apply_fused_blocked,
    apply_fused_nocv_xla,
)


def _mk(rng, R, C, n_ins, nbits):
    nt = C // LANE
    doc = jnp.asarray(
        rng.integers(2, 2000, (R, C)).astype(np.int32)
    )
    dest = np.sort(
        rng.choice(C - 1, size=(R, n_ins), replace=False), axis=1
    )
    combo = np.zeros((R, C), np.int32)
    for r in range(R):
        combo[r, dest[r]] = (
            rng.integers(1, 1 << 22, n_ins).astype(np.int32) << 1
        ) | 1
    cnt_base = np.zeros((R, nt), np.int32)
    ind = (combo & 1).reshape(R, nt, LANE).sum(axis=2)
    cnt_base[:, 1:] = np.cumsum(ind, axis=1)[:, :-1]
    new_len = jnp.asarray(
        rng.integers(C // 2, C, R).astype(np.int32)
    )
    return doc, jnp.asarray(combo), jnp.asarray(cnt_base), new_len


@pytest.mark.parametrize("seed", [0, 2])
@pytest.mark.parametrize("block_tiles", [8, 16])
@pytest.mark.slow
def test_blocked_matches_xla(seed, block_tiles):
    rng = np.random.default_rng(seed)
    R, C, n_ins = 2, 4096, 60  # nt=32, several blocks
    nbits = 6  # max shift 64 -> halo 2 tiles
    doc, combo, cb, ln = _mk(rng, R, C, n_ins, nbits)
    want = np.asarray(
        apply_fused_nocv_xla(doc, combo, cb, ln, nbits=nbits)
    )
    got = np.asarray(
        apply_fused_blocked(
            doc, combo, cb, ln, nbits=nbits, block_tiles=block_tiles,
            interpret=True,
        )
    )
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_blocked_dense_shifts_at_boundaries():
    """Inserts clustered right at a block boundary so the halo is
    exercised with near-maximal shifts."""
    rng = np.random.default_rng(7)
    R, C = 1, 4096
    nt = C // LANE
    nbits = 7  # shifts up to 127
    doc = jnp.asarray(rng.integers(2, 999, (R, C)).astype(np.int32))
    # 100 consecutive insert destinations just before the block-1 start
    combo = np.zeros((R, C), np.int32)
    d0 = 4 * LANE - 60
    combo[0, d0 : d0 + 100] = (
        rng.integers(1, 1 << 20, 100).astype(np.int32) << 1
    ) | 1
    ind = (combo & 1).reshape(R, nt, LANE).sum(axis=2)
    cb = np.zeros((R, nt), np.int32)
    cb[:, 1:] = np.cumsum(ind, axis=1)[:, :-1]
    ln = jnp.asarray(np.asarray([C], np.int32))
    want = np.asarray(
        apply_fused_nocv_xla(
            doc, jnp.asarray(combo), jnp.asarray(cb), ln, nbits=nbits
        )
    )
    got = np.asarray(
        apply_fused_blocked(
            doc, jnp.asarray(combo), jnp.asarray(cb), ln, nbits=nbits,
            block_tiles=8, interpret=True,
        )
    )
    np.testing.assert_array_equal(got, want)


# ---- real-TPU (non-interpret) coverage --------------------------------------

import os
import jax

_on_tpu = (
    os.environ.get("CRDT_TPU_TESTS") == "1"
    and jax.default_backend() == "tpu"
)


@pytest.mark.skipif(not _on_tpu, reason="set CRDT_TPU_TESTS=1 on a TPU")
def test_blocked_on_silicon_above_vmem_gate():
    """Compile + run the blocked kernel NON-interpreted on the real chip
    at a capacity ABOVE the ~1.09M-position monolithic-VMEM gate (the
    round-2 verdict gap: the kernel had only ever run in interpret mode
    at C=4096)."""
    from crdt_benches_tpu.ops.expand_pallas import (
        FUSED_STACK_BYTES_PER_POS,
    )

    rng = np.random.default_rng(11)
    C = 1536 * 1024  # 1.57M positions > the 96MB VMEM gate
    assert FUSED_STACK_BYTES_PER_POS * C > 96 * 2**20
    R, n_ins, nbits = 4, 500, 9
    doc, combo, cb, ln = _mk(rng, R, C, n_ins, nbits)
    want = np.asarray(
        apply_fused_nocv_xla(doc, combo, cb, ln, nbits=nbits)
    )
    got = np.asarray(
        apply_fused_blocked(doc, combo, cb, ln, nbits=nbits)
    )
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(not _on_tpu, reason="set CRDT_TPU_TESTS=1 on a TPU")
def test_blocked_on_silicon_boundary_shifts():
    """Non-interpret boundary-cluster case: inserts packed right at a
    block edge so the halo path runs on silicon."""
    rng = np.random.default_rng(13)
    R, C = 2, 256 * 1024
    nt = C // LANE
    nbits = 10
    doc = jnp.asarray(rng.integers(2, 999, (R, C)).astype(np.int32))
    combo = np.zeros((R, C), np.int32)
    bt = 64  # force several blocks
    d0 = bt * LANE - 400
    combo[:, d0 : d0 + 800] = (
        rng.integers(1, 1 << 20, (R, 800)).astype(np.int32) << 1
    ) | 1
    ind = (combo & 1).reshape(R, nt, LANE).sum(axis=2)
    cb = np.zeros((R, nt), np.int32)
    cb[:, 1:] = np.cumsum(ind, axis=1)[:, :-1]
    ln = jnp.asarray(np.full(R, C, np.int32))
    want = np.asarray(
        apply_fused_nocv_xla(
            doc, jnp.asarray(combo), jnp.asarray(cb), ln, nbits=nbits
        )
    )
    got = np.asarray(
        apply_fused_blocked(
            doc, jnp.asarray(combo), jnp.asarray(cb), ln, nbits=nbits,
            block_tiles=bt,
        )
    )
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_blocked_pads_indivisible_tile_counts():
    # nt with no usable divisor (e.g. odd) must pad to a block multiple
    # instead of degrading to 1-tile blocks that cannot host the halo.
    rng = np.random.default_rng(17)
    R, C = 1, 131 * LANE  # nt = 131 (prime)
    nbits = 6
    doc, combo, cb, ln = _mk(rng, R, C, 40, nbits)
    want = np.asarray(
        apply_fused_nocv_xla(doc, combo, cb, ln, nbits=nbits)
    )
    got = np.asarray(
        apply_fused_blocked(
            doc, combo, cb, ln, nbits=nbits, block_tiles=16,
            interpret=True,
        )
    )
    np.testing.assert_array_equal(got, want)
