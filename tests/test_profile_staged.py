"""Lockstep guard for the profiler's staged pipeline replica.

tools/profile.py `range` profiles a truncated copy of
ops/apply_range_fused.apply_range_batch4 (stages cut after each spread).
The round-4 profilers rotted against live signature changes because
nothing executed them in CI; this test pins the stage-3 replica to the
real function bit-exactly so any future drift fails loudly.
"""

import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, ".")


@pytest.mark.slow
@pytest.mark.parametrize("batch", [16, 1536])
def test_range_staged_matches_apply_range_batch4(batch):
    import jax.numpy as jnp

    from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
    from crdt_benches_tpu.ops.apply2 import init_state4
    from crdt_benches_tpu.ops.apply_range_fused import apply_range_batch4
    from crdt_benches_tpu.ops.resolve_range_pallas import (
        resolve_range_pallas,
    )
    from crdt_benches_tpu.traces.synth import synth_trace
    from crdt_benches_tpu.traces.tensorize import tensorize_ranges
    from tools.profile import _range_staged

    trace = synth_trace(seed=5, n_ops=2 * batch, base="staged lockstep ")
    rt = tensorize_ranges(trace, batch=batch)
    eng = RangeReplayEngine(rt, n_replicas=2, interpret=True, chunk=4)
    kind_b, pos_b, rlen_b, slot0_b = rt.batched()

    st = init_state4(2, eng.capacity, eng.n_init)
    tokens, dints, _ = jax.jit(resolve_range_pallas,
                               static_argnames=("interpret",))(
        jnp.asarray(kind_b[0]), jnp.asarray(pos_b[0]),
        jnp.asarray(rlen_b[0]), jnp.asarray(slot0_b[0]),
        st.nvis, interpret=True,
    )

    want = apply_range_batch4(st, tokens, dints, nbits=eng.nbits,
                              interpret=True)
    doc, cv, vt, length2 = _range_staged(
        st, tokens, dints, eng.nbits, stage=3, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(doc), np.asarray(want.doc))
    np.testing.assert_array_equal(np.asarray(vt), np.asarray(want.vis_tile))
    np.testing.assert_array_equal(
        np.asarray(length2), np.asarray(want.length)
    )
    # earlier stages must at least trace/execute (shape-level lockstep)
    for stage in (0, 1, 2):
        out = _range_staged(st, tokens, dints, eng.nbits, stage)
        assert out.shape == (2, 1)
