"""Macro-round serving: parity, mid-macro churn, and telemetry.

The macro engine changes WHEN everything happens (K rounds per dispatch,
boundary-batched row movement, row-tier compaction, RLE op coalescing)
but must never change WHAT each document becomes — every test's ground
truth is the oracle or the K=1 drain of the identical fleet.
"""

import os

import numpy as np
import pytest

from crdt_benches_tpu.bench.harness import steady_quantiles
from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import FleetScheduler, prepare_streams
from crdt_benches_tpu.serve.workload import Session, build_fleet, trace_prefix

TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


def _drain(sessions, pool, batch=16, macro_k=1, batch_chars=64):
    streams = prepare_streams(
        sessions, pool, batch=batch, batch_chars=batch_chars
    )
    sched = FleetScheduler(
        pool, streams, batch=batch, macro_k=macro_k,
        batch_chars=batch_chars,
    )
    stats = sched.run()
    assert sched.done
    return stats


def _mixed_sessions(tmp_path):
    """A small fleet spanning synth AND real-trace classes (both test
    pool classes host docs), with arrivals staggered."""
    sessions = build_fleet(
        10, mix=TINY_MIX, seed=7, arrival_span=3, bands=TINY_BANDS
    )
    nxt = len(sessions)
    sessions += [
        Session(doc_id=nxt, band="trace-small", source="automerge-paper",
                trace=trace_prefix("automerge-paper", 240), arrival=1),
        Session(doc_id=nxt + 1, band="trace-medium",
                source="sveltecomponent",
                trace=trace_prefix("sveltecomponent", 500)),
    ]
    return sessions


def test_macro_k8_byte_identical_to_k1(tmp_path):
    """THE parity gate: the same fleet drained with macro-rounds (K=8)
    and with single rounds (K=1) is byte-identical for every doc — a
    sample spanning every hosted class — and both match the oracle."""
    sessions = _mixed_sessions(tmp_path)

    def run(k, sub):
        pool = DocPool(classes=(256, 1024), slots=(6, 3),
                       spool_dir=str(tmp_path / sub))
        stats = _drain(sessions, pool, macro_k=k)
        out = {s.doc_id: pool.decode(s.doc_id) for s in sessions}
        hosted = {pool.docs[s.doc_id].cls for s in sessions}
        return out, stats, hosted

    k1, stats1, _ = run(1, "k1")
    k8, stats8, hosted = run(8, "k8")
    assert k1 == k8
    for s in sessions:
        assert k8[s.doc_id] == replay_trace(s.trace), (
            f"doc {s.doc_id} ({s.band}) diverged from oracle"
        )
    # the sample really spans hosted classes, and the macro engine
    # actually batched: fewer macro-rounds than K=1 rounds
    assert len([c for c in hosted if c]) >= 1
    assert stats8.rounds < stats1.rounds
    # identical op streams -> identical coalescing accounting
    assert stats8.unit_ops == stats1.unit_ops
    assert stats8.ops == stats1.ops


def test_evict_restore_mid_macro_round_roundtrip(tmp_path):
    """Eviction mid-macro-round is a FORCED SYNC boundary: dispatch a
    macro-round, then — with the device potentially still draining —
    evict a scheduled doc through the checkpoint spool, reload it into a
    different row, and finish.  Byte-identical to an uninterrupted
    replay."""
    from crdt_benches_tpu.traces.synth import synth_trace

    traces = [synth_trace(seed=200 + i, n_ops=120) for i in range(3)]
    sessions = [
        Session(doc_id=i, band="synth-small", source="synth", trace=t)
        for i, t in enumerate(traces)
    ]
    pool = DocPool(classes=(128,), slots=(2,), spool_dir=str(tmp_path))
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32)

    # one macro-round dispatched; its device work may still be in
    # flight — pool.evict's row pull must fence it (the boundary sync)
    sched.run(max_rounds=1)
    rec0 = pool.docs[0]
    assert streams[0].cursor > 0 and streams[0].remaining > 0
    if rec0.cls is None:
        if not pool.buckets[128].free:
            pool.evict(pool.residents(128)[0][0])
        pool.admit(0, need=rec0.length)
    row_before = rec0.row
    spool = pool.evict(0)
    assert os.path.exists(spool) and rec0.spool == spool
    assert rec0.cls is None

    # occupy the freed row, then free the OTHER row, so doc 0 must
    # rehydrate into a different slot
    other = next(d for d in (1, 2) if pool.docs[d].cls is None)
    assert pool.admit(other, need=pool.docs[other].length)[1] == row_before
    for d, _row in pool.residents(128):
        if pool.docs[d].row != row_before:
            pool.evict(d)
    cls, row_after = pool.admit(0, need=rec0.length)
    assert (cls, row_after) != (128, row_before), (
        "test setup: doc 0 restored into its old slot; churn not exercised"
    )

    sched.run()  # drain the rest through macro-rounds
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)
    assert pool.restores >= 1


def test_spool_checkpoint_trimmed_roundtrip(tmp_path):
    """The macro engine's spool writes are length-trimmed and
    uncompressed — they must still round-trip bit-exactly through
    utils/checkpoint for ANY resident doc state."""
    from crdt_benches_tpu.utils.checkpoint import load_state

    sessions = _mixed_sessions(tmp_path)
    pool = DocPool(classes=(256, 1024), slots=(6, 3),
                   spool_dir=str(tmp_path / "sp"))
    streams = prepare_streams(sessions, pool, batch=16, batch_chars=64)
    sched = FleetScheduler(pool, streams, batch=16, macro_k=4,
                           batch_chars=64)
    sched.run(max_rounds=2)
    doc_id, _row = pool.residents(256)[0]
    before = pool.decode(doc_id)
    path = pool.evict(doc_id)
    st = load_state(path)
    rec = pool.docs[doc_id]
    assert st.doc.shape[1] == int(st.length[0])  # trimmed to used prefix
    assert pool.decode(doc_id) == before  # spooled decode == resident
    pool.admit(doc_id, need=rec.length)
    assert pool.decode(doc_id) == before
    sched.run()
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)


def test_mesh_macro_fleet_matches_unsharded(tmp_path):
    """Docs-over-mesh with ROW-TIER SLICING: bucket rows big enough that
    compaction picks a sub-tier (Rt < R) on the 8-device virtual mesh —
    sharded slice/writeback must decode identically to the single-device
    drain, and both match the oracle."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")
    from crdt_benches_tpu.parallel.mesh import replica_mesh

    sessions = build_fleet(
        12, mix={"synth-small": 1.0}, seed=5, arrival_span=2,
        bands=TINY_BANDS,
    )

    def run(mesh, sub):
        # 128 rows over 8 shards = 16 local rows; 12 docs compact into
        # the Rt=32 tier (4 locals/shard), exercising the sliced path
        pool = DocPool(classes=(128,), slots=(128,), mesh=mesh,
                       spool_dir=str(tmp_path / sub))
        stats = _drain(sessions, pool, macro_k=4)
        assert stats.pad_fraction < 1.0
        return {s.doc_id: pool.decode(s.doc_id) for s in sessions}

    plain = run(None, "plain")
    sharded = run(replica_mesh(8), "mesh")
    assert plain == sharded
    for s in sessions:
        assert plain[s.doc_id] == replay_trace(s.trace)


def test_stats_pad_fraction_and_coalesce_ratio(tmp_path):
    """The occupancy-waste telemetry satellite: both metrics live in
    ServeStats and land in the serve_*.json artifact."""
    import json

    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=12, batch=16,
        classes=(128, 512), slots=(8, 4), seed=3, arrival_span=2,
        verify_sample=4, bands=TINY_BANDS, macro_k=4, batch_chars=64,
        spool_dir=str(tmp_path / "spool"),
        results_dir=str(tmp_path / "results"),
        log=lambda *_: None,
    )
    assert info["verify_ok"]
    stats = info["stats"]
    assert 0.0 <= stats.pad_fraction < 1.0
    assert stats.coalesce_ratio >= 1.0
    assert stats.unit_ops >= stats.ops
    with open(info["path"]) as f:
        (d,) = json.load(f)
    ex = d["extra"]
    assert 0.0 <= ex["pad_fraction"] < 1.0
    assert ex["coalesce_ratio"] >= 1.0
    assert ex["macro_k"] == 4
    assert ex["unit_ops"] >= ex["range_ops"] > 0
    # compile rounds are excluded from the latency quantiles and
    # reported separately
    assert ex["compile_rounds"] >= 1
    assert ex["compile_time"] > 0
    lat = ex["batch_latency"]
    assert set(lat) == {"p50", "p95", "p99"}
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"]


@pytest.mark.parametrize("macro_k", [1, 8])
def test_fused_scan_byte_parity_all_classes(tmp_path, macro_k):
    """THE kernel-selection parity gate: the same fleet drained through
    the fused serve step and the legacy scan body is byte-identical for
    EVERY doc, across both hosted capacity classes, at K=1 and K=8 —
    and both match the oracle."""
    sessions = _mixed_sessions(tmp_path)

    def run(kernel, sub):
        pool = DocPool(classes=(256, 1024), slots=(6, 3),
                       spool_dir=str(tmp_path / sub),
                       serve_kernel=kernel)
        stats = _drain(sessions, pool, macro_k=macro_k)
        out = {s.doc_id: pool.decode(s.doc_id) for s in sessions}
        hosted = {pool.docs[s.doc_id].cls for s in sessions}
        return out, stats, hosted

    fused, sf, hosted = run("fused", f"fused{macro_k}")
    scan, ss, _ = run("scan", f"scan{macro_k}")
    assert fused == scan
    assert len([c for c in hosted if c]) >= 2
    for s in sessions:
        assert fused[s.doc_id] == replay_trace(s.trace), (
            f"doc {s.doc_id} ({s.band}) diverged from oracle"
        )
    # identical streams -> identical op accounting on both kernels
    assert sf.ops == ss.ops and sf.unit_ops == ss.unit_ops


def test_fused_scan_parity_row_tier_slicing(tmp_path):
    """Fused-vs-scan parity where compaction picks a SUB-tier
    (Rt < R): 64 rows, 12 docs -> the Rt=16 tier, so the fused path's
    tier take/put executables and the scan path's in-jit slice are both
    exercised — and must agree byte for byte."""
    sessions = build_fleet(
        12, mix={"synth-small": 1.0}, seed=9, arrival_span=2,
        bands=TINY_BANDS,
    )

    def run(kernel, sub):
        pool = DocPool(classes=(128,), slots=(64,),
                       spool_dir=str(tmp_path / sub),
                       serve_kernel=kernel)
        stats = _drain(sessions, pool, macro_k=4)
        assert stats.pad_fraction < 1.0
        return {s.doc_id: pool.decode(s.doc_id) for s in sessions}

    fused = run("fused", "fused")
    scan = run("scan", "scan")
    assert fused == scan
    for s in sessions:
        assert fused[s.doc_id] == replay_trace(s.trace)


@pytest.mark.parametrize("kernel", ["fused", "scan"])
def test_evict_restore_mid_macro_round_both_kernels(tmp_path, kernel):
    """Mid-macro-round evict/restore churn under BOTH kernels: the
    forced-sync spool round-trip must land on identical bytes whichever
    serve step is selected."""
    from crdt_benches_tpu.traces.synth import synth_trace

    traces = [synth_trace(seed=400 + i, n_ops=100) for i in range(3)]
    sessions = [
        Session(doc_id=i, band="synth-small", source="synth", trace=t)
        for i, t in enumerate(traces)
    ]
    pool = DocPool(classes=(128,), slots=(2,),
                   spool_dir=str(tmp_path / kernel),
                   serve_kernel=kernel)
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32)
    sched.run(max_rounds=1)
    victim = next(
        d for d, _row in pool.residents(128) if streams[d].remaining > 0
    )
    pool.evict(victim)  # forced sync against the in-flight dispatch
    pool.admit(victim, need=pool.docs[victim].length)
    sched.run()
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)
    assert pool.restores >= 1


def test_steady_quantiles_excludes_flagged():
    lats = [5.0, 0.1, 0.2, 0.3, 9.0]
    flags = [True, False, False, False, True]
    q, skipped_time, n = steady_quantiles(lats, flags)
    assert n == 2 and skipped_time == 14.0
    assert q["p50"] == 0.2 and q["p99"] <= 0.3
    # all-flagged falls back to the full list instead of raising
    q2, t2, n2 = steady_quantiles([1.0, 2.0], [True, True], ps=(0.5,))
    assert q2["p50"] == 1.5 and n2 == 2 and t2 == 3.0
    with pytest.raises(ValueError):
        steady_quantiles([1.0], [True, False])
