"""graftlint v5 runtime twin: the lifecycle sanitizer's
disarmed-identity contract, the armed typed-error surface (undeclared
/ illegal / wrong-state transitions, double release, use-after-release,
negative gauges, the drain-end leak gate), the generation tags that pin
the PR 17 id-recycling and lazy-tensorize cache incidents, and the
Prefetcher reap-race integration (the inflight gauge can never go
negative again)."""

import time

import pytest

from crdt_benches_tpu.lint import lifecycle_sanitizer as lcs
from crdt_benches_tpu.serve.prefetch import Prefetcher


@pytest.fixture(autouse=True)
def _lc_reset(monkeypatch):
    """Every test owns a clean sanitizer: counters zeroed, disarmed
    unless the test arms it, and machine declarations restored (they
    survive reset_counters by design — other suites' pools declare
    machines as a side effect of construction)."""
    monkeypatch.delenv("CRDT_BENCH_SANITIZE_LIFECYCLE", raising=False)
    saved = dict(lcs._decls)
    lcs.disarm()
    lcs.reset_counters()
    yield
    lcs.disarm()
    lcs.reset_counters()
    lcs._decls.clear()
    lcs._decls.update(saved)


# ---------------------------------------------------------------------------
# disarmed identity
# ---------------------------------------------------------------------------


def test_disarmed_counts_everything_but_enforces_nothing():
    """Disarmed, the sanitizer is a pure counter (the G025 ground
    truth): illegal edges, double releases, and negative gauges all
    record without raising, and no live-object model exists."""
    assert not lcs.armed()
    lcs.declare_machine("spool", ("live", "cold"), (("live", "cold"),))
    lcs.transition("spool", "cold", "live")  # illegal edge: counted
    lcs.acquire("rows", 7)
    lcs.release("rows", 7)
    lcs.release("rows", 7)  # double release: counted, no raise
    lcs.gauge("prefetch_inflight", -3)  # negative: recorded, no raise
    lcs.touch("rows", 7)  # no tracking, no raise
    c = lcs.counters()
    assert c["machines"] == {"spool": {"cold->live": 1}}
    assert c["resources"]["rows"] == {"acquire": 1, "release": 2}
    assert c["gauges"]["prefetch_inflight"] == -3
    assert lcs.live_count() == 0 and lcs.live_keys() == []
    lcs.assert_all_released()  # nothing tracked -> nothing leaked


def test_disarmed_undeclared_transition_lands_in_unattributed():
    lcs.transition("ghost", "x", "y")
    assert lcs.counters()["unattributed"] == ["ghost:x->y"]


def test_env_flag_arms_eagerly_at_reset(monkeypatch):
    """``CRDT_BENCH_SANITIZE_LIFECYCLE=1`` arms at reset_counters (not
    at first transition) so acquisitions before any edge are tracked."""
    monkeypatch.setenv("CRDT_BENCH_SANITIZE_LIFECYCLE", "1")
    lcs.reset_counters()
    assert lcs.armed()
    with pytest.raises(lcs.DoubleReleaseError, match="never acquired"):
        lcs.release("rows", 1)


# ---------------------------------------------------------------------------
# armed enforcement
# ---------------------------------------------------------------------------


def test_armed_undeclared_machine_is_a_typed_error():
    lcs.arm()
    with pytest.raises(lcs.UndeclaredTransitionError,
                       match="undeclared machine `ghost`"):
        lcs.transition("ghost", "x", "y")
    # still counted on the way out: the artifact names the rogue edge
    assert lcs.counters()["unattributed"] == ["ghost:x->y"]


def test_armed_illegal_edge_is_a_typed_error():
    lcs.arm()
    lcs.declare_machine("spool", ("live", "cold"), (("live", "cold"),))
    with pytest.raises(lcs.UndeclaredTransitionError,
                       match="not in the declared edge graph"):
        lcs.transition("spool", "cold", "live")


def test_armed_keyed_transition_tracks_per_instance_state():
    """A keyed transition must depart from the instance's ACTUAL state;
    a key the model has not seen yet passes any legal departure (docs
    exist before their first counted edge)."""
    lcs.arm()
    lcs.declare_machine(
        "spool", ("live", "cold"),
        (("live", "cold"), ("cold", "live")),
    )
    lcs.transition("spool", "cold", "live", key=11)  # unseen key: ok
    lcs.transition("spool", "live", "cold", key=11)
    with pytest.raises(lcs.UndeclaredTransitionError,
                       match="is in state `cold`, not `live`"):
        lcs.transition("spool", "live", "cold", key=11)
    # unkeyed edges never consult instance state
    lcs.transition("spool", "live", "cold")


def test_armed_double_release_distinguishes_its_two_shapes():
    lcs.arm()
    lcs.acquire("segment", "wal-0001")
    lcs.release("segment", "wal-0001")
    with pytest.raises(lcs.DoubleReleaseError, match="already released"):
        lcs.release("segment", "wal-0001")
    with pytest.raises(lcs.DoubleReleaseError, match="never acquired"):
        lcs.release("segment", "wal-0002")


def test_armed_use_after_release_raises_but_unseen_keys_pass():
    lcs.arm()
    lcs.acquire("stream", 5)
    lcs.touch("stream", 5)  # live: fine
    lcs.release("stream", 5)
    with pytest.raises(lcs.UseAfterReleaseError, match="after its release"):
        lcs.touch("stream", 5)
    lcs.touch("stream", 99)  # out of jurisdiction: passes


def test_generation_tag_bumps_on_reacquire():
    """The PR 17 id-recycling pin: a recycled key re-acquired is a NEW
    object under a fresh generation — cache layers keying entries as
    ``(key, generation(...))`` (the lazy-tensorize fix) can never take
    a stale hit, because the dead object's generation is unreachable."""
    lcs.arm()
    lcs.acquire("stream", 0xBEEF)
    g1 = lcs.generation("stream", 0xBEEF)
    lcs.release("stream", 0xBEEF)
    assert lcs.generation("stream", 0xBEEF) is None
    lcs.acquire("stream", 0xBEEF)  # id recycled by the allocator
    g2 = lcs.generation("stream", 0xBEEF)
    assert g2 == g1 + 1
    lcs.touch("stream", 0xBEEF)  # live again under the new generation
    lcs.release("stream", 0xBEEF)


def test_negative_gauge_is_a_typed_error_armed():
    """The PR 17 inflight-underflow pin, as a typed error instead of a
    silently wrong submission budget."""
    lcs.arm()
    lcs.gauge("prefetch_inflight", 2)
    lcs.gauge("prefetch_inflight", 0)
    with pytest.raises(lcs.NegativeGaugeError, match="observed at -1"):
        lcs.gauge("prefetch_inflight", -1)


def test_leak_gate_names_the_leaked_keys_then_passes_after_release():
    lcs.arm()
    lcs.acquire("rows", (64, 3))
    lcs.acquire("socket", "front")
    assert lcs.live_count() == 2
    assert lcs.live_count("rows") == 1
    with pytest.raises(lcs.LifecycleLeakError) as ei:
        lcs.assert_all_released()
    msg = str(ei.value)
    assert "2 unreleased acquisition(s) at drain end" in msg
    assert "rows:(64, 3)" in msg and "socket:'front'" in msg
    lcs.release("rows", (64, 3))
    lcs.release("socket", "front")
    lcs.assert_all_released()


# ---------------------------------------------------------------------------
# Prefetcher integration: the reap race stays fixed
# ---------------------------------------------------------------------------


def test_prefetcher_reap_race_never_drives_the_gauge_negative():
    """A payload whose read outlives its reaping used to decrement
    ``inflight`` a second time; armed, that underflow would now be a
    NegativeGaugeError at the callsite — so this drain completing
    without one IS the regression pin."""
    lcs.arm()
    p = Prefetcher(capacity=8)
    p.start()
    try:
        seq = p.submit_construct(7, lambda: {"row": None})
        assert seq >= 1 and p.inflight == 1
        p.note_lost([seq])  # reaped before the payload lands
        assert p.inflight == 0
        deadline = time.time() + 10.0
        while p.reap_dropped == 0 and time.time() < deadline:
            p.drain()
            time.sleep(0.01)
        assert p.reap_dropped == 1
        assert p.inflight == 0  # no second decrement
        assert lcs.counters()["gauges"]["prefetch_inflight"] == 0
    finally:
        p.stop()
    lcs.assert_all_released()  # start/stop thread pairing is clean


def test_prefetcher_count_only_reap_clamps_at_zero():
    lcs.arm()
    p = Prefetcher()
    p.note_lost(3)  # bare-int reap with nothing in flight: clamped
    assert p.inflight == 0
    assert lcs.counters()["gauges"]["prefetch_inflight"] == 0
