"""Downstream (remote-update-apply) correctness: generated updates integrate
into a fresh replica to byte-identical final content (the upgrade over the
reference's length-only downstream assert, src/main.rs:68)."""

import numpy as np
import pytest

from crdt_benches_tpu.engine.downstream import (
    JaxDownstreamEngine,
    generate_updates,
)
from crdt_benches_tpu.oracle import replay_unit_ops
from crdt_benches_tpu.traces import tensorize
from crdt_benches_tpu.traces.tensorize import DELETE, INSERT

from test_engine import tensorize_ops

A, B_, C_ = ord("a"), ord("b"), ord("c")


def check_downstream(kinds, poss, chs, batch=8, start="", n_replicas=1):
    tt = tensorize_ops(kinds, poss, chs, batch=batch, start=start)
    want = replay_unit_ops(
        tt.kind[: tt.n_ops], tt.pos[: tt.n_ops], tt.ch[: tt.n_ops], start=start
    )
    eng = JaxDownstreamEngine(tt, n_replicas=n_replicas)
    state = eng.run()
    for r in range(n_replicas):
        assert eng.decode(state, replica=r) == want


@pytest.mark.slow
def test_append_only():
    check_downstream([INSERT] * 4, [0, 1, 2, 3], [A, B_, C_, A])


def test_insert_at_head():
    check_downstream([INSERT] * 4, [0, 0, 0, 0], [A, B_, C_, A])


@pytest.mark.slow
def test_inserts_span_batches():
    # 20 ops across 3 batches of 8: interleaved head/tail inserts
    kinds = [INSERT] * 20
    poss = [0, 1, 0, 2, 1, 5, 0, 7, 3, 9, 0, 1, 2, 3, 4, 15, 0, 17, 5, 19]
    chs = [A + (i % 26) for i in range(20)]
    check_downstream(kinds, poss, chs)


@pytest.mark.slow
def test_delete_prebatch():
    check_downstream(
        [INSERT, INSERT, INSERT, INSERT, INSERT, INSERT, INSERT, INSERT,
         DELETE, DELETE],
        [0, 1, 2, 3, 4, 5, 6, 7, 0, 3],
        [A + i for i in range(8)] + [0, 0],
    )


def test_same_batch_insert_and_delete():
    # insert then delete within one batch: the killed insert must tombstone
    check_downstream(
        [INSERT, INSERT, INSERT, DELETE, INSERT, DELETE, INSERT, INSERT],
        [0, 1, 2, 1, 1, 2, 0, 4],
        [A, B_, C_, 0, A, 0, B_, C_],
    )


def test_with_start_content():
    check_downstream(
        [INSERT, DELETE, INSERT, DELETE],
        [3, 0, 5, 1],
        [A, 0, B_, 0],
        start="hello",
    )


@pytest.mark.slow
def test_vmapped_replicas():
    check_downstream(
        [INSERT] * 6 + [DELETE] * 2,
        [0, 0, 2, 1, 4, 3, 2, 0],
        [A + i for i in range(6)] + [0, 0],
        n_replicas=3,
    )


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.slow
def test_random_ops_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    kinds, poss, chs = [], [], []
    doc_len = 4
    for _ in range(300):
        if doc_len == 0 or rng.random() < 0.65:
            kinds.append(INSERT)
            poss.append(int(rng.integers(0, doc_len + 1)))
            chs.append(int(rng.integers(97, 123)))
            doc_len += 1
        else:
            kinds.append(DELETE)
            poss.append(int(rng.integers(0, doc_len)))
            chs.append(0)
            doc_len -= 1
    check_downstream(kinds, poss, chs, batch=32, start="base")


@pytest.mark.parametrize("engine", ["v5", "v3", "v1"])
@pytest.mark.slow
def test_svelte_trace_byte_identical(svelte_trace, engine):
    tt = tensorize(svelte_trace, batch=512)
    eng = JaxDownstreamEngine(tt, engine=engine)
    state = eng.run()
    assert int(np.asarray(state.nvis).reshape(-1)[0]) == len(
        svelte_trace.end_content
    )
    assert eng.decode(state) == svelte_trace.end_content


@pytest.mark.parametrize("engine", ["v3", "v1"])
@pytest.mark.parametrize("seed", [3, 11])
@pytest.mark.slow
def test_random_ops_all_engines(seed, engine):
    """The non-default engines (positional v3, scatter v1) integrate the
    same random streams byte-identically."""
    rng = np.random.default_rng(seed)
    kinds, poss, chs = [], [], []
    doc_len = 4
    for _ in range(300):
        if doc_len == 0 or rng.random() < 0.6:
            kinds.append(INSERT)
            poss.append(int(rng.integers(0, doc_len + 1)))
            chs.append(int(rng.integers(97, 123)))
            doc_len += 1
        else:
            kinds.append(DELETE)
            poss.append(int(rng.integers(0, doc_len)))
            chs.append(0)
            doc_len -= 1
    tt = tensorize_ops(kinds, poss, chs, batch=32, start="base")
    want = replay_unit_ops(
        tt.kind[: tt.n_ops], tt.pos[: tt.n_ops], tt.ch[: tt.n_ops],
        start="base",
    )
    eng = JaxDownstreamEngine(tt, engine=engine)
    assert eng.decode(eng.run()) == want


@pytest.mark.slow
def test_update_wire_size_reported(svelte_trace):
    tt = tensorize(svelte_trace, batch=512)
    upd = generate_updates(tt)
    assert upd.nbytes() > 0
    assert upd.n_patches == len(svelte_trace)
