"""Deterministic chaos: injected faults, in-run repair, backpressure,
quarantine, and graceful degradation.

Every test's ground truth is the oracle: whatever the injector breaks,
non-lossy documents must finish byte-identical — loss is only ever the
result of an EXPLICIT, surfaced decision (shed / quarantine)."""

import json

import pytest

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.faults import (
    FaultEvent,
    FaultInjector,
    FaultPlan,
)
from crdt_benches_tpu.serve.journal import OpJournal
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import FleetScheduler, prepare_streams
from crdt_benches_tpu.serve.workload import build_fleet
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.serve.workload import Session

TINY_BANDS = {"synth-small": ("synth", (40, 120))}
TINY_MIX = {"synth-small": 1.0}


def _fleet(tmp_path, n=5, seed=11, classes=(128,), slots=(2,), **kw):
    """A deliberately over-subscribed fleet (more docs than rows) so
    eviction spools churn — the surface most faults target."""
    sessions = build_fleet(
        n, mix=TINY_MIX, seed=seed, arrival_span=2, bands=TINY_BANDS
    )
    pool = DocPool(classes=classes, slots=slots,
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32, **kw)
    return sessions, pool, streams, sched


def _assert_oracle_parity(sessions, pool, streams, skip_lossy=True):
    for s in sessions:
        if skip_lossy and streams[s.doc_id].lossy:
            continue
        assert pool.decode(s.doc_id) == replay_trace(s.trace), (
            f"doc {s.doc_id} diverged"
        )


@pytest.mark.parametrize("kind", ["spool_corrupt", "spool_truncate"])
def test_spool_damage_healed_by_rebuild(tmp_path, kind):
    """A spool that fails its CRC on restore is rebuilt from the stream
    through the macro replay path — every doc still matches the oracle
    and the event is recovered."""
    plan = FaultPlan([FaultEvent(kind=kind, round=2)], seed=3)
    sessions, pool, streams, sched = _fleet(
        tmp_path, faults=FaultInjector(plan)
    )
    sched.run()
    assert sched.done
    (ev,) = plan.events
    assert ev.fired and ev.recovered
    assert sched.stats.recoveries >= 1
    assert sched.stats.ops_replayed > 0
    assert sched.stats.mttr_rounds  # MTTR recorded per recovery
    assert not sched.stats.quarantines
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=False)


def test_spool_heal_uses_snapshot_base(tmp_path):
    """With snapshot barriers enabled, the rebuild starts from the last
    snapshot base instead of replaying the whole stream — the redo span
    is bounded by the barrier."""
    plan = FaultPlan([FaultEvent(kind="spool_corrupt", round=4)], seed=3)
    jd = str(tmp_path / "journal")
    sessions, pool, streams, sched = _fleet(
        tmp_path, faults=FaultInjector(plan),
        journal=OpJournal(jd), snapshot_every=1,
    )
    sched.run()
    assert sched.done
    (ev,) = plan.events
    assert ev.fired and ev.recovered and sched.stats.recoveries >= 1
    victim = ev.detail["doc"]
    # the rebuilt span must be shorter than the victim's full stream
    assert sched.stats.ops_replayed < streams[victim].cursor or (
        sched.stats.ops_replayed <= streams[victim].n_total
    )
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=False)


def test_device_loss_mid_macro_round_recovers(tmp_path):
    """Clobbering a class's device state right after a macro dispatch:
    that round's lanes are dropped un-advanced, every resident row is
    rebuilt at its applied cursor, and the drain converges to oracle
    parity."""
    plan = FaultPlan([FaultEvent(kind="device_loss", round=3)], seed=5)
    sessions, pool, streams, sched = _fleet(
        tmp_path, faults=FaultInjector(plan)
    )
    sched.run()
    assert sched.done
    (ev,) = plan.events
    assert ev.fired and ev.recovered
    assert ev.detail["docs"] >= 1
    assert sched.stats.recoveries >= 1
    assert sched.stats.mttr_rounds
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=False)


def test_duplicated_batch_clamped_not_reapplied(tmp_path):
    """Redelivered (duplicate/stale-reordered) batches are clamped at
    the cursor high-water mark: counted, dropped, and the final state is
    unaffected."""
    plan = FaultPlan([FaultEvent(kind="dup_batch", round=2),
                      FaultEvent(kind="dup_batch", round=3)], seed=1)
    sessions, pool, streams, sched = _fleet(
        tmp_path, faults=FaultInjector(plan)
    )
    sched.run()
    assert sched.done
    assert all(e.fired and e.recovered for e in plan.events)
    assert sched.stats.dup_ops_dropped > 0
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=False)


def test_bounded_queue_backpressure_defer_loses_nothing(tmp_path):
    """A small queue cap clips delivery (backpressure) but defers, never
    drops: deferred_ops counts the pushback, the drain still completes,
    and every doc matches the oracle."""
    sessions, pool, streams, sched = _fleet(
        tmp_path, queue_cap=8, overflow_policy="defer"
    )
    sched.run()
    assert sched.done
    assert sched.stats.deferred_ops > 0
    assert sched.stats.backpressure_rounds > 0
    assert sched.stats.shed_ops == 0
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=False)


def test_banded_delivery_burst_flows_through(tmp_path):
    """workload.build_fleet(delivery='banded') attaches per-band
    producer rates that the bounded queue consumes."""
    sessions = build_fleet(
        4, mix=TINY_MIX, seed=2, arrival_span=1, bands=TINY_BANDS,
        delivery="banded",
    )
    assert all(s.burst is not None and s.burst > 0 for s in sessions)
    pool = DocPool(classes=(128,), slots=(4,),
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    assert all(st.burst == s.burst
               for s, st in zip(sessions, streams.values()))
    sched = FleetScheduler(pool, streams, batch=8, macro_k=2,
                           batch_chars=32, queue_cap=16)
    sched.run()
    assert sched.done
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=False)


def test_queue_overflow_shed_policy_is_explicit_and_surfaced(tmp_path):
    """Under the shed policy an overflow burst tail-drops ONE session's
    remaining ops: the loss is counted, the doc marked lossy (excluded
    from verification), and every other doc still matches the oracle."""
    plan = FaultPlan(
        [FaultEvent(kind="queue_overflow", round=2, param=64)], seed=9
    )
    sessions, pool, streams, sched = _fleet(
        tmp_path, faults=FaultInjector(plan),
        queue_cap=8, overflow_policy="shed",
    )
    sched.run()
    assert sched.done
    (ev,) = plan.events
    assert ev.fired and ev.recovered and ev.detail["shed"] > 0
    assert sched.stats.overflow_events == 1
    assert sched.stats.shed_ops == ev.detail["shed"]
    lossy = [d for d, st in streams.items() if st.lossy]
    assert lossy == [ev.detail["doc"]]
    st = streams[lossy[0]]
    assert st.limit is not None and st.remaining == 0
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=True)


def test_poisoned_rebuild_quarantines_and_fleet_survives(tmp_path):
    """When repair itself fails, the doc is quarantined — remaining ops
    shed, row freed — and the REST of the fleet drains to oracle
    parity.  Availability beats completeness for one tenant."""
    plan = FaultPlan([
        FaultEvent(kind="spool_corrupt", round=2),
        FaultEvent(kind="poison_rebuild", round=0),
    ], seed=3)
    sessions, pool, streams, sched = _fleet(
        tmp_path, faults=FaultInjector(plan)
    )
    sched.run()
    assert sched.done
    assert len(sched.stats.quarantines) == 1
    q = sched.stats.quarantines[0]
    assert streams[q["doc"]].lossy
    assert sched.stats.shed_ops >= q["shed_ops"] >= 0
    assert pool.docs[q["doc"]].cls is None  # row freed, fleet serving
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=True)


def test_repeated_faults_degrade_to_k1_then_restore(tmp_path):
    """Fault density trips the macro-K -> K=1 synchronous fallback for a
    cooldown window, then K restores automatically."""
    plan = FaultPlan([FaultEvent(kind="stall", round=2, param=1),
                      FaultEvent(kind="stall", round=3, param=1),
                      FaultEvent(kind="stall", round=4, param=1)], seed=0)
    # long enough streams that the drain outlives the cooldown window
    traces = [synth_trace(seed=300 + i, n_ops=600) for i in range(3)]
    sessions = [
        Session(doc_id=i, band="synth-small", source="synth", trace=t)
        for i, t in enumerate(traces)
    ]
    pool = DocPool(classes=(1024,), slots=(3,),
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    sched = FleetScheduler(
        pool, streams, batch=8, macro_k=4, batch_chars=32,
        faults=FaultInjector(plan), degrade_after=2, degrade_window=8,
        degrade_rounds=3,
    )
    sched.run()
    assert sched.done
    assert sched.stats.stall_rounds == 3
    assert sched.stats.degraded_rounds >= 3  # the K=1 cooldown ran
    assert sched.effective_k == 4  # ...and K restored afterwards
    _assert_oracle_parity(sessions, pool, streams, skip_lossy=False)


def test_fault_spec_grammar():
    plan = FaultPlan.from_spec(
        "seed=7,span=6,stall_ms=5,burst=32,"
        "spool_corrupt=2,device_loss@4=1,queue_overflow=1"
    )
    kinds = sorted(e.kind for e in plan.events)
    assert kinds == ["device_loss", "queue_overflow",
                     "spool_corrupt", "spool_corrupt"]
    assert next(e for e in plan.events if e.kind == "device_loss").round == 4
    assert all(2 <= e.round <= 6 for e in plan.events)
    assert plan.stall_ms == 5 and plan.burst == 32
    # same spec -> same schedule (seeded determinism)
    plan2 = FaultPlan.from_spec(plan.spec or
                                "seed=7,span=6,stall_ms=5,burst=32,"
                                "spool_corrupt=2,device_loss@4=1,"
                                "queue_overflow=1")
    assert [(e.kind, e.round) for e in plan.events] == \
        [(e.kind, e.round) for e in plan2.events]
    with pytest.raises(ValueError, match="unknown kind"):
        FaultPlan.from_spec("meteor_strike=1")


def test_bench_chaos_artifact_and_gates(tmp_path):
    """run_serve_bench in chaos mode: verify_ok AND faults_ok, with the
    full robustness surface (faults block, recovery metrics, journal
    stats, shed/deferred counters) persisted in the artifact."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=8, batch=8,
        classes=(128, 512), slots=(3, 2), seed=3, arrival_span=2,
        verify_sample=4, bands=TINY_BANDS, macro_k=4, batch_chars=32,
        spool_dir=str(tmp_path / "spool"),
        journal_dir=str(tmp_path / "journal"),
        snapshot_every=2,
        faults="seed=5,span=4,spool_corrupt=1,device_loss=1,"
               "queue_overflow=1,dup_batch=1,stall=1,stall_ms=1",
        results_dir=str(tmp_path / "results"),
        log=lambda *_: None,
    )
    assert info["verify_ok"] and info["faults_ok"]
    with open(info["path"]) as f:
        (d,) = json.load(f)
    ex = d["extra"]
    f = ex["faults"]
    assert f["injected"] == 5 and f["unrecovered"] == 0
    assert f["not_fired"] == 0
    kinds = {e["kind"] for e in f["events"] if e["fired"]}
    assert kinds == {"spool_corrupt", "device_loss", "queue_overflow",
                     "dup_batch", "stall"}
    assert ex["queue_cap"] > 0  # auto-defaulted for queue_overflow
    assert ex["mttr_rounds"]["n"] >= 1
    assert ex["recoveries"] >= 1 and ex["ops_replayed"] > 0
    assert ex["journal"]["records"] > 0
    assert ex["journal"]["snapshots"] >= 1
    assert ex["shed_ops"] == 0  # defer policy: chaos without data loss
    assert ex["verify_ok"] is True


def test_device_loss_under_tiered_pool_rebuilds_all_tiers(tmp_path):
    """``device_loss`` on a TIERED lazy fleet mid-drain: the warm tier
    is host memory the loss cannot touch, a still-genesis doc has no
    device state to lose at all, and every lost hot row rebuilds at its
    applied cursor — the drain converges to oracle parity across all
    four residency tiers."""
    from crdt_benches_tpu.serve.scheduler import LazyStreams
    from crdt_benches_tpu.serve.workload import FleetSpec

    spec = FleetSpec.build(8, mix=TINY_MIX, seed=9, arrival_span=3,
                           bands=TINY_BANDS)
    pool = DocPool(classes=(128,), slots=(2,),
                   spool_dir=str(tmp_path / "spool"), warm_docs=4)
    streams = LazyStreams(spec, pool, batch=8, batch_chars=32)
    plan = FaultPlan([FaultEvent(kind="device_loss", round=3)], seed=5)
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32, faults=FaultInjector(plan))
    assert pool.genesis_docs == 8  # a lazy fleet is born fully genesis
    sched.run()
    assert sched.done and streams.all_done
    (ev,) = plan.events
    assert ev.fired and ev.recovered
    assert ev.detail["docs"] >= 1
    assert sched.stats.recoveries >= 1
    ts = pool.tier_status()
    assert ts["genesis_docs"] == 0  # every doc materialized post-loss
    # 8 docs over 2 hot rows with a 4-entry warm tier: demotions land
    # warm, so the loss round had host-side state to rebuild from
    assert ts["warm_evictions"] + ts["warm_hits"] + len(pool.warm) > 0
    for d in range(spec.n_docs):
        s = spec.session(d)
        assert pool.decode(d) == replay_trace(s.trace), (
            f"doc {d} diverged"
        )
