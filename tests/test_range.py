"""Range-op engine tests: tensorizer invariants, kernel (interpret mode on
CPU) + apply vs the oracle, and equivalence with the exploded v3 engine."""

import jax.numpy as jnp
import numpy as np
import pytest

from crdt_benches_tpu.engine.replay import ReplayEngine
from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
from crdt_benches_tpu.oracle import OracleDocument
from crdt_benches_tpu.traces.synth import synth_trace
from crdt_benches_tpu.traces.tensorize import (
    tensorize,
    tensorize_ranges,
)


def _oracle(trace):
    doc = OracleDocument.from_str(trace.start_content)
    for p, d, ins in trace.iter_patches():
        doc.replace(p, p + d, ins)
    return doc.content()


@pytest.fixture(params=["v3", "v4"])
def range_apply(request, monkeypatch):
    """Run the test under both range-apply engines: v4 (fused kernel,
    the default) AND v3 (the per-pass XLA apply the driver auto-falls
    back to on large-capacity TPU runs).  interpret-mode CI otherwise
    never touches v3 (ADVICE r4)."""
    monkeypatch.setenv("CRDT_RANGE_APPLY", request.param)
    return request.param


def test_tensorize_ranges_invariants(svelte_trace):
    rt = tensorize_ranges(svelte_trace, batch=256)
    tt = tensorize(svelte_trace, batch=256)
    assert rt.capacity == tt.capacity  # same slot universe
    assert rt.n_ins_chars == tt.n_inserts
    assert rt.n_ops <= 2 * len(svelte_trace)
    assert rt.n_ops < tt.n_ops  # the whole point
    np.testing.assert_array_equal(
        rt.chars, np.asarray(tt.ch[tt.slot >= 0])
    ) if len(rt.init_chars) == 0 else None


@pytest.mark.parametrize("seed", [0, 1, 2, 5])
@pytest.mark.parametrize("batch", [16, 64])
@pytest.mark.slow
def test_range_engine_vs_oracle_synth(seed, batch, range_apply):
    trace = synth_trace(seed=seed, n_ops=250, base="range engine test ")
    rt = tensorize_ranges(trace, batch=batch)
    eng = RangeReplayEngine(rt, n_replicas=2, interpret=True, chunk=4)
    st = eng.run()
    want = _oracle(trace)
    assert eng.decode(st, replica=0) == want
    assert eng.decode(st, replica=1) == want
    assert (eng.lengths(st) == len(want)).all()


@pytest.mark.slow
def test_range_engine_block_edits(range_apply):
    # Big block inserts/deletes (the rustcode-style workload).
    from crdt_benches_tpu.traces.loader import TestData, TestPatch, TestTxn

    rng = np.random.default_rng(7)
    txns = []
    content = ""
    for i in range(60):
        r = rng.random()
        pos = int(rng.integers(0, len(content) + 1))
        if r < 0.6 or not content:
            ins = "".join(
                chr(97 + int(c)) for c in rng.integers(0, 26, int(rng.integers(1, 400)))
            )
            txns.append([[pos, 0, ins]])
            content = content[:pos] + ins + content[pos:]
        else:
            d = int(rng.integers(1, min(300, len(content) - pos) + 1)) if pos < len(content) else 0
            txns.append([[pos, d, ""]])
            content = content[:pos] + content[pos + d:]
    trace = TestData(
        start_content="",
        end_content=content,
        txns=[
            TestTxn(time="", patches=[TestPatch(*p) for p in t])
            for t in txns
        ],
    )
    rt = tensorize_ranges(trace, batch=16)
    eng = RangeReplayEngine(rt, n_replicas=1, interpret=True, chunk=4)
    st = eng.run()
    assert eng.decode(st) == content


@pytest.mark.slow
def test_range_matches_exploded_v3(svelte_trace):
    # Prefix of the real svelte trace through both engines.
    import dataclasses

    sub = dataclasses.replace(
        svelte_trace, txns=svelte_trace.txns[:300]
    )
    # recompute end content via oracle for the truncated trace
    want = _oracle(sub)
    rt = tensorize_ranges(sub, batch=64)
    e_r = RangeReplayEngine(rt, n_replicas=1, interpret=True, chunk=4)
    assert e_r.decode(e_r.run()) == want
    tt = tensorize(sub, batch=64)
    e_v = ReplayEngine(tt, n_replicas=1, resolver="scan", engine="v3")
    assert e_v.decode(e_v.run()) == want


# ---- cross-patch run coalescing (RLE of the edit stream) -------------------


def test_coalesce_patches_patterns():
    from crdt_benches_tpu.traces.loader import TestData, TestPatch, TestTxn
    from crdt_benches_tpu.traces.tensorize import coalesce_patches

    def mk(patches):
        return TestData(
            start_content="", end_content="",
            txns=[TestTxn(time="", patches=[TestPatch(*p) for p in patches])],
        )

    # typing run: consecutive inserts at advancing positions merge
    t = mk([[0, 0, "a"], [1, 0, "b"], [2, 0, "c"]])
    assert list(coalesce_patches(t)) == [(0, 0, "abc")]
    # forward delete (Del key): same position
    t = mk([[3, 1, ""], [3, 1, ""], [3, 1, ""]])
    assert list(coalesce_patches(t)) == [(3, 3, "")]
    # backspace run: deletes walking leftward
    t = mk([[5, 1, ""], [4, 1, ""], [3, 1, ""]])
    assert list(coalesce_patches(t)) == [(3, 3, "")]
    # non-adjacent edits do NOT merge
    t = mk([[0, 0, "a"], [5, 0, "b"]])
    assert list(coalesce_patches(t)) == [(0, 0, "a"), (5, 0, "b")]
    # replace patches split into delete + insert, each coalescing separately
    t = mk([[2, 2, "xy"], [4, 0, "z"]])
    assert list(coalesce_patches(t)) == [(2, 2, ""), (2, 0, "xyz")]


@pytest.mark.parametrize("seed", [0, 3, 9])
def test_coalesce_oracle_equivalence_synth(seed):
    from crdt_benches_tpu.traces.synth import synth_trace
    from crdt_benches_tpu.traces.tensorize import coalesce_patches

    trace = synth_trace(seed=seed, n_ops=300, base="coalesce me now")
    want = _oracle(trace)
    doc = OracleDocument.from_str(trace.start_content)
    n_coal = 0
    for p, d, ins in coalesce_patches(trace):
        doc.replace(p, p + d, ins)
        n_coal += 1
    assert doc.content() == want
    assert n_coal <= sum(len(t.patches) for t in trace.txns) * 2


@pytest.mark.slow
def test_coalesced_range_engine_byte_identical(svelte_trace, range_apply):
    rt = tensorize_ranges(svelte_trace, batch=256, coalesce=True)
    rt_plain = tensorize_ranges(svelte_trace, batch=256)
    assert rt.n_ops < rt_plain.n_ops // 2  # the point: far fewer ops
    assert rt.capacity == rt_plain.capacity  # same slot universe
    eng = RangeReplayEngine(rt, n_replicas=2, interpret=True, chunk=8)
    st = eng.run()
    assert eng.decode(st, replica=0) == svelte_trace.end_content
    assert eng.decode(st, replica=1) == svelte_trace.end_content


def test_del_stop_shift_bounds():
    from crdt_benches_tpu.ops.apply_range_fused import _del_stop_shift

    for B in (1, 16, 512, 1024):
        assert _del_stop_shift(B) == 14  # historical packing preserved
    for B in (1025, 1536, 2048, 3000, 4095):
        sh = _del_stop_shift(B)
        assert (1 << sh) > B  # field holds counts up to B
        assert B * ((1 << sh) + 1) <= 1 << 24  # f32-exact accumulation
    with pytest.raises(ValueError):
        _del_stop_shift(4096)  # first B where no single packing is exact


@pytest.mark.slow
def test_range_engine_wide_batch_byte_identical():
    # B > 1024 routes the delete-boundary spread through the narrowed
    # stop-shift (_del_stop_shift); the headline config runs B=1536.
    trace = synth_trace(seed=11, n_ops=2600, base="wide batch dsh test ")
    rt = tensorize_ranges(trace, batch=1536)
    eng = RangeReplayEngine(rt, n_replicas=1, interpret=True, chunk=4)
    assert eng.decode(eng.run()) == _oracle(trace)


@pytest.mark.slow
def test_range_token_cap_exact(svelte_trace):
    # The capped resolver must produce byte-identical replay: the host
    # simulation (simulate_range_token_counts) bounds the real token list.
    import os

    rt = tensorize_ranges(svelte_trace, batch=128, coalesce=True)
    eng = RangeReplayEngine(rt, n_replicas=1, interpret=True, chunk=8)
    # caps actually bite: strictly below the uncapped rounded T (384)
    assert any(c is not None and c < 384 for c in eng.token_caps)
    assert eng.decode(eng.run()) == svelte_trace.end_content

    os.environ["CRDT_ENGINE_TOKENSIM"] = "0"
    try:
        eng2 = RangeReplayEngine(rt, n_replicas=1, interpret=True, chunk=8)
        assert eng2.token_caps == [None] * len(eng2.chunks)
        assert eng2.decode(eng2.run()) == svelte_trace.end_content
    finally:
        del os.environ["CRDT_ENGINE_TOKENSIM"]


def _random_blocked_inputs(seed, R=2, C=2048, L=1500):
    """Plausible dense inputs for the fused range kernels: disjoint
    delete intervals, disjoint insert runs with increasing destinations,
    consistent dd deltas (the apply_range_batch4 producer's invariants)."""
    rng = np.random.default_rng(seed)
    doc = np.full((R, C), 2, np.int32)
    for r in range(R):
        vis = rng.random(L) < 0.8
        doc[r, :L] = ((np.arange(L) + 2) << 1) | vis.astype(np.int32)
    delpk = np.zeros((R, C), np.int32)
    for r in range(R):
        pos = np.sort(rng.choice(L, 6, replace=False))
        for i in range(0, 6, 2):
            delpk[r, pos[i]] += 1
            delpk[r, pos[i + 1] + 1] += 1 << 14
    ind_d = np.zeros((R, C), np.int32)
    dd = np.zeros((R, C), np.int32)
    newlen = np.full(R, L, np.int32)
    for r in range(R):
        dests = np.sort(rng.choice(np.arange(50, L, 37), 5, replace=False))
        total = 0
        prev_delta = 0
        for d0 in dests:
            ln = int(rng.integers(1, 9))
            dest = d0 + total
            ind_d[r, dest] += 1
            ind_d[r, dest + ln] -= 1
            delta = (1600 + total) - dest
            dd[r, dest] = delta - prev_delta
            prev_delta = delta
            total += ln
        newlen[r] = L + total
    return doc, delpk, ind_d, dd, newlen


@pytest.mark.parametrize(
    "seed", [0] + [pytest.param(x, marks=pytest.mark.slow) for x in (3, 8)]
)
def test_range_fused_blocked_matches_xla(seed):
    """The halo-blocked kernel (capacities beyond the monolithic VMEM
    gate, round-5) must reproduce the XLA twin bit-exactly, including
    the emitted cv/vis_tile maintenance structure."""
    from crdt_benches_tpu.ops.apply_range_fused import (
        range_fused_blocked,
        range_fused_xla,
    )

    args = [jnp.asarray(x) for x in _random_blocked_inputs(seed)]
    want = range_fused_xla(*args, nbits=4, dsh=14)
    got = range_fused_blocked(
        *args, nbits=4, dsh=14, block_tiles=8, interpret=True
    )
    for w, g in zip(want, got):
        np.testing.assert_array_equal(
            np.asarray(w).astype(np.float32),
            np.asarray(g).astype(np.float32),
        )


@pytest.mark.slow
def test_range_engine_above_old_capacity_ceiling(range_apply):
    """Capacity > 2^20 (the retired r4 ValueError bound): the widened
    ddelta levels must keep the replay byte-identical (both engines)."""
    from crdt_benches_tpu.traces.loader import TestData, TestPatch, TestTxn

    rng = np.random.default_rng(23)
    content = ""
    txns = []
    total = 0
    while total < 1_100_000:
        pos = int(rng.integers(0, len(content) + 1))
        n = int(rng.integers(2000, 12000))
        ins = "".join(
            chr(97 + int(c)) for c in rng.integers(0, 26, n)
        )
        txns.append([[pos, 0, ins]])
        content = content[:pos] + ins + content[pos:]
        total += n
    trace = TestData(
        "", content,
        [TestTxn("", [TestPatch(*p) for p in t]) for t in txns],
    )
    rt = tensorize_ranges(trace, batch=32)
    assert rt.capacity > 1 << 20
    eng = RangeReplayEngine(rt, n_replicas=1, interpret=True, chunk=4)
    assert eng.decode(eng.run()) == content
