"""Durability v2: WAL segmentation + GC, delta snapshot chains, the
measured recovery-time objective, and the crash-during-compaction /
delta-corruption chaos kinds.

The invariants under test:

- the on-disk WAL stays O(ops since the last committed snapshot): a
  segment fully covered by a barrier is deleted, crash-safely;
- a delta barrier persists exactly the rows dirty since the previous
  barrier, CRC-chained to its base; recovery composes root -> deltas
  and falls back DOWN the chain on any broken link — always ending
  byte-identical to an uninterrupted run and to the oracle;
- ``read_journal`` drops torn tails, empty trailing segments, and
  GC'd-round resurrections cleanly, never propagating them.
"""

import json
import os
import shutil
import zlib

import numpy as np
import pytest

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.faults import FaultEvent, FaultInjector, FaultPlan
from crdt_benches_tpu.serve.journal import (
    GC_MANIFEST,
    OpJournal,
    chain_members,
    finish_torn_gc,
    list_snapshots,
    probe_recovery,
    read_journal,
    recover_fleet,
    sweep_staging,
    wal_segments,
)
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import FleetScheduler, prepare_streams
from crdt_benches_tpu.serve.workload import build_fleet

TINY_BANDS = {
    "synth-small": ("synth", (10, 60)),
    "synth-medium": ("synth", (150, 360)),
}
TINY_MIX = {"synth-small": 0.6, "synth-medium": 0.4}


def _fleet(tmp_path, sub, n=10, seed=7, **kw):
    sessions = build_fleet(
        n, mix=TINY_MIX, seed=seed, arrival_span=3, bands=TINY_BANDS
    )
    pool = DocPool(classes=(256, 1024), slots=(6, 3),
                   spool_dir=str(tmp_path / f"spool_{sub}"))
    streams = prepare_streams(sessions, pool, batch=16, batch_chars=64)
    sched = FleetScheduler(pool, streams, batch=16, macro_k=4,
                           batch_chars=64, **kw)
    return sessions, pool, streams, sched


def _oracle(sessions):
    return {s.doc_id: replay_trace(s.trace) for s in sessions}


# ---------------------------------------------------------------------------
# WAL segmentation + read_journal edge cases
# ---------------------------------------------------------------------------


def test_wal_rolls_into_segments_and_reads_in_order(tmp_path):
    jd = str(tmp_path / "j")
    j = OpJournal(jd, segment_bytes=256)
    for r in range(20):
        j.round_record(r, {256: [[1, r * 4, r * 4 + 4]]})
        j.maybe_roll()  # the barrier-time roll point
    j.close()
    segs = wal_segments(jd)
    assert len(segs) >= 2  # tiny threshold: the active file rolled
    assert segs == sorted(segs)
    recs, dropped = read_journal(jd)
    assert dropped == 0
    assert [rec["r"] for rec in recs] == list(range(20))
    # a roll below the threshold is a no-op
    j3 = OpJournal(jd, segment_bytes=1 << 20)
    assert j3.maybe_roll() is False
    j3.close()
    # reopening continues the sequence instead of reusing a seal
    j2 = OpJournal(jd, segment_bytes=256)
    for r in range(20, 28):
        j2.round_record(r, {256: [[1, r, r + 1]]})
        j2.maybe_roll()
    j2.close()
    recs2, _ = read_journal(jd)
    assert [rec["r"] for rec in recs2] == list(range(28))


def test_torn_tail_at_segment_boundary_drops_cleanly(tmp_path):
    """A partial CRC line right at a segment boundary (the active file
    torn just after a roll) drops cleanly — the sealed prefix
    survives, nothing after the tear is trusted."""
    jd = str(tmp_path / "j")
    j = OpJournal(jd, segment_bytes=200)
    for r in range(10):
        j.round_record(r, {256: [[1, r, r + 1]]})
        j.maybe_roll()
    j.close()
    assert wal_segments(jd)
    # tear the ACTIVE file's first line (boundary position: byte 0 of
    # the post-roll file)
    with open(os.path.join(jd, "journal.log"), "r+", encoding="utf-8") as f:
        lines = f.readlines()
    n_active = len(lines)
    with open(os.path.join(jd, "journal.log"), "w", encoding="utf-8") as f:
        f.write('deadbeef {"t":"round"')  # no newline, bad crc
    recs, dropped = read_journal(jd)
    assert dropped == 1
    assert all("r" in r for r in recs)
    # reopening truncates the torn tail so appends stay visible
    j2 = OpJournal(jd, segment_bytes=200)
    j2.round_record(99, {256: [[1, 0, 1]]})
    j2.close()
    recs2, dropped2 = read_journal(jd)
    assert dropped2 == 0 and recs2[-1]["r"] == 99
    assert n_active >= 1  # the tear really replaced live records


def test_empty_trailing_segment_and_fsync_off_crash(tmp_path):
    """An empty active file after a roll reads as zero records; an
    fsync-off crash (arbitrary byte truncation mid-record) drops only
    the damaged suffix."""
    jd = str(tmp_path / "j")
    j = OpJournal(jd, segment_bytes=120)
    for r in range(8):
        j.round_record(r, {256: [[2, r, r + 1]]})
        j.maybe_roll()
    j.close()
    n_active = len(open(os.path.join(jd, "journal.log")).readlines())
    # empty trailing active file (crash right after a roll)
    open(os.path.join(jd, "journal.log"), "w").close()
    recs, dropped = read_journal(jd)
    assert dropped == 0 and len(recs) == 8 - n_active
    assert [r["r"] for r in recs] == list(range(8 - n_active))
    # fsync-off crash: the LAST file with data loses an arbitrary
    # suffix mid-record
    last_seg = os.path.join(jd, wal_segments(jd)[-1])
    size = os.path.getsize(last_seg)
    with open(last_seg, "r+b") as f:
        f.truncate(size - 7)
    recs2, dropped2 = read_journal(jd)
    assert dropped2 >= 1
    assert len(recs2) < 8 - n_active
    for rec in recs2:  # every surviving record is fully intact
        assert rec["t"] == "round" and "lanes" in rec


def test_gc_deletes_covered_segments_and_survives_crash(tmp_path):
    """compact() deletes sealed segments whose records are all below
    the covering barrier round — two-phase: a pass killed between the
    GC-manifest write and the unlinks is completed on the next open."""
    jd = str(tmp_path / "j")
    j = OpJournal(jd, segment_bytes=150)
    for r in range(12):
        j.round_record(r, {256: [[1, r, r + 1]]})
        j.maybe_roll()
    n_before = len(wal_segments(jd))
    assert n_before >= 2
    # crash mid-GC: manifest written, unlink skipped
    info = j.compact(12, crash_hook=lambda: True)
    assert info["crashed"] and os.path.exists(os.path.join(jd, GC_MANIFEST))
    assert len(wal_segments(jd)) == n_before  # nothing unlinked yet
    j.close()
    # reopening completes the torn pass
    j2 = OpJournal(jd, segment_bytes=150)
    assert j2.torn_gc_completed >= 1
    assert not os.path.exists(os.path.join(jd, GC_MANIFEST))
    assert len(wal_segments(jd)) < n_before
    # a second pass with nothing covered is a no-op
    info2 = j2.compact(0)
    assert info2["deleted"] == 0 and not info2["crashed"]
    j2.close()
    # recovery-side completion works standalone too
    assert finish_torn_gc(jd) == 0


def test_resurrected_gcd_segment_is_ignored_by_recovery(tmp_path):
    """A CRC-valid record from a GC'd round (a segment that escaped
    deletion — torn GC, backup restore) must not double-apply: the
    recovery redo rule skips records below the snapshot round."""
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", journal=OpJournal(str(tmp_path / "j"),
                                         segment_bytes=200),
        snapshot_every=2, snapshot_full_every=2,
    )
    jd = str(tmp_path / "j")
    sched.run(max_rounds=4)
    # copy a sealed segment aside, run to completion (GC eats it), put
    # it back — the resurrection
    segs = wal_segments(jd)
    saved = None
    if segs:
        saved = os.path.join(str(tmp_path), "resurrect.log")
        shutil.copy2(os.path.join(jd, segs[0]), saved)
    sched.run()
    assert sched.done
    want = _oracle(sessions)
    if saved is not None:
        shutil.copy2(saved, os.path.join(jd, segs[0]))
    pool_b = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "spool_b"))
    streams_b = prepare_streams(sessions, pool_b, batch=16,
                                batch_chars=64)
    rep = recover_fleet(pool_b, streams_b, jd)
    assert rep.snapshot_round >= 0
    FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                   batch_chars=64,
                   start_round=rep.resume_round).run()
    for s in sessions:
        assert pool_b.decode(s.doc_id) == want[s.doc_id]


# ---------------------------------------------------------------------------
# delta snapshot chains
# ---------------------------------------------------------------------------


def test_delta_captures_only_dirty_rows(tmp_path):
    """A delta barrier persists exactly the rows touched since the
    previous barrier — and is byte-smaller than the full it chains to
    on a mostly-idle fleet."""
    jd = str(tmp_path / "j")
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", journal=OpJournal(jd), snapshot_every=1,
        snapshot_full_every=4,
    )
    sched.run(max_rounds=3)
    snaps = list_snapshots(jd)
    manifests = {
        s: json.load(open(os.path.join(jd, s, "MANIFEST.json")))
        for s in snaps
    }
    kinds = [manifests[s]["kind"] for s in snaps]
    assert kinds[0] == "full" and "delta" in kinds[1:]
    for s in snaps:
        m = manifests[s]
        if m["kind"] != "delta":
            continue
        # chain link verified: base present, CRC matches
        assert chain_members(jd, s)[0] == m["chain"]
        # delta rows are a subset of the class's rows, with shapes
        for cls, rows in m["delta_rows"].items():
            R, C = m["class_shapes"][cls]
            assert all(0 <= r < R for r in rows)
            assert C == int(cls)  # C is the class capacity
        # member bytes strictly below the chain root's
        root = m["chain"]
        d_bytes = sum(
            os.path.getsize(os.path.join(jd, s, f))
            for f in os.listdir(os.path.join(jd, s))
            if f.endswith(".npz") and f.startswith("delta_")
        )
        r_bytes = sum(
            os.path.getsize(os.path.join(jd, root, f))
            for f in os.listdir(os.path.join(jd, root))
            if f.endswith(".npz") and f.startswith("class_")
        )
        if d_bytes and r_bytes:
            assert d_bytes < r_bytes


def test_chain_recovery_parity_with_deltas(tmp_path):
    """THE durability v2 recovery gate: kill a fleet mid-drain under
    delta barriers + tiny WAL segments + GC, recover into a FRESH pool
    by composing the chain, resume — byte-identical to an uninterrupted
    run and to the oracle."""
    sessions = build_fleet(
        10, mix=TINY_MIX, seed=3, arrival_span=3, bands=TINY_BANDS
    )
    pool_a = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sa"))
    streams_a = prepare_streams(sessions, pool_a, batch=16, batch_chars=64)
    FleetScheduler(pool_a, streams_a, batch=16, macro_k=4,
                   batch_chars=64).run()
    want = {s.doc_id: pool_a.decode(s.doc_id) for s in sessions}

    jd = str(tmp_path / "j")
    pool_b = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sb"))
    streams_b = prepare_streams(sessions, pool_b, batch=16, batch_chars=64)
    sb = FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                        batch_chars=64,
                        journal=OpJournal(jd, segment_bytes=300),
                        snapshot_every=1, snapshot_full_every=3)
    sb.run(max_rounds=5)
    assert not sb.done
    assert sb.stats.snapshots_delta >= 1  # deltas actually exercised
    del pool_b, streams_b, sb

    pool_c = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sc"))
    streams_c = prepare_streams(sessions, pool_c, batch=16, batch_chars=64)
    rep = recover_fleet(pool_c, streams_c, jd)
    assert rep.snapshot_round >= 0
    assert rep.chain_depth >= 1
    FleetScheduler(pool_c, streams_c, batch=16, macro_k=4,
                   batch_chars=64,
                   start_round=rep.resume_round).run()
    for s in sessions:
        assert pool_c.decode(s.doc_id) == want[s.doc_id]
        assert want[s.doc_id] == replay_trace(s.trace)


def test_chain_fallback_on_corrupt_delta_and_root(tmp_path):
    """Damage at each chain level falls back exactly one level: a
    corrupt delta member -> the link below it; a corrupt full root ->
    an older chain or cold start.  Parity holds either way."""
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a",
        journal=OpJournal(str(tmp_path / "j"), segment_bytes=400),
        snapshot_every=1, snapshot_full_every=4, snapshot_keep=2,
    )
    jd = str(tmp_path / "j")
    # 4 barriers under full_every=4: full, delta, delta, delta — the
    # chain TIP is a delta, so corrupting the newest delta forces the
    # candidate walk to fall back at least one link
    sched.run(max_rounds=4)
    want = _oracle(sessions)
    snaps = list_snapshots(jd)
    manifests = {
        s: json.load(open(os.path.join(jd, s, "MANIFEST.json")))
        for s in snaps
    }
    deltas = [s for s in snaps if manifests[s]["kind"] == "delta"]
    assert deltas
    victim = deltas[-1]
    members = [
        f for f in os.listdir(os.path.join(jd, victim))
        if f.startswith("delta_")
    ]
    target = os.path.join(
        jd, victim, members[0] if members else "MANIFEST.json"
    )
    with open(target, "r+b") as f:
        f.seek(max(0, os.path.getsize(target) // 2))
        f.write(b"\xff" * 12)

    pool_c = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sc"))
    streams_c = prepare_streams(sessions, pool_c, batch=16,
                                batch_chars=64)
    rep = recover_fleet(pool_c, streams_c, jd)
    assert rep.chain_fallbacks >= 1  # fell back DOWN the chain
    FleetScheduler(pool_c, streams_c, batch=16, macro_k=4,
                   batch_chars=64, start_round=rep.resume_round).run()
    for s in sessions:
        assert pool_c.decode(s.doc_id) == want[s.doc_id]

    # now kill every chain root: recovery degrades to cold start and
    # STILL converges (streams are deterministic)
    for s in list_snapshots(jd):
        m = json.load(open(os.path.join(jd, s, "MANIFEST.json")))
        if m["kind"] == "full":
            for f in os.listdir(os.path.join(jd, s)):
                if f.startswith("class_"):
                    p = os.path.join(jd, s, f)
                    with open(p, "r+b") as fh:
                        fh.seek(os.path.getsize(p) // 2)
                        fh.write(b"\xff" * 12)
    pool_d = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sd"))
    streams_d = prepare_streams(sessions, pool_d, batch=16,
                                batch_chars=64)
    rep_d = recover_fleet(pool_d, streams_d, jd)
    assert rep_d.chain_fallbacks >= 1
    FleetScheduler(pool_d, streams_d, batch=16, macro_k=4,
                   batch_chars=64, start_round=rep_d.resume_round).run()
    for s in sessions:
        assert pool_d.decode(s.doc_id) == want[s.doc_id]


def test_staging_dir_with_valid_manifest_is_never_a_candidate(tmp_path):
    """The crash-window satellite: a staging directory abandoned before
    the atomic rename — even one containing a fully valid-looking
    manifest — is never listed, never recovered from, and is cleaned
    up by the sweep."""
    jd = str(tmp_path / "j")
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", journal=OpJournal(jd), snapshot_every=2,
    )
    sched.run()
    want = _oracle(sessions)
    snaps = list_snapshots(jd)
    assert snaps
    # plant an abandoned staging dir NEWER than every committed
    # snapshot, with a valid-looking manifest copied from a real one
    fake = os.path.join(jd, "snap_99999990.tmp")
    shutil.copytree(os.path.join(jd, snaps[-1]), fake)
    m = json.load(open(os.path.join(fake, "MANIFEST.json")))
    m["round"] = 99999990  # poison: using it would skip every redo op
    json.dump(m, open(os.path.join(fake, "MANIFEST.json"), "w"))
    assert "snap_99999990.tmp" not in list_snapshots(jd)

    pool_b = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sb"))
    streams_b = prepare_streams(sessions, pool_b, batch=16,
                                batch_chars=64)
    rep = recover_fleet(pool_b, streams_b, jd)
    assert rep.snapshot_round < 99999990
    assert rep.staging_removed >= 1
    assert not os.path.exists(fake)  # swept, not just skipped
    FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                   batch_chars=64, start_round=rep.resume_round).run()
    for s in sessions:
        assert pool_b.decode(s.doc_id) == want[s.doc_id]
    # the standalone sweep is idempotent
    assert sweep_staging(jd) == []


def test_dirty_tracking_marks_exactly_touched_rows(tmp_path):
    """Unit-level dirty contract: installs and op-carrying dispatch
    rows mark; PAD-only lanes don't; take_dirty consumes."""
    pool = DocPool(classes=(256,), slots=(4,),
                   spool_dir=str(tmp_path / "s"))
    sessions = build_fleet(2, mix={"synth-small": 1.0}, seed=1,
                           arrival_span=1,
                           bands={"synth-small": ("synth", (10, 20))})
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    for s in sessions:
        pool.admit(s.doc_id, 16)
    assert pool.dirty_rows(256) == {0, 1}  # installs mark
    assert pool.take_dirty() == {256: [0, 1]}
    assert pool.take_dirty() == {}  # consumed
    # an all-PAD macro dispatch marks nothing
    from crdt_benches_tpu.traces.tensorize import PAD

    K, Rt, B = 2, 4, 8
    dts = pool.op_dtypes
    kind = np.full((K, Rt, B), PAD, dts[0])
    pos = np.zeros((K, Rt, B), dts[1])
    rlen = np.zeros((K, Rt, B), dts[2])
    slot0 = np.zeros((K, Rt, B), dts[3])
    pool.macro_step(256, kind, pos, rlen, slot0, nbits=6)
    assert pool.take_dirty() == {}
    # ops in one row mark exactly that row
    st = streams[sessions[0].doc_id]
    take = min(4, st.n_total)
    kind[0, 1, :take] = st.kind[:take]
    pos[0, 1, :take] = st.pos[:take]
    rlen[0, 1, :take] = st.rlen[:take]
    slot0[0, 1, :take] = st.slot0[:take]
    pool.macro_step(256, kind, pos, rlen, slot0, nbits=6)
    assert pool.take_dirty() == {256: [1]}


# ---------------------------------------------------------------------------
# chaos: crash_compact + delta_corrupt
# ---------------------------------------------------------------------------


def test_crash_compact_fires_and_recovers(tmp_path):
    """The GC pass is killed between its manifest write and the
    unlinks; the torn pass must complete (next barrier or finalize)
    and the drain stays oracle-green."""
    jd = str(tmp_path / "j")
    plan = FaultPlan([FaultEvent(kind="crash_compact", round=2)], seed=3)
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", faults=FaultInjector(plan),
        journal=OpJournal(jd, segment_bytes=200),
        snapshot_every=1, snapshot_full_every=2,
    )
    sched.run()
    assert sched.done
    (ev,) = plan.events
    assert ev.fired and ev.recovered, ev.to_dict()
    assert not os.path.exists(os.path.join(jd, GC_MANIFEST))
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace)


def test_delta_corrupt_fires_and_recovery_falls_back(tmp_path):
    """A mid-chain delta member is bit-flipped; the finalizer's
    recovery probe must materialize a usable snapshot (chain fallback
    or re-root) and a real recovery must byte-verify green."""
    jd = str(tmp_path / "j")
    plan = FaultPlan([FaultEvent(kind="delta_corrupt", round=3)], seed=5)
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", faults=FaultInjector(plan),
        journal=OpJournal(jd, segment_bytes=400),
        snapshot_every=1, snapshot_full_every=4,
    )
    sched.run()
    assert sched.done
    (ev,) = plan.events
    assert ev.fired, ev.to_dict()
    assert ev.detail.get("member"), ev.detail
    assert ev.recovered, ev.to_dict()
    used, _fallbacks = probe_recovery(jd)
    assert used is not None
    # the real thing: recover a fresh fleet over the damaged chain
    want = _oracle(sessions)
    pool_b = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sb"))
    streams_b = prepare_streams(sessions, pool_b, batch=16,
                                batch_chars=64)
    rep = recover_fleet(pool_b, streams_b, jd)
    FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                   batch_chars=64, start_round=rep.resume_round).run()
    for s in sessions:
        assert pool_b.decode(s.doc_id) == want[s.doc_id]


def test_journal_kinds_rejected_without_preconditions(tmp_path):
    """Durability chaos kinds whose injection points are unreachable
    must be rejected up front — a loud config error, never a
    drain-end not_fired."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    common = dict(mix=TINY_MIX, n_docs=4, bands=TINY_BANDS,
                  results_dir=str(tmp_path), log=lambda *_: None)
    with pytest.raises(ValueError, match="serve-journal"):
        run_serve_bench(faults="crash_compact=1", **common)
    with pytest.raises(ValueError, match="snapshot-every"):
        run_serve_bench(faults="crash_compact=1",
                        journal_dir=str(tmp_path / "j1"),
                        snapshot_every=0, **common)
    with pytest.raises(ValueError, match="full-every"):
        run_serve_bench(faults="delta_corrupt=1",
                        journal_dir=str(tmp_path / "j2"),
                        snapshot_every=2, snapshot_full_every=1,
                        **common)
    with pytest.raises(ValueError, match="recovery leg"):
        run_serve_bench(longhaul=2, **common)


def test_parseable_garbage_manifest_falls_back(tmp_path):
    """A bit-flip that leaves the tip manifest PARSEABLE but garbled
    (a resident row index past the bucket) must still degrade to the
    next candidate — recovery never crashes on designed-recoverable
    corruption."""
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", journal=OpJournal(str(tmp_path / "j")),
        snapshot_every=1, snapshot_full_every=2,
    )
    jd = str(tmp_path / "j")
    sched.run(max_rounds=4)
    want = _oracle(sessions)
    snaps = list_snapshots(jd)
    mpath = os.path.join(jd, snaps[-1], "MANIFEST.json")
    m = json.load(open(mpath))
    for key in list(m["resident"]):
        m["resident"][key][1] = 9999  # valid JSON, impossible row
    json.dump(m, open(mpath, "w"), separators=(",", ":"))
    pool_b = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sb"))
    streams_b = prepare_streams(sessions, pool_b, batch=16,
                                batch_chars=64)
    rep = recover_fleet(pool_b, streams_b, jd)
    assert rep.chain_fallbacks >= 1
    FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                   batch_chars=64, start_round=rep.resume_round).run()
    for s in sessions:
        assert pool_b.decode(s.doc_id) == want[s.doc_id]


def test_gc_floor_preserves_decisions_for_fallback(tmp_path):
    """A journaled shed decision must survive WAL GC for as long as
    ANY retained snapshot predates it: chain fallback landing below
    the decision's round re-applies it from the WAL — deleting the
    segment on the newest barrier's say-so would silently un-shed the
    doc on fallback (the GC-floor regression)."""
    jd = str(tmp_path / "j")
    plan = FaultPlan([FaultEvent(kind="queue_overflow", round=8)],
                     seed=1)
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", faults=FaultInjector(plan),
        journal=OpJournal(jd, segment_bytes=200),
        snapshot_every=1, snapshot_full_every=2, snapshot_keep=0,
        queue_cap=8, overflow_policy="shed",
    )
    sched.run()
    assert sched.done
    (ev,) = plan.events
    assert ev.fired and ev.detail.get("shed", 0) > 0
    shed_round = ev.fired_round
    want = {s.doc_id: pool.decode(s.doc_id) for s in sessions}
    lossy_docs = sorted(d for d, st in streams.items() if st.lossy)
    assert lossy_docs
    # corrupt every snapshot committed AFTER the decision: recovery
    # must land below it and recover the decision from the WAL alone
    for snap in list_snapshots(jd):
        if int(snap[len("snap_"):]) > shed_round:
            mp = os.path.join(jd, snap, "MANIFEST.json")
            with open(mp, "r+b") as f:
                f.seek(max(0, os.path.getsize(mp) // 2))
                f.write(b"\xff" * 8)
    pool_b = DocPool(classes=(256, 1024), slots=(6, 3),
                     spool_dir=str(tmp_path / "sb"))
    streams_b = prepare_streams(sessions, pool_b, batch=16,
                                batch_chars=64)
    rep = recover_fleet(pool_b, streams_b, jd)
    assert rep.snapshot_round <= shed_round
    assert rep.shed_ops > 0  # the decision came back from the WAL
    assert sorted(
        d for d, st in streams_b.items() if st.lossy
    ) == lossy_docs
    FleetScheduler(pool_b, streams_b, batch=16, macro_k=4,
                   batch_chars=64, queue_cap=8,
                   overflow_policy="shed",
                   start_round=rep.resume_round).run()
    for s in sessions:  # INCLUDING lossy docs: the truncation must
        # reproduce byte-exactly, not just the clean docs
        assert pool_b.decode(s.doc_id) == want[s.doc_id], s.doc_id


def test_snapshot_keep_zero_never_prunes(tmp_path):
    """keep <= 0 is the historical keep-all contract: every barrier's
    snapshot survives."""
    jd = str(tmp_path / "j")
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", journal=OpJournal(jd), snapshot_every=1,
        snapshot_keep=0, snapshot_full_every=2,
    )
    sched.run(max_rounds=5)
    assert sched.stats.snapshots >= 4
    assert len(list_snapshots(jd)) == sched.stats.snapshots


# ---------------------------------------------------------------------------
# the serve/longhaul family + measured RTO (bench level)
# ---------------------------------------------------------------------------


def test_longhaul_bench_crash_recovery_and_artifact(tmp_path):
    """End to end at smoke scale: a longhaul drain with an injected
    crash, durability chaos, tiny WAL segments and delta barriers —
    the recovery leg restores, resumes, byte-verifies, and the
    artifact carries the recovery / durability blocks bench_compare
    gates on."""
    from crdt_benches_tpu.serve.bench import run_serve_bench

    r, info = run_serve_bench(
        mix=TINY_MIX, n_docs=8, bands=TINY_BANDS, seed=5,
        batch=16, batch_chars=64, macro_k=4,
        classes=(256, 1024), slots=(6, 3),
        arrival_span=2, verify_sample=6,
        journal_dir=str(tmp_path / "j"),
        snapshot_every=2, snapshot_full_every=2,
        wal_segment_bytes=128,
        longhaul=4, crash_after=5,
        faults="crash_compact@2=1,delta_corrupt@2=1",
        results_dir=str(tmp_path / "res"),
        save_name="longhaul_test",
        log=lambda *_: None,
    )
    assert info["verify_ok"], "recovered fleet failed the oracle gate"
    assert info["faults_ok"], r.extra["faults"]
    assert r.bench_id.startswith("serve/longhaul/"), r.bench_id
    rec = r.extra["recovery"]
    assert rec is not None and rec["verify_ok"]
    assert rec["recover_ms"] > 0 and rec["redo_ops"] > 0
    assert rec["chain_depth"] >= 1
    j = r.extra["journal"]
    assert j["segments_sealed"] >= 1
    assert j["disk_bytes"] > 0
    assert j["snapshots_delta"] >= 1
    # durability gauges landed in the run's registry
    gauges = r.extra["metrics"]["gauges"]
    assert "serve.journal.wal_segments" in gauges
    assert "serve.journal.bytes_since_snapshot" in gauges
    assert "serve.durability.chain_depth" in gauges
    assert "serve.durability.last_compaction_round" in gauges


def test_durability_status_fields_and_flight_events(tmp_path):
    """The /status.json durability block and the flight recorder's
    snapshot/compaction event ring."""
    from crdt_benches_tpu.obs.flight import FlightRecorder, validate_flight
    from crdt_benches_tpu.obs.timeseries import ServeTelemetry

    flight = FlightRecorder(str(tmp_path / "flight.json"), ring=16)
    telemetry = ServeTelemetry(flight=flight)
    jd = str(tmp_path / "j")
    sessions, pool, streams, sched = _fleet(
        tmp_path, "a", journal=OpJournal(jd, segment_bytes=300),
        snapshot_every=1, snapshot_full_every=2, telemetry=telemetry,
    )
    sched.run()
    st = sched.status_fields()
    d = st["durability"]
    assert d["wal_segments"] >= 1
    assert d["snapshots_full"] >= 1
    assert "bytes_since_snapshot" in d and "chain_depth" in d
    assert "last_compaction_round" in d
    kinds = {e["kind"] for e in flight.events}
    assert "snapshot" in kinds
    assert flight.events_seen >= sched.stats.snapshots
    # events ride the dump and the validator accepts them
    flight.trigger("test", status=st)
    dump = json.load(open(str(tmp_path / "flight.json")))
    assert dump["events"] and validate_flight(dump) == []
