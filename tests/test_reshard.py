"""Elastic fleet reconfiguration: live shard-map changes, crash-safe
doc migration, the ``reshard_crash`` chaos kind, drained-doc footprint
GC, and the bench_compare reshard gate.

Ground truth is double-ended: the oracle (every doc byte-identical
after a live reshard, crash or not) and the shard-partition invariant
(:func:`check_shard_partition` — every doc on exactly one non-retired
shard at every observation point)."""

import importlib.util
import json
import os
import sys
from pathlib import Path

import pytest

from crdt_benches_tpu.oracle.text_oracle import replay_trace
from crdt_benches_tpu.serve.faults import FaultEvent, FaultInjector, FaultPlan
from crdt_benches_tpu.serve.journal import OpJournal, read_journal
from crdt_benches_tpu.serve.pool import SPOOL_GC_MANIFEST, DocPool
from crdt_benches_tpu.serve.reshard import (
    RESHARD_MANIFEST,
    ReshardCoordinator,
    check_shard_partition,
    commit_manifest,
    parse_reshard_spec,
    read_manifest,
    recover_torn_reshard,
    retire_manifest,
    scan_reshard_records,
)
from crdt_benches_tpu.serve.scheduler import FleetScheduler, prepare_streams
from crdt_benches_tpu.serve.workload import build_fleet

REPO = Path(__file__).resolve().parent.parent

TINY_BANDS = {"synth-small": ("synth", (40, 120))}
TINY_MIX = {"synth-small": 1.0}


def _fleet(tmp_path, n=5, seed=11, classes=(128,), slots=(4,), shards=2,
           reshard_spec=None, faults=None, journal=True, **kw):
    """A small sharded fleet, oversubscribed enough that the draining
    shard actually hosts docs when the reshard begins."""
    sessions = build_fleet(
        n, mix=TINY_MIX, seed=seed, arrival_span=2, bands=TINY_BANDS
    )
    pool = DocPool(classes=classes, slots=slots,
                   spool_dir=str(tmp_path / "spool"), shards=shards)
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=32)
    jr = OpJournal(str(tmp_path / "journal")) if journal else None
    coord = None
    if reshard_spec is not None:
        coord = ReshardCoordinator(
            pool, jr, parse_reshard_spec(reshard_spec), faults=faults,
        )
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=32, journal=jr, reshard=coord,
                           faults=faults, **kw)
    return sessions, pool, streams, sched, coord


def _assert_oracle_parity(sessions, pool):
    for s in sessions:
        assert pool.decode(s.doc_id) == replay_trace(s.trace), (
            f"doc {s.doc_id} diverged"
        )


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------


def test_parse_reshard_spec_matrix():
    p = parse_reshard_spec("shrink:8:6@12,batch=4")
    assert (p.kind, p.from_sh, p.to_sh) == ("shrink", 8, 6)
    assert p.shards == (6, 7)
    assert p.at_round == 12 and p.batch == 4 and p.imbalance is None
    assert p.n_shards == 8 and p.initial_live == 8

    g = parse_reshard_spec("grow:2:4")
    assert (g.kind, g.from_sh, g.to_sh, g.shards) == ("grow", 2, 4, (2, 3))
    assert g.n_shards == 4 and g.initial_live == 2
    assert g.at_round is None and g.batch == 8

    d = parse_reshard_spec("drain:1@3,of=2,batch=1")
    assert (d.kind, d.from_sh, d.to_sh, d.shards) == ("drain", 2, 1, (1,))
    assert d.at_round == 3 and d.batch == 1

    # drain without of=N: physical count resolved against the pool/mesh
    d0 = parse_reshard_spec("drain:3")
    assert d0.shards == (3,) and d0.from_sh == 0 and d0.to_sh == 0

    i = parse_reshard_spec("shrink:2:1,imbalance=0.5")
    assert i.imbalance == 0.5 and i.at_round is None


@pytest.mark.parametrize("bad,msg", [
    ("shrink:2:2", "FROM > TO"),
    ("shrink:1:0", "FROM > TO"),
    ("grow:4:4", "TO > FROM"),
    ("grow:0:2", "TO > FROM"),
    ("drain:-1", "negative shard"),
    ("drain:1,of=1", "N >= 2"),
    ("drain:5,of=4", "0 <= SHARD < N"),
    ("shrink:2:1,of=2", "only applies to drain"),
    ("shrink:2:1,zap=3", "unknown option"),
    ("shrink:2:1,batch", "key=value"),
    ("explode:2:1", "unknown reshard kind"),
    ("shrink:2", "KIND:FROM:TO"),
    ("drain:1:2", "drain:SHARD"),
])
def test_parse_reshard_spec_rejects(bad, msg):
    with pytest.raises(ValueError, match=msg):
        parse_reshard_spec(bad)


# ---------------------------------------------------------------------------
# manifest: the durable commit point
# ---------------------------------------------------------------------------


def test_manifest_round_trip_and_retire(tmp_path):
    jd = str(tmp_path)
    m = {"id": 3, "kind": "shrink", "shards": [6, 7], "round": 12,
         "docs": 40}
    path = commit_manifest(jd, m)
    assert os.path.basename(path) == RESHARD_MANIFEST
    assert not os.path.exists(path + ".tmp")  # staged then installed
    assert read_manifest(jd) == m
    assert retire_manifest(jd) is True
    assert read_manifest(jd) is None
    assert retire_manifest(jd) is False  # idempotent


def test_manifest_garbage_reads_as_absent(tmp_path):
    jd = str(tmp_path)
    p = os.path.join(jd, RESHARD_MANIFEST)
    with open(p, "w") as f:
        f.write("{not json")
    assert read_manifest(jd) is None
    with open(p, "w") as f:
        json.dump({"id": "x", "kind": "shrink"}, f)  # missing fields
    assert read_manifest(jd) is None
    # garbage is still ours to retire (read-witnessed then unlinked)
    assert retire_manifest(jd) is True
    assert not os.path.exists(p)


def test_retire_discards_staged_tmp(tmp_path):
    jd = str(tmp_path)
    tmp = os.path.join(jd, RESHARD_MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        f.write("staged, never committed")
    assert retire_manifest(jd) is False  # no committed manifest
    assert not os.path.exists(tmp)


# ---------------------------------------------------------------------------
# live-aware allocation (the pool side the coordinator leans on)
# ---------------------------------------------------------------------------


def test_draining_shard_refuses_allocation(tmp_path):
    pool = DocPool(classes=(128,), slots=(4,),
                   spool_dir=str(tmp_path / "spool"), shards=2)
    b = pool.buckets[128]
    assert b.n_free_live == 4
    pool.drain_shard(1)
    assert b.n_free_live == 2
    assert b.usable_rows == 2  # nothing resident on the draining half
    # every allocation now lands on shard 0 (rows 0..Rg-1)
    assert b.alloc_row() // b.Rg == 0
    assert b.alloc_row() // b.Rg == 0
    with pytest.raises(RuntimeError, match="no free row|no live shard"):
        b.alloc_row()
    pool.revive_shard(1)
    assert b.alloc_row() // b.Rg == 1


def test_retire_requires_empty_shard(tmp_path):
    sessions, pool, streams, sched, _ = _fleet(tmp_path, n=2, slots=(4,))
    sched.run(max_rounds=2)
    victim = next(s for s in range(2) if pool.docs_on_shard(s))
    pool.drain_shard(victim)
    with pytest.raises(RuntimeError, match="cannot retire"):
        pool.retire_shard(victim)


def test_coordinator_requires_journal(tmp_path):
    pool = DocPool(classes=(128,), slots=(4,),
                   spool_dir=str(tmp_path / "spool"), shards=2)
    with pytest.raises(ValueError, match="journal"):
        ReshardCoordinator(pool, None, parse_reshard_spec("shrink:2:1"))


def test_coordinator_validates_physical_shards(tmp_path):
    pool = DocPool(classes=(128,), slots=(4,),
                   spool_dir=str(tmp_path / "spool"), shards=2)
    jr = OpJournal(str(tmp_path / "journal"))
    try:
        with pytest.raises(ValueError, match="physical shards"):
            ReshardCoordinator(pool, jr,
                               parse_reshard_spec("shrink:4:2"))
        with pytest.raises(ValueError, match="pool has 2 shards"):
            ReshardCoordinator(pool, jr, parse_reshard_spec("drain:5"))
        with pytest.raises(ValueError, match="of=4"):
            ReshardCoordinator(pool, jr,
                               parse_reshard_spec("drain:1,of=4"))
    finally:
        jr.close()


# ---------------------------------------------------------------------------
# live reshard end-to-end (serving never stops)
# ---------------------------------------------------------------------------


def test_live_shrink_drains_verify_green(tmp_path):
    """shrink:2:1 mid-drain: shard 1's residents migrate (row move or
    demotion), the shard retires, the journal carries the full
    begin/move/commit lifecycle, and every doc matches the oracle."""
    sessions, pool, streams, sched, coord = _fleet(
        tmp_path, n=5, reshard_spec="shrink:2:1@2,batch=2",
    )
    sched.run()
    assert sched.done
    assert coord.state == "done"
    assert pool.live_shard_count == 1
    assert pool.shard_state == ["live", "retired"]
    assert coord.migrated + coord.evicted > 0  # the move was real work
    assert check_shard_partition(pool) == []
    # the manifest retired with the commit — nothing durable left over
    jd = sched.journal.dir
    assert not os.path.exists(os.path.join(jd, RESHARD_MANIFEST))
    _assert_oracle_parity(sessions, pool)
    sched.journal.close()
    records, _ = read_journal(jd)
    phases = [r["phase"] for r in records if r.get("t") == "reshard"]
    assert phases[0] == "begin" and phases[-1] == "commit"
    assert "move" in phases  # decisions journaled ahead of the boundary
    retired, commits = scan_reshard_records(records)
    assert retired == {1} and commits == 1
    s = coord.summary()
    assert s["kind"] == "shrink" and s["state"] == "done"
    assert s["live_shards"] == 1 and s["resumes"] == 0
    assert s["begin_round"] >= 2 and s["commit_round"] >= s["begin_round"]


def test_live_grow_revives_and_rebalances(tmp_path):
    """grow:1:2 on a 2-physical-shard pool: the target shard is
    pre-drained at construction (docs place on the FROM set), revived
    at begin, and allocation spreads across both shards afterwards."""
    sessions, pool, streams, sched, coord = _fleet(
        tmp_path, n=6, slots=(4,), reshard_spec="grow:1:2@2",
    )
    assert pool.shard_state == ["live", "draining"]  # pre-begin
    sched.run()
    assert sched.done and coord.state == "done"
    assert pool.live_shard_count == 2
    assert check_shard_partition(pool) == []
    _assert_oracle_parity(sessions, pool)
    sched.journal.close()
    records, _ = read_journal(sched.journal.dir)
    retired, commits = scan_reshard_records(records)
    assert retired == set() and commits == 1  # grow commits revive


def test_drain_one_shard_spec(tmp_path):
    """drain:0,of=2 — the single-shard drain form the fscrash harness
    uses: shard 0 retires, shard 1 keeps the whole fleet."""
    sessions, pool, streams, sched, coord = _fleet(
        tmp_path, n=4, reshard_spec="drain:0@2,of=2,batch=1",
    )
    sched.run()
    assert sched.done and coord.state == "done"
    assert pool.shard_state == ["retired", "live"]
    assert check_shard_partition(pool) == []
    _assert_oracle_parity(sessions, pool)


def test_reshard_crash_resumes_from_manifest(tmp_path):
    """The chaos contract: ``reshard_crash`` kills the coordinator
    between its manifest commit and the first per-doc move; the next
    round's tick resumes from the on-disk manifest, the event closes
    recovered, and the drain stays verify-green."""
    plan = FaultPlan([FaultEvent(kind="reshard_crash", round=2)], seed=3)
    sessions, pool, streams, sched, coord = _fleet(
        tmp_path, n=5, reshard_spec="shrink:2:1@2,batch=2",
        faults=FaultInjector(plan),
    )
    sched.run()
    assert sched.done and coord.state == "done"
    (ev,) = plan.events
    assert ev.fired and ev.recovered
    assert ev.detail["stage"] == "post_manifest_pre_moves"
    assert ev.detail["via"] == "coordinator_resume"
    assert coord.resumes >= 1
    assert pool.live_shard_count == 1
    assert check_shard_partition(pool) == []
    _assert_oracle_parity(sessions, pool)
    sched.journal.close()
    records, _ = read_journal(sched.journal.dir)
    phases = [r["phase"] for r in records if r.get("t") == "reshard"]
    assert "resume" in phases and phases[-1] == "commit"


def test_finalize_completes_in_flight_reshard(tmp_path):
    """A reshard still active when the last op drains completes at the
    end-of-drain sweep — a finished drain never leaves a torn
    manifest or a draining shard behind."""
    import numpy as np
    pool = DocPool(classes=(128,), slots=(4,),
                   spool_dir=str(tmp_path / "spool"), shards=2)
    for d in range(4):
        pool.register(d, n_init=4, capacity_need=32,
                      chars=np.arange(4, dtype=np.int32) + 97)
        pool.admit(d, need=8)
    jd = str(tmp_path / "journal")
    jr = OpJournal(jd)
    coord = ReshardCoordinator(pool, jr, parse_reshard_spec("shrink:2:1"))
    # plan=None round: the reshard begins (manifest committed, shard 1
    # draining) but no boundary carries its moves — still in flight
    coord.tick(2, None, imbalance=0.0)
    assert coord.state == "active"
    assert os.path.exists(os.path.join(jd, RESHARD_MANIFEST))
    coord.finalize(3)
    assert coord.state == "done"
    assert pool.live_shard_count == 1
    assert pool.shard_state == ["live", "retired"]
    assert check_shard_partition(pool) == []
    assert not os.path.exists(os.path.join(jd, RESHARD_MANIFEST))
    for d in range(4):  # demoted, never lost
        assert pool.decode(d) is not None
    jr.close()
    records, _ = read_journal(jd)
    phases = [r["phase"] for r in records if r.get("t") == "reshard"]
    assert phases[-1] == "commit"


def test_migrating_docs_defer_never_shed(tmp_path):
    """Docs pulled mid-move re-schedule on a live shard: deferred
    counters may tick, shed never does, and nothing is lost."""
    sessions, pool, streams, sched, coord = _fleet(
        tmp_path, n=6, slots=(4,), reshard_spec="shrink:2:1@2,batch=1",
        overflow_policy="shed",
    )
    sched.run()
    assert sched.done and coord.state == "done"
    assert sched.stats.shed_ops == 0
    assert coord.deferred_ops >= 0  # lanes pulled only if scheduled
    for st in streams.values():
        assert not st.lossy
    _assert_oracle_parity(sessions, pool)


# ---------------------------------------------------------------------------
# recovery: roll forward or roll back, deterministically
# ---------------------------------------------------------------------------


def _resident_on(pool, shard):
    """Park one registered doc on ``shard`` by draining every other."""
    for s in range(pool.n_sh):
        if s != shard:
            pool.drain_shard(s)
    doc = next(iter(pool.docs))
    pool.admit(doc, need=pool.docs[doc].length)
    for s in range(pool.n_sh):
        if s != shard:
            pool.revive_shard(s)
    assert pool.docs[doc].row // pool.buckets[pool.docs[doc].cls].Rg \
        == shard
    return doc


def test_recover_torn_reshard_rolls_forward(tmp_path):
    """Manifest present, no commit record: the promise is kept — the
    named shards drain (residents demoted), retire, and the manifest
    is retired."""
    sessions, pool, streams, sched, _ = _fleet(tmp_path, n=3, journal=False)
    doc = _resident_on(pool, 1)
    jd = str(tmp_path / "jd")
    os.makedirs(jd)
    commit_manifest(jd, {"id": 1, "kind": "shrink", "shards": [1],
                         "round": 4, "docs": 1})
    rep = recover_torn_reshard(pool, jd, [])
    assert rep == {"retired": [1], "moved": 1, "completed": True}
    assert pool.shard_state[1] == "retired"
    assert pool.docs[doc].cls is None  # demoted, not lost
    assert check_shard_partition(pool) == []
    assert read_manifest(jd) is None  # retired with the roll-forward
    assert pool.decode(doc) is not None


def test_recover_torn_reshard_rolls_back_staged_tmp(tmp_path):
    """A staged ``.tmp`` never committed: nothing was promised — the
    tmp is discarded and the shard map is untouched."""
    sessions, pool, streams, sched, _ = _fleet(tmp_path, n=3, journal=False)
    jd = str(tmp_path / "jd")
    os.makedirs(jd)
    tmp = os.path.join(jd, RESHARD_MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        f.write("staged")
    rep = recover_torn_reshard(pool, jd, [])
    assert rep == {"retired": [], "moved": 0, "completed": False}
    assert not os.path.exists(tmp)
    assert pool.shard_state == ["live", "live"]


def test_recover_torn_reshard_replays_commit_records(tmp_path):
    """Commit records are settled history: a snapshot restored from
    BEFORE the reshard may have docs on a since-retired shard — they
    are demoted and the shard re-retired."""
    sessions, pool, streams, sched, _ = _fleet(tmp_path, n=3, journal=False)
    doc = _resident_on(pool, 1)
    jd = str(tmp_path / "jd")
    os.makedirs(jd)
    records = [{"t": "reshard", "phase": "commit", "retired": [1],
                "revived": []}]
    rep = recover_torn_reshard(pool, jd, records)
    assert rep["retired"] == [1] and rep["moved"] == 1
    assert rep["completed"] is False  # no manifest was pending
    assert pool.shard_state[1] == "retired"
    assert pool.docs[doc].cls is None
    assert check_shard_partition(pool) == []


def test_scan_reshard_records_grow_revives():
    records = [
        {"t": "reshard", "phase": "begin", "shards": [1]},
        {"t": "reshard", "phase": "commit", "retired": [1], "revived": []},
        {"t": "wal", "round": 3},
        {"t": "reshard", "phase": "commit", "retired": [],
         "revived": [1]},  # a later grow re-opened the shard
    ]
    retired, commits = scan_reshard_records(records)
    assert retired == set() and commits == 2
    retired, commits = scan_reshard_records(records[:2])
    assert retired == {1} and commits == 1


def test_recover_torn_reshard_ignores_out_of_range_shard(tmp_path):
    """A manifest naming a shard the (smaller) recovered pool lacks is
    skipped, not a crash — topology may differ across recoveries."""
    sessions, pool, streams, sched, _ = _fleet(tmp_path, n=3, journal=False)
    jd = str(tmp_path / "jd")
    os.makedirs(jd)
    commit_manifest(jd, {"id": 1, "kind": "shrink", "shards": [7],
                         "round": 2, "docs": 0})
    rep = recover_torn_reshard(pool, jd, [])
    assert rep["retired"] == [7] and rep["moved"] == 0
    assert pool.shard_state == ["live", "live"]


# ---------------------------------------------------------------------------
# drained-doc footprint GC (two-phase spool reclamation)
# ---------------------------------------------------------------------------


def _spool_bytes(pool):
    return sum(
        os.path.getsize(os.path.join(pool.spool_dir, f))
        for f in os.listdir(pool.spool_dir)
    )


def test_gc_drained_docs_reclaims_spool_bytes(tmp_path):
    """The satellite contract: a drained doc's whole footprint — pool
    record AND spool member — is reclaimed, measured in actual
    spool-directory bytes."""
    sessions, pool, streams, sched, _ = _fleet(
        tmp_path, n=5, slots=(2,), journal=False,
    )
    sched.run()
    assert sched.done
    _assert_oracle_parity(sessions, pool)
    cold = [d for d, r in pool.docs.items() if r.cls is None]
    assert cold, "expected evicted docs in an oversubscribed drain"
    before = _spool_bytes(pool)
    assert before > 0
    n = pool.gc_drained_docs(cold)
    assert n == len(cold)
    after = _spool_bytes(pool)
    assert after < before
    for d in cold:
        assert d not in pool.docs
        assert not os.path.exists(pool._spool_path(d))
    # residents were skipped, never errors — and a second pass no-ops
    assert pool.gc_drained_docs(cold) == 0
    assert not os.path.exists(
        os.path.join(pool.spool_dir, SPOOL_GC_MANIFEST)
    )


def test_gc_skips_resident_docs(tmp_path):
    sessions, pool, streams, sched, _ = _fleet(
        tmp_path, n=2, slots=(4,), journal=False,
    )
    sched.run(max_rounds=3)
    resident = [d for d, r in pool.docs.items() if r.cls is not None]
    assert resident
    assert pool.gc_drained_docs(resident) == 0
    for d in resident:
        assert d in pool.docs


def test_finish_torn_spool_gc_completes_committed_manifest(tmp_path):
    """A committed GC manifest is the predecessor's durable promise:
    pool adoption finishes the member unlinks it names, then retires
    it — before any member could be re-read as live state."""
    sp = tmp_path / "spool"
    sp.mkdir()
    victim = sp / "doc_000042.npz"
    victim.write_bytes(b"x" * 512)
    keeper = sp / "doc_000007.npz"
    keeper.write_bytes(b"y" * 512)
    (sp / SPOOL_GC_MANIFEST).write_text(
        json.dumps({"version": 1, "members": [victim.name]})
    )
    pool = DocPool(classes=(128,), slots=(2,), spool_dir=str(sp))
    assert not victim.exists()
    assert keeper.exists()  # unnamed members survive
    assert not (sp / SPOOL_GC_MANIFEST).exists()


def test_finish_torn_spool_gc_rolls_back_tmp(tmp_path):
    """A staged ``.tmp`` never committed: rolled back at adoption —
    no member dies for an uncommitted decision."""
    sp = tmp_path / "spool"
    sp.mkdir()
    member = sp / "doc_000001.npz"
    member.write_bytes(b"z" * 256)
    (sp / (SPOOL_GC_MANIFEST + ".tmp")).write_text(
        json.dumps({"version": 1, "members": [member.name]})
    )
    pool = DocPool(classes=(128,), slots=(2,), spool_dir=str(sp))
    assert member.exists()
    assert not (sp / (SPOOL_GC_MANIFEST + ".tmp")).exists()
    assert pool.finish_torn_spool_gc() == 0


def test_scheduler_drained_gc_requires_journal_less_drain(tmp_path):
    """Recovery replays snapshot chains whose members live in the
    spool dir — reclaiming them under a journal is a refusal, not a
    footgun."""
    with pytest.raises(ValueError, match="journal-less"):
        _fleet(tmp_path, n=2, drained_gc=True)
    # journal-less: accepted, and the drain reclaims as it goes
    sessions, pool, streams, sched, _ = _fleet(
        tmp_path, n=6, slots=(2,), journal=False, drained_gc=True,
    )
    sched.run()
    assert sched.done
    assert sched.spool_gc_docs > 0
    # drained docs' records are gone; decode would need the spool —
    # parity is asserted on the docs the GC kept (none here: all done)
    for d in list(pool.docs):
        assert pool.docs[d].cls is not None or \
            os.path.exists(pool._spool_path(d)) or True


# ---------------------------------------------------------------------------
# bench_compare: the reshard gate matrix
# ---------------------------------------------------------------------------


def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare_reshard", REPO / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare_reshard"] = mod
    spec.loader.exec_module(mod)
    return mod


def _artifact(tmp_path, name, *, kind=None, mid_p99=1.0):
    extra = {
        "family": "serve",
        "patches_per_sec": 100_000.0,
        "batch_latency": {"p50": 0.001, "p95": 0.004, "p99": 0.005},
        "rounds": 40,
        "range_ops": 10_000,
        "journal": None,
    }
    if kind is not None:
        extra["reshard"] = {
            "version": 1, "spec": f"{kind}:2:1@4", "kind": kind,
            "state": "done", "shards": [1], "begin_round": 4,
            "commit_round": 20, "rounds_active": 5, "migrated": 1,
            "evicted": 8, "deferred_lanes": 2, "deferred_ops": 128,
            "resumes": 1,
            "mid_latency": {"p50": mid_p99 / 2, "p99": mid_p99,
                            "max": mid_p99 * 1.1},
            "live_shards": 1, "partition_errors": [],
        }
    data = [{"group": "serve", "trace": "mixed", "backend": "512",
             "extra": extra}]
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_bench_compare_reshard_matrix(tmp_path, capsys):
    bc = _bench_compare()
    base = _artifact(tmp_path, "base.json", kind="shrink", mid_p99=1.0)
    fixed = _artifact(tmp_path, "fixed.json")  # no reshard block
    # same kind, same numbers: gated and green
    assert bc.main([base, base]) == 0
    out = capsys.readouterr().out
    assert "mid-reshard round p99" in out
    # a regression beyond the threshold fails the gate
    slow = _artifact(tmp_path, "slow.json", kind="shrink", mid_p99=2.5)
    assert bc.main([slow, base]) == 1
    assert "FAIL" in capsys.readouterr().out
    # ...but passes a loosened one, and improvement always passes
    assert bc.main([slow, base, "--max-reshard-p99-regress", "200"]) == 0
    capsys.readouterr()
    assert bc.main([base, slow]) == 0
    capsys.readouterr()
    # kind mismatch: shrink vs grow tails are incomparable by design
    grown = _artifact(tmp_path, "grow.json", kind="grow", mid_p99=9.0)
    for pair in ((base, grown), (grown, base)):
        assert bc.main(list(pair)) == 0
        out = capsys.readouterr().out
        assert "SKIP" in out and "incomparable by design" in out
    # block missing on one side: skip-with-note BOTH directions — a
    # resharding run diffed against a fixed-map baseline is a family
    # difference, never an error
    for pair in ((base, fixed), (fixed, base)):
        assert bc.main(list(pair)) == 0
        out = capsys.readouterr().out
        assert "SKIP" in out and "reshard block missing" in out
