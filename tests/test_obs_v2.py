"""obs/ v2: per-round time-series, per-shard metrics, the live status
endpoint, and the soak anomaly detectors.

Contracts under test:

- time-series windows partition the drain exactly: window sums equal
  the end-of-run ServeStats totals (rounds, ops, unit ops), the ring
  bounds memory with counted drops, and the JSONL stream mirrors the
  ring;
- shard-sum parity on the 8-device virtual mesh: per-shard ops / lane
  series sum to the fleet totals for EVERY window, and the imbalance
  gauge reads exactly 1.0 on a uniform fleet;
- ``/metrics`` conforms to Prometheus text exposition (``# HELP`` /
  ``# TYPE``, ``_total`` counters, cumulative ``_bucket``/``_sum``/
  ``_count``, label parsing + escaping);
- ``/status.json`` advances monotonically while a drain is live
  (scraped from the test thread, mid-run);
- the anomaly detectors fire and CLEAR: the stuck-round watchdog on an
  injected chaos ``stall``, throughput degradation and leak growth on
  synthetic series;
- ``tools/bench_compare.py`` gates the per-window throughput floor and
  tolerates obs/ v2 blocks missing from older baselines.
"""

import importlib.util
import json
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from crdt_benches_tpu.obs.anomaly import AnomalyDetector
from crdt_benches_tpu.obs.status import (
    StatusServer,
    escape_label_value,
    render_prometheus,
    split_labeled_name,
)
from crdt_benches_tpu.obs.status import main as status_main
from crdt_benches_tpu.obs.timeseries import (
    ServeTelemetry,
    TimeseriesRecorder,
)
from crdt_benches_tpu.serve.faults import (
    FaultEvent,
    FaultInjector,
    FaultPlan,
)
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import (
    FleetScheduler,
    prepare_streams,
)
from crdt_benches_tpu.serve.workload import Session, build_fleet

REPO = Path(__file__).resolve().parent.parent

TINY_BANDS = {"synth-small": ("synth", (40, 120))}
TINY_MIX = {"synth-small": 1.0}


def _fleet(tmp_path, n=8, seed=11, classes=(128,), slots=(2,),
           bands=TINY_BANDS, mix=TINY_MIX, arrival_span=2, batch=8,
           batch_chars=32, macro_k=4, mesh=None, **kw):
    sessions = build_fleet(
        n, mix=mix, seed=seed, arrival_span=arrival_span, bands=bands
    )
    pool = DocPool(classes=classes, slots=slots, mesh=mesh,
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(
        sessions, pool, batch=batch, batch_chars=batch_chars
    )
    sched = FleetScheduler(pool, streams, batch=batch, macro_k=macro_k,
                           batch_chars=batch_chars, **kw)
    return sessions, pool, streams, sched


# ---------------------------------------------------------------------------
# time-series recorder: exact partition, bounded ring, JSONL stream
# ---------------------------------------------------------------------------


def test_timeseries_windows_partition_the_drain(tmp_path):
    stream = tmp_path / "ts.jsonl"
    tel = ServeTelemetry(recorder=TimeseriesRecorder(
        window_rounds=2, stream_path=str(stream)
    ))
    _sessions, _pool, _streams, sched = _fleet(
        tmp_path, telemetry=tel
    )
    stats = sched.run()
    assert sched.done
    tel.drain_end()
    blk = tel.recorder.block()
    assert blk["version"] == 1
    ws = blk["windows"]
    assert ws and blk["rounds_seen"] == stats.rounds
    # exact partition: no round, op, or unit op is lost or counted twice
    assert sum(w["rounds"] for w in ws) == stats.rounds
    assert sum(w["ops"] for w in ws) == stats.ops
    assert sum(w["unit_ops"] for w in ws) == stats.unit_ops
    assert sum(w["compile_rounds"] for w in ws) == stats.compile_rounds
    assert sum(w["evictions"] for w in ws) == stats.evictions
    for w in ws:
        assert 0.0 <= w["occupancy"] <= 1.0
        assert w["seconds"] > 0
        assert w["full"] == (w["rounds"] >= 2)
        # shard series partition the fleet numbers (n_sh == 1 here)
        assert sum(w["shard_ops"]) == w["ops"]
        assert sum(w["shard_lanes"]) == w["lanes"]
    # only the final window may be partial
    assert all(w["full"] for w in ws[:-1])
    # the JSONL stream mirrors the ring exactly
    lines = [json.loads(ln) for ln in
             stream.read_text().splitlines()]
    assert lines == ws


def test_timeseries_ring_is_bounded_with_counted_drops():
    rec = TimeseriesRecorder(window_rounds=1, capacity=2)
    rec.rebase(n_shards=1)
    cum = dict.fromkeys(
        ("ops", "unit_ops", "shed", "deferred", "quarantines",
         "dup_dropped", "evictions", "restores", "promotions",
         "recoveries", "journal_bytes", "fence_entries"), 0)
    for i in range(5):
        cum["ops"] = (i + 1) * 10
        w = rec.note_round(
            round_no=i, seconds=0.01, compiled=False, barrier=False,
            occupancy=0.5, queue_depth=0, cum=cum,
        )
        assert w is not None  # window_rounds=1: every round closes one
    blk = rec.block()
    assert len(blk["windows"]) == 2 and blk["dropped_windows"] == 3
    # delta encoding survived the drops: the retained windows carry
    # their OWN deltas, not cumulative values
    assert [w["ops"] for w in blk["windows"]] == [10, 10]


# ---------------------------------------------------------------------------
# shard-sum parity + imbalance on the 8-device virtual mesh
# ---------------------------------------------------------------------------


def test_shard_sum_parity_and_uniform_imbalance(tmp_path):
    """Per-shard series are a PARTITION of the fleet totals for every
    window, and a perfectly uniform fleet (16 identical docs over 8
    shards) gauges imbalance at exactly 1.0 all drain long."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")
    from crdt_benches_tpu.parallel.mesh import replica_mesh
    from crdt_benches_tpu.serve.workload import trace_prefix

    tr = trace_prefix("automerge-paper", 240)
    sessions = [
        Session(doc_id=i, band="t", source="automerge-paper", trace=tr)
        for i in range(16)
    ]
    pool = DocPool(classes=(256,), slots=(16,), mesh=replica_mesh(8),
                   spool_dir=str(tmp_path / "spool"))
    streams = prepare_streams(sessions, pool, batch=8, batch_chars=64)
    tel = ServeTelemetry(recorder=TimeseriesRecorder(window_rounds=2))
    sched = FleetScheduler(pool, streams, batch=8, macro_k=4,
                           batch_chars=64, telemetry=tel)
    stats = sched.run()
    assert sched.done
    tel.drain_end()
    ws = tel.recorder.block()["windows"]
    assert ws and tel.recorder.n_shards == 8
    for w in ws:
        assert len(w["shard_ops"]) == 8
        assert sum(w["shard_ops"]) == w["ops"]
        assert sum(w["shard_unit_ops"]) == w["unit_ops"]
        assert sum(w["shard_lanes"]) == w["lanes"]
        # uniform fleet: every shard carries exactly its share
        assert len(set(w["shard_ops"])) == 1
        assert len(set(w["shard_lanes"])) == 1
    # window sums equal the fleet totals the artifact already reports
    assert sum(w["ops"] for w in ws) == stats.ops
    assert sum(w["unit_ops"] for w in ws) == stats.unit_ops
    m = stats.metrics.to_dict()
    shard_ops = [
        m["counters"][f'serve.shard.ops{{shard="{s}"}}'] for s in range(8)
    ]
    assert sum(shard_ops) == stats.ops
    assert len(set(shard_ops)) == 1
    imb = m["gauges"]["serve.shard.imbalance"]
    assert imb["min"] == imb["max"] == 1.0


def test_imbalance_gauge_reads_skew(tmp_path):
    """A deliberately skewed round (all lanes on shard 0) must gauge
    max/mean = n_shards, not 1.0 — the signal the mesh push needs."""
    from crdt_benches_tpu.obs.shard import ShardMetrics
    from crdt_benches_tpu.obs.metrics import MetricsRegistry

    class _B:
        Rg = 4
        n_sh = 4

        def free_locals(self, s):
            return set()

    class _P:
        n_sh = 4
        buckets = {0: _B()}

        def shard_occupancy(self):
            return [4, 4, 4, 4]

    reg = MetricsRegistry()
    sm = ShardMetrics(_P(), reg)
    sm.note_round([4, 0, 0, 0], [32, 0, 0, 0], [64, 0, 0, 0])
    assert sm.imbalance.value == 4.0
    sm.note_round([1, 1, 1, 1], [8, 8, 8, 8], [8, 8, 8, 8])
    assert sm.imbalance.value == 1.0
    sm.note_round([0, 0, 0, 0], [0, 0, 0, 0], [0, 0, 0, 0])
    assert sm.imbalance.value == 1.0  # idle round is balanced


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


def test_prometheus_exposition_conformance():
    from crdt_benches_tpu.obs.metrics import (
        LATENCY_BUCKETS_S,
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    reg.counter("serve.pool.evictions").inc(7)
    for s in range(3):
        reg.counter(f'serve.shard.ops{{shard="{s}"}}').inc(10 * (s + 1))
    reg.gauge("serve.shard.imbalance").set(1.25)
    h = reg.histogram("serve.round.latency.steady", LATENCY_BUCKETS_S)
    for v in (0.001, 0.01, 0.01, 0.5, 999.0):  # incl. overflow bucket
        h.observe(v)
    text = render_prometheus(reg.to_dict())
    lines = text.splitlines()
    # counters: HELP + TYPE + _total suffix, dots sanitized
    assert "# HELP serve_pool_evictions_total registry counter serve.pool.evictions" in lines
    assert "# TYPE serve_pool_evictions_total counter" in lines
    assert "serve_pool_evictions_total 7" in lines
    # labeled series share ONE header per base name
    assert lines.count("# TYPE serve_shard_ops_total counter") == 1
    assert 'serve_shard_ops_total{shard="1"} 20' in lines
    # gauges
    assert "# TYPE serve_shard_imbalance gauge" in lines
    assert "serve_shard_imbalance 1.25" in lines
    # histogram conformance: cumulative buckets, +Inf == count, sum
    assert "# TYPE serve_round_latency_steady histogram" in lines
    buckets = [ln for ln in lines
               if ln.startswith("serve_round_latency_steady_bucket")]
    assert len(buckets) == len(h.bounds) + 1
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert buckets[-1].startswith(
        'serve_round_latency_steady_bucket{le="+Inf"}'
    )
    assert counts[-1] == 5
    assert any(ln.startswith("serve_round_latency_steady_sum ")
               for ln in lines)
    assert "serve_round_latency_steady_count 5" in lines
    # every metric name is exposition-legal (no dots or braces outside
    # the label block)
    for ln in lines:
        if ln.startswith("#"):
            continue
        name = ln.split("{")[0].split(" ")[0]
        assert "." not in name and name.replace("_", "a").isalnum(), ln


def test_prometheus_label_parsing_and_escaping():
    assert split_labeled_name('a.b{shard="3"}') == ("a.b", {"shard": "3"})
    assert split_labeled_name("a.b") == ("a.b", {})
    assert escape_label_value('a\\b"c\nd') == 'a\\\\b\\"c\\nd'
    from crdt_benches_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter('serve.x{host="a\\b"}').inc()
    text = render_prometheus(reg.to_dict())
    assert 'serve_x_total{host="a\\\\b"} 1' in text


# ---------------------------------------------------------------------------
# live status endpoint: mid-run scrape, monotonic advance
# ---------------------------------------------------------------------------


def test_status_json_advances_monotonically_during_live_drain(tmp_path):
    """The drain runs on a worker thread; THIS thread scrapes
    /status.json mid-run and must see the round counters advance
    monotonically, then /healthz 200 and a final done=True snapshot."""
    status = StatusServer(port=0)
    port = status.start()
    tel = ServeTelemetry(
        recorder=TimeseriesRecorder(window_rounds=1), status=status
    )
    bands = {"synth-big": ("synth", (700, 900))}
    _s, _p, _st, sched = _fleet(
        tmp_path, n=8, bands=bands, mix={"synth-big": 1.0},
        classes=(1024,), slots=(2,), batch=4, batch_chars=32,
        macro_k=2, telemetry=tel,
    )
    errors = []

    def drain():
        try:
            sched.run()
            tel.drain_end(status={
                **sched.status_fields(), "phase": "done", "done": True,
            })
        except Exception as e:  # surfaces in the main thread's assert
            errors.append(e)

    t = threading.Thread(target=drain)
    t.start()
    try:
        base = f"http://127.0.0.1:{port}"
        samples = []
        for _ in range(2000):
            s = json.load(urllib.request.urlopen(
                base + "/status.json", timeout=5
            ))
            if "ops" in s:  # the pre-round "starting" snapshot has none
                samples.append((s["rounds"], s["ops"]))
            if len(samples) >= 3 and samples[-1][0] > samples[0][0]:
                break
            if not t.is_alive():
                break
            time.sleep(0.02)
        h = urllib.request.urlopen(base + "/healthz", timeout=5)
        assert h.status == 200
    finally:
        t.join(timeout=120)
    assert not errors, errors
    assert sched.done
    # fields advanced monotonically while the drain was live
    assert len(samples) >= 2, "never caught the drain mid-run"
    assert samples == sorted(samples)
    assert samples[-1] > samples[0], samples
    final = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/status.json", timeout=5
    ))
    assert final["done"] is True and final["phase"] == "done"
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ).read().decode()
    assert "serve_pool_evictions_total" in text
    # the watch CLI renders one line per poll against the same server
    status_main(["--watch", "--url", f"http://127.0.0.1:{port}",
                 "--count", "1", "--interval", "0.01"])
    status.stop()


def test_healthz_degrades_on_staleness_and_anomaly():
    srv = StatusServer(port=0, stale_after=0.05)
    port = srv.start()
    try:
        srv.publish_status({"rounds": 1})
        h = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        )
        assert h.status == 200
        srv.set_health(False, "stuck_round")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            )
        assert ei.value.code == 503
        assert b"stuck_round" in ei.value.read()
        srv.set_health(True)
        time.sleep(0.1)  # publisher goes silent past stale_after
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            )
        assert ei.value.code == 503
        assert b"stale" in ei.value.read()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------


def test_watchdog_fires_and_clears_synthetic():
    det = AnomalyDetector(watchdog_s=0.05)
    for i in range(5):
        det.note_round(0.01, skip=False, round_no=i)
    det.note_round(10.0, skip=True, round_no=5)  # compile round: exempt
    assert det.fired == 0
    det.note_round(0.2, skip=False, round_no=6)
    assert det.active_kinds() == ["stuck_round"]
    det.note_round(0.01, skip=False, round_no=7)
    assert det.uncleared == 0 and det.fired == 1
    ev = det.events[0]
    assert ev["kind"] == "stuck_round" and ev["cleared"]
    assert ev["round"] == 6 and ev["cleared_round"] == 7
    # a stalled round never drags the rolling baseline up
    assert max(det._lat) == pytest.approx(0.01)


def _window(i, *, tput=100.0, occ=0.5, rss=None, jbytes=0, ops=1000):
    return {
        "end_round": i, "full": True, "throughput": tput,
        "occupancy": occ, "rss_bytes": rss, "journal_bytes": jbytes,
        "ops": ops,
    }


def test_throughput_degradation_fires_and_skips_drain_down():
    det = AnomalyDetector(min_windows=4)
    for i in range(6):
        det.note_window(_window(i, tput=100.0 + i % 3))
    det.note_window(_window(6, tput=30.0))  # collapse at held occupancy
    assert det.active_kinds() == ["throughput_degradation"]
    det.note_window(_window(7, tput=100.0))
    assert det.uncleared == 0 and det.fired == 1
    # the same collapse WITH collapsed occupancy is a legit drain-down
    det2 = AnomalyDetector(min_windows=4)
    for i in range(6):
        det2.note_window(_window(i))
    det2.note_window(_window(6, tput=30.0, occ=0.05))
    assert det2.fired == 0
    # partial windows never feed the rate detector
    det3 = AnomalyDetector(min_windows=4)
    for i in range(6):
        det3.note_window(_window(i))
    det3.note_window(dict(_window(6, tput=1.0), full=False))
    assert det3.fired == 0


def test_leak_detectors_fire_on_monotonic_growth_and_clear():
    det = AnomalyDetector(leak_windows=4, leak_frac=0.2)
    rss = 100_000_000
    for i in range(4):
        rss = int(rss * 1.08)  # strictly rising, +36% over 4 windows
        det.note_window(_window(i, rss=rss))
    assert "rss_leak" in det.active_kinds()
    det.note_window(_window(4, rss=rss))  # plateau clears
    assert det.uncleared == 0
    # journal bytes-per-op growth trips the same machinery
    det2 = AnomalyDetector(leak_windows=4, leak_frac=0.2)
    for i in range(4):
        det2.note_window(_window(i, jbytes=1000 * int(1.1 ** i * 100)))
    assert "journal_growth" in det2.active_kinds()


def test_stall_fault_trips_watchdog_and_recovery_clears_it(tmp_path):
    """THE chaos contract: an injected host ``stall`` must show up as a
    ``stuck_round`` anomaly, and the next healthy round must clear it —
    exit-green, because a cleared anomaly is a demonstration."""
    plan = FaultPlan(
        [FaultEvent(kind="stall", round=6, param=250)], seed=3
    )
    tel = ServeTelemetry(
        recorder=TimeseriesRecorder(window_rounds=2),
        anomaly=AnomalyDetector(watchdog_s=0.1),
    )
    bands = {"synth-big": ("synth", (500, 700))}
    _s, _p, _st, sched = _fleet(
        tmp_path, n=6, bands=bands, mix={"synth-big": 1.0},
        classes=(1024,), slots=(2,), batch=4, batch_chars=32,
        macro_k=4, arrival_span=1,
        faults=FaultInjector(plan), telemetry=tel,
    )
    stats = sched.run()
    assert sched.done
    tel.drain_end()
    assert stats.stall_rounds == 1
    blk = tel.anomaly.block()
    stuck = [e for e in blk["events"] if e["kind"] == "stuck_round"]
    assert stuck, f"stall never tripped the watchdog: {blk}"
    assert all(e["cleared"] for e in stuck)
    assert blk["uncleared"] == 0


# ---------------------------------------------------------------------------
# bench_compare: window floor + schema tolerance
# ---------------------------------------------------------------------------


def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare_v2", REPO / "tools" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_compare_v2"] = mod
    spec.loader.exec_module(mod)
    return mod


def _artifact(tmp_path, name, *, pps=100_000.0, floors=(90_000.0,),
              timeseries=True, anomalies=True):
    extra = {
        "family": "serve",
        "patches_per_sec": pps,
        "batch_latency": {"p99": 0.005},
        "rounds": 20,
        "range_ops": 10_000,
        "journal": None,
    }
    if timeseries:
        extra["timeseries"] = {
            "version": 1,
            "windows": [
                {"full": True, "throughput": f} for f in floors
            ] + [{"full": False, "throughput": 1.0}],  # partial: ignored
        }
    if anomalies:
        extra["anomalies"] = {"version": 1, "fired": 0, "uncleared": 0}
    path = tmp_path / name
    path.write_text(json.dumps([{"group": "serve", "extra": extra}]))
    return str(path)


def test_bench_compare_window_floor_gates(tmp_path, capsys):
    bc = _bench_compare()
    base = _artifact(tmp_path, "base.json", floors=(90_000.0, 95_000.0))
    same = _artifact(tmp_path, "same.json", floors=(91_000.0,))
    assert bc.main([same, base]) == 0
    # one collapsed window fails the floor even at identical mean
    dip = _artifact(tmp_path, "dip.json", floors=(95_000.0, 40_000.0))
    assert bc.main([dip, base]) == 1
    out = capsys.readouterr().out
    assert "window throughput floor" in out and "FAIL" in out
    # a TOTAL stall (throughput 0.0) is the worst floor, not a skipped
    # sample — the truthiness trap this check exists to avoid
    stall = _artifact(tmp_path, "stall.json", floors=(95_000.0, 0.0))
    assert bc.main([stall, base]) == 1


def test_bench_compare_tolerates_missing_v2_blocks(tmp_path, capsys):
    """An old baseline without timeseries/anomalies blocks diffs
    cleanly against a new artifact: skip with a note, exit 0 — never
    the exit-2 artifact-error path."""
    bc = _bench_compare()
    old = _artifact(tmp_path, "old.json", timeseries=False,
                    anomalies=False)
    new = _artifact(tmp_path, "new.json")
    assert bc.main([new, old]) == 0
    out = capsys.readouterr().out
    assert "timeseries block" in out and "anomalies block" in out
    assert "present only in the newer artifact" in out
    assert out.count("FAIL") == 0


# ---------------------------------------------------------------------------
# the soak wrapper
# ---------------------------------------------------------------------------


def test_run_serve_soak_single_drain_artifact(tmp_path):
    from crdt_benches_tpu.serve.bench import run_serve_soak

    r, info = run_serve_soak(
        0.0, seed=3, status_port=0, timeseries_window=2,
        mix=TINY_MIX, bands=TINY_BANDS, n_docs=6, batch=8,
        classes=(128,), slots=(4,), arrival_span=2, macro_k=2,
        batch_chars=32, verify_sample=4,
        results_dir=str(tmp_path), save_name="soak_test",
        log=lambda m: None,
    )
    assert info["verify_ok"] and info["anomalies_ok"]
    assert info["iterations"] == 1
    data = json.load(open(info["path"]))
    extra = data[0]["extra"]
    assert extra["timeseries"]["windows"]
    assert extra["timeseries"]["drains"] == 1
    assert extra["anomalies"]["fired"] == 0
    assert extra["status_port"] > 0
    assert sum(
        w["ops"] for w in extra["timeseries"]["windows"]
    ) == extra["range_ops"]
