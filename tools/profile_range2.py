"""Finer-grain TPU profile of the range-apply pieces (big arrays passed as
jit ARGS, not closures — closures ship as constants through the remote
compile tunnel and blow its request limit).

Usage: python tools/profile_range2.py [R] [B] [trace] [K] [coalesce]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data
from crdt_benches_tpu.traces.tensorize import tensorize_ranges
from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
from crdt_benches_tpu.ops.resolve_range_pallas import resolve_range_pallas
from crdt_benches_tpu.ops.apply_range import (
    _two_level_vis,
    apply_range_batch,
    extract_range_tokens,
)
from crdt_benches_tpu.ops.apply2 import (
    LANE,
    _excl_cumsum_small,
    _mxu_spread,
    count_le_two_level,
    init_state3,
)


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def scan_k(body, K):
    """jit((init, *consts) -> scan(body over K)) with consts as ARGS."""

    @jax.jit
    def run(init, *consts):
        def b(c, _):
            return body(c, *consts), None

        return jax.lax.scan(b, init, None, length=K)[0]

    return run


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    trace_name = sys.argv[3] if len(sys.argv) > 3 else "automerge-paper"
    K = int(sys.argv[4]) if len(sys.argv) > 4 else 8
    coalesce = (len(sys.argv) > 5 and sys.argv[5] == "1")

    trace = load_testing_data(trace_name)
    if coalesce:
        from crdt_benches_tpu.traces.tensorize import coalesce_patches

        rt = tensorize_ranges(
            trace, batch=B, coalesce=True,
            patches=list(coalesce_patches(trace)),
        )
    else:
        rt = tensorize_ranges(trace, batch=B)
    eng = RangeReplayEngine(rt, n_replicas=R)
    C = eng.capacity
    nb = rt.n_batches
    print(
        f"R={R} B={B} C={C} n_batches={nb} nbits={eng.nbits}"
        f" coalesce={coalesce} trace={trace_name} K={K}"
    )

    mid = nb // 2
    kind_b, pos_b, rlen_b, slot0_b = rt.batched()
    kind = jnp.asarray(kind_b[mid])
    pos = jnp.asarray(pos_b[mid])
    rlen = jnp.asarray(rlen_b[mid])
    slot0 = jnp.asarray(slot0_b[mid])
    v0 = jnp.full((R,), int(pos_b[mid].max()) + 1, jnp.int32)
    tcap = eng.token_caps[min(mid // eng.chunk, len(eng.token_caps) - 1)]

    st = init_state3(R, C, C // 2)
    base = timeit(lambda: scan_k(lambda c: c + 1, K)(jnp.zeros((8, 128))))

    tokens, dints, _ = jax.jit(
        lambda k, p, r, v: resolve_range_pallas(k, p, r, v, token_cap=tcap)
    )(kind, pos, rlen, v0)
    T = tokens[0].shape[1]
    print(f"no-op floor: {base/K*1e3:.3f} ms/iter   T={T}")

    def report(name, run, *args):
        t = (timeit(lambda: run(*args)) - base) / K
        print(f"{name:28s} {t*1e3:9.3f} ms")
        return t

    # full apply
    run_ap = scan_k(
        lambda stc, tok, di, s0: apply_range_batch(
            stc, tok, di, s0, nbits=eng.nbits
        ),
        K,
    )
    report("apply_range_batch", run_ap, st, tokens, dints, slot0)

    # _two_level_vis alone (forced via small output)
    def tv(doc, length):
        cvt, tb, tm = _two_level_vis(doc, length)
        return doc, (
            cvt[:, ::LANE].astype(jnp.int32) + tb + tm
        )  # force all three

    run_tv = scan_k(lambda c, ln: tv(c[0], ln)[0] if False else c, K)

    @jax.jit
    def run_tv2(doc, length):
        def b(c, _):
            cvt, tb, tm = _two_level_vis(doc, length)
            return c + tm[:, :1] * 0 + cvt[:, :1].astype(jnp.int32) * 0, None

        return jax.lax.scan(b, jnp.zeros((R, 1), jnp.int32), None, length=K)[0]

    t = (timeit(lambda: run_tv2(st.doc, st.length)) - base) / K
    print(f"{'_two_level_vis':28s} {t*1e3:9.3f} ms")

    # vis cumsum variants
    @jax.jit
    def cs_a(doc):
        def b(c, _):
            vis = jnp.bitwise_and(doc, 1)
            cv = jnp.cumsum(vis.reshape(R, C // LANE, LANE), axis=2)
            return c + cv[:, :1, LANE - 1] * 0, None

        return jax.lax.scan(b, jnp.zeros((R, 1), jnp.int32), None, length=K)[0]

    t = (timeit(lambda: cs_a(st.doc)) - base) / K
    print(f"{'  tile cumsum axis=2':28s} {t*1e3:9.3f} ms")

    @jax.jit
    def cs_b(doc):
        def b(c, _):
            vis = jnp.bitwise_and(doc, 1)
            cv = jnp.cumsum(vis, axis=1)
            return c + cv[:, :1] * 0, None

        return jax.lax.scan(b, jnp.zeros((R, 1), jnp.int32), None, length=K)[0]

    t = (timeit(lambda: cs_b(st.doc)) - base) / K
    print(f"{'  full cumsum axis=1':28s} {t*1e3:9.3f} ms")

    # count_le pieces at Q = 2B + T
    cvt, tile_base, tmax_abs = jax.jit(_two_level_vis)(st.doc, st.length)
    Q = 2 * B + T
    q = jnp.asarray(
        np.broadcast_to(
            (np.arange(Q, dtype=np.int32) * 91) % (C // 2), (R, Q)
        ).copy()
    )

    @jax.jit
    def cl_full(cvt, tile_base, tmax_abs, q):
        def b(c, _):
            r = count_le_two_level(cvt, tile_base, tmax_abs, q + c[:, :1] * 0)
            return c + r[:, :1] * 0, None

        return jax.lax.scan(b, q, None, length=K)[0]

    t = (timeit(lambda: cl_full(cvt, tile_base, tmax_abs, q)) - base) / K
    print(f"{'count_le_two_level':28s} {t*1e3:9.3f} ms")

    nt = C // LANE

    @jax.jit
    def cl_nfull(tmax_abs, q):
        def b(c, _):
            nfull = jnp.sum(
                (tmax_abs[:, None, :] <= q[:, :, None]).astype(jnp.int32),
                axis=2,
            )
            return c + nfull[:, :1] * 0, None

        return jax.lax.scan(b, q, None, length=K)[0]

    t = (timeit(lambda: cl_nfull(tmax_abs, q)) - base) / K
    print(f"{'  nfull compare-reduce':28s} {t*1e3:9.3f} ms")

    @jax.jit
    def cl_rows(cvt, q):
        tiles = cvt.reshape(R, nt, LANE)

        def b(c, _):
            tq = (q + c[:, :1] * 0) % nt
            oh = (
                jax.lax.broadcasted_iota(jnp.int32, (R, Q, nt), 2)
                == tq[:, :, None]
            ).astype(jnp.bfloat16)
            rows = jnp.einsum(
                "rbt,rtl->rbl", oh, tiles,
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            return c + rows[:, :1, 0] * 0, None

        return jax.lax.scan(b, q, None, length=K)[0]

    t = (timeit(lambda: cl_rows(cvt, q)) - base) / K
    print(f"{'  rows one-hot einsum':28s} {t*1e3:9.3f} ms")

    # extract tokens
    @jax.jit
    def ext(ttype, ta, tch, tlen, nvis):
        def b(c, _):
            live, gvis, cumlen = extract_range_tokens(
                ttype, ta, tch, tlen + c[:, :1] * 0, v0=nvis
            )
            return c + cumlen[:, :1] * 0 + gvis[:, :1] * 0, None

        return jax.lax.scan(b, tlen, None, length=K)[0]

    t = (timeit(lambda: ext(*tokens, st.nvis)) - base) / K
    print(f"{'extract_range_tokens':28s} {t*1e3:9.3f} ms")

    # spreads
    qb = jnp.asarray(
        np.broadcast_to(
            (np.arange(B, dtype=np.int32) * 197) % (C // 2), (R, B)
        ).copy()
    )

    @jax.jit
    def sp2(qb):
        ones_b = jnp.ones((R, B), jnp.int32)

        def b(c, _):
            (s1,) = _mxu_spread(qb + c[:, :1] * 0, [ones_b], C)
            (s2,) = _mxu_spread(qb + 3, [ones_b], C)
            ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
            return c + ind[:, :1] * 0, None

        return jax.lax.scan(b, qb, None, length=K)[0]

    t = (timeit(lambda: sp2(qb)) - base) / K
    print(f"{'2 B-spreads + C-cumsum':28s} {t*1e3:9.3f} ms")

    qt = jnp.asarray(
        np.broadcast_to(
            (np.arange(T, dtype=np.int32) * 137) % (C // 2), (R, T)
        ).copy()
    )

    @jax.jit
    def d6(qt):
        ones_t = jnp.ones((R, T), jnp.int32)

        def b(c, _):
            outs = _mxu_spread(qt + c[:, :1] * 0, [ones_t] * 6, C)
            dd = outs[0] + outs[1] - outs[2] + outs[3] - outs[4] + outs[5]
            dc = jnp.cumsum(dd, axis=1)
            return c + dc[:, :1] * 0, None

        return jax.lax.scan(b, qt, None, length=K)[0]

    t = (timeit(lambda: d6(qt)) - base) / K
    print(f"{'6-chunk T-spread + cumsum':28s} {t*1e3:9.3f} ms")

    # expand kernel
    from crdt_benches_tpu.ops.expand_pallas import expand_packed

    cntind = jnp.asarray(
        np.cumsum(
            np.tile(
                (np.arange(C) % max(C // B, 1) == 0).astype(np.int32) * 2,
                (R, 1),
            ),
            axis=1,
        )
        | np.tile(
            (np.arange(C) % max(C // B, 1) == 0).astype(np.int32), (R, 1)
        )
    )

    @jax.jit
    def xp(doc, cntind):
        def b(c, _):
            d = expand_packed(c, cntind, nbits=eng.nbits)
            return d, None

        return jax.lax.scan(b, doc, None, length=K)[0]

    t = (timeit(lambda: xp(st.doc, cntind)) - base) / K
    print(f"{'expand_packed':28s} {t*1e3:9.3f} ms (nbits={eng.nbits})")


if __name__ == "__main__":
    main()
