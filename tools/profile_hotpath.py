"""Micro-profile of the replay hot path on the real chip.

Every dispatch on this runtime costs ~25ms round trip, so each component is
timed as K iterations inside ONE jitted lax.scan, subtracting a baseline
no-op scan of the same length.  Sync is by value fetch.

Usage: python tools/profile_hotpath.py [R] [B] [trace] [K]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data
from crdt_benches_tpu.traces.tensorize import tensorize
from crdt_benches_tpu.engine.replay import ReplayEngine
from crdt_benches_tpu.ops.resolve_pallas import resolve_batch_pallas
from crdt_benches_tpu.ops.apply2 import apply_batch3, init_state3


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=5, warmup=2):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    trace_name = sys.argv[3] if len(sys.argv) > 3 else "automerge-paper"
    K = int(sys.argv[4]) if len(sys.argv) > 4 else 32

    trace = load_testing_data(trace_name)
    tt = tensorize(trace, batch=B)
    eng = ReplayEngine(tt, n_replicas=R)
    C = eng.capacity
    n_ops = len(trace)
    print(f"R={R} B={B} C={C} n_batches={tt.n_batches} trace={trace_name} K={K}")

    mid = tt.n_batches // 2
    kind_b, pos_b, _, slot_b = tt.batched()
    kind = jnp.asarray(kind_b[mid])
    pos = jnp.asarray(pos_b[mid])
    slot = jnp.asarray(slot_b[mid])
    v0 = jnp.full((R,), int(pos_b[mid].max()) + 1, jnp.int32)

    def scan_k(body, init):
        @jax.jit
        def run(init):
            return jax.lax.scan(body, init, None, length=K)[0]

        return lambda: run(init)

    # Baseline: trivial scan to subtract scan-step floor.
    base = timeit(scan_k(lambda c, _: (c + 1, None), jnp.zeros((8, 128))))
    print(f"no-op scan floor:      {base/K*1e3:8.3f} ms/iter")

    # --- resolver alone: carry v0, resolve repeatedly ---
    def res_body(carry, _):
        r = resolve_batch_pallas(kind, pos, carry, emit_origin=False)
        # fold outputs into the carry so nothing is dead-code eliminated
        return carry + r.del_rank[:, 0] * 0 + r.ins_gvis[:, -1] * 0, None

    t = (timeit(scan_k(res_body, v0)) - base) / K
    print(
        f"resolver+extract:      {t*1e3:8.3f} ms/batch"
        f"  -> {t/B*1e9/R:8.1f} ns/op/replica"
    )

    # --- resolver kernel only (skip _extract_gather) ---
    from crdt_benches_tpu.ops import resolve_pallas as rp

    def kern_only(kind, pos, v0):
        Bx = kind.shape[0]
        Rx = v0.shape[0]
        T = rp._round_up(2 * Bx + 2, 128)
        Rt = min(32, max(8, (12 * 2**20) // ((10 * T + 6 * Bx) * 4)))
        Rt = 1 << (Rt.bit_length() - 1)
        while Rx % Rt:
            Rt //= 2
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        kernel = functools.partial(
            rp._kernel, B=Bx, T=T, Rt=Rt, emit_origin=False
        )
        out = pl.pallas_call(
            kernel,
            grid=(Rx // Rt,),
            in_specs=[
                pl.BlockSpec((1, Bx), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, Bx), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((Rt, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((Rt, Bx), lambda i: (i, 0), memory_space=pltpu.VMEM)
            ] * 4
            + [
                pl.BlockSpec((Rt, T), lambda i: (i, 0), memory_space=pltpu.VMEM)
            ] * 3,
            out_shape=[jax.ShapeDtypeStruct((Rx, Bx), jnp.int32)] * 4
            + [jax.ShapeDtypeStruct((Rx, T), jnp.int32)] * 3,
        )(
            kind.reshape(1, Bx).astype(jnp.int32),
            pos.reshape(1, Bx).astype(jnp.int32),
            v0.reshape(Rx, 1).astype(jnp.int32),
        )
        return out

    def kern_body(carry, _):
        out = kern_only(kind, pos, carry)
        return carry + out[0][:, 0] * 0, None

    try:
        t = (timeit(scan_k(kern_body, v0)) - base) / K
        print(
            f"resolver kernel only:  {t*1e3:8.3f} ms/batch"
            f"  -> {t/B*1e9/R:8.1f} ns/op/replica"
        )
    except TypeError as e:
        print(f"resolver kernel only:  skipped ({e})")

    # --- apply_batch4 (the default engine's apply) ---
    from crdt_benches_tpu.ops.apply2 import apply_batch4, init_state4

    st40 = init_state4(R, C, 0)

    def ap4_body(st, _):
        return apply_batch4(st, resolved4, slot), None

    resolved4 = jax.tree.map(
        jnp.asarray, resolve_batch_pallas(kind, pos, v0, emit_origin=False)
    )
    t = (timeit(scan_k(ap4_body, st40)) - base) / K
    print(
        f"apply_batch4:          {t*1e3:8.3f} ms/batch"
        f"  -> {t/B*1e9/R:8.1f} ns/op/replica"
    )

    # --- apply alone ---
    resolved = jax.tree.map(
        jnp.asarray, resolve_batch_pallas(kind, pos, v0, emit_origin=False)
    )
    st0 = init_state3(R, C, 0)

    def ap_body(st, _):
        return apply_batch3(st, resolved, slot), None

    t = (timeit(scan_k(ap_body, st0)) - base) / K
    print(
        f"apply_batch3:          {t*1e3:8.3f} ms/batch"
        f"  -> {t/B*1e9/R:8.1f} ns/op/replica"
    )

    # --- apply sub-pieces ---
    from crdt_benches_tpu.ops.apply2 import rank_to_phys2, _mxu_spread
    from crdt_benches_tpu.ops.expand_pallas import expand_packed

    cumvis = jnp.cumsum(jnp.bitwise_and(st0.doc, 1), axis=1)
    q = jnp.clip(resolved.del_rank, 0, None)

    def cv_body(carry, _):
        c = jnp.cumsum(jnp.bitwise_and(carry, 1), axis=1)
        return carry + (c[:, -1:] * 0), None

    t = (timeit(scan_k(cv_body, st0.doc)) - base) / K
    print(f"  cumsum (R,C):        {t*1e3:8.3f} ms")

    def rp_body(carry, _):
        r = rank_to_phys2(cumvis, q + carry[:, :1] * 0)
        return carry + r[:, :1] * 0, None

    t = (timeit(scan_k(rp_body, q)) - base) / K
    print(f"  rank_to_phys2 x1:    {t*1e3:8.3f} ms")

    def mx_body(carry, _):
        (o,) = _mxu_spread(q, [carry * 0 + 1], C)
        return carry + o[:, :1] * 0, None

    t = (timeit(scan_k(mx_body, q)) - base) / K
    print(f"  mxu_spread 1chunk:   {t*1e3:8.3f} ms")

    cntind = jnp.cumsum(
        jnp.zeros((R, C), jnp.int32).at[:, ::357].set(1), axis=1
    )

    def ex_body(carry, _):
        o = expand_packed(carry, cntind, nbits=10)
        return o, None

    t = (timeit(scan_k(ex_body, st0.doc)) - base) / K
    print(f"  expand_packed:       {t*1e3:8.3f} ms")

    # --- full replay ---
    def full():
        s = eng.run()
        return s.nvis

    t = timeit(full, n=3, warmup=1)
    eps = n_ops * R / t
    print(
        f"full replay:           {t:8.3f} s"
        f"  -> {t/n_ops*1e9/R:8.1f} ns/op/replica"
        f"  -> aggregate {eps/1e6:.2f}M el/s"
    )


if __name__ == "__main__":
    main()
