#!/usr/bin/env bash
# The blessed tier-1 gate — byte-for-byte the ROADMAP.md "Tier-1 verify"
# command, so builders and CI invoke ONE script instead of re-typing it.
# Runs the quick tier (every non-slow test) in a single process on CPU,
# with a hard timeout, and echoes DOTS_PASSED=<count> for the driver.
#
# Usage: tools/tier1.sh        (from the repo root)
#
# Stage 0 is the LINT gate (graftlint G001-G016 + ruff when installed;
# the artifact-driven cross-checks G011/G017 ride the bench smoke,
# sub-10s, see tools/lint.sh): JAX-hygiene violations fail tier-1 before
# a single test runs.  Escape hatch: `# graftlint: disable=G00X` on the
# offending line (reviewed, never drive-by).
set -o pipefail
bash "$(dirname "$0")/lint.sh" || { echo "tier1: lint gate failed" >&2; exit 1; }
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
