#!/usr/bin/env bash
# The blessed tier-1 gate — byte-for-byte the ROADMAP.md "Tier-1 verify"
# command, so builders and CI invoke ONE script instead of re-typing it.
# Runs the quick tier (every non-slow test) in a single process on CPU,
# with a hard timeout, and echoes DOTS_PASSED=<count> for the driver.
#
# Usage: tools/tier1.sh        (from the repo root)
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
