"""Truncated-pipeline profile of apply_range_batch4 (the fused v4 path):
stage deltas isolate queries / spread A / spread B / kernel.

Usage: python tools/profile_range4.py [R] [B] [trace] [K] [coalesce]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data
from crdt_benches_tpu.traces.tensorize import tensorize_ranges
from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
from crdt_benches_tpu.ops.resolve_range_pallas import resolve_range_pallas
from crdt_benches_tpu.ops.apply_range import _prev_value, extract_range_tokens
from crdt_benches_tpu.ops.apply2 import (
    LANE,
    _excl_cumsum_small,
    _mxu_spread,
    count_le_two_level,
    init_state4,
)
from crdt_benches_tpu.ops.apply_range_fused import range_fused


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def staged(state, tokens, dints, slot0_b, nbits, stage):
    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    R, C = state.doc.shape
    B = dlo.shape[1]
    drop = jnp.int32(C + 7)

    tile_base = _excl_cumsum_small(state.vis_tile)
    tmax_abs = tile_base + state.vis_tile
    has_del = dlo >= 0
    live, gvis, cumlen = extract_range_tokens(ttype, ta, tch, tlen,
                                              v0=state.nvis)
    allq = count_le_two_level(
        state.cv_intile, tile_base, tmax_abs,
        jnp.concatenate(
            [jnp.where(has_del, dlo, 0), jnp.where(has_del, dhi, 0),
             jnp.where(live, gvis, 0)], axis=1,
        ),
    )
    lo_phys = allq[:, :B]
    hi_phys = allq[:, B : 2 * B]
    gq_phys = allq[:, 2 * B :]
    if stage == 0:
        return jnp.sum(allq, axis=1, keepdims=True)

    at_end = gvis >= state.nvis[:, None]
    g_phys = jnp.where(at_end, state.length[:, None], gq_phys)
    dest0 = jnp.where(live, g_phys + cumlen, drop)
    dstop = jnp.where(live, dest0 + tlen, drop)

    idxA = jnp.concatenate(
        [jnp.where(has_del, lo_phys, drop),
         jnp.where(has_del, hi_phys + 1, drop)], axis=1
    )
    pm = has_del.astype(jnp.int32)
    zb = jnp.zeros_like(pm)
    deldp, deldn = _mxu_spread(
        idxA,
        [jnp.concatenate([pm, zb], axis=1),
         jnp.concatenate([zb, pm], axis=1)], C,
    )
    delpk = deldp | jnp.left_shift(deldn, 14)
    if stage == 1:
        return jnp.sum(delpk, axis=1, keepdims=True)

    slot0_t = jnp.where(
        live,
        jnp.take(
            jnp.concatenate([slot0_b, jnp.zeros((1,), jnp.int32)]),
            jnp.clip(ta, 0, slot0_b.shape[0]),
        ), 0,
    )
    delta = jnp.where(live, slot0_t + tch - dest0, 0)
    ddelta = jnp.where(live, delta - _prev_value(delta, live), 0)
    lv = live.astype(jnp.int32)
    zeros_t = jnp.zeros_like(lv)
    dp = jnp.where(ddelta > 0, ddelta, 0)
    dn = jnp.where(ddelta < 0, -ddelta, 0)
    half = lambda x: jnp.concatenate([x, zeros_t], axis=1)
    idxB = jnp.concatenate([dest0, dstop], axis=1)
    ind_d, p0, p1, p2, n0, n1, n2 = _mxu_spread(
        idxB,
        [jnp.concatenate([lv, -lv], axis=1),
         half(jnp.bitwise_and(dp, 127)),
         half(jnp.bitwise_and(jnp.right_shift(dp, 7), 127)),
         half(jnp.bitwise_and(jnp.right_shift(dp, 14), 127)),
         half(jnp.bitwise_and(dn, 127)),
         half(jnp.bitwise_and(jnp.right_shift(dn, 7), 127)),
         half(jnp.bitwise_and(jnp.right_shift(dn, 14), 127))], C,
    )
    ddp_d = p0 + jnp.left_shift(p1, 7) + jnp.left_shift(p2, 14)
    ddn_d = n0 + jnp.left_shift(n1, 7) + jnp.left_shift(n2, 14)
    if stage == 2:
        return (
            jnp.sum(delpk + ind_d + ddp_d + ddn_d, axis=1, keepdims=True)
        )

    n_ins = jnp.sum(jnp.where(live, tlen, 0), axis=1)
    length2 = state.length + n_ins
    doc, cv, vt = range_fused(
        state.doc, delpk, ind_d, ddp_d, ddn_d, length2, nbits=nbits
    )
    return jnp.sum(doc, axis=1, keepdims=True) + vt[:, -1:]


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    trace_name = sys.argv[3] if len(sys.argv) > 3 else "automerge-paper"
    K = int(sys.argv[4]) if len(sys.argv) > 4 else 8
    coalesce = (len(sys.argv) <= 5 or sys.argv[5] == "1")

    trace = load_testing_data(trace_name)
    if coalesce:
        from crdt_benches_tpu.traces.tensorize import coalesce_patches

        rt = tensorize_ranges(trace, batch=B, coalesce=True,
                              patches=list(coalesce_patches(trace)))
    else:
        rt = tensorize_ranges(trace, batch=B)
    eng = RangeReplayEngine(rt, n_replicas=R)
    C = eng.capacity
    nb = rt.n_batches
    print(f"R={R} B={B} C={C} n_batches={nb} nbits={eng.nbits}"
          f" coalesce={coalesce} K={K} engine={eng.engine}")

    mid = nb // 2
    kind_b, pos_b, rlen_b, slot0_b = rt.batched()
    kind = jnp.asarray(kind_b[mid])
    pos = jnp.asarray(pos_b[mid])
    rlen = jnp.asarray(rlen_b[mid])
    slot0 = jnp.asarray(slot0_b[mid])
    v0 = jnp.full((R,), int(pos_b[mid].max()) + 1, jnp.int32)
    tcap = eng.token_caps[min(mid // eng.chunk, len(eng.token_caps) - 1)]

    st = init_state4(R, C, C // 2)
    tokens, dints, _ = jax.jit(
        lambda k, p, r, v: resolve_range_pallas(k, p, r, v, token_cap=tcap)
    )(kind, pos, rlen, v0)
    print("T =", tokens[0].shape[1])

    @jax.jit
    def nop(doc):
        def b(c, _):
            return c + 1, None
        return jax.lax.scan(b, doc[:, :1], None, length=K)[0]

    base = timeit(lambda: nop(st.doc))
    print(f"floor: {base/K*1e3:.3f} ms/iter")

    # resolver
    @jax.jit
    def res_run(kind, pos, rlen, v0):
        def b(c, _):
            tk, di, nu = resolve_range_pallas(
                kind, pos, rlen, v0 + c[:1] * 0, token_cap=tcap
            )
            return jnp.minimum(c, nu[:, 0]), None
        return jax.lax.scan(b, v0, None, length=K)[0]

    t = (timeit(lambda: res_run(kind, pos, rlen, v0)) - base) / K
    print(f"{'resolver':26s} {t*1e3:9.3f} ms")

    def make(stage):
        @jax.jit
        def run(doc, cv, vt, length, nvis, tokens, dints, slot0):
            from crdt_benches_tpu.ops.apply2 import PackedState4

            def b(c, _):
                z = jnp.where(c == jnp.int32(-123456789), 1, 0)
                stt = PackedState4(doc + z, cv, vt, length, nvis)
                out = staged(stt, tokens, dints, slot0, eng.nbits, stage)
                return jnp.minimum(c, out), None
            return jax.lax.scan(b, doc[:, :1], None, length=K)[0]
        return lambda: run(st.doc, st.cv_intile, st.vis_tile, st.length,
                           st.nvis, tokens, dints, slot0)

    names = ["0 extract+queries", "1 + spread A (del)",
             "2 + spread B (ind/dd)", "3 + fused kernel"]
    prev = 0.0
    for stage, name in enumerate(names):
        t = (timeit(make(stage)) - base) / K
        print(f"{name:26s} {t*1e3:9.3f} ms  (+{(t-prev)*1e3:8.3f})")
        prev = t


if __name__ == "__main__":
    main()
