"""Piece-wise TPU profile of the run/patch downstream apply step.

Times each component of one merge_runlogs batch step (the jax-patch /
jax-runs downstream hot path) as K iterations inside one jitted lax.scan
minus a no-op scan baseline, exactly like tools/profile_hotpath.py (every
dispatch on this runtime costs ~25ms round trip; sync is by value fetch).

Usage: python tools/profile_downstream.py [R] [W] [trace] [K] [epoch]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data
from crdt_benches_tpu.traces.tensorize import tensorize
from crdt_benches_tpu.engine.merge import MergeSimulation
from crdt_benches_tpu.engine.merge_range import (
    BIGKEY,
    RunMergeSimulation,
    _run_batch_fragments,
)
from crdt_benches_tpu.engine.downstream import down_packed_init
from crdt_benches_tpu.engine.downstream_range import (
    _apply_range_update_batch5,
)
from crdt_benches_tpu.ops.idpos import query, snap_rebuild


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=5, warmup=2):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    W = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    trace_name = sys.argv[3] if len(sys.argv) > 3 else "automerge-paper"
    K = int(sys.argv[4]) if len(sys.argv) > 4 else 16
    EPOCH = int(sys.argv[5]) if len(sys.argv) > 5 else 8

    trace = load_testing_data(trace_name)
    tt = tensorize(trace, batch=512)
    sim = MergeSimulation([tt], base=trace.start_content, batch=W)
    ps = np.zeros(tt.n_ops, bool)
    u = 0
    for _pos, d, ins in trace.iter_patches():
        ps[u] = True
        u += d + len(ins)
    rm = RunMergeSimulation(sim, batch=W, epoch=EPOCH, patch_starts=[ps])
    C = sim.capacity
    nb = len(rm.lamport) // W
    print(
        f"R={R} W={W} C={C} n_runs={rm.n_runs} n_batches={nb}"
        f" nbits={rm.nbits} epoch={EPOCH} trace={trace_name} K={K}"
    )

    # mid-stream batch (device arrays)
    mid = nb // 2
    sl = slice(mid * W, (mid + 1) * W)
    lam = jnp.asarray(rm.lamport[sl])
    ag = jnp.asarray(rm.agent[sl])
    s0 = jnp.asarray(rm.slot0[sl])
    rl = jnp.asarray(rm.rlen[sl])
    orig = jnp.asarray(rm.origin[sl])
    key = jnp.where(rl > 0, lam * 1024 + ag, BIGKEY)

    # a plausible mid-stream doc state: first half of slots laid out in id
    # order (positions are only used as gather/shift fodder — cost is
    # shape-dependent, not value-dependent)
    st = down_packed_init(R, C, C // 2)
    snap = st.snap
    neg1 = jnp.full((W,), -1, jnp.int32)

    def scan_k(body, init):
        @jax.jit
        def run(init):
            return jax.lax.scan(body, init, None, length=K)[0]

        return lambda: run(init)

    base = timeit(scan_k(lambda c, _: (c + 1, None), jnp.zeros((8, 128))))
    print(f"no-op scan floor:        {base/K*1e3:8.3f} ms/iter")

    # --- fragments (replica-independent W x W forest) ---
    def frag_body(carry, _):
        fa, fr, fs, fl = _run_batch_fragments(key, s0, rl, orig + carry * 0)
        return carry + fa[0] * 0 + fr[-1] * 0 + fs[0] * 0 + fl[0] * 0, None

    t = (timeit(scan_k(frag_body, jnp.int32(0))) - base) / K
    print(f"_run_batch_fragments:    {t*1e3:8.3f} ms/batch")

    # --- id query at various level depths ---
    fa, fr, fs, fl = jax.jit(_run_batch_fragments)(key, s0, rl, orig)
    from crdt_benches_tpu.ops.idpos import make_level_runs

    bc = lambda x: jnp.broadcast_to(x[None], (R,) + x.shape)
    lvl = jax.jit(make_level_runs)(
        bc(jnp.abs(fa) % C), bc(fl), bc(jnp.maximum(fs, 0)), bc(fl > 0)
    )
    ids = bc(jnp.concatenate([jnp.maximum(fa, 0)] * 3))[:, : 3 * W]

    for L in (0, EPOCH // 2, EPOCH - 1):
        levels = [lvl] * L

        def q_body(carry, _):
            p = query(snap, levels, ids + carry[:, :1] * 0)
            return carry + p[:, :1] * 0, None

        t = (timeit(scan_k(q_body, ids)) - base) / K
        print(f"query {L:2d} levels (3W):   {t*1e3:8.3f} ms/batch")

    # --- snap_rebuild ---
    def sr_body(carry, _):
        s = snap_rebuild(st.doc + carry[:, :1] * 0)
        return carry + s[:, :1] * 0, None

    t = (timeit(scan_k(sr_body, snap)) - base) / K
    print(f"snap_rebuild:            {t*1e3:8.3f} ms   (1 per epoch)")

    # --- full batch apply at various level depths ---
    for L in (0, EPOCH // 2, EPOCH - 1):
        levels = [lvl] * L

        def ap_body(carry, _):
            doc, length, nvis = carry
            doc, length, nvis, _lv = _apply_range_update_batch5(
                doc, length, nvis, snap, levels,
                fa, fr, fs, fl, jnp.ones_like(fa),
                jnp.concatenate([neg1, neg1]),
                jnp.concatenate([neg1, neg1]),
                nbits=rm.nbits,
            )
            return (doc, length, nvis), None

        t = (
            timeit(scan_k(ap_body, (st.doc, st.length, st.nvis))) - base
        ) / K
        print(f"apply5 {L:2d} levels:       {t*1e3:8.3f} ms/batch")

    # --- spread block alone (the 5 _mxu_spread calls + cumsums) ---
    from crdt_benches_tpu.ops.apply2 import _mxu_spread

    dest0 = jnp.broadcast_to(
        (jnp.arange(2 * W, dtype=jnp.int32) * 37) % C, (R, 2 * W)
    )
    ones = jnp.ones((R, 2 * W), jnp.int32)

    def sp_body(carry, _):
        (s1,) = _mxu_spread(dest0 + carry[:, :1] * 0, [ones], C)
        (s2,) = _mxu_spread(dest0 + 1, [ones], C)
        ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
        return carry + ind[:, :1] * 0, None

    t = (timeit(scan_k(sp_body, dest0)) - base) / K
    print(f"2 spreads + cumsum:      {t*1e3:8.3f} ms/batch")

    # --- 8-chunk spread (the fill/delta block's shape) ---
    chunks = [ones] * 8

    def sp8_body(carry, _):
        outs = _mxu_spread(dest0 + carry[:, :1] * 0, chunks, C)
        acc = outs[0]
        for o in outs[1:]:
            acc = acc + o
        return carry + acc[:, :1] * 0, None

    t = (timeit(scan_k(sp8_body, dest0)) - base) / K
    print(f"8-chunk spread:          {t*1e3:8.3f} ms/batch")

    # --- fused expansion kernel ---
    from crdt_benches_tpu.ops.expand_pallas import (
        fused_apply_nocv_dispatch,
    )

    combo = jnp.zeros((R, C), jnp.int32).at[:, ::357].set(5)
    cnt_base = jnp.cumsum(
        jnp.sum(combo.reshape(R, C // 128, 128) & 1, axis=2), axis=1
    )
    cnt_base = cnt_base - cnt_base[:, :1]

    def fx_body(carry, _):
        d = fused_apply_nocv_dispatch(
            carry, combo, cnt_base, st.length, nbits=rm.nbits
        )
        return d, None

    t = (timeit(scan_k(fx_body, st.doc)) - base) / K
    print(f"fused expand+fill:       {t*1e3:8.3f} ms/batch")

    # --- argsort of the whole wire (once per merge) ---
    allkey = jnp.asarray(
        np.where(rm.rlen > 0, rm.lamport * 1024 + rm.agent, 2**31 - 1)
    )

    def srt_body(carry, _):
        p = jnp.argsort(allkey + carry[0] * 0)
        return carry + p[:1] * 0, None

    t = (timeit(scan_k(srt_body, jnp.zeros(8, jnp.int32))) - base) / K
    print(f"wire argsort (n_runs):   {t*1e3:8.3f} ms   (1 per merge)")


if __name__ == "__main__":
    main()
