#!/usr/bin/env python
"""Serve-bench regression gate: diff a fresh artifact against a baseline.

The BENCH_r* trajectory used to be a log; this makes it an enforced
contract.  Given two serve artifacts (``bench/harness.py save_results``
files), the gate compares:

- **throughput** (``patches_per_sec``) — the headline number;
- **steady p99** (``batch_latency.p99``) — serving jitter, already
  compile/barrier-excluded by ``ServeStats.note_round``;
- **journal overhead** (journal bytes per range op) — the WAL's cost,
  only when both runs journaled;
- **boundary syncs** (fence entries per macro-round from the
  ``boundary_syncs`` block) — the "syncs only at boundaries" invariant
  as a *rate*: a new sync on the hot path shows up here before it shows
  up in latency;
- **window throughput floor** — when BOTH artifacts carry the obs/ v2
  ``timeseries`` block, the worst full window's throughput is compared
  too: a mid-run stall the end-of-run mean averages away fails here.

Open-loop artifacts (``--serve-open``, the ``ingest`` block) flip the
headline semantics: throughput follows the OFFERED load, so its gate
is skipped with a note, and steady p99 is gated only when both
artifacts ran at the same offered load — the "p99 at fixed offered
load" contract.  Rate-mismatched or mixed open/closed pairs skip p99
with a note instead of comparing incomparable numbers.

Artifacts of different schema vintages diff cleanly: an obs/ v2 block
(``timeseries`` / ``anomalies``) present on only one side is reported
as a skip with a note, never an error — a new baseline is not required
to start recording time-series.

Every check carries a noise threshold (benchmarks jitter; the defaults
are deliberately looser than run-to-run variance on this box) and the
exit code carries the verdict: 0 = no regression, 1 = at least one
check failed, 2 = usage/artifact error.

Usage::

    python tools/bench_compare.py NEW.json BASELINE.json \
        [--max-throughput-regress 10] [--max-p99-regress 40] \
        [--max-journal-regress 25] [--max-syncs-regress 60] [--json]

The committed baseline for ``serve/mixed/4096`` lives at
``bench_results/serve_baseline.json``; the open-loop baseline for
``serve/open/mixed/4096`` at ``bench_results/serve_open_baseline.json``.
CI smokes also reuse this gate to bound armed-tracing overhead (traced
leg vs plain leg, 5%).
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass


@dataclass
class Check:
    name: str
    status: str  # "pass" | "fail" | "skip"
    new: float | None = None
    base: float | None = None
    change_pct: float | None = None
    threshold_pct: float | None = None
    note: str = ""

    def line(self) -> str:
        tag = self.status.upper()
        if self.status == "skip":
            return f"{tag:4s} {self.name}: {self.note}"
        return (
            f"{tag:4s} {self.name}: {self.new:.6g} vs baseline "
            f"{self.base:.6g} ({self.change_pct:+.1f}%, "
            f"threshold {self.threshold_pct:.0f}%)"
        )


def load_serve_extra(path: str) -> dict:
    """The ``extra`` block of the first serve-family result in a
    ``save_results`` artifact."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise ValueError(f"{path}: not a bench result list")
    for entry in data:
        extra = entry.get("extra") if isinstance(entry, dict) else None
        if isinstance(extra, dict) and extra.get("family") in (
            "serve", "serve-repl",
        ):
            return extra
    raise ValueError(f"{path}: no serve-family result found")


def _regress(name: str, new: float | None, base: float | None,
             threshold: float, higher_is_better: bool,
             skip_note: str = "") -> Check:
    """One thresholded comparison; ``change_pct`` is signed so the
    report reads naturally (negative = the metric went down)."""
    if new is None or base is None:
        return Check(name, "skip",
                     note=skip_note or "metric missing in one artifact")
    if base <= 0:
        return Check(name, "skip", note=f"baseline value {base!r} unusable")
    change = (new - base) / base * 100.0
    regress = -change if higher_is_better else change
    status = "fail" if regress > threshold else "pass"
    return Check(name, status, new=new, base=base, change_pct=change,
                 threshold_pct=threshold)


def _journal_bytes_per_op(extra: dict) -> float | None:
    j = extra.get("journal")
    ops = extra.get("range_ops")
    if not j or not ops or not j.get("bytes"):
        return None
    return j["bytes"] / ops


def _syncs_per_round(extra: dict) -> float | None:
    b = extra.get("boundary_syncs")
    rounds = extra.get("rounds")
    if not b or not rounds or not isinstance(b.get("entries"), dict):
        return None
    return sum(b["entries"].values()) / rounds


#: Artifact blocks newer runs may carry that older baselines will not
#: (obs/ v2 + v3).  One-sided presence is a schema difference, not a
#: regression: it becomes a "skip" line with a note, never an error.
#: ``replication`` / ``convergence`` are the serve/replicate/ blocks —
#: a replicated run diffed against a pre-replication baseline (or a
#: plain run against a replicated one) must also diff cleanly;
#: ``reqtrace`` / ``slo`` / ``flight`` are the obs/ v3 request-tracing
#: blocks.
#: ``recovery`` is the durability v2 measured-RTO block (runs with the
#: recovery leg armed).
#: ``residency`` is the tiered-residency block (``--serve-tiers``
#: runs) — skip-with-note in BOTH directions: a tier run diffed
#: against a flat baseline (or vice versa) is a schema difference,
#: never an error.
#: ``fs_ops`` is the graftlint v4 durable-protocol block (fs sanitizer
#: entry/op counters, G021's ground truth) — same both-directions
#: skip: artifacts written before the block existed (or by a run that
#: never journaled) diff cleanly against sanitized ones.
#: ``ingest`` / ``knee`` are the open-loop serving blocks
#: (``--serve-open`` / ``--serve-open-sweep``) — both-directions skip:
#: an open-loop artifact diffed against a closed-loop baseline (or
#: vice versa) is a family difference, never an error.
#: ``construction`` is the streaming-fleet-construction block
#: (construction_ms + RSS, both modes carry it) — artifacts written
#: before the block existed skip-with-note one-sided, and a
#: stream-vs-eager pair skips the numeric gates (mode mismatch), so
#: both directions diff cleanly.
#: ``reshard`` is the elastic-reconfiguration block (``--serve-reshard``
#: runs: live shard-map change mid-drain) — both-directions skip: a
#: resharding run diffed against a fixed-map baseline (or vice versa)
#: is a family difference, never an error, and a shrink-vs-grow pair
#: skips the mid-reshard latency gate (kind mismatch).
#: ``lifecycle`` is the graftlint v5 lifecycle & ownership block
#: (machine edge + resource acquire/release counters, G025's ground
#: truth) — same both-directions skip: artifacts written before the
#: block existed diff cleanly against runs that carry it.
#: ``ranges`` is the graftlint v6 value-range block (declared range
#: check + mask-consumer counters, G029's ground truth) — again
#: presence-mismatch is a skip-with-note, never a failure.
_OPTIONAL_BLOCKS = ("timeseries", "anomalies", "replication",
                    "convergence", "reqtrace", "slo", "flight",
                    "recovery", "residency", "fs_ops", "ingest",
                    "knee", "construction", "reshard", "lifecycle",
                    "ranges")


def _tier_hit_rate(extra: dict) -> float | None:
    """Warm+prefetch hit rate from the ``residency`` block: of the
    admissions that needed a doc's state back, the fraction that
    avoided a synchronous cold read.  None when the artifact predates
    the block, ran flat, or saw no re-admissions."""
    res = extra.get("residency")
    if not isinstance(res, dict):
        return None
    return res.get("hit_rate")


def _construction_mode(extra: dict) -> str | None:
    """``"stream"`` / ``"eager"`` from the ``construction`` block;
    None when the artifact predates it."""
    c = extra.get("construction")
    return c.get("mode") if isinstance(c, dict) else None


def _construction_ms(extra: dict) -> float | None:
    """Fleet setup wall time (spec/sessions -> pool -> streams ->
    scheduler ready) in ms.  None when the artifact predates the
    ``construction`` block."""
    c = extra.get("construction")
    return c.get("construction_ms") if isinstance(c, dict) else None


def _construction_rss(extra: dict) -> float | None:
    """Process peak RSS in bytes from the ``construction`` block —
    the O(active-set)-vs-O(fleet) footprint number.  None when the
    artifact predates the block."""
    c = extra.get("construction")
    return c.get("peak_rss_bytes") if isinstance(c, dict) else None


def _construction_checks(new: dict, base: dict,
                         max_construction_regress: float,
                         max_rss_regress: float) -> list[Check]:
    """The streaming-construction gates: setup wall time + peak RSS,
    one-sided skip-with-note like timeseries — and skipped (with the
    modes named) when one side built eagerly and the other streamed,
    since O(fleet) vs O(active-set) numbers are incomparable by
    design, not a regression."""
    nm, bm = _construction_mode(new), _construction_mode(base)
    if nm is not None and bm is not None and nm != bm:
        note = (f"construction mode differs ({nm} vs {bm}): "
                "O(active-set) and O(fleet) setup costs are "
                "incomparable by design")
        return [
            Check("construction time (ms)", "skip", note=note),
            Check("peak RSS (bytes)", "skip", note=note),
        ]
    return [
        _regress(
            "construction time (ms)",
            _construction_ms(new), _construction_ms(base),
            max_construction_regress, higher_is_better=False,
            skip_note="construction block missing in at least one "
                      "artifact",
        ),
        _regress(
            "peak RSS (bytes)",
            _construction_rss(new), _construction_rss(base),
            max_rss_regress, higher_is_better=False,
            skip_note="construction block missing in at least one "
                      "artifact",
        ),
    ]


def _reshard_kind(extra: dict) -> str | None:
    """``"shrink"`` / ``"grow"`` / ``"drain"`` from the ``reshard``
    block (elastic reconfiguration, ``--serve-reshard`` runs); None
    for fixed-shard-map artifacts."""
    r = extra.get("reshard")
    return r.get("kind") if isinstance(r, dict) else None


def _reshard_mid_p99(extra: dict) -> float | None:
    """Mid-reshard round p99 in seconds — the latency of macro-rounds
    SERVED WHILE the shard-map change was in flight, the number the
    "no downtime" claim lives or dies on (the end-of-run p99 averages
    the migration window away).  None when the artifact carries no
    ``reshard`` block or the move never spanned a served round."""
    r = extra.get("reshard")
    if not isinstance(r, dict):
        return None
    lat = r.get("mid_latency")
    return lat.get("p99") if isinstance(lat, dict) else None


def _reshard_checks(new: dict, base: dict,
                    max_reshard_p99_regress: float) -> list[Check]:
    """The elastic-reconfiguration gate: mid-reshard round p99,
    one-sided skip-with-note like recovery — and skipped (with the
    kinds named) when the two artifacts ran different shard-map
    changes, since the tail under a shrink (docs funneling onto fewer
    shards) and under a grow (an emptier map absorbing moves) are
    incomparable by design, not a regression.  The worst-class
    SLO-burn leg of the reshard gate is the ordinary ``slo`` check —
    both reshard artifacts carry an slo block, so violation growth
    during the migration window fails there."""
    nk, bk = _reshard_kind(new), _reshard_kind(base)
    if nk is not None and bk is not None and nk != bk:
        return [Check(
            "mid-reshard round p99 (s)", "skip",
            note=(f"reshard kind differs ({nk} vs {bk}): the tail "
                  "under a shrink and under a grow are incomparable "
                  "by design"),
        )]
    return [
        _regress(
            "mid-reshard round p99 (s)",
            _reshard_mid_p99(new), _reshard_mid_p99(base),
            max_reshard_p99_regress, higher_is_better=False,
            skip_note="reshard block missing in at least one artifact",
        ),
    ]


def _recover_ms(extra: dict) -> float | None:
    """The measured recovery-time objective: ``recover_fleet`` wall
    time in ms from the ``recovery`` block (durability v2).  None when
    the artifact predates the block or the leg did not run."""
    rec = extra.get("recovery")
    return rec.get("recover_ms") if isinstance(rec, dict) else None


def _journal_disk_bytes(extra: dict) -> float | None:
    """On-disk journal footprint at drain end — the bounded-footprint
    number (O(ops since last committed snapshot) under segment GC, not
    O(history)).  Prefers the recovery block's measurement (taken at
    the recovery point), falls back to the journal block's."""
    rec = extra.get("recovery")
    if isinstance(rec, dict) and rec.get("journal_disk_bytes"):
        return rec["journal_disk_bytes"]
    j = extra.get("journal")
    if isinstance(j, dict) and j.get("disk_bytes"):
        return j["disk_bytes"]
    return None


def _drain_p999(extra: dict) -> float | None:
    """The per-doc admission-to-drain p99.9 of cleanly drained ("ok")
    docs — the obs/ v3 tail-latency headline.  None when the artifact
    predates the block or no ok-tagged doc drained."""
    block = extra.get("doc_drain_latency")
    if not isinstance(block, dict):
        return None
    q = (block.get("ok") or {}).get("quantiles")
    return q.get("p99.9") if isinstance(q, dict) else None


def _slo_worst_violation(extra: dict) -> tuple[float, int] | None:
    """The WORST per-class SLO violation of the run as ``(fraction,
    requests)`` — ``1 - compliance`` maxed over classes with at least
    one request, paired with that class's request count (the blowout
    floor needs a violation COUNT, not just a fraction).  Violations —
    not compliance — are the gated quantity: a relative compliance
    check saturates near 1.0, where a 0.1%% -> 5%% violation blow-up
    (50x the error budget) reads as a 4.9%% compliance dip.  None
    without an ``slo`` block."""
    s = extra.get("slo")
    if not isinstance(s, dict):
        return None
    viols = [
        (1.0 - c["compliance"], c["requests"])
        for c in (s.get("classes") or {}).values()
        if isinstance(c, dict) and c.get("requests")
        and c.get("compliance") is not None
    ]
    return max(viols) if viols else None


#: Violation-fraction changes below this are measurement noise (half a
#: percentage point of requests) — the budget-blowout gate never fires
#: inside it.
_SLO_VIOLATION_NOISE = 0.005

#: ...and never on fewer than this many violating REQUESTS: a fraction
#: floor alone lets one shed doc in a 24-request smoke (1/24 = 4.2%)
#: blow past it against a clean baseline, the exact flake the floor
#: exists to absorb.  3 violations is past single-blip territory at
#: any fleet size.
_SLO_MIN_VIOLATIONS = 3

#: A new violation fraction more than this multiple of the baseline's
#: (beyond the noise floor) fails regardless of the points threshold —
#: the error-budget blow-up a points gate misses near tight objectives.
_SLO_BLOWOUT_RATIO = 10.0


def _slo_check(new: dict, base: dict, threshold_pct: float) -> Check:
    """The SLO gate: worst-class violation growth, one-sided.  Fails
    when violations grew by more than ``threshold_pct`` percentage
    POINTS of requests, or blew past ``_SLO_BLOWOUT_RATIO`` x the
    baseline's violation fraction (beyond the noise floor)."""
    name = "slo compliance floor (violation growth, worst class)"
    nw = _slo_worst_violation(new)
    bw = _slo_worst_violation(base)
    if nw is None or bw is None:
        return Check(name, "skip",
                     note="slo block missing in at least one artifact")
    (nv, n_req), (bv, _) = nw, bw
    points = (nv - bv) * 100.0
    blowout = (
        nv > max(bv * _SLO_BLOWOUT_RATIO, bv + _SLO_VIOLATION_NOISE)
        and nv * n_req >= _SLO_MIN_VIOLATIONS
    )
    status = "fail" if points > threshold_pct or blowout else "pass"
    return Check(name, status, new=nv, base=bv, change_pct=points,
                 threshold_pct=threshold_pct)


def _window_floor(extra: dict) -> float | None:
    """The WORST full time-series window's throughput — a mid-run dip
    the end-of-run average hides.  None when the artifact predates the
    ``timeseries`` block (or carries no full window)."""
    ts = extra.get("timeseries")
    if not isinstance(ts, dict):
        return None
    tputs = [
        w.get("throughput") for w in ts.get("windows", ())
        if isinstance(w, dict) and w.get("full")
        and w.get("throughput") is not None
    ]
    return min(tputs) if tputs else None


def _open_rate(extra: dict) -> float | None:
    """The offered load (ops/round) of an open-loop artifact
    (``--serve-open``); None for closed-loop replay artifacts."""
    ing = extra.get("ingest")
    if not isinstance(ing, dict):
        return None
    return (ing.get("open") or {}).get("rate")


def _block_presence_checks(new: dict, base: dict) -> list[Check]:
    out = []
    for blk in _OPTIONAL_BLOCKS:
        has_new = isinstance(new.get(blk), dict)
        has_base = isinstance(base.get(blk), dict)
        if has_new != has_base:
            where = "newer" if has_new else "baseline"
            out.append(Check(
                f"{blk} block", "skip",
                note=(
                    f"present only in the {where} artifact "
                    "(obs/ v2+v3 schema difference); not compared"
                ),
            ))
    return out


def compare(new: dict, base: dict, *, max_throughput_regress: float,
            max_p99_regress: float, max_journal_regress: float,
            max_syncs_regress: float,
            max_window_floor_regress: float = 30.0,
            max_drain_p999_regress: float = 75.0,
            max_slo_regress: float = 5.0,
            max_recover_regress: float = 75.0,
            max_journal_disk_regress: float = 40.0,
            max_hit_rate_regress: float = 25.0,
            max_construction_regress: float = 60.0,
            max_rss_regress: float = 40.0,
            max_reshard_p99_regress: float = 60.0) -> list[Check]:
    # open-loop artifacts (--serve-open) invert what the headline
    # numbers mean: throughput TRACKS the offered load (the client
    # decides it, not the engine), so gating it is meaningless — the
    # open-loop regression surface is p99 AT A FIXED OFFERED LOAD.
    # Mixed or rate-mismatched pairs skip-with-note instead of
    # comparing incomparable numbers.
    new_rate, base_rate = _open_rate(new), _open_rate(base)
    open_any = new_rate is not None or base_rate is not None
    if open_any:
        tput_check = Check(
            "throughput (patches/s)", "skip",
            note="open-loop artifact: throughput follows the offered "
                 "load, not engine capability — p99 at fixed offered "
                 "load is the gated number",
        )
    else:
        tput_check = _regress(
            "throughput (patches/s)",
            new.get("patches_per_sec"), base.get("patches_per_sec"),
            max_throughput_regress, higher_is_better=True,
        )
    if open_any and new_rate != base_rate:
        p99_check = Check(
            "steady p99 latency (s)", "skip",
            note=f"offered load differs ({new_rate!r} vs "
                 f"{base_rate!r}): open-loop p99 is only comparable "
                 "at a fixed offered load",
        )
    else:
        name = ("steady p99 latency (s, at offered load "
                f"{new_rate:g} ops/round)" if open_any
                else "steady p99 latency (s)")
        p99_check = _regress(
            name,
            (new.get("batch_latency") or {}).get("p99"),
            (base.get("batch_latency") or {}).get("p99"),
            max_p99_regress, higher_is_better=False,
        )
    checks = [
        tput_check,
        p99_check,
        _regress(
            "journal bytes per range op",
            _journal_bytes_per_op(new), _journal_bytes_per_op(base),
            max_journal_regress, higher_is_better=False,
            skip_note="journal disabled in at least one run",
        ),
        _regress(
            "boundary syncs per round",
            _syncs_per_round(new), _syncs_per_round(base),
            max_syncs_regress, higher_is_better=False,
            skip_note="boundary_syncs block missing",
        ),
        # per-window floor: only when BOTH artifacts carry full
        # time-series windows (the looser threshold reflects that a
        # single worst window is noisier than the run mean)
        _regress(
            "window throughput floor (patches-equivalent/s)",
            _window_floor(new), _window_floor(base),
            max_window_floor_regress, higher_is_better=True,
            skip_note="timeseries block missing in at least one "
                      "artifact",
        ),
        # obs/ v3 gates, one-sided like timeseries: the per-doc drain
        # tail and the worst per-class SLO compliance (a looser
        # threshold on p99.9 — a 1-in-1000 quantile is the noisiest
        # number the artifact carries)
        _regress(
            "doc drain p99.9 (s, ok-tagged)",
            _drain_p999(new), _drain_p999(base),
            max_drain_p999_regress, higher_is_better=False,
            skip_note="doc_drain_latency p99.9 missing in at least "
                      "one artifact",
        ),
        _slo_check(new, base, max_slo_regress),
        # durability v2 gates, one-sided like timeseries: the measured
        # recovery-time objective and the on-disk journal footprint at
        # fixed workload — history growth or a slower chain walk fails
        # here before anyone notices a multi-minute recovery in prod
        _regress(
            "recovery time (ms, recover_fleet)",
            _recover_ms(new), _recover_ms(base),
            max_recover_regress, higher_is_better=False,
            skip_note="recovery block missing in at least one artifact",
        ),
        _regress(
            "journal on-disk bytes (segmented WAL after GC)",
            _journal_disk_bytes(new), _journal_disk_bytes(base),
            max_journal_disk_regress, higher_is_better=False,
            skip_note="journal disk footprint missing in at least one "
                      "artifact",
        ),
        # tiered residency, one-sided like timeseries: the warm+
        # prefetch hit rate — a prefetcher that stopped predicting (or
        # a warm tier that started thrashing) fails here before the
        # throughput gate can even see it
        _regress(
            "tier warm+prefetch hit rate",
            _tier_hit_rate(new), _tier_hit_rate(base),
            max_hit_rate_regress, higher_is_better=True,
            skip_note="residency hit rate missing in at least one "
                      "artifact",
        ),
    ]
    # streaming-construction gates: setup wall time + peak RSS (mode
    # mismatch or a pre-block artifact skips-with-note, never errors)
    checks.extend(_construction_checks(
        new, base, max_construction_regress, max_rss_regress))
    # elastic-reconfiguration gate: the mid-reshard tail (kind
    # mismatch or a fixed-map artifact skips-with-note, never errors)
    checks.extend(_reshard_checks(new, base, max_reshard_p99_regress))
    checks.extend(_block_presence_checks(new, base))
    return checks


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="serve-bench regression gate (new vs baseline)"
    )
    ap.add_argument("new", help="fresh serve artifact JSON")
    ap.add_argument("baseline", help="baseline serve artifact JSON")
    ap.add_argument("--max-throughput-regress", type=float, default=10.0,
                    metavar="PCT",
                    help="max tolerated patches/s drop (default 10%%)")
    ap.add_argument("--max-p99-regress", type=float, default=40.0,
                    metavar="PCT",
                    help="max tolerated steady-p99 increase "
                         "(default 40%%: p99 of a ~dozen-round drain "
                         "is the noisiest number here)")
    ap.add_argument("--max-journal-regress", type=float, default=25.0,
                    metavar="PCT",
                    help="max tolerated journal bytes/op increase")
    ap.add_argument("--max-syncs-regress", type=float, default=60.0,
                    metavar="PCT",
                    help="max tolerated fence-entries-per-round "
                         "increase (a new hot-path sync shows up here)")
    ap.add_argument("--max-window-floor-regress", type=float,
                    default=30.0, metavar="PCT",
                    help="max tolerated drop of the worst full "
                         "time-series window's throughput (checked "
                         "only when both artifacts carry a "
                         "timeseries block)")
    ap.add_argument("--max-drain-p999-regress", type=float,
                    default=75.0, metavar="PCT",
                    help="max tolerated increase of the per-doc "
                         "admission-to-drain p99.9 (ok-tagged docs; "
                         "a 1-in-1000 quantile jitters — the default "
                         "is deliberately loose)")
    ap.add_argument("--max-slo-regress", type=float, default=5.0,
                    metavar="PCT",
                    help="max tolerated growth of the worst per-class "
                         "SLO violation fraction, in percentage points "
                         "of requests; a >10x violation blow-up past "
                         "the noise floor fails regardless (checked "
                         "only when both artifacts carry an slo block)")
    ap.add_argument("--max-recover-regress", type=float, default=75.0,
                    metavar="PCT",
                    help="max tolerated recover_fleet wall-time "
                         "increase (recovery block; ms-scale host "
                         "work jitters, the default is loose)")
    ap.add_argument("--max-hit-rate-regress", type=float, default=25.0,
                    metavar="PCT",
                    help="max tolerated drop of the tiered pool's "
                         "warm+prefetch hit rate (checked only when "
                         "both artifacts carry a residency block)")
    ap.add_argument("--max-journal-disk-regress", type=float,
                    default=40.0, metavar="PCT",
                    help="max tolerated growth of the on-disk journal "
                         "footprint at fixed workload (segment GC + "
                         "snapshot pruning keep it O(ops since last "
                         "barrier); unbounded history fails here)")
    ap.add_argument("--max-construction-regress", type=float,
                    default=60.0, metavar="PCT",
                    help="max tolerated fleet-construction wall-time "
                         "increase (construction block; skipped on a "
                         "stream-vs-eager mode mismatch — O(active-"
                         "set) vs O(fleet) setup is incomparable)")
    ap.add_argument("--max-rss-regress", type=float, default=40.0,
                    metavar="PCT",
                    help="max tolerated peak-RSS growth (construction "
                         "block; same mode-mismatch skip as the "
                         "construction-time gate)")
    ap.add_argument("--max-reshard-p99-regress", type=float,
                    default=60.0, metavar="PCT",
                    help="max tolerated increase of the mid-reshard "
                         "round p99 — the rounds served WHILE the "
                         "shard-map change was in flight (reshard "
                         "block; skipped on a shrink-vs-grow kind "
                         "mismatch — the migration-window tails are "
                         "incomparable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    try:
        new = load_serve_extra(args.new)
        base = load_serve_extra(args.baseline)
    except (OSError, ValueError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2

    checks = compare(
        new, base,
        max_throughput_regress=args.max_throughput_regress,
        max_p99_regress=args.max_p99_regress,
        max_journal_regress=args.max_journal_regress,
        max_syncs_regress=args.max_syncs_regress,
        max_window_floor_regress=args.max_window_floor_regress,
        max_drain_p999_regress=args.max_drain_p999_regress,
        max_slo_regress=args.max_slo_regress,
        max_recover_regress=args.max_recover_regress,
        max_journal_disk_regress=args.max_journal_disk_regress,
        max_hit_rate_regress=args.max_hit_rate_regress,
        max_construction_regress=args.max_construction_regress,
        max_rss_regress=args.max_rss_regress,
        max_reshard_p99_regress=args.max_reshard_p99_regress,
    )
    failed = [c for c in checks if c.status == "fail"]
    if args.json:
        print(json.dumps({
            "new": args.new,
            "baseline": args.baseline,
            "checks": [c.__dict__ for c in checks],
            "ok": not failed,
        }, indent=2))
    else:
        print(f"bench_compare: {args.new} vs {args.baseline}")
        for c in checks:
            print("  " + c.line())
        print(
            "bench_compare: "
            + ("OK" if not failed else f"{len(failed)} REGRESSION(S)")
        )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
