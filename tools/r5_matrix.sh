#!/bin/bash
# Round-5 bench matrix (serialized TPU job — ONE tpu client at a time on
# this box).  Finer-grained invocations so a single slow/failing cell
# cannot take out the rest of the matrix; artifacts land incrementally in
# bench_results/{down_r5,merge_traces_r5,merge_adv10m_r5,up_r5*}.json.
# Byte verification runs as separate --verify-only passes at small
# replica counts (identical code paths; every TIMED iteration asserts
# final lengths regardless, the reference's in-loop oracle).
# Run with: nohup bash tools/r5_matrix.sh > /tmp/r5matrix.log 2>&1 &
set -x
cd /root/repo

run() { timeout 3000 python -m crdt_benches_tpu.bench.runner "$@" || true; }

# 1) downstream timed matrix: every wire granularity incl. the round-5
#    one-shot flat engines
run --filter downstream \
    --backends cpp-crdt,jax,jax-range,jax-runs,jax-patch,jax-unitwire \
    --replicas 64 --samples 5 --save-baseline down_r5

# 2) merge cells timed
run --filter merge --backends cpp-crdt,jax,jax-range,jax-flat \
    --merge-configs traces --replicas 64 --samples 5 \
    --save-baseline merge_traces_r5
run --filter merge --backends cpp-crdt,jax,jax-flat \
    --merge-configs adversarial --merge-ops 10000000 \
    --replicas 64 --samples 5 --save-baseline merge_adv10m_r5

# 3) upstream timed matrix, per trace (isolates any OOM at r1024)
for t in automerge-paper sveltecomponent seph-blog1; do
  run --filter upstream --traces "$t" \
      --backends cpp-rope,cpp-crdt,cpp-cola,jax,jax-unit \
      --replicas 1024 --samples 5 --save-baseline "up_r5_$t"
done
# rustcode's unit layout at r1024 exceeds HBM (523k-slot capacity);
# r512 is the committed configuration (same as r3)
run --filter upstream --traces rustcode \
    --backends cpp-rope,cpp-crdt,cpp-cola,jax,jax-unit \
    --replicas 512 --samples 5 --save-baseline up_r5_rustcode

# 4) byte-verification passes (small replicas, same code paths)
run --filter downstream \
    --backends cpp-crdt,jax,jax-range,jax-runs,jax-patch,jax-unitwire \
    --replicas 4 --verify-only
run --filter merge --backends none --merge-configs traces \
    --replicas 4 --verify-only
run --filter merge --backends none --merge-configs adversarial \
    --merge-ops 10000000 --replicas 4 --verify-only
run --filter upstream --backends cpp-rope,cpp-crdt,cpp-cola,jax,jax-unit \
    --replicas 4 --verify-only

# 5) the Criterion-analog HTML report over everything committed
python -m crdt_benches_tpu.bench.report || true
