#!/bin/bash
# Round-4 downstream experiment (serialized TPU job — ONE tpu client at a
# time on this box): per-patch-wire downstream (jax-patch) vs cpp-crdt,
# batch/replica sweep on automerge-paper + rustcode, paired baselines.
# Results land in bench_results/ via the runner's save_results plus this
# log.  Run with: nohup bash tools/r4_down_experiment.sh > /tmp/r4down.log 2>&1 &
set -x
cd /root/repo

run() {  # run one cell matrix with a timeout and keep going on failure
  timeout 2400 python -m crdt_benches_tpu.bench.runner "$@" || true
}

# 1) paired cpp-crdt downstream baselines (the denominator, same run)
run --filter downstream --backends cpp-crdt \
    --traces automerge-paper,rustcode,sveltecomponent,seph-blog1 \
    --samples 5 --save-baseline down_cpp_r4

# 2) jax-patch at r64, default batch 512
run --filter downstream --backends jax-patch \
    --traces automerge-paper,rustcode --replicas 64 \
    --samples 3 --save-baseline down_patch_r64_b512

# 3) batch sweep via env (RunMergeSimulation batch is the backend arg;
#    expose via CRDT_DOWN_RUNS_BATCH)
CRDT_DOWN_RUNS_BATCH=1024 run --filter downstream --backends jax-patch \
    --traces automerge-paper --replicas 64 \
    --samples 3 --save-baseline down_patch_r64_b1024
CRDT_DOWN_RUNS_BATCH=2048 run --filter downstream --backends jax-patch \
    --traces automerge-paper --replicas 64 \
    --samples 3 --save-baseline down_patch_r64_b2048

# 4) replica scaling at the best-known batch
CRDT_DOWN_RUNS_BATCH=1024 run --filter downstream --backends jax-patch \
    --traces automerge-paper --replicas 256 \
    --samples 3 --save-baseline down_patch_r256_b1024

echo DONE_R4_DOWN_EXPERIMENT
