"""Capture a jax.profiler device trace of a few replay chunks and print the
top device ops by total self time.

Usage: python tools/profile_trace.py [R] [B] [trace] [n_chunks]
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

import jax
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data
from crdt_benches_tpu.traces.tensorize import tensorize
from crdt_benches_tpu.engine.replay import (
    ReplayEngine,
    replay_batches_r4,
)


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    trace_name = sys.argv[3] if len(sys.argv) > 3 else "automerge-paper"
    n_chunks = int(sys.argv[4]) if len(sys.argv) > 4 else 4

    trace = load_testing_data(trace_name)
    tt = tensorize(trace, batch=B)
    eng = ReplayEngine(tt, n_replicas=R)
    print(f"R={R} B={B} C={eng.capacity} chunks={len(eng.chunks)}")

    # Warm: run a couple of chunks to compile.
    from crdt_benches_tpu.ops.apply2 import init_state4

    st = init_state4(R, eng.capacity, eng.n_init)
    for kind, pos, slot in eng.chunks[:2]:
        st = replay_batches_r4(
            st, kind, pos, slot, resolver=eng.resolver, pack=eng.pack
        )
    np.asarray(st.nvis)

    logdir = "/tmp/jaxtrace"
    os.system(f"rm -rf {logdir}")
    jax.profiler.start_trace(logdir)
    # Trace chunks 2..2+n (mid-trace, half-grown doc).
    for kind, pos, slot in eng.chunks[2 : 2 + n_chunks]:
        st = replay_batches_r4(
            st, kind, pos, slot, resolver=eng.resolver, pack=eng.pack
        )
    np.asarray(st.nvis)
    jax.profiler.stop_trace()

    files = glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True)
    print(files)
    agg = defaultdict(float)
    cnt = defaultdict(int)
    total = 0.0
    for f in files:
        with gzip.open(f, "rt") as fh:
            data = json.load(fh)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            # device lanes only: pid names like "/device:TPU:0" appear in
            # metadata; keep all complete events with args.long_name or a
            # duration, filter host python by tid name heuristics
            name = ev.get("name", "")
            dur = ev.get("dur", 0) / 1e3  # ms
            cat = ev.get("args", {}) or {}
            if not name or dur <= 0:
                continue
            agg[name] += dur
            cnt[name] += 1
    items = sorted(agg.items(), key=lambda kv: -kv[1])
    print(f"\ntop ops by total time (ms) over {n_chunks} chunks of "
          f"{eng.chunk} batches:")
    for name, ms in items[:40]:
        print(f"  {ms:10.2f} ms  x{cnt[name]:5d}  {name[:110]}")


if __name__ == "__main__":
    main()
