"""Microbenchmark candidate primitives for id -> position resolution.

The merge/downstream integration needs: given B element ids per replica,
find their current physical positions in the packed doc (R, C).  Candidate
building blocks measured here on the real chip (same one-scan-K-iters
methodology as tools/profile.py):

  a) snapshot rebuild, scatter form:   pos_by_slot[doc[p]] = p   (R, C)
  b) snapshot rebuild, argsort form:   argsort of slot keys      (R, C)
  c) stale-position gather (MXU one-hot): pos0 = snap[ids]       (R, B, C)
  d) correction pass: count_le of B queries against a sorted B-dest list
     (B x B compare), K_ring of them
  e) take_along_axis gather (R, B) from (R, C) — the serializing baseline

Usage: python tools/micro_idpos.py [R] [B] [C] [K]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.ops.gather import onehot_gather_vec


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    C = int(sys.argv[3]) if len(sys.argv) > 3 else 294912
    K = int(sys.argv[4]) if len(sys.argv) > 4 else 16
    print(f"R={R} B={B} C={C} K={K}")

    rng = np.random.default_rng(0)
    perm = np.stack([rng.permutation(C) for _ in range(R)]).astype(np.int32)
    doc = jnp.asarray(perm)  # doc[p] = slot
    snap = jnp.asarray(np.argsort(perm, axis=1).astype(np.int32))
    ids = jnp.asarray(rng.integers(0, C, (R, B)), dtype=jnp.int32)
    vals = jnp.asarray(np.arange(C, dtype=np.int32)[None].repeat(R, 0))
    dests = jnp.asarray(
        np.sort(rng.integers(0, C, (R, K, B)), axis=2).astype(np.int32)
    )

    def scan_k(body, init, *args, k=K):
        @jax.jit
        def run(init, *args):
            return jax.lax.scan(
                lambda c, _: (body(c, *args), None), init, None, length=k
            )[0]

        return lambda: run(init, *args)

    base = timeit(scan_k(lambda c: c + 1, jnp.zeros((8, 128))))
    print(f"no-op scan floor:        {base/K*1e3:9.3f} ms/iter")

    # (a) scatter rebuild
    def scat_body(carry, doc, vals):
        snap2 = jax.vmap(
            lambda d, v: jnp.zeros(C, jnp.int32).at[d].set(v)
        )(doc + carry[0, 0].astype(jnp.int32) * 0, vals)
        return carry + snap2[:, :128].astype(jnp.float32) * 0 + 1

    t = (timeit(scan_k(scat_body, jnp.zeros((R, 128)), doc, vals)) - base) / K
    print(f"(a) scatter rebuild:     {t*1e3:9.3f} ms")

    # (b) argsort rebuild
    def sort_body(carry, doc):
        snap2 = jnp.argsort(doc + carry[0, 0].astype(jnp.int32) * 0, axis=1)
        return carry + snap2[:, :128].astype(jnp.float32) * 0 + 1

    t = (timeit(scan_k(sort_body, jnp.zeros((R, 128)), doc)) - base) / K
    print(f"(b) argsort rebuild:     {t*1e3:9.3f} ms")

    # (c) one-hot stale gather (B ids from C)
    def oh_body(carry, snap, ids):
        q = ids + carry[:, :B].astype(jnp.int32) * 0
        p0 = onehot_gather_vec(snap, q, max_value=C)
        return carry + p0.astype(jnp.float32) * 0 + 1

    t = (timeit(scan_k(oh_body, jnp.zeros((R, B)), snap, ids)) - base) / K
    print(f"(c) one-hot gather BxC:  {t*1e3:9.3f} ms")

    # (d) ring correction: K count_le passes of B queries vs sorted B dests
    def ring_body(carry, ids, dests):
        p = ids + carry[:, :B].astype(jnp.int32) * 0
        for k in range(K):
            d = dests[:, k]
            le = (d[:, None, :] <= p[:, :, None]).astype(jnp.int32)
            p = p + jnp.sum(le, axis=2)
        return carry + p.astype(jnp.float32) * 0 + 1

    t = (
        timeit(scan_k(ring_body, jnp.zeros((R, B)), ids, dests, k=4)) - base
    ) / 4
    print(f"(d) {K}-deep ring corr:  {t*1e3:9.3f} ms")

    # (e) take_along_axis gather
    def taa_body(carry, snap, ids):
        q = ids + carry[:, :B].astype(jnp.int32) * 0
        p0 = jnp.take_along_axis(snap, q, axis=1)
        return carry + p0.astype(jnp.float32) * 0 + 1

    t = (timeit(scan_k(taa_body, jnp.zeros((R, B)), snap, ids)) - base) / K
    print(f"(e) take_along_axis:     {t*1e3:9.3f} ms")


if __name__ == "__main__":
    main()
