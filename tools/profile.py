"""One profiling entry point for the hot paths (replaces the per-round
profile_* script generations; git history preserves the retired ones).

Subcommands:
  hotpath     unit-op replay path: resolver / apply_batch{3,4} / sub-pieces
  range       fused range path: staged apply_range_batch4 pipeline deltas
  downstream  run/patch downstream apply: fragments / query / apply5 / spreads
  trace       jax.profiler device trace of a few replay chunks, top ops

Methodology (all subcommands): every dispatch on this runtime costs ~25ms
round trip, so each component is timed as K iterations inside ONE jitted
lax.scan, subtracting a no-op scan of the same length; sync is by value
fetch.  Run on the real chip.

Usage: python tools/profile.py <subcommand> [R] [B] [trace] [K] [extra]
"""

from __future__ import annotations

import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data  # noqa: E402


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=5, warmup=2):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def scan_k(body, init, K):
    @jax.jit
    def run(init):
        return jax.lax.scan(body, init, None, length=K)[0]

    return lambda: run(init)


def noop_floor(K):
    return timeit(scan_k(lambda c, _: (c + 1, None), jnp.zeros((8, 128)), K))


# --------------------------------------------------------------------------
# hotpath: the unit-op replay path
# --------------------------------------------------------------------------


def cmd_hotpath(args):
    from crdt_benches_tpu.engine.replay import ReplayEngine
    from crdt_benches_tpu.ops.resolve_pallas import resolve_batch_pallas
    from crdt_benches_tpu.traces.tensorize import tensorize

    R, B, K = args.R, args.B, args.K
    trace = load_testing_data(args.trace)
    tt = tensorize(trace, batch=B)
    eng = ReplayEngine(tt, n_replicas=R)
    C = eng.capacity
    n_ops = len(trace)
    print(f"R={R} B={B} C={C} n_batches={tt.n_batches} trace={args.trace} K={K}")

    mid = tt.n_batches // 2
    kind_b, pos_b, _, slot_b = tt.batched()
    kind = jnp.asarray(kind_b[mid])
    pos = jnp.asarray(pos_b[mid])
    slot = jnp.asarray(slot_b[mid])
    v0 = jnp.full((R,), int(pos_b[mid].max()) + 1, jnp.int32)

    base = noop_floor(K)
    print(f"no-op scan floor:      {base/K*1e3:8.3f} ms/iter")

    def res_body(carry, _):
        r = resolve_batch_pallas(kind, pos, carry, emit_origin=False)
        return carry + r.del_rank[:, 0] * 0 + r.ins_gvis[:, -1] * 0, None

    t = (timeit(scan_k(res_body, v0, K)) - base) / K
    print(f"resolver+extract:      {t*1e3:8.3f} ms/batch"
          f"  -> {t/B*1e9/R:8.1f} ns/op/replica")

    from crdt_benches_tpu.ops.apply2 import (
        _mxu_spread,
        apply_batch3,
        apply_batch4,
        init_state3,
        init_state4,
        rank_to_phys2,
    )

    resolved = jax.tree.map(
        jnp.asarray, resolve_batch_pallas(kind, pos, v0, emit_origin=False)
    )
    st40 = init_state4(R, C, 0)

    def ap4_body(st, _):
        return apply_batch4(st, resolved, slot), None

    t = (timeit(scan_k(ap4_body, st40, K)) - base) / K
    print(f"apply_batch4:          {t*1e3:8.3f} ms/batch"
          f"  -> {t/B*1e9/R:8.1f} ns/op/replica")

    st0 = init_state3(R, C, 0)

    def ap_body(st, _):
        return apply_batch3(st, resolved, slot), None

    t = (timeit(scan_k(ap_body, st0, K)) - base) / K
    print(f"apply_batch3:          {t*1e3:8.3f} ms/batch"
          f"  -> {t/B*1e9/R:8.1f} ns/op/replica")

    # sub-pieces
    from crdt_benches_tpu.ops.expand_pallas import expand_packed

    cumvis = jnp.cumsum(jnp.bitwise_and(st0.doc, 1), axis=1)
    q = jnp.clip(resolved.del_rank, 0, None)

    def cv_body(carry, _):
        c = jnp.cumsum(jnp.bitwise_and(carry, 1), axis=1)
        return carry + (c[:, -1:] * 0), None

    t = (timeit(scan_k(cv_body, st0.doc, K)) - base) / K
    print(f"  cumsum (R,C):        {t*1e3:8.3f} ms")

    def rp_body(carry, _):
        r = rank_to_phys2(cumvis, q + carry[:, :1] * 0)
        return carry + r[:, :1] * 0, None

    t = (timeit(scan_k(rp_body, q, K)) - base) / K
    print(f"  rank_to_phys2 x1:    {t*1e3:8.3f} ms")

    def mx_body(carry, _):
        (o,) = _mxu_spread(q, [carry * 0 + 1], C)
        return carry + o[:, :1] * 0, None

    t = (timeit(scan_k(mx_body, q, K)) - base) / K
    print(f"  mxu_spread 1chunk:   {t*1e3:8.3f} ms")

    cntind = jnp.cumsum(
        jnp.zeros((R, C), jnp.int32).at[:, ::357].set(1), axis=1
    )

    def ex_body(carry, _):
        return expand_packed(carry, cntind, nbits=10), None

    t = (timeit(scan_k(ex_body, st0.doc, K)) - base) / K
    print(f"  expand_packed:       {t*1e3:8.3f} ms")

    def full():
        s = eng.run()
        return s.nvis

    t = timeit(full, n=3, warmup=1)
    eps = n_ops * R / t
    print(f"full replay:           {t:8.3f} s"
          f"  -> {t/n_ops*1e9/R:8.1f} ns/op/replica"
          f"  -> aggregate {eps/1e6:.2f}M el/s")


# --------------------------------------------------------------------------
# range: staged deltas through the CURRENT apply_range_batch4 pipeline
# --------------------------------------------------------------------------


def _range_staged(state, tokens, dints, nbits, stage, interpret=False):
    """Truncated replica of ops/apply_range_fused.apply_range_batch4:
    stage 0 = token extract + rank queries, 1 = + delete-boundary spread,
    2 = + insert-run/delta spreads, 3 = + fused kernel (stage 3 returns
    the full (doc, cv, vt, length2) outputs).  Lockstep with the real
    function is enforced by tests/test_profile_staged.py — stage 3 must
    reproduce apply_range_batch4 bit-exactly (the r4 profilers rotted
    against signature changes precisely because nothing checked them)."""
    from crdt_benches_tpu.ops.apply2 import (
        _excl_cumsum_small,
        _mxu_spread,
        count_le_two_level,
    )
    from crdt_benches_tpu.ops.apply_range import (
        _prev_value,
        extract_range_tokens,
    )
    from crdt_benches_tpu.ops.apply_range_fused import (
        _del_stop_shift,
        range_fused,
    )

    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    R, C = state.doc.shape
    B = dlo.shape[1]
    drop = jnp.int32(C + 7)

    tile_base = _excl_cumsum_small(state.vis_tile)
    tmax_abs = tile_base + state.vis_tile
    has_del = dlo >= 0
    live, gvis, cumlen = extract_range_tokens(
        ttype, ta, tch, tlen, v0=state.nvis
    )
    allq = count_le_two_level(
        state.cv_intile, tile_base, tmax_abs,
        jnp.concatenate(
            [jnp.where(has_del, dlo, 0), jnp.where(has_del, dhi, 0),
             jnp.where(live, gvis, 0)], axis=1,
        ),
    )
    lo_phys = allq[:, :B]
    hi_phys = allq[:, B : 2 * B]
    gq_phys = allq[:, 2 * B :]
    if stage == 0:
        return jnp.sum(allq, axis=1, keepdims=True)

    at_end = gvis >= state.nvis[:, None]
    g_phys = jnp.where(at_end, state.length[:, None], gq_phys)
    dest0 = jnp.where(live, g_phys + cumlen, drop)
    dstop = jnp.where(live, dest0 + tlen, drop)

    dsh = _del_stop_shift(B)
    idxA = jnp.concatenate(
        [jnp.where(has_del, lo_phys, drop),
         jnp.where(has_del, hi_phys + 1, drop)], axis=1
    )
    pm = has_del.astype(jnp.int32)
    (delpk,) = _mxu_spread(
        idxA, [jnp.concatenate([pm, pm * (1 << dsh)], axis=1)], C, cb=4096
    )
    if stage == 1:
        return jnp.sum(delpk, axis=1, keepdims=True)

    lv = live.astype(jnp.int32)
    (ind_d,) = _mxu_spread(
        jnp.concatenate([dest0, dstop], axis=1),
        [jnp.concatenate([lv, -lv], axis=1)], C, cb=4096,
    )
    delta = jnp.where(live, ta + tch - dest0, 0)
    ddelta = jnp.where(live, delta - _prev_value(delta, live), 0)
    sgn = jnp.where(ddelta < 0, -1, 1)
    mag = jnp.abs(ddelta)
    lvl = lambda k: sgn * jnp.left_shift(
        jnp.bitwise_and(jnp.right_shift(mag, 7 * k), 127), 7 * k
    )
    (dd,) = _mxu_spread(
        jnp.concatenate([dest0, dest0, dest0], axis=1),
        [jnp.concatenate([lvl(0), lvl(1), lvl(2)], axis=1)], C, cb=4096,
    )
    if stage == 2:
        return jnp.sum(delpk + ind_d + dd, axis=1, keepdims=True)

    n_ins = jnp.sum(jnp.where(live, tlen, 0), axis=1)
    length2 = state.length + n_ins
    doc, cv, vt = range_fused(
        state.doc, delpk, ind_d, dd, length2, nbits=nbits, dsh=dsh,
        interpret=interpret,
    )
    return doc, cv, vt, length2


def cmd_range(args):
    from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
    from crdt_benches_tpu.ops.apply2 import PackedState4, init_state4
    from crdt_benches_tpu.ops.resolve_range_pallas import (
        resolve_range_pallas,
    )
    from crdt_benches_tpu.traces.tensorize import (
        coalesce_patches,
        tensorize_ranges,
    )

    R, B, K = args.R, args.B, args.K
    trace = load_testing_data(args.trace)
    if args.coalesce:
        rt = tensorize_ranges(trace, batch=B, coalesce=True,
                              patches=list(coalesce_patches(trace)))
    else:
        rt = tensorize_ranges(trace, batch=B)
    eng = RangeReplayEngine(rt, n_replicas=R)
    C = eng.capacity
    nb = rt.n_batches
    print(f"R={R} B={B} C={C} n_batches={nb} nbits={eng.nbits}"
          f" coalesce={args.coalesce} K={K} engine={eng.engine}")

    mid = nb // 2
    kind_b, pos_b, rlen_b, slot0_b = rt.batched()
    kind = jnp.asarray(kind_b[mid])
    pos = jnp.asarray(pos_b[mid])
    rlen = jnp.asarray(rlen_b[mid])
    slot0 = jnp.asarray(slot0_b[mid])
    v0 = jnp.full((R,), int(pos_b[mid].max()) + 1, jnp.int32)
    tcap = eng.token_caps[min(mid // eng.chunk, len(eng.token_caps) - 1)]

    st = init_state4(R, C, C // 2)
    tokens, dints, _ = jax.jit(
        functools.partial(resolve_range_pallas, token_cap=tcap)
    )(kind, pos, rlen, slot0, v0)
    print("T =", tokens[0].shape[1])

    base = noop_floor(K)
    print(f"floor: {base/K*1e3:.3f} ms/iter")

    def res_body(c, _):
        tk, di, nu = resolve_range_pallas(
            kind, pos, rlen, slot0, c * 0 + v0, token_cap=tcap
        )
        return jnp.minimum(c, nu[:, 0]), None

    t = (timeit(scan_k(res_body, v0, K)) - base) / K
    print(f"{'resolver':26s} {t*1e3:9.3f} ms")

    def make(stage):
        @jax.jit
        def run(doc, cv, vt, length, nvis, tokens, dints):
            def b(c, _):
                z = jnp.where(c == jnp.int32(-123456789), 1, 0)
                stt = PackedState4(doc + z, cv, vt, length, nvis)
                out = _range_staged(stt, tokens, dints, eng.nbits, stage)
                if stage == 3:
                    d, _cv, vtile, _l2 = out
                    out = jnp.sum(d, axis=1, keepdims=True) + vtile[:, -1:]
                return jnp.minimum(c, out), None
            return jax.lax.scan(b, doc[:, :1], None, length=K)[0]
        return lambda: run(st.doc, st.cv_intile, st.vis_tile, st.length,
                           st.nvis, tokens, dints)

    names = ["0 extract+queries", "1 + spread A (del)",
             "2 + spread B (ind/dd)", "3 + fused kernel"]
    prev = 0.0
    for stage, name in enumerate(names):
        t = (timeit(make(stage)) - base) / K
        print(f"{name:26s} {t*1e3:9.3f} ms  (+{(t-prev)*1e3:8.3f})")
        prev = t


# --------------------------------------------------------------------------
# downstream: run/patch downstream apply path
# --------------------------------------------------------------------------


def cmd_downstream(args):
    from crdt_benches_tpu.engine.downstream import down_packed_init
    from crdt_benches_tpu.engine.downstream_range import (
        _apply_range_update_batch5,
    )
    from crdt_benches_tpu.engine.merge import MergeSimulation
    from crdt_benches_tpu.engine.merge_range import (
        BIGKEY,
        RunMergeSimulation,
        _run_batch_fragments,
    )
    from crdt_benches_tpu.ops.idpos import (
        make_level_runs,
        query,
        snap_rebuild,
    )
    from crdt_benches_tpu.traces.tensorize import tensorize

    R, W, K, EPOCH = args.R, args.B, args.K, args.epoch
    trace = load_testing_data(args.trace)
    tt = tensorize(trace, batch=512)
    sim = MergeSimulation([tt], base=trace.start_content, batch=W)
    ps = np.zeros(tt.n_ops, bool)
    u = 0
    for _pos, d, ins in trace.iter_patches():
        ps[u] = True
        u += d + len(ins)
    rm = RunMergeSimulation(sim, batch=W, epoch=EPOCH, patch_starts=[ps])
    C = sim.capacity
    nb = len(rm.lamport) // W
    print(f"R={R} W={W} C={C} n_runs={rm.n_runs} n_batches={nb}"
          f" nbits={rm.nbits} epoch={EPOCH} trace={args.trace} K={K}")

    mid = nb // 2
    sl = slice(mid * W, (mid + 1) * W)
    lam = jnp.asarray(rm.lamport[sl])
    ag = jnp.asarray(rm.agent[sl])
    s0 = jnp.asarray(rm.slot0[sl])
    rl = jnp.asarray(rm.rlen[sl])
    orig = jnp.asarray(rm.origin[sl])
    key = jnp.where(rl > 0, lam * 1024 + ag, BIGKEY)

    st = down_packed_init(R, C, C // 2)
    snap = st.snap
    neg1 = jnp.full((W,), -1, jnp.int32)

    base = noop_floor(K)
    print(f"no-op scan floor:        {base/K*1e3:8.3f} ms/iter")

    def frag_body(carry, _):
        fa, fr, fs, fl = _run_batch_fragments(key, s0, rl, orig + carry * 0)
        return carry + fa[0] * 0 + fr[-1] * 0 + fs[0] * 0 + fl[0] * 0, None

    t = (timeit(scan_k(frag_body, jnp.int32(0), K)) - base) / K
    print(f"_run_batch_fragments:    {t*1e3:8.3f} ms/batch")

    fa, fr, fs, fl = jax.jit(_run_batch_fragments)(key, s0, rl, orig)
    bc = lambda x: jnp.broadcast_to(x[None], (R,) + x.shape)
    lvl = jax.jit(make_level_runs)(
        bc(jnp.abs(fa) % C), bc(fl), bc(jnp.maximum(fs, 0)), bc(fl > 0)
    )
    ids = bc(jnp.concatenate([jnp.maximum(fa, 0)] * 3))[:, : 3 * W]

    for L in (0, EPOCH // 2, EPOCH - 1):
        levels = [lvl] * L

        def q_body(carry, _):
            p = query(snap, levels, ids + carry[:, :1] * 0)
            return carry + p[:, :1] * 0, None

        t = (timeit(scan_k(q_body, ids, K)) - base) / K
        print(f"query {L:2d} levels (3W):   {t*1e3:8.3f} ms/batch")

    def sr_body(carry, _):
        s = snap_rebuild(st.doc + carry[:, :1] * 0)
        return carry + s[:, :1] * 0, None

    t = (timeit(scan_k(sr_body, snap, K)) - base) / K
    print(f"snap_rebuild:            {t*1e3:8.3f} ms   (1 per epoch)")

    for L in (0, EPOCH // 2, EPOCH - 1):
        levels = [lvl] * L

        def ap_body(carry, _):
            doc, length, nvis = carry
            doc, length, nvis, _lv = _apply_range_update_batch5(
                doc, length, nvis, snap, levels,
                fa, fr, fs, fl, jnp.ones_like(fa),
                jnp.concatenate([neg1, neg1]),
                jnp.concatenate([neg1, neg1]),
                nbits=rm.nbits,
            )
            return (doc, length, nvis), None

        t = (
            timeit(scan_k(ap_body, (st.doc, st.length, st.nvis), K)) - base
        ) / K
        print(f"apply5 {L:2d} levels:       {t*1e3:8.3f} ms/batch")

    from crdt_benches_tpu.ops.apply2 import _mxu_spread

    dest0 = jnp.broadcast_to(
        (jnp.arange(2 * W, dtype=jnp.int32) * 37) % C, (R, 2 * W)
    )
    ones = jnp.ones((R, 2 * W), jnp.int32)

    def sp_body(carry, _):
        (s1,) = _mxu_spread(dest0 + carry[:, :1] * 0, [ones], C)
        (s2,) = _mxu_spread(dest0 + 1, [ones], C)
        ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
        return carry + ind[:, :1] * 0, None

    t = (timeit(scan_k(sp_body, dest0, K)) - base) / K
    print(f"2 spreads + cumsum:      {t*1e3:8.3f} ms/batch")

    from crdt_benches_tpu.ops.expand_pallas import fused_apply_nocv_dispatch

    combo = jnp.zeros((R, C), jnp.int32).at[:, ::357].set(5)
    cnt_base = jnp.cumsum(
        jnp.sum(combo.reshape(R, C // 128, 128) & 1, axis=2), axis=1
    )
    cnt_base = cnt_base - cnt_base[:, :1]

    def fx_body(carry, _):
        d = fused_apply_nocv_dispatch(
            carry, combo, cnt_base, st.length, nbits=rm.nbits
        )
        return d, None

    t = (timeit(scan_k(fx_body, st.doc, K)) - base) / K
    print(f"fused expand+fill:       {t*1e3:8.3f} ms/batch")

    allkey = jnp.asarray(
        np.where(rm.rlen > 0, rm.lamport * 1024 + rm.agent, 2**31 - 1)
    )

    def srt_body(carry, _):
        p = jnp.argsort(allkey + carry[0] * 0)
        return carry + p[:1] * 0, None

    t = (timeit(scan_k(srt_body, jnp.zeros(8, jnp.int32), K)) - base) / K
    print(f"wire argsort (n_runs):   {t*1e3:8.3f} ms   (1 per merge)")


# --------------------------------------------------------------------------
# trace: jax.profiler device trace -> top ops
# --------------------------------------------------------------------------


def cmd_trace(args):
    import glob
    import gzip
    import json
    import os
    from collections import defaultdict

    from crdt_benches_tpu.engine.replay import (
        ReplayEngine,
        replay_batches_r4,
    )
    from crdt_benches_tpu.ops.apply2 import init_state4
    from crdt_benches_tpu.traces.tensorize import tensorize

    R, B, n_chunks = args.R, args.B, args.K
    trace = load_testing_data(args.trace)
    tt = tensorize(trace, batch=B)
    eng = ReplayEngine(tt, n_replicas=R)
    print(f"R={R} B={B} C={eng.capacity} chunks={len(eng.chunks)}")

    st = init_state4(R, eng.capacity, eng.n_init)
    for kind, pos, slot in eng.chunks[:2]:
        st = replay_batches_r4(
            st, kind, pos, slot, resolver=eng.resolver, pack=eng.pack
        )
    np.asarray(st.nvis)

    logdir = "/tmp/jaxtrace"
    os.system(f"rm -rf {logdir}")
    jax.profiler.start_trace(logdir)
    for kind, pos, slot in eng.chunks[2 : 2 + n_chunks]:
        st = replay_batches_r4(
            st, kind, pos, slot, resolver=eng.resolver, pack=eng.pack
        )
    np.asarray(st.nvis)
    jax.profiler.stop_trace()

    files = glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True)
    print(files)
    agg = defaultdict(float)
    cnt = defaultdict(int)
    for f in files:
        with gzip.open(f, "rt") as fh:
            data = json.load(fh)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            name = ev.get("name", "")
            dur = ev.get("dur", 0) / 1e3  # ms
            if not name or dur <= 0:
                continue
            agg[name] += dur
            cnt[name] += 1
    items = sorted(agg.items(), key=lambda kv: -kv[1])
    print(f"\ntop ops by total time (ms) over {n_chunks} chunks of "
          f"{eng.chunk} batches:")
    for name, ms in items[:40]:
        print(f"  {ms:10.2f} ms  x{cnt[name]:5d}  {name[:110]}")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    defaults = {
        "hotpath": (128, 512, 32),
        "range": (1024, 512, 8),
        "downstream": (64, 512, 16),
        "trace": (128, 512, 4),
    }
    for name, (dR, dB, dK) in defaults.items():
        p = sub.add_parser(name)
        p.add_argument("R", nargs="?", type=int, default=dR)
        p.add_argument("B", nargs="?", type=int, default=dB,
                       help="op batch (W for downstream)")
        p.add_argument("trace", nargs="?", default="automerge-paper")
        p.add_argument("K", nargs="?", type=int, default=dK,
                       help="iters per scan (chunks for trace)")
        if name == "range":
            p.add_argument("coalesce", nargs="?", type=int, default=1)
        if name == "downstream":
            p.add_argument("epoch", nargs="?", type=int, default=8)
    args = ap.parse_args()
    {"hotpath": cmd_hotpath, "range": cmd_range,
     "downstream": cmd_downstream, "trace": cmd_trace}[args.cmd](args)


if __name__ == "__main__":
    main()
