#!/usr/bin/env bash
# The lint gate: graftlint (JAX hygiene G001-G013 + thread-confinement
# G014-G017 + crash-consistency G018-G020 + lifecycle & ownership
# G022-G025; the artifact-driven cross-checks G011/G017/G021/G025 run
# in the bench smoke) + ruff (when installed).  Exits NONZERO on any
# finding — CI and the tier-1 gate both call this before running a
# single test.
#
# Usage:
#   tools/lint.sh                 # lint the shipped tree (the CI gate)
#   tools/lint.sh path [path...]  # lint specific files/dirs (fixtures,
#                                 # pre-commit partial runs)
#
# Suppression escape hatch (reviewed, never drive-by): a trailing
#   # graftlint: disable=G00X
# silences one rule on one line; `# graftlint: disable-file=G00X`
# anywhere in a file silences it file-wide.  Ruff uses its own
# `# noqa: <code>`.
#
# graftlint is pure stdlib-ast (no jax import): the whole gate runs in
# well under 10s.
set -euo pipefail
cd "$(dirname "$0")/.."

targets=("$@")
if [ ${#targets[@]} -eq 0 ]; then
  # tests/ is part of the gate too (lint_fixtures/ is pruned by the
  # walker — the corpus is intentionally dirty)
  targets=(crdt_benches_tpu tools tests)
fi

python -m crdt_benches_tpu.lint "${targets[@]}"

# ruff (pyflakes + isort + pycodestyle subset, pinned in ruff.toml) is
# part of the gate wherever it is installed; this container image does
# not bake it in, so its absence is a skip, not a failure.
if command -v ruff >/dev/null 2>&1; then
  ruff check "${targets[@]}"
elif python -c "import ruff" >/dev/null 2>&1; then
  python -m ruff check "${targets[@]}"
else
  echo "lint.sh: ruff not installed — skipping (graftlint gate still applied)" >&2
fi
