"""Piece-wise TPU profile of the RANGE replay hot path (the headline).

Times resolve_range_pallas and each component of apply_range_batch as K
iterations inside one jitted lax.scan minus a no-op scan baseline
(tools/profile_hotpath.py pattern — dispatch costs ~25ms round trip on
this runtime, sync by value fetch).

Usage: python tools/profile_range.py [R] [B] [trace] [K]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data
from crdt_benches_tpu.traces.tensorize import tensorize_ranges
from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
from crdt_benches_tpu.ops.resolve_range_pallas import resolve_range_pallas
from crdt_benches_tpu.ops.apply_range import (
    _two_level_vis,
    apply_range_batch,
    extract_range_tokens,
)
from crdt_benches_tpu.ops.apply2 import (
    LANE,
    _mxu_spread,
    count_le_two_level,
    init_state3,
)


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=5, warmup=2):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    trace_name = sys.argv[3] if len(sys.argv) > 3 else "automerge-paper"
    K = int(sys.argv[4]) if len(sys.argv) > 4 else 8

    trace = load_testing_data(trace_name)
    rt = tensorize_ranges(trace, batch=B)
    eng = RangeReplayEngine(rt, n_replicas=R)
    C = eng.capacity
    nb = rt.n_batches
    print(
        f"R={R} B={B} C={C} n_batches={nb} nbits={eng.nbits}"
        f" trace={trace_name} K={K} token_caps={eng.token_caps}"
    )

    mid = nb // 2
    kind_b, pos_b, rlen_b, slot0_b = rt.batched()
    kind = jnp.asarray(kind_b[mid])
    pos = jnp.asarray(pos_b[mid])
    rlen = jnp.asarray(rlen_b[mid])
    slot0 = jnp.asarray(slot0_b[mid])
    v0 = jnp.full((R,), int(pos_b[mid].max()) + 1, jnp.int32)
    tcap = eng.token_caps[min(mid // eng.chunk, len(eng.token_caps) - 1)]

    # a half-full doc
    st = init_state3(R, C, C // 2)

    def scan_k(body, init):
        @jax.jit
        def run(init):
            return jax.lax.scan(body, init, None, length=K)[0]

        return lambda: run(init)

    base = timeit(scan_k(lambda c, _: (c + 1, None), jnp.zeros((8, 128))))
    print(f"no-op scan floor:       {base/K*1e3:8.3f} ms/iter")

    # --- range resolver kernel ---
    def res_body(carry, _):
        tokens, dints, nused = resolve_range_pallas(
            kind, pos, rlen, carry, token_cap=tcap
        )
        return carry + tokens[0][:, :1].reshape(-1) * 0 + nused[:, 0] * 0, None

    t = (timeit(scan_k(res_body, v0)) - base) / K
    print(f"range resolver:         {t*1e3:8.3f} ms/batch")

    # --- full apply ---
    tokens, dints, _ = jax.jit(
        lambda k, p, r, v: resolve_range_pallas(k, p, r, v, token_cap=tcap)
    )(kind, pos, rlen, v0)
    tokens = jax.tree.map(jnp.asarray, tokens)
    dints = jax.tree.map(jnp.asarray, dints)

    def ap_body(stc, _):
        return apply_range_batch(stc, tokens, dints, slot0, nbits=eng.nbits), None

    t_ap = (timeit(scan_k(ap_body, st)) - base) / K
    print(f"apply_range_batch:      {t_ap*1e3:8.3f} ms/batch")

    # --- apply pieces ---
    # 1. two-level vis recompute
    def tv_body(carry, _):
        cvt, tb, tm = _two_level_vis(carry, st.length)
        return carry + tm[:, :1] * 0, None

    t = (timeit(scan_k(tv_body, st.doc)) - base) / K
    print(f"  _two_level_vis:       {t*1e3:8.3f} ms")

    # 2. the fused count_le query (2B + T queries)
    cvt, tile_base, tmax_abs = jax.jit(_two_level_vis)(st.doc, st.length)
    T = tokens[0].shape[1]
    q = jnp.broadcast_to(
        (jnp.arange(2 * B + T, dtype=jnp.int32) * 91) % (C // 2), (R, 2 * B + T)
    )

    def cq_body(carry, _):
        r = count_le_two_level(cvt, tile_base, tmax_abs, q + carry[:, :1] * 0)
        return carry + r[:, :1] * 0, None

    t = (timeit(scan_k(cq_body, q)) - base) / K
    print(f"  count_le (2B+T q):    {t*1e3:8.3f} ms")

    # 3. extract_range_tokens (token-axis passes)
    def ex_body(carry, _):
        live, gvis, cumlen = extract_range_tokens(
            tokens[0], tokens[1], tokens[2], tokens[3] + carry[:, :1] * 0,
            v0=st.nvis,
        )
        return carry + cumlen[:, :1] * 0, None

    t = (timeit(scan_k(ex_body, tokens[3])) - base) / K
    print(f"  extract_tokens:       {t*1e3:8.3f} ms")

    # 4. interval spreads: 2 x (R, B) + 2 x (R, T) one-hot spreads + cumsums
    qb = jnp.broadcast_to(
        (jnp.arange(B, dtype=jnp.int32) * 197) % (C // 2), (R, B)
    )
    ones_b = jnp.ones((R, B), jnp.int32)

    def sp_body(carry, _):
        (s1,) = _mxu_spread(qb + carry[:, :1] * 0, [ones_b], C)
        (s2,) = _mxu_spread(qb + 3, [ones_b], C)
        ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
        return carry + ind[:, :1] * 0, None

    t = (timeit(scan_k(sp_body, qb)) - base) / K
    print(f"  2 B-spreads + cumsum: {t*1e3:8.3f} ms")

    # 5. the 6-chunk delta spread (R, T) + delta cumsum
    qt = jnp.broadcast_to(
        (jnp.arange(T, dtype=jnp.int32) * 137) % (C // 2), (R, T)
    )
    ones_t = jnp.ones((R, T), jnp.int32)

    def d6_body(carry, _):
        outs = _mxu_spread(qt + carry[:, :1] * 0, [ones_t] * 6, C)
        dd = outs[0] + outs[1] - outs[2] + outs[3] - outs[4] + outs[5]
        dc = jnp.cumsum(dd, axis=1)
        return carry + dc[:, :1] * 0, None

    t = (timeit(scan_k(d6_body, qt)) - base) / K
    print(f"  6-chunk T-spread+cum: {t*1e3:8.3f} ms")

    # 6. expansion kernel
    from crdt_benches_tpu.ops.expand_pallas import expand_packed

    cntind = jnp.cumsum(
        jnp.zeros((R, C), jnp.int32).at[:, :: max(C // B, 1)].set(2), axis=1
    ) | jnp.zeros((R, C), jnp.int32).at[:, :: max(C // B, 1)].set(1)

    def xp_body(carry, _):
        d = expand_packed(carry, cntind, nbits=eng.nbits)
        return d, None

    t = (timeit(scan_k(xp_body, st.doc)) - base) / K
    print(f"  expand_packed:        {t*1e3:8.3f} ms (nbits={eng.nbits})")


if __name__ == "__main__":
    main()
