#!/usr/bin/env bash
# Sub-minute bench smoke for CI, runnable alongside tools/tier1.sh.
#
# Usage: tools/bench_smoke.sh [--family serve|serve-repl|serve-faults|serve-soak|serve-longhaul|serve-tier|serve-stream|serve-open|serve-reshard]   (repo root)
#
# The serve family (the default) drains a tiny document fleet through the
# macro-round engine (K=4) on host CPU and exits NONZERO when the in-run
# oracle byte-verification fails (`verify_ok: false`) — the runner's exit
# code carries the gate, so a correctness regression in the serving hot
# path fails CI even when every unit test was green.
#
# The serve-faults family is the CHAOS smoke: the same tiny fleet drained
# under a seeded FaultPlan (spool corruption, mid-macro device-state
# loss, queue-overflow burst, duplicated batch, host stall) with the
# write-ahead journal + snapshot barriers enabled, and the soak anomaly
# detectors armed so the injected stall must trip the stuck-round
# watchdog AND clear on recovery.  It exits NONZERO when the byte-verify
# fails, any injected fault goes unfired/unrecovered, or an anomaly is
# still active at drain end — recovery itself is the thing under test.
#
# The serve-soak family runs ~30s of back-to-back drains with the live
# status server + time-series stream armed, scrapes /healthz +
# /status.json + /metrics mid-run, and fails on any scrape error or any
# anomaly at all.
#
# The serve-tier family is the TIERED-RESIDENCY smoke: a fleet many
# times its device-row budget (--serve-tiers hot=14,warm=6 against 40
# docs) drained race-sanitized with the async prefetch thread live and
# both tier chaos kinds armed (forced warm-tier churn + dropped
# prefetch batches), gated by bench_compare against the committed
# bench_results/serve_tier_baseline.json (throughput + the warm/
# prefetch hit rate) and by G017 against the prefetch publish surface.
# It exits NONZERO on a verify failure, an unfired/unrecovered tier
# fault, a missing residency/hit-rate block, or an undeclared
# cross-thread handoff.
#
# The serve-longhaul family is the DURABILITY smoke (durability v2): a
# short longhaul drain (journal + delta snapshot chains + segmented WAL
# with GC) ending in a measured recovery leg, gated against the
# committed bench_results/serve_longhaul_baseline.json on recover_ms
# and on-disk journal bytes — then a second leg under
# CRDT_BENCH_SANITIZE_RACES=1 with an INJECTED CRASH plus the
# crash-during-compaction and delta-chain-corruption chaos kinds:
# recover_fleet must restore from the surviving chain, resume the redo
# tail, and byte-verify against the oracle (the runner's exit code
# carries the gate) — then the graftlint v4 crash-consistency legs: a
# 12-doc drain under CRDT_BENCH_SANITIZE_FS=1 (fs ops attributed to
# their declared durable protocols, G019 orderings enforced live, the
# G021 cross-check green in both directions against the emitted fs_ops
# block) — then the graftlint v5 lifecycle legs: a churn-heavy
# record-evict streaming drain under CRDT_BENCH_SANITIZE_LIFECYCLE=1
# (keyed residency edges + ownership checked live, the G025
# cross-check green in both directions against the emitted lifecycle
# block) and the lifecheck zero-leak headline (every declared machine
# exercised, zero unreleased acquisitions at drain end) — then the
# graftlint v6 value-range legs: a drain under
# CRDT_BENCH_SANITIZE_RANGES=1 (staged index/narrow-lane/PAD bounds
# validated live on the host tensors, the G029 cross-check green in
# both directions against the emitted ranges block) and the
# dtype-edge adversarial headline (edgecheck --small: the structural
# edge fleet through BOTH kernels, oracle- and cross-kernel
# byte-identical, every boundary contract fuzz-rejected at its dtype
# edges) — and finally the exhaustive crash-point enumeration harness
# (a crash at EVERY mutating fs-op boundary must recover
# byte-verified).
#
# The serve-stream family is the STREAMING-CONSTRUCTION smoke: the
# same tiered fleet built LAZILY (--serve-stream: FleetSpec-derived
# bands/arrivals/traces, docs born in the pool's genesis state,
# first-admission tensorization on the prefetch thread), run
# race-sanitized and gated by bench_compare against the committed
# bench_results/serve_stream_baseline.json (construction_ms + peak
# RSS + hit rate) and by G017 against the prefetch publish surface —
# then an in-process eager-vs-lazy BYTE-PARITY leg (same seed, both
# paths drained, every doc's decoded bytes and the oracle replay must
# match exactly, mid-run evict/restore included).  The stream-vs-eager
# construction gates must also diff skip-with-note in both directions
# against the eager tier baseline (mode mismatch is a schema
# difference, never an error).  Exits NONZERO on a verify failure, a
# parity mismatch, a missing construction block, or an undeclared
# cross-thread handoff.
#
# The serve-open family is the LIVE-INGEST smoke (serve/ingest/): the
# fleet's ops arrive over a real loopback TCP front under an open-loop
# Poisson process (the wire paces arrivals — frames ahead of the hot
# clock are retried, not acked) with two tenants, SLO-aware admission
# and EDF deadlines, run RACE-SANITIZED with the status server live so
# a sidecar can scrape the per-tenant admission gauges MID-RUN.  The
# p99 at the fixed offered load is gated against the committed
# bench_results/serve_open_baseline.json (throughput is skip-with-note:
# open loop follows the offered load), G017 cross-checks the ingest
# publish surface, and a chaos leg fires conn_churn (sessions must
# reconnect-and-resume) + tenant_flood (admission must defer/shed and
# drain the backlog) — the runner exits nonzero on a verify failure or
# an unfired/unrecovered ingest fault.
#
# The serve-reshard family is the ELASTIC-RECONFIGURATION smoke: a
# 2-shard fleet drained race-sanitized while a live shrink:2:1 retires
# shard 1 mid-run — every migration journaled, admission open
# throughout — with reshard_crash armed so the coordinator is killed
# between its manifest commit and the per-doc moves and MUST resume
# deterministically.  A sidecar scrapes the serve.reshard.* gauges on
# the LIVE /metrics endpoint WHILE the move is in flight, the
# mid-reshard round p99 is gated by bench_compare against the
# committed bench_results/serve_reshard_baseline.json (plus the
# both-directions skip contract vs a fixed-map artifact), G017
# cross-checks the race artifact, and an fs-sanitized second leg
# proves the reshard durable protocol under G021.  Exits NONZERO on a
# verify failure, a shard-partition violation, an unfired/unrecovered
# reshard_crash, a missed mid-move scrape, or an unattributed fs op.
#
# Artifacts land in bench_results/ under smoke-specific names so they
# never clobber committed headline numbers.
set -euo pipefail

# Stage 0: the fast lint gate (graftlint + ruff-if-installed, sub-10s)
# — a hygiene regression fails the bench smoke before any fleet spins
# up.  See tools/lint.sh for the suppression escape hatch.
bash "$(dirname "$0")/lint.sh" || { echo "bench_smoke: lint gate failed" >&2; exit 1; }

family="serve"
while [ $# -gt 0 ]; do
  case "$1" in
    --family) family="$2"; shift 2 ;;
    *) echo "unknown argument: $1" >&2; exit 2 ;;
  esac
done

case "$family" in
  serve)
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-save-name serve_smoke
    # Scan-kernel leg: the same fleet through the legacy per-shape
    # lax.scan serve step (--serve-kernel scan; the default leg above
    # runs the fused ops/serve_fused.py path).  Both must byte-verify
    # green, and the fused leg is gated at <=15% throughput vs scan —
    # on host CPU the gate is correctness + no-pathology, not speedup
    # (the 24-doc drain is compile-dominated and jitters ~+-10% run to
    # run, so a tighter gate is pure flake)
    # (the 1.5x fused headline is measured on the full
    # serve/mixed/4096 fleet where compile spread and steady rate
    # dominate; a 24-doc smoke is all cold start).
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-kernel scan \
        --serve-save-name serve_smoke_scan
    # (p99 is relaxed for THIS cross-kernel comparison only: the two
    # kernels shape rounds differently — the fused path trims k_eff
    # exactly, so its rounds are fewer and individually longer at toy
    # scale — and on the full fleet fused p99 is strictly better:
    # 1.64s vs 1.95s, bench_results/serve_mixed_4096*.json)
    python tools/bench_compare.py \
      bench_results/serve_smoke.json bench_results/serve_smoke_scan.json \
      --max-throughput-regress 15 --max-p99-regress 150
    # Sanitized leg: the same drain under CRDT_BENCH_SANITIZE_SYNCS=1 —
    # any host sync outside a declared `# graftlint: fence` raises at
    # its callsite and fails this smoke (the dynamic proof of the G002
    # "syncs only at boundaries" invariant)...
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_SYNCS=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-save-name serve_smoke_sanitized
    # ...and the G011 fence-cost cross-check closes the loop: every
    # declared fence must have crossed in that run's boundary_syncs
    # counters (dead fences fail), every runtime counter must map back
    # to a declared fence (unattributed boundaries fail).
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G011 \
      --sync-artifact bench_results/serve_smoke_sanitized.json
    # Traced leg: the same drain with the obs/trace.py span tracer
    # armed.  Two gates: the emitted Chrome trace must validate against
    # the schema (spans nested, fence instants inside their owning
    # span), and armed-tracing THROUGHPUT overhead vs the plain leg
    # must stay within 15% (the compile-dominated 24-doc drain jitters
    # ~+-10% run to run — measured PR 8 — so a tighter gate is pure
    # flake; the 2% headline overhead claim is measured on the full
    # serve/mixed/4096 fleet where run noise is smaller).
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-trace bench_results/serve_smoke_trace.json \
        --serve-save-name serve_smoke_traced
    python -m crdt_benches_tpu.obs.trace bench_results/serve_smoke_trace.json
    python tools/bench_compare.py \
      bench_results/serve_smoke_traced.json bench_results/serve_smoke.json \
      --max-throughput-regress 15
    # Telemetry leg: the same drain with the obs/ v2 continuous
    # telemetry armed — live status server (ephemeral port) + windowed
    # time-series recorder — PLUS the obs/ v3 request tracer and a
    # (generous) SLO objective, so the artifact carries reqtrace + slo
    # blocks and the burn-rate gauges render on /metrics.  Armed
    # overhead vs the plain leg is gated at the same 15% the traced leg
    # uses (the 2% headline claim is measured on the full
    # serve/mixed/4096 fleet, bench_results/serve_mixed_4096_v3.json,
    # where run noise is smaller).
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-status 0 \
        --serve-timeseries bench_results/serve_smoke_timeseries.jsonl \
        --serve-reqtrace 16 --serve-slo "default=p99:60000" \
        --serve-save-name serve_smoke_telemetry
    python tools/bench_compare.py \
      bench_results/serve_smoke_telemetry.json bench_results/serve_smoke.json \
      --max-throughput-regress 15
    # Race-sanitized leg: the SAME status+timeseries drain under
    # CRDT_BENCH_SANITIZE_RACES=1 — the status/metrics snapshots become
    # ownership-tracking proxies and any cross-thread access outside a
    # declared `# graftlint: publish` point raises at its callsite
    # (lint/race_sanitizer.py, the dynamic proof of the static
    # G014/G015 confinement model).  Gated at <=10% vs the telemetry
    # leg it mirrors (identical config, env flag aside: the armed cost
    # is one proxy hop per scrape + a counter bump per publish — but
    # interleaved probes of this 24-doc pair measure a +-6% run-to-run
    # spread with the armed run sometimes FASTER, so the original 5%
    # gate flaked every other run; the real <=2% armed-overhead claim
    # is measured on the full serve/mixed/4096 fleet via
    # bench_compare, where run noise is small enough to resolve it).
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-status 0 \
        --serve-timeseries bench_results/serve_smoke_races.jsonl \
        --serve-reqtrace 16 --serve-slo "default=p99:60000" \
        --serve-save-name serve_smoke_races
    python tools/bench_compare.py \
      bench_results/serve_smoke_races.json \
      bench_results/serve_smoke_telemetry.json \
      --max-throughput-regress 10
    # ...and G017 closes the loop exactly like G011 does for fences:
    # every declared publish point the armed run should have crossed
    # must appear in its thread_crossings counters (dead points fail),
    # every runtime counter must map back to a declared point
    # (unattributed handoffs fail).
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_smoke_races.json
    # Race-sanitized CHAOS leg: the serve-faults recipe (800ms stall
    # against a 250ms watchdog, journal + snapshot barriers — the
    # barriers are what surface the staging stall as a stuck ROUND
    # instead of hiding it behind the async device wait) re-run under
    # the race sanitizer with the status server live — the watchdog
    # flip crosses set_health's immutable tuple swap while the handler
    # threads read it, so an unpublished handoff anywhere on the
    # anomaly -> health -> scrape path would raise and fail the leg.
    # Exit 0 = verify green + stall fired AND cleared + zero
    # undeclared cross-thread accesses.
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 3 \
        --serve-queue-cap 128 \
        --serve-faults "seed=5,span=5,stall_ms=800,spool_corrupt=1,device_loss=1,queue_overflow=1,dup_batch=1,stall@7=1" \
        --serve-soak 0 --serve-watchdog 0.25 \
        --serve-status 0 \
        --serve-timeseries bench_results/serve_smoke_races_chaos.jsonl \
        --serve-reqtrace 16 --serve-slo "default=p99:60000" \
        --serve-save-name serve_smoke_races_chaos
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_smoke_races_chaos.json
    exec python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_smoke_races_chaos.json"))
          if e.get("extra", {}).get("family") == "serve"]
x = extras[0]
tc = x["thread_crossings"]
assert tc["sanitized"] and tc["status"], tc
assert tc["publishes"].get("StatusServer.publish_status"), tc
assert set(tc["crossings"] or {}) <= set(tc["publishes"]), tc
stuck = [e for e in x["anomalies"]["events"] if e["kind"] == "stuck_round"]
assert stuck and all(e["cleared"] for e in stuck), x["anomalies"]
# obs/ v3 acceptance cross-check: every sampled request trace's
# publish-point hops are a SUBSET of the G017 thread_crossings
# publishes — the request tracer and the race sanitizer observe the
# same declared edges, so a hop with no publish counter means the two
# causal pictures diverged
rq = x["reqtrace"]
assert rq and rq["requests_closed"] > 0, rq
assert set(rq["hops"]) <= set(tc["publishes"]), (rq["hops"], tc)
assert rq["hops"].get("OpJournal.round_record"), rq["hops"]
for t in rq["traces"]:
    assert set(t["hops"]) <= set(tc["publishes"]), (t, tc)
print(f"race chaos: stall -> stuck_round -> cleared under the race "
      f"sanitizer; {sum(tc['publishes'].values())} publish entries, "
      f"{sum((tc['crossings'] or {}).values())} attributed crossings; "
      f"{len(rq['traces'])} request traces, hops {sorted(rq['hops'])} "
      "all subset of the declared publish points")
PYEOF
    ;;
  serve-repl)
    # Replication smoke: a small fleet of 2-writer groups drained
    # through the broadcast bus + batched downstream merge.  The runner
    # exits NONZERO when any replica diverges from the oracle (full-
    # fleet convergence, not a sample) or when the RA-linearizability
    # checker finds a visibility-axiom violation — the new verification
    # tier IS the gate.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 12 --serve-writers 2 --serve-mix mixed \
        --serve-batch 16 --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-turn-ops 16 \
        --serve-save-name serve_repl_smoke
    # Schema tolerance: the replicated artifact diffed against ITSELF
    # must pass every check (exit 0, never 2) — and the repl-only
    # blocks (replication / convergence) ride the same skip-with-note
    # path bench_compare gives obs/ v2 blocks, so a plain pre-
    # replication baseline also diffs cleanly (covered by tests).
    python tools/bench_compare.py \
      bench_results/serve_repl_smoke.json \
      bench_results/serve_repl_smoke.json
    # G017 vs the REPL artifact: the only family that arms the
    # broadcast-bus publish surface — a dead BroadcastBus._cross_block
    # annotation (or a rogue runtime counter) is invisible to the plain
    # family's cross-check, where bus=False skips the dead-point check.
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_repl_smoke.json
    exec python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_repl_smoke.json"))
          if e.get("extra", {}).get("family") == "serve-repl"]
x = extras[0]
rb, conv = x["replication"], x["convergence"]
assert x["verify_ok"] and x["ra_ok"], (x["verify_ok"], x["ra_ok"])
assert conv["replicas_checked"] == 2 * x["fleet_docs"], conv
assert rb["merged_ops"] > 0 and rb["broadcast_bytes"] > 0, rb
assert rb["divergence_depth_max"] >= 1, rb
print(f"repl smoke: {conv['replicas_checked']} replicas converged, "
      f"{rb['merged_ops']} remote ops merged over "
      f"{rb['broadcast_bytes']} broadcast bytes, RA axioms ok on "
      f"{conv['ra_groups_checked']} sampled histories")
PYEOF
    ;;
  serve-faults)
    # Chaos smoke under the soak detectors: the pinned late-round stall
    # (800ms against a 250ms watchdog) MUST trip the stuck-round
    # watchdog and recovery MUST clear it — the runner exits nonzero on
    # a verify failure, an unfired/unrecovered fault, OR an anomaly
    # still active at drain end, so exit 0 here IS the
    # stall -> watchdog -> recovered demonstration.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 3 \
        --serve-queue-cap 128 \
        --serve-faults "seed=5,span=5,stall_ms=800,spool_corrupt=1,device_loss=1,queue_overflow=1,dup_batch=1,stall@7=1" \
        --serve-soak 0 --serve-watchdog 0.25 \
        --serve-reqtrace 16 \
        --serve-flight bench_results/serve_faults_smoke_flight.json \
        --serve-save-name serve_faults_smoke
    # The flight recorder MUST have dumped on the injected stall (the
    # watchdog fire is an anomaly trigger even though it later clears)
    # and the dump must be schema-valid — the validator exits nonzero
    # otherwise.
    python -m crdt_benches_tpu.obs.flight bench_results/serve_faults_smoke_flight.json
    python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_faults_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
an = extras[0]["anomalies"]
stuck = [e for e in an["events"] if e["kind"] == "stuck_round"]
assert stuck, f"stall fault never tripped the watchdog: {an}"
assert all(e["cleared"] for e in stuck), f"watchdog never cleared: {stuck}"
assert an["uncleared"] == 0, an
fb = extras[0]["flight"]
assert fb and fb["dumps"] >= 1, f"flight recorder never dumped: {fb}"
assert any(r.startswith("anomaly:stuck_round") for r in fb["reasons"]), fb
dump = json.load(open("bench_results/serve_faults_smoke_flight.json"))
assert dump["rounds"], dump.get("reasons")
# the dump carries the post-mortem window: the stalled round is in the
# ring, and the sampled/in-flight request traces rode along
assert any(r["round"] >= stuck[0]["round"] for r in dump["rounds"]), (
    [r["round"] for r in dump["rounds"]], stuck[0]["round"])
assert dump["requests"], "armed reqtrace produced no traces in the dump"
print(f"chaos smoke: stall -> stuck_round at round {stuck[0]['round']} "
      f"-> cleared at round {stuck[0]['cleared_round']}; flight dump "
      f"({dump['reason']!r}) holds {len(dump['rounds'])} rounds + "
      f"{len(dump['requests'])} request traces")
PYEOF
    # Replicated chaos leg: the two replication fault kinds against a
    # 2-writer fleet with the WAL + snapshot barriers armed.  A
    # replica_partition must fire, diverge a replica, and RECONVERGE on
    # heal; a merge_reorder must deliver a round's remote batches
    # permuted and stay verify-green (sequence-keyed reassembly
    # commutes).  The runner exits nonzero on a convergence/RA-checker
    # failure or any unfired/unrecovered fault.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 12 --serve-writers 2 --serve-mix mixed \
        --serve-batch 16 --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-turn-ops 16 \
        --serve-journal auto --serve-snapshot-every 4 \
        --serve-faults "seed=7,span=4,replica_partition=1,merge_reorder=1" \
        --serve-save-name serve_repl_faults_smoke
    exec python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_repl_faults_smoke.json"))
          if e.get("extra", {}).get("family") == "serve-repl"]
x = extras[0]
f = x["faults"]
kinds = {e["kind"]: e for e in f["events"]}
assert kinds["replica_partition"]["fired"] and kinds["replica_partition"]["recovered"], f
assert kinds["merge_reorder"]["fired"] and kinds["merge_reorder"]["recovered"], f
assert x["verify_ok"] and x["ra_ok"], (x["verify_ok"], x["ra_ok"])
assert x["replication"]["partitions_healed"] >= 1, x["replication"]
assert x["replication"]["reordered_rounds"] >= 1, x["replication"]
print("repl chaos: partition fired+healed, reorder fired+commuted, "
      f"divergence max {x['replication']['divergence_depth_max']} blocks, "
      "all replicas reconverged")
PYEOF
    ;;
  serve-soak)
    # The soak leg: ~30s of back-to-back drains with the anomaly
    # detectors, time-series stream, and status server all armed on an
    # ephemeral port.  A sidecar scrapes /healthz + /metrics +
    # /status.json MID-RUN (any scrape error fails the leg), then the
    # runner's own exit code gates verify + anomalies, and a final
    # check asserts the clean soak fired NO anomaly at all.
    rm -f bench_results/serve_smoke_soak.log
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-soak 25 --serve-status 0 \
        --serve-timeseries bench_results/serve_smoke_soak.jsonl \
        --serve-slo "default=p99:60000" --serve-reqtrace 16 \
        --serve-save-name serve_smoke_soak \
        2> >(tee bench_results/serve_smoke_soak.log >&2) &
    soak_pid=$!
    python - <<'PYEOF'
import json, re, sys, time, urllib.request

log = "bench_results/serve_smoke_soak.log"
port = None
deadline = time.time() + 120
while time.time() < deadline:
    try:
        m = re.search(r"status server on http://127\.0\.0\.1:(\d+)",
                      open(log, encoding="utf-8").read())
    except OSError:
        m = None
    if m:
        port = int(m.group(1))
        break
    time.sleep(0.25)
assert port, "soak scrape: status server never announced its port"
base = f"http://127.0.0.1:{port}"
rounds, err = [], None
for _ in range(400):
    try:
        h = urllib.request.urlopen(base + "/healthz", timeout=2)
        assert h.status == 200, h.read()
        s = json.load(urllib.request.urlopen(base + "/status.json", timeout=2))
        text = urllib.request.urlopen(base + "/metrics", timeout=2).read().decode()
        # before the first drain binds, /metrics is an empty (but
        # well-formed) exposition — keep polling until the registry
        # snapshot lands; between drains, "rounds" restarts at 0, so
        # advancement means one strictly-increasing consecutive pair
        assert "# TYPE" in text and "serve_pool_evictions_total" in text
        # obs/ v3: the per-class SLO burn-rate gauges render on the
        # live endpoint MID-RUN (pre-registered at scheduler bind, so
        # they are present from the first registry snapshot on)
        assert 'serve_slo_burn_rate{class="default",window="fast"}' in text, \
            "burn-rate gauges missing from /metrics"
        assert 'serve_slo_burn_rate{class="default",window="slow"}' in text
        assert 'serve_slo_compliance{class="default"}' in text
        rounds.append(int(s.get("rounds", 0)))
        if len(rounds) >= 2 and rounds[-1] > rounds[-2]:
            break
    except (OSError, AssertionError) as e:  # not serving yet: retry
        err = e
    time.sleep(0.2)
else:
    sys.exit(f"soak scrape: /status.json never advanced ({rounds!r}, last error {err!r})")
print(f"soak scrape ok: rounds {rounds[-2]} -> {rounds[-1]} over {len(rounds)} scrapes, /metrics + /healthz answering")
PYEOF
    wait "$soak_pid"
    exec python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_smoke_soak.json"))
          if e.get("extra", {}).get("family") == "serve"]
an, ts = extras[0]["anomalies"], extras[0]["timeseries"]
assert an["fired"] == 0, f"clean soak fired anomalies: {an}"
assert ts["windows"], "soak produced no time-series windows"
print(f"soak: {ts['drains']} drain(s), {len(ts['windows'])} windows, 0 anomalies")
PYEOF
    ;;
  serve-longhaul)
    # Clean longhaul leg: days-of-edits-scale synth streams (x4
    # horizon), WAL segments rolled at 4 KiB with GC at every barrier,
    # delta barriers every 2 rounds (chain re-rooted every 3rd), and
    # the measured recovery leg at drain end.  The runner exits
    # nonzero on a verify failure in EITHER the live drain or the
    # recovered fleet.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 16 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 2 \
        --serve-full-every 3 --serve-wal-segment-bytes 4096 \
        --serve-longhaul 4 \
        --serve-save-name serve_longhaul_smoke
    # The durability regression gate: recover_ms + on-disk journal
    # bytes vs the committed baseline (same recipe).  Thresholds are
    # loose where wall time is box-dependent; the BYTE columns are
    # workload-determined, so real history-growth regressions fail
    # well inside them.
    python tools/bench_compare.py \
      bench_results/serve_longhaul_smoke.json \
      bench_results/serve_longhaul_baseline.json \
      --max-throughput-regress 60 --max-p99-regress 200 \
      --max-drain-p999-regress 200 \
      --max-recover-regress 400 --max-journal-disk-regress 75
    # Crash + durability-chaos leg under the race sanitizer: the GC
    # pass is killed between its manifest write and the unlinks
    # (crash_compact), the newest delta member is bit-flipped
    # (delta_corrupt at barrier 2 — the DELTA barrier), and the whole
    # drain is killed right after it (crash round 4), so the recovery
    # tip IS the corrupted delta: recover_fleet must complete the torn
    # GC, fall back down the snapshot chain (chain_fallbacks >= 1,
    # asserted below), resume the redo tail, and byte-verify green,
    # all with zero undeclared cross-thread accesses.
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 16 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 2 \
        --serve-full-every 2 --serve-wal-segment-bytes 256 \
        --serve-longhaul 4 --serve-crash-round 4 \
        --serve-faults "seed=3,crash_compact@2=1,delta_corrupt@2=1" \
        --serve-save-name serve_longhaul_crash_smoke
    python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_longhaul_crash_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
x = extras[0]
f = {e["kind"]: e for e in x["faults"]["events"]}
assert f["crash_compact"]["fired"] and f["crash_compact"]["recovered"], f
assert f["delta_corrupt"]["fired"] and f["delta_corrupt"]["recovered"], f
rec = x["recovery"]
assert rec and rec["verify_ok"], rec
assert rec["recover_ms"] > 0, rec
# the crash lands on the corrupted delta tip: recovery must have
# actually walked DOWN the chain, not found a clean full on top
assert rec["chain_fallbacks"] >= 1, rec
j = x["journal"]
assert j["segments_sealed"] >= 1 and j["snapshots_delta"] >= 1, j
g = x["metrics"]["gauges"]
for name in ("serve.journal.wal_segments",
             "serve.journal.bytes_since_snapshot",
             "serve.durability.chain_depth",
             "serve.durability.last_compaction_round"):
    assert name in g, (name, sorted(g))
print(f"longhaul crash smoke: crash_compact + delta_corrupt fired and "
      f"recovered; recovery {rec['recover_ms']:.1f}ms restore "
      f"(chain depth {rec['chain_depth']}, {rec['chain_fallbacks']} "
      f"fallbacks, {rec['gc_segments_completed']} torn-GC segments "
      f"completed) + {rec['redo_ops']} redo ops, WAL "
      f"{rec['journal_disk_bytes']} B on disk, oracle verify green")
PYEOF
    # FS-sanitized crash-consistency leg (graftlint v4): a 12-doc
    # journaled drain under CRDT_BENCH_SANITIZE_FS=1 — the filesystem
    # surface is interposed, every op on the journal/spool roots is
    # attributed to its declared durable protocol, and the G019
    # ordering invariants are enforced LIVE (an unlink before its
    # committed install raises at the callsite).  The artifact's
    # fs_ops block is then cross-checked by G021 in both directions:
    # dead declared protocols and unattributed runtime fs ops both
    # fail the gate.
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_FS=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 12 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 2 \
        --serve-full-every 2 --serve-wal-segment-bytes 4096 \
        --serve-save-name serve_longhaul_fs_smoke
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G021 \
      --fs-artifact bench_results/serve_longhaul_fs_smoke.json
    python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_longhaul_fs_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
fo = extras[0]["fs_ops"]
assert fo["sanitized"] and fo["journal"], fo
for tag in ("wal", "gc", "snapshot"):
    assert fo["protocols"].get(tag, 0) > 0, (tag, fo["protocols"])
assert fo["unattributed"] == {}, fo["unattributed"]
assert set(fo["ops"]) <= set(fo["protocols"]), (fo["ops"], fo["protocols"])
print(f"fs leg: {sum(fo['protocols'].values())} protocol entries, "
      f"{sum(n for t in fo['ops'].values() for n in t.values())} fs ops "
      "attributed, zero unattributed, G021 clean both directions")
PYEOF
    # Lifecycle-sanitized leg (graftlint v5): a churn-heavy journal-less
    # streaming drain with drained-doc record eviction under
    # CRDT_BENCH_SANITIZE_LIFECYCLE=1 — every keyed doc residency edge,
    # row acquire/release, and stream release is checked LIVE (illegal
    # edges, double releases, and negative gauges raise at the
    # callsite), and the artifact's lifecycle block is cross-checked by
    # G025 in both directions: dead declared machines on armed surfaces
    # and rogue/unattributed runtime transitions both fail the gate.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      CRDT_BENCH_SANITIZE_LIFECYCLE=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 2 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 4,2,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 4 \
        --serve-stream --serve-record-evict \
        --serve-save-name serve_longhaul_lc_smoke
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G025 \
      --lifecycle-artifact bench_results/serve_longhaul_lc_smoke.json
    python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_longhaul_lc_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
lc = extras[0]["lifecycle"]
assert lc["sanitized"] and lc["pool"] and lc["stream"], lc
for m in ("doc", "stream"):
    assert lc["machines"].get(m), (m, lc["machines"])
assert lc["resources"].get("rows", {}).get("acquire", 0) > 0, lc["resources"]
assert lc["unattributed"] == [], lc["unattributed"]
edges = sum(n for t in lc["machines"].values() for n in t.values())
print(f"lifecycle leg: {edges} transitions across "
      f"{len(lc['machines'])} machines, "
      f"{lc['resources']['rows']['acquire']} row acquisitions, zero "
      "unattributed, G025 clean both directions")
PYEOF
    # Range-sanitized leg (graftlint v6): the same drain under
    # CRDT_BENCH_SANITIZE_RANGES=1 — every staged gather/scatter index,
    # narrow uint16 lane, and PAD-masked operand is bounds-validated
    # LIVE on the host tensors pre-dispatch (an out-of-range value
    # raises a typed error at the staging callsite instead of XLA
    # clamping it silently), and the artifact's ranges block is
    # cross-checked by G029 in both directions: dead declared
    # inrange=/mask= facts on armed surfaces and rogue runtime
    # counters both fail the gate.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      CRDT_BENCH_SANITIZE_RANGES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 2 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 4,2,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 4 \
        --serve-save-name serve_longhaul_rg_smoke
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G029 \
      --ranges-artifact bench_results/serve_longhaul_rg_smoke.json
    python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_longhaul_rg_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
rg = extras[0]["ranges"]
assert rg["sanitized"] and rg["staging"], rg
for name in ("pool.write-row", "pool.macro-pos", "pool.macro-ids"):
    assert rg["checks"].get(name, 0) > 0, (name, rg["checks"])
assert rg["masks"].get("count-le-clamp", 0) > 0, rg["masks"]
print(f"ranges leg: {sum(rg['checks'].values())} armed range checks "
      f"across {len(rg['checks'])} declared facts, "
      f"{sum(rg['masks'].values())} mask dispatches, G029 clean both "
      "directions")
PYEOF
    # ...the value-range headline: the dtype-edge adversarial fleet
    # (position extremes, empty churn, a zero-op all-PAD stream,
    # exact-capacity landings, id pressure) drained ARMED through both
    # kernels — every doc oracle- and cross-kernel byte-identical —
    # plus the seeded differential fuzz of every @boundary contract at
    # its dtype edges (each must reject every one-field perturbation).
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.serve.edgecheck --small
    # ...the lifecycle headline: the churn-heavy protocol-complete
    # lifecheck drain (journaled churn + reshard + live ingest front,
    # then a record-evict streaming drain) armed end to end, requiring
    # ZERO unreleased acquisitions at each drain end and nonzero edge
    # counts on every declared machine.
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.serve.lifecheck --small
    # ...and the durability headline: exhaustive crash-point
    # enumeration — a crash injected at EVERY mutating fs-op boundary
    # of the sub-minute protocol workload (snapshot barriers, delta
    # chains, WAL seal+GC, spool churn, flight dump) must be followed
    # by byte-verified recovery; the per-protocol point counts are
    # asserted nonzero inside the harness so it can never silently
    # cover nothing.
    exec timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.serve.fscrash --small
    ;;
  serve-tier)
    # Tiered-residency smoke: 40 docs on a ~14-row hot budget with a
    # 6-doc warm tier — real tier traffic by construction (hot→warm
    # evictions every round, warm→cold LRU demotions, prefetch
    # rehydrates ahead of the rotation) — run RACE-SANITIZED so the
    # prefetch thread's bounded-queue handoff is proven at its declared
    # publish point, with both tier chaos kinds armed and the journal
    # on so snapshot barriers compose warm shadows.  The zipf arrival
    # skew makes the hot set real.  The runner exits nonzero on verify
    # fail or any unfired/unrecovered fault.
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 40 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-tiers hot=14,warm=6 --serve-arrival-dist zipf \
        --serve-arrival-span 4 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 3 \
        --serve-faults "seed=3,span=4,tier_evict_pressure=1,prefetch_miss=1" \
        --serve-save-name serve_tier_smoke
    # The tier regression gate: throughput + the warm/prefetch hit
    # rate vs the committed baseline (same recipe).  Thresholds are
    # loose — a 40-doc drain is compile-dominated — but a prefetcher
    # that stopped predicting or a thrashing warm tier fails the
    # hit-rate check regardless of wall-clock noise.
    python tools/bench_compare.py \
      bench_results/serve_tier_smoke.json \
      bench_results/serve_tier_baseline.json \
      --max-throughput-regress 40 --max-p99-regress 200 \
      --max-hit-rate-regress 40
    # ...and the residency block must diff skip-with-note in BOTH
    # directions against a flat (pre-tier) artifact — a schema
    # difference, never an error (exit 0, not 2; thresholds are moot,
    # the two runs are different scales — the point is the schema).
    python tools/bench_compare.py \
      bench_results/serve_tier_smoke.json \
      bench_results/serve_baseline.json \
      --max-throughput-regress 100 --max-p99-regress 100000 \
      --max-syncs-regress 100000 --max-drain-p999-regress 100000
    python tools/bench_compare.py \
      bench_results/serve_baseline.json \
      bench_results/serve_tier_smoke.json \
      --max-throughput-regress 100 --max-p99-regress 100000 \
      --max-syncs-regress 100000 --max-drain-p999-regress 100000
    # G017 vs the tier artifact: the only family that arms the
    # prefetch publish surface — a dead Prefetcher._publish annotation
    # (or a rogue runtime counter) is invisible everywhere else.
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_tier_smoke.json
    exec python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_tier_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
x = extras[0]
assert x["verify_ok"], "tier smoke failed oracle byte-verify"
res = x["residency"]
assert res is not None, "residency block missing from the tier artifact"
assert res["hit_rate"] is not None, f"hit-rate missing: {res}"
assert res["warm_hits"] + res["cold_restores"] > 0, res
assert res["prefetch_submitted"] > 0, f"prefetcher never ran: {res}"
assert res["warm_evictions"] > 0, f"no warm->cold traffic: {res}"
f = {e["kind"]: e for e in x["faults"]["events"]}
assert f["tier_evict_pressure"]["fired"] and f["tier_evict_pressure"]["recovered"], f
assert f["prefetch_miss"]["fired"] and f["prefetch_miss"]["recovered"], f
tc = x["thread_crossings"]
assert tc["sanitized"] and tc["prefetch"], tc
assert tc["publishes"].get("Prefetcher._publish"), tc
assert set(tc["crossings"] or {}) <= set(tc["publishes"]), tc
g = x["metrics"]["gauges"]
for name in ("serve.tier.hot_rows", "serve.tier.warm_docs",
             "serve.tier.cold_docs", "serve.tier.prefetch_inflight"):
    assert name in g, (name, sorted(g))
print(f"tier smoke: {res['warm_hits']} warm hits "
      f"({res['prefetch_hits']} prefetched) / {res['cold_restores']} "
      f"cold restores (hit rate {res['hit_rate']:.3f}), "
      f"{res['warm_evictions']} warm→cold demotions, both tier chaos "
      f"kinds fired+recovered, prefetch publish point proven under the "
      f"race sanitizer ({tc['publishes']['Prefetcher._publish']} entries)")
PYEOF
    ;;
  serve-stream)
    # Streaming-construction smoke: the serve-tier recipe rebuilt
    # LAZILY — 40 docs born in genesis on a 14-row hot budget with a
    # 6-doc warm tier, bands/arrivals/traces derived from (seed,
    # doc_id) at first admission, tensorization riding the prefetch
    # thread's declared publish point — run RACE-SANITIZED so the new
    # construct payload shape is proven thread-confined.  The explicit
    # --serve-sample-seed exercises the auditable-verify knob (seed +
    # picked doc ids land in the artifact).
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 40 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-tiers hot=14,warm=6 --serve-arrival-dist zipf \
        --serve-arrival-span 4 --serve-verify-sample 6 \
        --serve-stream --serve-sample-seed 5 \
        --serve-save-name serve_stream_smoke
    # Regression gate vs the committed streaming baseline (same
    # recipe, same mode): construction time + peak RSS are the
    # tentpole numbers; hit rate guards the genesis->prefetch path.
    # Thresholds are loose — a 40-doc drain is compile-dominated and
    # ms-scale construction jitters — but an eager build sneaking back
    # into the lazy path fails the construction gate outright.
    python tools/bench_compare.py \
      bench_results/serve_stream_smoke.json \
      bench_results/serve_stream_baseline.json \
      --max-throughput-regress 40 --max-p99-regress 200 \
      --max-hit-rate-regress 40 \
      --max-construction-regress 150 --max-rss-regress 60
    # Mode-mismatch contract, both directions: stream-vs-eager
    # construction numbers are incomparable by design — the gates must
    # SKIP with the modes named, never fail or error (the other
    # thresholds are moot, the runs are different scales).
    python tools/bench_compare.py \
      bench_results/serve_stream_smoke.json \
      bench_results/serve_tier_baseline.json \
      --max-throughput-regress 100 --max-p99-regress 100000 \
      --max-syncs-regress 100000 --max-drain-p999-regress 100000 \
      --max-hit-rate-regress 100
    python tools/bench_compare.py \
      bench_results/serve_tier_baseline.json \
      bench_results/serve_stream_smoke.json \
      --max-throughput-regress 100 --max-p99-regress 100000 \
      --max-syncs-regress 100000 --max-drain-p999-regress 100000 \
      --max-hit-rate-regress 100
    # G017 vs the streaming artifact: the construct payloads ride the
    # same Prefetcher._publish surface — the cross-check proves the
    # runtime counters still match the declared annotations.
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_stream_smoke.json
    # Artifact contract: sampled verify green + auditable, the
    # construction block present with the streaming counters, and the
    # new payload shape attributed to the declared publish point.
    python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_stream_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
x = extras[0]
assert x["verify_ok"], "stream smoke failed oracle byte-verify"
c = x["construction"]
assert c is not None, "construction block missing from the artifact"
assert c["mode"] == "stream", c
assert c["construction_ms"] > 0, c
assert c["peak_rss_bytes"] > 0, c
assert c["materialized_docs"] == c["fleet_docs"] == 40, c
assert c["released_docs"] > 0, f"no drained stream was released: {c}"
assert c["prefetch_built"] > 0, f"no stream tensorized off-drain: {c}"
assert c["genesis_docs_end"] == 0, c
# the auditable sampled verify: the explicit seed + the picked ids
assert c["verify_sample_seed"] == 5, c
assert x["verified_docs"], x["verified_docs"]
res = x["residency"]
assert res is not None and res["prefetch_submitted"] > 0, res
tc = x["thread_crossings"]
assert tc["sanitized"] and tc["prefetch"], tc
assert tc["publishes"].get("Prefetcher._publish"), tc
assert set(tc["crossings"] or {}) <= set(tc["publishes"]), tc
g = x["metrics"]["gauges"]
assert "serve.tier.genesis_docs" in g, sorted(g)
print(f"stream smoke: construction {c['construction_ms']:.0f}ms "
      f"(peak rss {c['peak_rss_bytes'] / 2**20:.0f} MiB), "
      f"{c['prefetch_built']} streams tensorized off-drain / "
      f"{c['released_docs']} released after drain, sampled verify "
      f"green (seed {c['verify_sample_seed']}, docs {x['verified_docs']}), "
      f"publish point proven under the race sanitizer "
      f"({tc['publishes']['Prefetcher._publish']} entries)")
PYEOF
    # Eager-vs-lazy byte parity, in-process: SAME seed, both paths
    # drained on a hot budget small enough to force mid-run
    # evict/restore traffic; every doc's decoded bytes must match the
    # eager fleet's AND the oracle replay.  This is the acceptance
    # pin: the lazy derivation is byte-stable, not just statistically
    # similar.
    exec timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'PYEOF'
from crdt_benches_tpu.serve.pool import DocPool
from crdt_benches_tpu.serve.scheduler import (
    FleetScheduler, LazyStreams, prepare_streams,
)
from crdt_benches_tpu.serve.workload import FleetSpec, build_fleet
from crdt_benches_tpu.oracle.text_oracle import replay_trace

KW = dict(mix="mixed", seed=11, arrival_span=4, arrival_dist="zipf")
CLASSES = (256, 1024, 4096, 8192, 49152)
SLOTS = (6, 3, 2, 2, 2)  # tight: evict/restore churn by construction
N = 24

sessions = build_fleet(N, **KW)
epool = DocPool(classes=CLASSES, slots=SLOTS, warm_docs=4)
estreams = prepare_streams(sessions, epool, batch=16, batch_chars=64)
esched = FleetScheduler(epool, estreams, batch=16, batch_chars=64)
estats = esched.run()
assert esched.done
assert estats.evictions > 0, "hot budget too loose: no tier churn"

spec = FleetSpec.build(N, **KW)
lpool = DocPool(classes=CLASSES, slots=SLOTS, warm_docs=4)
lstreams = LazyStreams(spec, lpool, batch=16, batch_chars=64)
lsched = FleetScheduler(lpool, lstreams, batch=16, batch_chars=64)
lstats = lsched.run()
assert lsched.done
assert lstats.patches == estats.patches, (lstats.patches, estats.patches)
assert lstreams.materialized == N, lstreams.materialized

mismatch = []
for d in range(N):
    want = replay_trace(sessions[d].trace)
    eager, lazy = epool.decode(d), lpool.decode(d)
    if not (eager == lazy == want):
        mismatch.append(d)
assert not mismatch, f"eager-vs-lazy byte mismatch on docs {mismatch}"
epool.close(); lpool.close()
print(f"parity: {N} docs byte-identical across eager/lazy/oracle "
      f"({estats.patches} patches each; {estats.evictions} evictions "
      f"exercised mid-run)")
PYEOF
    ;;
  serve-open)
    # Leg 1: the open-loop drain over the live wire — 24 docs, two
    # tenants (gold generously provisioned, free budget-capped so the
    # admission path actually defers), EDF deadlines, offered load 64
    # ops/round — race-sanitized with the status server on an
    # ephemeral port.  --serve-soak 10 keeps the telemetry bundle
    # armed across the drain so the sidecar below has a live /metrics
    # to scrape; the clean-soak contract (no active anomaly at end)
    # rides along for free.
    rm -f bench_results/serve_open_smoke.log
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-open 64 --serve-tenants "gold=48:192,free=16:32:128" \
        --serve-deadline \
        --serve-soak 10 --serve-status 0 \
        --serve-slo "default=p99:60000" \
        --serve-save-name serve_open_smoke \
        2> >(tee bench_results/serve_open_smoke.log >&2) &
    open_pid=$!
    # Mid-run sidecar: the per-tenant ingest gauges + admission
    # counters must render on the LIVE /metrics endpoint while the
    # front is accepting connections (pre-registered at bind, so they
    # are present from the first registry snapshot on), and
    # /status.json must be advancing rounds.
    python - <<'PYEOF'
import json, re, sys, time, urllib.request

log = "bench_results/serve_open_smoke.log"
port = None
deadline = time.time() + 120
while time.time() < deadline:
    try:
        m = re.search(r"status server on http://127\.0\.0\.1:(\d+)",
                      open(log, encoding="utf-8").read())
    except OSError:
        m = None
    if m:
        port = int(m.group(1))
        break
    time.sleep(0.25)
assert port, "open smoke: status server never announced its port"
base = f"http://127.0.0.1:{port}"
rounds, err = [], None
for _ in range(400):
    try:
        h = urllib.request.urlopen(base + "/healthz", timeout=2)
        assert h.status == 200, h.read()
        s = json.load(urllib.request.urlopen(base + "/status.json", timeout=2))
        text = urllib.request.urlopen(base + "/metrics", timeout=2).read().decode()
        assert "# TYPE" in text
        for series in ('serve_ingest_tokens{tenant="free"}',
                       'serve_ingest_tokens{tenant="gold"}',
                       'serve_ingest_admitted_ops_total{tenant="gold"}'):
            assert series in text, f"{series} missing from live /metrics"
        rounds.append(int(s.get("rounds", 0)))
        if len(rounds) >= 2 and rounds[-1] > rounds[-2]:
            break
    except (OSError, AssertionError) as e:  # not serving yet: retry
        err = e
    time.sleep(0.2)
else:
    sys.exit(f"open smoke scrape: never saw the ingest gauges on an advancing run ({rounds!r}, last error {err!r})")
print(f"open smoke scrape ok: rounds {rounds[-2]} -> {rounds[-1]}, per-tenant ingest gauges live on /metrics")
PYEOF
    wait "$open_pid"
    # The open-loop regression gate: p99 AT THE FIXED OFFERED LOAD vs
    # the committed baseline (same recipe, 64 ops/round) — throughput
    # is skip-with-note by design.  Thresholds are loose: a 24-doc
    # drain is compile-dominated and the smoke leg runs sanitized +
    # soak-armed while the baseline is plain.
    python tools/bench_compare.py \
      bench_results/serve_open_smoke.json \
      bench_results/serve_open_baseline.json \
      --max-p99-regress 200 --max-drain-p999-regress 200
    # G017 vs the open artifact: the only family that arms the ingest
    # publish surface — a dead IngestFront._publish annotation (or a
    # rogue runtime counter) is invisible to every other family, where
    # ingest=False skips the dead-point check.
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_open_smoke.json
    # Leg 2: ingest chaos under the race sanitizer with the journal on
    # — conn_churn drops every live connection mid-drain (clients must
    # reconnect-and-resume; redelivered frames dup-drop idempotently)
    # and tenant_flood inflates one tenant's admission pressure for a
    # window (admission must defer/shed it and the backlog must
    # drain).  Exit 0 = verify green + both faults fired AND
    # recovered.
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-open 64 --serve-tenants "gold=48:192,free=16:32:128" \
        --serve-deadline --serve-journal auto --serve-snapshot-every 3 \
        --serve-faults "seed=5,conn_churn@6=1,tenant_flood@10=1" \
        --serve-save-name serve_open_chaos_smoke
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_open_chaos_smoke.json
    exec python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_open_chaos_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
x = extras[0]
assert x["verify_ok"], "open chaos smoke failed oracle byte-verify"
f = {e["kind"]: e for e in x["faults"]["events"]}
assert f["conn_churn"]["fired"] and f["conn_churn"]["recovered"], f
assert f["tenant_flood"]["fired"] and f["tenant_flood"]["recovered"], f
ing = x["ingest"]
# the churn really severed live connections and the clients really
# came back: drops AND resumed sessions, zero client-side errors
assert ing["front"]["churn_drops"] >= 1, ing["front"]
assert ing["front"]["sessions_resumed"] >= 1, ing["front"]
assert ing["client"]["reconnects"] >= 1, ing["client"]
assert ing["client"]["errors"] == 0, ing["client"]
# every planned op still arrived over the wire (pacing + resume)
assert ing["front"]["ops_delivered"] == ing["open"]["total_ops"], ing
dl = ing["deadline"]
assert dl["met"] + dl["missed"] == x["fleet_docs"], dl
tc = x["thread_crossings"]
assert tc["sanitized"] and tc["ingest"], tc
assert tc["publishes"].get("IngestFront._publish"), tc
assert set(tc["crossings"] or {}) <= set(tc["publishes"]), tc
adm = ing["admission"]["tenants"]
print(f"open chaos: churn dropped {ing['front']['churn_drops']} conns, "
      f"{ing['front']['sessions_resumed']} sessions resumed "
      f"({ing['client']['reconnects']} reconnects); flood verdicts — "
      + "; ".join(f"{t}: admit {d['admitted_ops']} defer {d['deferred_ops']} "
                  f"shed {d['shed_ops']}" for t, d in sorted(adm.items()))
      + f"; deadline hit rate {dl['hit_rate']}, ingest publish point "
      f"proven under the race sanitizer "
      f"({tc['publishes']['IngestFront._publish']} entries)")
PYEOF
    ;;
  serve-reshard)
    # Leg 1: the live shrink under chaos, race-sanitized, status
    # server on an ephemeral port.  24 docs on 2 logical shards;
    # shrink:2:1 begins at round 4 with batch=2 so the migration spans
    # several served rounds (the sidecar's mid-move window), and
    # reshard_crash@4 kills the coordinator between its manifest
    # commit and the first per-doc move — the next round's tick must
    # resume from the journaled manifest, finish the moves, retire
    # shard 1, and the drain must stay verify-green with the
    # partition invariant intact (the runner exits nonzero otherwise).
    rm -f bench_results/serve_reshard_smoke.log
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_RACES=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 3 \
        --serve-reshard "shrink:2:1@4,batch=2" \
        --serve-faults "seed=5,reshard_crash@4=1" \
        --serve-status 0 --serve-slo "default=p99:60000" \
        --serve-save-name serve_reshard_smoke \
        2> >(tee bench_results/serve_reshard_smoke.log >&2) &
    reshard_pid=$!
    # Mid-move sidecar: the serve.reshard.* gauges must render on the
    # LIVE /metrics endpoint WHILE the migration is in flight —
    # serve_reshard_active flips to 1 at the manifest commit and stays
    # up until the retire, so catching it mid-run (with pending docs
    # still counting down) proves the fleet was serving and observable
    # DURING the shard-map change, not just around it.
    python - <<'PYEOF'
import re, sys, time, urllib.request

log = "bench_results/serve_reshard_smoke.log"
port = None
deadline = time.time() + 120
while time.time() < deadline:
    try:
        m = re.search(r"status server on http://127\.0\.0\.1:(\d+)",
                      open(log, encoding="utf-8").read())
    except OSError:
        m = None
    if m:
        port = int(m.group(1))
        break
    time.sleep(0.25)
assert port, "reshard smoke: status server never announced its port"
base = f"http://127.0.0.1:{port}"
seen, err = [], None
deadline = time.time() + 150
while time.time() < deadline:
    try:
        text = urllib.request.urlopen(base + "/metrics", timeout=2).read().decode()
        act = re.search(r"^serve_reshard_active (\d+)", text, re.M)
        pend = re.search(r"^serve_reshard_pending_docs (\d+)", text, re.M)
        drn = re.search(r"^serve_reshard_draining_shards (\d+)", text, re.M)
        if act:
            seen.append((int(act.group(1)),
                         int(pend.group(1)) if pend else -1,
                         int(drn.group(1)) if drn else -1))
        if act and act.group(1) == "1":
            assert pend and drn, f"reshard gauges incomplete mid-move: {seen[-1]}"
            assert int(drn.group(1)) >= 1, seen[-1]
            print(f"reshard scrape ok: mid-move /metrics shows active=1, "
                  f"pending_docs={pend.group(1)}, "
                  f"draining_shards={drn.group(1)} "
                  f"({len(seen)} scrapes to catch it)")
            break
    except (OSError, AssertionError) as e:  # not serving yet: retry
        err = e
    time.sleep(0.05)
else:
    sys.exit(f"reshard scrape: never saw serve_reshard_active=1 mid-run "
             f"(observed {seen[-5:]!r}, last error {err!r})")
PYEOF
    wait "$reshard_pid"
    # The elastic-reconfiguration regression gate: mid-reshard round
    # p99 (+ the worst-class SLO burn riding the ordinary slo check)
    # vs the committed baseline — same recipe, run plain, so the
    # thresholds are loose where the smoke leg pays the sanitizer +
    # chaos overhead on a compile-dominated 24-doc drain.
    python tools/bench_compare.py \
      bench_results/serve_reshard_smoke.json \
      bench_results/serve_reshard_baseline.json \
      --max-throughput-regress 60 --max-p99-regress 200 \
      --max-drain-p999-regress 200 --max-reshard-p99-regress 300
    # ...and the reshard block must diff skip-with-note in BOTH
    # directions against a fixed-shard-map artifact — a family
    # difference, never an error (exit 0, not 2; the other thresholds
    # are moot, the runs are different scales — the point is the
    # schema).
    python tools/bench_compare.py \
      bench_results/serve_reshard_smoke.json \
      bench_results/serve_baseline.json \
      --max-throughput-regress 100 --max-p99-regress 100000 \
      --max-syncs-regress 100000 --max-drain-p999-regress 100000
    python tools/bench_compare.py \
      bench_results/serve_baseline.json \
      bench_results/serve_reshard_smoke.json \
      --max-throughput-regress 100 --max-p99-regress 100000 \
      --max-syncs-regress 100000 --max-drain-p999-regress 100000
    # G017 vs the race artifact: the reshard runs on the scheduler
    # thread, so the cross-check proves the migration added no
    # undeclared cross-thread handoff anywhere on the
    # gauge -> registry -> scrape path it was observed through.
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G017 \
      --thread-artifact bench_results/serve_reshard_smoke.json
    python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_reshard_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
x = extras[0]
assert x["verify_ok"], "reshard smoke failed oracle byte-verify"
rs = x["reshard"]
assert rs is not None, "reshard block missing from the artifact"
assert rs["kind"] == "shrink" and rs["state"] == "done", rs
assert rs["partition_errors"] == [], rs["partition_errors"]
assert rs["live_shards"] == 1, rs
# the move was real work, spread over served rounds: docs left the
# retiring shard (by row move or demotion), lanes deferred briefly
# (never shed), and the mid-reshard latency window is non-empty
assert rs["migrated"] + rs["evicted"] > 0, rs
assert rs["rounds_active"] >= 2, rs
assert rs["mid_latency"], rs
# the chaos contract: the coordinator was killed after its manifest
# commit and the NEXT tick resumed from the journal
f = {e["kind"]: e for e in x["faults"]["events"]}
assert f["reshard_crash"]["fired"] and f["reshard_crash"]["recovered"], f
assert rs["resumes"] >= 1, rs
tc = x["thread_crossings"]
assert tc["sanitized"], tc
assert set(tc["crossings"] or {}) <= set(tc["publishes"]), tc
g = x["metrics"]["gauges"]
for name in ("serve.reshard.active", "serve.reshard.draining_shards",
             "serve.reshard.pending_docs"):
    assert name in g, (name, sorted(g))
print(f"reshard smoke: shrink 2->1 live ({rs['migrated']} row moves + "
      f"{rs['evicted']} demotions over {rs['rounds_active']} served "
      f"rounds, {rs['deferred_lanes']} lanes deferred, 0 shed); "
      f"reshard_crash fired + resumed ({rs['resumes']} resumes), "
      f"partition invariant clean, verify green")
PYEOF
    # Leg 2: the same shrink under CRDT_BENCH_SANITIZE_FS=1 — every fs
    # op of the reshard protocol (manifest tmp-write -> fsync ->
    # rename -> dir fsync, and the retire record) attributed live,
    # G019 orderings enforced at the callsite, then G021 cross-checks
    # the emitted fs_ops block in both directions: the `reshard`
    # protocol must have real runtime entries (a dead declaration
    # fails) and no fs op may go unattributed.
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_FS=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 12 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 3 \
        --serve-reshard "shrink:2:1@2,batch=2" \
        --serve-save-name serve_reshard_fs_smoke
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G021 \
      --fs-artifact bench_results/serve_reshard_fs_smoke.json
    exec python - <<'PYEOF'
import json
extras = [e["extra"] for e in json.load(open("bench_results/serve_reshard_fs_smoke.json"))
          if e.get("extra", {}).get("family") == "serve"]
x = extras[0]
assert x["verify_ok"], "reshard fs smoke failed oracle byte-verify"
assert x["reshard"] and x["reshard"]["state"] == "done", x["reshard"]
assert x["reshard"]["partition_errors"] == [], x["reshard"]
fo = x["fs_ops"]
assert fo["sanitized"] and fo["reshard"], fo
assert fo["protocols"].get("reshard", 0) > 0, fo["protocols"]
assert fo["unattributed"] == {}, fo["unattributed"]
assert set(fo["ops"]) <= set(fo["protocols"]), (fo["ops"], fo["protocols"])
print(f"reshard fs leg: {fo['protocols']['reshard']} reshard protocol "
      f"entries attributed ({sum(fo['protocols'].values())} total), "
      "zero unattributed, G021 clean both directions")
PYEOF
    ;;
  *)
    echo "unknown family: $family (expected: serve, serve-repl, serve-faults, serve-soak, serve-longhaul, serve-tier, serve-stream, serve-open, serve-reshard)" >&2
    exit 2
    ;;
esac
