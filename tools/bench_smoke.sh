#!/usr/bin/env bash
# Sub-minute bench smoke for CI, runnable alongside tools/tier1.sh.
#
# Usage: tools/bench_smoke.sh [--family serve|serve-faults]   (repo root)
#
# The serve family (the default) drains a tiny document fleet through the
# macro-round engine (K=4) on host CPU and exits NONZERO when the in-run
# oracle byte-verification fails (`verify_ok: false`) — the runner's exit
# code carries the gate, so a correctness regression in the serving hot
# path fails CI even when every unit test was green.
#
# The serve-faults family is the CHAOS smoke: the same tiny fleet drained
# under a seeded FaultPlan (spool corruption, mid-macro device-state
# loss, queue-overflow burst, duplicated batch, host stall) with the
# write-ahead journal + snapshot barriers enabled.  It exits NONZERO when
# the byte-verify fails OR any injected fault goes unfired/unrecovered —
# recovery itself is the thing under test.
#
# Artifacts land in bench_results/ under smoke-specific names so they
# never clobber committed headline numbers.
set -euo pipefail

# Stage 0: the fast lint gate (graftlint + ruff-if-installed, sub-10s)
# — a hygiene regression fails the bench smoke before any fleet spins
# up.  See tools/lint.sh for the suppression escape hatch.
bash "$(dirname "$0")/lint.sh" || { echo "bench_smoke: lint gate failed" >&2; exit 1; }

family="serve"
while [ $# -gt 0 ]; do
  case "$1" in
    --family) family="$2"; shift 2 ;;
    *) echo "unknown argument: $1" >&2; exit 2 ;;
  esac
done

case "$family" in
  serve)
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-save-name serve_smoke
    # Sanitized leg: the same drain under CRDT_BENCH_SANITIZE_SYNCS=1 —
    # any host sync outside a declared `# graftlint: fence` raises at
    # its callsite and fails this smoke (the dynamic proof of the G002
    # "syncs only at boundaries" invariant)...
    timeout -k 10 300 env JAX_PLATFORMS=cpu CRDT_BENCH_SANITIZE_SYNCS=1 \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-save-name serve_smoke_sanitized
    # ...and the G011 fence-cost cross-check closes the loop: every
    # declared fence must have crossed in that run's boundary_syncs
    # counters (dead fences fail), every runtime counter must map back
    # to a declared fence (unattributed boundaries fail).
    python -m crdt_benches_tpu.lint crdt_benches_tpu --select G011 \
      --sync-artifact bench_results/serve_smoke_sanitized.json
    # Traced leg: the same drain with the obs/trace.py span tracer
    # armed.  Two gates: the emitted Chrome trace must validate against
    # the schema (spans nested, fence instants inside their owning
    # span), and armed-tracing THROUGHPUT overhead vs the plain leg
    # must stay within 5% (bench_compare with a tightened threshold;
    # the p99 of a tiny smoke drain is too noisy to gate that hard —
    # the 2% headline overhead claim is measured on the full
    # serve/mixed/4096 fleet where run noise is smaller).
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-trace bench_results/serve_smoke_trace.json \
        --serve-save-name serve_smoke_traced
    python -m crdt_benches_tpu.obs.trace bench_results/serve_smoke_trace.json
    exec python tools/bench_compare.py \
      bench_results/serve_smoke_traced.json bench_results/serve_smoke.json \
      --max-throughput-regress 5
    ;;
  serve-faults)
    exec timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python -m crdt_benches_tpu.bench.runner --family serve \
        --serve-docs 24 --serve-mix mixed --serve-batch 16 \
        --serve-macro 4 --serve-batch-chars 64 \
        --serve-classes 256,1024,4096,8192,49152 \
        --serve-slots 16,6,2,2,2 \
        --serve-arrival-span 2 --serve-verify-sample 6 \
        --serve-journal auto --serve-snapshot-every 3 \
        --serve-queue-cap 128 \
        --serve-faults "seed=5,span=5,spool_corrupt=1,device_loss=1,queue_overflow=1,dup_batch=1,stall=1" \
        --serve-save-name serve_faults_smoke
    ;;
  *)
    echo "unknown family: $family (expected: serve, serve-faults)" >&2
    exit 2
    ;;
esac
