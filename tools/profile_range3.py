"""Truncated-pipeline TPU profile of apply_range_batch: stage k runs the
real apply dataflow up to stage k (everything downstream of the scan-carried
doc, so XLA cannot hoist), returns the carry doc plus a tiny dependence on
the stage output.  Successive deltas = per-stage cost.

Usage: python tools/profile_range3.py [R] [B] [trace] [K] [coalesce]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from crdt_benches_tpu.traces.loader import load_testing_data
from crdt_benches_tpu.traces.tensorize import tensorize_ranges
from crdt_benches_tpu.engine.replay_range import RangeReplayEngine
from crdt_benches_tpu.ops.resolve_range_pallas import resolve_range_pallas
from crdt_benches_tpu.ops.apply_range import (
    _BIG,
    _prev_value,
    _two_level_vis,
    extract_range_tokens,
)
from crdt_benches_tpu.ops.apply2 import (
    LANE,
    _mxu_spread,
    count_le_two_level,
    init_state3,
)


def fetch(x):
    return np.asarray(jax.tree.leaves(x)[-1]).reshape(-1)[0]


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    fetch(r)
    return (time.perf_counter() - t0) / n


def staged_apply(state_doc, length, nvis, tokens, dints, slot0_b,
                 nbits: int, stage: int):
    """apply_range_batch truncated after `stage`.  Returns (R, 1) int32
    depending on everything computed so far."""
    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    R, C = state_doc.shape
    T = ttype.shape[1]
    B = dlo.shape[1]
    drop = jnp.int32(C + 7)
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)

    vis_bit = jnp.bitwise_and(state_doc, 1)
    cvt, tile_base, tmax_abs = _two_level_vis(state_doc, length)
    if stage == 0:
        return (
            jnp.sum(tile_base, axis=1, keepdims=True)
            + jnp.sum(cvt.astype(jnp.int32), axis=1, keepdims=True)
        )

    has_del = dlo >= 0
    live, gvis, cumlen = extract_range_tokens(ttype, ta, tch, tlen, v0=nvis)
    if stage == 1:
        return (
            jnp.sum(gvis + cumlen, axis=1, keepdims=True)
            + jnp.sum(cvt.astype(jnp.int32), axis=1, keepdims=True)
        )

    allq = count_le_two_level(
        cvt, tile_base, tmax_abs,
        jnp.concatenate(
            [
                jnp.where(has_del, dlo, 0),
                jnp.where(has_del, dhi, 0),
                jnp.where(live, gvis, 0),
            ],
            axis=1,
        ),
    )
    lo_phys = allq[:, :B]
    hi_phys = allq[:, B : 2 * B]
    gq_phys = allq[:, 2 * B :]
    if stage == 2:
        return jnp.sum(allq, axis=1, keepdims=True)

    starts, = _mxu_spread(
        jnp.where(has_del, lo_phys, drop), [has_del.astype(jnp.int32)], C
    )
    stops, = _mxu_spread(
        jnp.where(has_del, hi_phys + 1, drop), [has_del.astype(jnp.int32)], C
    )
    in_del = jnp.cumsum(starts - stops, axis=1) > 0
    doc = state_doc - (vis_bit & in_del.astype(jnp.int32))
    if stage == 3:
        return (
            jnp.sum(doc, axis=1, keepdims=True)
            + jnp.sum(allq, axis=1, keepdims=True)
        )

    at_end = gvis >= nvis[:, None]
    g_phys = jnp.where(at_end, length[:, None], gq_phys)
    dest0 = jnp.where(live, g_phys + cumlen, drop)
    dstop = jnp.where(live, dest0 + tlen, drop)
    s1, = _mxu_spread(dest0, [live.astype(jnp.int32)], C)
    s2, = _mxu_spread(dstop, [live.astype(jnp.int32)], C)
    ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
    cnt = jnp.cumsum(ind, axis=1)
    if stage == 4:
        return (
            jnp.sum(doc, axis=1, keepdims=True) + cnt[:, -1:]
            + jnp.sum(ind, axis=1, keepdims=True)
        )

    slot0_t = jnp.where(
        live,
        jnp.take(
            jnp.concatenate([slot0_b, jnp.zeros((1,), jnp.int32)]),
            jnp.clip(ta, 0, slot0_b.shape[0]),
        ),
        0,
    )
    delta = jnp.where(live, slot0_t + tch - dest0, 0)
    prev_live_delta = _prev_value(delta, live)
    ddelta = jnp.where(live, delta - prev_live_delta, 0)
    dpos_ = jnp.where(live, dest0, drop)
    pos_chunks = [
        jnp.bitwise_and(v, 127)
        for v in (
            jnp.where(ddelta > 0, ddelta, 0),
            jnp.right_shift(jnp.where(ddelta > 0, ddelta, 0), 7),
            jnp.right_shift(jnp.where(ddelta > 0, ddelta, 0), 14),
            jnp.where(ddelta < 0, -ddelta, 0),
            jnp.right_shift(jnp.where(ddelta < 0, -ddelta, 0), 7),
            jnp.right_shift(jnp.where(ddelta < 0, -ddelta, 0), 14),
        )
    ]
    p0, p1, p2, n0, n1, n2 = _mxu_spread(dpos_, pos_chunks, C)
    dd_dense = (
        p0 + jnp.left_shift(p1, 7) + jnp.left_shift(p2, 14)
        - n0 - jnp.left_shift(n1, 7) - jnp.left_shift(n2, 14)
    )
    delta_cum = jnp.cumsum(dd_dense, axis=1)
    fill_slot = col + delta_cum
    fill_dense = jnp.where(ind > 0, jnp.left_shift(fill_slot + 2, 1) | 1, 0)
    if stage == 5:
        return (
            jnp.sum(doc, axis=1, keepdims=True) + cnt[:, -1:]
            + jnp.sum(fill_dense, axis=1, keepdims=True)
        )

    cntind = jnp.left_shift(cnt, 1) | ind
    from crdt_benches_tpu.ops.expand_pallas import expand_packed

    doc = expand_packed(doc, cntind, nbits=nbits)
    doc = doc + fill_dense
    n_ins = jnp.sum(jnp.where(live, tlen, 0), axis=1)
    length2 = length + n_ins
    beyond = col >= length2[:, None]
    doc = jnp.where(beyond, jnp.int32(2), doc)
    return jnp.sum(doc, axis=1, keepdims=True) + length2[:, None]


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    B = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    trace_name = sys.argv[3] if len(sys.argv) > 3 else "automerge-paper"
    K = int(sys.argv[4]) if len(sys.argv) > 4 else 16
    coalesce = (len(sys.argv) > 5 and sys.argv[5] == "1")

    trace = load_testing_data(trace_name)
    if coalesce:
        from crdt_benches_tpu.traces.tensorize import coalesce_patches

        rt = tensorize_ranges(
            trace, batch=B, coalesce=True,
            patches=list(coalesce_patches(trace)),
        )
    else:
        rt = tensorize_ranges(trace, batch=B)
    eng = RangeReplayEngine(rt, n_replicas=R)
    C = eng.capacity
    nb = rt.n_batches
    print(
        f"R={R} B={B} C={C} n_batches={nb} nbits={eng.nbits}"
        f" coalesce={coalesce} trace={trace_name} K={K}"
    )

    mid = nb // 2
    kind_b, pos_b, rlen_b, slot0_b = rt.batched()
    kind = jnp.asarray(kind_b[mid])
    pos = jnp.asarray(pos_b[mid])
    rlen = jnp.asarray(rlen_b[mid])
    slot0 = jnp.asarray(slot0_b[mid])
    v0 = jnp.full((R,), int(pos_b[mid].max()) + 1, jnp.int32)
    tcap = eng.token_caps[min(mid // eng.chunk, len(eng.token_caps) - 1)]

    st = init_state3(R, C, C // 2)
    tokens, dints, _ = jax.jit(
        lambda k, p, r, v: resolve_range_pallas(k, p, r, v, token_cap=tcap)
    )(kind, pos, rlen, v0)
    T = tokens[0].shape[1]

    @jax.jit
    def nop(doc):
        def b(c, _):
            return c + 1, None

        return jax.lax.scan(b, doc[:, :1], None, length=K)[0]

    base = timeit(lambda: nop(st.doc))
    print(f"floor: {base/K*1e3:.3f} ms/iter")

    def make(stage):
        @jax.jit
        def run(doc, length, nvis, tokens, dints, slot0):
            def b(c, _):
                # value-opaque zero: XLA cannot fold it, so the body stays
                # inside the scan and re-runs every iteration
                z = jnp.where(c == jnp.int32(-123456789), 1, 0)
                out = staged_apply(
                    doc + z, length, nvis, tokens, dints, slot0,
                    eng.nbits, stage,
                )
                return jnp.minimum(c, out), None

            return jax.lax.scan(
                b, doc[:, :1], None, length=K
            )[0]

        return lambda: run(st.doc, st.length, st.nvis, tokens, dints, slot0)

    names = [
        "0 two_level_vis",
        "1 + extract_tokens",
        "2 + count_le queries",
        "3 + del spreads+cumsum",
        "4 + dest spreads+cnt",
        "5 + delta spread+fill",
        "6 + expand (full)",
    ]
    prev = 0.0
    for stage, name in enumerate(names):
        t = (timeit(make(stage)) - base) / K
        print(f"{name:28s} {t*1e3:9.3f} ms  (+{(t-prev)*1e3:8.3f})")
        prev = t


if __name__ == "__main__":
    main()
