"""Scatter-free replica-batched batch application (the TPU fast path).

The original apply (ops/apply.py) maintains slot-indexed visibility plus a
doc-order permutation and rebuilds the permutation each batch with large
scatters.  Measured on TPU, arbitrary-index scatters/gathers over the
capacity-sized arrays serialize (~10ms per batch each at C≈180k) while
vector passes, small B-row scatters, and MXU matmuls are orders of magnitude
cheaper.  This module reformulates the whole batch application in those fast
primitives only:

- State is **doc-order only**: ``order[R, C]`` (slot ids, tombstones
  included) and ``vis[R, C]`` (visibility *in document order*).  No
  slot-indexed array is touched in the hot path; by-slot views are derived
  once at decode time.
- rank -> physical-position resolution (for deletes and insert gaps) is a
  **tiled searchsorted**: the monotone ``cumsum(vis)`` is cut into 128-lane
  tiles; a query finds its tile by comparing against tile maxima, fetches
  the tile's row with a one-hot **MXU matmul** (f32 is exact for values
  < 2^24), and counts within the row.  No binary-search gather chains.
- The order/vis merge (old entries shift right by the number of insert
  destinations before them; inserts fill the holes) is a **log-shift
  expansion**: dest-side gather ``y[d] = x[d - r(d)]`` decomposed over the
  bits of ``r`` with static rolls.  Correct because insert destinations are
  distinct, so ``r = cumsum(dest indicator)`` is monotone and 1-Lipschitz:
  if bit b of r(d) is set, ``r(d) - r(d - 2^b) <= 2^b`` keeps both in the
  same higher-bit block, which is exactly the invariant the bit-recursion
  needs (see _expand).
- Per-op insert destinations use a B x B comparison matrix instead of a
  histogram scatter.

Semantics are identical to ops/apply.py `apply_batch` (differentially
tested); this is the capability of the reference CRDTs' internal index
structures (e.g. diamond-types' range tree, reference src/rope.rs:105-137)
re-expressed in the primitives the MXU/VPU actually execute well.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from .resolve import ORIGIN_BATCH, ResolvedBatch

LANE = 128


class ReplayState(NamedTuple):
    """Replica-batched doc-order state (leading replica axis R everywhere)."""

    order: jax.Array  # int32[R, C] slot ids in doc order (incl. tombstones)
    vis: jax.Array  # int32[R, C]  0/1 visibility by doc-order position
    length: jax.Array  # int32[R]  used entries of order
    nvis: jax.Array  # int32[R]  visible char count


def init_state2(n_replicas: int, capacity: int, n_init: int = 0) -> ReplayState:
    idx = jnp.arange(capacity, dtype=jnp.int32)
    order = jnp.where(idx < n_init, idx, -1)
    vis = (idx < n_init).astype(jnp.int32)
    bc = lambda x: jnp.broadcast_to(x, (n_replicas,) + jnp.shape(x))
    return ReplayState(
        order=bc(order),
        vis=bc(vis),
        length=jnp.full((n_replicas,), n_init, jnp.int32),
        nvis=jnp.full((n_replicas,), n_init, jnp.int32),
    )


def count_le_tiled(sorted_rc: jax.Array, q: jax.Array) -> jax.Array:
    """#{i : sorted_rc[r, i] <= q[r, b]} for a row-wise nondecreasing array.

    sorted_rc: int32[R, C] (C a multiple of 128), q: int32[R, B] ->
    int32[R, B].  Tile maxima locate the crossing tile, one batched one-hot
    matmul (MXU) fetches the tile row, a 128-lane compare finishes.
    """
    R, C = sorted_rc.shape
    B = q.shape[1]
    nt = C // LANE
    tiles = sorted_rc.reshape(R, nt, LANE)
    tmax = tiles[:, :, -1]  # (R, nt) — nondecreasing
    if nt <= 256:
        # Single-level: compare against all tile maxima.
        nfull = jnp.sum(
            (tmax[:, None, :] <= q[:, :, None]).astype(jnp.int32), axis=2
        )  # (R, B)
    else:
        # Two-level: narrow to a 128-tile super-block first, so the compare
        # volume is B*(ns + 128) instead of B*nt — required for large B.
        ns = -(-nt // LANE)
        big = np.int32(2**31 - 1)
        pad = ns * LANE - nt
        tmax_p = jnp.concatenate(
            [tmax, jnp.full((R, pad), big, jnp.int32)], axis=1
        ) if pad else tmax
        sup = tmax_p.reshape(R, ns, LANE)
        smax = sup[:, :, -1]  # (R, ns)
        nsf = jnp.sum(
            (smax[:, None, :] <= q[:, :, None]).astype(jnp.int32), axis=2
        )
        sq = jnp.minimum(nsf, ns - 1)
        # the clamp region (nsf == ns) reads the LAST super-block; the
        # nfull >= nt select below overwrites those queries with C
        srow = jnp.take_along_axis(sup, sq[:, :, None], axis=1, mode="clip")  # graftlint: mask=count-le-clamp
        nfull = sq * LANE + jnp.sum(
            (srow <= q[:, :, None]).astype(jnp.int32), axis=2
        )
    tq = jnp.minimum(nfull, nt - 1)
    # Fetch each query's crossing tile row.  Integer gather of B rows (exact;
    # an MXU one-hot matmul here silently rounds through bf16 passes and
    # would corrupt cumvis values above 2^8-mantissa range).
    rows = jnp.take_along_axis(  # graftlint: mask=count-le-clamp
        tiles, tq[:, :, None], axis=1, mode="clip"
    )  # (R, B, LANE)
    within = jnp.sum((rows <= q[:, :, None]).astype(jnp.int32), axis=2)
    return jnp.where(nfull >= nt, C, nfull * LANE + within)  # graftlint: mask=count-le-clamp


def rank_to_phys2(cumvis: jax.Array, rank: jax.Array) -> jax.Array:
    """Doc-order position of the visible char with rank[r, b] (0-based),
    given inclusive cumvis[R, C].  Equals #{cumvis <= rank}."""
    return count_le_tiled(cumvis, rank)


def _expand(arrays, r, nbits: int):
    """Dest-side log-shift expansion: for each array x, returns y with
    y[d] = x[d - r[d]] (r monotone nondecreasing, 1-Lipschitz, >= 0).
    Positions with d - r[d] < 0 get unspecified values (callers overwrite)."""
    R, C = r.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    ys = list(arrays)
    for b in reversed(range(nbits)):
        step = 1 << b
        take = (jnp.bitwise_and(r, step) != 0) & (col >= step)
        ys = [jnp.where(take, jnp.roll(y, step, axis=1), y) for y in ys]
    return ys


def apply_batch2(
    state: ReplayState, resolved: ResolvedBatch, slots: jax.Array
) -> ReplayState:
    """Apply one resolved batch to replica-batched doc-order state.

    resolved leaves are (R, B); ``slots`` int32[B] preassigned slot ids for
    insert ops (shared across replicas).  Same semantics as
    ops/apply.py apply_batch, without slot-indexed state or big scatters.
    All row-wise scatters are ADDs (scatter-set serializes per row on the
    TPU runtime; add vectorizes): deletes subtract from a guaranteed-1 vis
    bit, and insert fills add into holes the expansion zeroed.
    """
    R, C = state.order.shape
    B = slots.shape[0]
    drop = jnp.int32(C + 7)  # out-of-range for mode="drop" scatters
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    valid = col < state.length[:, None]

    cumvis = jnp.cumsum(state.vis * valid, axis=1)

    # ---- deletes of pre-batch chars: rank -> doc position, clear vis ----
    # Targets are distinct visible chars (the resolver tombstones each char
    # at most once per batch), so add(-1) on a 1-bit is an exact clear.
    dr = resolved.del_rank
    has_del = dr >= 0
    dphys = rank_to_phys2(cumvis, jnp.where(has_del, dr, 0))
    vis = _scatter_rows(
        state.vis, jnp.where(has_del, dphys, drop), -1, C, add=True
    )

    # ---- insert destinations ----
    is_ins = resolved.ins_gvis >= 0
    gv = resolved.ins_gvis
    at_end = gv >= state.nvis[:, None]
    g_phys = jnp.where(
        at_end,
        state.length[:, None],
        rank_to_phys2(cumvis, jnp.where(is_ins, gv, 0)),
    )
    g_phys = jnp.where(is_ins, g_phys, drop)
    # #inserts at strictly smaller gaps (B x B compare; no histogram).
    smaller = (g_phys[:, :, None] > g_phys[:, None, :]) & is_ins[:, None, :]
    n_before = jnp.sum(smaller.astype(jnp.int32), axis=2)
    dest = jnp.where(is_ins, g_phys + n_before + resolved.ins_seq, drop)

    # ---- merge: shift old entries right past their insert destinations ----
    ind = _scatter_rows(jnp.zeros((R, C), jnp.int32), dest, 1, C, add=True)
    cnt = jnp.cumsum(ind, axis=1)  # r(d): monotone, 1-Lipschitz
    nbits = max(1, (B).bit_length())
    if jax.default_backend() == "tpu":
        from .expand_pallas import expand_fill_zero

        order, vis = expand_fill_zero(state.order, vis, cnt, ind, nbits=nbits)
    else:
        order, vis = _expand([state.order, vis], cnt, nbits)
        hole = ind != 0
        order = jnp.where(hole, 0, order)
        vis = jnp.where(hole, 0, vis)

    # ---- fill the zeroed holes with the batch inserts (adds) ----
    slots_b = jnp.broadcast_to(slots[None, :], (R, B))
    order = _scatter_rows(order, dest, slots_b, C, add=True)
    vis = _scatter_rows(
        vis, dest, resolved.ins_alive.astype(jnp.int32), C, add=True
    )

    n_ins = jnp.sum(is_ins.astype(jnp.int32), axis=1)
    n_live = jnp.sum((is_ins & resolved.ins_alive).astype(jnp.int32), axis=1)
    n_del = jnp.sum(has_del.astype(jnp.int32), axis=1)
    length = state.length + n_ins
    beyond = col >= length[:, None]
    return ReplayState(
        order=jnp.where(beyond, -1, order),
        vis=jnp.where(beyond, 0, vis),
        length=length,
        nvis=state.nvis - n_del + n_live,
    )


def _scatter_rows(arr, idx, val, C, add: bool = False):
    """Row-wise B-index scatter into (R, C) — small-B scatters are cheap on
    TPU (unlike capacity-sized ones).  idx out of [0, C) are dropped."""
    if isinstance(val, int):
        val = jnp.full(idx.shape, val, arr.dtype)
    val = val.astype(arr.dtype)
    if add:
        return jax.vmap(lambda a, i, v: a.at[i].add(v, mode="drop"))(
            arr, idx, val
        )
    return jax.vmap(lambda a, i, v: a.at[i].set(v, mode="drop"))(
        arr, idx, val
    )


def spread_add_rows(idx, val, C: int):
    """Backend-dispatched exact dense spread: int32[R, C] with
    ``val[r, b]`` added at ``idx[r, b]`` (out-of-range indices dropped;
    indices distinct per row).

    On TPU this is the 7-bit-chunk one-hot MXU matmul (_mxu_spread —
    capacity-sized scatters serialize on the TPU runtime).  Off-TPU the
    MXU trick is backwards: the one-hot einsum burns R*B*(C/128)*128
    MACs on a vector unit while a native row scatter-add is O(R*B) — the
    serve/ fleet's CPU-mesh hot path uses this entry point so each
    backend gets the primitive it actually executes well.

    TPU precondition: ``val`` in [0, 2^28) so four 7-bit chunks cover it
    (callers with signed values split sign first, as apply_range.py's
    ddelta spread does).  Off-TPU any int32 value is exact."""
    if jax.default_backend() == "tpu":
        chunks = [
            jnp.bitwise_and(val, 127),
            jnp.bitwise_and(jnp.right_shift(val, 7), 127),
            jnp.bitwise_and(jnp.right_shift(val, 14), 127),
            jnp.bitwise_and(jnp.right_shift(val, 21), 127),
        ]
        outs = _mxu_spread(idx, chunks, C)
        return (
            outs[0]
            + jnp.left_shift(outs[1], 7)
            + jnp.left_shift(outs[2], 14)
            + jnp.left_shift(outs[3], 21)
        )
    R = idx.shape[0]
    return _scatter_rows(
        jnp.zeros((R, C), jnp.int32), idx, val, C, add=True
    )


class PackedState(NamedTuple):
    """Packed doc-order state: one int32 per position.

    ``doc = ((order + 2) << 1) | vis`` — the slot id (order, -1 for unused)
    and the visibility bit travel as a single array, halving HBM traffic and
    VMEM footprint everywhere in the hot path.  The packing survives the two
    mutation kinds directly: a delete is ``add(-1)`` (clears a guaranteed-1
    vis bit), an insert fill is ``add(packed value)`` into a zeroed hole.
    """

    doc: jax.Array  # int32[R, C]
    length: jax.Array  # int32[R]
    nvis: jax.Array  # int32[R]


def pack_doc(order, vis):
    return jnp.left_shift(order + 2, 1) | vis


def unpack_doc(doc):
    return jnp.right_shift(doc, 1) - 2, jnp.bitwise_and(doc, 1)


def init_state3(n_replicas: int, capacity: int, n_init: int = 0) -> PackedState:
    s2 = init_state2(n_replicas, capacity, n_init)
    return PackedState(
        doc=pack_doc(s2.order, s2.vis), length=s2.length, nvis=s2.nvis
    )


def _mxu_spread(idx, vals_7bit_chunks, C: int, cb: int = 512):
    """Batched scatter-add via one-hot MXU matmuls: returns, for each 7-bit
    chunk array v in ``vals_7bit_chunks`` (each int32[R, B] with values in
    [0, 127]), the dense int32[R, C] array with v[r, b] added at position
    idx[r, b].  Indices must be distinct per row (out-of-range = dropped);
    then every output cell receives at most one contribution, so the bf16
    matmuls are exact.  On this TPU runtime a row-wise scatter-add costs
    ~53ns/row (serialized); the matmul form runs on the MXU at
    R*B*nt*128 MACs per chunk (~0.2ms at R=256, C=182k)."""
    return _mxu_spread_tc(idx, vals_7bit_chunks, C, cb=cb)[0]


@boundary(dtypes=("int32", None, "int32"))
def apply_batch3(
    state: PackedState, resolved: ResolvedBatch, slots: jax.Array
) -> PackedState:
    """apply_batch2 on the packed representation (see PackedState).

    All three B-row scatters of the v2 formulation are eliminated: delete
    clears, the insert-destination indicator, and the insert fills are
    spread to dense (R, C) arrays with exact one-hot MXU matmuls
    (_mxu_spread) and combined with vector adds.

    ``slots`` may be int32[B] (one op stream replayed by every row — the
    replica-parallel engines) or int32[R, B] (a different op stream per
    row — the serve/ document-fleet pool, where each lane is an
    independent document and ``resolved`` came from a per-row vmapped
    resolve_batch).
    """
    R, C = state.doc.shape
    B = slots.shape[-1]
    drop = jnp.int32(C + 7)
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    valid = col < state.length[:, None]

    vis_bit = jnp.bitwise_and(state.doc, 1)
    cumvis = jnp.cumsum(vis_bit * valid, axis=1)

    dr = resolved.del_rank
    has_del = dr >= 0
    dphys = jnp.where(
        has_del, rank_to_phys2(cumvis, jnp.where(has_del, dr, 0)), drop
    )

    is_ins = resolved.ins_gvis >= 0
    gv = resolved.ins_gvis
    g_phys = jnp.where(
        gv >= state.nvis[:, None],
        state.length[:, None],
        rank_to_phys2(cumvis, jnp.where(is_ins, gv, 0)),
    )
    g_phys = jnp.where(is_ins, g_phys, drop)
    if B <= 1024:
        # #inserts at strictly smaller gaps via a B x B compare.
        smaller = (
            (g_phys[:, :, None] > g_phys[:, None, :]) & is_ins[:, None, :]
        )
        n_before = jnp.sum(smaller.astype(jnp.int32), axis=2)
        dest = jnp.where(is_ins, g_phys + n_before + resolved.ins_seq, drop)
    else:
        # dest = g_phys + lexicographic rank of (g_phys, seq) among inserts
        # (identical interleave, avoids the B^2 blowup).  rank = double
        # argsort of a combined key; non-inserts key to the top and drop.
        # key fits int32 while C*(B+1) < 2^31 (holds for all four traces at
        # B=4096); non-inserts sort to the top and are dropped.
        key = jnp.where(
            is_ins,
            g_phys * jnp.int32(B + 1) + resolved.ins_seq,
            jnp.int32(2**31 - 1),
        )
        perm = jnp.argsort(key, axis=1, stable=True)
        rank = jnp.argsort(perm, axis=1, stable=True).astype(jnp.int32)
        dest = jnp.where(is_ins, g_phys + rank, drop)

    # Deletes: subtract a 0/1 indicator (each target has vis bit 1).
    (del_ind,) = _mxu_spread(dphys, [has_del.astype(jnp.int32)], C)
    doc = state.doc - del_ind

    # Insert destinations: indicator + packed fill values in 7-bit chunks,
    # all from the same one-hot pair.
    slots_b = jnp.broadcast_to(
        slots[None, :] if slots.ndim == 1 else slots, (R, B)
    )
    fill = jnp.where(
        is_ins, pack_doc(slots_b, resolved.ins_alive.astype(jnp.int32)), 0
    )
    chunks = [
        is_ins.astype(jnp.int32),
        jnp.bitwise_and(fill, 127),
        jnp.bitwise_and(jnp.right_shift(fill, 7), 127),
        jnp.bitwise_and(jnp.right_shift(fill, 14), 127),
        jnp.bitwise_and(jnp.right_shift(fill, 21), 127),
    ]
    ind, f0, f1, f2, f3 = _mxu_spread(dest, chunks, C)
    fill_dense = (
        f0
        + jnp.left_shift(f1, 7)
        + jnp.left_shift(f2, 14)
        + jnp.left_shift(f3, 21)
    )

    cnt = jnp.cumsum(ind, axis=1)
    nbits = max(1, (B).bit_length())
    cntind = jnp.left_shift(cnt, 1) | ind
    if jax.default_backend() == "tpu":
        from .expand_pallas import expand_packed

        doc = expand_packed(doc, cntind, nbits=nbits)
    else:
        (doc,) = _expand([doc], cnt, nbits)
        doc = jnp.where(ind != 0, 0, doc)

    doc = doc + fill_dense

    n_ins = jnp.sum(is_ins.astype(jnp.int32), axis=1)
    n_live = jnp.sum((is_ins & resolved.ins_alive).astype(jnp.int32), axis=1)
    n_del = jnp.sum(has_del.astype(jnp.int32), axis=1)
    length = state.length + n_ins
    beyond = col >= length[:, None]
    return PackedState(
        doc=jnp.where(beyond, pack_doc(-1, 0), doc),
        length=length,
        nvis=state.nvis - n_del + n_live,
    )


class PackedState4(NamedTuple):
    """PackedState plus a *maintained* visibility-prefix structure.

    ``cv_intile[r, c]`` is the inclusive cumsum of vis bits **within c's
    128-lane tile** (stored bf16 — values are <= 128, exact, and the only
    consumer is a one-hot bf16 einsum); ``vis_tile[r, t]`` is tile t's
    total.  Together they give absolute cumvis without ever running a
    capacity-sized cumsum in XLA: the fused apply kernel
    (apply_range_fused.apply_fused2, or expand_pallas.apply_fused_xla off
    TPU) re-emits both for the post-batch document each batch.
    """

    doc: jax.Array  # int32[R, C] packed ((slot+2)<<1)|vis
    cv_intile: jax.Array  # bfloat16[R, C]
    vis_tile: jax.Array  # int32[R, C // LANE]
    length: jax.Array  # int32[R]
    nvis: jax.Array  # int32[R]


def init_state4(n_replicas: int, capacity: int, n_init: int = 0) -> PackedState4:
    s3 = init_state3(n_replicas, capacity, n_init)
    R, C = s3.doc.shape
    nt = C // LANE
    vis = jnp.bitwise_and(s3.doc, 1).reshape(R, nt, LANE)
    cv = jnp.cumsum(vis, axis=2)
    return PackedState4(
        doc=s3.doc,
        cv_intile=cv.reshape(R, C).astype(jnp.bfloat16),
        vis_tile=cv[:, :, LANE - 1],
        length=s3.length,
        nvis=s3.nvis,
    )


def count_le_two_level(cv_intile, tile_base, tmax_abs, q):
    """#{i : cumvis_abs[r, i] <= q[r, b]} from the maintained two-level
    structure: cv_intile int32[R, C] (within-tile inclusive cumsum),
    tile_base int32[R, nt] (exclusive cross-tile prefix), tmax_abs
    int32[R, nt] (= tile_base + tile total, nondecreasing).  Same result as
    count_le_tiled(absolute_cumvis, q).

    The crossing tile is found by a fused compare-reduce over tile maxima
    (no materialized (R, B, nt) array); the crossing tile's row is fetched
    with one bf16 one-hot einsum (cv_intile is stored bf16 — values
    <= 128, exact); its cross-tile base is fetched by a FACTORED two-level
    one-hot (tq = 128*sq + wq): contract the within-super axis first so
    every intermediate is (R, B, ns) tiny.  take_along_axis here
    serializes per row (~21ns each) and was the single largest XLA cost of
    the apply step.
    """
    R, C = cv_intile.shape
    B = q.shape[1]
    nt = C // LANE
    tiles = cv_intile.reshape(R, nt, LANE)
    if nt <= 256:
        nfull = jnp.sum(
            (tmax_abs[:, None, :] <= q[:, :, None]).astype(jnp.int32),
            axis=2,
        )
    else:
        # Two-level narrowing (count_le_tiled's ns path): compare against
        # super-block maxima first so the compare volume is
        # B*(ns + LANE) instead of B*nt — at nt ~1400 the flat compare
        # alone was ~4ms/batch at R=1024 (XLA trace, r4).
        ns = -(-nt // LANE)
        big = np.int32(2**31 - 1)
        pad = ns * LANE - nt
        tmax_p = (
            jnp.concatenate(
                [tmax_abs, jnp.full((R, pad), big, jnp.int32)], axis=1
            )
            if pad
            else tmax_abs
        ).reshape(R, ns, LANE)
        smax = tmax_p[:, :, -1]  # (R, ns) nondecreasing
        nsf = jnp.sum(
            (smax[:, None, :] <= q[:, :, None]).astype(jnp.int32), axis=2
        )
        sq2 = jnp.minimum(nsf, ns - 1)
        ohs = (
            jax.lax.broadcasted_iota(jnp.int32, (R, B, ns), 2)
            == sq2[:, :, None]
        ).astype(jnp.bfloat16)
        # super rows hold tile maxima < C < 2^21: fetch via 7-bit chunks
        # (bf16-exact products, f32-exact sums), like the base fetch.
        srow = jnp.zeros((R, B, LANE), jnp.int32)
        n_ch = max(3, -(-((int(C) - 1).bit_length()) // 7))
        for k in range(n_ch):
            ck = jnp.bitwise_and(
                jnp.right_shift(tmax_p, 7 * k), 127
            ).astype(jnp.bfloat16)
            srow = srow + jnp.left_shift(
                jnp.einsum(
                    "rbs,rsl->rbl", ohs, ck,
                    preferred_element_type=jnp.float32,
                ).astype(jnp.int32),
                7 * k,
            )
        nfull = sq2 * LANE + jnp.sum(
            (srow <= q[:, :, None]).astype(jnp.int32), axis=2
        )
        nfull = jnp.minimum(nfull, nt)
    tq = jnp.minimum(nfull, nt - 1)
    oh = (
        jax.lax.broadcasted_iota(jnp.int32, (R, B, nt), 2) == tq[:, :, None]
    ).astype(jnp.bfloat16)
    rows = jnp.einsum(
        "rbt,rtl->rbl", oh, tiles, preferred_element_type=jnp.float32
    ).astype(jnp.int32)

    ns = -(-nt // LANE)
    pad = ns * LANE - nt
    base_p = (
        jnp.concatenate(
            [tile_base, jnp.zeros((R, pad), jnp.int32)], axis=1
        )
        if pad
        else tile_base
    ).reshape(R, ns, LANE)
    sq = jnp.right_shift(tq, 7)
    wq = jnp.bitwise_and(tq, 127)
    ohw = (
        jax.lax.broadcasted_iota(jnp.int32, (R, B, LANE), 2)
        == wq[:, :, None]
    ).astype(jnp.bfloat16)
    ssel = (
        jax.lax.broadcasted_iota(jnp.int32, (R, B, ns), 2) == sq[:, :, None]
    )
    base = jnp.zeros((R, B), jnp.int32)
    # tile_base < C: derive the chunk count from the static capacity so
    # capacities beyond 2^21 cannot silently drop high bits (the same
    # adaptive widening spread_fill_combo applies).
    n_chunks = max(3, -(-((int(C) - 1).bit_length()) // 7))
    for k in range(n_chunks):
        chunk = jnp.bitwise_and(
            jnp.right_shift(base_p, 7 * k), 127
        ).astype(jnp.bfloat16)
        tmp = jnp.einsum(
            "rbw,rsw->rbs", ohw, chunk, preferred_element_type=jnp.float32
        ).astype(jnp.int32)
        base = base + jnp.left_shift(
            jnp.sum(jnp.where(ssel, tmp, 0), axis=2), 7 * k
        )
    within = jnp.sum(
        (rows + base[:, :, None] <= q[:, :, None]).astype(jnp.int32), axis=2
    )
    return jnp.where(nfull >= nt, C, nfull * LANE + within)


def _excl_cumsum_small(x):
    """Exclusive cumsum along axis=1 of a small (R, nt) array."""
    inc = jnp.cumsum(x, axis=1)
    return inc - x


def _mxu_spread_tc(idx, vals_7bit_chunks, C: int, cb: int = 512):
    """_mxu_spread that additionally returns the per-tile index counts
    (int32[R, nt]) — reused by the fused kernel's cross-tile cnt base.

    ``cb`` bounds the one-hot's index-chunk width.  Each chunk iteration
    ACCUMULATES into the dense outputs — a full (R, C) read+write per
    iteration — so callers whose value set is a single array should pass
    ``cb >= B`` for a one-shot spread (the one-hot itself fuses into the
    convolution and never materializes; XLA trace, r4)."""
    R, B = idx.shape
    nt = C // LANE
    outs = [jnp.zeros((R, C), jnp.int32) for _ in vals_7bit_chunks]
    tcount = jnp.zeros((R, nt), jnp.int32)
    CB = cb if B > cb else B
    for c0 in range(0, B, CB):
        cb = min(CB, B - c0)
        idx_c = jax.lax.slice_in_dim(idx, c0, c0 + cb, axis=1)
        tq = jnp.right_shift(idx_c, 7)
        lq = jnp.bitwise_and(idx_c, 127)
        in_range = (idx_c >= 0) & (idx_c < C)
        oh_tile = (
            (
                jax.lax.broadcasted_iota(jnp.int32, (R, cb, nt), 2)
                == tq[:, :, None]
            )
            & in_range[:, :, None]
        ).astype(jnp.bfloat16)
        tcount = tcount + jnp.sum(oh_tile, axis=1).astype(jnp.int32)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (R, cb, LANE), 2)
        oh_lane = (lane_iota == lq[:, :, None]).astype(jnp.bfloat16)
        for i, v in enumerate(vals_7bit_chunks):
            vc = jax.lax.slice_in_dim(v, c0, c0 + cb, axis=1)
            vb = oh_lane * vc[:, :, None].astype(jnp.bfloat16)
            dense = jnp.einsum(
                "rbt,rbl->rtl", oh_tile, vb,
                preferred_element_type=jnp.float32,
            )
            outs[i] = outs[i] + dense.astype(jnp.int32).reshape(R, C)
    return outs, tcount


def spread_fill_combo(dest, fill, C: int):
    """Spread packed insert fills to a dense combo array for the fused
    apply kernels: returns (combo int32[R, C] = (fill << 1) | ind where ind
    marks insert destinations, cnt_base int32[R, nt] exclusive cross-tile
    prefix of destination counts).

    Three 8-bit chunks (one-hot spreads deliver exactly one contribution
    per cell, and integers <= 255 are exact in bf16) cover combo bits
    0..23, i.e. fill < 2**23 (capacity < 2**21, since
    fill = ((slot + 2) << 1) | vis < 4 * capacity).  Capacities beyond
    that gain a FOURTH chunk for combo bits 24..30 (fill < 2**30, i.e.
    capacity < 2**28 — the int32 combo ceiling); the chunk count is
    static per compiled shape, so small documents never pay for it.
    ``fill`` must be 0 where ``dest`` is out of range.
    """
    if C >= 1 << 28:
        raise ValueError(
            f"capacity {C} >= 2^28: combo = (fill << 1) | ind no longer"
            " fits int32"
        )
    chunks = [
        jnp.bitwise_and(fill, 127) * 2 + 1,
        jnp.bitwise_and(jnp.right_shift(fill, 7), 255),
        jnp.bitwise_and(jnp.right_shift(fill, 15), 255),
    ]
    wide = 4 * C > 1 << 23  # fill can exceed the 3-chunk range
    if wide:
        chunks.append(jnp.bitwise_and(jnp.right_shift(fill, 23), 127))
    outs, ind_tcount = _mxu_spread_tc(dest, chunks, C)
    combo = outs[0] + jnp.left_shift(outs[1], 8) + jnp.left_shift(outs[2], 16)
    if wide:
        combo = combo + jnp.left_shift(outs[3], 24)
    return combo, _excl_cumsum_small(ind_tcount)


def apply_batch4(
    state: PackedState4, resolved: ResolvedBatch, slots: jax.Array
) -> PackedState4:
    """apply_batch3 with (a) cumvis read from the maintained two-level
    structure instead of a per-batch (R, C) cumsum, and (b) delete-apply +
    expansion + fill + next-batch cumvis emission fused into one Pallas
    kernel (apply_range_fused.apply_fused2).  Falls back to plain XLA
    (expand_pallas.apply_fused_xla) off-TPU.
    """
    R, C = state.doc.shape
    B = slots.shape[0]
    nt = C // LANE
    drop = jnp.int32(C + 7)

    tile_base = _excl_cumsum_small(state.vis_tile)
    tmax_abs = tile_base + state.vis_tile

    dr = resolved.del_rank
    has_del = dr >= 0
    is_ins = resolved.ins_gvis >= 0
    gv = resolved.ins_gvis
    # One fused two-level query for both delete ranks and insert gaps —
    # shares the tile-maxima compare and row-fetch einsum setup.
    both = count_le_two_level(
        state.cv_intile, tile_base, tmax_abs,
        jnp.concatenate(
            [jnp.where(has_del, dr, 0), jnp.where(is_ins, gv, 0)], axis=1
        ),
    )
    dphys = jnp.where(has_del, both[:, :B], drop)
    g_phys = jnp.where(
        gv >= state.nvis[:, None], state.length[:, None], both[:, B:]
    )
    g_phys = jnp.where(is_ins, g_phys, drop)
    if B <= 1024:
        smaller = (
            (g_phys[:, :, None] > g_phys[:, None, :]) & is_ins[:, None, :]
        )
        n_before = jnp.sum(smaller.astype(jnp.int32), axis=2)
        dest = jnp.where(is_ins, g_phys + n_before + resolved.ins_seq, drop)
    else:
        key = jnp.where(
            is_ins,
            g_phys * jnp.int32(B + 1) + resolved.ins_seq,
            jnp.int32(2**31 - 1),
        )
        perm = jnp.argsort(key, axis=1, stable=True)
        rank = jnp.argsort(perm, axis=1, stable=True).astype(jnp.int32)
        dest = jnp.where(is_ins, g_phys + rank, drop)

    (del_ind,), _ = _mxu_spread_tc(dphys, [has_del.astype(jnp.int32)], C)
    # XLA fuses this subtraction into the spread epilogue — one HBM write.
    doc_predel = state.doc - del_ind

    slots_b = jnp.broadcast_to(slots[None, :], (R, B))
    fill = jnp.where(
        is_ins, pack_doc(slots_b, resolved.ins_alive.astype(jnp.int32)), 0
    )
    combo, cnt_base = spread_fill_combo(dest, fill, C)

    n_ins = jnp.sum(is_ins.astype(jnp.int32), axis=1)
    n_live = jnp.sum((is_ins & resolved.ins_alive).astype(jnp.int32), axis=1)
    n_del = jnp.sum(has_del.astype(jnp.int32), axis=1)
    length = state.length + n_ins

    nbits = max(1, (B).bit_length())
    from .expand_pallas import (
        FUSED_STACK_BYTES_PER_POS,
        apply_fused_xla,
    )

    if (
        jax.default_backend() == "tpu"
        and FUSED_STACK_BYTES_PER_POS * C <= 96 * 2**20
    ):
        from .apply_range_fused import apply_fused2

        doc, cv, vt = apply_fused2(
            doc_predel, combo, cnt_base, length, nbits=nbits
        )
    else:
        doc, cv, vt = apply_fused_xla(
            doc_predel, combo, cnt_base, length, nbits=nbits
        )
    return PackedState4(
        doc=doc,
        cv_intile=cv,
        vis_tile=vt,
        length=length,
        nvis=state.nvis - n_del + n_live,
    )


def decode_state4(state: PackedState4, chars: jax.Array, replica: int = 0):
    s3 = PackedState(doc=state.doc, length=state.length, nvis=state.nvis)
    return decode_state3(s3, chars, replica)


def decode_state3(state: PackedState, chars: jax.Array, replica: int = 0):
    order, vis = unpack_doc(state.doc)
    s2 = ReplayState(
        order=order, vis=vis, length=state.length, nvis=state.nvis
    )
    return decode_state2(s2, chars, replica)


def decode_state2(state: ReplayState, chars: jax.Array, replica: int = 0):
    """Materialize one replica's visible document: (codepoints[C], nvis).
    Off the hot path — plain gathers/scatter are fine here."""
    order = state.order[replica]
    vis = state.vis[replica]
    C = order.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    valid = idx < state.length[replica]
    v = (vis > 0) & valid
    cum = jnp.cumsum(v.astype(jnp.int32))
    out = (
        jnp.zeros(C, jnp.int32)
        .at[jnp.where(v, cum - 1, C)]
        .set(chars[jnp.clip(order, 0, chars.shape[0] - 1)], mode="drop")
    )
    return out, cum[-1]
