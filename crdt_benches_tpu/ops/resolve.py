"""Within-batch op resolution — the sequential heart, kept tiny on purpose.

The reference's hot loop applies one patch at a time to a mutable rope, so
every op's position depends on all prior ops (reference src/main.rs:30-34 and
SURVEY.md section 3.5 — "the core algorithmic obstacle").  The TPU engine
restructures this: ops are processed in batches of ``B``; the *sequential*
per-op dependency is resolved by a ``lax.scan`` over a small **token list**
(size O(B), independent of document size), and only the batch *summary* is
applied to the big per-replica state tensors in one vectorized pass
(ops/apply.py).

Token list
----------
The current visible document during a batch is represented as a sequence of
tokens:

- ``RUN(a, len)`` — a run of ``len`` surviving pre-batch visible chars,
  identified by their pre-batch visible *ranks* ``a .. a+len-1`` (rank = index
  among chars visible at batch start).  Deletes split runs, so runs only ever
  contain surviving chars and stay ascending.
- ``INS(j)`` — the char inserted by batch op ``j`` (length 1).
- ``DEAD(j)`` — a batch insert later deleted in the same batch (length 0).
  Kept in place so it still receives a stable position for its tombstone.

Crucially the scan state depends on the pre-batch document **only through its
visible char count** ``v0`` — ranks are resolved to physical slots after the
scan, outside the sequential region.

Outputs per op ``j`` (all fixed-shape, -1 = not applicable):

- ``del_rank[j]``   pre-batch visible rank tombstoned by a DELETE op
- ``ins_gvis[j]``   for INSERT ops: rank of the first *surviving* pre-batch
                    char after the inserted char at batch end (``v0`` = none —
                    the insert belongs at the document tail)
- ``ins_seq[j]``    tie-break order among batch inserts that share a gap
- ``ins_alive[j]``  1 unless the insert was deleted within the batch
- ``origin[j]``     identity of the char immediately left of the insert at
                    insert time: ``-1`` = document head, ``0 <= r < v0`` = the
                    pre-batch char of rank ``r``, ``ORIGIN_BATCH + k`` = the
                    char inserted by batch op ``k``.  This is the CRDT
                    left-origin (the analog of diamond-types' op-log parents,
                    reference src/rope.rs:117-126), used for update encoding
                    and merge.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..traces.tensorize import DELETE, INSERT

# Token types.
FREE, RUN, TINS, TDEAD = 0, 1, 2, 3

#: Origin codes >= ORIGIN_BATCH refer to batch op indices.
ORIGIN_BATCH = 1 << 24
#: Host-side constant on purpose: a module-scope *device* scalar (jnp.int32)
#: would be captured by every jit as a committed buffer, which on the axon
#: TPU tunnel forces a ~12ms slow dispatch path per executable launch.
_BIG = np.int32(1 << 30)


class ResolvedBatch(NamedTuple):
    del_rank: jax.Array  # int32[B]
    ins_gvis: jax.Array  # int32[B]  (-1 for non-insert ops)
    ins_seq: jax.Array  # int32[B]
    ins_alive: jax.Array  # bool[B]
    origin: jax.Array  # int32[B]  (-2 for non-insert ops)
    del_batch: jax.Array  # int32[B]  batch op index of a same-batch insert
    #                       killed by this DELETE op (-1 otherwise) — needed
    #                       by update generation (engine/downstream.py) to
    #                       name every delete's target element.


@boundary(dtypes=("int32", "int32", "int32"), shapes=("B", "B", None))
def resolve_batch(kind: jax.Array, pos: jax.Array, v0: jax.Array) -> ResolvedBatch:
    """Resolve one batch of unit ops against a document with ``v0`` visible
    chars.  ``kind``/``pos``: int32[B].  Fully jit/vmap-compatible."""
    B = kind.shape[0]
    T = 2 * B + 2

    ttype0 = jnp.zeros(T, jnp.int32).at[0].set(RUN)
    ta0 = jnp.zeros(T, jnp.int32)
    tlen0 = jnp.zeros(T, jnp.int32).at[0].set(v0)

    didx = jnp.arange(T, dtype=jnp.int32)

    def step(carry, op):
        ttype, ta, tlen = carry
        k, p, j = op
        is_ins = k == INSERT
        is_del = k == DELETE  # refined below once `total` is known

        cum = jnp.cumsum(tlen)  # free tokens have len 0 -> flat tail
        total = cum[-1]
        pre = cum - tlen
        # Malformed-stream robustness: positions clamp to [0, total]; deletes
        # beyond the end are no-ops (mirrors oracle semantics).
        p = jnp.clip(p, 0, total)
        is_del = is_del & (p < total)
        # Token containing the char at offset p (pre[t] <= p < cum[t]).  An
        # insert at the very end finds no such token (the free tail keeps cum
        # flat), so clamp to the first FREE index — the off == 0 path then
        # places the new token there.
        n_used = jnp.sum((ttype != FREE).astype(jnp.int32))
        t = jnp.searchsorted(cum, p, side="right").astype(jnp.int32)
        t = jnp.minimum(t, n_used)
        off = p - pre[t]

        a, ln, tt = ta[t], tlen[t], ttype[t]
        hit_run = tt == RUN

        # Replacement of token t by m in {1, 2, 3} new tokens.
        #   INSERT off == 0 : [ INS(j), old_t ]                        m = 2
        #   INSERT off  > 0 : [ RUN(a,off), INS(j), RUN(a+off,ln-off)] m = 3
        #   DELETE on INS   : [ DEAD(j') ]                             m = 1
        #   DELETE on RUN   : [ RUN(a,off), RUN(a+off+1,ln-off-1) ]    m = 2
        #   PAD             : [ old_t ]                                m = 1
        split = is_ins & (off > 0)
        m = jnp.where(
            is_ins,
            jnp.where(split, 3, 2),
            jnp.where(is_del, jnp.where(hit_run, 2, 1), 1),
        )

        # New token triple (only the first m are used).
        n0t = jnp.where(
            is_ins,
            jnp.where(split, RUN, TINS),
            jnp.where(is_del, jnp.where(hit_run, RUN, TDEAD), tt),
        )
        n0a = jnp.where(is_ins, jnp.where(split, a, j), jnp.where(is_del & ~hit_run, a, a))
        n0l = jnp.where(
            is_ins,
            jnp.where(split, off, 1),
            jnp.where(is_del, jnp.where(hit_run, off, 0), ln),
        )
        n1t = jnp.where(is_ins, jnp.where(split, TINS, tt), RUN)
        n1a = jnp.where(
            is_ins, jnp.where(split, j, a), a + off + 1
        )
        n1l = jnp.where(is_ins, jnp.where(split, 1, ln), ln - off - 1)
        n2t, n2a, n2l = RUN, a + off, ln - off

        src = jnp.clip(didx - (m - 1), 0, T - 1)
        shifted_t = ttype[src]
        shifted_a = ta[src]
        shifted_l = tlen[src]

        def place(old, shifted, x0, x1, x2):
            out = jnp.where(didx < t, old, shifted)
            out = jnp.where(didx == t, x0, out)
            out = jnp.where((m >= 2) & (didx == t + 1), x1, out)
            out = jnp.where((m == 3) & (didx == t + 2), x2, out)
            return out

        ttype_n = place(ttype, shifted_t, n0t, n1t, n2t)
        ta_n = place(ta, shifted_a, n0a, n1a, n2a)
        tlen_n = place(tlen, shifted_l, n0l, n1l, n2l)

        # Per-op outputs.
        del_rank = jnp.where(is_del & hit_run, a + off, -1)
        del_batch = jnp.where(is_del & (tt == TINS), a, -1)
        # Origin: char at offset p-1 at insert time.
        tp = jnp.searchsorted(cum, p - 1, side="right").astype(jnp.int32)
        origin_char = jnp.where(
            ttype[tp] == RUN,
            ta[tp] + (p - 1 - pre[tp]),
            ORIGIN_BATCH + ta[tp],
        )
        origin = jnp.where(is_ins, jnp.where(p == 0, -1, origin_char), -2)

        return (ttype_n, ta_n, tlen_n), (del_rank, origin, del_batch)

    ops = (kind, pos, jnp.arange(B, dtype=jnp.int32))
    (ttype, ta, tlen), (del_rank, origin, del_batch) = jax.lax.scan(
        step, (ttype0, ta0, tlen0), ops
    )

    ins_gvis, ins_seq, ins_alive = extract_from_tokens(ttype, ta, tlen, v0, B)
    return ResolvedBatch(
        del_rank=del_rank,
        ins_gvis=ins_gvis,
        ins_seq=ins_seq,
        ins_alive=ins_alive,
        origin=origin,
        del_batch=del_batch,
    )


def extract_from_tokens(ttype, ta, tlen, v0, B: int):
    """Post-scan extraction, vectorized over the final token list: per-insert
    gap rank (``ins_gvis``), same-gap tie-break (``ins_seq``), and liveness
    (``ins_alive``).  Shared by the lax.scan resolver above and the fused
    Pallas resolver (ops/resolve_pallas.py)."""
    is_instok = (ttype == TINS) | (ttype == TDEAD)
    # First surviving pre-batch char after each token: suffix-min of run starts.
    run_start = jnp.where((ttype == RUN) & (tlen > 0), ta, _BIG)
    suff = jnp.flip(jax.lax.cummin(jnp.flip(run_start)))
    nxt = jnp.concatenate([suff[1:], jnp.full((1,), _BIG, jnp.int32)])
    gvis = jnp.where(nxt >= _BIG, v0, nxt)

    # Tie-break: rank among instok tokens within the same gap.  Instok tokens
    # sharing a gap are contiguous (any surviving RUN between two inserts
    # would give the earlier one a smaller gap), so group starts are where the
    # gap differs from the previous instok token's gap.
    tpos = jnp.arange(ttype.shape[0], dtype=jnp.int32)
    ci = jnp.cumsum(is_instok.astype(jnp.int32))  # inclusive count
    prev_ipos = jax.lax.cummax(jnp.where(is_instok, tpos, -1))
    prev_ipos = jnp.concatenate([jnp.full((1,), -1, jnp.int32), prev_ipos[:-1]])
    prev_gvis = jnp.where(prev_ipos >= 0, gvis[jnp.clip(prev_ipos, 0)], -1)
    boundary = is_instok & ((prev_ipos < 0) | (prev_gvis != gvis))
    base = jnp.where(boundary, ci - 1, -1)
    seq = ci - 1 - jax.lax.cummax(base)

    # Scatter token results to per-op arrays (drop non-instok tokens).
    B_ = B
    opidx = jnp.where(is_instok, ta, B_)
    ins_gvis = jnp.full(B_, -1, jnp.int32).at[opidx].set(gvis, mode="drop")
    ins_seq = jnp.zeros(B_, jnp.int32).at[opidx].set(seq, mode="drop")
    ins_alive = (
        jnp.zeros(B_, jnp.bool_)
        .at[opidx]
        .set(ttype == TINS, mode="drop")
    )
    return ins_gvis, ins_seq, ins_alive
