"""Pure-JAX range-op resolver: the vmappable twin of the Pallas kernel.

``ops/resolve_range_pallas.py`` resolves one batch of RANGE ops for R
replicas, but it has two constraints the serve/ document fleet cannot
live with: the op batch is SHARED across replicas (every row replays the
same stream), and off-TPU it only runs in Pallas interpret mode.  This
module re-expresses the same cum-primary token-list algorithm as a
``lax.scan`` over the ops of ONE document — plain jnp, jit/vmap
compatible — so

- ``jax.vmap(resolve_ranges_scan)`` over (kind[R, B], pos, rlen, slot0,
  nvis[R]) resolves a *different* range batch per row (the fleet pool's
  per-document lanes), and
- off-TPU single-stream replay (engine/replay_range.py) gets a native
  XLA resolver instead of interpret-mode emulation.

Semantics are identical to the kernel (differentially tested in
tests/test_resolve_range_scan.py): same token encoding — RUN ``ta`` is a
pre-batch rank, TINS ``ta`` is the op's first SLOT id, ``tch`` the
run-internal char offset — and the same per-delete rank intervals
``(dlo, dhi, dcount)``.  The token list is the full 2B+2 worst case
(token_cap staging is a VMEM concern; XLA just streams it), so overflow
is impossible by construction and ``nused`` is returned for interface
parity only.  The scan body lives in :func:`res_step` with the token
capacity ``T`` as a parameter: the serve fused path
(``ops/serve_fused.py``) scans the same step over a GROWING token list
(T = 2i + 2 suffices after i ops), which is where most of its resolve
speedup comes from.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from ..traces.tensorize import DELETE, INSERT
from .resolve import FREE, RUN, TINS

_BIG = np.int32(1 << 30)


def res_step(carry, op, T: int):
    """ONE resolve step over a token list of capacity ``T`` — the scan
    body of :func:`resolve_ranges_scan`, factored out with ``T`` as a
    parameter so the serve path (``ops/serve_fused.py``) can run the
    same arithmetic over a GROWING token list (the list holds at most
    ``2 * i + 2`` live tokens after ``i`` ops, so early ops need not
    pay the full worst-case width).  Semantics are pinned by the
    differential tests against the Pallas kernel; any change here
    changes both resolvers."""
    didx = jnp.arange(T, dtype=jnp.int32)
    tta, tch, cum, total, nused = carry
    k, p0, L0, s0 = op

    is_ins = (k == INSERT) & (L0 > 0)
    p = jnp.clip(p0, 0, total)
    D = jnp.where(k == DELETE, jnp.clip(L0, 0, total - p), 0)
    is_del = (k == DELETE) & (D > 0)
    L = jnp.where(is_ins, L0, 0)

    pre_all = jnp.concatenate([jnp.zeros((1,), jnp.int32), cum[:-1]])
    ttok = jnp.bitwise_and(tta, 3)
    is_run_tok = ttok == RUN

    # ---- delete rank-interval outputs (pre-clamp coordinates) ----
    pD = p + D
    ov_lo = jnp.maximum(pre_all, p)
    ov_hi = jnp.minimum(cum, pD)
    has_ov = is_del & is_run_tok & (ov_hi > ov_lo)
    ta_all = jnp.right_shift(tta, 2)
    r_lo = ta_all + (ov_lo - pre_all)
    r_hi = ta_all + (ov_hi - pre_all) - 1
    dlo = jnp.min(jnp.where(has_ov, r_lo, _BIG))
    dhi = jnp.max(jnp.where(has_ov, r_hi, -1))
    dn = jnp.sum(jnp.where(has_ov, ov_hi - ov_lo, 0))
    dlo = jnp.where(is_del & (dlo < _BIG), dlo, -1)
    dhi = jnp.where(is_del, dhi, -1)
    dn = jnp.where(is_del, dn, 0)

    # ---- vector clamp: the delete's effect on every token ----
    consumed = jnp.maximum(
        0, jnp.minimum(cum, pD) - jnp.maximum(pre_all, p)
    )
    adv = jnp.where(is_del & (cum > pD), consumed, 0)
    cum_c = jnp.where(
        is_del, jnp.minimum(cum, p) + jnp.maximum(0, cum - pD), cum
    )
    tta_c = tta + jnp.where(is_run_tok, adv * 4, 0)
    tch_c = tch + jnp.where(ttok == TINS, adv, 0)

    # ---- locate the token containing p (pre-clamp coordinates) ----
    t = jnp.sum((cum <= p).astype(jnp.int32))
    t = jnp.minimum(t, nused)
    c_t = cum[t]
    pre = pre_all[t]
    tta_t = tta[t]
    ch = tch[t]
    tt = jnp.bitwise_and(tta_t, 3)
    off = p - pre
    is_run_t = tt == RUN

    split_ins = is_ins & (off > 0)
    split_del = is_del & (off > 0) & (pD < c_t)
    m = jnp.where(
        is_ins,
        jnp.where(split_ins, 3, 2),
        jnp.where(split_del, 2, 1),
    )

    # Replacement pieces (same arithmetic as the kernel: m == 1
    # writes the token's CLAMPED values back — identity for
    # inserts/PAD, the boundary adjustment for spanning deletes).
    c_t_clamped = jnp.where(
        is_del, jnp.minimum(c_t, p) + jnp.maximum(0, c_t - pD), c_t
    )
    adv_t = jnp.where(
        is_del & (c_t > pD),
        jnp.maximum(0, jnp.minimum(c_t, pD) - jnp.maximum(pre, p)),
        0,
    )
    tta_cl = tta_t + jnp.where(is_run_t, adv_t * 4, 0)
    ch_cl = ch + jnp.where(tt == TINS, adv_t, 0)
    tta_right_del = tta_t + jnp.where(is_run_t, (pD - pre) * 4, 0)
    ch_right_del = jnp.where(is_run_t, ch, ch + (pD - pre))
    tta_right_ins = tta_t + jnp.where(is_run_t, off * 4, 0)
    ch_right_ins = jnp.where(is_run_t, ch, ch + off)
    jj_tins = s0 * 4 + TINS  # TINS carries the op's first slot id

    n0ta = jnp.where(
        is_ins & ~split_ins, jj_tins,
        jnp.where(split_del, tta_t, tta_cl),
    )
    n0c_ = jnp.where(
        is_ins & ~split_ins, 0, jnp.where(split_del, ch, ch_cl)
    )
    n0cum = jnp.where(
        is_ins,
        jnp.where(split_ins, p, pre + L),
        jnp.where(split_del, p, c_t_clamped),
    )
    n1ta = jnp.where(
        is_ins, jnp.where(split_ins, jj_tins, tta_t), tta_right_del
    )
    n1c_ = jnp.where(
        is_ins, jnp.where(split_ins, 0, ch), ch_right_del
    )
    n1cum = jnp.where(
        is_ins, jnp.where(split_ins, p + L, c_t + L), c_t - D
    )
    n2ta, n2c_, n2cum = tta_right_ins, ch_right_ins, c_t + L

    src = jnp.clip(didx - (m - 1), 0, T - 1)

    def place(x, x0, x1, x2, dlt):
        out = jnp.where(didx < t, x, x[src] + dlt)
        out = jnp.where(didx == t, x0, out)
        out = jnp.where((m >= 2) & (didx == t + 1), x1, out)
        out = jnp.where((m == 3) & (didx == t + 2), x2, out)
        return out

    tta_n = place(tta_c, n0ta, n1ta, n2ta, 0)
    tch_n = place(tch_c, n0c_, n1c_, n2c_, 0)
    # tail cum shifts by L past the placed pieces (deletes: 0 — their
    # tail effect is already in the vector clamp)
    cum_n = place(cum_c, n0cum, n1cum, n2cum, L)

    return (
        (tta_n, tch_n, cum_n, total + L - D, nused + (m - 1)),
        (dlo, dhi, dn),
    )


def res_carry_init(T: int, v0):
    """The resolve scan's initial carry for a token list of capacity
    ``T``: token 0 = RUN(0, v0), flat ``cum`` tail (every unused token
    carries the running total)."""
    didx = jnp.arange(T, dtype=jnp.int32)
    v0 = jnp.asarray(v0, jnp.int32)
    tta0 = jnp.where(didx == 0, RUN, FREE).astype(jnp.int32)
    tch0 = jnp.zeros(T, jnp.int32)
    cum0 = jnp.zeros(T, jnp.int32) + v0
    return (tta0, tch0, cum0, v0, jnp.int32(1))


def res_carry_grow(carry, T: int):
    """Widen a resolve carry to token capacity ``T`` (the growing-list
    serve path): new tail tokens are FREE with ``cum`` = the running
    total — exactly the flat tail :func:`res_carry_init` builds, so a
    widened carry is indistinguishable from a full-width scan's."""
    tta, tch, cum, total, nused = carry
    pad = T - tta.shape[0]
    if pad <= 0:
        return carry
    return (
        jnp.concatenate([tta, jnp.full((pad,), FREE, jnp.int32)]),
        jnp.concatenate([tch, jnp.zeros((pad,), jnp.int32)]),
        jnp.concatenate([cum, jnp.zeros((pad,), jnp.int32) + total]),
        total,
        nused,
    )


def res_finalize(carry):
    """Unpack a final resolve carry into the ``(ttype, ta, tch, tlen)``
    token arrays ``apply_range_batch`` consumes (plus ``nused``)."""
    tta, tch, cum, _total, nused = carry
    pre_all = jnp.concatenate([jnp.zeros((1,), jnp.int32), cum[:-1]])
    ttype = jnp.bitwise_and(tta, 3)
    ta = jnp.right_shift(tta, 2)
    tlen = cum - pre_all
    return (ttype, ta, tch, tlen), nused


def resolve_ranges_scan(kind, pos, rlen, slot0, v0):
    """Resolve one batch of range ops against a document with ``v0``
    visible chars.  ``kind``/``pos``/``rlen``/``slot0``: int32[B]; ``v0``
    scalar.  Returns ``((ttype, ta, tch, tlen) int32[T], (dlo, dhi,
    dcount) int32[B], nused)`` with T = 2B + 2 — the shapes
    ``ops/apply_range.py apply_range_batch`` consumes (leading replica
    axis supplied by vmap)."""
    B = kind.shape[0]
    T = 2 * B + 2

    ops = (
        jnp.asarray(kind, jnp.int32),
        jnp.asarray(pos, jnp.int32),
        jnp.asarray(rlen, jnp.int32),
        jnp.asarray(slot0, jnp.int32),
    )
    carry, (dlo, dhi, dn) = jax.lax.scan(
        lambda c, o: res_step(c, o, T), res_carry_init(T, v0), ops
    )
    tokens, nused = res_finalize(carry)
    return tokens, (dlo, dhi, dn), nused


@boundary(
    dtypes=("int32", "int32", "int32", "int32", "int32"),
    shapes=("R B", "R B", "R B", "R B", "R"),
)
def resolve_ranges_rows(kind, pos, rlen, slot0, v0):
    """Per-row fleet form: kind/pos/rlen/slot0 int32[R, B] (a different
    op batch per document lane), v0 int32[R].  Returns token arrays
    [R, T] and delete intervals [R, B] — exactly what
    ``apply_range_batch`` consumes."""
    return jax.vmap(resolve_ranges_scan)(kind, pos, rlen, slot0, v0)


def resolve_ranges_shared(kind, pos, rlen, slot0, v0):
    """Shared-stream form (the Pallas kernel's interface): one op batch
    int32[B] replayed by every row, per-row v0 int32[R].  The off-TPU
    resolver for engine/replay_range.py."""
    return jax.vmap(
        resolve_ranges_scan, in_axes=(None, None, None, None, 0)
    )(kind, pos, rlen, slot0, v0)
