"""Host-side exact simulation of resolver token-list growth.

The fused resolver kernel (ops/resolve_pallas.py) sizes its VMEM token list
as T = 2B + 2 — the worst case (every op adds two tokens).  Real editing
traces are far below that bound most of the time (typing bursts add 2
tokens per op only when they split a run), and resolver cost is linear in
T, so the engine picks T per chunk from this simulation.

Token growth is replica-independent: it depends only on (kind, pos) and
the batch-start visible length v0 — both host-known for an upstream replay
(v0 per batch = n_init + running insert count minus deletes... tracked by
the same simulation).  The growth rule replicated here is exactly the
m-token replacement of ops/resolve.py `resolve_batch` (differentially
tested against the Pallas kernel in tests/test_token_sim.py: capped and
uncapped resolver outputs must match): the simulation carries (ttype, tlen)
per token and counts tokens; `required_T[b]` = token count at the end of
batch b, which dominates every in-batch write index (writes go to
t + 2 <= nused + 2 and nused is nondecreasing).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..traces.tensorize import DELETE, INSERT
from .resolve import FREE, RUN, TDEAD, TINS


@functools.partial(jax.jit, static_argnames=("B",), backend="cpu")
def _sim_batches(kind_b, pos_b, v0_b, *, B: int):
    """kind_b/pos_b: int32[nb, B]; v0_b: int32[nb] batch-start visible
    lengths.  Returns int32[nb] final token counts."""
    T = 2 * B + 2

    def batch_sim(kind, pos, v0):
        ttype0 = jnp.zeros(T, jnp.int32).at[0].set(RUN)
        tlen0 = jnp.zeros(T, jnp.int32).at[0].set(v0)
        didx = jnp.arange(T, dtype=jnp.int32)

        def step(carry, op):
            ttype, tlen, nused = carry
            k, p = op
            is_ins = k == INSERT
            cum = jnp.cumsum(tlen)
            total = cum[-1]
            p = jnp.clip(p, 0, total)
            is_del = (k == DELETE) & (p < total)
            t = jnp.searchsorted(cum, p, side="right").astype(jnp.int32)
            t = jnp.minimum(t, nused)
            off = p - (cum[t] - tlen[t])
            tt = ttype[t]
            ln = tlen[t]
            hit_run = tt == RUN
            split = is_ins & (off > 0)
            m = jnp.where(
                is_ins,
                jnp.where(split, 3, 2),
                jnp.where(is_del, jnp.where(hit_run, 2, 1), 1),
            )
            n0t = jnp.where(
                is_ins,
                jnp.where(split, RUN, TINS),
                jnp.where(is_del, jnp.where(hit_run, RUN, TDEAD), tt),
            )
            n0l = jnp.where(
                is_ins,
                jnp.where(split, off, 1),
                jnp.where(is_del, jnp.where(hit_run, off, 0), ln),
            )
            n1t = jnp.where(is_ins, jnp.where(split, TINS, tt), RUN)
            n1l = jnp.where(is_ins, jnp.where(split, 1, ln), ln - off - 1)
            n2t, n2l = RUN, ln - off

            src = jnp.clip(didx - (m - 1), 0, T - 1)

            def place(old, shifted, x0, x1, x2):
                out = jnp.where(didx < t, old, shifted)
                out = jnp.where(didx == t, x0, out)
                out = jnp.where((m >= 2) & (didx == t + 1), x1, out)
                out = jnp.where((m == 3) & (didx == t + 2), x2, out)
                return out

            ttype_n = place(ttype, ttype[src], n0t, n1t, n2t)
            tlen_n = place(tlen, tlen[src], n0l, n1l, n2l)
            return (ttype_n, tlen_n, nused + m - 1), None

        (_, _, nused), _ = jax.lax.scan(
            step, (ttype0, tlen0, jnp.int32(1)),
            (kind, pos),
        )
        return nused

    return jax.vmap(batch_sim)(kind_b, pos_b, v0_b)


def simulate_token_counts(
    kind_b: np.ndarray, pos_b: np.ndarray, n_init: int
) -> np.ndarray:
    """Final resolver token count per batch for an upstream replay starting
    from ``n_init`` visible chars.  Host-side (CPU jit), prepare-time only.
    """
    nb, B = kind_b.shape
    ins = (kind_b == INSERT).sum(axis=1)
    # Visible length at batch start: inserts minus applied deletes.  The
    # sim itself clamps out-of-range deletes, and v0 only matters through
    # position clamping — use the oracle-consistent visible count (every
    # in-range delete applies; traces are well-formed by construction).
    dels = (kind_b == DELETE).sum(axis=1)
    end_vis = n_init + np.cumsum(ins - dels)
    v0 = np.concatenate([[n_init], end_vis[:-1]]).astype(np.int32)
    out = _sim_batches(
        jnp.asarray(kind_b), jnp.asarray(pos_b), jnp.asarray(v0), B=B
    )
    return np.asarray(out)


# ---- range-op variant (ops/resolve_range_pallas.py sizing) ------------------


@functools.partial(jax.jit, static_argnames=("B",), backend="cpu")
def _sim_batches_range(kind_b, pos_b, rlen_b, v0, *, B: int):
    """Token-count simulation for the RANGE resolver: inserts add 1-2
    tokens (2 when splitting a run), deletes add a token only when
    strictly inside one token (the vector clamp handles spanning deletes
    without growth) — mirroring resolve_range_pallas's ``m`` rule.

    Batches chain SEQUENTIALLY: each batch's end total (with the
    kernel's own delete clamping applied) is the next batch's v0, so an
    over-long delete cannot skew later batches' caps — an undersized cap
    silently corrupts by the kernel's contract."""
    T = 2 * B + 2

    def batch_sim(v0, ops):
        kind, pos, rlen = ops
        tlen0 = jnp.zeros(T, jnp.int32).at[0].set(v0)
        didx = jnp.arange(T, dtype=jnp.int32)

        def step(carry, op):
            tlen, nused = carry
            k, p0, L0 = op
            cum = jnp.cumsum(tlen)
            total = cum[-1]
            p = jnp.clip(p0, 0, total)
            is_ins = (k == INSERT) & (L0 > 0)
            D = jnp.where(k == DELETE, jnp.clip(L0, 0, total - p), 0)
            is_del = (k == DELETE) & (D > 0)
            L = jnp.where(is_ins, L0, 0)
            pD = p + D

            t = jnp.searchsorted(cum, p, side="right").astype(jnp.int32)
            t = jnp.minimum(t, nused)
            c_t = cum[t]
            off = p - (c_t - tlen[t])
            split_ins = is_ins & (off > 0)
            split_del = is_del & (off > 0) & (pD < c_t)
            m = jnp.where(
                is_ins,
                jnp.where(split_ins, 3, 2),
                jnp.where(split_del, 2, 1),
            )

            # delete clamp: remove [p, pD) overlap from every token
            clamped = jnp.minimum(cum, p) + jnp.maximum(0, cum - pD)
            cum_c = jnp.where(is_del, clamped, cum)
            tlen_c = cum_c - jnp.concatenate([jnp.zeros(1, jnp.int32),
                                              cum_c[:-1]])

            n0l = jnp.where(
                is_ins,
                jnp.where(split_ins, off, L),
                jnp.where(split_del, off, tlen_c[t]),
            )
            n1l = jnp.where(
                is_ins,
                jnp.where(split_ins, L, tlen[t]),
                tlen[t] - off - D,
            )
            n2l = tlen[t] - off

            src = jnp.clip(didx - (m - 1), 0, T - 1)
            base = jnp.where(is_del, tlen_c, tlen)
            shifted = base[src]
            out = jnp.where(didx < t, base, shifted)
            out = jnp.where(didx == t, n0l, out)
            out = jnp.where((m >= 2) & (didx == t + 1), n1l, out)
            out = jnp.where((m == 3) & (didx == t + 2), n2l, out)
            return (out, nused + m - 1), None

        (tlen, nused), _ = jax.lax.scan(
            step, (tlen0, jnp.int32(1)), (kind, pos, rlen)
        )
        return jnp.sum(tlen), nused  # (next batch's v0, token count)

    _, counts = jax.lax.scan(
        batch_sim, jnp.int32(v0), (kind_b, pos_b, rlen_b)
    )
    return counts


def simulate_range_token_counts(
    kind_b: np.ndarray, pos_b: np.ndarray, rlen_b: np.ndarray, n_init: int
) -> np.ndarray:
    """Final token count per RANGE batch (host, prepare-time)."""
    nb, B = kind_b.shape
    out = _sim_batches_range(
        jnp.asarray(kind_b), jnp.asarray(pos_b), jnp.asarray(rlen_b),
        int(n_init), B=B,
    )
    return np.asarray(out)
