"""Fused log-shift expansion kernel: the order/vis merge of ops/apply2.py
as one Pallas call.

In XLA the 10 data-dependent bit passes of `_expand` cannot fuse (each pass
reads the previous pass's full arrays), so every pass round-trips the
(R, C) state through HBM — measured ~8ms/batch at R=64, C=182k.  This
kernel runs all passes per replica with the arrays resident in VMEM: HBM
traffic drops to one read + one write per array.

Layout: Pallas TPU blocks must have their last two dims divisible by
(8, 128) or equal to the array's, so the C axis is viewed as (nt, 128)
tiles and a flat-order roll by ``s = k*128 + sl`` decomposes into a k-tile
sublane roll plus an sl lane roll with a one-extra-tile carry for the lanes
that wrap (see _flat_roll).

The kernel also zeroes the insert-destination holes (``ind != 0``) so the
caller can fill them with plain scatter-ADDs — on this TPU runtime,
scatter-add vectorizes while scatter-set serializes per row.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from .pallas_compat import pltpu  # CompilerParams shim for jax 0.4

LANE = 128

#: Measured Mosaic scoped-stack footprint of _apply_fused_kernel per doc
#: position per replica (see apply_fused) — used to pick the replica tile
#: and to gate the Pallas path vs the XLA fallback.
FUSED_STACK_BYTES_PER_POS = 92


def _roll_ax(x, s: int, axis: int):
    """Static roll that avoids jnp.roll's zero-size slice at s == 0 (Mosaic
    rejects 0-width vector types)."""
    if s == 0:
        return x
    return jnp.concatenate(
        [
            jax.lax.slice_in_dim(x, x.shape[axis] - s, x.shape[axis], axis=axis),
            jax.lax.slice_in_dim(x, 0, x.shape[axis] - s, axis=axis),
        ],
        axis=axis,
    )


def _flat_roll(x, s: int):
    """Roll right by ``s`` positions in flattened (tile, lane) order.
    x: (1, nt, LANE).  Wrapped-in values are garbage the caller masks."""
    k, sl = divmod(s, LANE)
    a = _roll_ax(x, k, 1)
    if sl == 0:
        return a
    b = _roll_ax(x, k + 1, 1)
    a = _roll_ax(a, sl, 2)
    b = _roll_ax(b, sl, 2)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 2)
    return jnp.where(lane >= sl, a, b)


def _expand_kernel(order_ref, vis_ref, cnt_ref, ind_ref,
                   order_out, vis_out, *, nt: int, nbits: int):
    order = order_ref[:]  # (1, nt, LANE)
    vis = vis_ref[:]
    cnt = cnt_ref[:]
    ind = ind_ref[:]
    tile = jax.lax.broadcasted_iota(jnp.int32, (1, nt, LANE), 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, nt, LANE), 2)
    col = tile * LANE + lane
    for b in reversed(range(nbits)):
        step = 1 << b
        take = (jnp.bitwise_and(cnt, step) != 0) & (col >= step)
        order = jnp.where(take, _flat_roll(order, step), order)
        vis = jnp.where(take, _flat_roll(vis, step), vis)
    hole = ind != 0
    order_out[:] = jnp.where(hole, 0, order)
    vis_out[:] = jnp.where(hole, 0, vis)


def _expand_packed_kernel(doc_ref, cntind_ref, out_ref,
                          *, nt: int, nbits: int, Rt: int):
    """Packed variant: doc = ((order+2)<<1)|vis moves as one array;
    cntind = (cnt<<1)|ind carries both the shift map and the hole mask (the
    shift-bit test reads cntind directly — bit b of cnt is bit b+1 of
    cntind — to keep VMEM live-array count down).  Bits above the block's
    max shift are skipped (small insert batches rarely use the high bits)."""
    cntind = cntind_ref[:]
    tile = jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 2)
    col = tile * LANE + lane
    maxcnt = jnp.max(jnp.right_shift(cntind, 1))
    out_ref[:] = doc_ref[:]
    for b in reversed(range(nbits)):
        step = 1 << b

        @pl.when(maxcnt >= step)
        def _():
            doc = out_ref[:]
            take = (jnp.bitwise_and(cntind, step << 1) != 0) & (col >= step)
            out_ref[:] = jnp.where(take, _flat_roll(doc, step), doc)

    hole = jnp.bitwise_and(cntind, 1) != 0
    out_ref[:] = jnp.where(hole, 0, out_ref[:])


@functools.partial(
    jax.jit, static_argnames=("nbits", "replica_tile", "interpret")
)
def expand_packed(doc, cntind, *, nbits: int, replica_tile: int = 0,
                  interpret: bool = False):
    """Move the packed doc array by the cnt map and zero insert-destination
    holes.  doc/cntind: int32[R, C], C a multiple of 128.  replica_tile 0 =
    auto (largest power of two whose VMEM footprint stays under budget)."""
    R, C = doc.shape
    nt = C // LANE
    # Mosaic's stack peaks at ~8 live (Rt, C) int32 arrays (state + roll
    # temps + iotas); stay under the 16MB scoped-vmem limit with margin.
    per_replica = 8 * 4 * C
    if per_replica > 14 * 2**20:
        # Capacity too large for VMEM even at one replica per grid step:
        # run the bit passes in XLA (HBM round trips, but correct).
        col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
        out = doc
        for b in reversed(range(nbits)):
            step = 1 << b
            take = (jnp.bitwise_and(cntind, step << 1) != 0) & (col >= step)
            out = jnp.where(take, jnp.roll(out, step, axis=1), out)
        return jnp.where(jnp.bitwise_and(cntind, 1) != 0, 0, out)
    Rt = replica_tile
    if Rt <= 0:
        Rt = max(1, (14 * 2**20) // per_replica)
    Rt = min(Rt, R)
    while R % Rt:
        Rt -= 1
    spec = pl.BlockSpec(
        (Rt, nt, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _expand_packed_kernel, nt=nt, nbits=nbits, Rt=Rt
    )
    out = pl.pallas_call(
        kernel,
        grid=(R // Rt,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
        interpret=interpret,
    )(doc.reshape(R, nt, LANE), cntind.reshape(R, nt, LANE))
    return out.reshape(R, C)


def _lane_cumsum(x):
    """Inclusive cumsum along the LANE axis (axis=2) via 7 log-shift passes
    (Mosaic-safe: no jnp.cumsum dependence)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 2)
    c = x
    for b in range(7):
        s = 1 << b
        c = c + jnp.where(lane >= s, _roll_ax(c, s, 2), 0)
    return c


def apply_fused_nocv_xla(doc_predel, combo, cnt_base, new_len, *, nbits: int):
    """XLA fallback for apply_fused_nocv (CPU / oversized capacities)."""
    out, _, _ = apply_fused_xla(
        doc_predel, combo, cnt_base, new_len, nbits=nbits
    )
    return out


def fused_apply_nocv_dispatch(doc_predel, combo, cnt_base, new_len, *,
                              nbits: int):
    """Pick the right no-cumvis fused apply for the platform and capacity:
    monolithic VMEM kernel under the ~1.09M-position gate, the blocked
    halo kernel above it (TPU), XLA fallback elsewhere.

    The monolithic path is apply_fused2 (ops/apply_range_fused.py):
    same math as apply_fused via the triangular-matmul cumsum, no
    scratch refs, and it self-pads unaligned tile counts (nt % 8 != 0
    sends Mosaic compile time into minutes)."""
    C = doc_predel.shape[1]
    if jax.default_backend() == "tpu":
        if FUSED_STACK_BYTES_PER_POS * C <= 96 * 2**20:
            from .apply_range_fused import apply_fused2

            return apply_fused2(
                doc_predel, combo, cnt_base, new_len, nbits=nbits,
                emit_cv=False,
            )
        return apply_fused_blocked(
            doc_predel, combo, cnt_base, new_len, nbits=nbits
        )
    return apply_fused_nocv_xla(
        doc_predel, combo, cnt_base, new_len, nbits=nbits
    )


def _apply_fused_blocked_kernel(
    doc_ref, docp_ref, combo_ref, combop_ref, cbase_ref, cbasep_ref,
    newlen_ref, doc_out, cnt_scr, work_scr,
    *, bt: int, pt: int, nbits: int,
):
    """Blocked fused apply for capacities beyond VMEM: grid (R, nt/bt).
    The expansion y[d] = x[d - r(d)] reads only LEFTWARD, and every
    intermediate read of the bit recursion stays within [d - r(d), d]
    (the 1-Lipschitz argument, see _expand), with r(d) < 2**nbits — so an
    output block of ``bt`` tiles needs just its own tiles plus a halo of
    ``pt`` = ceil(2**nbits / 128) + 1 previous tiles, delivered as a
    second BlockSpec view of the same array (block j-1; at j == 0 the
    halo aliases block 0, whose values are never read: the gcol >= step
    guards keep reads at nonnegative global positions).

    The per-tile global insert-count exclusive prefix rides the same
    block+halo views as the doc (cbase/cbasep, shape (1, bt, 1)) so no
    dynamic slicing happens in-kernel.
    """
    j = pl.program_id(1)
    ext = pt + bt
    work_scr[:, :pt, :] = docp_ref[:, bt - pt :, :]
    work_scr[:, pt:, :] = doc_ref[:]
    combo = jnp.concatenate(
        [combop_ref[:, bt - pt :, :], combo_ref[:]], axis=1
    )
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, ext, LANE), 2)
    gcol = (
        (
            jax.lax.broadcasted_iota(jnp.int32, (1, ext, LANE), 1)
            + j * bt
            - pt
        )
        * LANE
        + lane
    )

    # absolute shift map over the window: within-tile lane cumsum of the
    # insert indicator + the global per-tile base
    cnt_scr[:] = jnp.bitwise_and(combo, 1)
    for b in range(7):
        s = 1 << b
        c = cnt_scr[:]
        cnt_scr[:] = c + jnp.where(lane >= s, _roll_ax(c, s, 2), 0)
    row = jnp.concatenate(
        [cbasep_ref[:, bt - pt :, :], cbase_ref[:]], axis=1
    )
    cnt_scr[:] = cnt_scr[:] + row
    maxcnt = jnp.max(cnt_scr[:, pt:, LANE - 1 :])

    for b in reversed(range(nbits)):
        step = 1 << b

        @pl.when(maxcnt >= step)
        def _():
            w = work_scr[:]
            take = (jnp.bitwise_and(cnt_scr[:], step) != 0) & (
                gcol >= step
            )
            work_scr[:] = jnp.where(take, _flat_roll(w, step), w)

    out = jnp.where(
        jnp.bitwise_and(combo, 1) != 0,
        jnp.right_shift(combo, 1),
        work_scr[:],
    )
    out = jnp.where(gcol >= newlen_ref[:], 2, out)
    doc_out[:] = out[:, pt:, :]


@functools.partial(
    jax.jit, static_argnames=("nbits", "block_tiles", "interpret")
)
def apply_fused_blocked(doc_predel, combo, cnt_base, new_len, *,
                        nbits: int, block_tiles: int = 1024,
                        interpret: bool = False):
    """apply_fused_nocv for arbitrary capacities (the two-pass/windowed
    form): blocked along C with a left halo of ceil(2**nbits / 128) + 1
    tiles — the max shift any position receives in one batch.  VMEM per
    grid step ~ 5 * (block + halo) * 128 * 4 bytes, independent of C."""
    R, C = doc_predel.shape
    nt = C // LANE
    bt = block_tiles
    # When the tile count doesn't divide into blocks (e.g. an odd nt at
    # multi-M capacities), PAD the capacity axis up to a block multiple
    # rather than shrinking bt toward 1 (a 1-tile block cannot host the
    # halo): the pad region carries no inserts (combo 0), tombstone-coded
    # doc (pack_doc(-1,0) == 2), and a flat cnt_base, so every padded
    # output column is past new_len and sliced away below.
    pad_t = (-nt) % bt
    if pad_t and pad_t > nt // 4 and bt > 8:
        # avoid >25% padded work: try smaller blocks first
        while bt > 8 and (-nt) % bt > nt // 4:
            bt //= 2
        pad_t = (-nt) % bt
    if pad_t:
        padc = pad_t * LANE
        doc_predel = jnp.concatenate(
            [doc_predel, jnp.full((R, padc), 2, jnp.int32)], axis=1
        )
        combo = jnp.concatenate(
            [combo, jnp.zeros((R, padc), jnp.int32)], axis=1
        )
        cnt_base = jnp.concatenate(
            [cnt_base,
             jnp.broadcast_to(cnt_base[:, -1:], (R, pad_t))],
            axis=1,
        )
        nt += pad_t
    # halo tiles, rounded to a multiple of 8 so every sublane-dim slice
    # and roll in the kernel stays tile-aligned (unaligned VMEM copies
    # serialize in Mosaic)
    pt = -(-(-(-(1 << nbits) // LANE) + 1) // 8) * 8
    if pt > bt:
        raise ValueError(
            f"halo {pt} tiles exceeds block {bt}; raise block_tiles or"
            " lower the per-batch insert bound (nbits)"
        )
    nblk = nt // bt
    r3 = lambda x: x.reshape(R, nt, LANE)
    cb3 = cnt_base.reshape(R, nt, 1)
    blk = pl.BlockSpec(
        (1, bt, LANE), lambda r, j: (r, j, 0), memory_space=pltpu.VMEM
    )
    blkp = pl.BlockSpec(
        (1, bt, LANE),
        lambda r, j: (r, jnp.maximum(j - 1, 0), 0),
        memory_space=pltpu.VMEM,
    )
    cbs = pl.BlockSpec(
        (1, bt, 1), lambda r, j: (r, j, 0), memory_space=pltpu.VMEM
    )
    cbsp = pl.BlockSpec(
        (1, bt, 1),
        lambda r, j: (r, jnp.maximum(j - 1, 0), 0),
        memory_space=pltpu.VMEM,
    )
    one = pl.BlockSpec(
        (1, 1, 1), lambda r, j: (r, 0, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _apply_fused_blocked_kernel, bt=bt, pt=pt, nbits=nbits
    )
    out = pl.pallas_call(
        kernel,
        grid=(R, nblk),
        in_specs=[blk, blkp, blk, blkp, cbs, cbsp, one],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((1, bt + pt, LANE), jnp.int32),
            pltpu.VMEM((1, bt + pt, LANE), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2**20
        ),
        interpret=interpret,
    )(
        r3(doc_predel), r3(doc_predel), r3(combo), r3(combo),
        cb3, cb3,
        new_len.reshape(R, 1, 1).astype(jnp.int32),
    )
    out = out.reshape(R, nt * LANE)
    return out[:, :C] if nt * LANE != C else out


def apply_fused_xla(doc_predel, combo, cnt_base, new_len, *, nbits: int):
    """Reference/fallback implementation of apply_fused in plain XLA
    (used on CPU and for differential tests)."""
    R, C = doc_predel.shape
    nt = C // LANE
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    ind = jnp.bitwise_and(combo, 1)
    cnt = (
        _lane_cumsum(ind.reshape(R, nt, LANE))
        + cnt_base.reshape(R, nt, 1)
    ).reshape(R, C)
    out = doc_predel
    for b in reversed(range(nbits)):
        step = 1 << b
        take = (jnp.bitwise_and(cnt, step) != 0) & (col >= step)
        out = jnp.where(take, jnp.roll(out, step, axis=1), out)
    out = jnp.where(ind != 0, jnp.right_shift(combo, 1), out)
    out = jnp.where(col >= new_len[:, None], 2, out)
    cv = _lane_cumsum(jnp.bitwise_and(out, 1).reshape(R, nt, LANE))
    return (
        out,
        cv.reshape(R, C).astype(jnp.bfloat16),
        cv[:, :, LANE - 1],
    )


@functools.partial(jax.jit, static_argnames=("nbits", "interpret"))
def expand_fill_zero(order, vis, cnt, ind, *, nbits: int,
                     interpret: bool = False):
    """y[d] = x[d - cnt[d]] for order and vis, with insert-destination holes
    (ind != 0) zeroed so fills can be scatter-adds.  All args int32[R, C],
    C a multiple of 128."""
    R, C = order.shape
    nt = C // LANE
    r3 = lambda x: x.reshape(R, nt, LANE)
    spec = pl.BlockSpec(
        (1, nt, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(_expand_kernel, nt=nt, nbits=nbits)
    o, v = pl.pallas_call(
        kernel,
        grid=(R,),
        in_specs=[spec, spec, spec, spec],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
            jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
        ],
        interpret=interpret,
    )(r3(order), r3(vis), r3(cnt), r3(ind))
    return o.reshape(R, C), v.reshape(R, C)
