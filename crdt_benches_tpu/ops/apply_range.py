"""Batch application for RANGE-op resolution (ops/resolve_range_pallas.py)
on the packed doc-order state (ops/apply2.py PackedState).

Everything stays in the fast-primitive set: interval indicators built from
start/stop one-hot MXU spreads + cumsum, the log-shift expansion kernel (the
per-position insert indicator is 0/1 because destination positions are
distinct, so the 1-Lipschitz correctness argument is unchanged), and the
insert fill painted arithmetically: within a destination run the filled slot
is ``position + delta`` with a per-run constant delta, and per-run constants
materialize as a cumsum over spread delta-differences — no per-char work
anywhere on the host or in scatters.

Deletes arrive as per-op PRE-BATCH RANK intervals [lo, hi] (plus the exact
covered count): visible chars with ranks in the interval are exactly the
delete's targets (interior ranks missing from it were tombstoned earlier in
the same batch and are already invisible), so clearing the whole physical
interval [phys(lo), phys(hi)] is correct.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.boundary import boundary
from .apply2 import (
    LANE,
    PackedState,
    _excl_cumsum_small,
    _expand,
    _mxu_spread,
    count_le_tiled,
    count_le_two_level,
    spread_add_rows,
)
from .resolve import RUN, TINS

_BIG = np.int32(1 << 30)


def ddelta_levels(C: int) -> int:
    """Number of 7-bit chunk levels needed to carry a signed per-run
    slot-delta difference (|ddelta| <= 2C) through the one-hot spreads
    and the fused kernel's in-kernel re-chunking.  3 for every capacity
    below 2^20 (the historical packing); grows adaptively above."""
    return max(3, -(-(2 * int(C)).bit_length() // 7))


def _two_level_vis(doc, length):
    """Per-batch two-level visible-rank structure from the packed doc:
    (cv_intile bf16[R, C] within-tile inclusive cumsum — values <= 128,
    exact in bf16 — tile_base int32[R, nt] exclusive cross-tile prefix,
    tmax_abs int32[R, nt]).  Feeds count_le_two_level, whose factored
    one-hot row fetches ride the MXU — the take_along_axis row gather it
    replaces serializes per row (~21ns each; was ~100ms/batch at R=1024,
    3 query sets).  Also removes the full-capacity cumvis cumsum: the
    within-tile cumsum has no cross-tile dependency.

    Same structure init_state4 builds for the MAINTAINED-cumvis engine
    (apply2.py) — kept separate because this one masks by ``length``
    (the recomputed-per-batch form) while init_state4 builds from a
    fresh doc with no live length; change both if the layout changes."""
    R, C = doc.shape
    nt = C // LANE
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    vis = jnp.bitwise_and(doc, 1) * (col < length[:, None]).astype(jnp.int32)
    cv = jnp.cumsum(vis.reshape(R, nt, LANE), axis=2)
    vis_tile = cv[:, :, LANE - 1]
    tile_base = _excl_cumsum_small(vis_tile)
    return (
        cv.reshape(R, C).astype(jnp.bfloat16),
        tile_base,
        tile_base + vis_tile,
    )


def extract_range_tokens(ttype, ta, tch, tlen, v0):
    """Per-token placement info from the final token list (all int32[R, T]):
    live mask (surviving insert runs), gap rank ``gvis`` (rank of the first
    surviving pre-batch char to the token's right, v0 = document tail), and
    ``cumlen`` (exclusive prefix sum of live lengths = chars inserted before
    this token in (gap, order) interleave order, since token order is
    document order and gaps are monotone along it)."""
    R, T = ttype.shape
    live = (ttype == TINS) & (tlen > 0)
    run_start = jnp.where((ttype == RUN) & (tlen > 0), ta, _BIG)
    suff = jax.lax.cummin(run_start, axis=1, reverse=True)
    nxt = jnp.concatenate(
        [suff[:, 1:], jnp.full((R, 1), _BIG, jnp.int32)], axis=1
    )
    gvis = jnp.where(nxt >= _BIG, v0[:, None], nxt)
    llen = jnp.where(live, tlen, 0)
    cumlen = jnp.cumsum(llen, axis=1) - llen
    return live, gvis, cumlen


@boundary(dtypes=("int32", "int32", "int32"))
def apply_range_batch(
    state: PackedState,
    tokens,  # (ttype, ta, tch, tlen) int32[R, T]; TINS ta = slot0
    dints,  # (dlo, dhi, dcount) int32[R, B]
    nbits: int,
) -> PackedState:
    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    R, C = state.doc.shape
    T = ttype.shape[1]
    B = dlo.shape[1]
    drop = jnp.int32(C + 7)
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    on_tpu = jax.default_backend() == "tpu"

    vis_bit = jnp.bitwise_and(state.doc, 1)

    # ---- resolve ALL rank queries in one pass: delete interval
    # endpoints (B each) + insert-gap ranks (T) ----
    has_del = dlo >= 0
    live, gvis, cumlen = extract_range_tokens(
        ttype, ta, tch, tlen, v0=state.nvis
    )
    allq_in = jnp.concatenate(
        [
            jnp.where(has_del, dlo, 0),
            jnp.where(has_del, dhi, 0),
            jnp.where(live, gvis, 0),
        ],
        axis=1,
    )
    if on_tpu:
        # Two-level structure + factored one-hot row fetches: the
        # take_along_axis gathers of count_le_tiled serialize per row on
        # the TPU runtime.
        cvt, tile_base, tmax_abs = _two_level_vis(state.doc, state.length)
        allq = count_le_two_level(cvt, tile_base, tmax_abs, allq_in)
    else:
        # Off-TPU the gathers are cheap and the einsum row fetches are
        # not: plain absolute cumvis + tiled searchsorted.
        cumvis = jnp.cumsum(
            vis_bit * (col < state.length[:, None]).astype(jnp.int32),
            axis=1,
        )
        allq = count_le_tiled(cumvis, allq_in)
    lo_phys = allq[:, :B]
    hi_phys = allq[:, B : 2 * B]
    gq_phys = allq[:, 2 * B :]

    # ---- deletes: clear visible bits over physical rank intervals ----
    starts = spread_add_rows(
        jnp.where(has_del, lo_phys, drop), has_del.astype(jnp.int32), C
    )
    stops = spread_add_rows(
        jnp.where(has_del, hi_phys + 1, drop), has_del.astype(jnp.int32), C
    )
    in_del = jnp.cumsum(starts - stops, axis=1) > 0
    doc = state.doc - (vis_bit & in_del.astype(jnp.int32))

    # ---- insert runs: destinations ----
    at_end = gvis >= state.nvis[:, None]
    g_phys = jnp.where(at_end, state.length[:, None], gq_phys)
    dest0 = jnp.where(live, g_phys + cumlen, drop)  # (R, T)
    dstop = jnp.where(live, dest0 + tlen, drop)

    s1 = spread_add_rows(dest0, live.astype(jnp.int32), C)
    s2 = spread_add_rows(dstop, live.astype(jnp.int32), C)
    ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
    cnt = jnp.cumsum(ind, axis=1)

    # ---- fill values: slot(d) = d + delta(run of d) ----
    # slot of char k of token i = slot0[ta_i] + tch_i + k, at position
    # dest0_i + k  ->  delta_i = slot0[ta_i] + tch_i - dest0_i.
    # TINS tokens carry slot0 directly in ``ta`` (the range resolver
    # bakes it in; see ops/resolve_range_pallas.py).
    delta = jnp.where(live, ta + tch - dest0, 0)
    # Per-run constants as cumsum of differences painted at run starts.
    prev_live_delta = _prev_value(delta, live)
    ddelta = jnp.where(live, delta - prev_live_delta, 0)
    dpos_ = jnp.where(live, dest0, drop)
    if on_tpu:
        # |ddelta| <= 2C: derive the 7-bit chunk count from the static
        # capacity (3 levels covered only C < 2^20 — round-5 widening;
        # each level's values are bf16-exact shifted small ints and every
        # cell receives at most one contribution, so exactness is
        # per-level).
        dlv = ddelta_levels(C)
        dp = jnp.where(ddelta > 0, ddelta, 0)
        dn = jnp.where(ddelta < 0, -ddelta, 0)
        pos_chunks = [
            jnp.bitwise_and(jnp.right_shift(v, 7 * k), 127)
            for v in (dp, dn)
            for k in range(dlv)
        ]
        outs = _mxu_spread(dpos_, pos_chunks, C)
        dd_dense = sum(
            jnp.left_shift(outs[k], 7 * k) for k in range(dlv)
        ) - sum(
            jnp.left_shift(outs[dlv + k], 7 * k) for k in range(dlv)
        )
    else:
        # Native scatter-add carries the full signed int32 in one pass.
        dd_dense = spread_add_rows(dpos_, ddelta, C)
    delta_cum = jnp.cumsum(dd_dense, axis=1)
    fill_slot = col + delta_cum
    fill_dense = jnp.where(
        ind > 0, jnp.left_shift(fill_slot + 2, 1) | 1, 0
    )

    # ---- expansion + fill ----
    cntind = jnp.left_shift(cnt, 1) | ind
    if jax.default_backend() == "tpu":
        from .expand_pallas import expand_packed

        doc = expand_packed(doc, cntind, nbits=nbits)
    else:
        (doc,) = _expand([doc], cnt, nbits)
        doc = jnp.where(ind != 0, 0, doc)
    doc = doc + fill_dense

    n_ins = jnp.sum(jnp.where(live, tlen, 0), axis=1)
    n_del = jnp.sum(jnp.where(has_del, dcount, 0), axis=1)
    length = state.length + n_ins
    beyond = col >= length[:, None]
    return PackedState(
        doc=jnp.where(beyond, jnp.int32(2), doc),  # pack(-1, 0) == 2
        length=length,
        nvis=state.nvis + n_ins - n_del,
    )


def _prev_value(x, mask):
    """Per row: for each masked position, the previous masked position's
    value (0 if none).  O(T log T) log-shift forward-fill over the small
    token axis."""
    R, T = x.shape
    carry_v = jnp.where(mask, x, 0)
    carry_m = mask
    steps = 1
    while steps < T:
        sv = jnp.concatenate(
            [jnp.zeros((R, steps), x.dtype), carry_v[:, :-steps]], axis=1
        )
        sm = jnp.concatenate(
            [jnp.zeros((R, steps), bool), carry_m[:, :-steps]], axis=1
        )
        carry_v = jnp.where(carry_m, carry_v, sv)
        carry_m = carry_m | sm
        steps *= 2
    # carry_v now holds, at every position, the value of the nearest masked
    # position at-or-before it.  Shift by one masked step: take the carry
    # just BEFORE each masked position.
    pv = jnp.concatenate([jnp.zeros((R, 1), x.dtype), carry_v[:, :-1]], axis=1)
    pm = jnp.concatenate([jnp.zeros((R, 1), bool), carry_m[:, :-1]], axis=1)
    return jnp.where(mask & pm, pv, 0)
