"""Exact MXU one-hot gathers.

On this TPU runtime, arbitrary-index gathers (``take_along_axis``) lower to
a per-row serialized loop (~21ns per gathered row — measured; see README
environment notes), so a (R, B) gather costs R*B*21ns regardless of how
little data moves.  A one-hot bf16 matmul performs the same gather on the
MXU: the one-hot operand is exact in bf16, each output receives exactly one
contribution (so accumulation order is irrelevant), and integer values are
split into 7-bit chunks (<= 127, exact in bf16) and recombined.

These helpers are the gather-side twins of apply2._mxu_spread (the
scatter side), used by the resolver post-extraction and the two-level
rank->position queries.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _n_chunks(max_value: int) -> int:
    n = 1
    while (1 << (7 * n)) <= max_value:
        n += 1
    return n


def onehot_gather_vec(src, idx, *, max_value: int):
    """out[r, b] = src[r, idx[r, b]] for int32 src in [0, max_value].

    src: int32[R, N]; idx: int32[R, B] (out-of-range -> 0).
    """
    R, N = src.shape
    B = idx.shape[1]
    oh = (
        (
            jax.lax.broadcasted_iota(jnp.int32, (R, B, N), 2)
            == idx[:, :, None]
        )
        & (idx >= 0)[:, :, None]
        & (idx < N)[:, :, None]
    ).astype(jnp.bfloat16)
    out = jnp.zeros((R, B), jnp.int32)
    for k in range(_n_chunks(max_value)):
        chunk = jnp.bitwise_and(
            jnp.right_shift(src, 7 * k), 127
        ).astype(jnp.bfloat16)
        part = jnp.einsum(
            "rbn,rn->rb", oh, chunk, preferred_element_type=jnp.float32
        ).astype(jnp.int32)
        out = out + jnp.left_shift(part, 7 * k)
    return out


def onehot_gather_rows(tiles, tq, *, max_value: int):
    """rows[r, b, :] = tiles[r, tq[r, b], :] for int32 tiles in
    [0, max_value].  tiles: int32[R, nt, L]; tq: int32[R, B] (out-of-range
    -> 0 rows)."""
    R, nt, L = tiles.shape
    B = tq.shape[1]
    oh = (
        (
            jax.lax.broadcasted_iota(jnp.int32, (R, B, nt), 2)
            == tq[:, :, None]
        )
        & (tq >= 0)[:, :, None]
        & (tq < nt)[:, :, None]
    ).astype(jnp.bfloat16)
    out = jnp.zeros((R, B, L), jnp.int32)
    for k in range(_n_chunks(max_value)):
        chunk = jnp.bitwise_and(
            jnp.right_shift(tiles, 7 * k), 127
        ).astype(jnp.bfloat16)
        part = jnp.einsum(
            "rbt,rtl->rbl", oh, chunk, preferred_element_type=jnp.float32
        ).astype(jnp.int32)
        out = out + jnp.left_shift(part, 7 * k)
    return out


def onehot_gather_vec_multi(srcs_and_maxes, idx):
    """Gather several (R, N) sources at the same indices, sharing one
    one-hot operand.  srcs_and_maxes: list of (src, max_value)."""
    R, N = srcs_and_maxes[0][0].shape
    B = idx.shape[1]
    oh = (
        (
            jax.lax.broadcasted_iota(jnp.int32, (R, B, N), 2)
            == idx[:, :, None]
        )
        & (idx >= 0)[:, :, None]
        & (idx < N)[:, :, None]
    ).astype(jnp.bfloat16)
    outs = []
    for src, max_value in srcs_and_maxes:
        out = jnp.zeros((R, B), jnp.int32)
        for k in range(_n_chunks(max_value)):
            chunk = jnp.bitwise_and(
                jnp.right_shift(src, 7 * k), 127
            ).astype(jnp.bfloat16)
            part = jnp.einsum(
                "rbn,rn->rb", oh, chunk,
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            out = out + jnp.left_shift(part, 7 * k)
        outs.append(out)
    return outs
