"""Vectorized batch application to per-replica document state tensors.

Given a resolved batch (ops/resolve.py) expressed in pre-batch *rank* space,
update the big fixed-shape state arrays in O(capacity) vectorized work:

1. gather visibility in document order and prefix-sum it (rank -> physical),
2. tombstone deleted slots / set visibility of new slots (scatters),
3. merge the batch's new slots into the document-order permutation with a
   counting merge: ``new_index_old[i] = i + #inserts at gaps <= i`` and
   ``new_index_ins[j] = gap_j + #inserts before j`` — two disjoint scatters,
   no sort (SURVEY.md section 7 hard-part 3, "re-compaction via prefix-sum").

The physical buffer holds every slot ever allocated (tombstones included), in
document order; ``visible`` is indexed by slot id.  This is the TPU analog of
the reference CRDTs' rope/B-tree structures (e.g. diamond-types' op-log +
checkout, reference src/rope.rs:105-137) with a statically-known capacity.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .resolve import ORIGIN_BATCH, ResolvedBatch


class DocState(NamedTuple):
    """Per-replica document state (a scan carry / vmap operand).

    capacity C = init chars + total inserts (padded); all arrays fixed-shape.
    """

    order: jax.Array  # int32[C]  slot ids in document order (incl. tombstones)
    visible: jax.Array  # bool[C]  by slot id
    origin: jax.Array  # int32[C] by slot id: left-origin slot (-1 = head)
    length: jax.Array  # int32    used entries of `order`
    nvis: jax.Array  # int32    visible char count


def init_state(capacity: int, n_init: int = 0) -> DocState:
    """Fresh document: slots 0..n_init-1 hold the start content (the
    ``from_str`` capability, reference src/rope.rs:10)."""
    idx = jnp.arange(capacity, dtype=jnp.int32)
    return DocState(
        order=jnp.where(idx < n_init, idx, -1),
        visible=idx < n_init,
        origin=jnp.where(idx < n_init, idx - 1, -1),
        length=jnp.int32(n_init),
        nvis=jnp.int32(n_init),
    )


def _doc_order_visibility(state: DocState):
    """vis[i] = is the i-th document-order entry a visible char;
    cumvis = inclusive prefix sum (rank+1 at visible entries)."""
    C = state.order.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    valid = idx < state.length
    slot_at = jnp.where(valid, state.order, 0)
    vis = valid & state.visible[slot_at]
    cumvis = jnp.cumsum(vis.astype(jnp.int32))
    return slot_at, vis, cumvis


def rank_to_phys(cumvis: jax.Array, rank: jax.Array) -> jax.Array:
    """Physical document-order index of the visible char with given rank."""
    return jnp.searchsorted(cumvis, rank + 1, side="left").astype(jnp.int32)


def apply_batch(
    state: DocState, resolved: ResolvedBatch, slots: jax.Array
) -> DocState:
    """Apply one resolved batch.  ``slots``: int32[B] preassigned slot ids for
    insert ops (-1 otherwise, from the tensorizer)."""
    state, _ = apply_batch_collect(state, resolved, slots)
    return state


def apply_batch_collect(
    state: DocState, resolved: ResolvedBatch, slots: jax.Array
) -> tuple[DocState, jax.Array]:
    """Like :func:`apply_batch` but also returns ``dslot``: int32[B], the slot
    id tombstoned by each DELETE op (-1 for non-deletes) — covering both
    pre-batch targets and same-batch inserts.  Update generation
    (engine/downstream.py) uses it to name every delete's target element, the
    analog of diamond-types encoding delete targets into updates
    (reference src/rope.rs:201-214)."""
    C = state.order.shape[0]
    B = slots.shape[0]
    drop = jnp.int32(C)  # any out-of-range index with mode="drop"

    slot_at, vis, cumvis = _doc_order_visibility(state)

    # --- deletes of pre-batch chars: rank -> phys -> slot, clear visibility
    dr = resolved.del_rank
    has_del = dr >= 0
    dphys = rank_to_phys(cumvis, jnp.where(has_del, dr, 0))
    dslot = state.order[jnp.clip(dphys, 0, C - 1)]
    visible = state.visible.at[jnp.where(has_del, dslot, drop)].set(
        False, mode="drop"
    )

    # --- batch inserts: visibility (dead-on-arrival stays False)
    is_ins = resolved.ins_gvis >= 0
    ins_idx = jnp.where(is_ins, slots, drop)
    visible = visible.at[ins_idx].set(resolved.ins_alive, mode="drop")

    # --- origin codes -> slot ids, scattered by slot
    oc = resolved.origin
    oc_rank = jnp.clip(oc, 0, ORIGIN_BATCH - 1)
    origin_from_rank = state.order[
        jnp.clip(rank_to_phys(cumvis, oc_rank), 0, C - 1)
    ]
    origin_from_batch = slots[jnp.clip(oc - ORIGIN_BATCH, 0, B - 1)]
    origin_slot = jnp.where(
        oc < 0, -1, jnp.where(oc >= ORIGIN_BATCH, origin_from_batch, origin_from_rank)
    )
    origin = state.origin.at[ins_idx].set(
        jnp.where(is_ins, origin_slot, -1), mode="drop"
    )

    # --- gap rank -> physical gap (index in pre-batch doc order)
    gv = resolved.ins_gvis
    g_phys = jnp.where(
        gv >= state.nvis,
        state.length,
        rank_to_phys(cumvis, jnp.where(is_ins, gv, 0)),
    )

    # --- counting merge of new slots into the order permutation
    bump = jnp.zeros(C + 1, jnp.int32).at[
        jnp.where(is_ins, g_phys, C + 1)
    ].add(1, mode="drop")
    csum = jnp.cumsum(bump)  # csum[x] = #inserts with gap <= x
    idx = jnp.arange(C, dtype=jnp.int32)
    new_idx_old = idx + csum[idx]
    n_before = jnp.where(g_phys > 0, csum[jnp.clip(g_phys - 1, 0)], 0)
    new_idx_ins = g_phys + n_before + resolved.ins_seq

    valid = idx < state.length
    order = (
        jnp.full(C, -1, jnp.int32)
        .at[jnp.where(valid, new_idx_old, drop)]
        .set(jnp.where(valid, state.order, -1), mode="drop")
        .at[jnp.where(is_ins, new_idx_ins, drop)]
        .set(slots, mode="drop")
    )

    n_ins = jnp.sum(is_ins.astype(jnp.int32))
    n_live = jnp.sum((is_ins & resolved.ins_alive).astype(jnp.int32))
    n_del = jnp.sum(has_del.astype(jnp.int32))
    new_state = DocState(
        order=order,
        visible=visible,
        origin=origin,
        length=state.length + n_ins,
        nvis=state.nvis - n_del + n_live,
    )
    db = resolved.del_batch
    out_dslot = jnp.where(
        has_del,
        dslot,
        jnp.where(db >= 0, slots[jnp.clip(db, 0, B - 1)], -1),
    )
    return new_state, out_dslot


def decode_state(state: DocState, chars: jax.Array):
    """Materialize the visible document: returns (codepoints[C], nvis) where
    the first ``nvis`` entries are the document's chars in order.  The analog
    of diamond-types' ``checkout_tip()`` (reference src/rope.rs:135), upgraded
    from length-only to full content."""
    C = state.order.shape[0]
    slot_at, vis, cumvis = _doc_order_visibility(state)
    out = (
        jnp.zeros(C, jnp.int32)
        .at[jnp.where(vis, cumvis - 1, C)]
        .set(chars[slot_at], mode="drop")
    )
    return out, cumvis[-1]
