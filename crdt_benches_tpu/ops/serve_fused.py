"""Fused serve step: resolve + apply for a whole macro round in one place.

The serve engine's macro dispatch used to be one jitted ``lax.scan``
whose body called ``resolve_ranges_rows`` then ``apply_range_batch`` —
two separate ops per round, recompiled for every (class, K, row-tier)
shape, with every round's capacity-wide intermediates round-tripping
through HBM.  This module restructures that hot loop around one fact:
**range resolution does not depend on the document contents**, only on
the running visible-char count, and that count evolves by a scalar
recurrence (``total' = total + L_ins - D_del``) that needs no token
machinery at all.  So:

- :func:`round_starts` computes every round's starting visible count
  with one cheap scalar scan over all K*B ops — after which the K
  rounds' resolves are *independent* of the applies;
- :func:`resolve_round_rows_grow` resolves one round over a **growing
  token list**: after ``i`` ops the list holds at most ``2i + 2`` live
  tokens, so the scan widens through chunk-sized capacities instead of
  paying the full ``2B + 2`` width from op 0 (~35% fewer token-element
  ops, byte-identical results — ``res_step`` is the single shared scan
  body);
- :func:`serve_apply_round_xla` is the off-TPU apply tuned for hosts:
  native scatter-add spreads and a **gather-based expansion**
  (``y[d] = x[d - cnt[d]]`` as one ``take_along_axis`` instead of
  ``nbits`` roll passes — host gathers are cheap; the roll cascade
  exists for the TPU runtime where gathers serialize);
- :func:`serve_macro_fused` is the TPU path: ONE ``pallas_call`` with
  grid ``(row_blocks, K)`` applying all K rounds of a macro dispatch
  with the document block **resident in VMEM across rounds** (the
  output block is revisited along the K axis, so state never touches
  HBM between rounds) while the Pallas pipeline prefetches round
  ``m + 1``'s op tensors during round ``m`` — the double-buffered VMEM
  staging the ROADMAP item asks for.  Rank queries (slot lookup against
  the visibility prefix structure), the boundary spreads, delete-depth
  /hole-count cumsums (triangular-matmul form), the log-shift
  expansion, and the fill all run in-kernel; XLA touches only B/T-sized
  token arrays.

The host orchestration (which shapes share which compiled executables)
lives in ``serve/pool.py``; everything here is pure shape-in/shape-out.
Differential byte-parity against the scan path is pinned by
``tests/test_serve_fused.py`` and the fleet-level tests in
``tests/test_serve_macro.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..traces.tensorize import DELETE, INSERT
from .apply2 import LANE, PackedState, count_le_tiled, spread_add_rows
from .apply_range import (
    _prev_value,
    apply_range_batch,
    ddelta_levels,
    extract_range_tokens,
)
from .apply_range_fused import (
    _flat_cumsum_f32,
    _tile_cumsum,
    _tile_scan_excl,
)
from .expand_pallas import _flat_roll
from .pallas_compat import pltpu  # CompilerParams shim for jax 0.4
from .resolve import TINS
from .resolve_range_scan import (
    res_carry_grow,
    res_carry_init,
    res_finalize,
    res_step,
)

#: Row-chunk width the pool resolves at: ONE compiled resolve
#: executable per (chunk, B, lane-dtypes) serves every capacity class
#: and row tier (the resolve is row-local and capacity-independent).
#: 128 measured best on host CPU: 64 pays ~8% more dispatch overhead,
#: 256 wastes up to 2s of padded compute on the small-tier classes.
RESOLVE_CHUNK_ROWS = 128

#: Growing-token-list chunk: ops [16i, 16(i+1)) scan at capacity
#: 32(i+1) + 2.
RESOLVE_OP_CHUNK = 16

#: Op width of the narrow resolve executable: chunks whose every lane
#: carries at most this many ops (they are front-packed at staging)
#: resolve a [R, 16] slice and pad (resolve_round_rows_padded) — ~6%
#: of the full-width cost, and small-doc classes are mostly such
#: chunks.
NARROW_RESOLVE_OPS = 16

#: Compiler options for the fused path's host executables: the serve
#: bodies are huge scan loops whose LLVM "expensive" optimization
#: passes buy nothing measurable at runtime (probed: run time flat to
#: slightly better) while costing ~25% of each compile — and compile
#: spread is the serve fleet's dominant cold-start cost.
FUSED_COMPILER_OPTIONS = {"xla_llvm_disable_expensive_passes": True}


class AotJit:
    """``jax.jit`` that AOT-lowers on first call so
    :data:`FUSED_COMPILER_OPTIONS` can be applied (``jax.jit`` itself
    grew no compiler_options pass-through until well after the pinned
    jax).  Falls back to the plain jit if lower/compile rejects the
    options (older/newer runtimes), so behavior never depends on them.
    """

    def __init__(self, fn, donate_argnums=(), options=None):
        self._jit = jax.jit(fn, donate_argnums=donate_argnums)
        self._opts = dict(
            FUSED_COMPILER_OPTIONS if options is None else options
        )
        self._compiled = None

    def __call__(self, *args):
        if self._compiled is None:
            try:
                self._compiled = self._jit.lower(*args).compile(
                    compiler_options=self._opts
                )
            except Exception:  # pragma: no cover - runtime-dependent
                self._compiled = self._jit
        if self._compiled is self._jit:
            return self._jit(*args)
        try:
            return self._compiled(*args)
        except ValueError:
            # input sharding/layout drifted from the AOT signature
            # (mesh pools slice staged tensors across devices, so chunk
            # placements vary call to call): the plain jit reshards and
            # recompiles as jax normally would.  The AOT form is a
            # compile-latency optimization, never a semantics one —
            # demote permanently and keep serving.
            self._compiled = self._jit
            return self._jit(*args)


def trivial_round_tokens(v0, B: int):
    """The resolve output of an ALL-PAD op chunk, built directly: one
    RUN(0, v0) token, FREE tail, no delete intervals.  Byte-identical
    to scanning the PAD ops (each PAD step writes its token back
    unchanged) — the fused dispatcher substitutes this for resolve
    calls on chunks the host can see carry no ops, which trailing
    macro slices of drained lanes often are."""
    from .resolve import FREE, RUN

    R = v0.shape[0]
    T = 2 * B + 2
    didx = jnp.arange(T, dtype=jnp.int32)
    first = (didx == 0)[None, :]
    ttype = jnp.broadcast_to(
        jnp.where(first, RUN, FREE).astype(jnp.int32), (R, T)
    )
    zeros = jnp.zeros((R, T), jnp.int32)
    tlen = jnp.where(first, jnp.asarray(v0, jnp.int32)[:, None], 0)
    neg = jnp.full((R, B), -1, jnp.int32)
    return (
        (ttype, zeros, zeros, tlen),
        (neg, neg, jnp.zeros((R, B), jnp.int32)),
    )


# ---------------------------------------------------------------------
# round starts: the scalar totals recurrence
# ---------------------------------------------------------------------


def round_starts(kind, pos, rlen, v0):
    """Starting visible-char count of every round in a macro dispatch:
    kind/pos/rlen int32[K, R, B], v0 int32[R] -> int32[K, R].

    The recurrence mirrors ``res_step``'s clamping exactly (positions
    clip to [0, total], deletes clip to the remaining suffix), so the
    result equals the nvis each round's resolve would have observed
    inside the old interleaved scan — which is what makes the K
    resolves independent of the K applies."""
    K, R, B = kind.shape
    # (K*B, R) op-major: round k's ops occupy rows [k*B, (k+1)*B)
    flat = lambda x: jnp.swapaxes(
        jnp.asarray(x, jnp.int32), 0, 1
    ).reshape(R, K * B).T
    k2, p2, l2 = flat(kind), flat(pos), flat(rlen)

    def step(tot, op):
        k, p0, L0 = op
        is_ins = (k == INSERT) & (L0 > 0)
        p = jnp.clip(p0, 0, tot)
        D = jnp.where(k == DELETE, jnp.clip(L0, 0, tot - p), 0)
        L = jnp.where(is_ins, L0, 0)
        return tot + L - D, tot

    _, pre = jax.lax.scan(step, jnp.asarray(v0, jnp.int32), (k2, p2, l2))
    return pre[::B]  # (K, R): the total BEFORE each round's first op


def round_total_delta(kind, pos, rlen, v0):
    """Advance the visible-count recurrence across ONE round: kind/pos/
    rlen int32[R, B], v0 int32[R] -> the next round's v0.  The pool
    chains this per round instead of jitting :func:`round_starts` per
    macro depth — K never keys an executable anywhere on the fused
    path."""
    def step(tot, op):
        k, p0, L0 = op
        is_ins = (k == INSERT) & (L0 > 0)
        p = jnp.clip(p0, 0, tot)
        D = jnp.where(k == DELETE, jnp.clip(L0, 0, tot - p), 0)
        L = jnp.where(is_ins, L0, 0)
        return tot + L - D, None

    out, _ = jax.lax.scan(
        step,
        jnp.asarray(v0, jnp.int32),
        tuple(
            jnp.asarray(a, jnp.int32).T for a in (kind, pos, rlen)
        ),
    )
    return out


# ---------------------------------------------------------------------
# growing-token-list resolve
# ---------------------------------------------------------------------


def _resolve_grow1(kind, pos, rlen, slot0, v0, chunk: int):
    """One row's round resolved over a growing token list.  Exactly
    ``resolve_ranges_scan`` (same step, same outputs) but the scan runs
    in op chunks of ``chunk`` with the carry widened between chunks —
    ops [0, c) only ever touch ``2c + 2`` tokens, so early chunks skip
    most of the worst-case width."""
    B = kind.shape[0]
    T_full = 2 * B + 2
    ops = (
        jnp.asarray(kind, jnp.int32),
        jnp.asarray(pos, jnp.int32),
        jnp.asarray(rlen, jnp.int32),
        jnp.asarray(slot0, jnp.int32),
    )
    carry = res_carry_init(2 * min(chunk, B) + 2, v0)
    outs = []
    c0 = 0
    while c0 < B:
        c1 = min(c0 + chunk, B)
        T = 2 * c1 + 2
        carry = res_carry_grow(carry, T)
        sl = tuple(o[c0:c1] for o in ops)
        carry, ys = jax.lax.scan(
            lambda c, o: res_step(c, o, T), carry, sl
        )
        outs.append(ys)
        c0 = c1
    carry = res_carry_grow(carry, T_full)
    dlo = jnp.concatenate([y[0] for y in outs])
    dhi = jnp.concatenate([y[1] for y in outs])
    dn = jnp.concatenate([y[2] for y in outs])
    tokens, nused = res_finalize(carry)
    return tokens, (dlo, dhi, dn), nused


def resolve_round_rows_grow(kind, pos, rlen, slot0, v0,
                            chunk: int = RESOLVE_OP_CHUNK):
    """Per-row growing-list resolve of ONE round: kind/pos/rlen/slot0
    [R, B] (any integer dtype — widened here, see ops/packing.py), v0
    int32[R].  Returns (tokens [R, T], dints [R, B]) — byte-identical
    to ``resolve_ranges_rows`` (differentially tested)."""
    f = lambda k, p, l, s, v: _resolve_grow1(k, p, l, s, v, chunk)
    tokens, dints, _ = jax.vmap(f)(
        *(jnp.asarray(a, jnp.int32) for a in (kind, pos, rlen, slot0)),
        jnp.asarray(v0, jnp.int32),
    )
    return tokens, dints


def resolve_round_rows_padded(kind, pos, rlen, slot0, v0, out_B: int,
                              chunk: int = RESOLVE_OP_CHUNK):
    """Resolve a FRONT-PACKED narrow op slice (ops [R, b] with b <
    out_B) and pad the outputs to the full round width: FREE/zero-
    length tail tokens and empty delete intervals are inert everywhere
    downstream, so the result is byte-identical to resolving the full
    [R, out_B] slice whose trailing slots are PAD.  The pool uses this
    when the host can see every lane of a chunk carries few ops —
    resolve cost scales with b * (2b + 2), so a 16-op slice costs ~6%
    of a 64-op one."""
    tokens, dints = resolve_round_rows_grow(
        kind, pos, rlen, slot0, v0, chunk
    )
    from .resolve import FREE

    R, b = kind.shape[0], kind.shape[1]
    padT = (2 * out_B + 2) - (2 * b + 2)
    padB = out_B - b
    ttype, ta, tch, tlen = tokens
    fill = lambda x, v: jnp.concatenate(
        [x, jnp.full((R, padT), v, jnp.int32)], axis=1
    )
    tokens = (
        fill(ttype, FREE), fill(ta, 0), fill(tch, 0), fill(tlen, 0)
    )
    dlo, dhi, dn = dints
    fillB = lambda x, v: jnp.concatenate(
        [x, jnp.full((R, padB), v, jnp.int32)], axis=1
    )
    return tokens, (fillB(dlo, -1), fillB(dhi, -1), fillB(dn, 0))


# ---------------------------------------------------------------------
# off-TPU apply round (the XLA twin of the serve kernel)
# ---------------------------------------------------------------------


def serve_apply_round_xla(state: PackedState, tokens, dints,
                          nbits: int) -> PackedState:
    """One round's range application, host-tuned: same contract and
    byte semantics as ``apply_range_batch`` (differentially tested) but
    with the expansion as ONE gather — ``y[d] = x[d - cnt[d]]`` via
    ``take_along_axis`` — instead of ``nbits`` masked roll passes, and
    all spreads as native row scatter-adds.  Positions with
    ``d - cnt[d] < 0`` can only be insert holes (cnt[d] > d means every
    position <= d is a hole), so the clamped gather's garbage there is
    always overwritten by the fill."""
    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    R, C = state.doc.shape
    B = dlo.shape[1]
    drop = jnp.int32(C + 7)
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    vis_bit = jnp.bitwise_and(state.doc, 1)

    has_del = dlo >= 0
    live, gvis, cumlen = extract_range_tokens(
        ttype, ta, tch, tlen, v0=state.nvis
    )
    allq_in = jnp.concatenate(
        [
            jnp.where(has_del, dlo, 0),
            jnp.where(has_del, dhi, 0),
            jnp.where(live, gvis, 0),
        ],
        axis=1,
    )
    cumvis = jnp.cumsum(
        vis_bit * (col < state.length[:, None]).astype(jnp.int32), axis=1
    )
    allq = count_le_tiled(cumvis, allq_in)
    lo_phys = allq[:, :B]
    hi_phys = allq[:, B : 2 * B]
    gq_phys = allq[:, 2 * B :]

    # ---- deletes: clear visible bits over physical rank intervals ----
    starts = spread_add_rows(
        jnp.where(has_del, lo_phys, drop), has_del.astype(jnp.int32), C
    )
    stops = spread_add_rows(
        jnp.where(has_del, hi_phys + 1, drop), has_del.astype(jnp.int32), C
    )
    in_del = jnp.cumsum(starts - stops, axis=1) > 0
    doc = state.doc - (vis_bit & in_del.astype(jnp.int32))

    # ---- insert runs: destinations, hole counts, per-run deltas ----
    at_end = gvis >= state.nvis[:, None]
    g_phys = jnp.where(at_end, state.length[:, None], gq_phys)
    dest0 = jnp.where(live, g_phys + cumlen, drop)
    dstop = jnp.where(live, dest0 + tlen, drop)
    s1 = spread_add_rows(dest0, live.astype(jnp.int32), C)
    s2 = spread_add_rows(dstop, live.astype(jnp.int32), C)
    ind = (jnp.cumsum(s1 - s2, axis=1) > 0).astype(jnp.int32)
    cnt = jnp.cumsum(ind, axis=1)
    delta = jnp.where(live, ta + tch - dest0, 0)
    ddelta = jnp.where(live, delta - _prev_value(delta, live), 0)
    dd_dense = spread_add_rows(
        jnp.where(live, dest0, drop), ddelta, C
    )
    delta_cum = jnp.cumsum(dd_dense, axis=1)

    # ---- expansion as one clamped gather + fill ----
    # col - cnt < 0 exactly on the insert-fill columns; the clamped
    # gather reads column 0 garbage there, and the ind > 0 select
    # below overwrites every such column with the fill encoding
    doc = jnp.take_along_axis(doc, jnp.maximum(col - cnt, 0), axis=1)  # graftlint: mask=fused-gap-gather surface=fused
    doc = jnp.where(  # graftlint: mask=fused-gap-gather surface=fused
        ind > 0, jnp.left_shift(col + delta_cum + 2, 1) | 1, doc
    )

    n_ins = jnp.sum(jnp.where(live, tlen, 0), axis=1)
    n_del = jnp.sum(jnp.where(has_del, dcount, 0), axis=1)
    length = state.length + n_ins
    beyond = col >= length[:, None]
    return PackedState(
        doc=jnp.where(beyond, jnp.int32(2), doc),  # pack(-1, 0) == 2
        length=length,
        nvis=state.nvis + n_ins - n_del,
    )


# ---------------------------------------------------------------------
# the serve kernel: all K rounds in one pallas_call
# ---------------------------------------------------------------------

#: Estimated Mosaic scoped-stack bytes per doc position for
#: _serve_round_kernel: the range-fused working set (~150 B/pos) plus
#: the in-kernel rank-query intermediates — the (Rt, nt, Q) tile
#: compare and the (Rt, LANE, Q) row fetch with Q = 2*Bp + Tp.
SERVE_FUSED_BYTES_PER_POS = 220


def _serve_pads(B: int) -> tuple[int, int]:
    """(Bp, Tp): the kernel's lane-padded delete-interval and token
    widths (minor dims must be LANE multiples — lint G010)."""
    Bp = -(-B // LANE) * LANE
    Tp = -(-(2 * B + 2) // LANE) * LANE
    return Bp, Tp


def serve_fused_fits(C: int, B: int) -> bool:
    """The ONE VMEM gate for the serve kernel (mirrors
    ``range_fused_fits``): callers and the dispatcher must agree."""
    return SERVE_FUSED_BYTES_PER_POS * C <= 96 * 2**20


def _prev_value_flat(x, m, t2: int):
    """In-kernel ``_prev_value``: per row, for each masked position the
    previous masked position's value (0 if none), over (Rt, t2, LANE)
    arrays in flattened (tile, lane) order.  Log-shift forward fill via
    _flat_roll with the wrapped lanes masked by the flat column."""
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 2)
    col = (
        jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) * LANE + lane
    )
    carry_v = jnp.where(m, x, 0)
    carry_m = m.astype(jnp.int32)
    s = 1
    while s < t2 * LANE:
        sv = jnp.where(col >= s, _flat_roll(carry_v, s), 0)
        sm = jnp.where(col >= s, _flat_roll(carry_m, s), 0)
        carry_v = jnp.where(carry_m > 0, carry_v, sv)
        carry_m = jnp.maximum(carry_m, sm)
        s *= 2
    pv = jnp.where(col >= 1, _flat_roll(carry_v, 1), 0)
    pm = jnp.where(col >= 1, _flat_roll(carry_m, 1), 0)
    return jnp.where(m & (pm > 0), pv, 0)


def _spread_dot(tileq, laneq, val, nt: int):
    """In-kernel exact scatter-add of ``val[r, w]`` at flat position
    ``tileq[r, w] * LANE + laneq[r, w]`` into a dense (Rt, nt, LANE)
    int32 array, as two one-hot contractions (the _mxu_spread
    factorization run in VMEM).  Out-of-range positions must arrive
    with ``tileq >= nt`` (no one-hot match = dropped).  Exactness:
    every value is f32-exact (small ints or 7-bit chunks shifted by
    2^7k) and collisions accumulate in f32 (< 2^24)."""
    Rt, W = tileq.shape
    ohT = (
        jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, W), 1)
        == tileq[:, None, :]
    ).astype(jnp.float32)
    m1 = ohT * val[:, None, :].astype(jnp.float32)
    ohL = (
        jax.lax.broadcasted_iota(jnp.int32, (Rt, W, LANE), 2)
        == laneq[:, :, None]
    ).astype(jnp.float32)
    dense = jax.lax.dot_general(
        m1, ohL, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    return dense.astype(jnp.int32)


def _count_le_kernel(cv, q, nt: int, C: int):
    """In-kernel ``count_le``: #{flat positions with cumvis <= q} from
    the absolute within-kernel cumvis (Rt, nt, LANE).  Tile-maxima
    narrowing + a 7-bit-chunked one-hot row fetch (cumvis values reach
    C > the bf16-exact range, so the fetch rides chunk dots), then a
    lane compare — the count_le_tiled contract without a single
    serialized gather."""
    Rt, Q = q.shape
    tmax = cv[:, :, LANE - 1 :]  # (Rt, nt, 1)
    nfull = jnp.sum(
        (tmax <= q[:, None, :]).astype(jnp.int32), axis=1
    )  # (Rt, Q)
    tq = jnp.minimum(nfull, nt - 1)
    ohT = (
        jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, Q), 1)
        == tq[:, None, :]
    ).astype(jnp.float32)
    n_ch = max(3, -(-((int(C) - 1).bit_length()) // 7))
    rows = jnp.zeros((Rt, LANE, Q), jnp.int32)
    for k in range(n_ch):
        chunk = jnp.bitwise_and(
            jnp.right_shift(cv, 7 * k), 127
        ).astype(jnp.float32)
        rows = rows + jnp.left_shift(
            jax.lax.dot_general(
                chunk, ohT, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32),
            7 * k,
        )
    within = jnp.sum(
        (rows <= q[:, None, :]).astype(jnp.int32), axis=1
    )
    return jnp.where(nfull >= nt, C, nfull * LANE + within)


def _serve_round_kernel(
    doc_ref, dlo_ref, dhi_ref, gvis_ref, live_ref, cumlen_ref,
    atch_ref, tlen_ref, lenk_ref, nvisk_ref, newlen_ref,
    doc_out,
    *, nt: int, nbits: int, Rt: int, Bp: int, Tp: int, dlv: int,
):
    """One (row-block, round) grid step of the fused serve dispatch.

    The doc block is CARRIED across the K rounds of the grid's minor
    axis: the output block's index map pins (i, k) -> block i, so
    Pallas keeps it VMEM-resident between rounds (round 0 seeds it from
    the input doc) while the per-round op tensors stream in
    double-buffered.  Everything capacity-wide happens here; the
    B/T-sized inputs were precomputed by :func:`serve_round_inputs`.
    """
    k = pl.program_id(1)
    C = nt * LANE
    drop = jnp.int32(C + 7)
    lane = jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 2)
    col = (
        jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 1) * LANE
        + lane
    )
    li = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 0)
    lj = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
    tri = (li <= lj).astype(jnp.float32)

    @pl.when(k == 0)
    def _():
        doc_out[:] = doc_ref[:]

    doc = doc_out[:]
    vis = jnp.bitwise_and(doc, 1)
    cv = _flat_cumsum_f32(vis, tri)  # absolute cumvis of THIS round

    dlo = dlo_ref[0]
    dhi = dhi_ref[0]
    gvis = gvis_ref[0]
    live = live_ref[0] > 0
    cumlen = cumlen_ref[0]
    atch = atch_ref[0]
    tlen = tlen_ref[0]
    len_k = lenk_ref[0]  # (Rt, 1)
    nvis_k = nvisk_ref[0]
    newlen = newlen_ref[0]

    # ---- rank queries: delete endpoints + insert gaps in one pass ----
    has_del = dlo >= 0
    q = jnp.concatenate(
        [
            jnp.where(has_del, dlo, 0),
            jnp.where(has_del, dhi, 0),
            jnp.where(live, gvis, 0),
        ],
        axis=1,
    )  # (Rt, 2*Bp + Tp)
    allq = _count_le_kernel(cv, q, nt, C)
    lo_phys = allq[:, :Bp]
    hi_phys = allq[:, Bp : 2 * Bp]
    gq_phys = allq[:, 2 * Bp :]

    # ---- deletes: signed boundary spread -> depth -> clear vis ----
    idx_d = jnp.concatenate(
        [
            jnp.where(has_del, lo_phys, drop),
            jnp.where(has_del, hi_phys + 1, drop),
        ],
        axis=1,
    )
    hd = has_del.astype(jnp.int32)
    val_d = jnp.concatenate([hd, -hd], axis=1)
    deld = _spread_dot(
        jnp.right_shift(idx_d, 7), jnp.bitwise_and(idx_d, 127), val_d, nt
    )
    depth = _flat_cumsum_f32(deld, tri)
    doc = doc - (vis & (depth > 0).astype(jnp.int32))

    # ---- insert destinations and the hole map ----
    at_end = gvis >= nvis_k
    g_phys = jnp.where(at_end, len_k, gq_phys)
    dest0 = jnp.where(live, g_phys + cumlen, drop)
    dstop = jnp.where(live, dest0 + tlen, drop)
    lv = live.astype(jnp.int32)
    idx_i = jnp.concatenate([dest0, dstop], axis=1)
    val_i = jnp.concatenate([lv, -lv], axis=1)
    ind_d = _spread_dot(
        jnp.right_shift(idx_i, 7), jnp.bitwise_and(idx_i, 127), val_i, nt
    )
    run_ind = (_flat_cumsum_f32(ind_d, tri) > 0).astype(jnp.int32)
    cnt = _flat_cumsum_f32(run_ind, tri)

    # ---- per-run slot deltas: one chunked spread + chunked cumsum ----
    delta = jnp.where(live, atch - dest0, 0)
    ddelta = jnp.where(
        live, delta - _prev_value_flat(
            delta.reshape(Rt, Tp // LANE, LANE),
            live.reshape(Rt, Tp // LANE, LANE),
            Tp // LANE,
        ).reshape(Rt, Tp),
        0,
    )
    sgn = jnp.where(ddelta < 0, -1, 1)
    mag = jnp.abs(ddelta)
    lvl = [
        sgn * jnp.left_shift(
            jnp.bitwise_and(jnp.right_shift(mag, 7 * j), 127), 7 * j
        )
        for j in range(dlv)
    ]
    dd = _spread_dot(
        jnp.concatenate([jnp.right_shift(dest0, 7)] * dlv, axis=1),
        jnp.concatenate([jnp.bitwise_and(dest0, 127)] * dlv, axis=1),
        jnp.concatenate(lvl, axis=1),
        nt,
    )
    # chunked tile cumsum of the signed dd (the _range_fused_kernel
    # exactness argument: per level, partial sums stay below 2^24)
    dcum_w = jnp.zeros((Rt, nt, LANE), jnp.int32)
    for v, sign in ((jnp.maximum(dd, 0), 1), (jnp.maximum(-dd, 0), -1)):
        for j in range(dlv):
            chunk = jnp.bitwise_and(jnp.right_shift(v, 7 * j), 127)
            dcum_w = dcum_w + sign * jnp.left_shift(
                _tile_cumsum(chunk, tri), 7 * j
            )
    dcum = dcum_w + _tile_scan_excl(dcum_w[:, :, LANE - 1 :])

    # ---- expansion y[d] = x[d - cnt[d]] + fill + beyond-length ----
    maxcnt = jnp.max(cnt[:, :, LANE - 1 :])
    doc_out[:] = doc
    for b in reversed(range(nbits)):
        step = 1 << b

        @pl.when(maxcnt >= step)
        def _():
            d = doc_out[:]
            take = (jnp.bitwise_and(cnt, step) != 0) & (col >= step)
            doc_out[:] = jnp.where(take, _flat_roll(d, step), d)

    fill = jnp.left_shift(col + dcum + 2, 1) | 1
    doc_out[:] = jnp.where(run_ind != 0, fill, doc_out[:])
    nl = newlen.reshape(Rt, 1, 1)
    doc_out[:] = jnp.where(col >= nl, 2, doc_out[:])


def serve_round_inputs(tokens, dints, length0, nvis0):
    """XLA prologue shared by the kernel and its fallback: per-round
    B/T-sized arrays derived from the K resolved rounds.  tokens:
    (ttype, ta, tch, tlen) int32[K, R, T]; dints int32[K, R, B];
    length0/nvis0 int32[R] the macro dispatch's starting state.

    Per-round lengths and visible counts are data-independent of the
    document (insert/delete volumes come straight from the resolve
    outputs), so the whole K-round schedule is computed here once:
    returns (live, gvis, cumlen int32[K, R, T], len_k, nvis_k, newlen
    int32[K, R], length_K, nvis_K int32[R])."""
    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    live0 = (ttype == TINS) & (tlen > 0)
    n_ins = jnp.sum(jnp.where(live0, tlen, 0), axis=2)  # (K, R)
    n_del = jnp.sum(jnp.where(dlo >= 0, dcount, 0), axis=2)
    ins_cum = jnp.cumsum(n_ins, axis=0)
    del_cum = jnp.cumsum(n_del, axis=0)
    len_k = length0[None, :] + ins_cum - n_ins  # round-start lengths
    nvis_k = nvis0[None, :] + (ins_cum - n_ins) - (del_cum - n_del)
    newlen = length0[None, :] + ins_cum
    live, gvis, cumlen = jax.vmap(extract_range_tokens)(
        ttype, ta, tch, tlen, nvis_k
    )
    return (
        live.astype(jnp.int32), gvis, cumlen, len_k, nvis_k, newlen,
        length0 + ins_cum[-1], nvis0 + ins_cum[-1] - del_cum[-1],
    )


@functools.partial(
    jax.jit, static_argnames=("nbits", "replica_tile", "interpret")
)
def serve_macro_fused(state: PackedState, tokens, dints, *,
                      nbits: int, replica_tile: int = 0,
                      interpret: bool = False) -> PackedState:
    """Apply all K resolved rounds to a PackedState stack with ONE
    pallas_call (grid = (row_blocks, K); the doc block rides VMEM
    across the K axis).  tokens/dints as from K stacked
    ``resolve_round_rows_grow`` calls.  Falls back is the caller's job
    (see ``serve_fused_fits``); interpret=True runs the kernel under
    the Pallas interpreter for off-TPU differential tests."""
    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    K, R, T = ttype.shape
    B = dlo.shape[2]
    C = state.doc.shape[1]
    nt = C // LANE
    Bp, Tp = _serve_pads(B)

    (live, gvis, cumlen, len_k, nvis_k, newlen, length_K, nvis_K
     ) = serve_round_inputs(tokens, dints, state.length, state.nvis)

    padT = lambda x, v: jnp.concatenate(
        [x, jnp.full((K, R, Tp - T), v, jnp.int32)], axis=2
    ) if Tp > T else x
    padB = lambda x, v: jnp.concatenate(
        [x, jnp.full((K, R, Bp - B), v, jnp.int32)], axis=2
    ) if Bp > B else x

    Rt = replica_tile
    if Rt <= 0:
        Rt = max(1, (96 * 2**20) // (SERVE_FUSED_BYTES_PER_POS * C))
    Rt = min(Rt, R)
    while R % Rt:
        Rt -= 1
    doc_spec = pl.BlockSpec(
        (Rt, nt, LANE), lambda i, k: (i, 0, 0), memory_space=pltpu.VMEM
    )
    rnd = lambda W: pl.BlockSpec(
        (1, Rt, W), lambda i, k: (k, i, 0), memory_space=pltpu.VMEM
    )
    one = pl.BlockSpec(
        (1, Rt, 1), lambda i, k: (k, i, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _serve_round_kernel, nt=nt, nbits=nbits, Rt=Rt, Bp=Bp, Tp=Tp,
        dlv=ddelta_levels(C),
    )
    doc_o = pl.pallas_call(
        kernel,
        grid=(R // Rt, K),
        in_specs=[doc_spec] + [rnd(Bp)] * 2 + [rnd(Tp)] * 5
        + [one] * 3,
        out_specs=doc_spec,
        out_shape=jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2**20
        ),
        interpret=interpret,
    )(
        state.doc.reshape(R, nt, LANE),
        padB(dlo, -1), padB(dhi, -1),
        padT(gvis, 0), padT(live, 0), padT(cumlen, 0),
        padT(ta + tch, 0), padT(tlen, 0),
        len_k[:, :, None], nvis_k[:, :, None], newlen[:, :, None],
    )
    return PackedState(
        doc=doc_o.reshape(R, C), length=length_K, nvis=nvis_K
    )


def serve_macro_rounds_xla(state: PackedState, tokens, dints,
                           nbits: int) -> PackedState:
    """The fused dispatch's non-kernel twin: scan the K resolved rounds
    through the per-round apply (host-tuned off TPU, the proven
    ``apply_range_batch`` on TPU shapes beyond the VMEM gate)."""
    on_tpu = jax.default_backend() == "tpu"

    def body(st, x):
        tok, di = x
        if on_tpu:
            return apply_range_batch(st, tok, di, nbits=nbits), None
        return serve_apply_round_xla(st, tok, di, nbits), None

    out, _ = jax.lax.scan(body, state, (tokens, dints))
    return out
