"""Fused RANGE batch application for the REPLAY engines: one Pallas
kernel per batch runs the capacity-wide passes of
``engine/replay_range.py``-style shared-stream replay (the serve
fleet's macro dispatch has its own kernel in ``ops/serve_fused.py``,
which imports this module's in-kernel building blocks —
``_tile_cumsum`` / ``_tile_scan_excl`` / ``_flat_cumsum_f32`` — rather
than this kernel; keep that in mind when changing their semantics).

Profiling the XLA range apply (tools/profile.py range, R=1024, C=182k)
put it at ~131 ms/batch against a ~3 ms HBM floor: every stage — the
per-batch visibility cumsum, the one-hot spreads, four capacity-sized
cumsums, the fill pass — round-trips (R, C) intermediates through HBM,
and the spread one-hots materialize at (R, B, C/128) bf16.  This module
keeps the XLA side to SMALL arrays only (token extraction, two-level
rank queries, two merged one-hot spread calls with signed +-1 values)
and runs all capacity-wide work inside one kernel with the arrays VMEM
-resident:

- **Triangular-matmul prefix sums**: an inclusive 128-lane cumsum is one
  f32 dot with a (LANE, LANE) upper-triangular ones matrix — the MXU
  replaces ~21 VPU shift passes per cumsum.  f32 operands/accumulation
  are exact here because every running value is bounded by 2^24: delete
  -interval nesting depth <= B, insert-run indicator <= 1, and the
  slot-delta differences travel as ddelta_levels(C) 7-bit chunk levels
  (3 below 2^20 capacity) whose per-level within-tile cumsums stay
  below 2^24 while the shifted int32 level accumulation is bounded by
  cumsum(|dd|) <= 128 * 2C — exact through the engine guard C <= 2^22.
- Cross-tile bases by an in-kernel log-shift scan over the (nt, 1) tile
  totals (12 vregs — negligible).
- The log-shift expansion, hole fill (slot = position + delta prefix),
  beyond-length stamping, and the NEXT batch's visibility prefix
  structure (cv_intile bf16 + vis_tile) all emit from the same kernel,
  so the engine state is the maintained PackedState4 — no per-batch
  capacity cumsum anywhere in XLA.

Semantics are identical to ops/apply_range.py apply_range_batch
(differentially tested); this is the reference CRDTs' update application
(reference src/main.rs:30-34 hot loop over its range tree) restated in
MXU/VPU-native primitives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from .pallas_compat import pltpu  # CompilerParams shim for jax 0.4

from .apply2 import (
    LANE,
    PackedState4,
    _excl_cumsum_small,
    _mxu_spread,
    count_le_two_level,
)
from .apply_range import _prev_value, ddelta_levels, extract_range_tokens
from .expand_pallas import _flat_roll, _roll_ax

#: Mosaic scoped-stack bytes per doc position per replica for
#: _range_fused_kernel (measured: compiles at C=522k under the 100MB
#: budget; ~8 live (nt, LANE) f32/i32 arrays plus roll temps).
RANGE_FUSED_BYTES_PER_POS = 150


def _round_up_c(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def range_fused_fits(capacity: int) -> bool:
    """The ONE VMEM-stack gate for the fused range kernel — callers
    (engine selection, the batch dispatcher, range_fused itself) must all
    use this so a capacity near the edge cannot pass one copy of the
    check and fail another (code-review r4)."""
    return RANGE_FUSED_BYTES_PER_POS * capacity <= 96 * 2**20


def _tile_scan_excl(tot):
    """Exclusive prefix scan along the tile axis of (Rt, nt, 1) int32 —
    log-shift over the sublane dimension (tiny: nt/8 vregs)."""
    Rt, nt, _ = tot.shape
    inc = tot
    s = 1
    while s < nt:
        sh = _roll_ax(inc, s, 1)
        tile = jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, 1), 1)
        inc = inc + jnp.where(tile >= s, sh, 0)
        s *= 2
    return inc - tot


def _tile_cumsum(x_i32, tri):
    """Within-tile inclusive lane cumsum of (Rt, nt, LANE) int32 via one
    triangular f32 matmul.  Exact while every within-tile running value
    stays below 2^24 (callers' bounds in the module docstring)."""
    Rt, nt, _ = x_i32.shape
    xf = x_i32.astype(jnp.float32)
    return jax.lax.dot_general(
        xf.reshape(Rt * nt, LANE), tri,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(Rt, nt, LANE).astype(jnp.int32)


def _flat_cumsum_f32(x_i32, tri):
    """Inclusive flat cumsum: within-tile triangular matmul + cross-tile
    sublane scan of the tile totals."""
    y = _tile_cumsum(x_i32, tri)
    return y + _tile_scan_excl(y[:, :, LANE - 1 :])


def _apply_fused2_kernel(doc_ref, combo_ref, newlen_ref,
                         *rest, nt: int, nbits: int, Rt: int,
                         emit_cv: bool):
    """expand_pallas._apply_fused_kernel re-expressed with the
    triangular-matmul cumsum and NO scratch refs — same measured speed
    as the original, kept because it shares range_fused's building
    blocks and the caller-side wrapper self-pads unaligned tile counts
    (nt % 8 != 0 blows Mosaic compile time up to minutes)."""
    if emit_cv:
        doc_out, cv_ref, vistot_ref = rest
    else:
        (doc_out,) = rest
    lane = jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 2)
    col = (
        jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 1) * LANE + lane
    )
    li = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 0)
    lj = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
    tri = (li <= lj).astype(jnp.float32)

    combo = combo_ref[:]
    ind = jnp.bitwise_and(combo, 1)
    # cross-tile base recomputed in-kernel (== the caller's cnt_base by
    # construction: both are the exclusive prefix of per-tile counts of
    # combo's low bit); an (Rt, nt, 1) INPUT block spec forced layout
    # transposes on the XLA side.
    cnt = _flat_cumsum_f32(ind, tri)
    maxcnt = jnp.max(cnt[:, :, LANE - 1 :])

    doc_out[:] = doc_ref[:]
    for b in reversed(range(nbits)):
        step = 1 << b

        @pl.when(maxcnt >= step)
        def _():
            d = doc_out[:]
            take = (jnp.bitwise_and(cnt, step) != 0) & (col >= step)
            doc_out[:] = jnp.where(take, _flat_roll(d, step), d)

    doc_out[:] = jnp.where(
        ind != 0, jnp.right_shift(combo, 1), doc_out[:]
    )
    doc_out[:] = jnp.where(col >= newlen_ref[:], 2, doc_out[:])
    if emit_cv:
        cv_in = _tile_cumsum(jnp.bitwise_and(doc_out[:], 1), tri)
        cv_ref[:] = cv_in.astype(jnp.bfloat16)
        vistot_ref[:] = cv_in[:, :, LANE - 1 :]


@functools.partial(
    jax.jit,
    static_argnames=("nbits", "replica_tile", "interpret", "emit_cv"),
)
def apply_fused2(doc_predel, combo, cnt_base, new_len, *, nbits: int,
                 replica_tile: int = 0, interpret: bool = False,
                 emit_cv: bool = True):
    """Monolithic fused apply (same contract as the dispatchers'
    blocked/XLA twins: doc_predel/combo int32[R, C], cnt_base int32[R, nt]
    exclusive cross-tile insert-count prefix, new_len int32[R]; returns
    doc' or (doc', cv_intile bf16, vis_tile)).

    WARNING: ``cnt_base`` is accepted only for signature parity with
    apply_fused_blocked / apply_fused_xla and is IGNORED — the kernel
    recomputes the cross-tile insert-count base from combo's low bit
    (an (Rt, nt, 1) input block spec forced XLA-side layout transposes).
    A caller-supplied cnt_base that differs from the exclusive prefix of
    per-tile popcounts of ``combo & 1`` is silently dropped here while
    the other two paths would honor it."""
    R, C = doc_predel.shape
    nt = C // LANE
    if nt % 8 and not interpret:
        # Unaligned sublane tile counts send Mosaic compilation into
        # minutes (measured 243s at nt=1425 vs ~1s aligned).  Pad the
        # capacity axis to the next 8-tile boundary and slice after —
        # padded doc positions are beyond-length (2), padded combo/base
        # carry no inserts.
        Cp = _round_up_c(C, 8 * LANE)
        pad = Cp - C
        doc_p = jnp.concatenate(
            [doc_predel, jnp.full((R, pad), 2, jnp.int32)], axis=1
        )
        combo_p = jnp.concatenate(
            [combo, jnp.zeros((R, pad), jnp.int32)], axis=1
        )
        base_p = jnp.concatenate(
            [cnt_base,
             jnp.broadcast_to(cnt_base[:, -1:], (R, pad // LANE))],
            axis=1,
        )
        out = apply_fused2(
            doc_p, combo_p, base_p, new_len, nbits=nbits,
            replica_tile=replica_tile, interpret=interpret,
            emit_cv=emit_cv,
        )
        if not emit_cv:
            return out[:, :C]
        d, cv, vt = out
        return d[:, :C], cv[:, :C], vt[:, :nt]
    # ~6 live (nt, LANE) i32/f32 arrays + roll temps; the r4 estimate of
    # 40 B/pos compiled to a 100.16M stack at Rt=64, C=32k (observed on
    # the r5 upstream matrix — 164K over the 100M limit), so size against
    # the measured ~49 B/pos with an 88M budget
    per_replica = 49 * C
    Rt = replica_tile
    if Rt <= 0:
        Rt = max(1, (88 * 2**20) // per_replica)
    Rt = min(Rt, R)
    while R % Rt:
        Rt -= 1
    big = pl.BlockSpec(
        (Rt, nt, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    small = pl.BlockSpec(
        (Rt, nt, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    one = pl.BlockSpec(
        (Rt, 1, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _apply_fused2_kernel, nt=nt, nbits=nbits, Rt=Rt, emit_cv=emit_cv
    )
    r3 = lambda x: x.reshape(R, nt, LANE)
    out = pl.pallas_call(
        kernel,
        grid=(R // Rt,),
        in_specs=[big, big, one],
        out_specs=[big, big, small] if emit_cv else [big],
        out_shape=(
            [
                jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
                jax.ShapeDtypeStruct((R, nt, LANE), jnp.bfloat16),
                jax.ShapeDtypeStruct((R, nt, 1), jnp.int32),
            ]
            if emit_cv
            else [jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32)]
        ),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2**20
        ),
        interpret=interpret,
    )(
        r3(doc_predel), r3(combo),
        new_len.reshape(R, 1, 1).astype(jnp.int32),
    )
    if not emit_cv:
        return out[0].reshape(R, C)
    doc_o, cv, vt = out
    return doc_o.reshape(R, C), cv.reshape(R, C), vt.reshape(R, nt)


def _range_fused_kernel(doc_ref, delpk_ref, ind_ref, dd_ref,
                        newlen_ref, doc_out, cv_ref, vistot_ref,
                        *, nt: int, nbits: int, Rt: int, dsh: int = 14,
                        dlvl: int = 3):
    """One-batch range application with all capacity-wide work in VMEM.

    Inputs (per grid step, (Rt, nt, LANE) int32 unless noted):
    - doc: packed pre-batch doc ((slot+2)<<1 | vis)
    - delpk: packed delete-interval boundary counts — starts in bits
      0..dsh-1, one-past-end stops in bits dsh..2*dsh-1 (several ops'
      intervals may share a boundary, so per-cell counts reach B and get
      the same chunked treatment as ddp/ddn below).  ``dsh`` is chosen by
      the producer (_del_stop_shift) so the f32 spread accumulation
      B*2^dsh + B stays <= 2^24 exact.
    - ind: insert-run boundary deltas (+1 at dest0, -1 at dstop)
    - dd: signed slot-delta differences painted at run starts (prefix =
      the containing run's slot0 + tch - dest0).  |element| < 2^21, so
      the kernel sign-splits and re-chunks to 3x7 bits before the
      triangular matmuls: the MXU truncates dot operands to bf16 and
      accumulates in tree order, which is only exact when every term
      (and hence any partial sum up to 128 terms) stays small — the same
      bound the unfused path's chunked spread relied on.
    - newlen (Rt, 1, 1): post-batch used length
    Outputs: new doc, cv_intile (bf16), vis_tile — the maintained
    visibility prefix structure for the next batch's rank queries.
    """
    lane = jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 2)
    col = (
        jax.lax.broadcasted_iota(jnp.int32, (Rt, nt, LANE), 1) * LANE + lane
    )
    li = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 0)
    lj = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
    tri = (li <= lj).astype(jnp.float32)

    # ---- deletes: nesting depth > 0 -> clear visible bit ----
    delpk = delpk_ref[:]
    depth_w = jnp.zeros((Rt, nt, LANE), jnp.int32)
    for lo_bit, sign in ((0, 1), (dsh, -1)):
        v = jnp.bitwise_and(jnp.right_shift(delpk, lo_bit), (1 << dsh) - 1)
        for k in range(2):
            chunk = jnp.bitwise_and(jnp.right_shift(v, 7 * k), 127)
            depth_w = depth_w + sign * jnp.left_shift(
                _tile_cumsum(chunk, tri), 7 * k
            )
    depth = depth_w + _tile_scan_excl(depth_w[:, :, LANE - 1 :])
    doc = doc_ref[:]
    vis = jnp.bitwise_and(doc, 1)
    doc = doc - (vis & (depth > 0).astype(jnp.int32))

    # ---- insert destinations: run indicator and expansion shift map ----
    run_ind = (
        _flat_cumsum_f32(ind_ref[:], tri) > 0
    ).astype(jnp.int32)
    cnt = _flat_cumsum_f32(run_ind, tri)

    # ---- expansion y[d] = x[d - cnt[d]] (cnt monotone, 1-Lipschitz) ----
    maxcnt = jnp.max(cnt[:, :, LANE - 1 :])
    doc_out[:] = doc
    for b in reversed(range(nbits)):
        step = 1 << b

        @pl.when(maxcnt >= step)
        def _():
            d = doc_out[:]
            take = (jnp.bitwise_and(cnt, step) != 0) & (col >= step)
            doc_out[:] = jnp.where(take, _flat_roll(d, step), d)

    # ---- fill: slot(d) = d + delta(run of d), vis = 1 ----
    # 7-bit-chunked within-tile cumsums (exact under bf16 MXU operands),
    # one shared cross-tile scan on the recombined tile totals.  The dd
    # input arrives as one signed dense array (each cell holds a single
    # token's ddelta, so the in-kernel sign split recovers the
    # non-negative halves exactly).
    # dlvl 7-bit levels (3 below 2^20 capacity; ddelta_levels(C) above).
    # int32 exactness of the shifted level accumulation: per sign side
    # the running partial equals a prefix of cumsum(|dd|) <= 128 * 2C,
    # so everything fits int32 through C = 2^22 (the engine guard).
    dd = dd_ref[:]
    dcum_w = jnp.zeros((Rt, nt, LANE), jnp.int32)
    for v, sign in (
        (jnp.maximum(dd, 0), 1),
        (jnp.maximum(-dd, 0), -1),
    ):
        for k in range(dlvl):
            chunk = jnp.bitwise_and(jnp.right_shift(v, 7 * k), 127)
            dcum_w = dcum_w + sign * jnp.left_shift(
                _tile_cumsum(chunk, tri), 7 * k
            )
    dcum = dcum_w + _tile_scan_excl(dcum_w[:, :, LANE - 1 :])
    fill = jnp.left_shift(col + dcum + 2, 1) | 1
    doc_out[:] = jnp.where(run_ind != 0, fill, doc_out[:])
    doc_out[:] = jnp.where(col >= newlen_ref[:], 2, doc_out[:])

    # ---- next batch's visibility prefix structure ----
    cv_in = _tile_cumsum(jnp.bitwise_and(doc_out[:], 1), tri)
    cv_ref[:] = cv_in.astype(jnp.bfloat16)
    vistot_ref[:] = cv_in[:, :, LANE - 1 :]


def _del_stop_shift(B: int) -> int:
    """Static bit position of the stop-count field in the packed
    delete-boundary spread.  The spread's f32 einsum accumulates up to B
    stops (weight 2^dsh) plus B starts (weight 1) into one cell; integer
    exactness needs B*2^dsh + B <= 2^24, while the field itself must hold
    counts up to B (2^dsh > B).  dsh=14 preserves the historical packing
    for every B <= 1024; above that the field narrows to bit_length(B),
    which satisfies both bounds through B = 4095 exactly (4095 * 4097 =
    2^24 - 1); B = 4096 is the first failure (ADVICE r4)."""
    if B <= 1024:
        return 14
    sh = B.bit_length()
    if B * ((1 << sh) + 1) > 1 << 24:
        raise ValueError(
            f"delete-boundary spread not f32-exact at batch {B}: "
            f"{B} * (2^{sh} + 1) > 2^24; cap the op batch at 4095 or "
            "split the start/stop spreads into separate value arrays"
        )
    return sh


@functools.partial(
    jax.jit,
    static_argnames=("nbits", "replica_tile", "interpret", "dsh", "dlvl"),
)
def range_fused(doc, delpk, ind_d, dd, new_len, *, nbits: int,
                replica_tile: int = 0, interpret: bool = False,
                dsh: int = 14, dlvl: int = 3):
    """Run the fused range kernel.  All dense args int32[R, C] (C a
    multiple of 128); new_len int32[R].  Returns (doc', cv_intile bf16,
    vis_tile).  ``dsh`` must match the producer's _del_stop_shift(B)."""
    R, C = doc.shape
    nt = C // LANE
    if not (interpret or range_fused_fits(C)):
        # interpret mode ignores VMEM budgets, so only the real Mosaic
        # path enforces the gate.
        raise NotImplementedError(
            "range_fused: capacity beyond the VMEM gate; use the XLA path"
        )
    Rt = replica_tile
    if Rt <= 0:
        Rt = max(1, (96 * 2**20) // (RANGE_FUSED_BYTES_PER_POS * C))
    Rt = min(Rt, R)
    while R % Rt:
        Rt -= 1
    big = pl.BlockSpec(
        (Rt, nt, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    small = pl.BlockSpec(
        (Rt, nt, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    one = pl.BlockSpec(
        (Rt, 1, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _range_fused_kernel, nt=nt, nbits=nbits, Rt=Rt, dsh=dsh, dlvl=dlvl
    )
    r3 = lambda x: x.reshape(R, nt, LANE)
    doc_o, cv, vt = pl.pallas_call(
        kernel,
        grid=(R // Rt,),
        in_specs=[big, big, big, big, one],
        out_specs=[big, big, small],
        out_shape=[
            jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
            jax.ShapeDtypeStruct((R, nt, LANE), jnp.bfloat16),
            jax.ShapeDtypeStruct((R, nt, 1), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2**20
        ),
        interpret=interpret,
    )(
        r3(doc), r3(delpk), r3(ind_d), r3(dd),
        new_len.reshape(R, 1, 1).astype(jnp.int32),
    )
    return doc_o.reshape(R, C), cv.reshape(R, C), vt.reshape(R, nt)


def range_fused_xla(doc, delpk, ind_d, dd, new_len, *, nbits: int,
                    dsh: int = 14, dlvl: int = 3):
    # (dlvl accepted for signature parity; the XLA twin's plain int32
    # cumsum needs no chunking)
    """XLA fallback with identical semantics (CPU tests, oversized
    capacities)."""
    R, C = doc.shape
    nt = C // LANE
    col = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)
    deld = jnp.bitwise_and(delpk, (1 << dsh) - 1) - jnp.right_shift(
        delpk, dsh
    )
    depth = jnp.cumsum(deld, axis=1)
    vis = jnp.bitwise_and(doc, 1)
    doc = doc - (vis & (depth > 0).astype(jnp.int32))
    run_ind = (jnp.cumsum(ind_d, axis=1) > 0).astype(jnp.int32)
    cnt = jnp.cumsum(run_ind, axis=1)
    out = doc
    for b in reversed(range(nbits)):
        step = 1 << b
        take = (jnp.bitwise_and(cnt, step) != 0) & (col >= step)
        out = jnp.where(take, jnp.roll(out, step, axis=1), out)
    dcum = jnp.cumsum(dd, axis=1)
    fill = jnp.left_shift(col + dcum + 2, 1) | 1
    out = jnp.where(run_ind != 0, fill, out)
    out = jnp.where(col >= new_len[:, None], 2, out)
    cv = jnp.cumsum(
        jnp.bitwise_and(out, 1).reshape(R, nt, LANE), axis=2
    )
    return (
        out,
        cv.reshape(R, C).astype(jnp.bfloat16),
        cv[:, :, LANE - 1],
    )


#: Measured Mosaic scoped-stack bytes per WINDOW TILE for
#: _range_blocked_kernel (~24 live (1, window, LANE) i32 buffers: the
#: halo-concatenated views, their cumsums, roll temps and two scratches;
#: the 8208-tile window compiled to a 101.78M stack).
RANGE_BLOCKED_BYTES_PER_TILE = 24 * LANE * 4
_RANGE_BLOCKED_VMEM = 112 * 2**20  # v5e VMEM is 128M; leave headroom


def _blocked_window(nbits: int, block_tiles: int) -> tuple[int, int]:
    """(block, halo) tile counts: halo = the expansion's max leftward
    reach (2**nbits positions) tile-rounded to 8; the block auto-grows to
    at least the halo (big per-batch insert volumes would otherwise
    exceed any fixed block)."""
    pt = -(-(-(-(1 << nbits) // LANE) + 1) // 8) * 8
    return max(block_tiles, pt), pt


def range_blocked_fits(nbits: int, block_tiles: int = 1024) -> bool:
    """Whether the halo-blocked range kernel's window fits the VMEM
    stack at this per-batch insert bound — the ONE gate shared by the
    dispatcher and range_fused_blocked itself."""
    bt, pt = _blocked_window(nbits, block_tiles)
    return RANGE_BLOCKED_BYTES_PER_TILE * (bt + pt) <= _RANGE_BLOCKED_VMEM


def _range_blocked_kernel(
    doc_ref, docp_ref, delpk_ref, delpkp_ref, ind_ref, indp_ref,
    dd_ref, ddp_ref,
    dbase_ref, dbasep_ref, ibase_ref, ibasep_ref,
    cbase_ref, cbasep_ref, ddbase_ref, ddbasep_ref,
    newlen_ref, doc_out, cv_ref, vistot_ref,
    work_scr, cnt_scr,
    *, bt: int, pt: int, nbits: int, dsh: int,
):
    """Halo-blocked twin of _range_fused_kernel for capacities beyond the
    monolithic VMEM gate: grid (R, nt/bt), left halo of ``pt`` tiles (the
    expansion's 1-Lipschitz leftward window, same argument as
    expand_pallas._apply_fused_blocked_kernel).

    Every global prefix (delete depth, insert-run indicator, hole count,
    slot-delta cumsum) arrives as PER-TILE exclusive bases precomputed
    XLA-side (2-3 capacity-wide elementwise+reduce passes), so in-kernel
    work is pure int32 lane cumsums + base adds — no cross-tile scan, no
    bf16 chunk levels, exact to the int32 range (the monolithic kernel's
    C <= 2^22 level-accumulation bound does not apply here)."""
    j = pl.program_id(1)
    ext = pt + bt
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, ext, LANE), 2)
    gcol = (
        (
            jax.lax.broadcasted_iota(jnp.int32, (1, ext, LANE), 1)
            + j * bt - pt
        ) * LANE
        + lane
    )

    def lanecum(x):  # inclusive within-tile lane cumsum, int32 rolls
        ln = jax.lax.broadcasted_iota(jnp.int32, x.shape, 2)
        c = x
        for b in range(7):
            s = 1 << b
            c = c + jnp.where(ln >= s, _roll_ax(c, s, 2), 0)
        return c

    def win(ref, refp):  # halo window: last pt tiles of block j-1 + block
        return jnp.concatenate([refp[:, bt - pt :, :], ref[:]], axis=1)

    # ---- deletes over the whole window (rolled-in halo values must be
    # post-delete) ----
    delpk = win(delpk_ref, delpkp_ref)
    deld = jnp.bitwise_and(delpk, (1 << dsh) - 1) - jnp.right_shift(
        delpk, dsh
    )
    depth = lanecum(deld) + win(dbase_ref, dbasep_ref)
    doc = win(doc_ref, docp_ref)
    vis = jnp.bitwise_and(doc, 1)
    work_scr[:] = doc - (vis & (depth > 0).astype(jnp.int32))

    # ---- hole map: run indicator from the global ind_d prefix, hole
    # count from its own global base ----
    ind = win(ind_ref, indp_ref)
    run_ind = (
        lanecum(ind) + win(ibase_ref, ibasep_ref) > 0
    ).astype(jnp.int32)
    cnt_scr[:] = lanecum(run_ind) + win(cbase_ref, cbasep_ref)
    maxcnt = jnp.max(cnt_scr[:, pt:, LANE - 1 :])

    for b in reversed(range(nbits)):
        step = 1 << b

        @pl.when(maxcnt >= step)
        def _():
            w = work_scr[:]
            take = (jnp.bitwise_and(cnt_scr[:], step) != 0) & (
                gcol >= step
            )
            work_scr[:] = jnp.where(take, _flat_roll(w, step), w)

    # ---- fill: slot(d) = d + global dd prefix ----
    dcum = lanecum(win(dd_ref, ddp_ref)) + win(ddbase_ref, ddbasep_ref)
    fill = jnp.left_shift(gcol + dcum + 2, 1) | 1
    out = jnp.where(run_ind != 0, fill, work_scr[:])
    out = jnp.where(gcol >= newlen_ref[:], 2, out)
    doc_out[:] = out[:, pt:, :]
    cv_in = lanecum(jnp.bitwise_and(out[:, pt:, :], 1))
    cv_ref[:] = cv_in.astype(jnp.bfloat16)
    vistot_ref[:] = cv_in[:, :, LANE - 1 :]


@functools.partial(
    jax.jit,
    static_argnames=("nbits", "dsh", "block_tiles", "interpret"),
)
def range_fused_blocked(doc, delpk, ind_d, dd, new_len, *, nbits: int,
                        dsh: int = 14, block_tiles: int = 1024,
                        interpret: bool = False):
    """range_fused for capacities beyond the monolithic VMEM gate: same
    contract ((doc', cv_intile bf16, vis_tile)), blocked along C with a
    left halo of ceil(2**nbits / 128) + 1 tiles.  VMEM per grid step is
    RANGE_BLOCKED_BYTES_PER_TILE * (block + halo) — measured ~24 live
    (1, window, LANE) i32 buffers, i.e. ~12.3KB per window tile —
    independent of C."""
    R, C = doc.shape
    nt = C // LANE
    # halo = the expansion's max leftward reach (2**nbits positions),
    # tile-rounded to 8; the block auto-grows to at least the halo (big
    # per-batch insert volumes would otherwise exceed any fixed block —
    # VMEM per step stays ~7 * 2 * pt tiles, bounded by the same batch
    # volume that sized nbits)
    bt, pt = _blocked_window(nbits, block_tiles)
    pad_t = (-nt) % bt
    if pad_t and pad_t > nt // 4 and bt > max(8, pt):
        while bt > max(8, pt) and (-nt) % bt > nt // 4:
            bt //= 2
        bt = max(bt, pt)
        pad_t = (-nt) % bt
    if pad_t:
        padc = pad_t * LANE
        doc = jnp.concatenate(
            [doc, jnp.full((R, padc), 2, jnp.int32)], axis=1
        )
        z = jnp.zeros((R, padc), jnp.int32)
        delpk = jnp.concatenate([delpk, z], axis=1)
        ind_d = jnp.concatenate([ind_d, z], axis=1)
        dd = jnp.concatenate([dd, z], axis=1)
        nt += pad_t
    if not range_blocked_fits(nbits, block_tiles):
        raise ValueError(
            f"blocked range kernel window {bt + pt} tiles exceeds VMEM;"
            " lower the per-batch insert volume (nbits) or use"
            " range_fused_xla"
        )
    nblk = nt // bt
    r3 = lambda x: x.reshape(R, nt, LANE)

    # ---- XLA-side per-tile exclusive prefix bases (the blocked tier's
    # analog of the unit path's cnt_base): 2 capacity-wide elementwise
    # passes + tile reductions, all int32-exact ----
    deld = jnp.bitwise_and(delpk, (1 << dsh) - 1) - jnp.right_shift(
        delpk, dsh
    )
    excl = lambda t: jnp.cumsum(t, axis=1) - t
    dtile = jnp.sum(r3(deld), axis=2)
    dbase = excl(dtile)
    ind3 = r3(ind_d)
    itile = jnp.sum(ind3, axis=2)
    ibase = excl(itile)
    # hole counts need the within-tile detail: one in-tile cumsum pass
    holes = (
        jnp.cumsum(ind3, axis=2) + ibase[:, :, None] > 0
    ).astype(jnp.int32)
    cbase = excl(jnp.sum(holes, axis=2))
    ddbase = excl(jnp.sum(r3(dd), axis=2))

    blk = pl.BlockSpec(
        (1, bt, LANE), lambda r, j: (r, j, 0), memory_space=pltpu.VMEM
    )
    blkp = pl.BlockSpec(
        (1, bt, LANE),
        lambda r, j: (r, jnp.maximum(j - 1, 0), 0),
        memory_space=pltpu.VMEM,
    )
    row = pl.BlockSpec(
        (1, bt, 1), lambda r, j: (r, j, 0), memory_space=pltpu.VMEM
    )
    rowp = pl.BlockSpec(
        (1, bt, 1),
        lambda r, j: (r, jnp.maximum(j - 1, 0), 0),
        memory_space=pltpu.VMEM,
    )
    one = pl.BlockSpec(
        (1, 1, 1), lambda r, j: (r, 0, 0), memory_space=pltpu.VMEM
    )
    srow = pl.BlockSpec(
        (1, bt, 1), lambda r, j: (r, j, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _range_blocked_kernel, bt=bt, pt=pt, nbits=nbits, dsh=dsh
    )
    b3 = lambda x: x.reshape(R, nt, 1)
    doc_o, cv, vt = pl.pallas_call(
        kernel,
        grid=(R, nblk),
        in_specs=[
            blk, blkp, blk, blkp, blk, blkp, blk, blkp,
            row, rowp, row, rowp, row, rowp, row, rowp,
            one,
        ],
        out_specs=[blk, blk, srow],
        out_shape=[
            jax.ShapeDtypeStruct((R, nt, LANE), jnp.int32),
            jax.ShapeDtypeStruct((R, nt, LANE), jnp.bfloat16),
            jax.ShapeDtypeStruct((R, nt, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, bt + pt, LANE), jnp.int32),
            pltpu.VMEM((1, bt + pt, LANE), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_RANGE_BLOCKED_VMEM + 8 * 2**20
        ),
        interpret=interpret,
    )(
        r3(doc), r3(doc), r3(delpk), r3(delpk), r3(ind_d), r3(ind_d),
        r3(dd), r3(dd),
        b3(dbase), b3(dbase), b3(ibase), b3(ibase),
        b3(cbase), b3(cbase), b3(ddbase), b3(ddbase),
        new_len.reshape(R, 1, 1).astype(jnp.int32),
    )
    doc_o = doc_o.reshape(R, nt * LANE)
    cv = cv.reshape(R, nt * LANE)
    vt = vt.reshape(R, nt)
    if nt * LANE != C:
        doc_o, cv, vt = doc_o[:, :C], cv[:, :C], vt[:, : C // LANE]
    return doc_o, cv, vt


def apply_range_batch4(
    state: PackedState4,
    tokens,  # (ttype, ta, tch, tlen) int32[R, T]; TINS ta = slot0
    dints,  # (dlo, dhi, dcount) int32[R, B]
    nbits: int,
    interpret: bool = False,
) -> PackedState4:
    """apply_range_batch on the maintained-cv state with the fused
    kernel: XLA touches only B/T-sized arrays plus two merged one-hot
    spread calls; every capacity-wide pass runs in range_fused."""
    ttype, ta, tch, tlen = tokens
    dlo, dhi, dcount = dints
    R, C = state.doc.shape
    B = dlo.shape[1]
    drop = jnp.int32(C + 7)

    tile_base = _excl_cumsum_small(state.vis_tile)
    tmax_abs = tile_base + state.vis_tile

    has_del = dlo >= 0
    live, gvis, cumlen = extract_range_tokens(
        ttype, ta, tch, tlen, v0=state.nvis
    )
    allq = count_le_two_level(
        state.cv_intile, tile_base, tmax_abs,
        jnp.concatenate(
            [
                jnp.where(has_del, dlo, 0),
                jnp.where(has_del, dhi, 0),
                jnp.where(live, gvis, 0),
            ],
            axis=1,
        ),
    )
    lo_phys = allq[:, :B]
    hi_phys = allq[:, B : 2 * B]
    gq_phys = allq[:, 2 * B :]

    at_end = gvis >= state.nvis[:, None]
    g_phys = jnp.where(at_end, state.length[:, None], gq_phys)
    dest0 = jnp.where(live, g_phys + cumlen, drop)
    dstop = jnp.where(live, dest0 + tlen, drop)

    # ---- spreads: ONE einsum -> ONE dense output each (XLA trace, r4:
    # the one-hot fuses into the convolution and never materializes, so
    # the cost is dense (R, C) writes and combine passes — every extra
    # chunk einsum or shift-add combine is a full HBM traversal).
    # Exactness: each operand value is bf16-exact (small ints, and
    # 7-bit chunks SHIFTED by 2^7k keep the same mantissa), collisions
    # accumulate in f32 (exact below 2^24).
    #
    # delete boundaries: starts count in bits 0..dsh-1, one-past-end
    # stops in bits dsh..2*dsh-1 of one dense array (vals 1 and 2^dsh).
    # _del_stop_shift picks dsh so a cell holding up to B stops plus B
    # starts stays <= 2^24 (f32-exact) — B > 1024 narrows the field
    # instead of paying a second dense spread output (ADVICE r4).
    dsh = _del_stop_shift(B)
    idxA = jnp.concatenate(
        [jnp.where(has_del, lo_phys, drop),
         jnp.where(has_del, hi_phys + 1, drop)], axis=1
    )
    pm = has_del.astype(jnp.int32)
    (delpk,) = _mxu_spread(
        idxA,
        [jnp.concatenate([pm, pm * (1 << dsh)], axis=1)],
        C, cb=4096,
    )

    # insert-run boundary deltas: +1 at dest0, -1 at dstop.
    lv = live.astype(jnp.int32)
    (ind_d,) = _mxu_spread(
        jnp.concatenate([dest0, dstop], axis=1),
        [jnp.concatenate([lv, -lv], axis=1)],
        C, cb=4096,
    )

    # delta(run) = slot0[ta] + tch - dest0, painted as differences at
    # run starts (token order == dest order: gaps and cumlen are both
    # monotone along the token axis).  The signed 7-bit chunk levels
    # (ddelta_levels(C) of them — 3 below 2^20 capacity, adaptive above;
    # round-5 widening) ride ONE einsum as index copies with shifted
    # values.  TINS tokens carry slot0 directly in ``ta`` (the range
    # resolver bakes it in — a take() here serialized per row,
    # ~3.5ms/batch).
    dlv = ddelta_levels(C)
    delta = jnp.where(live, ta + tch - dest0, 0)
    ddelta = jnp.where(live, delta - _prev_value(delta, live), 0)
    sgn = jnp.where(ddelta < 0, -1, 1)
    mag = jnp.abs(ddelta)
    lvl = lambda k: sgn * jnp.left_shift(
        jnp.bitwise_and(jnp.right_shift(mag, 7 * k), 127), 7 * k
    )
    (dd,) = _mxu_spread(
        jnp.concatenate([dest0] * dlv, axis=1),
        [jnp.concatenate([lvl(k) for k in range(dlv)], axis=1)],
        C, cb=4096,
    )

    n_ins = jnp.sum(jnp.where(live, tlen, 0), axis=1)
    n_del = jnp.sum(jnp.where(has_del, dcount, 0), axis=1)
    length2 = state.length + n_ins

    if interpret or (
        jax.default_backend() == "tpu" and range_fused_fits(C)
    ):
        doc, cv, vt = range_fused(
            state.doc, delpk, ind_d, dd, length2, nbits=nbits, dsh=dsh,
            dlvl=dlv, interpret=interpret,
        )
    elif jax.default_backend() == "tpu" and range_blocked_fits(nbits):
        # beyond the monolithic VMEM gate: the halo-blocked twin (per-
        # tile prefix bases XLA-side, windowed kernel) keeps the fused
        # path alive to arbitrary capacities (round-5, VERDICT r4 #5)
        doc, cv, vt = range_fused_blocked(
            state.doc, delpk, ind_d, dd, length2, nbits=nbits, dsh=dsh
        )
    else:
        doc, cv, vt = range_fused_xla(
            state.doc, delpk, ind_d, dd, length2, nbits=nbits, dsh=dsh,
            dlvl=dlv,
        )
    return PackedState4(
        doc=doc,
        cv_intile=cv,
        vis_tile=vt,
        length=length2,
        nvis=state.nvis + n_ins - n_del,
    )
