"""Fused TPU resolver for RANGE ops: insert-runs and delete-ranges.

Same architecture as ops/resolve_pallas.py (whole op batch in one Pallas
kernel, token list in VMEM, cum-primary representation) but one op covers a
run of chars, so resolver work scales with PATCHES instead of chars — the
per-char explosion costs up to ~24x on block-edit traces (SURVEY.md §6).

Token list: (ttype, ta, tch, cum) per token.
- RUN(a):    surviving pre-batch chars with ranks a .. a+len-1
- TINS(j, c): chars c .. c+len-1 of batch op j's inserted run (len > 0 means
  surviving; zero-length means fully deleted within the batch — such chars
  simply never materialize, no tombstone is needed for the upstream replay)
- FREE: unused slot (cum stays flat)

An INSERT(p, L) replaces the token containing p by up to 3 tokens (left
piece, the new TINS run, right piece) exactly like the unit kernel but with
lengths.  A DELETE(p, D) is *mostly a vector pass*: every token's cum is
clamped by ``min(cum, p) + max(0, cum - p - D)`` and boundary starts advance
by their consumed prefix; only a delete strictly inside one token needs a
real split (left keep + right keep, one extra token).  Per delete op the
kernel emits the covered surviving pre-batch chars as ONE rank interval
[drank_lo, drank_hi] plus their count — correct because ranks inside the
interval that are *not* covered were deleted earlier in the same batch and
are already invisible, so the apply can clear the whole physical interval.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from .pallas_compat import pltpu  # CompilerParams shim for jax 0.4

from ..traces.tensorize import DELETE, INSERT
from .resolve import FREE, RUN, TINS

_BIG = np.int32(1 << 30)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def effective_token_list_size(B: int, token_cap: int | None) -> int:
    """The kernel's actual VMEM token-list size T for a batch of B ops
    under ``token_cap`` — the ONE formula shared with overflow-checking
    callers (engine/replay_range.py), so the nused <= T guard can never
    drift from the kernel's real sizing."""
    return _round_up(min(2 * B + 2, token_cap) if token_cap else 2 * B + 2,
                     128)


def _roll1(x):
    return jnp.concatenate([x[:, -1:], x[:, :-1]], axis=1)


def _kernel(kind_ref, pos_ref, rlen_ref, slot0_ref, v0_ref,
            dlo_ref, dhi_ref, dn_ref,
            ttype_ref, ta_ref, tch_ref, tlen_ref, nused_ref,
            *, B: int, T: int, Rt: int):
    lane_t = jax.lax.broadcasted_iota(jnp.int32, (Rt, T), 1)
    lane_b = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    kind_v = kind_ref[:]
    pos_v = pos_ref[:]
    rlen_v = rlen_ref[:]
    slot0_v = slot0_ref[:]
    v0 = v0_ref[:]  # (Rt, 1)

    dlo_ref[:] = jnp.full((Rt, B), -1, jnp.int32)
    dhi_ref[:] = jnp.full((Rt, B), -1, jnp.int32)
    dn_ref[:] = jnp.zeros((Rt, B), jnp.int32)

    # ttype (2 bits) and ta travel PACKED as tta = ta*4 + ttype — one
    # place() pass instead of two and one masked-sum lookup instead of
    # two (the unit kernel's packing, lifted here; ta < 2^20 ranks keep
    # the pack well inside int32).
    tta0 = jnp.where(lane_t == 0, RUN, FREE)  # ta = 0 everywhere
    tch0 = jnp.zeros((Rt, T), jnp.int32)
    cum0 = jnp.broadcast_to(v0, (Rt, T))
    total0 = v0
    nused0 = jnp.ones((Rt, 1), jnp.int32)

    def body(j, carry):
        tta, tch, cum, total, nused = carry
        jj = jnp.int32(j)
        opm = (lane_b == jj).astype(jnp.int32)
        k = jnp.sum(kind_v * opm, axis=1, keepdims=True)
        p0 = jnp.sum(pos_v * opm, axis=1, keepdims=True)
        L0 = jnp.sum(rlen_v * opm, axis=1, keepdims=True)
        s0 = jnp.sum(slot0_v * opm, axis=1, keepdims=True)

        is_ins = (k == INSERT) & (L0 > 0)
        p = jnp.clip(p0, 0, total)
        D = jnp.where(k == DELETE, jnp.clip(L0, 0, total - p), 0)
        is_del = (k == DELETE) & (D > 0)
        L = jnp.where(is_ins, L0, 0)

        pre_all = jnp.where(lane_t == 0, 0, _roll1(cum))
        is_run_tok = jnp.bitwise_and(tta, 3) == RUN

        # ---- delete rank-interval outputs (from pre-clamp state) ----
        pD = p + D
        ov_lo = jnp.maximum(pre_all, p)
        ov_hi = jnp.minimum(cum, pD)
        has_ov = is_del & is_run_tok & (ov_hi > ov_lo)
        ta_all = jnp.right_shift(tta, 2)
        r_lo = ta_all + (ov_lo - pre_all)
        r_hi = ta_all + (ov_hi - pre_all) - 1
        dlo = jnp.min(jnp.where(has_ov, r_lo, _BIG), axis=1, keepdims=True)
        dhi = jnp.max(jnp.where(has_ov, r_hi, -1), axis=1, keepdims=True)
        dcount = jnp.sum(
            jnp.where(has_ov, ov_hi - ov_lo, 0), axis=1, keepdims=True
        )
        dlo = jnp.where(dlo >= _BIG, -1, dlo)

        # ---- vector clamp (delete effect on every token) ----
        consumed = jnp.maximum(
            0, jnp.minimum(cum, pD) - jnp.maximum(pre_all, p)
        )
        adv = jnp.where(is_del & (cum > pD), consumed, 0)
        cum_c = jnp.where(
            is_del, jnp.minimum(cum, p) + jnp.maximum(0, cum - pD), cum
        )
        tta_c = tta + jnp.where(is_run_tok, adv * 4, 0)
        tch_c = tch + jnp.where(
            jnp.bitwise_and(tta, 3) == TINS, adv, 0
        )

        # ---- locate token containing p (pre-clamp coordinates) ----
        t = jnp.sum((cum <= p).astype(jnp.int32), axis=1, keepdims=True)
        t = jnp.minimum(t, nused)
        m_t = lane_t == t
        c_t = jnp.sum(jnp.where(m_t, cum, 0), axis=1, keepdims=True)
        pre = jnp.sum(jnp.where(m_t, pre_all, 0), axis=1, keepdims=True)
        tta_t = jnp.sum(jnp.where(m_t, tta, 0), axis=1, keepdims=True)
        ch = jnp.sum(jnp.where(m_t, tch, 0), axis=1, keepdims=True)
        a = jnp.right_shift(tta_t, 2)
        tt = jnp.bitwise_and(tta_t, 3)
        off = p - pre
        is_run_t = tt == RUN

        split_ins = is_ins & (off > 0)
        split_del = is_del & (off > 0) & (pD < c_t)
        m = jnp.where(
            is_ins,
            jnp.where(split_ins, 3, 2),
            jnp.where(split_del, 2, 1),
        )

        # Replacement pieces.  For inserts: [left?, TINS(j,0,L), right].
        # For an inside-delete: [left-keep, right-keep].  m == 1 writes the
        # token's CLAMPED values back (identity for inserts/PAD; the
        # delete's boundary adjustment for spanning deletes).  The clamped
        # values AT t are derived by scalar arithmetic from the already-
        # fetched (c_t, pre, tta_t, ch) — three fewer (Rt, T) reductions
        # per op than re-reducing the clamped arrays.
        c_t_clamped = jnp.where(
            is_del,
            jnp.minimum(c_t, p) + jnp.maximum(0, c_t - pD),
            c_t,
        )
        adv_t = jnp.where(
            is_del & (c_t > pD),
            jnp.maximum(0, jnp.minimum(c_t, pD) - jnp.maximum(pre, p)),
            0,
        )
        tta_cl = tta_t + jnp.where(is_run_t, adv_t * 4, 0)
        ch_cl = ch + jnp.where(tt == TINS, adv_t, 0)
        tta_right_del = tta_t + jnp.where(is_run_t, (pD - pre) * 4, 0)
        ch_right_del = jnp.where(is_run_t, ch, ch + (pD - pre))
        tta_right_ins = tta_t + jnp.where(is_run_t, off * 4, 0)
        ch_right_ins = jnp.where(is_run_t, ch, ch + off)
        # TINS tokens carry the op's FIRST SLOT ID (not the op index):
        # the apply's fill needs slot0 + tch per token, and baking slot0
        # in here removes a serializing (R, T) gather from the XLA side
        # (~3.5ms/batch at R=1024; slot ids < capacity < 2^20 share the
        # op-index packing range).
        jj_tins = s0 * 4 + TINS

        n0ta = jnp.where(
            is_ins & ~split_ins, jj_tins,
            jnp.where(split_del, tta_t, tta_cl),
        )
        n0c_ = jnp.where(
            is_ins & ~split_ins, 0, jnp.where(split_del, ch, ch_cl)
        )
        n0cum = jnp.where(
            is_ins,
            jnp.where(split_ins, p, pre + L),
            jnp.where(split_del, p, c_t_clamped),
        )

        n1ta = jnp.where(
            is_ins, jnp.where(split_ins, jj_tins, tta_t), tta_right_del
        )
        n1c_ = jnp.where(
            is_ins, jnp.where(split_ins, 0, ch), ch_right_del
        )
        n1cum = jnp.where(
            is_ins, jnp.where(split_ins, p + L, c_t + L), c_t - D
        )

        n2ta, n2c_, n2cum = tta_right_ins, ch_right_ins, c_t + L

        m2 = m >= 2
        m3 = m == 3
        delta = L  # tail cum shift beyond the placed pieces (deletes: 0,
        #            their tail effect is already in the clamp)

        def place(x, x0, x1, x2, dlt):
            r1, r2 = _roll1(x), _roll1(_roll1(x))
            sh = jnp.where(m == 1, x, jnp.where(m == 2, r1, r2)) + dlt
            out = jnp.where(lane_t < t, x, sh)
            out = jnp.where(lane_t == t, x0, out)
            out = jnp.where(m2 & (lane_t == t + 1), x1, out)
            out = jnp.where(m3 & (lane_t == t + 2), x2, out)
            return out

        tta_n = place(tta_c, n0ta, n1ta, n2ta, 0)
        tch_n = place(tch_c, n0c_, n1c_, n2c_, 0)
        cum_n = place(cum_c, n0cum, n1cum, n2cum, delta)

        colm = lane_b == jj
        dlo_ref[:] = jnp.where(colm & is_del, dlo, dlo_ref[:])
        dhi_ref[:] = jnp.where(colm & is_del, dhi, dhi_ref[:])
        dn_ref[:] = jnp.where(colm & is_del, dcount, dn_ref[:])

        return (
            tta_n, tch_n, cum_n,
            total + L - D,
            nused + (m - 1),
        )

    tta, tch, cum, _, nused = jax.lax.fori_loop(
        0, B, body, (tta0, tch0, cum0, total0, nused0)
    )
    ttype = jnp.bitwise_and(tta, 3)
    ta = jnp.right_shift(tta, 2)
    ttype_ref[:] = ttype
    ta_ref[:] = ta
    tch_ref[:] = tch
    tlen_ref[:] = cum - jnp.where(lane_t == 0, 0, _roll1(cum))
    # nused counts m-1 per op UNCONDITIONALLY, so it is the TRUE token
    # demand even when placements past T were dropped — callers compare
    # it against T to turn an undersized token_cap into a loud failure.
    nused_ref[:] = nused


@functools.partial(
    jax.jit, static_argnames=("replica_tile", "interpret", "token_cap")
)
def resolve_range_pallas(
    kind, pos, rlen, slot0, v0, *, replica_tile: int = 64,
    interpret: bool = False, token_cap: int | None = None,
):
    """Resolve one batch of range ops for R replicas.

    kind/pos/rlen/slot0: int32[B]; v0: int32[R].  Returns
    (ttype, ta, tch, tlen) int32[R, T] token arrays — ``ta`` is the
    pre-batch RANK for RUN tokens and the op's first SLOT ID for TINS
    tokens —
    (drank_lo, drank_hi, dcount) int32[R, B] per-op delete intervals,
    and nused int32[R, 1] — the batch's TRUE final token demand.

    ``token_cap`` bounds the VMEM token list below the 2B+2 worst case
    when the caller KNOWS the batch's final token count (host simulation,
    ops/token_sim.py simulate_range_token_counts — kernel cost is linear
    in the list size).  An undersized cap corrupts the token arrays, so
    callers MUST check ``nused <= T`` (T = the rounded cap this function
    used) after the run — nused counts demand past T, turning sim/kernel
    drift into a loud failure instead of silent corruption (ADVICE r3).
    """
    B = kind.shape[0]
    R = v0.shape[0]
    T = effective_token_list_size(B, token_cap)
    # 12MB scoped-VMEM budget: at typical B the power-of-two floor below
    # caps Rt at 64 — measured fastest (32 is ~6% slower; 128 fails to
    # compile under Mosaic's real VMEM accounting)
    Rt = min(replica_tile, max(8, (12 * 2**20) // ((12 * T + 6 * B) * 4)))
    Rt = 1 << (Rt.bit_length() - 1)
    while R % Rt:
        Rt //= 2

    kernel = functools.partial(_kernel, B=B, T=T, Rt=Rt)
    bspec = lambda n: pl.BlockSpec(
        (1, n), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    ospec = lambda n: pl.BlockSpec(
        (Rt, n), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        kernel,
        grid=(R // Rt,),
        in_specs=[bspec(B), bspec(B), bspec(B), bspec(B),
                  pl.BlockSpec((Rt, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[ospec(B), ospec(B), ospec(B),
                   ospec(T), ospec(T), ospec(T), ospec(T),
                   pl.BlockSpec((Rt, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((R, B), jnp.int32)] * 3
        + [jax.ShapeDtypeStruct((R, T), jnp.int32)] * 4
        + [jax.ShapeDtypeStruct((R, 1), jnp.int32)],
        # Mosaic's conservative stack accounting rejects Rt=128 under
        # the default 16MB scoped budget even though live state is a
        # fraction of it; v5e has 128MB of physical VMEM (the same
        # raise expand_pallas.apply_fused uses).
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 2**20
        ),
        interpret=interpret,
    )(
        kind.reshape(1, B).astype(jnp.int32),
        pos.reshape(1, B).astype(jnp.int32),
        rlen.reshape(1, B).astype(jnp.int32),
        slot0.reshape(1, B).astype(jnp.int32),
        v0.reshape(R, 1).astype(jnp.int32),
    )
    dlo, dhi, dn, ttype, ta, tch, tlen, nused = out
    return (ttype, ta, tch, tlen), (dlo, dhi, dn), nused
