"""Fused TPU resolver — the whole per-batch op loop as ONE Pallas kernel.

Why this exists: the reference resolver (ops/resolve.py) runs the sequential
per-op token-list update as a ``lax.scan`` whose body compiles to dozens of
tiny HLO ops.  On TPU every scan iteration then pays dispatch/sequencer
overhead for work that touches a few KB — measured ~240us per unit op, i.e.
the hot loop of the reference (src/main.rs:30-34) re-created with a ~1000x
constant factor.  This kernel keeps the *same algorithm* but runs the entire
B-op loop inside one ``pl.pallas_call``: the token list lives in
VMEM/registers as ``(Rt, T)`` tiles (replicas on sublanes, tokens on lanes),
each op is a handful of VPU passes, and the only HBM traffic is the batch's
inputs and outputs.

Representation change vs the scan resolver: the token list is stored
**cum-primary** — ``(ttype, ta, cum)`` where ``cum[i]`` is the inclusive
prefix sum of token lengths — so no O(T·logT) cumsum is needed per op; the
prefix array is maintained incrementally by the same shift/place update that
maintains the token arrays (total document length changes by ±1 per op).
``tlen`` is reconstructed once at the end for the shared post-extraction
(ops/resolve.py ``extract_from_tokens``).

The kernel is replica-batched: ``v0`` is int32[R] (one visible-length per
replica), token state is (Rt, T) per grid step, and all per-op scalars become
(Rt, 1) columns — every replica honestly performs its own full resolution
(the batched equivalent of running the reference's loop R times), it just
does so on the VPU's sublane axis instead of in R separate programs.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import jax.numpy as jnp
from .pallas_compat import pl, pltpu  # CompilerParams shim for jax 0.4

from ..traces.tensorize import DELETE, INSERT
from .resolve import (
    FREE,
    ORIGIN_BATCH,
    RUN,
    TDEAD,
    TINS,
    ResolvedBatch,
    extract_from_tokens,
)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _roll1(x):
    """Shift right by 1 along lanes (wrap: lane 0 gets old last lane —
    callers overwrite or mask every wrapped position)."""
    return jnp.concatenate([x[:, -1:], x[:, :-1]], axis=1)


def _shiftl(x, s: int, fill):
    """out[:, i] = x[:, i+s] (tail filled with `fill`)."""
    pad = jnp.full((x.shape[0], s), fill, x.dtype)
    return jnp.concatenate([x[:, s:], pad], axis=1)


def _shiftr(x, s: int, fill):
    """out[:, i] = x[:, i-s] (head filled with `fill`)."""
    pad = jnp.full((x.shape[0], s), fill, x.dtype)
    return jnp.concatenate([pad, x[:, : x.shape[1] - s]], axis=1)


def _suffix_min(x, T: int, big):
    for b in range(T.bit_length()):
        s = 1 << b
        if s >= T:
            break
        x = jnp.minimum(x, _shiftl(x, s, big))
    return x


def _cummax_incl(x, T: int, small):
    for b in range(T.bit_length()):
        s = 1 << b
        if s >= T:
            break
        x = jnp.maximum(x, _shiftr(x, s, small))
    return x


def _cumsum_incl(x, T: int):
    for b in range(T.bit_length()):
        s = 1 << b
        if s >= T:
            break
        x = x + _shiftr(x, s, 0)
    return x


def _kernel(kind_ref, pos_ref, v0_ref,
            drank_ref, origin_ref, dbatch_ref,
            opos_ref, gvis_ref, seq_ref,
            *, B: int, T: int, Rt: int, emit_origin: bool = True):
    lane_t = jax.lax.broadcasted_iota(jnp.int32, (Rt, T), 1)
    lane_b = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    kind_v = kind_ref[:]  # (1, B)
    pos_v = pos_ref[:]
    v0 = v0_ref[:]  # (Rt, 1)

    drank_ref[:] = jnp.full((Rt, B), -1, jnp.int32)
    origin_ref[:] = jnp.full((Rt, B), -2, jnp.int32)
    dbatch_ref[:] = jnp.full((Rt, B), -1, jnp.int32)
    # opos[r, j] = final token-list index of op j's token.  Tracked in-kernel
    # so the host-side extraction can GATHER per-op results from token space
    # instead of scattering token results into op space — TPU scatters
    # serialize per row (~19ms/batch measured); gathers vectorize.
    opos_ref[:] = jnp.zeros((Rt, B), jnp.int32)

    # The token type (2 bits) and token attribute `ta` travel PACKED as
    # tta = (ta << 2) | ttype — one place() pass instead of two, and one
    # masked-sum extraction instead of two.  Initial token list: one
    # RUN(0, v0) then FREE; cum is flat at v0.
    tta0 = jnp.where(lane_t == 0, RUN, FREE)  # ta = 0 everywhere
    cum0 = jnp.broadcast_to(v0, (Rt, T))
    total0 = v0  # (Rt, 1)
    nused0 = jnp.ones((Rt, 1), jnp.int32)

    def body(j, carry):
        tta, cum, total, nused = carry
        jj = jnp.int32(j)
        opmask = (lane_b == jj).astype(jnp.int32)
        k = jnp.sum(kind_v * opmask, axis=1, keepdims=True)  # (1, 1)
        p0 = jnp.sum(pos_v * opmask, axis=1, keepdims=True)

        is_ins = k == INSERT
        p = jnp.clip(p0, 0, total)  # (Rt, 1) — per replica
        is_del = (k == DELETE) & (p < total)

        # Token containing offset p: first index with cum > p, clamped to the
        # first FREE slot for an at-end insert (cum is flat there).
        t = jnp.sum((cum <= p).astype(jnp.int32), axis=1, keepdims=True)
        t = jnp.minimum(t, nused)
        m_t = lane_t == t
        c_t = jnp.sum(jnp.where(m_t, cum, 0), axis=1, keepdims=True)
        pre = jnp.sum(
            jnp.where(lane_t == (t - 1), cum, 0), axis=1, keepdims=True
        )
        tta_t = jnp.sum(jnp.where(m_t, tta, 0), axis=1, keepdims=True)
        a = jnp.right_shift(tta_t, 2)
        tt = jnp.bitwise_and(tta_t, 3)
        off = p - pre
        hit_run = tt == RUN
        split = is_ins & (off > 0)

        # Replacement of token t by m in {1,2,3} tokens (see ops/resolve.py).
        m = jnp.where(
            is_ins,
            jnp.where(split, 3, 2),
            jnp.where(is_del, jnp.where(hit_run, 2, 1), 1),
        )
        delta = jnp.where(is_ins, 1, 0) - jnp.where(is_del, 1, 0)

        jj4 = jj * 4
        n0 = jnp.where(
            is_ins,
            jnp.where(split, a * 4 + RUN, jj4 + TINS),
            jnp.where(
                is_del,
                jnp.where(hit_run, a * 4 + RUN, a * 4 + TDEAD),
                tta_t,
            ),
        )
        n0c = jnp.where(
            is_ins,
            jnp.where(split, p, pre + 1),
            jnp.where(is_del, jnp.where(hit_run, p, pre), c_t),
        )
        n1 = jnp.where(
            is_ins,
            jnp.where(split, jj4 + TINS, tta_t),
            (a + off + 1) * 4 + RUN,
        )
        n1c = jnp.where(is_ins, jnp.where(split, p + 1, c_t + 1), c_t - 1)
        n2 = (a + off) * 4 + RUN
        n2c = c_t + 1

        m2 = m >= 2
        m3 = m == 3

        def place(x, x0, x1, x2, dlt):
            r1, r2 = _roll1(x), _roll1(_roll1(x))
            sh = jnp.where(m == 1, x, jnp.where(m == 2, r1, r2)) + dlt
            out = jnp.where(lane_t < t, x, sh)
            out = jnp.where(lane_t == t, x0, out)
            out = jnp.where(m2 & (lane_t == t + 1), x1, out)
            out = jnp.where(m3 & (lane_t == t + 2), x2, out)
            return out

        tta_n = place(tta, n0, n1, n2, 0)
        cum_n = place(cum, n0c, n1c, n2c, delta)

        # Per-op outputs (column j).
        del_rank = jnp.where(is_del & hit_run, a + off, -1)
        del_batch = jnp.where(is_del & (tt == TINS), a, -1)
        if emit_origin:
            # Origin: char at offset p-1 at op time (token tp contains it;
            # tp is always a len>0 token — zero-len tokens share their
            # predecessor's cum, so they can never be the first index with
            # cum > p-1).
            tp = jnp.sum(
                (cum <= p - 1).astype(jnp.int32), axis=1, keepdims=True
            )
            m_tp = lane_t == tp
            pre_tp = jnp.sum(
                jnp.where(lane_t == tp - 1, cum, 0), axis=1, keepdims=True
            )
            tta_tp = jnp.sum(jnp.where(m_tp, tta, 0), axis=1, keepdims=True)
            a_tp = jnp.right_shift(tta_tp, 2)
            origin_char = jnp.where(
                jnp.bitwise_and(tta_tp, 3) == RUN,
                a_tp + (p - 1 - pre_tp),
                ORIGIN_BATCH + a_tp,
            )
            origin = jnp.where(
                is_ins, jnp.where(p == 0, -1, origin_char), -2
            )
        else:
            # Upstream replay needs only the insert/non-insert distinction
            # downstream of the extraction (-2 = non-insert).
            origin = jnp.where(is_ins, -1, -2)

        colm = lane_b == jj
        drank_ref[:] = jnp.where(colm, del_rank, drank_ref[:])
        origin_ref[:] = jnp.where(colm, origin, origin_ref[:])
        dbatch_ref[:] = jnp.where(colm, del_batch, dbatch_ref[:])
        # Track token positions of earlier ops through this op's shift, then
        # record this op's own token position (split inserts land at t+1).
        shifted_opos = opos_ref[:] + (opos_ref[:] >= t).astype(jnp.int32) * (
            m - 1
        )
        opos_ref[:] = jnp.where(colm, jnp.where(split, t + 1, t), shifted_opos)

        return tta_n, cum_n, total + delta, nused + (m - 1)

    tta, cum, _, _ = jax.lax.fori_loop(
        0, B, body, (tta0, cum0, total0, nused0)
    )

    # ---- token-space extraction, fused in-kernel (ops/resolve.py
    # `extract_from_tokens` semantics; everything below is log-shift
    # passes over the VMEM-resident (Rt, T) arrays, replacing XLA-level
    # cummin/cummax/cumsum passes and their layout copies) ----
    big = jnp.int32(1 << 30)
    ttype = jnp.bitwise_and(tta, 3)
    ta = jnp.right_shift(tta, 2)
    tlen = cum - jnp.where(lane_t == 0, 0, _roll1(cum))
    is_instok = (ttype == TINS) | (ttype == TDEAD)

    # Per token: rank of the first surviving pre-batch char to its right.
    run_start = jnp.where((ttype == RUN) & (tlen > 0), ta, big)
    nxt = _suffix_min(_shiftl(run_start, 1, big), T, big)
    gvis_tok = jnp.where(nxt >= big, v0, nxt)

    # Tie-break rank among instok tokens sharing a gap.  gvis_tok is
    # nondecreasing (suffix-min), so a masked cummax carries the previous
    # instok token's gvis.
    inst_i = is_instok.astype(jnp.int32)
    ci = _cumsum_incl(inst_i, T)
    pg = _cummax_incl(jnp.where(is_instok, gvis_tok, -1), T, -1)
    prev_gvis = _shiftr(pg, 1, -1)
    boundary = is_instok & (prev_gvis != gvis_tok)
    base = jnp.where(boundary, ci - 1, -1)
    seq_tok = ci - 1 - _cummax_incl(base, T, -1)

    gvis_ref[:] = gvis_tok
    seq_ref[:] = seq_tok


@functools.partial(
    jax.jit,
    static_argnames=("replica_tile", "interpret", "emit_origin", "token_cap"),
)
def resolve_batch_pallas(
    kind: jax.Array,
    pos: jax.Array,
    v0: jax.Array,
    *,
    replica_tile: int = 32,
    interpret: bool = False,
    emit_origin: bool = True,
    token_cap: int | None = None,
) -> ResolvedBatch:
    """Resolve one op batch for R replicas in one fused kernel.

    ``kind``/``pos``: int32[B] (shared op stream); ``v0``: int32[R] per-replica
    visible lengths.  Returns a ResolvedBatch whose leaves are (R, B).

    ``token_cap`` caps the VMEM token list below the 2B+2 worst case when
    the caller KNOWS the batch's final token count (host-side exact
    simulation, ops/token_sim.py — editing traces sit near B+2, typing
    appends replace one token by two at off == 0).  Kernel cost is linear
    in the list size, so this nearly halves resolver time.  An undersized
    cap silently corrupts results — callers must use the simulation, and
    verify modes byte-check against the oracle.
    """
    B = kind.shape[0]
    R = v0.shape[0]
    if R > 8 and R % 8:
        # Mosaic blocks need a sublane dim that is a multiple of 8 (or the
        # whole array); reject rather than silently miscompile (pad the
        # replica axis at the caller).
        raise ValueError(f"n_replicas must be a multiple of 8 (got {R})")
    T = _round_up(
        min(2 * B + 2, token_cap) if token_cap else 2 * B + 2, 128
    )
    # Scoped-VMEM budget: ~10 live (Rt, T) + ~6 (Rt, B) int32 arrays
    # (carries, roll temps, output blocks).  Power of two, >= 8 when R >= 8
    # (sublane-dim block constraint), dividing R.
    Rt = min(replica_tile, max(8, (12 * 2**20) // ((10 * T + 6 * B) * 4)))
    Rt = 1 << (Rt.bit_length() - 1)
    while R % Rt:
        Rt //= 2
    Rt = max(Rt, min(R, 8))  # sublane-dim floor (R <= 8 uses the whole array)

    kernel = functools.partial(
        _kernel, B=B, T=T, Rt=Rt, emit_origin=emit_origin
    )
    out = pl.pallas_call(
        kernel,
        grid=(R // Rt,),
        in_specs=[
            pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, T), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, T), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # del_rank
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # origin
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # del_batch
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # opos
            jax.ShapeDtypeStruct((R, T), jnp.int32),  # gvis_tok
            jax.ShapeDtypeStruct((R, T), jnp.int32),  # seq_tok
        ],
        interpret=interpret,
    )(
        kind.reshape(1, B).astype(jnp.int32),
        pos.reshape(1, B).astype(jnp.int32),
        v0.reshape(R, 1).astype(jnp.int32),
    )
    del_rank, origin, del_batch, opos, gvis_tok, seq_tok = out

    ins_gvis, ins_seq, ins_alive = _extract_gather(
        gvis_tok, seq_tok, opos, origin, del_batch
    )
    return ResolvedBatch(
        del_rank=del_rank,
        ins_gvis=ins_gvis,
        ins_seq=ins_seq,
        ins_alive=ins_alive,
        origin=origin,
        del_batch=del_batch,
    )


def _gather_token_space(srcs_and_maxes, at):
    """val[r, b] = src[r, at[r, b]] for (R, T) int32 sources, T a multiple
    of 128.  Lane-first one-hot einsum: contract the lane axis with a shared
    (R, B, 128) bf16 one-hot (tiny (R, B, T/128) outputs), then select the
    tile elementwise.  Exact: each value is 7-bit-chunked (<= 127, exact in
    bf16) and every output receives exactly one contribution.  ~25x cheaper
    than take_along_axis, which serializes per gathered row on this TPU.
    """
    R, T = srcs_and_maxes[0][0].shape
    B = at.shape[1]
    ntt = T // 128
    tq = jnp.right_shift(at, 7)
    lq = jnp.bitwise_and(at, 127)
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, B, 128), 2)
    ohl = (lane == lq[:, :, None]).astype(jnp.bfloat16)
    tsel = (
        jax.lax.broadcasted_iota(jnp.int32, (R, B, ntt), 2) == tq[:, :, None]
    )
    outs = []
    for src, max_value in srcs_and_maxes:
        srcv = src.reshape(R, ntt, 128)
        val = jnp.zeros((R, B), jnp.int32)
        k = 0
        while (1 << (7 * k)) <= max_value:
            chunk = jnp.bitwise_and(
                jnp.right_shift(srcv, 7 * k), 127
            ).astype(jnp.bfloat16)
            tmp = jnp.einsum(
                "rbl,rtl->rbt", ohl, chunk,
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            part = jnp.sum(jnp.where(tsel, tmp, 0), axis=2)
            val = val + jnp.left_shift(part, 7 * k)
            k += 1
        outs.append(val)
    return outs


def _extract_gather(gvis_tok, seq_tok, opos, origin, del_batch):
    """Per-op extraction from the kernel-emitted token-space values: gather
    at the kernel-tracked per-op token positions via exact one-hot MXU
    einsums (take_along_axis serializes per row on this TPU — measured
    ~21ns/row, ~4ms/batch at R=128, B=512).  All args replica-batched:
    gvis_tok/seq_tok int32[R, T], opos/origin/del_batch int32[R, B].
    """
    R, T = gvis_tok.shape
    B = opos.shape[1]
    # Per-op gathers at the tracked token positions.
    is_ins_op = origin != -2  # origin is -2 exactly for non-insert ops
    at = jnp.clip(opos, 0, T - 1)
    g, s = _gather_token_space(
        [(gvis_tok, 1 << 21), (seq_tok, max(B - 1, 1))], at
    )
    # An insert is alive unless a later same-batch delete killed it — the
    # kernel names the killed batch index in del_batch (avoids gathering
    # ttype at opos).
    killed = jnp.sum(
        (
            del_batch[:, :, None]
            == jax.lax.broadcasted_iota(jnp.int32, (R, B, B), 2)
        ).astype(jnp.int32),
        axis=1,
    ) > 0
    return (
        jnp.where(is_ins_op, g, -1),
        jnp.where(is_ins_op, s, 0),
        is_ins_op & ~killed,
    )
