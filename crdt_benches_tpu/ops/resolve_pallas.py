"""Fused TPU resolver — the whole per-batch op loop as ONE Pallas kernel.

Why this exists: the reference resolver (ops/resolve.py) runs the sequential
per-op token-list update as a ``lax.scan`` whose body compiles to dozens of
tiny HLO ops.  On TPU every scan iteration then pays dispatch/sequencer
overhead for work that touches a few KB — measured ~240us per unit op, i.e.
the hot loop of the reference (src/main.rs:30-34) re-created with a ~1000x
constant factor.  This kernel keeps the *same algorithm* but runs the entire
B-op loop inside one ``pl.pallas_call``: the token list lives in
VMEM/registers as ``(Rt, T)`` tiles (replicas on sublanes, tokens on lanes),
each op is a handful of VPU passes, and the only HBM traffic is the batch's
inputs and outputs.

Representation change vs the scan resolver: the token list is stored
**cum-primary** — ``(ttype, ta, cum)`` where ``cum[i]`` is the inclusive
prefix sum of token lengths — so no O(T·logT) cumsum is needed per op; the
prefix array is maintained incrementally by the same shift/place update that
maintains the token arrays (total document length changes by ±1 per op).
``tlen`` is reconstructed once at the end for the shared post-extraction
(ops/resolve.py ``extract_from_tokens``).

The kernel is replica-batched: ``v0`` is int32[R] (one visible-length per
replica), token state is (Rt, T) per grid step, and all per-op scalars become
(Rt, 1) columns — every replica honestly performs its own full resolution
(the batched equivalent of running the reference's loop R times), it just
does so on the VPU's sublane axis instead of in R separate programs.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..traces.tensorize import DELETE, INSERT
from .resolve import (
    FREE,
    ORIGIN_BATCH,
    RUN,
    TDEAD,
    TINS,
    ResolvedBatch,
    extract_from_tokens,
)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _roll1(x):
    """Shift right by 1 along lanes (wrap: lane 0 gets old last lane —
    callers overwrite or mask every wrapped position)."""
    return jnp.concatenate([x[:, -1:], x[:, :-1]], axis=1)


def _kernel(kind_ref, pos_ref, v0_ref,
            drank_ref, origin_ref, dbatch_ref,
            opos_ref, ttype_ref, ta_ref, tlen_ref,
            *, B: int, T: int, Rt: int, emit_origin: bool = True):
    lane_t = jax.lax.broadcasted_iota(jnp.int32, (Rt, T), 1)
    lane_b = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    kind_v = kind_ref[:]  # (1, B)
    pos_v = pos_ref[:]
    v0 = v0_ref[:]  # (Rt, 1)

    drank_ref[:] = jnp.full((Rt, B), -1, jnp.int32)
    origin_ref[:] = jnp.full((Rt, B), -2, jnp.int32)
    dbatch_ref[:] = jnp.full((Rt, B), -1, jnp.int32)
    # opos[r, j] = final token-list index of op j's token.  Tracked in-kernel
    # so the host-side extraction can GATHER per-op results from token space
    # instead of scattering token results into op space — TPU scatters
    # serialize per row (~19ms/batch measured); gathers vectorize.
    opos_ref[:] = jnp.zeros((Rt, B), jnp.int32)

    # Initial token list: one RUN(0, v0) then FREE; cum is flat at v0.
    ttype0 = jnp.where(lane_t == 0, RUN, FREE)
    ta0 = jnp.zeros((Rt, T), jnp.int32)
    cum0 = jnp.broadcast_to(v0, (Rt, T))
    total0 = v0  # (Rt, 1)
    nused0 = jnp.ones((Rt, 1), jnp.int32)

    def body(j, carry):
        ttype, ta, cum, total, nused = carry
        jj = jnp.int32(j)
        opmask = (lane_b == jj).astype(jnp.int32)
        k = jnp.sum(kind_v * opmask, axis=1, keepdims=True)  # (1, 1)
        p0 = jnp.sum(pos_v * opmask, axis=1, keepdims=True)

        is_ins = k == INSERT
        p = jnp.clip(p0, 0, total)  # (Rt, 1) — per replica
        is_del = (k == DELETE) & (p < total)

        # Token containing offset p: first index with cum > p, clamped to the
        # first FREE slot for an at-end insert (cum is flat there).
        t = jnp.sum((cum <= p).astype(jnp.int32), axis=1, keepdims=True)
        t = jnp.minimum(t, nused)
        m_t = lane_t == t
        m_tm1 = lane_t == (t - 1)
        c_t = jnp.sum(jnp.where(m_t, cum, 0), axis=1, keepdims=True)
        pre = jnp.sum(jnp.where(m_tm1, cum, 0), axis=1, keepdims=True)
        a = jnp.sum(jnp.where(m_t, ta, 0), axis=1, keepdims=True)
        tt = jnp.sum(jnp.where(m_t, ttype, 0), axis=1, keepdims=True)
        off = p - pre
        hit_run = tt == RUN
        split = is_ins & (off > 0)

        # Replacement of token t by m in {1,2,3} tokens (see ops/resolve.py).
        m = jnp.where(
            is_ins,
            jnp.where(split, 3, 2),
            jnp.where(is_del, jnp.where(hit_run, 2, 1), 1),
        )
        delta = jnp.where(is_ins, 1, 0) - jnp.where(is_del, 1, 0)

        n0t = jnp.where(
            is_ins,
            jnp.where(split, RUN, TINS),
            jnp.where(is_del, jnp.where(hit_run, RUN, TDEAD), tt),
        )
        n0a = jnp.where(is_ins & ~split, jj, a)
        n0c = jnp.where(
            is_ins,
            jnp.where(split, p, pre + 1),
            jnp.where(is_del, jnp.where(hit_run, p, pre), c_t),
        )
        n1t = jnp.where(is_ins, jnp.where(split, TINS, tt), RUN)
        n1a = jnp.where(is_ins, jnp.where(split, jj, a), a + off + 1)
        n1c = jnp.where(is_ins, jnp.where(split, p + 1, c_t + 1), c_t - 1)
        n2t, n2a, n2c = jnp.int32(RUN), a + off, c_t + 1

        m2 = m >= 2
        m3 = m == 3

        def place(x, x0, x1, x2, dlt):
            r1, r2 = _roll1(x), _roll1(_roll1(x))
            sh = jnp.where(m == 1, x, jnp.where(m == 2, r1, r2)) + dlt
            out = jnp.where(lane_t < t, x, sh)
            out = jnp.where(lane_t == t, x0, out)
            out = jnp.where(m2 & (lane_t == t + 1), x1, out)
            out = jnp.where(m3 & (lane_t == t + 2), x2, out)
            return out

        ttype_n = place(ttype, n0t, n1t, n2t, 0)
        ta_n = place(ta, n0a, n1a, n2a, 0)
        cum_n = place(cum, n0c, n1c, n2c, delta)

        # Per-op outputs (column j).
        del_rank = jnp.where(is_del & hit_run, a + off, -1)
        del_batch = jnp.where(is_del & (tt == TINS), a, -1)
        if emit_origin:
            # Origin: char at offset p-1 at op time (token tp contains it;
            # tp is always a len>0 token — zero-len tokens share their
            # predecessor's cum, so they can never be the first index with
            # cum > p-1).
            tp = jnp.sum(
                (cum <= p - 1).astype(jnp.int32), axis=1, keepdims=True
            )
            m_tp = lane_t == tp
            pre_tp = jnp.sum(
                jnp.where(lane_t == tp - 1, cum, 0), axis=1, keepdims=True
            )
            a_tp = jnp.sum(jnp.where(m_tp, ta, 0), axis=1, keepdims=True)
            tt_tp = jnp.sum(jnp.where(m_tp, ttype, 0), axis=1, keepdims=True)
            origin_char = jnp.where(
                tt_tp == RUN, a_tp + (p - 1 - pre_tp), ORIGIN_BATCH + a_tp
            )
            origin = jnp.where(
                is_ins, jnp.where(p == 0, -1, origin_char), -2
            )
        else:
            # Upstream replay needs only the insert/non-insert distinction
            # downstream of the extraction (-2 = non-insert).
            origin = jnp.where(is_ins, -1, -2)

        colm = lane_b == jj
        drank_ref[:] = jnp.where(colm, del_rank, drank_ref[:])
        origin_ref[:] = jnp.where(colm, origin, origin_ref[:])
        dbatch_ref[:] = jnp.where(colm, del_batch, dbatch_ref[:])
        # Track token positions of earlier ops through this op's shift, then
        # record this op's own token position (split inserts land at t+1).
        shifted_opos = opos_ref[:] + (opos_ref[:] >= t).astype(jnp.int32) * (
            m - 1
        )
        opos_ref[:] = jnp.where(colm, jnp.where(split, t + 1, t), shifted_opos)

        return ttype_n, ta_n, cum_n, total + delta, nused + (m - 1)

    ttype, ta, cum, _, _ = jax.lax.fori_loop(
        0, B, body, (ttype0, ta0, cum0, total0, nused0)
    )
    ttype_ref[:] = ttype
    ta_ref[:] = ta
    tlen_ref[:] = cum - jnp.where(lane_t == 0, 0, _roll1(cum))


@functools.partial(
    jax.jit, static_argnames=("replica_tile", "interpret", "emit_origin")
)
def resolve_batch_pallas(
    kind: jax.Array,
    pos: jax.Array,
    v0: jax.Array,
    *,
    replica_tile: int = 32,
    interpret: bool = False,
    emit_origin: bool = True,
) -> ResolvedBatch:
    """Resolve one op batch for R replicas in one fused kernel.

    ``kind``/``pos``: int32[B] (shared op stream); ``v0``: int32[R] per-replica
    visible lengths.  Returns a ResolvedBatch whose leaves are (R, B).
    """
    B = kind.shape[0]
    R = v0.shape[0]
    T = _round_up(2 * B + 2, 128)
    # Scoped-VMEM budget: ~10 live (Rt, T) + ~6 (Rt, B) int32 arrays
    # (carries, roll temps, output blocks).  Power of two, >= 8 when R >= 8
    # (sublane-dim block constraint), dividing R.
    Rt = min(replica_tile, max(8, (12 * 2**20) // ((10 * T + 6 * B) * 4)))
    Rt = 1 << (Rt.bit_length() - 1)
    while R % Rt:
        Rt //= 2

    kernel = functools.partial(
        _kernel, B=B, T=T, Rt=Rt, emit_origin=emit_origin
    )
    out = pl.pallas_call(
        kernel,
        grid=(R // Rt,),
        in_specs=[
            pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, T), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, T), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rt, T), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # del_rank
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # origin
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # del_batch
            jax.ShapeDtypeStruct((R, B), jnp.int32),  # opos
            jax.ShapeDtypeStruct((R, T), jnp.int32),  # ttype
            jax.ShapeDtypeStruct((R, T), jnp.int32),  # ta
            jax.ShapeDtypeStruct((R, T), jnp.int32),  # tlen
        ],
        interpret=interpret,
    )(
        kind.reshape(1, B).astype(jnp.int32),
        pos.reshape(1, B).astype(jnp.int32),
        v0.reshape(R, 1).astype(jnp.int32),
    )
    del_rank, origin, del_batch, opos, ttype, ta, tlen = out

    ins_gvis, ins_seq, ins_alive = _extract_gather(
        ttype, ta, tlen, v0, opos, origin
    )
    return ResolvedBatch(
        del_rank=del_rank,
        ins_gvis=ins_gvis,
        ins_seq=ins_seq,
        ins_alive=ins_alive,
        origin=origin,
        del_batch=del_batch,
    )


def _extract_gather(ttype, ta, tlen, v0, opos, origin):
    """Scatter-free post-extraction: same results as
    ``resolve.extract_from_tokens`` but per-op values are GATHERED from token
    space at the kernel-tracked per-op token positions (TPU scatters
    serialize per row; gathers vectorize).  All args replica-batched:
    ttype/ta/tlen int32[R, T], v0 int32[R], opos/origin int32[R, B].
    """
    R, T = ttype.shape
    big = np.int32(1 << 30)
    is_instok = (ttype == TINS) | (ttype == TDEAD)
    # Per token: rank of the first surviving pre-batch char to its right.
    run_start = jnp.where((ttype == RUN) & (tlen > 0), ta, big)
    suff = jax.lax.cummin(run_start, axis=1, reverse=True)
    nxt = jnp.concatenate(
        [suff[:, 1:], jnp.full((R, 1), big, jnp.int32)], axis=1
    )
    gvis_tok = jnp.where(nxt >= big, v0[:, None], nxt)

    # Tie-break rank among instok tokens sharing a gap (same-gap instok
    # tokens are contiguous up to zero-length RUN remnants, which cummax
    # skips — see resolve.extract_from_tokens).
    tpos = jax.lax.broadcasted_iota(jnp.int32, (R, T), 1)
    ci = jnp.cumsum(is_instok.astype(jnp.int32), axis=1)
    prev_ipos = jax.lax.cummax(jnp.where(is_instok, tpos, -1), axis=1)
    prev_ipos = jnp.concatenate(
        [jnp.full((R, 1), -1, jnp.int32), prev_ipos[:, :-1]], axis=1
    )
    prev_gvis = jnp.where(
        prev_ipos >= 0,
        jnp.take_along_axis(gvis_tok, jnp.clip(prev_ipos, 0), axis=1),
        -1,
    )
    boundary = is_instok & ((prev_ipos < 0) | (prev_gvis != gvis_tok))
    base = jnp.where(boundary, ci - 1, -1)
    seq_tok = ci - 1 - jax.lax.cummax(base, axis=1)

    # Per-op gathers at the tracked token positions.
    is_ins_op = origin != -2  # origin is -2 exactly for non-insert ops
    at = jnp.clip(opos, 0, T - 1)
    g = jnp.take_along_axis(gvis_tok, at, axis=1)
    s = jnp.take_along_axis(seq_tok, at, axis=1)
    tt_at = jnp.take_along_axis(ttype, at, axis=1)
    return (
        jnp.where(is_ins_op, g, -1),
        jnp.where(is_ins_op, s, 0),
        is_ins_op & (tt_at == TINS),
    )
