"""Narrow-dtype packing for staged serve op tensors.

A macro dispatch stages ``(K, Rt, B)`` op tensors per capacity class —
four int32 arrays (kind / pos / rlen / slot0) that exist only to carry
small integers from the host planner to the device step.  Their value
ranges are bounded by STATIC engine facts, not by data:

- ``kind`` is one of the three op codes (PAD / INSERT / DELETE) — int8;
- ``pos`` is a position in visible space, < the pool's largest capacity
  class;
- ``rlen`` is a run length, <= the document length, < the largest class;
- ``slot0`` is a slot id, < the largest class (the id space is per-doc).

With the default class ladder (largest class 49152) all three fit
uint16, halving the staged bytes and the host->device transfer of every
macro round.  Pools whose largest class exceeds the uint16 range fall
back to int32 lanes — the engine guard caps classes at 2^22, so int32
always fits.  The dtype choice is a SINGLE static function of the
pool's largest class (not per-class, not per-batch): every class stages
the same lane dtypes, so the shared resolve executable compiles once
for the whole fleet and a quiet round cannot flip dtypes mid-run.

Packing is checked, not truncating: values outside the target lane's
range raise ``OpRangeError`` instead of wrapping, so a future id-space
bump past the uint16 ceiling surfaces as a loud staging error, never as
a silently corrupted slot id.  Widening back to int32 happens at the
jit boundary (``widen_ops``) — a free elementwise cast on every
backend.
"""

from __future__ import annotations

import numpy as np

#: The packed lane layouts, keyed by whether the pool's id space fits
#: uint16.  ``kind`` is always int8 (three op codes).
NARROW_DTYPES = (np.int8, np.uint16, np.uint16, np.uint16)
WIDE_DTYPES = (np.int8, np.int32, np.int32, np.int32)

#: Largest id-space bound the narrow (uint16) lanes can carry.  Kept a
#: literal (== np.iinfo(np.uint16).max) so the lint constant
#: environment can resolve ``inrange=...<=NARROW_ID_BOUND`` markers.
NARROW_ID_BOUND = 65535


class OpRangeError(ValueError):
    """A staged op value does not fit its packed lane dtype."""


def op_lane_dtypes(max_class: int) -> tuple[np.dtype, ...]:
    """The (kind, pos, rlen, slot0) lane dtypes for a pool whose largest
    capacity class is ``max_class``.  Static per pool: every class and
    every round stages the same dtypes (one shared resolve executable,
    no dtype-keyed recompiles)."""
    if max_class <= NARROW_ID_BOUND:
        return tuple(np.dtype(d) for d in NARROW_DTYPES)
    return tuple(np.dtype(d) for d in WIDE_DTYPES)


def _check_range(name: str, a: np.ndarray, dt: np.dtype) -> None:
    info = np.iinfo(dt)
    if a.size == 0:
        return
    lo = int(a.min())
    hi = int(a.max())
    if lo < info.min or hi > info.max:
        raise OpRangeError(
            f"op lane {name!r}: values [{lo}, {hi}] do not fit {dt}"
            f" [{info.min}, {info.max}]; widen the lane dtypes"
            " (op_lane_dtypes) before staging"
        )


def pack_ops(kind, pos, rlen, slot0, max_class: int):
    """Pack four host op arrays into the narrow lane dtypes for
    ``max_class``.  Lossless by construction: any out-of-range value
    raises ``OpRangeError`` (never wraps).  Arrays already in the
    target dtype pass through without a copy."""
    dts = op_lane_dtypes(max_class)
    out = []
    for name, a, dt in zip(
        ("kind", "pos", "rlen", "slot0"), (kind, pos, rlen, slot0), dts
    ):
        a = np.asarray(a)
        if a.dtype == dt:
            out.append(a)
            continue
        _check_range(name, a, dt)
        out.append(a.astype(dt))
    return tuple(out)


def widen_ops(kind, pos, rlen, slot0):
    """Widen packed op lanes back to int32 (jnp or np arrays; identity
    for already-int32 inputs).  The inverse of :func:`pack_ops` for all
    in-range values — the round-trip is exact because pack_ops refuses
    anything that will not fit."""
    return tuple(a.astype(np.int32) for a in (kind, pos, rlen, slot0))
